"""``tpurun`` — per-node process agent (torchrun equivalent).

Replaces the reference's ``torchrun_launcher.sh`` + the torchrun binary
itself (SURVEY.md §2.2 B2, §3.1):

- rendezvous: ``--coordinator host:port`` is the c10d ``--rdzv_endpoint``
  analog; ``--standalone`` (implied when ``--nnodes 1``) picks a free
  localhost port like torchrun's ``--standalone``
  (``torchrun_launcher.sh:13-14``).
- env contract: workers receive ``TPUDIST_COORDINATOR`` /
  ``TPUDIST_NUM_PROCESSES`` / ``TPUDIST_PROCESS_ID`` /
  ``TPUDIST_LOCAL_RANK`` / ``TPUDIST_LOCAL_WORLD_SIZE`` (consumed by
  ``tpudist.runtime.bootstrap.resolve_process_context`` priority 2).
- elasticity: ``--max-restarts`` (default 3 like
  ``torchrun_launcher.sh:19``) relaunches the *whole local worker group*
  with exponential backoff when any worker fails.  JAX's coordination
  service is not per-process elastic, so this is whole-group semantics
  (SURVEY.md §5.3); on multi-node jobs the peer agents' workers die on
  coordinator loss and their agents restart them too, converging on a
  fresh rendezvous for the same ``--run-id``.
- crash records: workers decorated with ``tpudist.utils.record.record``
  write structured tracebacks to ``TPUDIST_ERROR_FILE``; the agent
  collects and surfaces the *first* failure (the ``@record`` +
  elastic-error-file pattern, ``demo.py:14,156``).
- preemption: SLURM delivers SIGTERM to the agent's PROCESS GROUP ahead
  of a requeue.  The agent must not die under the workers mid-save: its
  handler forwards SIGTERM to any worker that did not share the group
  signal, then the agent WAITS for the group to finish its collective
  preemption checkpoint (``tpudist.runtime.preemption`` in the workers),
  skips the restart loop (the machine is going away), surfaces the
  outcome, and exits with the group's status.
- data staging: ``--stage-data a.tar.gz,b.tar.gz`` extracts into the
  job-local tmpdir before workers start (``torchrun_launcher.sh:35-40``).
- command validation: like ``torchrun_launcher.sh:23-25`` the worker
  command must start with ``python`` (or be a ``-m`` module invocation).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional

from tpudist.runtime.bootstrap import find_free_port
from tpudist.runtime.watchdog import WATCHDOG_EXIT_CODE


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpurun",
        description="tpudist per-node process agent (torchrun equivalent)",
    )
    p.add_argument("--nprocs", "--nproc-per-node", dest="nprocs", type=int, default=1,
                   help="worker processes on this node (torchrun --nproc_per_node)")
    p.add_argument("--nnodes", type=int, default=1)
    p.add_argument("--node-rank", type=int, default=0)
    p.add_argument("--coordinator", "--rdzv-endpoint", dest="coordinator", default=None,
                   help="host:port of process 0's coordination service")
    p.add_argument("--standalone", action="store_true",
                   help="single-node: rendezvous on a free localhost port")
    p.add_argument("--run-id", default=None,
                   help="job-scoped rendezvous id (torchrun --rdzv_id=$SLURM_JOB_ID)")
    p.add_argument("--max-restarts", type=int, default=3,
                   help="whole-group restarts on worker failure "
                        "(torchrun_launcher.sh:19 default)")
    p.add_argument("--elastic", action="store_true",
                   help="on restart exhaustion, relaunch the group at the "
                        "SURVIVING world size instead of giving up: crash "
                        "records identify the dead ranks, the remaining "
                        "workers renumber 0..n'-1 with a fresh "
                        "TPUDIST_NUM_PROCESSES, and the restart budget "
                        "resets per world size (single-node agents only "
                        "for now — the rank renumbering is node-local)")
    p.add_argument("--restart-backoff", type=float, default=5.0,
                   help="base seconds between restarts (doubles each retry)")
    p.add_argument("--stage-data", default=None,
                   help="comma-separated tarballs extracted into the job tmpdir "
                        "before workers start")
    p.add_argument("--tmpdir", default=None,
                   help="job-local scratch (default: $TPUDIST_TMPDIR or a fresh "
                        "tempdir); exported to workers as TPUDIST_TMPDIR")
    p.add_argument("--error-dir", default=None,
                   help="directory for per-rank crash records (default: tmpdir)")
    p.add_argument("--telemetry-dir", default=None,
                   help="where workers stream per-rank telemetry JSONL and "
                        "the end-of-run goodput report lands (default: "
                        "$TPUDIST_TELEMETRY_DIR or <tmpdir>/telemetry; "
                        "TPUDIST_TELEMETRY=0 disables)")
    p.add_argument("--devices-per-proc", type=int, default=None,
                   help="emulated devices per worker (sets XLA's "
                        "host-platform device-count flag in the worker "
                        "env) — lets CPU smoke rungs and tests run "
                        "per-process multi-device meshes, e.g. a sharded "
                        "serve worker per process")
    p.add_argument("--no-python-check", action="store_true",
                   help="allow worker commands that do not start with 'python'")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="worker command: python script.py [args...]")
    return p


def _validate_cmd(cmd: List[str], allow_any: bool) -> List[str]:
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        raise SystemExit("tpurun: no worker command given")
    if not allow_any and not os.path.basename(cmd[0]).startswith("python"):
        # torchrun_launcher.sh:23-25 — "the job command must start with python".
        raise SystemExit(
            f"tpurun: worker command must start with 'python' (got {cmd[0]!r}); "
            "pass --no-python-check to override"
        )
    return cmd


def _worker_env(base: Dict[str, str], *, coordinator: Optional[str], world: int,
                rank: int, local_rank: int, nprocs: int, run_id: str,
                restart_count: int, error_template: str, tmpdir: str,
                telemetry_dir: Optional[str] = None,
                devices_per_proc: Optional[int] = None) -> Dict[str, str]:
    env = dict(base)
    if devices_per_proc and devices_per_proc > 0:
        # Per-process emulated multi-device mesh (CPU rigs): the XLA
        # host-platform flag must be in the env BEFORE jax initializes
        # its backends in the worker.  An existing device-count flag in
        # the inherited XLA_FLAGS is replaced, not duplicated (last
        # occurrence wins in XLA, but a stale first one is confusing in
        # ps output and logs).
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        flags.append(
            f"--xla_force_host_platform_device_count={devices_per_proc}")
        env["XLA_FLAGS"] = " ".join(flags)
    env.update({
        "TPUDIST_NUM_PROCESSES": str(world),
        "TPUDIST_PROCESS_ID": str(rank),
        "TPUDIST_LOCAL_RANK": str(local_rank),
        "TPUDIST_LOCAL_WORLD_SIZE": str(nprocs),
        "TPUDIST_RUN_ID": run_id,
        "TPUDIST_RESTART_COUNT": str(restart_count),
        "TPUDIST_ERROR_FILE": error_template,
        "TPUDIST_TMPDIR": tmpdir,
    })
    if coordinator:
        env["TPUDIST_COORDINATOR"] = coordinator
    if telemetry_dir:
        # All generations of all local workers stream into ONE dir — the
        # per-rank/per-generation file names keep them apart, and the
        # end-of-run merge joins them into the goodput report.
        env["TPUDIST_TELEMETRY_DIR"] = telemetry_dir
    # Scrape-endpoint port fan-out: the AGENT binds the configured port
    # before any worker launches, so workers inheriting the same value
    # would all fail to bind and silently lose their endpoints — exactly
    # the serve/train /metrics the feature exists for.  A fixed port P
    # maps workers to P+1+local_rank (deterministic, documented); 0
    # (ephemeral) passes through — every process binds its own.
    port = env.get("TPUDIST_METRICS_PORT", "").strip()
    if port and port.isdigit() and int(port) > 0:
        env["TPUDIST_METRICS_PORT"] = str(int(port) + 1 + local_rank)
    return env


def _read_crash_records(error_template: str, world: int) -> List[dict]:
    records = []
    for path in sorted(glob.glob(error_template.replace("%r", "*"))):
        try:
            with open(path) as f:
                records.append(json.load(f))
        except (OSError, json.JSONDecodeError):
            continue
    records.sort(key=lambda r: r.get("timestamp", 0))
    return records


# Signal-handler state: the live worker group and whether a preemption
# signal arrived.  Module-level (not closure) so the handler, the attempt
# loop, and tests all see one source of truth.
_preempt_state: dict = {"flag": False, "procs": []}

#: Ranks the LAST attempt observed failing spontaneously (nonzero exit
#: before the agent terminated the rest of the group).  A SIGKILLed
#: worker writes no crash record — this observation is what lets the
#: elastic path name the dead ranks anyway.
_last_failed_ranks: List[int] = []


def _handle_agent_sigterm(signum, frame):  # noqa: ARG001
    """Agent-side preemption: mark, forward to workers, keep running.

    Returning (instead of dying, the default SIGTERM action) is the whole
    point — the agent must stay alive to reap the workers' collective
    checkpoint save and report it."""
    _preempt_state["flag"] = True
    for p in list(_preempt_state["procs"]):
        if p.poll() is None:
            try:
                p.send_signal(signal.SIGTERM)
            except OSError:
                pass


def _terminate(procs: List[subprocess.Popen], grace_s: float = 10.0) -> None:
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    deadline = time.time() + grace_s
    for p in procs:
        if p.poll() is None:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()


def _run_attempt(cmd: List[str], args, coordinator: str, world: int,
                 run_id: str, restart_count: int, error_template: str,
                 tmpdir: str, telemetry_dir: Optional[str] = None,
                 nprocs: Optional[int] = None) -> int:
    """Launch the local worker group once; return 0 iff all workers exit 0.

    ``nprocs`` overrides ``args.nprocs`` — the elastic path relaunches
    with fewer local workers than the original request."""
    if nprocs is None:
        nprocs = args.nprocs
    procs: List[subprocess.Popen] = []
    _preempt_state["procs"] = procs
    base_env = dict(os.environ)
    if nprocs > 1 and (
        os.path.exists("/dev/accel0") or base_env.get("TPU_NAME")
    ) and not any(k.startswith("TPU_") and "VISIBLE" in k for k in base_env):
        # The standard JAX shape on TPU hosts is ONE process per host that
        # sees all local chips (what distributed_dispatcher/tpu_pod_run
        # launch); N workers would all try to claim every chip.  Honor the
        # request (the operator may have set per-chip topology envs another
        # way) but say so.
        print(
            f"[tpurun] warning: {nprocs} workers on a TPU host without "
            "per-process chip binding (TPU_VISIBLE_* env); TPU jobs normally "
            "run 1 process/host — see launch/README.md",
            file=sys.stderr,
        )
    for i in range(nprocs):
        rank = args.node_rank * nprocs + i
        env = _worker_env(base_env, coordinator=coordinator, world=world,
                          rank=rank, local_rank=i, nprocs=nprocs,
                          run_id=run_id, restart_count=restart_count,
                          error_template=error_template, tmpdir=tmpdir,
                          telemetry_dir=telemetry_dir,
                          devices_per_proc=args.devices_per_proc)
        procs.append(subprocess.Popen(cmd, env=env))
    failed_rc = 0
    del _last_failed_ranks[:]
    try:
        live = list(procs)
        while live:
            for p in list(live):
                rc = p.poll()
                if rc is None:
                    continue
                live.remove(p)
                if rc != 0:
                    failed_rc = rc
                    # the rank that died on its own — a SIGKILLed worker
                    # leaves no crash record, so the agent's observation
                    # is the elastic path's dead-rank source of truth
                    _last_failed_ranks.append(
                        args.node_rank * nprocs + procs.index(p))
                    if _preempt_state["flag"]:
                        # Preempting: a straggler may still be finishing
                        # the collective save — keep waiting, don't kill.
                        continue
                    # One worker down ⇒ the group is done (the coordination
                    # service cannot re-admit a lone restarted process).
                    _terminate(live)
                    live = []
                    break
            time.sleep(0.2)
    except KeyboardInterrupt:
        _terminate(procs)
        raise
    finally:
        _preempt_state["procs"] = []
    return failed_rc


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    cmd = _validate_cmd(args.cmd, args.no_python_check)
    if args.nprocs < 1 or args.nnodes < 1 or not 0 <= args.node_rank < args.nnodes:
        raise SystemExit(
            f"tpurun: invalid topology nprocs={args.nprocs} nnodes={args.nnodes} "
            f"node_rank={args.node_rank}")

    if args.elastic and args.nnodes != 1:
        raise SystemExit(
            "tpurun: --elastic currently requires --nnodes 1 (survivor "
            "renumbering is node-local; multi-node elasticity needs a "
            "cross-agent rendezvous)")

    world = args.nnodes * args.nprocs
    standalone = args.standalone or (args.nnodes == 1 and args.coordinator is None)
    if standalone:
        coordinator = f"127.0.0.1:{find_free_port()}" if world > 1 else ""
    else:
        if not args.coordinator:
            raise SystemExit("tpurun: --coordinator required for multi-node jobs "
                             "(or pass --standalone)")
        coordinator = args.coordinator

    from tpudist.launch.staging import job_tmpdir

    run_id = args.run_id or os.environ.get("SLURM_JOB_ID") or f"tpurun-{os.getpid()}"
    tmpdir = args.tmpdir or job_tmpdir()
    owns_tmpdir = tmpdir is None
    if owns_tmpdir:
        tmpdir = tempfile.mkdtemp(prefix=f"tpudist_{run_id}_")
        # Job-lifetime scratch: remove on agent exit only when we created it
        # (a scheduler-provided dir is the scheduler's to clean).
        import atexit
        import shutil
        atexit.register(shutil.rmtree, tmpdir, ignore_errors=True)
    tmpdir = str(tmpdir)
    os.makedirs(tmpdir, exist_ok=True)
    error_dir = args.error_dir or tmpdir
    os.makedirs(error_dir, exist_ok=True)

    # Telemetry: workers stream per-rank/per-generation span JSONL into one
    # dir; the agent merges it into report.json/report.md on exit — every
    # run (clean, crashed, preempted, restart-exhausted) ends with a
    # goodput report next to the crash records.
    from tpudist.telemetry import enabled_from_env as _telemetry_enabled

    telemetry_dir: Optional[str] = None
    if _telemetry_enabled():
        # Default placement must survive the agent: an agent-owned tmpdir
        # is rmtree'd at exit, which would delete the very report a
        # crashed run exists to leave behind — fall back to the bare-run
        # default (runs/telemetry, cwd) in that case.
        telemetry_dir = (args.telemetry_dir
                         or os.environ.get("TPUDIST_TELEMETRY_DIR")
                         or (os.path.join("runs", "telemetry") if owns_tmpdir
                             else os.path.join(tmpdir, "telemetry")))

    # The agent has no global telemetry session; staging phases and the
    # restart_exhausted / world_resized lifecycle events record into ONE
    # lazily-created agent stream (pseudo-rank = initial world +
    # node_rank: past every worker rank AND distinct per node, so agents
    # sharing a --telemetry-dir never clobber each other's stream).
    # Event-only, so the aggregator never counts it as a goodput rank.
    agent_tele: Dict[str, object] = {"session": None}
    agent_rank = world + args.node_rank

    def _agent_session():
        if not telemetry_dir or agent_tele["session"] is not None:
            return agent_tele["session"]
        try:
            from tpudist import telemetry as _tele

            agent_tele["session"] = _tele.TelemetrySession(
                telemetry_dir, rank=agent_rank, generation=0)
        except Exception:  # noqa: BLE001 — telemetry never kills the run
            pass
        return agent_tele["session"]

    # Live observability: the agent exposes /metrics /healthz /statusz
    # when TPUDIST_METRICS_PORT is set — fleet-level restart/resize state
    # that was stderr-only before.  Best-effort; never kills the run.
    agent_state = {"world": world, "generation": 0, "attempt_in_world": 0,
                   "nprocs": args.nprocs, "run_id": run_id,
                   "restarts_max": args.max_restarts, "elastic":
                   bool(getattr(args, "elastic", False))}
    try:
        from tpudist.telemetry import statusz as _statusz

        _agent_statusz = _statusz.ensure_started()
        if _agent_statusz is not None:
            _agent_statusz.register_status(
                "tpurun", lambda: dict(agent_state))
    except Exception:  # noqa: BLE001
        pass

    if args.stage_data:
        from tpudist.launch.staging import extract_tarballs
        from tpudist.utils.profiling import StageTimer

        stage_timer = StageTimer()
        with stage_timer.phase("stage_data"):
            extract_tarballs(args.stage_data.split(","), tmpdir)
        s = _agent_session()
        if s is not None:
            stage_timer.emit(session=s)

    # Preemption protocol: SLURM SIGTERMs the agent's process group; the
    # agent must survive it (forwarding to workers that missed the group
    # signal), wait out the workers' collective checkpoint save, and NOT
    # restart — the allocation is going away.  Handler installed only in
    # the main thread (CPython restriction); restored on exit so embedding
    # callers (tests) keep their own handlers.
    _preempt_state["flag"] = False
    prev_handler = None
    import threading

    in_main_thread = threading.current_thread() is threading.main_thread()
    if in_main_thread:
        prev_handler = signal.signal(signal.SIGTERM, _handle_agent_sigterm)
    try:
        max_attempts = args.max_restarts + 1
        nprocs = args.nprocs
        attempt_in_world = 0  # restarts consumed at the CURRENT world size
        generation = 0        # TPUDIST_RESTART_COUNT across ALL launches,
        #                       monotone through elastic resizes so every
        #                       telemetry stream / crash record is distinct
        while True:
            error_template = os.path.join(
                error_dir, f"error_attempt{generation}_rank%r.json")
            if generation > 0:
                backoff = args.restart_backoff * (
                    2 ** max(0, attempt_in_world - 1))
                print(f"[tpurun] restarting worker group (attempt "
                      f"{attempt_in_world + 1}/{max_attempts} at world "
                      f"{world}) in {backoff:.1f}s", file=sys.stderr)
                time.sleep(backoff)
                if standalone and world > 1:
                    # Fresh rendezvous port: the dead service may linger in
                    # TIME_WAIT.
                    coordinator = f"127.0.0.1:{find_free_port()}"
            if _preempt_state["flag"]:
                # SIGTERM landed between attempts (e.g. during backoff):
                # a fresh group would never have received the group
                # signal and would train until SLURM's SIGKILL — don't
                # launch onto a node being reclaimed.
                print("[tpurun] preemption signal during restart window; "
                      "not launching a new worker group", file=sys.stderr)
                return 1
            rc = _run_attempt(cmd, args, coordinator, world, run_id,
                              generation, error_template, tmpdir,
                              telemetry_dir=telemetry_dir, nprocs=nprocs)
            if rc == WATCHDOG_EXIT_CODE:
                # The hang watchdog aborted a wedged worker on purpose so
                # THIS restart loop could re-admit the group — say so (the
                # stall stack dump is in the crash record below).
                print("[tpurun] worker group aborted by the hang watchdog "
                      f"(exit {WATCHDOG_EXIT_CODE}): a stalled step or "
                      "wedged collective was detected", file=sys.stderr)
            if _preempt_state["flag"]:
                ok = rc == 0
                print("[tpurun] preemption: worker group "
                      f"{'saved and exited cleanly' if ok else f'exited rc={rc}'} "
                      "after SIGTERM; not restarting", file=sys.stderr)
                return 0 if ok else 1
            if rc == 0:
                return 0
            records = _read_crash_records(error_template, world)
            if records:
                first = records[0]
                print(f"[tpurun] first failure: rank {first.get('process_id')} "
                      f"{first.get('exc_type')}: {first.get('message')}",
                      file=sys.stderr)
                tb = first.get("traceback")
                if tb:
                    print(tb, file=sys.stderr)
            else:
                print(f"[tpurun] worker group failed (exit {rc}); no crash "
                      f"record written (segfault or unhandled signal?)",
                      file=sys.stderr)
            generation += 1
            attempt_in_world += 1
            agent_state.update(generation=generation,
                               attempt_in_world=attempt_in_world)
            if attempt_in_world < max_attempts:
                continue
            # Restart budget exhausted at this world size.  Stamp the
            # event into the merged report (exhaustion used to be
            # stderr-only — invisible to `tpudist.telemetry report`)...
            # Dead ranks = the CULPRITS only: the timestamp-first crash
            # record plus the agent's first observed spontaneous exit.
            # Victims of the cascade (ranks whose collectives error and
            # record before the agent's SIGTERM lands) must NOT count —
            # over-shrinking throws away healthy workers, while
            # under-shrinking is safe: a still-doomed smaller world just
            # exhausts again and shrinks again.
            dead = set(_last_failed_ranks)
            if records and isinstance(records[0].get("process_id"), int):
                dead.add(int(records[0]["process_id"]))
            dead = sorted(dead)
            first = records[0] if records else {}
            s = _agent_session()
            if s is not None:
                s.event("restart_exhausted", attempts=attempt_in_world,
                        world=world, dead_ranks=dead,
                        exc_type=first.get("exc_type"),
                        message=str(first.get("message", ""))[:200])
                s.flush()
            # ...then either give up (fixed-size semantics) or relaunch
            # the group at the SURVIVING world size (--elastic): the
            # crash records name the dead ranks, survivors renumber
            # 0..n'-1, and the workers rebuild their mesh from the new
            # TPUDIST_NUM_PROCESSES.  The trainer resumes through the
            # reshardable-checkpoint path.
            if args.elastic and world > 1:
                new_world = max(1, world - max(1, len(dead)))
                print(f"[tpurun] elastic: restart budget exhausted at "
                      f"world {world}; relaunching at surviving world "
                      f"{new_world} (dead ranks: {dead or 'unknown'})",
                      file=sys.stderr)
                if s is not None:
                    s.event("world_resized", from_world=world,
                            to_world=new_world, generation=generation,
                            dead_ranks=dead)
                    s.flush()
                world = nprocs = new_world
                attempt_in_world = 0
                agent_state.update(world=world, nprocs=nprocs,
                                   attempt_in_world=0)
                if standalone:
                    coordinator = (f"127.0.0.1:{find_free_port()}"
                                   if world > 1 else "")
                continue
            print(f"[tpurun] giving up after {attempt_in_world} attempts "
                  f"at world {world}", file=sys.stderr)
            return 1
    finally:
        session = agent_tele["session"]
        if session is not None:
            try:
                session.close()
            except Exception:  # noqa: BLE001
                pass
        if in_main_thread and prev_handler is not None:
            try:
                signal.signal(signal.SIGTERM, prev_handler)
            except (ValueError, OSError):
                pass
        # Every exit path — clean, crashed, preempted, restart-exhausted —
        # ends with the merged goodput report next to the crash records
        # (a crashed run is exactly the one whose wall-clock needs
        # attributing).  A run-training rank 0 may already have written
        # one at its own finalize; the agent's merge supersedes it with
        # the view joined across ALL generations.
        _emit_telemetry_report(telemetry_dir)


def _emit_telemetry_report(telemetry_dir: Optional[str]) -> None:
    """Merge the workers' telemetry into report.json/report.md and print
    the headline.  Best-effort by design: report failure must never mask
    the run's own exit status."""
    if not telemetry_dir:
        return
    try:
        from tpudist.telemetry.aggregate import write_reports

        report, paths = write_reports(telemetry_dir)
        if report.get("num_records", 0) == 0:
            return
        g = report["goodput"]
        print(
            f"[tpurun] goodput report ({paths['md'] or telemetry_dir}): "
            f"wall {report['wall_clock_s']:.1f}s over "
            f"{report['num_ranks']} rank(s) x "
            f"{report['generations']} generation(s) — "
            f"step {g['step']['frac'] * 100:.0f}%, "
            f"compile {g['compile']['frac'] * 100:.0f}%, "
            f"data {g['data']['frac'] * 100:.0f}%, "
            f"ckpt {g['ckpt']['frac'] * 100:.0f}%, "
            f"idle {g['idle']['frac'] * 100:.0f}%, "
            f"resize {g.get('resize', {}).get('frac', 0.0) * 100:.0f}%, "
            f"lost-to-restart {g['lost_restart']['frac'] * 100:.0f}%",
            file=sys.stderr,
        )
    except Exception as e:  # noqa: BLE001 — never mask the run's status
        print(f"[tpurun] telemetry report failed: {type(e).__name__}: {e}",
              file=sys.stderr)


if __name__ == "__main__":
    sys.exit(main())
