"""Launch layer — the TPU-native replacement for the reference's L2/L3 bash
system (``hpc_files/`` + ``interactive_job_cmds/``, SURVEY.md §2.2, §7.7).

Components:

- :mod:`tpudist.launch.run` — ``tpurun`` (``python -m tpudist.launch``): the
  torchrun-equivalent per-node process agent.  Spawns N worker processes with
  the ``TPUDIST_*`` env contract, supervises them, captures crash records,
  and implements ``--max-restarts`` whole-job restart with backoff
  (``torchrun_launcher.sh:16-19`` parity — JAX's coordination service is not
  per-process elastic, so restarts are whole-node-agent, SURVEY.md §5.3).
- :mod:`tpudist.launch.staging` — data-staging tarball contract
  (``job_submitter.sh:166-174`` create side; ``torchrun_launcher.sh:35-40``
  extract side).
- :mod:`tpudist.launch.sweep` — W&B-style grid sweeps without the W&B server:
  YAML grid spec, combination counting (``count_sweeps.bash`` parity), and a
  local agent that runs the i-th configuration
  (``sweeper.yml`` / ``sweep_cmd.txt`` parity).

The cluster-facing bash front door (SLURM ``job_submitter`` equivalent and a
gcloud TPU-pod ``--worker=all`` dispatcher) lives in ``launch/`` at the repo
root, mirroring the reference's ``hpc_files/`` placement.
"""

from tpudist.launch.staging import create_tarball, extract_tarballs  # noqa: F401
from tpudist.launch.sweep import SweepSpec  # noqa: F401
