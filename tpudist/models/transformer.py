"""Decoder-only Transformer LM — the long-context model family.

The reference's only model is a 5-layer MLP on 2-dim inputs
(``toy_model_and_data.py:12-22``); this family is the capability extension
that gives the sequence-parallel machinery (``tpudist.parallel``) and the
Pallas attention kernel (``tpudist.ops``) a real consumer, designed
TPU-first:

- **pluggable attention op**: the block calls an injected
  ``attention_fn(q, k, v) -> out`` over ``[batch, heads, seq, head_dim]``.
  Three interchangeable implementations ship: the dense XLA reference
  (:func:`tpudist.parallel.attention_reference`), the Pallas flash kernel
  (:func:`tpudist.ops.flash_attention`), and ring attention over a
  ``seq``-sharded mesh (:func:`tpudist.parallel.make_ring_attention`) —
  all numerically identical (tests assert it), so single-chip and
  multi-chip long-context runs share one model definition.
- **static shapes, pre-LN, bias-free projections** — the standard
  XLA-friendly decoder block; everything jits into one program.
- DP×SP training: batch sharded over ``data``, sequence over ``seq``; the
  ring closure carries its own shard_map, the rest of the network is
  elementwise/feature-contracting so pjit keeps activations sharded as
  ``P(data, seq, None)`` throughout.
"""

from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from tpudist.parallel.ring_attention import attention_reference

AttentionFn = Callable[[jax.Array, jax.Array, jax.Array], jax.Array]


def make_length_aware_attention(window: Optional[int] = None):
    """Build the platform/length-aware single-device causal attention:
    dense XLA for short sequences (lowest dispatch overhead), the Pallas
    flash kernel on TPU / the blockwise XLA formulation elsewhere.
    Crossover measured on-chip (benchmarks/flash_sweep.py): flash fwd+bwd
    wins 3× at 1024 and 3.1× at 2048; dense wins below 1024.

    ``window``: sliding-window (local) attention — the flash kernels mask
    to the band and elide tiles outside it on both sides (compute scales
    with window, not seq); the non-kernel paths mask the dense scores.

    The result accepts grouped-query K/V (fewer heads than q): the flash
    kernels consume it natively — KV tiles are fetched once per group,
    never materialized at full head count; the non-kernel paths broadcast.
    """
    def attend(q, k, v):
        from tpudist.utils.tuning import tuned

        # Measured-on-v5e defaults, re-tunable per platform generation
        # via TPUDIST_FLASH_* env vars (tpudist.utils.tuning).
        min_seq = tuned("flash_min_seq")
        bq = tuned("flash_block_q")
        # Wider KV tiles amortize the per-tile grid overhead once the KV
        # sweep is long (8192: 6.8 vs 8.7 ms fwd+bwd — flash_sweep).
        bk_long = tuned("flash_block_k_long")
        seq = q.shape[2]
        bk = (bk_long if seq >= tuned("flash_long_seq")
              and seq % bk_long == 0 else tuned("flash_block_k"))
        # BOTH tile sizes must divide seq (the kernel's contract) — with
        # independently overridable knobs a bad combination routes to the
        # fallbacks instead of crashing at trace time.
        blocks_fit = seq >= min_seq and seq % bq == 0 and seq % bk == 0
        use_flash = blocks_fit and jax.devices()[0].platform == "tpu"
        if not use_flash and k.shape[1] != q.shape[1]:
            # only the flash kernels consume grouped K/V natively
            group = q.shape[1] // k.shape[1]
            k = jnp.repeat(k, group, axis=1)
            v = jnp.repeat(v, group, axis=1)
        if use_flash:
            from tpudist.ops import flash_attention

            return flash_attention(q, k, v, True, bq, bk, False, window)
        if not blocks_fit:
            return attention_reference(q, k, v, causal=True, window=window)
        from tpudist.ops import blockwise_attention

        return blockwise_attention(q, k, v, causal=True, block_k=bk,
                                   window=window)

    # Block consults this tag before broadcasting K/V to full head count —
    # this path handles grouped-query inputs itself (see above).
    attend.supports_gqa = True
    # Block's training-path guard checks this tag against its
    # sliding_window field (decode-cache masking alone is not windowed
    # training — the mismatch must be loud, not silent).
    attend.window = window
    return attend


_default_attention = make_length_aware_attention()


def rope_rotate(x: jax.Array, base: float = 10000.0, offset=0) -> jax.Array:
    """Rotary position embedding over ``[batch, heads, seq, head_dim]``.

    Angles are computed in f32 (precision-sensitive at long context) on the
    GLOBAL sequence axis — callers apply it before any seq sharding, so
    ring-attention shards see correct absolute positions.  Half-split
    rotation (GPT-NeoX convention).  ``offset`` (static or traced scalar,
    or a ``[batch]`` vector for the slot-batched paged-kernel decode path
    where every lane sits at its own cursor) shifts positions — the
    KV-cache decode path rotates tokens at their absolute position.
    """
    half = x.shape[-1] // 2
    freqs = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    off = jnp.asarray(offset, jnp.float32)
    positions = off[..., None] + jnp.arange(x.shape[-2], dtype=jnp.float32)
    angles = positions[..., None] * freqs            # [(b,) s, half]
    if off.ndim:
        # per-batch offsets: broadcast over the heads axis
        angles = angles[:, None]                     # [b, 1, s, half]
    sin, cos = jnp.sin(angles), jnp.cos(angles)
    # rotate in f32 (position precision at long context), cast back after
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    ).astype(x.dtype)


def moe_expert_fn(params, tokens):
    """The expert used by the MoE FFN: relu(x·w)·wo — shared between the
    sharded execution path (``tpudist.parallel.moe``) and the dense
    reference below, so they cannot drift."""
    return jax.nn.relu(tokens @ params["w"]) @ params["wo"]


def dense_moe_reference(params, tokens):
    """Single-device MoE execution: every expert computed for every token,
    combined by the top-1 gate.  Matches ``moe_shard`` exactly when no
    token overflows capacity; used at init time and on unsharded runs."""
    # Routing in f32 (precision-sensitive), expert matmuls in the compute
    # dtype — mirrors moe_shard's discipline exactly.
    probs = jax.nn.softmax((tokens @ params["router"]).astype(jnp.float32),
                           axis=-1)
    idx = jnp.argmax(probs, axis=-1)
    gate = jnp.take_along_axis(probs, idx[:, None], axis=-1)[:, 0]
    h = jax.nn.relu(jnp.einsum("td,edf->tef", tokens, params["experts"]["w"]))
    y_all = jnp.einsum("tef,efd->ted", h, params["experts"]["wo"])
    pick = jax.nn.one_hot(idx, probs.shape[-1], dtype=tokens.dtype)
    return jnp.einsum("ted,te->td", y_all,
                      pick * gate.astype(tokens.dtype)[:, None])


class MoEFFN(nn.Module):
    """Switch-style FFN: top-1 routed experts.  ``moe_fn`` (built with
    :func:`tpudist.parallel.make_moe` over a ``model``-axis mesh) runs the
    expert-parallel path; without it the dense reference executes — same
    parameters either way, so init and single-device runs need no mesh."""

    d_model: int
    d_ff: int
    n_experts: int
    moe_fn: Optional[Callable] = None
    dtype: jnp.dtype = jnp.float32  # compute dtype; params stay f32 masters

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, s, d = x.shape
        init = nn.initializers.lecun_normal()
        params = {
            "router": self.param("router", init, (d, self.n_experts)),
            "experts": {
                "w": self.param("w", init, (self.n_experts, d, self.d_ff)),
                "wo": self.param("wo", init, (self.n_experts, self.d_ff, d)),
            },
        }
        # Same mixed-precision contract as the Dense layers: f32 master
        # params cast to the compute dtype here; the routing softmax inside
        # both execution paths upcasts to f32.
        params = jax.tree.map(lambda a: a.astype(self.dtype), params)
        tokens = x.reshape(b * s, d).astype(self.dtype)
        if self.moe_fn is not None:
            y, stats = self.moe_fn(params, tokens)
            # Routing observability: collected by train steps built with
            # ``aux=True`` (make_lm_train_step) and logged host-side — the
            # reference's reduce-then-log-on-rank-0 discipline (SURVEY.md
            # §5.5) applied to expert load.
            self.sow("intermediates", "moe_dropped_fraction",
                     stats.dropped_fraction)
            self.sow("intermediates", "moe_expert_load", stats.expert_load)
            # Differentiable Switch/GShard balance loss — added to the LM
            # loss by make_lm_train_step(moe_balance_weight=...).
            self.sow("intermediates", "moe_balance_loss", stats.balance_loss)
        else:
            y = dense_moe_reference(params, tokens)
        return y.reshape(b, s, d)


class _Kernel(nn.Module):
    """Declares a Dense-compatible ``kernel`` param WITHOUT the matmul —
    the injection seam for externally-computed linear layers (the
    overlapped FSDP MLP).  Named like the ``nn.Dense`` it replaces, the
    param path (``block_i/wi/kernel``) and init (lecun_normal, same rng
    fold — flax folds by path) are IDENTICAL to the dense twin, so
    checkpoints, sharding rules, and parity tests see one param tree
    regardless of which execution path runs."""

    shape: tuple

    @nn.compact
    def __call__(self):
        return self.param("kernel", nn.initializers.lecun_normal(),
                          self.shape)


class Block(nn.Module):
    d_model: int
    n_heads: int
    d_ff: int
    attention_fn: AttentionFn
    n_experts: int = 0  # 0 = dense FFN; >0 = MoE FFN with that many experts
    moe_fn: Optional[Callable] = None
    # Pluggable dense-FFN execution (the attention_fn pattern applied to
    # the MLP): ``mlp_fn(params, x) -> y`` with
    # ``params = {"wi": [d, ff], "wo": [ff, d]}`` kernels (cast to the
    # compute dtype) and ``x: [b, s, d]`` the post-LN activations;
    # the residual add stays here.  Param tree is identical to the
    # built-in wi/wo Dense pair (see _Kernel), so the two paths are
    # checkpoint/sharding-compatible and parity-testable.  Used by the
    # overlapped FSDP layer compute
    # (tpudist.parallel.fsdp.overlap_fsdp_mlp).  Mutually exclusive
    # with the MoE FFN.
    mlp_fn: Optional[Callable] = None
    dtype: jnp.dtype = jnp.float32  # compute dtype; params stay f32 masters
    rope: bool = False  # rotary q/k position encoding (no learned pos table)
    # Grouped-query attention: project K/V at this many heads (must divide
    # n_heads; None = n_heads = plain MHA).  Attention fns tagged
    # ``supports_gqa`` (the default flash path) consume grouped K/V
    # natively; others get K/V broadcast to full heads.  The decode cache
    # stores only n_kv_heads either way (the GQA memory win).
    n_kv_heads: Optional[int] = None
    # Sliding-window size for the DECODE cache mask (training-time
    # windowing lives in attention_fn — TransformerLM threads both).
    sliding_window: Optional[int] = None
    # Autoregressive decode mode: single-token inputs attend over a
    # ``max_len`` K/V cache carried in the flax "cache" collection.
    decode: bool = False
    max_len: int = 2048  # cache length (decode only)
    # Per-slot parameter indirection (per-tenant adapters,
    # tpudist.models.lora): rank of the LoRA factor pairs applied
    # around the qkv/wi/wo projections.  0 = the seam is compiled out
    # (byte-identical program to the pre-adapter Block).  > 0: apply()
    # must supply an "adapters" collection — per layer the factor
    # leaves {a_qkv, b_qkv, a_wi, b_wi, a_wo, b_wo} plus the ``on``
    # mask (scalar for one lane, [batch] for a slot-batched program);
    # the projection output becomes ``where(on, y + (x·A)·B, y)`` — a
    # SELECT, so an off lane is bit-exact base.  The same seam later
    # serves multi-model and MoE routing: anything per-slot that picks
    # parameters rides in as gathered data, never as a new program.
    lora_rank: int = 0
    # Decode-attention execution (decode mode only) — the third arm of
    # the attention dispatch (reference / flash are the training arms):
    #   None      — the dense cached softmax below (the gather path:
    #               a paged engine gathers its pool to a dense view
    #               first, a dense engine owns the arena outright);
    #   "paged"   — the Pallas paged-attention kernel
    #               (tpudist.ops.paged_attention): the block table is
    #               walked INSIDE the kernel, so only live blocks are
    #               fetched.  The cache collection then carries a small
    #               WINDOW buffer instead of a [max_len] arena, and the
    #               block pool rides in through the read-only "pool"
    #               collection ({pk, pv, sk, sv, table, pos0} per
    #               layer) — built by the slot-decode programs
    #               (tpudist.models.generate), never flax-initialized.
    #   "paged_prefill" — the Pallas paged-PREFILL kernel
    #               (tpudist.ops.paged_prefill): multi-token chunks per
    #               slot attend over the pool prefix AND emit their
    #               quantized KV block writes in-kernel, sown into a
    #               "pwrites" collection the slot-decode program commits
    #               (no dense lane view, no sequential teacher-force).
    decode_kernel: Optional[str] = None
    # static layer index into the [L, ...] pool (decode_kernel only)
    layer_idx: int = 0
    # Fused RoPE+QKV projection (tpudist.ops.fused_linear.fused_rope_qkv)
    # on the paged decode/prefill arms: the qkv matmul, head split, and
    # rotary rotation run as one kernel on the per-slot cursor vector;
    # the attention arm receives q/k already rotated.  Param tree is
    # unchanged (_Kernel declares the same qkv/kernel param).
    fused_rope: bool = False
    # In-kernel LoRA gather-matmul (tpudist.ops.fused_linear.lora_delta):
    # the "adapters" collection carries the FULL factor pools plus the
    # per-slot ``ids`` vector (tpudist.models.lora.pool_collection)
    # instead of pre-gathered factors — each slot's grid step DMAs only
    # its own factor block.  Batched slot programs only (the vmapped
    # gather path keeps gather_collection).
    lora_kernel: bool = False

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        dh = self.d_model // self.n_heads
        n_kv = self.n_heads if self.n_kv_heads is None else self.n_kv_heads
        if not 1 <= n_kv <= self.n_heads or self.n_heads % n_kv:
            raise ValueError(
                f"n_kv_heads {n_kv} must be in [1, {self.n_heads}] and "
                f"divide n_heads {self.n_heads}")
        kv_dim = n_kv * dh
        # -- per-tenant adapter seam (lora_rank > 0): the gathered
        # factor collection rides in through apply() like the paged
        # kernel's pool — never flax-initialized (is_initializing skips
        # it: the seam adds no params and no cache).
        ad = None
        if self.lora_rank > 0 and not self.is_initializing():
            if self.n_experts > 0 or self.mlp_fn is not None:
                raise ValueError(
                    "lora_rank adapters wrap the plain qkv/wi/wo Dense "
                    "path; they cannot compose with an MoE FFN or an "
                    "injected mlp_fn (the fused MLP hides the wi/wo seam)")
            keys = ("a_qkv", "b_qkv", "a_wi", "b_wi", "a_wo", "b_wo", "on")
            if self.lora_kernel:
                keys = keys + ("ids",)   # pool form: full pools + ids
            ad = {k: self.get_variable("adapters", k) for k in keys}
            if ad["a_qkv"] is None:
                raise ValueError(
                    "lora_rank > 0 requires an 'adapters' collection "
                    "(tpudist.models.lora.gather_collection / "
                    "adapter_collection) supplied with apply()")

        def _delta(h_in, a_key, b_key):
            """The raw adapter delta ``(h·A)·B`` — in-graph against the
            pre-gathered factors, or through the Pallas gather-matmul
            when lora_kernel (the pool rides whole; each slot's grid
            step DMAs only its own factor block)."""
            if self.lora_kernel:
                from tpudist.ops.fused_linear import lora_delta
                interpret = jax.devices()[0].platform != "tpu"
                return lora_delta(
                    h_in.astype(self.dtype), ad[a_key], ad[b_key],
                    ad["ids"], layer=self.layer_idx, interpret=interpret)
            a = ad[a_key].astype(self.dtype)
            bm = ad[b_key].astype(self.dtype)
            return (h_in.astype(self.dtype) @ a) @ bm

        def _ad(y, h_in, a_key, b_key):
            """``where(on, y + (h·A)·B, y)`` — the adapter delta as a
            SELECT: an off lane's output is the base tensor bit-exactly
            (clamped-gather garbage in A/B is deselected, the KV-mask
            discipline applied to parameters)."""
            if ad is None:
                return y
            delta = _delta(h_in, a_key, b_key)
            on = jnp.asarray(ad["on"])
            m = on.reshape(on.shape + (1,) * (y.ndim - on.ndim))
            return jnp.where(m, y + delta, y)

        # LayerNorm statistics in f32 for stability; projections compute in
        # ``dtype`` (flax casts inputs + the f32 master params at apply).
        h = nn.LayerNorm(use_bias=False, dtype=jnp.float32)(x)
        use_fused_qkv = self.fused_rope and not self.is_initializing()
        if use_fused_qkv and not (
                self.decode
                and self.decode_kernel in ("paged", "paged_prefill")):
            raise ValueError(
                "fused_rope fuses the QKV projection with the per-slot "
                "rope offsets of the paged decode/prefill arms — set "
                "decode_kernel='paged'/'paged_prefill' (training and the "
                "dense decode path keep the unfused projection)")
        rotated = False
        if use_fused_qkv:
            from tpudist.ops.fused_linear import fused_rope_qkv
            # same qkv/kernel param as the Dense twin (_Kernel seam)
            w = _Kernel((self.d_model, self.d_model + 2 * kv_dim),
                        name="qkv")()
            if self.decode_kernel == "paged":
                offs = self.get_variable("cache", "idx")  # absolute cursors
            else:
                offs = self.get_variable("pool", "pos0")  # chunk starts
            extra = on = None
            if ad is not None:
                extra = _delta(h, "a_qkv", "b_qkv")
                on = jnp.asarray(ad["on"]).astype(jnp.int32)
            q, k, v = fused_rope_qkv(
                h.astype(self.dtype), w.astype(self.dtype),
                offs.astype(jnp.int32), extra, on,
                n_heads=self.n_heads, n_kv=n_kv, dh=dh, rope=self.rope,
                interpret=jax.devices()[0].platform != "tpu")
            rotated = True
        else:
            qkv = nn.Dense(self.d_model + 2 * kv_dim, use_bias=False,
                           name="qkv", dtype=self.dtype)(h)
            qkv = _ad(qkv, h, "a_qkv", "b_qkv")
            q = qkv[..., : self.d_model]
            k = qkv[..., self.d_model : self.d_model + kv_dim]
            v = qkv[..., self.d_model + kv_dim :]

            def heads(t, n):  # [b, s, n·dh] -> [b, n, s, dh]
                b, s, _ = t.shape
                return t.reshape(b, s, n, dh).transpose(0, 2, 1, 3)

            q = heads(q, self.n_heads)
            k = heads(k, n_kv)
            v = heads(v, n_kv)
        if self.decode:
            if self.decode_kernel == "paged":
                attn = self._decode_attention_paged(q, k, v,
                                                    rotated=rotated)
            elif self.decode_kernel == "paged_prefill":
                attn = self._prefill_attention_paged(q, k, v,
                                                     rotated=rotated)
            elif self.decode_kernel is not None:
                raise ValueError(
                    f"unknown decode_kernel {self.decode_kernel!r} "
                    "(None = dense cached softmax, 'paged' = the Pallas "
                    "paged-attention decode kernel, 'paged_prefill' = "
                    "the Pallas paged-prefill kernel)")
            else:
                attn = self._decode_attention(q, k, v)
        else:
            if self.sliding_window is not None and getattr(
                    self.attention_fn, "window", None) != self.sliding_window:
                # sliding_window alone only masks the decode cache; a
                # non-windowed attention_fn would train full-causal and
                # decode windowed.  TransformerLM/pipeline_lm thread a
                # matching windowed fn — raw Block users must too (fns
                # built by make_length_aware_attention / make_ring_attention
                # carry a ``window`` tag).
                raise ValueError(
                    "Block.sliding_window is set but attention_fn is not "
                    "tagged with a matching window — inject an attention_fn "
                    "built with the same window (e.g. "
                    "make_length_aware_attention(window)), or tag a custom "
                    "fn with .window")
            if self.rope:
                q, k = rope_rotate(q), rope_rotate(k)
            if n_kv != self.n_heads and not getattr(
                    self.attention_fn, "supports_gqa", False):
                group = self.n_heads // n_kv
                k = jnp.repeat(k, group, axis=1)
                v = jnp.repeat(v, group, axis=1)
            attn = self.attention_fn(q, k, v)
        b, nh, s, _ = attn.shape
        attn = attn.transpose(0, 2, 1, 3).reshape(b, s, self.d_model)
        x = x + nn.Dense(self.d_model, use_bias=False, name="proj",
                         dtype=self.dtype)(attn)

        h = nn.LayerNorm(use_bias=False, dtype=jnp.float32)(x)
        if self.n_experts > 0:
            if self.mlp_fn is not None:
                raise ValueError(
                    "mlp_fn replaces the dense FFN; it cannot compose "
                    "with the MoE FFN (n_experts > 0)")
            return x + MoEFFN(self.d_model, self.d_ff, self.n_experts,
                              self.moe_fn, dtype=self.dtype, name="moe")(h)
        if self.mlp_fn is not None:
            wi = _Kernel((self.d_model, self.d_ff), name="wi")()
            wo = _Kernel((self.d_ff, self.d_model), name="wo")()
            # Same mixed-precision contract as the Dense twins: f32
            # master kernels cast to the compute dtype at apply.
            y = self.mlp_fn(
                {"wi": wi.astype(self.dtype), "wo": wo.astype(self.dtype)},
                h.astype(self.dtype))
            return x + y
        hin = h
        h = nn.Dense(self.d_ff, use_bias=False, name="wi",
                     dtype=self.dtype)(hin)
        h = _ad(h, hin, "a_wi", "b_wi")
        h = nn.gelu(h)
        y = nn.Dense(self.d_model, use_bias=False, name="wo",
                     dtype=self.dtype)(h)
        return x + _ad(y, h, "a_wo", "b_wo")

    def _decode_attention(self, q, k, v):
        """Cached attention over a decode WINDOW of ``s >= 1`` tokens:
        write the window's K/V at the cache cursor, attend each query
        causally over the filled prefix plus the window tokens before it
        (per-query mask row ``arange(max_len) <= pos + i``).  ``s == 1``
        is the classic single-token decode step; ``s > 1`` is the
        speculative-decoding verify pass — K+1 drafted tokens scored
        against the cache in ONE forward, so the weights and the KV
        arena stream once per window instead of once per token (the
        fewer-HBM-sweeps-per-token lever the decode roofline left).
        Static shapes ([max_len] cache, masks instead of slicing) keep
        every window size one compiled program.  The cache is sized by
        the K/V head count — GQA models pay n_kv_heads/n_heads of the
        MHA cache."""
        b, nh, s, dh = q.shape
        n_kv = k.shape[1]
        ck = self.variable("cache", "k", jnp.zeros,
                           (b, n_kv, self.max_len, dh), self.dtype)
        cv = self.variable("cache", "v", jnp.zeros,
                           (b, n_kv, self.max_len, dh), self.dtype)
        ci = self.variable("cache", "idx",
                           lambda: jnp.zeros((), jnp.int32))
        pos = ci.value
        if self.rope:
            q = rope_rotate(q, offset=pos)
            k = rope_rotate(k, offset=pos)
        ck.value = jax.lax.dynamic_update_slice(
            ck.value, k.astype(self.dtype), (0, 0, pos, 0))
        cv.value = jax.lax.dynamic_update_slice(
            cv.value, v.astype(self.dtype), (0, 0, pos, 0))
        ci.value = pos + s
        scale = dh ** -0.5
        # grouped einsums read the un-repeated cache directly — per-step
        # bandwidth scales with n_kv_heads, the actual GQA win
        group = nh // n_kv
        qg = q.reshape(b, n_kv, group, s, dh)
        scores = jnp.einsum("bngqd,bnkd->bngqk", qg, ck.value,
                            preferred_element_type=jnp.float32) * scale
        # per-query causal rows: window token i sees cache <= pos + i
        qpos = pos + jnp.arange(s)
        live = jnp.arange(self.max_len)[None, :] <= qpos[:, None]
        if self.sliding_window is not None:
            live &= (jnp.arange(self.max_len)[None, :]
                     > qpos[:, None] - self.sliding_window)
        scores = jnp.where(live[None, None, None, :, :], scores, -1e30)
        w = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bngqk,bnkd->bngqd", w.astype(self.dtype), cv.value,
                         preferred_element_type=jnp.float32)
        return out.reshape(b, nh, s, dh).astype(q.dtype)

    def _decode_attention_paged(self, q, k, v, rotated=False):
        """Cached decode attention through the Pallas paged-attention
        kernel (:func:`tpudist.ops.paged_attention`): the KV pool stays
        paged — the kernel walks this slot batch's block tables in its
        grid and fetches only live blocks, so no dense ``[max_len]``
        view is ever materialized and bytes/token track live KV.

        Runs BATCHED over the slot axis (``b = num_slots``), not
        vmapped like the gather path: the kernel's grid covers every
        slot in one call, so per-slot cursors ride in as vectors.  The
        cache collection carries, per layer, a small decode-WINDOW
        buffer (``k``/``v`` ``[b, n_kv, W, dh]`` — this dispatch's
        uncommitted tokens; the slot-decode program commits them to the
        pool post-scan) and the per-slot absolute cursor ``idx [b]``;
        the pool itself ({pk, pv, sk, sv, table, pos0}) rides in
        read-only through the "pool" collection.  The same per-query
        causal window mask serves s == 1 decode and the s == K+1
        speculative verify pass (it is fused into the kernel)."""
        b, nh, s, dh = q.shape

        def _missing():
            raise ValueError(
                "decode_kernel='paged' caches are window views built by "
                "the slot-decode programs (tpudist.models.generate.make_"
                "slot_decode(attn_kernel='paged')) — they are supplied "
                "with apply(), never flax-initialized")

        pool_k = self.get_variable("pool", "pk")
        pool_v = self.get_variable("pool", "pv")
        scale_k = self.get_variable("pool", "sk")
        scale_v = self.get_variable("pool", "sv")
        table = self.get_variable("pool", "table")
        pos0 = self.get_variable("pool", "pos0")
        if pool_k is None:
            _missing()
        ck = self.variable("cache", "k", _missing)
        cv = self.variable("cache", "v", _missing)
        ci = self.variable("cache", "idx", _missing)
        pos = ci.value                      # [b] absolute cursors
        fill = (pos - pos0).astype(jnp.int32)   # window tokens already in
        if self.rope and not rotated:
            q = rope_rotate(q, offset=pos)
            k = rope_rotate(k, offset=pos)
        # append this call's K/V at each lane's window offset (the
        # kernel consumes them as the walk's final virtual block)
        ck.value = jax.vmap(
            lambda buf, kk, f: jax.lax.dynamic_update_slice(
                buf, kk, (0, f, 0)))(ck.value, k.astype(self.dtype), fill)
        cv.value = jax.vmap(
            lambda buf, vv, f: jax.lax.dynamic_update_slice(
                buf, vv, (0, f, 0)))(cv.value, v.astype(self.dtype), fill)
        ci.value = pos + s
        from tpudist.ops.paged_attention import paged_attention

        # interpret mode = the tier-1 CPU path (the flash-kernel rule)
        interpret = jax.devices()[0].platform != "tpu"
        return paged_attention(
            q, pool_k, pool_v, scale_k, scale_v, table,
            pos0.astype(jnp.int32), fill, ck.value, cv.value,
            layer=self.layer_idx, window=self.sliding_window,
            interpret=interpret)

    def _prefill_attention_paged(self, q, k, v, rotated=False):
        """Chunked-prefill attention through the Pallas paged-PREFILL
        kernel (:func:`tpudist.ops.paged_prefill`): every slot's
        multi-token chunk attends over its pool prefix (walked in-kernel
        via the block table) plus itself (causal), and the blocks the
        chunk touches are quantized and emitted IN-KERNEL — sown into
        the mutable "pwrites" collection (per layer: ``k``/``v``
        ``[S, Mw, n_kv, bs, dh]`` storage dtype + ``sk``/``sv``
        ``[S, Mw, n_kv]`` scales) for the slot-decode program to
        scatter with ``_Paged.commit_quantized``.  No dense lane view,
        no sequential teacher-force scan: the whole admission batch
        prefills in one dispatch per layer.

        The pool collection carries two extra leaves next to the decode
        arm's: ``clen [S]`` (each lane's live chunk length) and
        ``wtable [S, Mw]`` (the touched blocks' physical ids, sentinel
        past the lane's span — ``_Paged.write_tables``)."""

        def _missing():
            raise ValueError(
                "decode_kernel='paged_prefill' pools are built by the "
                "slot-decode prefill programs (tpudist.models.generate."
                "make_slot_decode(prefill_kernel=True)) — they are "
                "supplied with apply(), never flax-initialized")

        pool_k = self.get_variable("pool", "pk")
        pool_v = self.get_variable("pool", "pv")
        scale_k = self.get_variable("pool", "sk")
        scale_v = self.get_variable("pool", "sv")
        table = self.get_variable("pool", "table")
        pos0 = self.get_variable("pool", "pos0")
        clen = self.get_variable("pool", "clen")
        wtable = self.get_variable("pool", "wtable")
        if pool_k is None or wtable is None:
            _missing()
        if self.rope and not rotated:
            q = rope_rotate(q, offset=pos0)
            k = rope_rotate(k, offset=pos0)
        from tpudist.ops.paged_prefill import paged_prefill_attention

        interpret = jax.devices()[0].platform != "tpu"
        o, qk, qv, sk, sv = paged_prefill_attention(
            q, k.astype(self.dtype), v.astype(self.dtype),
            pool_k, pool_v, scale_k, scale_v, table, wtable,
            pos0.astype(jnp.int32), clen.astype(jnp.int32),
            layer=self.layer_idx, window=self.sliding_window,
            interpret=interpret)
        self.put_variable("pwrites", "k", qk)
        self.put_variable("pwrites", "v", qv)
        self.put_variable("pwrites", "sk", sk)
        self.put_variable("pwrites", "sv", sv)
        return o


class TransformerLM(nn.Module):
    """Causal LM: token + learned position embeddings, N pre-LN blocks,
    tied-free output head."""

    vocab: int = 256
    d_model: int = 128
    n_layers: int = 2
    n_heads: int = 4
    d_ff: int = 512
    max_len: int = 2048
    attention_fn: Optional[AttentionFn] = None
    n_experts: int = 0  # >0: MoE FFN in every block (expert parallelism)
    moe_fn: Optional[Callable] = None
    # Pluggable dense-FFN execution in every block (see Block.mlp_fn) —
    # e.g. the overlapped FSDP MLP (parallel/fsdp.py overlap_fsdp_mlp).
    mlp_fn: Optional[Callable] = None
    # Compute dtype.  bf16 = mixed precision: f32 master params (flax
    # param_dtype default) cast to bf16 at apply, matmuls at bf16 MXU
    # throughput, f32 LayerNorm/softmax/loss — grads land f32 for the
    # optimizer.  The Lightning ``precision=`` analog for the LM family.
    dtype: jnp.dtype = jnp.float32
    # Rotary position encoding on q/k instead of the learned position
    # table — length-extrapolating, the modern long-context default.
    rope: bool = False
    # Grouped-query attention (Llama-2/Mistral style): K/V heads shared by
    # groups of query heads; halves-or-better the decode KV cache.
    n_kv_heads: Optional[int] = None  # None = n_heads (MHA)
    # Sliding-window (local) attention: each token attends to the previous
    # ``sliding_window`` positions only (Mistral-style).  Ignored when a
    # custom attention_fn is injected (compose the window there).
    sliding_window: Optional[int] = None
    # KV-cache decode mode (see tpudist.models.generate): one token per
    # call, positions tracked in the flax "cache" collection.
    decode: bool = False
    # Decode-attention arm (see Block.decode_kernel): None = dense
    # cached softmax over a gathered/dense arena, "paged" = the Pallas
    # paged-attention kernel walking the block pool in place (the
    # slot-batched path — cursors become [batch] vectors).
    decode_kernel: Optional[str] = None
    # Per-tenant adapter seam in every block (see Block.lora_rank):
    # 0 compiles it out; > 0 makes apply() take an "adapters"
    # collection of gathered rank-r LoRA factors (tpudist.models.lora).
    lora_rank: int = 0
    # Fused RoPE+QKV projection on the paged decode/prefill arms
    # (see Block.fused_rope).
    fused_rope: bool = False
    # In-kernel LoRA gather-matmul (see Block.lora_kernel): the
    # "adapters" collection carries full pools + per-slot ids.
    lora_kernel: bool = False
    # Rematerialize each block in the backward pass (jax.checkpoint):
    # activation memory drops from O(layers × per-block internals) to the
    # block boundaries, at ~1 extra forward of FLOPs — the lever that fits
    # d_model≥1024 configs in HBM.  Identical numerics (tests assert it).
    remat: bool = False
    # What the remat'd backward may keep (jax.checkpoint policies — the
    # memory/FLOPs dial between full remat and no remat):
    #   "nothing"  save only block boundaries (max memory savings, ~1
    #              extra forward of recompute) — the default;
    #   "dots"     save matmul outputs (jax.checkpoint_policies.
    #              checkpoint_dots): recompute only the cheap elementwise/
    #              norm chains — most of the memory win at a sliver of
    #              the recompute, usually the best MFU under mild
    #              memory pressure;
    #   "dots_no_batch"  save non-batch matmul outputs only (scan-
    #              friendly variant).
    remat_policy: str = "nothing"

    @nn.compact
    def __call__(self, tokens: jax.Array,
                 positions: Optional[jax.Array] = None) -> jax.Array:
        """``tokens: [batch, seq] int32`` → logits ``[batch, seq, vocab]``.

        ``positions``: explicit ``[seq] int32`` position ids for the
        learned position table — for permuted sequence layouts (e.g. the
        zigzag causal-balanced ring, ``zigzag_indices``) where token
        order on device differs from temporal order.  Every non-attention
        sublayer is position-wise, so permuted tokens + matching position
        ids + a layout-aware ``attention_fn`` train identically to the
        natural order (tests assert it).  Unsupported with ``rope`` (the
        rotary path derives positions from array index; use the learned
        table for permuted layouts) and with ``decode``.
        """
        if positions is not None and (self.rope or self.decode):
            raise ValueError("explicit positions require the learned "
                             "position table in training mode "
                             "(rope=False, decode=False)")
        if positions is not None and self.attention_fn is None:
            # The default/windowed attention masks over ARRAY order; on a
            # permuted stream that attends temporally-future tokens with
            # no error and a decreasing loss.  Permuted layouts must
            # inject a layout-aware attention_fn (e.g. the zigzag ring).
            raise ValueError("explicit positions require a layout-aware "
                             "attention_fn (the built-in causal mask is "
                             "array-order)")
        if self.sliding_window is not None:
            if self.attention_fn is not None:
                raise ValueError(
                    "sliding_window with a custom attention_fn would window "
                    "decode but not training — compose the window inside "
                    "the injected attention_fn instead")
            if self.sliding_window < 1:
                raise ValueError(
                    f"sliding_window must be >= 1, got {self.sliding_window}")
        attn = self.attention_fn or (
            make_length_aware_attention(self.sliding_window)
            if self.sliding_window is not None else _default_attention)
        seq = tokens.shape[1]
        x = nn.Embed(self.vocab, self.d_model, name="tok_embed",
                     dtype=self.dtype)(tokens)
        if not self.rope:
            if self.decode:
                pi = self.variable("cache", "pos",
                                   lambda: jnp.zeros((), jnp.int32))
                if pi.value.ndim:
                    # slot-batched paged-kernel decode: every lane sits
                    # at its own cursor, so positions are [batch, seq]
                    positions = (pi.value[:, None]
                                 + jnp.arange(seq, dtype=jnp.int32)[None])
                else:
                    positions = pi.value + jnp.arange(seq, dtype=jnp.int32)
                pi.value = pi.value + seq
            elif positions is None:
                positions = jnp.arange(seq, dtype=jnp.int32)
            pos = nn.Embed(self.max_len, self.d_model, name="pos_embed",
                           dtype=self.dtype)(positions)
            x = x + (pos if pos.ndim == 3 else pos[None])
        block_cls = Block
        if self.remat and not self.decode:
            # static_argnums: nothing — Block takes only the activation.
            policies = {
                "nothing": None,  # save only block boundaries
                "dots": jax.checkpoint_policies.checkpoint_dots,
                "dots_no_batch":
                    jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
            }
            if self.remat_policy not in policies:
                raise ValueError(
                    f"remat_policy must be one of {sorted(policies)}, "
                    f"got {self.remat_policy!r}")
            pol = policies[self.remat_policy]
            block_cls = (nn.remat(Block) if pol is None
                         else nn.remat(Block, policy=pol))
        for i in range(self.n_layers):
            x = block_cls(
                self.d_model, self.n_heads, self.d_ff, attn,
                n_experts=self.n_experts, moe_fn=self.moe_fn,
                mlp_fn=self.mlp_fn,
                dtype=self.dtype, rope=self.rope,
                n_kv_heads=self.n_kv_heads, decode=self.decode,
                max_len=self.max_len, sliding_window=self.sliding_window,
                decode_kernel=self.decode_kernel, layer_idx=i,
                lora_rank=self.lora_rank, fused_rope=self.fused_rope,
                lora_kernel=self.lora_kernel,
                name=f"block_{i}",
            )(x)
        x = nn.LayerNorm(use_bias=False, dtype=jnp.float32)(x)
        return nn.Dense(self.vocab, use_bias=False, name="head",
                        dtype=self.dtype)(x)


def transformer_tp_sharding(mesh, tree, *, axis_name: str = "model"):
    """Megatron-style tensor-parallel layout for a TransformerLM state
    pytree (params or a whole ``ModelState`` including optimizer moments —
    matching is by path, and Adam's moments mirror the param tree).

    Per block: ``qkv`` column-split (attention heads land whole on each
    device), ``proj`` row-split, ``wi`` column-split, ``wo`` row-split; MoE
    expert stacks split on the expert axis; embeddings/norms/head
    replicated.  Under ``jit`` the XLA SPMD partitioner inserts the
    all-reduces these seams imply — the pjit-spec formulation of
    ``tpudist.parallel.tensor_parallel``, applied to the whole model.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    col = P(None, axis_name)
    row = P(axis_name, None)

    def spec_for(path) -> P:
        keys = [k for k in (getattr(e, "key", getattr(e, "name", None))
                            for e in path) if isinstance(k, str)]
        if "moe" in keys:
            if keys[-1] in ("w", "wo"):
                return P(axis_name)  # expert-stack leading axis
            return P()  # router replicated
        if "kernel" in keys:
            if "qkv" in keys or "wi" in keys:
                return col
            if "proj" in keys or "wo" in keys:
                return row
        return P()

    def shard_for(path, leaf):
        spec = spec_for(path)
        if getattr(leaf, "ndim", 0) < len(spec):
            spec = P()  # scalars/odd-rank leaves (e.g. Adam's count)
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(shard_for, tree)


def create_transformer(
    rng: jax.Array,
    *,
    seq_len: int = 128,
    attention_fn: Optional[AttentionFn] = None,
    **kwargs,
):
    """Init a TransformerLM; returns ``(module, params)``.  Same same-rng
    cross-process replication contract as :func:`create_toy_model`.

    Init always runs through the dense attention twin: parameter shapes do
    not depend on the attention op, and a sharded ring op would reject the
    size-1 dummy batch (not divisible by the mesh's data axis).
    """
    module = TransformerLM(attention_fn=attention_fn, **kwargs)
    # Init always runs the dense/unsharded twins: moe_fn would demand a
    # mesh-divisible dummy batch, mlp_fn a mesh at init time — and
    # neither changes parameter shapes or paths (_Kernel mirrors the
    # Dense pair exactly), so params are identical either way.
    init_kwargs = {k: v for k, v in kwargs.items()
                   if k not in ("moe_fn", "mlp_fn")}
    init_module = TransformerLM(attention_fn=None, **init_kwargs)
    params = init_module.init(rng, jnp.zeros((1, seq_len), jnp.int32))
    return module, params


def lm_loss(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Next-token cross entropy (mean over all predicted positions)."""
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


def lm_loss_with_targets(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Cross entropy against EXPLICIT per-position targets; ``-1`` masks a
    position out (mean over unmasked).  For permuted sequence layouts
    (zigzag ring), where "next token" is not the array neighbor: compute
    targets in temporal order, permute them alongside the tokens, mask
    the final temporal position with ``-1``.  Identical to :func:`lm_loss`
    on natural order (tests assert it)."""
    valid = targets >= 0
    safe = jnp.where(valid, targets, 0)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    return -jnp.sum(jnp.where(valid, ll, 0.0)) / jnp.maximum(
        jnp.sum(valid), 1)
