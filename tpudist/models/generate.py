"""Autoregressive generation for TransformerLM via a KV cache.

No reference counterpart (the reference trains a toy MLP and never
samples); this completes the LM family with the standard inference path,
TPU-first:

- ONE compiled program: the whole decode loop is a ``lax.scan`` whose body
  is the single-token cached forward — no per-token dispatch, no dynamic
  shapes (the K/V cache is ``[max_len]`` with a mask cursor, see
  ``Block._decode_attention``).
- prompt consumption is teacher-forced inside the same scan (prefill and
  decode share one program; at toy scale a separate batched prefill isn't
  worth a second compilation).
- works for both position encodings: learned tables read the cache's
  position counter; RoPE rotates each token at its absolute offset.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def make_decode_step(module, params):
    """Return ``(init_cache, step)``: ``init_cache(batch)`` builds a fresh
    all-zeros KV cache, ``step(cache, tok[b,1]) -> (cache, logits[b,vocab])``
    is the compiled single-token forward.

    The cache covers ``module.max_len`` positions; exceeding it silently
    attends over garbage — ``generate``/``decode_logits`` guard the budget.
    """
    # The sharded MoE closure (if any) cannot split a single decode token
    # over its batch axis; the dense reference is numerically identical
    # (same contract as create_transformer's init).
    dec = module.clone(decode=True, moe_fn=None)

    def step(cache, tok):
        logits, mut = dec.apply(
            {"params": params["params"], "cache": cache},
            tok, mutable=["cache"],
        )
        return mut["cache"], logits[:, -1].astype(jnp.float32)

    def init_cache(batch: int):
        # eval_shape: the cache STRUCTURE without materializing a second
        # parameter set (flax init would allocate + run a forward).  A
        # fresh cache is all-zeros (K/V empty, cursors at 0).
        shapes = jax.eval_shape(
            dec.init, jax.random.PRNGKey(0), jnp.zeros((batch, 1), jnp.int32)
        )["cache"]
        return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)

    return init_cache, step


def sample_logits(
    logits: jax.Array,
    key: jax.Array,
    *,
    temperature: float = 1.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
) -> jax.Array:
    """Sample token ids from ``logits [batch, vocab]`` (f32).

    ``temperature == 0`` is greedy argmax (``top_k``/``top_p`` ignored).
    ``top_k``: keep only the k highest logits.  ``top_p``: nucleus
    sampling — keep the smallest prefix of the probability-sorted vocab
    whose mass reaches ``top_p`` (the first token crossing the threshold
    is always kept, so the set is never empty).  Both filters compose
    (k-filter first, then nucleus), everything is fixed-shape ``jnp`` —
    the function jits and scans.
    """
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    neg = jnp.finfo(logits.dtype).min
    if top_k is not None and top_k < logits.shape[-1]:
        kth = lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, neg, logits)
    if top_p is not None and top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        cum = jnp.cumsum(jax.nn.softmax(sorted_logits, axis=-1), axis=-1)
        # Position i is kept while the mass BEFORE it is < top_p (shift by
        # one so the first token crossing the threshold stays in).  The
        # cutoff is the SMALLEST kept logit; everything below it is masked.
        keep = jnp.concatenate(
            [jnp.zeros_like(cum[..., :1]), cum[..., :-1]], axis=-1
        ) < top_p
        # Force-keep the top token so top_p <= 0 degenerates to greedy,
        # never to an empty set (which would un-mask everything below).
        keep = keep.at[..., 0].set(True)
        cutoff = jnp.min(jnp.where(keep, sorted_logits, jnp.inf), axis=-1,
                         keepdims=True)
        # Value-space masking: tokens exactly TIED with the cutoff logit
        # survive even when outside the nucleus prefix (same for top_k's
        # kth-value compare above).  Slightly more mass than requested on
        # tied logits — the standard HF/T5X behavior; exactness would need
        # masking in sorted-index space and a scatter back.
        logits = jnp.where(logits < cutoff, neg, logits)
    return jax.random.categorical(key, logits, axis=-1)


def make_generator(
    module,
    params,
    max_new: int,
    *,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
):
    """Build a reusable compiled sampler: ``gen(prompt, rng=None) ->
    [batch, plen + max_new]``.

    The returned callable holds ONE jitted program (prompt teacher-forcing
    + sampling in a single ``lax.scan``), so repeated calls with the same
    prompt shape hit the jit cache — this is the entry for serving/bench
    loops; :func:`generate` is the one-shot convenience wrapper.
    """
    init_cache, step = make_decode_step(module, params)

    def pick(logits, key):
        return sample_logits(logits, key, temperature=temperature,
                             top_k=top_k, top_p=top_p)

    @jax.jit
    def run(prompt, key):
        batch, plen = prompt.shape
        cache = init_cache(batch)

        def body(carry, i):
            cache, tok, key = carry
            cache, logits = step(cache, tok)
            key, sub = jax.random.split(key)
            sampled = pick(logits, sub)
            # teacher-force while the next position is still in the prompt
            forced = lax.dynamic_index_in_dim(
                prompt, jnp.minimum(i + 1, plen - 1), axis=1, keepdims=False
            )
            nxt = jnp.where(i + 1 < plen, forced, sampled)
            return (cache, nxt[:, None], key), nxt

        (_, _, _), out = lax.scan(
            body, (cache, prompt[:, :1], key), jnp.arange(plen + max_new - 1)
        )
        return jnp.concatenate([prompt[:, :1], out.T], axis=1)

    def gen(prompt: jax.Array, rng: Optional[jax.Array] = None) -> jax.Array:
        plen = prompt.shape[1]
        if plen + max_new > module.max_len:
            raise ValueError(
                f"prompt {plen} + max_new {max_new} exceeds the model's "
                f"max_len {module.max_len} (the KV-cache size)"
            )
        if rng is None:
            rng = jax.random.PRNGKey(0)
        return run(prompt, rng)

    return gen


def generate(
    module,
    params,
    prompt: jax.Array,
    max_new: int,
    *,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    rng: Optional[jax.Array] = None,
) -> jax.Array:
    """Sample ``max_new`` tokens after ``prompt [batch, plen]``.

    ``temperature == 0`` is greedy argmax; otherwise categorical sampling
    at that temperature, optionally filtered by ``top_k`` and/or nucleus
    ``top_p`` (:func:`sample_logits`).  Returns the full
    ``[batch, plen + max_new]`` sequence (prompt included).  One-shot
    wrapper over :func:`make_generator` (use that directly to amortize
    compilation across calls).
    """
    return make_generator(
        module, params, max_new, temperature=temperature, top_k=top_k,
        top_p=top_p,
    )(prompt, rng)


def decode_logits(module, params, tokens: jax.Array) -> jax.Array:
    """Teacher-forced per-position logits through the KV-cache path —
    must match ``module.apply(params, tokens)`` exactly (the consistency
    oracle for the cache implementation; tests assert it)."""
    batch, seq = tokens.shape
    if seq > module.max_len:
        raise ValueError(
            f"sequence {seq} exceeds the model's max_len {module.max_len} "
            "(the KV-cache size)"
        )
    init_cache, step = make_decode_step(module, params)

    @jax.jit
    def run(cache, tokens):
        def body(cache, tok):
            cache, logits = step(cache, tok[:, None])
            return cache, logits

        _, logits = lax.scan(body, cache, tokens.T)
        return jnp.swapaxes(logits, 0, 1)  # [batch, seq, vocab]

    return run(init_cache(batch), tokens)
