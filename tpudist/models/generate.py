"""Autoregressive generation for TransformerLM via a KV cache.

No reference counterpart (the reference trains a toy MLP and never
samples); this completes the LM family with the standard inference path,
TPU-first:

- ONE compiled program: the whole decode loop is a ``lax.scan`` whose body
  is the single-token cached forward — no per-token dispatch, no dynamic
  shapes (the K/V cache is ``[max_len]`` with a mask cursor, see
  ``Block._decode_attention``).
- prompt consumption is teacher-forced inside the same scan (prefill and
  decode share one program; at toy scale a separate batched prefill isn't
  worth a second compilation).
- works for both position encodings: learned tables read the cache's
  position counter; RoPE rotates each token at its absolute offset.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def make_decode_step(module, params):
    """Return ``(init_cache, step)``: ``init_cache(batch)`` builds a fresh
    all-zeros KV cache, ``step(cache, tok[b,1]) -> (cache, logits[b,vocab])``
    is the compiled single-token forward.

    The cache covers ``module.max_len`` positions; exceeding it silently
    attends over garbage — ``generate``/``decode_logits`` guard the budget.
    """
    # The sharded MoE closure (if any) cannot split a single decode token
    # over its batch axis; the dense reference is numerically identical
    # (same contract as create_transformer's init).
    dec = module.clone(decode=True, moe_fn=None)

    def step(cache, tok):
        logits, mut = dec.apply(
            {"params": params["params"], "cache": cache},
            tok, mutable=["cache"],
        )
        return mut["cache"], logits[:, -1].astype(jnp.float32)

    def init_cache(batch: int):
        # eval_shape: the cache STRUCTURE without materializing a second
        # parameter set (flax init would allocate + run a forward).  A
        # fresh cache is all-zeros (K/V empty, cursors at 0).
        shapes = jax.eval_shape(
            dec.init, jax.random.PRNGKey(0), jnp.zeros((batch, 1), jnp.int32)
        )["cache"]
        return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)

    return init_cache, step


def generate(
    module,
    params,
    prompt: jax.Array,
    max_new: int,
    *,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
) -> jax.Array:
    """Sample ``max_new`` tokens after ``prompt [batch, plen]``.

    ``temperature == 0`` is greedy argmax; otherwise categorical sampling
    at that temperature.  Returns the full ``[batch, plen + max_new]``
    sequence (prompt included).  The entire loop — prompt teacher-forcing
    plus sampling — is one jitted ``lax.scan``.
    """
    batch, plen = prompt.shape
    total = plen + max_new
    if total > module.max_len:
        raise ValueError(
            f"prompt {plen} + max_new {max_new} exceeds the model's "
            f"max_len {module.max_len} (the KV-cache size)"
        )
    if rng is None:
        rng = jax.random.PRNGKey(0)
    init_cache, step = make_decode_step(module, params)
    cache0 = init_cache(batch)

    def pick(logits, key):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1)
        return jax.random.categorical(key, logits / temperature, axis=-1)

    @jax.jit
    def run(cache, prompt, key):
        def body(carry, i):
            cache, tok, key = carry
            cache, logits = step(cache, tok)
            key, sub = jax.random.split(key)
            sampled = pick(logits, sub)
            # teacher-force while the next position is still in the prompt
            forced = lax.dynamic_index_in_dim(
                prompt, jnp.minimum(i + 1, plen - 1), axis=1, keepdims=False
            )
            nxt = jnp.where(i + 1 < plen, forced, sampled)
            return (cache, nxt[:, None], key), nxt

        (_, _, _), out = lax.scan(
            body, (cache, prompt[:, :1], key), jnp.arange(total - 1)
        )
        return jnp.concatenate([prompt[:, :1], out.T], axis=1)

    return run(cache0, prompt, rng)


def decode_logits(module, params, tokens: jax.Array) -> jax.Array:
    """Teacher-forced per-position logits through the KV-cache path —
    must match ``module.apply(params, tokens)`` exactly (the consistency
    oracle for the cache implementation; tests assert it)."""
    batch, seq = tokens.shape
    if seq > module.max_len:
        raise ValueError(
            f"sequence {seq} exceeds the model's max_len {module.max_len} "
            "(the KV-cache size)"
        )
    init_cache, step = make_decode_step(module, params)

    @jax.jit
    def run(cache, tokens):
        def body(cache, tok):
            cache, logits = step(cache, tok[:, None])
            return cache, logits

        _, logits = lax.scan(body, cache, tokens.T)
        return jnp.swapaxes(logits, 0, 1)  # [batch, seq, vocab]

    return run(init_cache(batch), tokens)
