"""Autoregressive generation for TransformerLM via a KV cache.

No reference counterpart (the reference trains a toy MLP and never
samples); this completes the LM family with the standard inference path,
TPU-first:

- ONE compiled program: the whole decode loop is a ``lax.scan`` whose body
  is the single-token cached forward — no per-token dispatch, no dynamic
  shapes (the K/V cache is ``[max_len]`` with a mask cursor, see
  ``Block._decode_attention``).
- prompt consumption is teacher-forced inside the same scan (prefill and
  decode share one program; at toy scale a separate batched prefill isn't
  worth a second compilation).
- works for both position encodings: learned tables read the cache's
  position counter; RoPE rotates each token at its absolute offset.
- speculative decoding (:func:`make_slot_decode` ``spec=``): a draft
  model proposes K tokens per slot, the target verifies the whole
  window in ONE multi-token cached pass (:func:`make_decode_window`) —
  the weights and KV arena stream once per K+1 candidates instead of
  once per token, which is the fewer-passes-per-token lever left after
  the decode path measured at 100.6% of its HBM roofline.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from tpudist.models.paged import PagedKV, PagedKVConfig, _Paged, strip_kv


class CacheFullError(RuntimeError):
    """A decode step was asked to write past ``module.max_len`` — the KV
    cache is full.  Raised by the eager :func:`make_decode_step` path
    (inside a traced program the cursor is a tracer and the caller owns
    the budget: ``generate``/``decode_logits`` pre-validate, the serving
    engine finishes the slot with reason ``"cache_full"``)."""


def _cache_cursor(cache):
    """The decode cache's write cursor (any per-layer ``idx`` leaf), or
    ``None`` when the pytree carries no recognizable cursor."""
    if not isinstance(cache, dict):
        return None
    for val in cache.values():
        if isinstance(val, dict) and "idx" in val:
            return val["idx"]
    return None


def make_decode_step(module, params, adapters=None):
    """Return ``(init_cache, step)``: ``init_cache(batch)`` builds a fresh
    all-zeros KV cache, ``step(cache, tok[b,1]) -> (cache, logits[b,vocab])``
    is the compiled single-token forward.

    ``adapters``: an ``"adapters"`` collection (:func:`tpudist.models.
    lora.adapter_collection`) applied on every step — the single-adapter
    sequential path (required iff ``module.lora_rank > 0``; the slot
    programs gather per-slot collections themselves instead).

    The cache covers ``module.max_len`` positions.  An EAGER call that
    would write past the end raises :class:`CacheFullError` instead of
    silently clamping the write onto the last position and attending
    over garbage; inside a traced program the cursor is a tracer, so
    the caller owns the budget (``generate``/``decode_logits`` validate
    up front, the serving engine finishes overflowing slots with reason
    ``"cache_full"``).
    """
    # The sharded MoE closure (if any) cannot split a single decode token
    # over its batch axis; the dense reference is numerically identical
    # (same contract as create_transformer's init).
    dec = module.clone(decode=True, moe_fn=None)

    def step(cache, tok):
        cur = _cache_cursor(cache)
        if cur is not None and not isinstance(cur, jax.core.Tracer):
            if int(jnp.max(cur)) + tok.shape[-1] > module.max_len:
                raise CacheFullError(
                    f"KV cache full: cursor {int(jnp.max(cur))} + "
                    f"{tok.shape[-1]} token(s) exceeds max_len "
                    f"{module.max_len}")
        variables = {"params": params["params"], "cache": cache}
        if adapters is not None:
            variables["adapters"] = adapters
        logits, mut = dec.apply(variables, tok, mutable=["cache"])
        return mut["cache"], logits[:, -1].astype(jnp.float32)

    def init_cache(batch: int):
        # eval_shape: the cache STRUCTURE without materializing a second
        # parameter set (flax init would allocate + run a forward).  A
        # fresh cache is all-zeros (K/V empty, cursors at 0).
        shapes = jax.eval_shape(
            dec.init, jax.random.PRNGKey(0), jnp.zeros((batch, 1), jnp.int32)
        )["cache"]
        return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)

    return init_cache, step


def make_decode_window(module, params):
    """Return ``window(cache, toks [s]) -> (cache, logits [s, vocab])``:
    the batch-1 multi-token decode pass — ``s`` tokens written at the
    cache cursor and scored against the cache in ONE forward (the
    speculative-decoding verify kernel).  Unlike a ``lax.scan`` of
    single-token steps, the weights and the KV arena stream once per
    window instead of once per token: at K drafted tokens the target
    pays ~1/K of the sequential HBM sweeps per emitted token — the
    fewer-passes-per-token lever the decode roofline measurement said
    was the only one left (ROOFLINE_r05 / ROADMAP item 5).  Logit row
    ``i`` is conditioned on the cache prefix plus ``toks[:i]`` — for a
    verify window ``[last_tok, d_1..d_K]`` row ``i`` scores candidate
    ``d_{i+1}`` exactly as a sequential decode step would."""
    dec = module.clone(decode=True, moe_fn=None)

    def window(cache, toks):
        logits, mut = dec.apply(
            {"params": params["params"], "cache": cache},
            toks[None], mutable=["cache"],
        )
        return mut["cache"], logits[0].astype(jnp.float32)

    return window


def tied_draft(module, params, layers: int):
    """Weight-tied shallow draft for speculative decoding: the target's
    first ``layers`` blocks plus its embeddings, final LayerNorm, and
    head — zero extra parameters, zero training (the LayerSkip-style
    early-exit draft).  Returns ``(draft_module, draft_params)`` for
    :func:`make_slot_decode`'s ``spec=``.  Draft quality only moves the
    acceptance rate, never output correctness (the target verify is the
    oracle); a trained/distilled draft can be loaded instead through the
    same ``(module, params)`` seam — it must share the target's
    ``vocab`` and ``max_len`` (cursor parity with the target cache)."""
    n = int(getattr(module, "n_layers"))
    if not 1 <= layers <= n:
        raise ValueError(f"draft layers {layers} must be in [1, {n}]")
    draft = module.clone(n_layers=layers, moe_fn=None, mlp_fn=None)
    src = params["params"]
    kept = {
        k: v for k, v in src.items()
        if not k.startswith("block_") or int(k.rsplit("_", 1)[1]) < layers
    }
    return draft, {"params": kept}


def sample_logits(
    logits: jax.Array,
    key: jax.Array,
    *,
    temperature: float = 1.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
) -> jax.Array:
    """Sample token ids from ``logits [batch, vocab]`` (f32).

    ``temperature == 0`` is greedy argmax (``top_k``/``top_p`` ignored).
    ``top_k``: keep only the k highest logits.  ``top_p``: nucleus
    sampling — keep the smallest prefix of the probability-sorted vocab
    whose mass reaches ``top_p`` (the first token crossing the threshold
    is always kept, so the set is never empty).  Both filters compose
    (k-filter first, then nucleus), everything is fixed-shape ``jnp`` —
    the function jits and scans.
    """
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    neg = jnp.finfo(logits.dtype).min
    if top_k is not None and top_k < logits.shape[-1]:
        kth = lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, neg, logits)
    if top_p is not None and top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        cum = jnp.cumsum(jax.nn.softmax(sorted_logits, axis=-1), axis=-1)
        # Position i is kept while the mass BEFORE it is < top_p (shift by
        # one so the first token crossing the threshold stays in).  The
        # cutoff is the SMALLEST kept logit; everything below it is masked.
        keep = jnp.concatenate(
            [jnp.zeros_like(cum[..., :1]), cum[..., :-1]], axis=-1
        ) < top_p
        # Force-keep the top token so top_p <= 0 degenerates to greedy,
        # never to an empty set (which would un-mask everything below).
        keep = keep.at[..., 0].set(True)
        cutoff = jnp.min(jnp.where(keep, sorted_logits, jnp.inf), axis=-1,
                         keepdims=True)
        # Value-space masking: tokens exactly TIED with the cutoff logit
        # survive even when outside the nucleus prefix (same for top_k's
        # kth-value compare above).  Slightly more mass than requested on
        # tied logits — the standard HF/T5X behavior; exactness would need
        # masking in sorted-index space and a scatter back.
        logits = jnp.where(logits < cutoff, neg, logits)
    return jax.random.categorical(key, logits, axis=-1)


def make_generator(
    module,
    params,
    max_new: int,
    *,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    adapters=None,
):
    """Build a reusable compiled sampler: ``gen(prompt, rng=None) ->
    [batch, plen + max_new]``.

    The returned callable holds ONE jitted program (prompt teacher-forcing
    + sampling in a single ``lax.scan``), so repeated calls with the same
    prompt shape hit the jit cache — this is the entry for serving/bench
    loops; :func:`generate` is the one-shot convenience wrapper.

    ``adapters``: single-adapter collection for a ``lora_rank > 0``
    module (:func:`tpudist.models.lora.adapter_collection`) — the
    sequential oracle the per-slot engine streams are byte-compared to.
    """
    init_cache, step = make_decode_step(module, params, adapters=adapters)

    def pick(logits, key):
        return sample_logits(logits, key, temperature=temperature,
                             top_k=top_k, top_p=top_p)

    @jax.jit
    def run(prompt, key):
        batch, plen = prompt.shape
        cache = init_cache(batch)

        def body(carry, i):
            cache, tok, key = carry
            cache, logits = step(cache, tok)
            key, sub = jax.random.split(key)
            sampled = pick(logits, sub)
            # teacher-force while the next position is still in the prompt
            forced = lax.dynamic_index_in_dim(
                prompt, jnp.minimum(i + 1, plen - 1), axis=1, keepdims=False
            )
            nxt = jnp.where(i + 1 < plen, forced, sampled)
            return (cache, nxt[:, None], key), nxt

        (_, _, _), out = lax.scan(
            body, (cache, prompt[:, :1], key), jnp.arange(plen + max_new - 1)
        )
        return jnp.concatenate([prompt[:, :1], out.T], axis=1)

    def gen(prompt: jax.Array, rng: Optional[jax.Array] = None) -> jax.Array:
        plen = prompt.shape[1]
        if plen + max_new > module.max_len:
            raise ValueError(
                f"prompt {plen} + max_new {max_new} exceeds the model's "
                f"max_len {module.max_len} (the KV-cache size)"
            )
        if rng is None:
            rng = jax.random.PRNGKey(0)
        return run(prompt, rng)

    return gen


def generate(
    module,
    params,
    prompt: jax.Array,
    max_new: int,
    *,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    rng: Optional[jax.Array] = None,
    adapters=None,
) -> jax.Array:
    """Sample ``max_new`` tokens after ``prompt [batch, plen]``.

    ``temperature == 0`` is greedy argmax; otherwise categorical sampling
    at that temperature, optionally filtered by ``top_k`` and/or nucleus
    ``top_p`` (:func:`sample_logits`).  Returns the full
    ``[batch, plen + max_new]`` sequence (prompt included).  One-shot
    wrapper over :func:`make_generator` (use that directly to amortize
    compilation across calls).
    """
    return make_generator(
        module, params, max_new, temperature=temperature, top_k=top_k,
        top_p=top_p, adapters=adapters,
    )(prompt, rng)


class SlotState(NamedTuple):
    """Per-slot decode state, resident ON DEVICE for the life of the
    engine (:mod:`tpudist.serve`).  Before this existed the engine
    re-uploaded five host arrays per decode step; now the host keeps
    shadow cursors for admission/budget decisions only, and the device
    round-trip per decode *block* is one token-block fetch.

    All leaves carry a leading ``[num_slots]`` axis:

    - ``last_tok [S] int32`` — the token the next decode step consumes
      (fed back IN-GRAPH inside ``decode_block``);
    - ``active [S] bool`` — lane is decoding (prefill-in-progress lanes
      are occupied on the host but inactive here);
    - ``pos [S] int32`` — filled cache positions (mirrors the cache's own
      cursor; kept for introspection/debug dumps);
    - ``counts [S] int32`` — tokens emitted so far, which is also the
      per-request sampling-stream index (``fold_in(key, count)``);
    - ``temps [S] f32`` / ``keys [S, 2] uint32`` — per-request sampling
      config (keys are derived in-graph from integer seeds at insert);
    - ``accepted [S] int32`` / ``drafted [S] int32`` — speculative-decode
      acceptance bookkeeping (:func:`make_slot_decode` ``spec=``):
      cumulative drafted tokens the target verify accepted / proposed for
      this tenant.  Updated in-graph by ``spec_verify`` (the rollback
      cursor itself is ``pos`` — the same leaf every path maintains), so
      acceptance telemetry needs no extra device round trips and the
      counters ride KV handoff with the rest of the row.  Zero on
      non-speculative engines;
    - ``adapter_id [S] int32`` — the slot's per-tenant adapter block in
      the paged LoRA pool (:mod:`tpudist.models.lora`); the pool's
      ``num_blocks`` sentinel = base-only (bit-exact base path via the
      ``on`` select).  The programs gather each slot's factors from
      this id IN-GRAPH, so tenants churn with zero recompilation; on a
      non-adapter engine the leaf rides along as zeros.  KV handoff
      carries it with the row, but ids are POOL-LOCAL — the importing
      engine re-binds by adapter NAME (the package's ``adapter`` field)
      before install;
    - ``gidx [S] int32`` / ``gstate [S] int32`` — the slot's grammar
      block in the structured-output pool (:mod:`tpudist.constrain`)
      and its automaton state.  The adapter-id discipline applied to
      grammars: the pool's ``num_blocks`` sentinel = unconstrained
      (the sentinel block's mask is all-True identity, so free lanes
      sample bit-exactly beside constrained neighbors), the programs
      gather each slot's mask/transition rows from ``(gidx, gstate)``
      IN-GRAPH, and ``gstate`` advances as part of the emitted-token
      commit — so park/resume and disagg handoff carry the constraint
      state byte-faithfully with the row.  Like adapter ids, ``gidx``
      is POOL-LOCAL: an importing engine re-binds by grammar SOURCE
      (the package's ``grammar`` field) and overwrites it.  Zeros on
      non-constrained engines.
    """

    last_tok: jax.Array
    active: jax.Array
    pos: jax.Array
    counts: jax.Array
    temps: jax.Array
    keys: jax.Array
    accepted: jax.Array
    drafted: jax.Array
    adapter_id: jax.Array
    gidx: jax.Array
    gstate: jax.Array


class SlotDecode(NamedTuple):
    """The compiled primitives of the continuous-batching serving engine
    (:mod:`tpudist.serve`): ``num_slots`` independent KV-cache lanes, each
    a batch-1 decode cache with its OWN position cursor (the single-batch
    decode step vmapped over a leading slot axis — per-slot cursors, masks,
    and RoPE offsets fall out of the vmap for free), plus a persistent
    on-device :class:`SlotState` threaded (and donated) through every
    primitive.

    Every callable is jitted with fixed shapes, so requests of any
    prompt/output length join and leave a running batch with ZERO
    recompilation — the SPMD fixed-shape discipline, applied to serving.
    ``decode_block`` is the one exception by design: ``K`` is static, so
    each distinct block size is one compile (the engine buckets K to
    powers of two, bounding the cache at ``log2(max_block)+1`` entries):

    - ``init_state()`` / ``init_slots()`` → all-zeros state / slot cache;
    - ``insert_batch(state, cache, prompts [S, pad], clens [S], dsts [S],
      seeds [S], temps [S], last [S])`` → ``(state, cache, firsts [S])``:
      ONE dispatch that teacher-forces up to ``S`` prompt chunks through
      the cached forward (a masked fixed-length scan: steps at
      ``i >= clen`` keep the old cache, so any ``clen <= prefill_pad``
      shares one program), derives each lane's threefry key from its
      integer seed IN-GRAPH, scatters lane ``j`` into slot ``dsts[j]``
      (``dsts[j] == num_slots`` marks an unused lane — the out-of-bounds
      scatter drops it), and where ``last[j]`` samples the first generated
      token from the post-chunk logits and arms the slot for decode.
      Lanes with ``last[j] == False`` hold a partial prompt: their slot
      stays inactive until ``prefill_extend`` feeds the remaining chunks;
    - ``prefill_extend(state, cache, slot, chunk [pad], clen, is_last)``
      → ``(state, cache, first)``: append one prompt chunk at slot's
      running cache offset (chunked prefill — prompts longer than the
      pad are admitted and teacher-forced ``pad`` tokens per call, so a
      long prompt stalls in-flight decode by at most one chunk per engine
      iteration).  On ``is_last`` the first generated token is sampled
      from the final chunk's last logits and the slot activates;
    - ``decode_block(state, cache, K)`` → ``(state, cache, toks [K, S])``:
      ``K`` decode steps fused into one dispatch via ``lax.scan`` with
      in-graph token feedback — K×num_slots tokens for one dispatch and
      one D2H fetch.  Inactive lanes compute too (fixed shape) but their
      cache writes are undone by the ``active`` select and their
      ``last_tok``/``counts`` hold still, so they neither advance nor
      corrupt;
    - ``evict(state, cache, slot)`` → that lane zeroed in both cache and
      state (a freed slot must not leak a tenant's K/V into the next
      request's garbage window);
    - ``export_lane(state, cache, slot)`` → ``(lane, lane_state)``: one
      slot's KV lane plus its SlotState row — the export half of the
      prefill→decode KV handoff (:mod:`tpudist.serve.disagg`).  Dense:
      the lane is the slot's flax cache slice; paged: a dense
      ``(k, v, meta)`` view gathered through the slot's block table
      (int8 pools dequantize; the re-import re-quantizes bit-exactly);
    - ``import_lane(state, cache, slot, [row,] lane, lane_state)`` →
      install an exported lane into ``slot`` (paged takes the dest
      allocator's fresh table ``row`` as data).  Greedy/sampled
      continuation after import is byte-identical to decoding in the
      source engine: the state row carries ``last_tok``/``counts``/
      ``keys``, and the sampling stream is ``fold_in(key, count)`` —
      independent of which engine or slot hosts the request;
    - ``sample(logits, keys, temps, counts)`` → per-slot token draw:
      greedy argmax where ``temps <= 0``, else categorical at that slot's
      temperature from ``fold_in(key, count)`` — a deterministic
      per-request stream independent of which slot/batch neighbors the
      request decoded beside, and independent of the block size K.

    **Paged mode** (``make_slot_decode(..., paged=PagedKVConfig(...))``,
    see :mod:`tpudist.models.paged`): the cache argument threaded
    through every primitive becomes a :class:`~tpudist.models.paged.
    PagedKV` (block pool + per-slot block table) and the programs do
    the gather/scatter indirection in-graph — same four fixed-shape
    programs, still zero recompilation under churn.  Three signatures
    widen to carry the host allocator's decisions as DATA (never as
    shapes): ``insert_batch`` prepends ``tables [S, M]`` (each lane's
    block-table row — shared prefix blocks first, freshly allocated
    ones after) and ``poss [S]`` (each lane's starting cursor = its
    reused prefix length, block-aligned); ``evict`` appends ``free_ids
    [M]`` (physical blocks whose refcount hit zero, sentinel-padded —
    shared blocks outlive any one tenant).  ``paged`` holds the
    geometry/accounting helper; ``peek_logits(state, cache) ->
    [S, vocab]`` reads every lane's next-token logits WITHOUT advancing
    state or cache (the int8-accuracy oracle; compiled separately, not
    one of the four hot programs).
    """

    num_slots: int
    prefill_pad: int
    init_state: Callable
    init_slots: Callable
    insert_batch: Callable
    prefill_extend: Callable
    decode_block: Callable
    evict: Callable
    sample: Callable
    peek_logits: Optional[Callable] = None
    paged: Optional["_Paged"] = None
    export_lane: Optional[Callable] = None
    import_lane: Optional[Callable] = None
    # -- speculative decoding (make_slot_decode(spec=...)) -----------------
    # The draft model's own slot cache rides beside the target cache
    # through a parallel primitive set (the four core programs above are
    # UNCHANGED — spec is additive, so non-spec engines keep their exact
    # compile pins):
    # - ``init_draft()`` → all-zeros draft slot cache (dense twin of
    #   ``init_slots``; paged engines get a PagedKV over the DRAFT
    #   template sharing the target pool's block ids — "its own smaller
    #   block pool": same allocator decisions, draft-sized bytes);
    # - ``draft_prefill(dcache, [tables, poss,] prompts, clens, dsts,
    #   dparams)`` → teacher-force each admission lane's prompt chunk
    #   through the draft (the draft twin of ``insert_batch``'s cache
    #   half).  Every draft FORWARD program (``draft_prefill`` /
    #   ``draft_extend`` / ``draft_track`` / ``draft_propose``) takes the
    #   draft's parameter pytree as its LAST argument instead of closing
    #   over it — a same-geometry replacement hot-swaps as pure data
    #   through the SAME compiled programs (``SlotEngine.swap_draft``);
    # - ``draft_extend(dcache, slot, chunk, clen, dparams)`` → one
    #   chunked-prefill append (twin of ``prefill_extend``);
    # - ``draft_evict(dcache, slot[, free_ids])`` → zero the lane (and
    #   recycled pool blocks);
    # - ``draft_arm(dcache, slot, [row,] pos)`` → cold-start a lane at
    #   cursor ``pos`` after a KV handoff import (packages are unchanged
    #   — the decode pool owns the draft, so an imported lane's draft
    #   context starts empty and warms as the request decodes);
    # - ``draft_track(state, dcache, toks [K, S])`` → teacher-force a
    #   plain decode block's emitted tokens through the draft, keeping
    #   draft and target cursors in lockstep across non-speculative
    #   iterations (remaining-budget-1 fallbacks);
    # - ``draft_propose(state, dcache, k)`` → ``(dcache, drafts [k, S],
    #   dlogits [k, S, vocab])``: k draft decode steps with in-graph
    #   token feedback (greedy argmax, or categorical on the per-request
    #   ``fold_in`` substream), plus one extra step feeding the last
    #   draft so an all-accepted verify leaves both cursors equal;
    # - ``spec_verify(state, cache, dcache, drafts, dlogits, spec_on,
    #   rem)`` → ``(state, cache, dcache, packed [S, k+2])``: the
    #   batched target verify — ONE multi-token window pass scores
    #   ``[last_tok, d_1..d_k]``, leading-prefix acceptance (greedy
    #   token match, or the standard residual-distribution correction),
    #   per-lane budget clamp (``rem``), bonus/correction token, and the
    #   in-graph rollback (cursors back to ``pos0 + emitted``; rejected
    #   KV beyond the cursor is masked garbage, the paged-gather
    #   contract).  ``packed`` is ``[S, k+3]``: column 0 the per-lane
    #   emitted count, column 1 the UNCLAMPED accept count (the
    #   draft-quality counter), columns 2.. the emitted tokens — ONE
    #   block-granularity fetch.
    init_draft: Optional[Callable] = None
    draft_prefill: Optional[Callable] = None
    draft_extend: Optional[Callable] = None
    draft_evict: Optional[Callable] = None
    draft_arm: Optional[Callable] = None
    draft_track: Optional[Callable] = None
    draft_propose: Optional[Callable] = None
    spec_verify: Optional[Callable] = None
    draft_paged: Optional["_Paged"] = None


def _slot_sample(logits: jax.Array, keys: jax.Array, temps: jax.Array,
                 counts: jax.Array) -> jax.Array:
    """Per-slot sampling (see :class:`SlotDecode`): ``logits [S, vocab]``,
    ``keys [S, 2] uint32``, ``temps [S]``, ``counts [S]`` → ``[S] int32``."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def one(key, lg, t, c):
        k = jax.random.fold_in(key, c)
        return jax.random.categorical(k, lg / jnp.maximum(t, 1e-6))

    sampled = jax.vmap(one)(keys, logits, temps, counts).astype(jnp.int32)
    return jnp.where(temps > 0.0, sampled, greedy)


def make_slot_decode(module, params, num_slots: int, prefill_pad: int,
                     paged: Optional[PagedKVConfig] = None,
                     cache_constraint: Optional[Callable] = None,
                     state_constraint: Optional[Callable] = None,
                     spec: Optional[Tuple] = None,
                     draft_constraint: Optional[Callable] = None,
                     attn_kernel: str = "gather",
                     prefill_kernel: bool = False,
                     sample_kernel: bool = False,
                     fused_rope: bool = False,
                     lora_kernel: bool = False,
                     adapters=None,
                     constrain=None,
                     logprobs: int = 0
                     ) -> SlotDecode:
    """Build the slot-decode primitive set over ``module``/``params`` —
    see :class:`SlotDecode` for the contract of each callable.  With
    ``paged`` set, the cache is a block pool + block tables instead of
    dense per-slot arenas (:mod:`tpudist.models.paged`); the unquantized
    paged path is byte-identical to the dense one (tests pin it).

    ``cache_constraint`` / ``state_constraint`` (SPMD serving,
    :mod:`tpudist.serve.spmd`): ``tree -> tree`` callables applying
    ``with_sharding_constraint`` to the cache / SlotState pytrees.  The
    hot programs re-assert them on their outputs, making the mesh
    layout STRUCTURAL — the engine's shardings cannot silently drift
    (decay to replicated, or pick up a partitioner-invented split that
    would recompile the next program) across donated iterations.

    ``spec``: ``(draft_module, draft_params)`` — enable the speculative
    decode primitive set (:class:`SlotDecode`, the ``draft_*`` /
    ``spec_verify`` fields): the draft proposes K tokens per slot with
    its own lightweight KV state, the target verifies all K in one
    batched multi-token window pass, and per-slot acceptance
    bookkeeping lives in :class:`SlotState`.  The draft must share the
    target's ``vocab`` and ``max_len`` (cursor parity);
    :func:`tied_draft` builds the zero-cost weight-tied variant.
    ``draft_constraint`` is the draft cache's sharding assert (the
    target's ``cache_constraint`` twin).

    ``attn_kernel`` selects the DECODE attention execution on a paged
    cache: ``"gather"`` (default) materializes a transient dense view
    per dispatch; ``"paged"`` routes ``decode_block`` and
    ``spec_verify`` through the Pallas paged-attention kernel
    (:mod:`tpudist.ops.paged_attention`) — the block table is walked
    inside the kernel, only live blocks are fetched, and the
    dispatch's fresh tokens ride a small window buffer committed back
    via :meth:`~tpudist.models.paged._Paged.commit_window`.  The
    prefill/insert/evict programs (compute-bound teacher-forcing and
    surgery, not the bandwidth-bound hot path) and the DRAFT's own
    small pool keep the gather path either way, so the program set and
    its compile pins are unchanged — only the decode arms swap.

    ``adapters``: an :class:`tpudist.models.lora.AdapterPoolConfig` —
    enable the per-tenant adapter seam.  Every forward-pass program
    grows an ``apool`` argument (the :class:`~tpudist.models.lora.
    AdapterPool`, read-only — host loads/unloads swap the arrays, never
    the program) and gathers each slot's rank-r factors from
    ``SlotState.adapter_id`` in-graph (``insert_batch`` additionally
    takes the admission batch's ``aids``); a sentinel id rides the
    bit-exact base-only select.  The tied draft shares its slot's
    adapter (the pool's first ``n_layers`` slices) whenever the draft's
    projection geometry matches the target's; a geometry-mismatched
    loaded draft runs base-only — acceptance may drop, output
    correctness cannot (the adapter'd target verify is the oracle).
    Without ``adapters`` every signature is byte-identical to before.

    ``constrain``: a :class:`tpudist.constrain.ConstrainConfig` —
    enable the structured-output seam.  ``insert_batch`` /
    ``prefill_extend`` / ``decode_block`` / ``spec_verify`` grow a
    trailing ``gpool`` argument: the dense grammar tables
    ``(allowed [G+1, S_max, V] bool, next [G+1, S_max, V] int32)``,
    read-only — host grammar binds swap rows in the arrays, never the
    program.  Each slot's mask row is gathered from ``SlotState.gidx``
    / ``gstate`` in-graph and applied on the vocabulary axis before
    sampling (the decode-window mask discipline applied to vocab
    instead of positions); block ``G`` is the all-True identity
    sentinel, so unconstrained lanes in the same batch sample
    bit-exactly.  In ``spec_verify`` the target's verify rows are
    masked along the draft's automaton trajectory and a
    grammar-forbidden draft token is simply a rejection (speculation
    composes for free; the draft itself decodes unmasked).
    ``insert_batch`` additionally takes the admission batch's ``gids``
    before the pool.  Without ``constrain`` every signature is
    byte-identical to before.

    ``logprobs``: top-n count for the logprobs surface (0 = off).
    When set, ``decode_block`` returns two extra arrays ``(lp_ids
    [K, S, n], lp_vals [K, S, n])`` and ``spec_verify`` returns
    ``(lp_ids [S, k+1, n], lp_vals [S, k+1, n])`` — the top-n of the
    POST-MASK log-softmax at each emitted position (constrained lanes
    report the distribution actually sampled), riding the existing
    packed D2H fetch.  Prefill-sampled first tokens carry no logprobs
    (the host surfaces ``None`` for them)."""
    if attn_kernel not in ("gather", "paged"):
        raise ValueError(
            f"attn_kernel must be 'gather' or 'paged', got {attn_kernel!r}")
    if attn_kernel == "paged" and paged is None:
        raise ValueError(
            "attn_kernel='paged' is the paged-pool kernel — it requires "
            "a paged cache (pass paged=PagedKVConfig(...))")
    # -- the kernel-family knobs (tpudist.ops; PR 19) -----------------------
    use_prefill_kernel = bool(prefill_kernel)
    use_sample_kernel = bool(sample_kernel)
    use_fused_rope = bool(fused_rope)
    use_lora_kernel = bool(lora_kernel)
    if use_prefill_kernel and paged is None:
        raise ValueError(
            "prefill_kernel=True is the paged-prefill kernel — it "
            "requires a paged cache (pass paged=PagedKVConfig(...))")
    if use_fused_rope and attn_kernel != "paged" and not use_prefill_kernel:
        raise ValueError(
            "fused_rope=True fuses RoPE+QKV on the kernel arms only — "
            "enable attn_kernel='paged' and/or prefill_kernel=True")
    if use_lora_kernel and adapters is None:
        raise ValueError(
            "lora_kernel=True is the in-kernel adapter gather-matmul — "
            "it requires the adapter seam (pass adapters=...)")
    if use_lora_kernel and attn_kernel != "paged" and not use_prefill_kernel:
        raise ValueError(
            "lora_kernel=True rides the slot-batched kernel programs "
            "only — enable attn_kernel='paged' and/or prefill_kernel="
            "True (the vmapped gather programs keep gather_collection)")
    if num_slots < 1:
        raise ValueError(f"num_slots must be >= 1, got {num_slots}")
    if not 1 <= prefill_pad <= module.max_len:
        raise ValueError(
            f"prefill_pad {prefill_pad} must be in [1, {module.max_len}] "
            "(the KV-cache size)")
    # -- per-tenant adapter seam (tpudist.models.lora) ----------------------
    use_lora = adapters is not None
    if use_lora:
        from tpudist.models import lora as _lora

        if getattr(module, "n_experts", 0) > 0 \
                or getattr(module, "mlp_fn", None) is not None:
            raise ValueError(
                "adapters wrap the plain qkv/wi/wo Dense path; they "
                "cannot compose with an MoE FFN or an injected mlp_fn")
        n_lora_layers = int(module.n_layers)
        #: the sentinel adapter id = base-only (also what evict resets to)
        _aid_empty = int(adapters.num_blocks)
    else:
        _aid_empty = 0

    def _gather_ads(apool, ids, n_layers: Optional[int] = None):
        """Per-slot ``"adapters"`` collection from the pool at ``ids``
        (None when the seam is off — vmap/apply treat it as empty)."""
        if not use_lora:
            return None
        return _lora.gather_collection(
            apool, ids, n_lora_layers if n_layers is None else n_layers)

    # -- structured-output seam (tpudist.constrain) -------------------------
    use_gram = constrain is not None
    #: the sentinel grammar id = unconstrained (also what evict resets to)
    _gid_empty = int(constrain.num_blocks) if use_gram else 0
    n_lp = int(logprobs)
    if n_lp < 0:
        raise ValueError(f"logprobs must be >= 0, got {n_lp}")

    def _gmask(gp, gidx, gstate, logits):
        """Vocabulary-axis grammar mask: disallowed tokens at each
        lane's ``(gidx, gstate)`` drop to finfo.min (identity when the
        seam is off or the lane indexes the sentinel block — the
        all-True row makes ``where`` a no-op, so free lanes keep
        bit-exact logits)."""
        if gp is None:
            return logits
        allow = gp[0][gidx, gstate]
        return jnp.where(allow, logits, jnp.finfo(logits.dtype).min)

    def _gadvance(gp, gidx, gstate, toks, moved):
        """Automaton advance over one emitted token per lane — part of
        the token commit, so parked/handed-off rows carry it.  Lanes
        with ``moved`` False (inactive, or a prefill that sampled
        nothing) hold still; the tables self-loop on disallowed tokens,
        so even a defensive gather never escapes the automaton."""
        if gp is None:
            return gstate
        nxt = gp[1][gidx, gstate, toks]
        return jnp.where(moved, nxt, gstate)

    def _top_lp(logits):
        """Top-n (id, logprob) of the POST-MASK distribution — the
        logprobs surface reports what was actually sampled from."""
        lp = jax.nn.log_softmax(logits, axis=-1)
        vals, ids = lax.top_k(lp, n_lp)
        return ids.astype(jnp.int32), vals.astype(jnp.float32)

    def _ads_for(apool, ids, kernel_path: bool,
                 n_layers: Optional[int] = None):
        """The ``"adapters"`` collection for a program: pool form
        (full pools + ids, consumed by the Pallas gather-matmul) on the
        slot-batched kernel programs when ``lora_kernel`` is on,
        pre-gathered factors everywhere else."""
        if not use_lora:
            return None
        nl = n_lora_layers if n_layers is None else n_layers
        if kernel_path and use_lora_kernel:
            return _lora.pool_collection(apool, ids, nl)
        return _lora.gather_collection(apool, ids, nl)

    def _slot_tail(tail, sel_ids, kernel_path: bool = False):
        """Split a program's variadic pool tail into ``(ads, gp)``:
        the adapter pool rides first (when that seam is on), the
        grammar pool last.  Both seams off → empty tail, and the
        traced signature is byte-identical to a pre-seam program.
        ``kernel_path`` marks slot-batched kernel programs (they take
        the pool-form adapter collection under ``lora_kernel``)."""
        i = 0
        ads = None
        if use_lora:
            ads = _ads_for(tail[0], sel_ids, kernel_path)
            i = 1
        gp = tail[i] if use_gram else None
        return ads, gp

    def _insert_tail(tail, kernel_path: bool = False):
        """The insert programs' tail: ``[aids, apool][, gids, gpool]``
        — per-lane ids ride as data beside each pool.  Seams that are
        off synthesize their sentinel ids."""
        i = 0
        if use_lora:
            aids, ads = tail[0], _ads_for(tail[1], tail[0], kernel_path)
            i = 2
        else:
            aids, ads = jnp.full(num_slots, _aid_empty, jnp.int32), None
        if use_gram:
            gids, gp = tail[i], tail[i + 1]
        else:
            gids, gp = jnp.full(num_slots, _gid_empty, jnp.int32), None
        return aids, ads, gids, gp

    # -- fused sampling tail (tpudist.ops.fused_sample) ---------------------
    _interp = jax.devices()[0].platform != "tpu"
    if use_sample_kernel:
        from tpudist.ops.fused_sample import (fused_residual_prep,
                                              fused_sample_prep)

        def _sample_tail(gp, gidx, gstate, logits, keys, temps, counts):
            """Fused-kernel twin of ``_gmask`` + ``_slot_sample``:
            constrain mask, greedy argmax, and temperature scaling run
            as ONE Pallas pass; the categorical draw stays in-graph on
            the kernel's scaled logits — same fold_in substream, same
            division, so sampled AND greedy streams are byte-identical
            to the unfused tail.  Returns ``(toks, masked_logits)``
            with ``masked_logits`` feeding ``_top_lp``/``_gadvance``
            unchanged."""
            ga = gp[0] if gp is not None else None
            masked, scaled, greedy = fused_sample_prep(
                logits, temps, ga, gidx, gstate, interpret=_interp)

            def one(key, lg, c):
                return jax.random.categorical(
                    jax.random.fold_in(key, c), lg)

            sampled = jax.vmap(one)(keys, scaled, counts).astype(jnp.int32)
            return jnp.where(temps > 0.0, sampled, greedy), masked
    else:
        def _sample_tail(gp, gidx, gstate, logits, keys, temps, counts):
            lg = _gmask(gp, gidx, gstate, logits)
            return _slot_sample(lg, keys, temps, counts), lg

    init_cache, _step_base = make_decode_step(module, params)
    vocab = module.vocab
    if use_lora:
        _ldec = module.clone(decode=True, moe_fn=None,
                             lora_rank=adapters.rank)

        def step(cache, tok, ad):
            logits, mut = _ldec.apply(
                {"params": params["params"], "cache": cache,
                 "adapters": ad},
                tok, mutable=["cache"])
            return mut["cache"], logits[:, -1].astype(jnp.float32)
    else:
        def step(cache, tok, ad):  # noqa: ARG001 - uniform signature
            return _step_base(cache, tok)
    vstep = jax.vmap(step, in_axes=(0, 0, 0))

    def _constrain(cache):
        return cache if cache_constraint is None else cache_constraint(cache)

    def _constrain_state(state):
        return state if state_constraint is None else state_constraint(state)

    def init_state():
        s = num_slots
        return SlotState(
            last_tok=jnp.zeros(s, jnp.int32),
            active=jnp.zeros(s, bool),
            pos=jnp.zeros(s, jnp.int32),
            counts=jnp.zeros(s, jnp.int32),
            temps=jnp.zeros(s, jnp.float32),
            keys=jnp.zeros((s, 2), jnp.uint32),
            accepted=jnp.zeros(s, jnp.int32),
            drafted=jnp.zeros(s, jnp.int32),
            adapter_id=jnp.full(s, _aid_empty, jnp.int32),
            gidx=jnp.full(s, _gid_empty, jnp.int32),
            gstate=jnp.zeros(s, jnp.int32))

    def init_slots():
        one = init_cache(1)
        return jax.tree.map(
            lambda a: jnp.zeros((num_slots,) + a.shape, a.dtype), one)

    def _make_force(step_fn):
        """Teacher-force ``chunk[:clen]`` through a batch-1 cache (masked
        fixed-length scan: steps at ``i >= clen`` keep the old cache, so
        every ``clen <= prefill_pad`` shares one program).  Returns the
        advanced cache and the logits after the LAST live token.
        Parameterized over the step so the speculative draft model
        shares the exact prefill mechanics (same program shape).
        ``ad``: the lane's adapter collection (None when the seam is
        off) — prefill MUST run the slot's adapter too, the written KV
        depends on the adapted qkv."""

        def force(cache, chunk, clen, ad):
            def body(carry, i):
                cache, last = carry
                tok = lax.dynamic_index_in_dim(chunk, i, keepdims=False)
                nc, logits = step_fn(cache, tok[None, None], ad)
                live = i < clen
                cache = jax.tree.map(
                    lambda n, o: jnp.where(live, n, o), nc, cache)
                last = jnp.where(i == clen - 1, logits[0], last)
                return (cache, last), None

            return lax.scan(body, (cache, jnp.zeros((vocab,), jnp.float32)),
                            jnp.arange(prefill_pad))[0]

        return force

    _force_chunk = _make_force(step)

    def _decode_scan(state, cache, k, ads, gp):
        """The K-step fused decode body shared by the dense and paged
        ``decode_block`` programs: in-graph token feedback, inactive
        lanes' cache writes undone by the ``active`` select.  ``ads``
        (the gathered per-slot adapter collections) and ``gp`` (the
        grammar pool) are loop-invariant — slot bindings never change
        mid-dispatch — so XLA hoists the gathers out of the scan.  The
        grammar mask applies BEFORE sampling and ``gstate`` advances
        with the token commit; with ``logprobs`` on, each step also
        emits the post-mask top-n rows."""

        def body(carry, _):
            state, cache = carry
            nc, logits = vstep(cache, state.last_tok[:, None, None], ads)

            def sel(n, o):
                m = state.active.reshape((-1,) + (1,) * (n.ndim - 1))
                return jnp.where(m, n, o)

            cache = jax.tree.map(sel, nc, cache)
            toks, lg = _sample_tail(gp, state.gidx, state.gstate,
                                    logits[:, 0], state.keys, state.temps,
                                    state.counts)
            toks = jnp.where(state.active, toks,
                             state.last_tok).astype(jnp.int32)
            inc = state.active.astype(jnp.int32)
            state = state._replace(last_tok=toks, counts=state.counts + inc,
                                   pos=state.pos + inc,
                                   gstate=_gadvance(gp, state.gidx,
                                                    state.gstate, toks,
                                                    state.active))
            ys = toks if n_lp == 0 else (toks,) + _top_lp(lg)
            return (state, cache), ys

        return lax.scan(body, (state, cache), None, length=k)

    def _sel_active(active, new, old):
        """Keep ``old`` leaves wherever ``active`` is False (inactive
        lanes neither advance nor corrupt) — shared by the gather-path
        selects, the spec programs, and the kernel path's window-view
        scan."""
        def sel(n, o):
            m = active.reshape((-1,) + (1,) * (n.ndim - 1))
            return jnp.where(m, n, o)

        return jax.tree.map(sel, new, old)

    # -- speculative decoding (spec=(draft_module, draft_params)) -----------
    # The additive primitive set SlotDecode documents: the draft keeps its
    # own slot cache in cursor lockstep with the target (insert / chunked
    # prefill / plain-block tracking / spec rollback all move both), the
    # target verifies a whole drafted window in ONE multi-token pass, and
    # the only D2H traffic per spec block is the packed token fetch.
    if spec is not None:
        d_module, d_params = spec
        if int(d_module.vocab) != vocab:
            raise ValueError(
                f"draft vocab {d_module.vocab} != target vocab {vocab}")
        if int(d_module.max_len) != int(module.max_len):
            raise ValueError(
                f"draft max_len {d_module.max_len} != target max_len "
                f"{module.max_len} (draft and target cursors move in "
                "lockstep)")
        # The draft's params are NOT baked into the compiled draft
        # programs: every draft forward program takes them as its LAST
        # runtime argument (``dparams``), so a distilled replacement with
        # identical tree/shape/dtype geometry hot-swaps as a pure data
        # update — same jit cache entries, every compile pin holds
        # (SlotEngine.swap_draft / tpudist.distill).  ``d_params`` here
        # only seeds the engine's initial copy and cache geometry.
        d_init_cache, _ = make_decode_step(d_module, d_params)
        # the tied draft shares its slot's adapter: the draft IS the
        # target's first N blocks, so its factors are the pool's first
        # N layer slices.  A loaded draft gets them too iff its
        # projection geometry matches the target's; otherwise it runs
        # base-only (quality-only — the adapted verify is the oracle).
        d_lora = use_lora and (
            int(d_module.d_model) == int(module.d_model)
            and int(d_module.d_ff) == int(module.d_ff)
            and int(d_module.n_heads) == int(module.n_heads)
            and int(d_module.n_kv_heads or d_module.n_heads)
            == int(module.n_kv_heads or module.n_heads))
        n_d_layers = int(d_module.n_layers)

        def _d_ads(apool, ids):
            if not d_lora:
                return None
            return _gather_ads(apool, ids, n_d_layers)

        if d_lora:
            _d_ldec = d_module.clone(decode=True, moe_fn=None,
                                     lora_rank=adapters.rank)

            def d_step(dp, cache, tok, ad):
                logits, mut = _d_ldec.apply(
                    {"params": dp["params"], "cache": cache,
                     "adapters": ad},
                    tok, mutable=["cache"])
                return mut["cache"], logits[:, -1].astype(jnp.float32)
        else:
            _d_dec = d_module.clone(decode=True, moe_fn=None)

            def d_step(dp, cache, tok, ad):  # noqa: ARG001 - uniform sig
                logits, mut = _d_dec.apply(
                    {"params": dp["params"], "cache": cache},
                    tok, mutable=["cache"])
                return mut["cache"], logits[:, -1].astype(jnp.float32)
        d_vstep = jax.vmap(d_step, in_axes=(None, 0, 0, 0))

        def d_force(dp, cache, chunk, clen, ad):
            return _make_force(partial(d_step, dp))(cache, chunk, clen, ad)
        if use_lora:
            def _window1(cache, toks, ad):
                logits, mut = _ldec.apply(
                    {"params": params["params"], "cache": cache,
                     "adapters": ad},
                    toks[None], mutable=["cache"])
                return mut["cache"], logits[0].astype(jnp.float32)
        else:
            _window_base = make_decode_window(module, params)

            def _window1(cache, toks, ad):  # noqa: ARG001
                return _window_base(cache, toks)
        vwindow = jax.vmap(_window1, in_axes=(0, 0, 0))

        def _dconstrain(tree_):
            return tree_ if draft_constraint is None \
                else draft_constraint(tree_)

        def _set_cursors(cache, cur):
            """Overwrite every cursor leaf of a slot-stacked dense cache
            with ``cur [S]`` — the spec rollback: K/V past the cursor
            becomes masked garbage (the same ``live <= pos`` contract
            the paged gather relies on), so no K/V write is undone."""
            out = {}
            for key, val in cache.items():
                if isinstance(val, dict) and "k" in val and "v" in val:
                    out[key] = {k2: (v2 if k2 in ("k", "v")
                                     else cur.astype(v2.dtype))
                                for k2, v2 in val.items()}
                else:
                    out[key] = cur.astype(val.dtype)
            return out

        def _propose_scan(state, dview, k, d_ads, dp):
            """``k + 1`` draft decode steps with in-graph token feedback:
            steps ``0..k-1`` propose ``d_1..d_k`` (greedy argmax, or a
            categorical draw on the per-request ``fold_in(fold_in(key,
            count), 1)`` substream), step ``k`` feeds ``d_k`` so an
            all-accepted verify leaves draft and target cursors equal.
            Inactive lanes keep their cache and hold ``last_tok``."""

            def body(carry, i):
                tok, dc = carry
                nc, logits = d_vstep(dp, dc, tok[:, None, None], d_ads)
                dc = _sel_active(state.active, nc, dc)
                lg = logits[:, 0]
                greedy = jnp.argmax(lg, -1).astype(jnp.int32)

                def one(key, lgr, t, c):
                    kc = jax.random.fold_in(jax.random.fold_in(key, c), 1)
                    return jax.random.categorical(
                        kc, lgr / jnp.maximum(t, 1e-6))

                samp = jax.vmap(one)(state.keys, lg, state.temps,
                                     state.counts + i).astype(jnp.int32)
                d = jnp.where(state.temps > 0.0, samp, greedy)
                d = jnp.where(state.active, d,
                              state.last_tok).astype(jnp.int32)
                return (d, dc), (d, lg)

            (_, dview), (drafts, dlogits) = lax.scan(
                body, (state.last_tok, dview), jnp.arange(k + 1))
            return dview, drafts[:k], dlogits[:k]

        def _accept(state, logits, drafts, dlogits, spec_on, rem, gp):
            """Leading-prefix acceptance over the verify window, the
            correction/bonus token, and the per-lane budget clamp.

            ``logits [S, k+1, V]`` — row ``i`` conditioned on the cache
            prefix + ``w_0..w_i``; rows ``0..k-1`` score candidates
            ``d_1..d_k``, row ``k`` the all-accepted bonus.  Greedy
            lanes accept while the draft matches the target argmax —
            the emitted stream is exactly the sequential oracle's.
            Sampled lanes use the standard residual-distribution
            correction (accept ``d`` iff ``u·p_d(d) <= p_t(d)``; on
            reject draw from ``norm(max(p_t - p_d, 0))``), every draw on
            a deterministic ``fold_in`` substream of the request's key at
            that token's stream index, so the stream is independent of
            cache layout and mesh shape.  Lanes with ``spec_on`` False
            force zero acceptance and draw their one token on the PLAIN
            ``fold_in(key, count)`` stream — byte-identical to the
            non-speculative engine's.  Returns ``(x, a, a_raw, inc,
            out)`` — ``a_raw`` is the UNCLAMPED accept count (the
            draft-quality measure acceptance-rate telemetry wants;
            ``a``/``inc`` are the budget-clamped emission).

            ``gp`` (the grammar pool): the verify rows are masked along
            each lane's automaton TRAJECTORY over its drafts (row ``i``
            masked at the state after consuming ``d_1..d_i``), so a
            grammar-forbidden draft token is just a rejection — its
            masked target probability is zero — and the correction/
            bonus draws come from the constrained distribution.  The
            rejection is additionally FORCED (``acc &= tok_ok``):
            ``u == 0.0`` is a real value of ``jax.random.uniform`` and
            ``0 * p_d <= 0`` would otherwise accept.  Row 0 is masked
            at the lane's CURRENT state, so spec-off lanes (whose
            trajectory over garbage drafts is meaningless past row 0)
            still sample their one token correctly.  Returns two extra
            values: ``gnew`` (post-commit automaton states) and ``lp``
            (post-mask top-n rows, or None with logprobs off)."""
            k = drafts.shape[0]
            d = jnp.swapaxes(drafts, 0, 1)                  # [S, k]
            ld = jnp.swapaxes(dlogits, 0, 1)                # [S, k, V]
            if gp is not None:
                gallow, gnext = gp

                def gstep(st, dt):
                    arow = gallow[state.gidx, st]           # [S, V]
                    ok = jnp.take_along_axis(
                        arow, dt[:, None], 1)[:, 0]
                    return (jnp.where(ok, gnext[state.gidx, st, dt], st),
                            (st, ok))

                st_end, (traj_pre, tok_ok) = lax.scan(
                    gstep, state.gstate, jnp.swapaxes(d, 0, 1))
                traj = jnp.concatenate(
                    [jnp.swapaxes(traj_pre, 0, 1), st_end[:, None]], 1)
                logits = jnp.where(gallow[state.gidx[:, None], traj],
                                   logits, jnp.finfo(logits.dtype).min)
                tok_ok = jnp.swapaxes(tok_ok, 0, 1)         # [S, k]
            else:
                tok_ok = jnp.ones((num_slots, k), bool)
            lt = logits[:, :k]                              # [S, k, V]
            temp = jnp.maximum(state.temps, 1e-6)[:, None, None]
            greedy = state.temps <= 0.0
            g_acc = d == jnp.argmax(lt, -1)
            if use_sample_kernel:
                # one fused pass: both softmaxes + the residual logits
                # (bit-matching the in-graph formulas below, so the
                # accept/reject decisions and residual draws are
                # byte-identical)
                pt, pd, res_logits = fused_residual_prep(
                    lt, ld, state.temps, interpret=_interp)
            else:
                pt = jax.nn.softmax(lt / temp, -1)
                pd = jax.nn.softmax(ld / temp, -1)
            pt_d = jnp.take_along_axis(pt, d[..., None], -1)[..., 0]
            pd_d = jnp.take_along_axis(pd, d[..., None], -1)[..., 0]
            cidx = state.counts[:, None] + jnp.arange(k)[None]

            def u_one(key, c):
                kc = jax.random.fold_in(jax.random.fold_in(key, c), 2)
                return jax.random.uniform(kc)

            u = jax.vmap(lambda key, cs: jax.vmap(
                lambda c: u_one(key, c))(cs))(state.keys, cidx)
            s_acc = u * pd_d <= pt_d
            acc = jnp.where(greedy[:, None], g_acc, s_acc)
            acc &= tok_ok
            acc &= (spec_on & state.active)[:, None]
            a_raw = jnp.cumprod(acc.astype(jnp.int32), axis=1).sum(1)
            # budget clamp: emitted = a + 1 <= rem.  A clamped lane's
            # final token is its last ACCEPTED draft (a target-verified
            # token), never a correction drawn for a row that accepted.
            a = jnp.minimum(a_raw, jnp.maximum(rem - 1, 0))
            arg_rows = jnp.argmax(logits, -1).astype(jnp.int32)
            call = state.counts[:, None] + jnp.arange(k + 1)[None]

            def plain_one(key, lgr, t, c):
                return jax.random.categorical(
                    jax.random.fold_in(key, c), lgr / jnp.maximum(t, 1e-6))

            plain_rows = jax.vmap(lambda key, ls, t, cs: jax.vmap(
                lambda lgr, c: plain_one(key, lgr, t, c))(ls, cs))(
                state.keys, logits, state.temps, call).astype(jnp.int32)
            if not use_sample_kernel:
                res = jnp.maximum(pt - pd, 0.0)
                has_res = res.sum(-1, keepdims=True) > 0.0
                res_logits = jnp.where(has_res, jnp.log(res + 1e-30),
                                       lt / temp)

            def res_one(key, lgr, c):
                kc = jax.random.fold_in(jax.random.fold_in(key, c), 3)
                return jax.random.categorical(kc, lgr)

            res_rows = jax.vmap(lambda key, ls, cs: jax.vmap(
                lambda lgr, c: res_one(key, lgr, c))(ls, cs))(
                state.keys, res_logits, cidx).astype(jnp.int32)
            row_g = jnp.take_along_axis(arg_rows, a[:, None], 1)[:, 0]
            row_p = jnp.take_along_axis(plain_rows, a[:, None], 1)[:, 0]
            row_r = jnp.take_along_axis(
                jnp.concatenate([res_rows, plain_rows[:, -1:]], 1),
                a[:, None], 1)[:, 0]
            # row a rejected a pending draft -> residual draw; row k (or a
            # spec-off lane's row 0) has no pending draft -> plain stream
            specced = spec_on & state.active & (a < k)
            x = jnp.where(greedy, row_g, jnp.where(specced, row_r, row_p))
            # clamped lane: final emitted token is the accepted draft
            clamp_d = jnp.take_along_axis(
                d, jnp.minimum(a, k - 1)[:, None], 1)[:, 0]
            x = jnp.where(a < a_raw, clamp_d, x).astype(jnp.int32)
            inc = jnp.where(state.active, a + 1, 0).astype(jnp.int32)
            i_ = jnp.arange(k + 1)[None]
            dpad = jnp.concatenate(
                [d, jnp.zeros((num_slots, 1), jnp.int32)], 1)
            out = jnp.where(i_ < a[:, None], dpad,
                            jnp.where(i_ == a[:, None], x[:, None], 0))
            out = jnp.where(state.active[:, None], out, 0)
            if gp is not None:
                # automaton advance over the EMITTED tokens (out[:, :inc])
                # — accepted drafts all passed tok_ok and corrections come
                # from the masked rows, so every consumed transition is a
                # real one (and the tables self-loop defensively anyway)
                def cstep(st, xs):
                    col, j = xs
                    nst = gnext[state.gidx, st, col]
                    return jnp.where(j < inc, nst, st), None

                gnew, _ = lax.scan(
                    cstep, state.gstate,
                    (jnp.swapaxes(out, 0, 1), jnp.arange(k + 1)))
            else:
                gnew = state.gstate
            lp = None if n_lp == 0 else _top_lp(logits)
            return x, a, a_raw, inc, out, gnew, lp

        def _spec_state(state, x, a_raw, inc, spec_on, k, gnew):
            return state._replace(
                last_tok=jnp.where(state.active, x, state.last_tok),
                counts=state.counts + inc,
                pos=state.pos + inc,
                accepted=state.accepted + a_raw,
                drafted=state.drafted + jnp.where(
                    state.active & spec_on, k, 0),
                gstate=gnew)

        def _build_spec(pg_target):
            if pg_target is None:
                def init_draft():
                    one = d_init_cache(1)
                    return jax.tree.map(
                        lambda a: jnp.zeros((num_slots,) + a.shape, a.dtype),
                        one)

                def _draft_prefill_impl(dcache, prompts, clens, dsts, ads,
                                        dp):
                    lanes = jax.vmap(
                        lambda p, n, a: d_force(dp, d_init_cache(1), p, n,
                                                a)[0]
                    )(prompts, clens, ads)
                    return _dconstrain(jax.tree.map(
                        lambda full, b: full.at[dsts].set(b), dcache, lanes))

                def _draft_extend_impl(dcache, slot, chunk, clen, ad, dp):
                    lane = jax.tree.map(
                        lambda full: lax.dynamic_index_in_dim(
                            full, slot, 0, keepdims=False), dcache)
                    lane, _ = d_force(dp, lane, chunk, clen, ad)
                    return _dconstrain(jax.tree.map(
                        lambda full, lv: lax.dynamic_update_index_in_dim(
                            full, lv, slot, 0), dcache, lane))

                if use_lora:
                    @partial(jax.jit, donate_argnums=(0,))
                    def draft_prefill(dcache, prompts, clens, dsts, aids,
                                      apool, dparams):
                        return _draft_prefill_impl(
                            dcache, prompts, clens, dsts,
                            _d_ads(apool, aids), dparams)

                    @partial(jax.jit, donate_argnums=(0,))
                    def draft_extend(dcache, slot, chunk, clen, aid, apool,
                                     dparams):
                        return _draft_extend_impl(
                            dcache, slot, chunk, clen, _d_ads(apool, aid),
                            dparams)
                else:
                    @partial(jax.jit, donate_argnums=(0,))
                    def draft_prefill(dcache, prompts, clens, dsts, dparams):
                        return _draft_prefill_impl(dcache, prompts, clens,
                                                   dsts, None, dparams)

                    @partial(jax.jit, donate_argnums=(0,))
                    def draft_extend(dcache, slot, chunk, clen, dparams):
                        return _draft_extend_impl(dcache, slot, chunk,
                                                  clen, None, dparams)

                @partial(jax.jit, donate_argnums=(0,))
                def draft_evict(dcache, slot):
                    return _dconstrain(jax.tree.map(
                        lambda full: lax.dynamic_update_index_in_dim(
                            full, jnp.zeros(full.shape[1:], full.dtype),
                            slot, 0), dcache))

                @partial(jax.jit, donate_argnums=(0,))
                def draft_arm(dcache, slot, pos):
                    out = {}
                    for key, val in dcache.items():
                        if isinstance(val, dict) and "k" in val \
                                and "v" in val:
                            out[key] = {
                                k2: (v2.at[slot].set(
                                    jnp.zeros(v2.shape[1:], v2.dtype))
                                    if k2 in ("k", "v")
                                    else v2.at[slot].set(
                                        jnp.asarray(pos, v2.dtype)))
                                for k2, v2 in val.items()}
                        else:
                            out[key] = val.at[slot].set(
                                jnp.asarray(pos, val.dtype))
                    return _dconstrain(out)

                def _draft_track_impl(state, dcache, prev_last, toks, d_ads,
                                      dp):
                    fed = jnp.concatenate([prev_last[None], toks[:-1]], 0)

                    def body(dc, tok):
                        nc, _ = d_vstep(dp, dc, tok[:, None, None], d_ads)
                        return _sel_active(state.active, nc, dc), None

                    dcache, _ = lax.scan(body, dcache, fed)
                    return _dconstrain(dcache)

                if use_lora:
                    @partial(jax.jit, donate_argnums=(1,))
                    def draft_track(state, dcache, prev_last, toks, apool,
                                    dparams):
                        return _draft_track_impl(
                            state, dcache, prev_last, toks,
                            _d_ads(apool, state.adapter_id), dparams)

                    @partial(jax.jit, static_argnums=2, donate_argnums=(1,))
                    def draft_propose(state, dcache, k, apool, dparams):
                        dcache, drafts, dlogits = _propose_scan(
                            state, dcache, k,
                            _d_ads(apool, state.adapter_id), dparams)
                        return _dconstrain(dcache), drafts, dlogits
                else:
                    @partial(jax.jit, donate_argnums=(1,))
                    def draft_track(state, dcache, prev_last, toks, dparams):
                        return _draft_track_impl(state, dcache, prev_last,
                                                 toks, None, dparams)

                    @partial(jax.jit, static_argnums=2, donate_argnums=(1,))
                    def draft_propose(state, dcache, k, dparams):
                        dcache, drafts, dlogits = _propose_scan(
                            state, dcache, k, None, dparams)
                        return _dconstrain(dcache), drafts, dlogits

                def _spec_verify_impl(state, cache, dcache, drafts, dlogits,
                                      spec_on, rem, ads, gp):
                    pos0 = _cache_cursor(cache)
                    toks = jnp.concatenate(
                        [state.last_tok[None], drafts], 0).T
                    ncache, logits = vwindow(cache, toks, ads)
                    x, a, a_raw, inc, out, gnew, lp = _accept(
                        state, logits, drafts, dlogits, spec_on, rem, gp)
                    cache = _sel_active(state.active, ncache, cache)
                    cache = _set_cursors(cache, pos0 + inc)
                    dcache = _set_cursors(dcache, pos0 + inc)
                    state = _spec_state(state, x, a_raw, inc, spec_on,
                                        drafts.shape[0], gnew)
                    packed = jnp.concatenate(
                        [inc[:, None], a_raw[:, None], out], 1)
                    base = (_constrain_state(state), _constrain(cache),
                            _dconstrain(dcache), packed)
                    return base if lp is None else base + lp

                @partial(jax.jit, donate_argnums=(0, 1, 2))
                def spec_verify(state, cache, dcache, drafts, dlogits,
                                spec_on, rem, *tail):
                    ads, gp = _slot_tail(tail, state.adapter_id)
                    return _spec_verify_impl(state, cache, dcache, drafts,
                                             dlogits, spec_on, rem, ads, gp)

                return dict(init_draft=init_draft,
                            draft_prefill=draft_prefill,
                            draft_extend=draft_extend,
                            draft_evict=draft_evict, draft_arm=draft_arm,
                            draft_track=draft_track,
                            draft_propose=draft_propose,
                            spec_verify=spec_verify)

            # paged target: the draft KV is its own smaller block pool —
            # the DRAFT template's bytes at the TARGET pool's geometry
            # (same num_blocks/block_size, so block ids, the host
            # allocator, prefix reuse, and evict free-lists are shared;
            # "smaller" is the per-block byte count, which is what HBM
            # residency is measured in).
            d_cfg = PagedKVConfig(num_blocks=paged.num_blocks,
                                  block_size=paged.block_size,
                                  quantized=False)
            pg_d = _Paged(d_init_cache(1), num_slots, d_cfg)
            d_meta_template = strip_kv(pg_d.template)

            def _draft_prefill_impl(dkv, tables, poss, prompts, clens,
                                    dsts, ads, dp):
                def lane(row, pos0, p, n, ad):
                    meta1 = jax.tree.map(
                        lambda t: jnp.asarray(pos0, t.dtype),
                        d_meta_template)
                    return d_force(dp, pg_d.lane_cache(dkv, row, meta1),
                                   p, n, ad)[0]

                lanes = jax.vmap(lane)(tables, poss, prompts, clens, ads)
                return _dconstrain(pg_d.commit_lanes(
                    dkv, lanes, tables, dsts, poss, prefill_pad))

            def _draft_extend_impl(dkv, slot, chunk, clen, ad, dp):
                row = dkv.table[slot]
                meta1 = jax.tree.map(lambda full: full[slot], dkv.meta)
                pos0 = _cache_cursor(meta1)
                cache, _ = d_force(dp, pg_d.lane_cache(dkv, row, meta1),
                                   chunk, clen, ad)
                return _dconstrain(pg_d.commit_lanes(
                    dkv, jax.tree.map(lambda a: a[None], cache),
                    row[None], jnp.reshape(slot, (1,)),
                    jnp.reshape(pos0, (1,)), prefill_pad))

            if use_lora:
                @partial(jax.jit, donate_argnums=(0,))
                def draft_prefill(dkv, tables, poss, prompts, clens, dsts,
                                  aids, apool, dparams):
                    return _draft_prefill_impl(dkv, tables, poss, prompts,
                                               clens, dsts,
                                               _d_ads(apool, aids), dparams)

                @partial(jax.jit, donate_argnums=(0,))
                def draft_extend(dkv, slot, chunk, clen, aid, apool,
                                 dparams):
                    return _draft_extend_impl(dkv, slot, chunk, clen,
                                              _d_ads(apool, aid), dparams)
            else:
                @partial(jax.jit, donate_argnums=(0,))
                def draft_prefill(dkv, tables, poss, prompts, clens, dsts,
                                  dparams):
                    return _draft_prefill_impl(dkv, tables, poss, prompts,
                                               clens, dsts, None, dparams)

                @partial(jax.jit, donate_argnums=(0,))
                def draft_extend(dkv, slot, chunk, clen, dparams):
                    return _draft_extend_impl(dkv, slot, chunk, clen, None,
                                              dparams)

            @partial(jax.jit, donate_argnums=(0,))
            def draft_evict(dkv, slot, free_ids):
                return _dconstrain(pg_d.release(dkv, slot, free_ids))

            @partial(jax.jit, donate_argnums=(0,))
            def draft_arm(dkv, slot, row, pos):
                meta = jax.tree.map(
                    lambda full: full.at[slot].set(
                        jnp.asarray(pos, full.dtype)), dkv.meta)
                return _dconstrain(dkv._replace(
                    table=dkv.table.at[slot].set(row), meta=meta))

            def _draft_track_impl(state, dkv, prev_last, toks, d_ads, dp):
                k = toks.shape[0]
                pos0 = _cache_cursor(dkv.meta)
                view = pg_d.slot_cache(dkv)
                fed = jnp.concatenate([prev_last[None], toks[:-1]], 0)

                def body(dc, tok):
                    nc, _ = d_vstep(dp, dc, tok[:, None, None], d_ads)
                    return _sel_active(state.active, nc, dc), None

                view, _ = lax.scan(body, view, fed)
                return _dconstrain(pg_d.commit_slots(
                    dkv, view, pos0, k, state.active))

            if use_lora:
                @partial(jax.jit, donate_argnums=(1,))
                def draft_track(state, dkv, prev_last, toks, apool, dparams):
                    return _draft_track_impl(
                        state, dkv, prev_last, toks,
                        _d_ads(apool, state.adapter_id), dparams)

                @partial(jax.jit, static_argnums=2, donate_argnums=(1,))
                def draft_propose(state, dkv, k, apool, dparams):
                    pos0 = _cache_cursor(dkv.meta)
                    view, drafts, dlogits = _propose_scan(
                        state, pg_d.slot_cache(dkv), k,
                        _d_ads(apool, state.adapter_id), dparams)
                    dkv = pg_d.commit_slots(dkv, view, pos0, k + 1,
                                            state.active)
                    return _dconstrain(dkv), drafts, dlogits
            else:
                @partial(jax.jit, donate_argnums=(1,))
                def draft_track(state, dkv, prev_last, toks, dparams):
                    return _draft_track_impl(state, dkv, prev_last, toks,
                                             None, dparams)

                @partial(jax.jit, static_argnums=2, donate_argnums=(1,))
                def draft_propose(state, dkv, k, dparams):
                    pos0 = _cache_cursor(dkv.meta)
                    view, drafts, dlogits = _propose_scan(
                        state, pg_d.slot_cache(dkv), k, None, dparams)
                    dkv = pg_d.commit_slots(dkv, view, pos0, k + 1,
                                            state.active)
                    return _dconstrain(dkv), drafts, dlogits

            def _spec_verify_impl(state, pkv, dkv, drafts, dlogits,
                                  spec_on, rem, ads, gp):
                k = drafts.shape[0]
                pos0 = _cache_cursor(pkv.meta)
                toks = jnp.concatenate([state.last_tok[None], drafts], 0).T
                if attn_kernel == "paged":
                    # the verify window runs through the SAME paged
                    # kernel as s=1 decode (the fused spec-window
                    # mask): one batched K+1-query pass, live blocks
                    # only, window committed via commit_window
                    wview = pg_target.window_view(pkv, k + 1)
                    nview, logits = _kernel_window(pkv, wview, pos0, toks,
                                                   ads)
                else:
                    nview, logits = vwindow(pg_target.slot_cache(pkv), toks,
                                            ads)
                x, a, a_raw, inc, out, gnew, lp = _accept(
                    state, logits, drafts, dlogits, spec_on, rem, gp)
                if attn_kernel == "paged":
                    pkv = pg_target.commit_window(pkv, nview, pos0, k + 1,
                                                  state.active)
                else:
                    pkv = pg_target.commit_slots(pkv, nview, pos0, k + 1,
                                                 state.active)
                new_cur = pos0 + inc
                pkv = pkv._replace(meta=jax.tree.map(
                    lambda full: new_cur.astype(full.dtype), pkv.meta))
                dkv = dkv._replace(meta=jax.tree.map(
                    lambda full: new_cur.astype(full.dtype), dkv.meta))
                state = _spec_state(state, x, a_raw, inc, spec_on, k, gnew)
                packed = jnp.concatenate(
                    [inc[:, None], a_raw[:, None], out], 1)
                base = (_constrain_state(state), _constrain(pkv),
                        _dconstrain(dkv), packed)
                return base if lp is None else base + lp

            @partial(jax.jit, donate_argnums=(0, 1, 2))
            def spec_verify(state, pkv, dkv, drafts, dlogits, spec_on,
                            rem, *tail):
                ads, gp = _slot_tail(tail, state.adapter_id,
                                     kernel_path=attn_kernel == "paged")
                return _spec_verify_impl(state, pkv, dkv, drafts, dlogits,
                                         spec_on, rem, ads, gp)

            return dict(init_draft=pg_d.init, draft_prefill=draft_prefill,
                        draft_extend=draft_extend, draft_evict=draft_evict,
                        draft_arm=draft_arm, draft_track=draft_track,
                        draft_propose=draft_propose, spec_verify=spec_verify,
                        draft_paged=pg_d)
    else:
        def _build_spec(pg_target):  # noqa: ARG001 - uniform call sites
            return {}

    if paged is not None:
        pg = _Paged(init_cache(1), num_slots, paged)
        meta_template = strip_kv(pg.template)
        use_kernel = attn_kernel == "paged"
        if use_kernel:
            # The kernel path's model clone: Block._decode_attention
            # dispatches to the Pallas paged-attention kernel, the
            # decode cache becomes a per-layer WINDOW buffer, and the
            # pool rides in read-only through the "pool" collection.
            # Runs BATCHED over slots (no vmap): the kernel's grid
            # covers all slots in one call per layer, per-slot cursors
            # ride as vectors.
            dec_kernel_mod = module.clone(
                decode=True, moe_fn=None, decode_kernel="paged",
                lora_rank=adapters.rank if use_lora else 0,
                fused_rope=use_fused_rope, lora_kernel=use_lora_kernel)

            def _pool_col(pkv, pos0):
                # one shared entry per layer; the leaves are the SAME
                # tracers, so nothing is duplicated or sliced (a
                # per-layer pool slice would copy a full layer's pool
                # per dispatch — the kernel indexes the [L, ...] pool
                # with its static layer_idx instead)
                col = dict(pk=pkv.pool_k, pv=pkv.pool_v, sk=pkv.scale_k,
                           sv=pkv.scale_v, table=pkv.table,
                           pos0=pos0.astype(jnp.int32))
                return {name: col for name in pg.layers}

            def _kernel_window(pkv, view, pos0, toks, ads=None):
                """One batched multi-token pass over a window view:
                every lane's ``s`` tokens in ONE forward, attention
                through the paged kernel — ``s == 1`` is the decode
                scan's body, ``s == K+1`` the spec verify.  ``ads``:
                the slot batch's gathered adapter collection ([S]-
                leading leaves — the batched twin of the vmapped
                path's per-lane collections)."""
                variables = {"params": params["params"], "cache": view,
                             "pool": _pool_col(pkv, pos0)}
                if ads is not None:
                    variables["adapters"] = ads
                logits, mut = dec_kernel_mod.apply(
                    variables, toks, mutable=["cache"])
                return mut["cache"], logits.astype(jnp.float32)

        if use_prefill_kernel:
            # The prefill twin of the decode kernel clone: Block
            # dispatches to the Pallas paged-PREFILL kernel — each
            # lane's chunk attends over its pool prefix (walked
            # in-kernel through the block table) plus itself, and the
            # touched KV blocks are quantized and emitted IN-KERNEL
            # (the "pwrites" collection), so no dense [slots, max_len]
            # lane view is ever materialized and no sequential
            # teacher-force scan runs.  ONE batched program serves the
            # whole admission batch AND single-slot chunk extends (the
            # extend synthesizes a one-hot batch) — program count
            # stays flat.
            pre_mod = module.clone(
                decode=True, moe_fn=None, decode_kernel="paged_prefill",
                lora_rank=adapters.rank if use_lora else 0,
                fused_rope=use_fused_rope, lora_kernel=use_lora_kernel)

            def _kernel_prefill(pkv, rows, poss, clens, prompts,
                                lane_mask, ads):
                """One batched kernel prefill over ``prompts [S, P]``:
                returns ``(pkv, last_logits [S, V])`` with the touched
                blocks committed (storage-form scatter; sentinel
                write-table entries drop, so masked/zero-clen lanes
                write nothing).  Table/meta installation is the
                caller's — insert scatters rows at ``dsts``, extend
                advances one cursor."""
                wtables = pg.write_tables(rows, poss, clens, prefill_pad,
                                          lane_mask)
                col = dict(pk=pkv.pool_k, pv=pkv.pool_v, sk=pkv.scale_k,
                           sv=pkv.scale_v, table=rows,
                           pos0=poss.astype(jnp.int32),
                           clen=clens.astype(jnp.int32), wtable=wtables)
                variables = {"params": params["params"],
                             "pool": {name: col for name in pg.layers}}
                if not module.rope:
                    # the learned position table reads per-lane vector
                    # cursors (the decode kernel path's contract)
                    variables["cache"] = {"pos": poss.astype(jnp.int32)}
                if ads is not None:
                    variables["adapters"] = ads
                logits, mut = pre_mod.apply(
                    variables, prompts, mutable=["cache", "pwrites"])
                pw = mut["pwrites"]
                qk = jnp.stack([pw[n]["k"] for n in pg.layers])
                qv = jnp.stack([pw[n]["v"] for n in pg.layers])
                sk = jnp.stack([pw[n]["sk"] for n in pg.layers])
                sv = jnp.stack([pw[n]["sv"] for n in pg.layers])
                n_ids = wtables.size
                pkv = pg.commit_quantized(
                    pkv, wtables.reshape(n_ids),
                    qk.reshape((qk.shape[0], n_ids) + qk.shape[3:]),
                    qv.reshape((qv.shape[0], n_ids) + qv.shape[3:]),
                    sk.reshape(sk.shape[0], n_ids, sk.shape[-1]),
                    sv.reshape(sv.shape[0], n_ids, sv.shape[-1]))
                last = jnp.take_along_axis(
                    logits, jnp.clip(clens - 1, 0, logits.shape[1] - 1)
                    [:, None, None], axis=1)
                return pkv, last[:, 0].astype(jnp.float32)

        def _insert_paged_impl(state, pkv, tables, poss, prompts, clens,
                               dsts, seeds, temps, last, aids, ads,
                               gids, gp):
            # Each lane teacher-forces its first NON-SHARED chunk on top
            # of a dense view gathered through its (host-built) table
            # row: a reused prefix's K/V is already in the pool, so the
            # lane's cursor starts at poss[j] — prefilled once, mapped
            # into every slot that shares it.
            if use_prefill_kernel:
                pkv, last_logits = _kernel_prefill(
                    pkv, tables, poss, clens, prompts,
                    dsts < num_slots, ads)
                new_cur = poss + clens
                pkv = _constrain(pkv._replace(
                    table=pkv.table.at[dsts].set(tables),
                    meta=jax.tree.map(
                        lambda full: full.at[dsts].set(
                            new_cur.astype(full.dtype)), pkv.meta)))
            else:
                def lane(row, pos0, p, n, ad):
                    meta1 = jax.tree.map(
                        lambda t: jnp.asarray(pos0, t.dtype), meta_template)
                    return _force_chunk(pg.lane_cache(pkv, row, meta1),
                                        p, n, ad)

                lanes, last_logits = jax.vmap(lane)(tables, poss, prompts,
                                                    clens, ads)
                pkv = _constrain(pg.commit_lanes(pkv, lanes, tables, dsts,
                                                 poss, prefill_pad))
            keys = jax.vmap(jax.random.PRNGKey)(seeds).astype(jnp.uint32)
            zero = jnp.zeros(num_slots, jnp.int32)
            firsts = _sample_tail(gp, gids, zero, last_logits,
                                  keys, temps, zero)[0]
            state = SlotState(
                last_tok=state.last_tok.at[dsts].set(
                    jnp.where(last, firsts, 0)),
                active=state.active.at[dsts].set(last),
                pos=state.pos.at[dsts].set(poss + clens),
                counts=state.counts.at[dsts].set(last.astype(jnp.int32)),
                temps=state.temps.at[dsts].set(temps),
                keys=state.keys.at[dsts].set(keys),
                accepted=state.accepted.at[dsts].set(zero),
                drafted=state.drafted.at[dsts].set(zero),
                adapter_id=state.adapter_id.at[dsts].set(aids),
                gidx=state.gidx.at[dsts].set(gids),
                gstate=state.gstate.at[dsts].set(
                    _gadvance(gp, gids, zero, firsts, last)))
            return _constrain_state(state), pkv, firsts

        @partial(jax.jit, donate_argnums=(0, 1))
        def insert_batch_paged(state, pkv, tables, poss, prompts, clens,
                               dsts, seeds, temps, last, *tail):
            aids, ads, gids, gp = _insert_tail(
                tail, kernel_path=use_prefill_kernel)
            return _insert_paged_impl(
                state, pkv, tables, poss, prompts, clens, dsts, seeds,
                temps, last, aids, ads, gids, gp)

        def _prefill_extend_paged_impl(state, pkv, slot, chunk, clen,
                                       is_last, ad, gp):
            if use_prefill_kernel:
                # one-hot batch through the SAME batched kernel-prefill
                # program as insert (zero-clen lanes' write tables are
                # all-sentinel, so they commit nothing) — chunked
                # prefill adds no second program shape.
                onehot = jnp.arange(num_slots) == slot
                poss = _cache_cursor(pkv.meta)
                prompts1 = jnp.zeros((num_slots, prefill_pad),
                                     jnp.int32).at[slot].set(chunk)
                clens = jnp.where(onehot, clen, 0).astype(jnp.int32)
                pkv, last_all = _kernel_prefill(
                    pkv, pkv.table, poss, clens, prompts1, onehot, ad)
                last_logits = last_all[slot]
                pkv = _constrain(pkv._replace(meta=jax.tree.map(
                    lambda full: full.at[slot].add(
                        jnp.asarray(clen, full.dtype)), pkv.meta)))
            else:
                row = pkv.table[slot]
                meta1 = jax.tree.map(lambda full: full[slot], pkv.meta)
                pos0 = _cache_cursor(meta1)
                cache, last_logits = _force_chunk(
                    pg.lane_cache(pkv, row, meta1), chunk, clen, ad)
                pkv = _constrain(pg.commit_lanes(
                    pkv, jax.tree.map(lambda a: a[None], cache),
                    row[None], jnp.reshape(slot, (1,)),
                    jnp.reshape(pos0, (1,)), prefill_pad))
            gi = state.gidx[slot][None]
            gs = state.gstate[slot][None]
            first = _sample_tail(
                gp, gi, gs, last_logits[None],
                state.keys[slot][None],
                state.temps[slot][None], jnp.zeros(1, jnp.int32))[0][0]
            state = state._replace(
                pos=state.pos.at[slot].add(clen),
                active=state.active.at[slot].set(is_last),
                last_tok=state.last_tok.at[slot].set(
                    jnp.where(is_last, first, 0)),
                counts=state.counts.at[slot].set(is_last.astype(jnp.int32)),
                gstate=state.gstate.at[slot].set(_gadvance(
                    gp, gi, gs, first[None],
                    jnp.reshape(is_last, (1,)))[0]))
            return _constrain_state(state), pkv, first

        @partial(jax.jit, donate_argnums=(0, 1))
        def prefill_extend_paged(state, pkv, slot, chunk, clen,
                                 is_last, *tail):
            if use_prefill_kernel:
                # the one-hot batched program runs EVERY lane's adapter
                ad, gp = _slot_tail(tail, state.adapter_id,
                                    kernel_path=True)
            else:
                ad, gp = _slot_tail(tail, state.adapter_id[slot])
            return _prefill_extend_paged_impl(
                state, pkv, slot, chunk, clen, is_last, ad, gp)

        if use_kernel:
            def _decode_kernel_impl(state, pkv, k, ads, gp):
                # The kernel arm: NO dense gather.  The pool is read in
                # place by the kernel (live blocks only — loop-invariant,
                # so it stays out of the scan carry); the scan carries
                # just the [S, n_kv, k, dh] window buffers + meta, and
                # the commit touches only the blocks the window spans.
                pos0 = _cache_cursor(pkv.meta)
                mask = state.active
                pool = _pool_col(pkv, pos0)
                view = pg.window_view(pkv, k)

                def body(carry, _):
                    state, view = carry
                    variables = {"params": params["params"], "cache": view,
                                 "pool": pool}
                    if ads is not None:
                        variables["adapters"] = ads
                    logits, mut = dec_kernel_mod.apply(
                        variables,
                        state.last_tok[:, None], mutable=["cache"])
                    view = _sel_active(state.active, mut["cache"], view)
                    toks, lg = _sample_tail(
                        gp, state.gidx, state.gstate,
                        logits[:, -1].astype(jnp.float32),
                        state.keys, state.temps, state.counts)
                    toks = jnp.where(state.active, toks,
                                     state.last_tok).astype(jnp.int32)
                    inc = state.active.astype(jnp.int32)
                    state = state._replace(
                        last_tok=toks, counts=state.counts + inc,
                        pos=state.pos + inc,
                        gstate=_gadvance(gp, state.gidx, state.gstate,
                                         toks, state.active))
                    ys = toks if n_lp == 0 else (toks,) + _top_lp(lg)
                    return (state, view), ys

                (state, view), ys = lax.scan(body, (state, view), None,
                                             length=k)
                pkv = _constrain(pg.commit_window(pkv, view, pos0, k, mask))
                if n_lp:
                    toks, li, lv = ys
                    return _constrain_state(state), pkv, toks, li, lv
                return _constrain_state(state), pkv, ys

            @partial(jax.jit, static_argnums=2, donate_argnums=(0, 1))
            def decode_block_paged(state, pkv, k, *tail):
                ads, gp = _slot_tail(tail, state.adapter_id,
                                     kernel_path=True)
                return _decode_kernel_impl(state, pkv, k, ads, gp)
        else:
            def _decode_paged_impl(state, pkv, k, ads, gp):
                pos0 = _cache_cursor(pkv.meta)
                mask = state.active
                (state, cache), ys = _decode_scan(
                    state, pg.slot_cache(pkv), k, ads, gp)
                pkv = _constrain(pg.commit_slots(pkv, cache, pos0, k, mask))
                if n_lp:
                    toks, li, lv = ys
                    return _constrain_state(state), pkv, toks, li, lv
                return _constrain_state(state), pkv, ys

            @partial(jax.jit, static_argnums=2, donate_argnums=(0, 1))
            def decode_block_paged(state, pkv, k, *tail):
                ads, gp = _slot_tail(tail, state.adapter_id)
                return _decode_paged_impl(state, pkv, k, ads, gp)

        @partial(jax.jit, donate_argnums=(0, 1))
        def evict_paged(state, pkv, slot, free_ids):
            pkv = _constrain(pg.release(pkv, slot, free_ids))
            zero = jnp.zeros((), jnp.int32)
            state = SlotState(
                last_tok=state.last_tok.at[slot].set(zero),
                active=state.active.at[slot].set(False),
                pos=state.pos.at[slot].set(zero),
                counts=state.counts.at[slot].set(zero),
                temps=state.temps.at[slot].set(jnp.zeros((), jnp.float32)),
                keys=state.keys.at[slot].set(jnp.zeros(2, jnp.uint32)),
                accepted=state.accepted.at[slot].set(zero),
                drafted=state.drafted.at[slot].set(zero),
                adapter_id=state.adapter_id.at[slot].set(
                    jnp.asarray(_aid_empty, jnp.int32)),
                gidx=state.gidx.at[slot].set(
                    jnp.asarray(_gid_empty, jnp.int32)),
                gstate=state.gstate.at[slot].set(zero))
            return _constrain_state(state), pkv

        def _peek_paged_impl(state, pkv, ads):
            _, logits = vstep(pg.slot_cache(pkv),
                              state.last_tok[:, None, None], ads)
            return logits[:, 0]

        if use_lora:
            @jax.jit
            def peek_logits_paged(state, pkv, apool):
                return _peek_paged_impl(
                    state, pkv, _gather_ads(apool, state.adapter_id))
        else:
            @jax.jit
            def peek_logits_paged(state, pkv):
                return _peek_paged_impl(state, pkv, None)

        @jax.jit
        def export_lane_paged(state, pkv, slot):
            ks, vs, meta1 = pg.extract_lane(pkv, slot)
            lane_state = jax.tree.map(lambda a: a[slot], state)
            return (ks, vs, meta1), lane_state

        @partial(jax.jit, donate_argnums=(0, 1))
        def import_lane_paged(state, pkv, slot, row, lane, lane_state):
            ks, vs, meta1 = lane
            pkv = _constrain(pg.adopt_lane(pkv, slot, row, ks, vs, meta1))
            state = jax.tree.map(lambda full, v: full.at[slot].set(v),
                                 state, lane_state)
            return _constrain_state(state), pkv

        return SlotDecode(
            num_slots=num_slots, prefill_pad=prefill_pad,
            init_state=init_state, init_slots=pg.init,
            insert_batch=insert_batch_paged,
            prefill_extend=prefill_extend_paged,
            decode_block=decode_block_paged, evict=evict_paged,
            sample=jax.jit(_slot_sample), peek_logits=peek_logits_paged,
            paged=pg, export_lane=export_lane_paged,
            import_lane=import_lane_paged, **_build_spec(pg))

    # The slot state AND cache are donated in every primitive that threads
    # them: the engine always overwrites both with the result, and without
    # donation each iteration would copy the whole [num_slots × layers ×
    # max_len] K/V arena into fresh buffers — doubling peak cache memory
    # and paying a full-arena memcpy per decode block.
    def _insert_impl(state, cache, prompts, clens, dsts, seeds, temps,
                     last, aids, ads, gids, gp):
        lanes, last_logits = jax.vmap(
            lambda p, n, a: _force_chunk(init_cache(1), p, n, a)
        )(prompts, clens, ads)
        keys = jax.vmap(jax.random.PRNGKey)(seeds).astype(jnp.uint32)
        zero = jnp.zeros(num_slots, jnp.int32)
        firsts = _sample_tail(gp, gids, zero, last_logits,
                              keys, temps, zero)[0]
        # Scatter lane j into slot dsts[j].  Unused lanes carry the
        # sentinel dst num_slots: out-of-bounds scatter indices are
        # DROPPED (jax's default scatter mode), so one fixed-shape
        # program serves every admission-batch size.
        cache = _constrain(jax.tree.map(
            lambda full, b: full.at[dsts].set(b), cache, lanes))
        state = SlotState(
            last_tok=state.last_tok.at[dsts].set(jnp.where(last, firsts, 0)),
            active=state.active.at[dsts].set(last),
            pos=state.pos.at[dsts].set(clens),
            counts=state.counts.at[dsts].set(last.astype(jnp.int32)),
            temps=state.temps.at[dsts].set(temps),
            keys=state.keys.at[dsts].set(keys),
            accepted=state.accepted.at[dsts].set(zero),
            drafted=state.drafted.at[dsts].set(zero),
            adapter_id=state.adapter_id.at[dsts].set(aids),
            gidx=state.gidx.at[dsts].set(gids),
            gstate=state.gstate.at[dsts].set(
                _gadvance(gp, gids, zero, firsts, last)))
        return _constrain_state(state), cache, firsts

    @partial(jax.jit, donate_argnums=(0, 1))
    def insert_batch(state, cache, prompts, clens, dsts, seeds, temps,
                     last, *tail):
        aids, ads, gids, gp = _insert_tail(tail)
        return _insert_impl(state, cache, prompts, clens, dsts, seeds,
                            temps, last, aids, ads, gids, gp)

    def _prefill_extend_impl(state, cache, slot, chunk, clen, is_last, ad,
                             gp):
        lane = jax.tree.map(
            lambda full: lax.dynamic_index_in_dim(
                full, slot, 0, keepdims=False), cache)
        lane, last_logits = _force_chunk(lane, chunk, clen, ad)
        cache = _constrain(jax.tree.map(
            lambda full, l: lax.dynamic_update_index_in_dim(full, l, slot, 0),
            cache, lane))
        gi = state.gidx[slot][None]
        gs = state.gstate[slot][None]
        first = _sample_tail(
            gp, gi, gs, last_logits[None],
            state.keys[slot][None],
            state.temps[slot][None], jnp.zeros(1, jnp.int32))[0][0]
        state = state._replace(
            pos=state.pos.at[slot].add(clen),
            active=state.active.at[slot].set(is_last),
            last_tok=state.last_tok.at[slot].set(
                jnp.where(is_last, first, 0)),
            counts=state.counts.at[slot].set(is_last.astype(jnp.int32)),
            gstate=state.gstate.at[slot].set(_gadvance(
                gp, gi, gs, first[None], jnp.reshape(is_last, (1,)))[0]))
        return _constrain_state(state), cache, first

    @partial(jax.jit, donate_argnums=(0, 1))
    def prefill_extend(state, cache, slot, chunk, clen, is_last, *tail):
        ad, gp = _slot_tail(tail, state.adapter_id[slot])
        return _prefill_extend_impl(state, cache, slot, chunk, clen,
                                    is_last, ad, gp)

    @partial(jax.jit, static_argnums=2, donate_argnums=(0, 1))
    def decode_block(state, cache, k, *tail):
        ads, gp = _slot_tail(tail, state.adapter_id)
        (state, cache), ys = _decode_scan(state, cache, k, ads, gp)
        if n_lp:
            toks, li, lv = ys
            return _constrain_state(state), _constrain(cache), toks, li, lv
        return _constrain_state(state), _constrain(cache), ys

    @partial(jax.jit, donate_argnums=(0, 1))
    def evict(state, cache, slot):
        cache = _constrain(jax.tree.map(
            lambda full: lax.dynamic_update_index_in_dim(
                full, jnp.zeros(full.shape[1:], full.dtype), slot, 0),
            cache))
        zero = jnp.zeros((), jnp.int32)
        state = SlotState(
            last_tok=state.last_tok.at[slot].set(zero),
            active=state.active.at[slot].set(False),
            pos=state.pos.at[slot].set(zero),
            counts=state.counts.at[slot].set(zero),
            temps=state.temps.at[slot].set(jnp.zeros((), jnp.float32)),
            keys=state.keys.at[slot].set(jnp.zeros(2, jnp.uint32)),
            accepted=state.accepted.at[slot].set(zero),
            drafted=state.drafted.at[slot].set(zero),
            adapter_id=state.adapter_id.at[slot].set(
                jnp.asarray(_aid_empty, jnp.int32)),
            gidx=state.gidx.at[slot].set(
                jnp.asarray(_gid_empty, jnp.int32)),
            gstate=state.gstate.at[slot].set(zero))
        return _constrain_state(state), cache

    def _peek_impl(state, cache, ads):
        _, logits = vstep(cache, state.last_tok[:, None, None], ads)
        return logits[:, 0]

    if use_lora:
        @jax.jit
        def peek_logits(state, cache, apool):
            return _peek_impl(state, cache,
                              _gather_ads(apool, state.adapter_id))
    else:
        @jax.jit
        def peek_logits(state, cache):
            return _peek_impl(state, cache, None)

    @jax.jit
    def export_lane(state, cache, slot):
        lane = jax.tree.map(
            lambda full: lax.dynamic_index_in_dim(
                full, slot, 0, keepdims=False), cache)
        lane_state = jax.tree.map(lambda a: a[slot], state)
        return lane, lane_state

    @partial(jax.jit, donate_argnums=(0, 1))
    def import_lane(state, cache, slot, lane, lane_state):
        cache = _constrain(jax.tree.map(
            lambda full, l: lax.dynamic_update_index_in_dim(full, l, slot, 0),
            cache, lane))
        state = jax.tree.map(lambda full, v: full.at[slot].set(v),
                             state, lane_state)
        return _constrain_state(state), cache

    return SlotDecode(
        num_slots=num_slots, prefill_pad=prefill_pad, init_state=init_state,
        init_slots=init_slots, insert_batch=insert_batch,
        prefill_extend=prefill_extend, decode_block=decode_block,
        evict=evict, sample=jax.jit(_slot_sample), peek_logits=peek_logits,
        export_lane=export_lane, import_lane=import_lane,
        **_build_spec(None))


def decode_logits(module, params, tokens: jax.Array) -> jax.Array:
    """Teacher-forced per-position logits through the KV-cache path —
    must match ``module.apply(params, tokens)`` exactly (the consistency
    oracle for the cache implementation; tests assert it)."""
    batch, seq = tokens.shape
    if seq > module.max_len:
        raise ValueError(
            f"sequence {seq} exceeds the model's max_len {module.max_len} "
            "(the KV-cache size)"
        )
    init_cache, step = make_decode_step(module, params)

    @jax.jit
    def run(cache, tokens):
        def body(cache, tok):
            cache, logits = step(cache, tok[:, None])
            return cache, logits

        _, logits = lax.scan(body, cache, tokens.T)
        return jnp.swapaxes(logits, 0, 1)  # [batch, seq, vocab]

    return run(init_cache(batch), tokens)
