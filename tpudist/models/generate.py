"""Autoregressive generation for TransformerLM via a KV cache.

No reference counterpart (the reference trains a toy MLP and never
samples); this completes the LM family with the standard inference path,
TPU-first:

- ONE compiled program: the whole decode loop is a ``lax.scan`` whose body
  is the single-token cached forward — no per-token dispatch, no dynamic
  shapes (the K/V cache is ``[max_len]`` with a mask cursor, see
  ``Block._decode_attention``).
- prompt consumption is teacher-forced inside the same scan (prefill and
  decode share one program; at toy scale a separate batched prefill isn't
  worth a second compilation).
- works for both position encodings: learned tables read the cache's
  position counter; RoPE rotates each token at its absolute offset.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def make_decode_step(module, params):
    """Return ``(init_cache, step)``: ``init_cache(batch)`` builds a fresh
    all-zeros KV cache, ``step(cache, tok[b,1]) -> (cache, logits[b,vocab])``
    is the compiled single-token forward.

    The cache covers ``module.max_len`` positions; exceeding it silently
    attends over garbage — ``generate``/``decode_logits`` guard the budget.
    """
    # The sharded MoE closure (if any) cannot split a single decode token
    # over its batch axis; the dense reference is numerically identical
    # (same contract as create_transformer's init).
    dec = module.clone(decode=True, moe_fn=None)

    def step(cache, tok):
        logits, mut = dec.apply(
            {"params": params["params"], "cache": cache},
            tok, mutable=["cache"],
        )
        return mut["cache"], logits[:, -1].astype(jnp.float32)

    def init_cache(batch: int):
        # eval_shape: the cache STRUCTURE without materializing a second
        # parameter set (flax init would allocate + run a forward).  A
        # fresh cache is all-zeros (K/V empty, cursors at 0).
        shapes = jax.eval_shape(
            dec.init, jax.random.PRNGKey(0), jnp.zeros((batch, 1), jnp.int32)
        )["cache"]
        return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)

    return init_cache, step


def sample_logits(
    logits: jax.Array,
    key: jax.Array,
    *,
    temperature: float = 1.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
) -> jax.Array:
    """Sample token ids from ``logits [batch, vocab]`` (f32).

    ``temperature == 0`` is greedy argmax (``top_k``/``top_p`` ignored).
    ``top_k``: keep only the k highest logits.  ``top_p``: nucleus
    sampling — keep the smallest prefix of the probability-sorted vocab
    whose mass reaches ``top_p`` (the first token crossing the threshold
    is always kept, so the set is never empty).  Both filters compose
    (k-filter first, then nucleus), everything is fixed-shape ``jnp`` —
    the function jits and scans.
    """
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    neg = jnp.finfo(logits.dtype).min
    if top_k is not None and top_k < logits.shape[-1]:
        kth = lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, neg, logits)
    if top_p is not None and top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        cum = jnp.cumsum(jax.nn.softmax(sorted_logits, axis=-1), axis=-1)
        # Position i is kept while the mass BEFORE it is < top_p (shift by
        # one so the first token crossing the threshold stays in).  The
        # cutoff is the SMALLEST kept logit; everything below it is masked.
        keep = jnp.concatenate(
            [jnp.zeros_like(cum[..., :1]), cum[..., :-1]], axis=-1
        ) < top_p
        # Force-keep the top token so top_p <= 0 degenerates to greedy,
        # never to an empty set (which would un-mask everything below).
        keep = keep.at[..., 0].set(True)
        cutoff = jnp.min(jnp.where(keep, sorted_logits, jnp.inf), axis=-1,
                         keepdims=True)
        # Value-space masking: tokens exactly TIED with the cutoff logit
        # survive even when outside the nucleus prefix (same for top_k's
        # kth-value compare above).  Slightly more mass than requested on
        # tied logits — the standard HF/T5X behavior; exactness would need
        # masking in sorted-index space and a scatter back.
        logits = jnp.where(logits < cutoff, neg, logits)
    return jax.random.categorical(key, logits, axis=-1)


def make_generator(
    module,
    params,
    max_new: int,
    *,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
):
    """Build a reusable compiled sampler: ``gen(prompt, rng=None) ->
    [batch, plen + max_new]``.

    The returned callable holds ONE jitted program (prompt teacher-forcing
    + sampling in a single ``lax.scan``), so repeated calls with the same
    prompt shape hit the jit cache — this is the entry for serving/bench
    loops; :func:`generate` is the one-shot convenience wrapper.
    """
    init_cache, step = make_decode_step(module, params)

    def pick(logits, key):
        return sample_logits(logits, key, temperature=temperature,
                             top_k=top_k, top_p=top_p)

    @jax.jit
    def run(prompt, key):
        batch, plen = prompt.shape
        cache = init_cache(batch)

        def body(carry, i):
            cache, tok, key = carry
            cache, logits = step(cache, tok)
            key, sub = jax.random.split(key)
            sampled = pick(logits, sub)
            # teacher-force while the next position is still in the prompt
            forced = lax.dynamic_index_in_dim(
                prompt, jnp.minimum(i + 1, plen - 1), axis=1, keepdims=False
            )
            nxt = jnp.where(i + 1 < plen, forced, sampled)
            return (cache, nxt[:, None], key), nxt

        (_, _, _), out = lax.scan(
            body, (cache, prompt[:, :1], key), jnp.arange(plen + max_new - 1)
        )
        return jnp.concatenate([prompt[:, :1], out.T], axis=1)

    def gen(prompt: jax.Array, rng: Optional[jax.Array] = None) -> jax.Array:
        plen = prompt.shape[1]
        if plen + max_new > module.max_len:
            raise ValueError(
                f"prompt {plen} + max_new {max_new} exceeds the model's "
                f"max_len {module.max_len} (the KV-cache size)"
            )
        if rng is None:
            rng = jax.random.PRNGKey(0)
        return run(prompt, rng)

    return gen


def generate(
    module,
    params,
    prompt: jax.Array,
    max_new: int,
    *,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    rng: Optional[jax.Array] = None,
) -> jax.Array:
    """Sample ``max_new`` tokens after ``prompt [batch, plen]``.

    ``temperature == 0`` is greedy argmax; otherwise categorical sampling
    at that temperature, optionally filtered by ``top_k`` and/or nucleus
    ``top_p`` (:func:`sample_logits`).  Returns the full
    ``[batch, plen + max_new]`` sequence (prompt included).  One-shot
    wrapper over :func:`make_generator` (use that directly to amortize
    compilation across calls).
    """
    return make_generator(
        module, params, max_new, temperature=temperature, top_k=top_k,
        top_p=top_p,
    )(prompt, rng)


class SlotDecode(NamedTuple):
    """The compiled primitives of the continuous-batching serving engine
    (:mod:`tpudist.serve`): ``num_slots`` independent KV-cache lanes, each
    a batch-1 decode cache with its OWN position cursor (the single-batch
    decode step vmapped over a leading slot axis — per-slot cursors, masks,
    and RoPE offsets fall out of the vmap for free).

    Every callable is jitted once with fixed shapes, so requests of any
    prompt/output length join and leave a running batch with ZERO
    recompilation — the SPMD fixed-shape discipline, applied to serving:

    - ``init_slots()`` → all-zeros slot cache (leading ``[num_slots]``
      axis on every leaf, scalar cursors become ``[num_slots]`` vectors);
    - ``prefill(prompts [S, pad], plens [S])`` → ``(caches, last_logits)``:
      teacher-force up to ``S`` prompts at once through the cached forward
      (a masked fixed-length scan: steps at ``i >= plen`` keep the old
      cache, so any ``plen <= prefill_pad`` shares one program); returns
      per-sequence caches (cursor at ``plen``) and the logits after the
      LAST prompt token — the distribution the first generated token is
      drawn from, exactly as :func:`generate` does it;
    - ``insert_from(slot_cache, batch_cache, i, slot)`` → slot cache with
      prefill lane ``i`` scattered into ``slot`` (indices traced: one
      compile serves every (i, slot) pair);
    - ``evict(slot_cache, slot)`` → that lane zeroed (a freed slot must
      not leak a tenant's K/V into the next request's garbage window);
    - ``decode_step(cache, toks, active, keys, temps, counts)`` →
      ``(cache, next_toks)``: ONE compiled step over all slots — inactive
      lanes compute too (fixed shape) but their cache writes are undone by
      the ``active`` select, so they neither advance nor corrupt;
    - ``sample(logits, keys, temps, counts)`` → per-slot token draw:
      greedy argmax where ``temps <= 0``, else categorical at that slot's
      temperature from ``fold_in(key, count)`` — a deterministic
      per-request stream independent of which slot/batch neighbors the
      request decoded beside.
    """

    num_slots: int
    prefill_pad: int
    init_slots: Callable
    prefill: Callable
    insert_from: Callable
    evict: Callable
    decode_step: Callable
    sample: Callable


def _slot_sample(logits: jax.Array, keys: jax.Array, temps: jax.Array,
                 counts: jax.Array) -> jax.Array:
    """Per-slot sampling (see :class:`SlotDecode`): ``logits [S, vocab]``,
    ``keys [S, 2] uint32``, ``temps [S]``, ``counts [S]`` → ``[S] int32``."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def one(key, lg, t, c):
        k = jax.random.fold_in(key, c)
        return jax.random.categorical(k, lg / jnp.maximum(t, 1e-6))

    sampled = jax.vmap(one)(keys, logits, temps, counts).astype(jnp.int32)
    return jnp.where(temps > 0.0, sampled, greedy)


def make_slot_decode(module, params, num_slots: int,
                     prefill_pad: int) -> SlotDecode:
    """Build the slot-decode primitive set over ``module``/``params`` —
    see :class:`SlotDecode` for the contract of each callable."""
    if num_slots < 1:
        raise ValueError(f"num_slots must be >= 1, got {num_slots}")
    if not 1 <= prefill_pad <= module.max_len:
        raise ValueError(
            f"prefill_pad {prefill_pad} must be in [1, {module.max_len}] "
            "(the KV-cache size)")
    init_cache, step = make_decode_step(module, params)
    vocab = module.vocab
    vstep = jax.vmap(step, in_axes=(0, 0))

    def init_slots():
        one = init_cache(1)
        return jax.tree.map(
            lambda a: jnp.zeros((num_slots,) + a.shape, a.dtype), one)

    @jax.jit
    def prefill(prompts, plens):
        def one_seq(prompt, plen):
            cache = init_cache(1)

            def body(carry, i):
                cache, last = carry
                tok = lax.dynamic_index_in_dim(prompt, i, keepdims=False)
                nc, logits = step(cache, tok[None, None])
                live = i < plen
                cache = jax.tree.map(
                    lambda n, o: jnp.where(live, n, o), nc, cache)
                last = jnp.where(i == plen - 1, logits[0], last)
                return (cache, last), None

            (cache, last), _ = lax.scan(
                body, (cache, jnp.zeros((vocab,), jnp.float32)),
                jnp.arange(prefill_pad))
            return cache, last

        return jax.vmap(one_seq)(prompts, plens)

    # The slot cache is donated in every primitive that threads it: the
    # engine always overwrites its cache with the result, and without
    # donation each iteration would copy the whole [num_slots × layers ×
    # max_len] K/V arena into fresh buffers — doubling peak cache memory
    # and paying a full-arena memcpy per decode step.
    @partial(jax.jit, donate_argnums=0)
    def insert_from(slot_cache, batch_cache, i, slot):
        return jax.tree.map(
            lambda full, b: lax.dynamic_update_index_in_dim(
                full, lax.dynamic_index_in_dim(b, i, 0, keepdims=False),
                slot, 0),
            slot_cache, batch_cache)

    @partial(jax.jit, donate_argnums=0)
    def evict(slot_cache, slot):
        return jax.tree.map(
            lambda full: lax.dynamic_update_index_in_dim(
                full, jnp.zeros(full.shape[1:], full.dtype), slot, 0),
            slot_cache)

    @partial(jax.jit, donate_argnums=0)
    def decode_step(slot_cache, toks, active, keys, temps, counts):
        new_cache, logits = vstep(slot_cache, toks[:, None, None])

        def sel(n, o):
            m = active.reshape((-1,) + (1,) * (n.ndim - 1))
            return jnp.where(m, n, o)

        cache = jax.tree.map(sel, new_cache, slot_cache)
        return cache, _slot_sample(logits[:, 0], keys, temps, counts)

    return SlotDecode(
        num_slots=num_slots, prefill_pad=prefill_pad, init_slots=init_slots,
        prefill=prefill, insert_from=insert_from, evict=evict,
        decode_step=decode_step, sample=jax.jit(_slot_sample))


def decode_logits(module, params, tokens: jax.Array) -> jax.Array:
    """Teacher-forced per-position logits through the KV-cache path —
    must match ``module.apply(params, tokens)`` exactly (the consistency
    oracle for the cache implementation; tests assert it)."""
    batch, seq = tokens.shape
    if seq > module.max_len:
        raise ValueError(
            f"sequence {seq} exceeds the model's max_len {module.max_len} "
            "(the KV-cache size)"
        )
    init_cache, step = make_decode_step(module, params)

    @jax.jit
    def run(cache, tokens):
        def body(cache, tok):
            cache, logits = step(cache, tok[:, None])
            return cache, logits

        _, logits = lax.scan(body, cache, tokens.T)
        return jnp.swapaxes(logits, 0, 1)  # [batch, seq, vocab]

    return run(init_cache(batch), tokens)
