"""Autoregressive generation for TransformerLM via a KV cache.

No reference counterpart (the reference trains a toy MLP and never
samples); this completes the LM family with the standard inference path,
TPU-first:

- ONE compiled program: the whole decode loop is a ``lax.scan`` whose body
  is the single-token cached forward — no per-token dispatch, no dynamic
  shapes (the K/V cache is ``[max_len]`` with a mask cursor, see
  ``Block._decode_attention``).
- prompt consumption is teacher-forced inside the same scan (prefill and
  decode share one program; at toy scale a separate batched prefill isn't
  worth a second compilation).
- works for both position encodings: learned tables read the cache's
  position counter; RoPE rotates each token at its absolute offset.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from tpudist.models.paged import PagedKV, PagedKVConfig, _Paged, strip_kv


class CacheFullError(RuntimeError):
    """A decode step was asked to write past ``module.max_len`` — the KV
    cache is full.  Raised by the eager :func:`make_decode_step` path
    (inside a traced program the cursor is a tracer and the caller owns
    the budget: ``generate``/``decode_logits`` pre-validate, the serving
    engine finishes the slot with reason ``"cache_full"``)."""


def _cache_cursor(cache):
    """The decode cache's write cursor (any per-layer ``idx`` leaf), or
    ``None`` when the pytree carries no recognizable cursor."""
    if not isinstance(cache, dict):
        return None
    for val in cache.values():
        if isinstance(val, dict) and "idx" in val:
            return val["idx"]
    return None


def make_decode_step(module, params):
    """Return ``(init_cache, step)``: ``init_cache(batch)`` builds a fresh
    all-zeros KV cache, ``step(cache, tok[b,1]) -> (cache, logits[b,vocab])``
    is the compiled single-token forward.

    The cache covers ``module.max_len`` positions.  An EAGER call that
    would write past the end raises :class:`CacheFullError` instead of
    silently clamping the write onto the last position and attending
    over garbage; inside a traced program the cursor is a tracer, so
    the caller owns the budget (``generate``/``decode_logits`` validate
    up front, the serving engine finishes overflowing slots with reason
    ``"cache_full"``).
    """
    # The sharded MoE closure (if any) cannot split a single decode token
    # over its batch axis; the dense reference is numerically identical
    # (same contract as create_transformer's init).
    dec = module.clone(decode=True, moe_fn=None)

    def step(cache, tok):
        cur = _cache_cursor(cache)
        if cur is not None and not isinstance(cur, jax.core.Tracer):
            if int(jnp.max(cur)) + tok.shape[-1] > module.max_len:
                raise CacheFullError(
                    f"KV cache full: cursor {int(jnp.max(cur))} + "
                    f"{tok.shape[-1]} token(s) exceeds max_len "
                    f"{module.max_len}")
        logits, mut = dec.apply(
            {"params": params["params"], "cache": cache},
            tok, mutable=["cache"],
        )
        return mut["cache"], logits[:, -1].astype(jnp.float32)

    def init_cache(batch: int):
        # eval_shape: the cache STRUCTURE without materializing a second
        # parameter set (flax init would allocate + run a forward).  A
        # fresh cache is all-zeros (K/V empty, cursors at 0).
        shapes = jax.eval_shape(
            dec.init, jax.random.PRNGKey(0), jnp.zeros((batch, 1), jnp.int32)
        )["cache"]
        return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)

    return init_cache, step


def sample_logits(
    logits: jax.Array,
    key: jax.Array,
    *,
    temperature: float = 1.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
) -> jax.Array:
    """Sample token ids from ``logits [batch, vocab]`` (f32).

    ``temperature == 0`` is greedy argmax (``top_k``/``top_p`` ignored).
    ``top_k``: keep only the k highest logits.  ``top_p``: nucleus
    sampling — keep the smallest prefix of the probability-sorted vocab
    whose mass reaches ``top_p`` (the first token crossing the threshold
    is always kept, so the set is never empty).  Both filters compose
    (k-filter first, then nucleus), everything is fixed-shape ``jnp`` —
    the function jits and scans.
    """
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    neg = jnp.finfo(logits.dtype).min
    if top_k is not None and top_k < logits.shape[-1]:
        kth = lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, neg, logits)
    if top_p is not None and top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        cum = jnp.cumsum(jax.nn.softmax(sorted_logits, axis=-1), axis=-1)
        # Position i is kept while the mass BEFORE it is < top_p (shift by
        # one so the first token crossing the threshold stays in).  The
        # cutoff is the SMALLEST kept logit; everything below it is masked.
        keep = jnp.concatenate(
            [jnp.zeros_like(cum[..., :1]), cum[..., :-1]], axis=-1
        ) < top_p
        # Force-keep the top token so top_p <= 0 degenerates to greedy,
        # never to an empty set (which would un-mask everything below).
        keep = keep.at[..., 0].set(True)
        cutoff = jnp.min(jnp.where(keep, sorted_logits, jnp.inf), axis=-1,
                         keepdims=True)
        # Value-space masking: tokens exactly TIED with the cutoff logit
        # survive even when outside the nucleus prefix (same for top_k's
        # kth-value compare above).  Slightly more mass than requested on
        # tied logits — the standard HF/T5X behavior; exactness would need
        # masking in sorted-index space and a scatter back.
        logits = jnp.where(logits < cutoff, neg, logits)
    return jax.random.categorical(key, logits, axis=-1)


def make_generator(
    module,
    params,
    max_new: int,
    *,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
):
    """Build a reusable compiled sampler: ``gen(prompt, rng=None) ->
    [batch, plen + max_new]``.

    The returned callable holds ONE jitted program (prompt teacher-forcing
    + sampling in a single ``lax.scan``), so repeated calls with the same
    prompt shape hit the jit cache — this is the entry for serving/bench
    loops; :func:`generate` is the one-shot convenience wrapper.
    """
    init_cache, step = make_decode_step(module, params)

    def pick(logits, key):
        return sample_logits(logits, key, temperature=temperature,
                             top_k=top_k, top_p=top_p)

    @jax.jit
    def run(prompt, key):
        batch, plen = prompt.shape
        cache = init_cache(batch)

        def body(carry, i):
            cache, tok, key = carry
            cache, logits = step(cache, tok)
            key, sub = jax.random.split(key)
            sampled = pick(logits, sub)
            # teacher-force while the next position is still in the prompt
            forced = lax.dynamic_index_in_dim(
                prompt, jnp.minimum(i + 1, plen - 1), axis=1, keepdims=False
            )
            nxt = jnp.where(i + 1 < plen, forced, sampled)
            return (cache, nxt[:, None], key), nxt

        (_, _, _), out = lax.scan(
            body, (cache, prompt[:, :1], key), jnp.arange(plen + max_new - 1)
        )
        return jnp.concatenate([prompt[:, :1], out.T], axis=1)

    def gen(prompt: jax.Array, rng: Optional[jax.Array] = None) -> jax.Array:
        plen = prompt.shape[1]
        if plen + max_new > module.max_len:
            raise ValueError(
                f"prompt {plen} + max_new {max_new} exceeds the model's "
                f"max_len {module.max_len} (the KV-cache size)"
            )
        if rng is None:
            rng = jax.random.PRNGKey(0)
        return run(prompt, rng)

    return gen


def generate(
    module,
    params,
    prompt: jax.Array,
    max_new: int,
    *,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    rng: Optional[jax.Array] = None,
) -> jax.Array:
    """Sample ``max_new`` tokens after ``prompt [batch, plen]``.

    ``temperature == 0`` is greedy argmax; otherwise categorical sampling
    at that temperature, optionally filtered by ``top_k`` and/or nucleus
    ``top_p`` (:func:`sample_logits`).  Returns the full
    ``[batch, plen + max_new]`` sequence (prompt included).  One-shot
    wrapper over :func:`make_generator` (use that directly to amortize
    compilation across calls).
    """
    return make_generator(
        module, params, max_new, temperature=temperature, top_k=top_k,
        top_p=top_p,
    )(prompt, rng)


class SlotState(NamedTuple):
    """Per-slot decode state, resident ON DEVICE for the life of the
    engine (:mod:`tpudist.serve`).  Before this existed the engine
    re-uploaded five host arrays per decode step; now the host keeps
    shadow cursors for admission/budget decisions only, and the device
    round-trip per decode *block* is one token-block fetch.

    All leaves carry a leading ``[num_slots]`` axis:

    - ``last_tok [S] int32`` — the token the next decode step consumes
      (fed back IN-GRAPH inside ``decode_block``);
    - ``active [S] bool`` — lane is decoding (prefill-in-progress lanes
      are occupied on the host but inactive here);
    - ``pos [S] int32`` — filled cache positions (mirrors the cache's own
      cursor; kept for introspection/debug dumps);
    - ``counts [S] int32`` — tokens emitted so far, which is also the
      per-request sampling-stream index (``fold_in(key, count)``);
    - ``temps [S] f32`` / ``keys [S, 2] uint32`` — per-request sampling
      config (keys are derived in-graph from integer seeds at insert).
    """

    last_tok: jax.Array
    active: jax.Array
    pos: jax.Array
    counts: jax.Array
    temps: jax.Array
    keys: jax.Array


class SlotDecode(NamedTuple):
    """The compiled primitives of the continuous-batching serving engine
    (:mod:`tpudist.serve`): ``num_slots`` independent KV-cache lanes, each
    a batch-1 decode cache with its OWN position cursor (the single-batch
    decode step vmapped over a leading slot axis — per-slot cursors, masks,
    and RoPE offsets fall out of the vmap for free), plus a persistent
    on-device :class:`SlotState` threaded (and donated) through every
    primitive.

    Every callable is jitted with fixed shapes, so requests of any
    prompt/output length join and leave a running batch with ZERO
    recompilation — the SPMD fixed-shape discipline, applied to serving.
    ``decode_block`` is the one exception by design: ``K`` is static, so
    each distinct block size is one compile (the engine buckets K to
    powers of two, bounding the cache at ``log2(max_block)+1`` entries):

    - ``init_state()`` / ``init_slots()`` → all-zeros state / slot cache;
    - ``insert_batch(state, cache, prompts [S, pad], clens [S], dsts [S],
      seeds [S], temps [S], last [S])`` → ``(state, cache, firsts [S])``:
      ONE dispatch that teacher-forces up to ``S`` prompt chunks through
      the cached forward (a masked fixed-length scan: steps at
      ``i >= clen`` keep the old cache, so any ``clen <= prefill_pad``
      shares one program), derives each lane's threefry key from its
      integer seed IN-GRAPH, scatters lane ``j`` into slot ``dsts[j]``
      (``dsts[j] == num_slots`` marks an unused lane — the out-of-bounds
      scatter drops it), and where ``last[j]`` samples the first generated
      token from the post-chunk logits and arms the slot for decode.
      Lanes with ``last[j] == False`` hold a partial prompt: their slot
      stays inactive until ``prefill_extend`` feeds the remaining chunks;
    - ``prefill_extend(state, cache, slot, chunk [pad], clen, is_last)``
      → ``(state, cache, first)``: append one prompt chunk at slot's
      running cache offset (chunked prefill — prompts longer than the
      pad are admitted and teacher-forced ``pad`` tokens per call, so a
      long prompt stalls in-flight decode by at most one chunk per engine
      iteration).  On ``is_last`` the first generated token is sampled
      from the final chunk's last logits and the slot activates;
    - ``decode_block(state, cache, K)`` → ``(state, cache, toks [K, S])``:
      ``K`` decode steps fused into one dispatch via ``lax.scan`` with
      in-graph token feedback — K×num_slots tokens for one dispatch and
      one D2H fetch.  Inactive lanes compute too (fixed shape) but their
      cache writes are undone by the ``active`` select and their
      ``last_tok``/``counts`` hold still, so they neither advance nor
      corrupt;
    - ``evict(state, cache, slot)`` → that lane zeroed in both cache and
      state (a freed slot must not leak a tenant's K/V into the next
      request's garbage window);
    - ``export_lane(state, cache, slot)`` → ``(lane, lane_state)``: one
      slot's KV lane plus its SlotState row — the export half of the
      prefill→decode KV handoff (:mod:`tpudist.serve.disagg`).  Dense:
      the lane is the slot's flax cache slice; paged: a dense
      ``(k, v, meta)`` view gathered through the slot's block table
      (int8 pools dequantize; the re-import re-quantizes bit-exactly);
    - ``import_lane(state, cache, slot, [row,] lane, lane_state)`` →
      install an exported lane into ``slot`` (paged takes the dest
      allocator's fresh table ``row`` as data).  Greedy/sampled
      continuation after import is byte-identical to decoding in the
      source engine: the state row carries ``last_tok``/``counts``/
      ``keys``, and the sampling stream is ``fold_in(key, count)`` —
      independent of which engine or slot hosts the request;
    - ``sample(logits, keys, temps, counts)`` → per-slot token draw:
      greedy argmax where ``temps <= 0``, else categorical at that slot's
      temperature from ``fold_in(key, count)`` — a deterministic
      per-request stream independent of which slot/batch neighbors the
      request decoded beside, and independent of the block size K.

    **Paged mode** (``make_slot_decode(..., paged=PagedKVConfig(...))``,
    see :mod:`tpudist.models.paged`): the cache argument threaded
    through every primitive becomes a :class:`~tpudist.models.paged.
    PagedKV` (block pool + per-slot block table) and the programs do
    the gather/scatter indirection in-graph — same four fixed-shape
    programs, still zero recompilation under churn.  Three signatures
    widen to carry the host allocator's decisions as DATA (never as
    shapes): ``insert_batch`` prepends ``tables [S, M]`` (each lane's
    block-table row — shared prefix blocks first, freshly allocated
    ones after) and ``poss [S]`` (each lane's starting cursor = its
    reused prefix length, block-aligned); ``evict`` appends ``free_ids
    [M]`` (physical blocks whose refcount hit zero, sentinel-padded —
    shared blocks outlive any one tenant).  ``paged`` holds the
    geometry/accounting helper; ``peek_logits(state, cache) ->
    [S, vocab]`` reads every lane's next-token logits WITHOUT advancing
    state or cache (the int8-accuracy oracle; compiled separately, not
    one of the four hot programs).
    """

    num_slots: int
    prefill_pad: int
    init_state: Callable
    init_slots: Callable
    insert_batch: Callable
    prefill_extend: Callable
    decode_block: Callable
    evict: Callable
    sample: Callable
    peek_logits: Optional[Callable] = None
    paged: Optional["_Paged"] = None
    export_lane: Optional[Callable] = None
    import_lane: Optional[Callable] = None


def _slot_sample(logits: jax.Array, keys: jax.Array, temps: jax.Array,
                 counts: jax.Array) -> jax.Array:
    """Per-slot sampling (see :class:`SlotDecode`): ``logits [S, vocab]``,
    ``keys [S, 2] uint32``, ``temps [S]``, ``counts [S]`` → ``[S] int32``."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def one(key, lg, t, c):
        k = jax.random.fold_in(key, c)
        return jax.random.categorical(k, lg / jnp.maximum(t, 1e-6))

    sampled = jax.vmap(one)(keys, logits, temps, counts).astype(jnp.int32)
    return jnp.where(temps > 0.0, sampled, greedy)


def make_slot_decode(module, params, num_slots: int, prefill_pad: int,
                     paged: Optional[PagedKVConfig] = None,
                     cache_constraint: Optional[Callable] = None,
                     state_constraint: Optional[Callable] = None
                     ) -> SlotDecode:
    """Build the slot-decode primitive set over ``module``/``params`` —
    see :class:`SlotDecode` for the contract of each callable.  With
    ``paged`` set, the cache is a block pool + block tables instead of
    dense per-slot arenas (:mod:`tpudist.models.paged`); the unquantized
    paged path is byte-identical to the dense one (tests pin it).

    ``cache_constraint`` / ``state_constraint`` (SPMD serving,
    :mod:`tpudist.serve.spmd`): ``tree -> tree`` callables applying
    ``with_sharding_constraint`` to the cache / SlotState pytrees.  The
    hot programs re-assert them on their outputs, making the mesh
    layout STRUCTURAL — the engine's shardings cannot silently drift
    (decay to replicated, or pick up a partitioner-invented split that
    would recompile the next program) across donated iterations."""
    if num_slots < 1:
        raise ValueError(f"num_slots must be >= 1, got {num_slots}")
    if not 1 <= prefill_pad <= module.max_len:
        raise ValueError(
            f"prefill_pad {prefill_pad} must be in [1, {module.max_len}] "
            "(the KV-cache size)")
    init_cache, step = make_decode_step(module, params)
    vocab = module.vocab
    vstep = jax.vmap(step, in_axes=(0, 0))

    def _constrain(cache):
        return cache if cache_constraint is None else cache_constraint(cache)

    def _constrain_state(state):
        return state if state_constraint is None else state_constraint(state)

    def init_state():
        s = num_slots
        return SlotState(
            last_tok=jnp.zeros(s, jnp.int32),
            active=jnp.zeros(s, bool),
            pos=jnp.zeros(s, jnp.int32),
            counts=jnp.zeros(s, jnp.int32),
            temps=jnp.zeros(s, jnp.float32),
            keys=jnp.zeros((s, 2), jnp.uint32))

    def init_slots():
        one = init_cache(1)
        return jax.tree.map(
            lambda a: jnp.zeros((num_slots,) + a.shape, a.dtype), one)

    def _force_chunk(cache, chunk, clen):
        """Teacher-force ``chunk[:clen]`` through a batch-1 cache (masked
        fixed-length scan: steps at ``i >= clen`` keep the old cache, so
        every ``clen <= prefill_pad`` shares one program).  Returns the
        advanced cache and the logits after the LAST live token."""

        def body(carry, i):
            cache, last = carry
            tok = lax.dynamic_index_in_dim(chunk, i, keepdims=False)
            nc, logits = step(cache, tok[None, None])
            live = i < clen
            cache = jax.tree.map(
                lambda n, o: jnp.where(live, n, o), nc, cache)
            last = jnp.where(i == clen - 1, logits[0], last)
            return (cache, last), None

        return lax.scan(body, (cache, jnp.zeros((vocab,), jnp.float32)),
                        jnp.arange(prefill_pad))[0]

    def _decode_scan(state, cache, k):
        """The K-step fused decode body shared by the dense and paged
        ``decode_block`` programs: in-graph token feedback, inactive
        lanes' cache writes undone by the ``active`` select."""

        def body(carry, _):
            state, cache = carry
            nc, logits = vstep(cache, state.last_tok[:, None, None])

            def sel(n, o):
                m = state.active.reshape((-1,) + (1,) * (n.ndim - 1))
                return jnp.where(m, n, o)

            cache = jax.tree.map(sel, nc, cache)
            toks = _slot_sample(logits[:, 0], state.keys, state.temps,
                                state.counts)
            toks = jnp.where(state.active, toks,
                             state.last_tok).astype(jnp.int32)
            inc = state.active.astype(jnp.int32)
            state = state._replace(last_tok=toks, counts=state.counts + inc,
                                   pos=state.pos + inc)
            return (state, cache), toks

        return lax.scan(body, (state, cache), None, length=k)

    if paged is not None:
        pg = _Paged(init_cache(1), num_slots, paged)
        meta_template = strip_kv(pg.template)

        @partial(jax.jit, donate_argnums=(0, 1))
        def insert_batch_paged(state, pkv, tables, poss, prompts, clens,
                               dsts, seeds, temps, last):
            # Each lane teacher-forces its first NON-SHARED chunk on top
            # of a dense view gathered through its (host-built) table
            # row: a reused prefix's K/V is already in the pool, so the
            # lane's cursor starts at poss[j] — prefilled once, mapped
            # into every slot that shares it.
            def lane(row, pos0, p, n):
                meta1 = jax.tree.map(
                    lambda t: jnp.asarray(pos0, t.dtype), meta_template)
                return _force_chunk(pg.lane_cache(pkv, row, meta1), p, n)

            lanes, last_logits = jax.vmap(lane)(tables, poss, prompts, clens)
            keys = jax.vmap(jax.random.PRNGKey)(seeds).astype(jnp.uint32)
            firsts = _slot_sample(last_logits, keys, temps,
                                  jnp.zeros(num_slots, jnp.int32))
            pkv = _constrain(pg.commit_lanes(pkv, lanes, tables, dsts, poss,
                                             prefill_pad))
            state = SlotState(
                last_tok=state.last_tok.at[dsts].set(
                    jnp.where(last, firsts, 0)),
                active=state.active.at[dsts].set(last),
                pos=state.pos.at[dsts].set(poss + clens),
                counts=state.counts.at[dsts].set(last.astype(jnp.int32)),
                temps=state.temps.at[dsts].set(temps),
                keys=state.keys.at[dsts].set(keys))
            return _constrain_state(state), pkv, firsts

        @partial(jax.jit, donate_argnums=(0, 1))
        def prefill_extend_paged(state, pkv, slot, chunk, clen, is_last):
            row = pkv.table[slot]
            meta1 = jax.tree.map(lambda full: full[slot], pkv.meta)
            pos0 = _cache_cursor(meta1)
            cache, last_logits = _force_chunk(
                pg.lane_cache(pkv, row, meta1), chunk, clen)
            pkv = _constrain(pg.commit_lanes(
                pkv, jax.tree.map(lambda a: a[None], cache),
                row[None], jnp.reshape(slot, (1,)), jnp.reshape(pos0, (1,)),
                prefill_pad))
            first = _slot_sample(
                last_logits[None], state.keys[slot][None],
                state.temps[slot][None], jnp.zeros(1, jnp.int32))[0]
            state = state._replace(
                pos=state.pos.at[slot].add(clen),
                active=state.active.at[slot].set(is_last),
                last_tok=state.last_tok.at[slot].set(
                    jnp.where(is_last, first, 0)),
                counts=state.counts.at[slot].set(is_last.astype(jnp.int32)))
            return _constrain_state(state), pkv, first

        @partial(jax.jit, static_argnums=2, donate_argnums=(0, 1))
        def decode_block_paged(state, pkv, k):
            pos0 = _cache_cursor(pkv.meta)
            mask = state.active
            (state, cache), toks = _decode_scan(
                state, pg.slot_cache(pkv), k)
            pkv = _constrain(pg.commit_slots(pkv, cache, pos0, k, mask))
            return _constrain_state(state), pkv, toks

        @partial(jax.jit, donate_argnums=(0, 1))
        def evict_paged(state, pkv, slot, free_ids):
            pkv = _constrain(pg.release(pkv, slot, free_ids))
            zero = jnp.zeros((), jnp.int32)
            state = SlotState(
                last_tok=state.last_tok.at[slot].set(zero),
                active=state.active.at[slot].set(False),
                pos=state.pos.at[slot].set(zero),
                counts=state.counts.at[slot].set(zero),
                temps=state.temps.at[slot].set(jnp.zeros((), jnp.float32)),
                keys=state.keys.at[slot].set(jnp.zeros(2, jnp.uint32)))
            return _constrain_state(state), pkv

        @jax.jit
        def peek_logits_paged(state, pkv):
            _, logits = vstep(pg.slot_cache(pkv),
                              state.last_tok[:, None, None])
            return logits[:, 0]

        @jax.jit
        def export_lane_paged(state, pkv, slot):
            ks, vs, meta1 = pg.extract_lane(pkv, slot)
            lane_state = jax.tree.map(lambda a: a[slot], state)
            return (ks, vs, meta1), lane_state

        @partial(jax.jit, donate_argnums=(0, 1))
        def import_lane_paged(state, pkv, slot, row, lane, lane_state):
            ks, vs, meta1 = lane
            pkv = _constrain(pg.adopt_lane(pkv, slot, row, ks, vs, meta1))
            state = jax.tree.map(lambda full, v: full.at[slot].set(v),
                                 state, lane_state)
            return _constrain_state(state), pkv

        return SlotDecode(
            num_slots=num_slots, prefill_pad=prefill_pad,
            init_state=init_state, init_slots=pg.init,
            insert_batch=insert_batch_paged,
            prefill_extend=prefill_extend_paged,
            decode_block=decode_block_paged, evict=evict_paged,
            sample=jax.jit(_slot_sample), peek_logits=peek_logits_paged,
            paged=pg, export_lane=export_lane_paged,
            import_lane=import_lane_paged)

    # The slot state AND cache are donated in every primitive that threads
    # them: the engine always overwrites both with the result, and without
    # donation each iteration would copy the whole [num_slots × layers ×
    # max_len] K/V arena into fresh buffers — doubling peak cache memory
    # and paying a full-arena memcpy per decode block.
    @partial(jax.jit, donate_argnums=(0, 1))
    def insert_batch(state, cache, prompts, clens, dsts, seeds, temps, last):
        lanes, last_logits = jax.vmap(
            lambda p, n: _force_chunk(init_cache(1), p, n))(prompts, clens)
        keys = jax.vmap(jax.random.PRNGKey)(seeds).astype(jnp.uint32)
        firsts = _slot_sample(last_logits, keys, temps,
                              jnp.zeros(num_slots, jnp.int32))
        # Scatter lane j into slot dsts[j].  Unused lanes carry the
        # sentinel dst num_slots: out-of-bounds scatter indices are
        # DROPPED (jax's default scatter mode), so one fixed-shape
        # program serves every admission-batch size.
        cache = _constrain(jax.tree.map(
            lambda full, b: full.at[dsts].set(b), cache, lanes))
        state = SlotState(
            last_tok=state.last_tok.at[dsts].set(jnp.where(last, firsts, 0)),
            active=state.active.at[dsts].set(last),
            pos=state.pos.at[dsts].set(clens),
            counts=state.counts.at[dsts].set(last.astype(jnp.int32)),
            temps=state.temps.at[dsts].set(temps),
            keys=state.keys.at[dsts].set(keys))
        return _constrain_state(state), cache, firsts

    @partial(jax.jit, donate_argnums=(0, 1))
    def prefill_extend(state, cache, slot, chunk, clen, is_last):
        lane = jax.tree.map(
            lambda full: lax.dynamic_index_in_dim(
                full, slot, 0, keepdims=False), cache)
        lane, last_logits = _force_chunk(lane, chunk, clen)
        cache = _constrain(jax.tree.map(
            lambda full, l: lax.dynamic_update_index_in_dim(full, l, slot, 0),
            cache, lane))
        first = _slot_sample(
            last_logits[None], state.keys[slot][None],
            state.temps[slot][None], jnp.zeros(1, jnp.int32))[0]
        state = state._replace(
            pos=state.pos.at[slot].add(clen),
            active=state.active.at[slot].set(is_last),
            last_tok=state.last_tok.at[slot].set(
                jnp.where(is_last, first, 0)),
            counts=state.counts.at[slot].set(is_last.astype(jnp.int32)))
        return _constrain_state(state), cache, first

    @partial(jax.jit, static_argnums=2, donate_argnums=(0, 1))
    def decode_block(state, cache, k):
        (state, cache), toks = _decode_scan(state, cache, k)
        return _constrain_state(state), _constrain(cache), toks

    @partial(jax.jit, donate_argnums=(0, 1))
    def evict(state, cache, slot):
        cache = _constrain(jax.tree.map(
            lambda full: lax.dynamic_update_index_in_dim(
                full, jnp.zeros(full.shape[1:], full.dtype), slot, 0),
            cache))
        zero = jnp.zeros((), jnp.int32)
        state = SlotState(
            last_tok=state.last_tok.at[slot].set(zero),
            active=state.active.at[slot].set(False),
            pos=state.pos.at[slot].set(zero),
            counts=state.counts.at[slot].set(zero),
            temps=state.temps.at[slot].set(jnp.zeros((), jnp.float32)),
            keys=state.keys.at[slot].set(jnp.zeros(2, jnp.uint32)))
        return _constrain_state(state), cache

    @jax.jit
    def peek_logits(state, cache):
        _, logits = vstep(cache, state.last_tok[:, None, None])
        return logits[:, 0]

    @jax.jit
    def export_lane(state, cache, slot):
        lane = jax.tree.map(
            lambda full: lax.dynamic_index_in_dim(
                full, slot, 0, keepdims=False), cache)
        lane_state = jax.tree.map(lambda a: a[slot], state)
        return lane, lane_state

    @partial(jax.jit, donate_argnums=(0, 1))
    def import_lane(state, cache, slot, lane, lane_state):
        cache = _constrain(jax.tree.map(
            lambda full, l: lax.dynamic_update_index_in_dim(full, l, slot, 0),
            cache, lane))
        state = jax.tree.map(lambda full, v: full.at[slot].set(v),
                             state, lane_state)
        return _constrain_state(state), cache

    return SlotDecode(
        num_slots=num_slots, prefill_pad=prefill_pad, init_state=init_state,
        init_slots=init_slots, insert_batch=insert_batch,
        prefill_extend=prefill_extend, decode_block=decode_block,
        evict=evict, sample=jax.jit(_slot_sample), peek_logits=peek_logits,
        export_lane=export_lane, import_lane=import_lane)


def decode_logits(module, params, tokens: jax.Array) -> jax.Array:
    """Teacher-forced per-position logits through the KV-cache path —
    must match ``module.apply(params, tokens)`` exactly (the consistency
    oracle for the cache implementation; tests assert it)."""
    batch, seq = tokens.shape
    if seq > module.max_len:
        raise ValueError(
            f"sequence {seq} exceeds the model's max_len {module.max_len} "
            "(the KV-cache size)"
        )
    init_cache, step = make_decode_step(module, params)

    @jax.jit
    def run(cache, tokens):
        def body(cache, tok):
            cache, logits = step(cache, tok[:, None])
            return cache, logits

        _, logits = lax.scan(body, cache, tokens.T)
        return jnp.swapaxes(logits, 0, 1)  # [batch, seq, vocab]

    return run(init_cache(batch), tokens)
