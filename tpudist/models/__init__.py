from tpudist.models.toy_mlp import ToyMLP, create_toy_model  # noqa: F401
