from tpudist.models.toy_mlp import ToyMLP, create_toy_model  # noqa: F401
from tpudist.models.transformer import (  # noqa: F401
    TransformerLM,
    create_transformer,
    lm_loss,
    lm_loss_with_targets,
)
from tpudist.models.generate import (  # noqa: F401
    SlotDecode,
    SlotState,
    decode_logits,
    generate,
    make_decode_step,
    make_decode_window,
    make_generator,
    make_slot_decode,
    sample_logits,
    tied_draft,
)
