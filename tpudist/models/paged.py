"""Paged KV-cache storage for the slot-decode engine (`tpudist.serve`).

The dense slot cache gives every lane its own ``[max_len]`` K/V arena, so
slot count is hard-coupled to the longest admissible sequence: resident
KV bytes = ``num_slots × max_len`` no matter how short the actual
requests run.  This module decouples them — the vLLM PagedAttention
idea, restated in the repo's fixed-shape compiled-program discipline:

- **storage** is a pool of ``num_blocks`` fixed-size blocks shared by
  every slot and every layer (`PagedKV.pool_k/pool_v`,
  ``[layers, num_blocks, n_kv, block_size, d_head]`` — one *logical*
  block id addresses the same physical block in all layers, so the
  host-side allocator is layer-oblivious);
- **indirection** is a per-slot block table (``[num_slots,
  max_len/block_size]`` int32, sentinel ``num_blocks`` = unmapped) read
  and written only INSIDE the compiled programs: gather on read,
  scatter on append, shapes never depend on a request — churn still
  causes zero recompilation;
- **sharing**: a block mapped into several tables is prefilled once and
  read by all of them (shared-prefix reuse; refcounts live on the host,
  :mod:`tpudist.serve.paged_alloc`).  Programs only ever scatter blocks
  at or past the dispatch's first *written* position, so a shared
  (read-only) prefix block is never rewritten — copy-on-write
  degenerates to "writes always land in private blocks" because only
  full blocks are ever shared;
- **quantization** (optional): the pool stores int8 with one f32 scale
  per (layer, block, kv-head) (`scale_k/scale_v`); gather dequantizes
  into the compute dtype IN-GRAPH, commit re-quantizes the touched
  blocks.  ~4x fewer resident KV bytes than f32, ~2x fewer than bf16;
  the unquantized path stays byte-identical to the dense engine.

Numerical contract (what makes gather→dense-compute→scatter safe):
positions beyond a slot's cursor are masked by the decode attention's
``live = arange(max_len) <= pos`` mask with a hard ``-1e30`` — the
*score* at a masked position is the same constant whether the gathered
value there was a zero (dense path) or another tenant's clamped-gather
garbage (paged path), so the two paths produce bit-equal attention.
Tests drive the full heterogeneous-churn oracle sweep over paged
engines to pin this.

**Draft pool (speculative decoding).**  A spec-enabled paged engine
(:func:`tpudist.models.generate.make_slot_decode` ``spec=``) gives the
draft model its own smaller pool: a second :class:`PagedKV` over the
DRAFT's cache template at the SAME ``(num_blocks, block_size)``
geometry.  Sharing the geometry means sharing block IDS — one host
allocator covers both pools, ``insert``'s table rows and ``evict``'s
free-lists apply to both, and a reused prefix block's draft KV is
already in place (it was written under the same id when the prefix
first prefilled).  "Smaller" is the per-block byte count (draft layers
× heads × dh), which is what HBM residency is measured in.

Two decode-attention executions share this storage (the engine's
``attn_kernel`` knob, ``TPUDIST_SERVE_ATTN_KERNEL``):

- **gather** (default): the compiled programs materialize a transient
  dense ``[slots, max_len]`` view per dispatch (:meth:`_Paged.
  slot_cache` — XLA scratch, not persistent state).  The *resident* KV
  footprint is the pool either way, but the transient view's bytes
  scale with pool geometry, not live KV;
- **paged** (the Pallas kernel, :mod:`tpudist.ops.paged_attention`):
  the block table is walked INSIDE the kernel, only live blocks are
  fetched, and the dispatch's own uncommitted tokens live in a small
  per-layer WINDOW buffer (:meth:`_Paged.window_view`) committed back
  through :meth:`_Paged.commit_window` touching only the blocks it
  spans — decode bytes/token ∝ live KV at any occupancy.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax


class PagedKVConfig(NamedTuple):
    """Static geometry of a paged pool.

    - ``num_blocks``: physical blocks in the pool (the capacity knob —
      resident KV bytes = ``num_blocks × block_bytes``);
    - ``block_size``: tokens per block; must divide the module's
      ``max_len`` (the per-slot table has ``max_len // block_size``
      entries);
    - ``quantized``: store int8 + per-block scales instead of the
      compute dtype.
    """

    num_blocks: int
    block_size: int
    quantized: bool = False


class PagedKV(NamedTuple):
    """The device-resident paged cache (replaces the dense slot cache as
    the second argument threaded/donated through the four programs).

    - ``pool_k``/``pool_v``: ``[L, num_blocks, n_kv, block_size, dh]``
      in the storage dtype (int8 when quantized);
    - ``scale_k``/``scale_v``: ``[L, num_blocks, n_kv]`` f32 dequant
      scales (all-ones when not quantized — kept so the pytree
      structure is mode-independent);
    - ``table``: ``[num_slots, max_len // block_size]`` int32 physical
      block ids; ``num_blocks`` is the unmapped sentinel (gathers clamp
      into masked territory, scatters drop);
    - ``meta``: the dense cache's non-K/V leaves (per-layer ``idx``
      cursor, the embedding ``pos`` counter when present), slot-stacked
      ``[num_slots]`` — tiny, so they stay dense.
    """

    pool_k: jax.Array
    pool_v: jax.Array
    scale_k: jax.Array
    scale_v: jax.Array
    table: jax.Array
    meta: Any


def _layer_names(cache: Dict[str, Any]):
    """Layer keys of a dense decode-cache dict, in layer order."""
    names = [k for k, v in cache.items()
             if isinstance(v, dict) and "k" in v and "v" in v]
    return sorted(names, key=lambda n: int(n.rsplit("_", 1)[1]))


def strip_kv(cache: Dict[str, Any]) -> Dict[str, Any]:
    """The meta half of a dense cache: everything except the K/V
    arenas (per-layer ``idx``, top-level ``pos``...)."""
    out: Dict[str, Any] = {}
    for key, val in cache.items():
        if isinstance(val, dict) and "k" in val and "v" in val:
            out[key] = {k: v for k, v in val.items() if k not in ("k", "v")}
        else:
            out[key] = val
    return out


def block_bytes(template: Dict[str, Any], cfg: PagedKVConfig) -> int:
    """Resident bytes of ONE logical block across all layers, K and V,
    scales included when quantized — the unit the allocator and the
    serving report account in."""
    layers = _layer_names(template)
    _, n_kv, _, dh = template[layers[0]]["k"].shape
    item = 1 if cfg.quantized else template[layers[0]]["k"].dtype.itemsize
    data = len(layers) * 2 * n_kv * cfg.block_size * dh * item
    scales = len(layers) * 2 * n_kv * 4 if cfg.quantized else 0
    return data + scales


def kv_bytes_per_pos(template: Dict[str, Any], cfg: PagedKVConfig) -> float:
    """Resident KV bytes per cached position (block bytes / block size)
    — the bytes-per-token lever the int8 path halves-or-better: decode
    streams ~context × this per emitted token."""
    return block_bytes(template, cfg) / cfg.block_size


class _Paged:
    """Gather/scatter machinery over one model's cache template.  Built
    once by :func:`tpudist.models.generate.make_slot_decode`; every
    method is pure jnp and runs inside the four compiled programs."""

    def __init__(self, template: Dict[str, Any], num_slots: int,
                 cfg: PagedKVConfig):
        self.cfg = cfg
        self.num_slots = num_slots
        self.layers = _layer_names(template)
        k0 = template[self.layers[0]]["k"]
        _, self.n_kv, self.max_len, self.dh = k0.shape
        self.compute_dtype = k0.dtype
        if cfg.block_size < 1 or self.max_len % cfg.block_size:
            raise ValueError(
                f"block_size {cfg.block_size} must be >= 1 and divide "
                f"max_len {self.max_len}")
        if cfg.num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {cfg.num_blocks}")
        self.blocks_per_slot = self.max_len // cfg.block_size
        self.template = template
        self.storage_dtype = jnp.int8 if cfg.quantized else self.compute_dtype

    # -- lifecycle ----------------------------------------------------------

    def init(self) -> PagedKV:
        L, B, cfg = len(self.layers), self.cfg.num_blocks, self.cfg
        shape = (L, B, self.n_kv, cfg.block_size, self.dh)
        meta = jax.tree.map(
            lambda a: jnp.zeros((self.num_slots,) + a.shape, a.dtype),
            strip_kv(self.template))
        return PagedKV(
            pool_k=jnp.zeros(shape, self.storage_dtype),
            pool_v=jnp.zeros(shape, self.storage_dtype),
            scale_k=jnp.ones((L, B, self.n_kv), jnp.float32),
            scale_v=jnp.ones((L, B, self.n_kv), jnp.float32),
            table=jnp.full((self.num_slots, self.blocks_per_slot),
                           B, jnp.int32),
            meta=meta)

    # -- gather: pool -> dense flax cache -----------------------------------

    def _dense_kv(self, pkv: PagedKV, rows: jax.Array
                  ) -> Tuple[jax.Array, jax.Array]:
        """Gather ``rows [..., M']`` of block ids into dense K/V
        ``[L, ..., n_kv, M'*bs, dh]`` in the compute dtype (sentinel
        ids clamp — the gathered garbage lands beyond every cursor,
        where the attention mask excludes it).  ``M'`` need not be the
        full table width: the window commit gathers only the TOUCHED
        blocks of a dispatch."""
        bs = self.cfg.block_size
        span = rows.shape[-1] * bs

        def view(pool, scale):
            g = pool[:, rows]                      # [L, ..., M', nk, bs, dh]
            g = g.astype(self.compute_dtype)
            if self.cfg.quantized:
                s = scale[:, rows]                 # [L, ..., M', nk]
                g = g * s[..., None, None].astype(self.compute_dtype)
            # [L, ..., M', nk, bs, dh] -> [L, ..., nk, M'*bs, dh]
            g = jnp.moveaxis(g, -3, -4)
            return g.reshape(g.shape[:-4] + (self.n_kv, span, self.dh))

        return (view(pkv.pool_k, pkv.scale_k),
                view(pkv.pool_v, pkv.scale_v))

    def lane_cache(self, pkv: PagedKV, row: jax.Array,
                   meta1: Dict[str, Any]) -> Dict[str, Any]:
        """One lane's batch-1 flax cache from its table ``row [M]`` and
        its (already slot-indexed) meta leaves."""
        ks, vs = self._dense_kv(pkv, row)          # [L, nk, max_len, dh]
        cache = jax.tree.map(lambda m: m, meta1)
        for li, name in enumerate(self.layers):
            cache[name] = dict(cache[name], k=ks[li][None], v=vs[li][None])
        return cache

    def slot_cache(self, pkv: PagedKV) -> Dict[str, Any]:
        """The full slot-stacked flax cache (leaves ``[S, 1, ...]``) the
        vmapped decode step consumes."""
        ks, vs = self._dense_kv(pkv, pkv.table)    # [L, S, nk, max_len, dh]
        cache = jax.tree.map(lambda m: m, pkv.meta)
        for li, name in enumerate(self.layers):
            cache[name] = dict(cache[name], k=ks[li][:, None],
                               v=vs[li][:, None])
        return cache

    # -- scatter: touched dense blocks -> pool ------------------------------

    def _touch_count(self, span: int) -> int:
        """Static block count covering ``span`` written positions from
        any (unaligned) start offset."""
        bs = self.cfg.block_size
        return min(self.blocks_per_slot, (max(1, span) - 1) // bs + 2)

    def _commit(self, pkv: PagedKV, rows: jax.Array, dense_k: jax.Array,
                dense_v: jax.Array, pos0: jax.Array, span: int,
                lane_mask: jax.Array) -> PagedKV:
        """Scatter the blocks written in ``[pos0, pos0 + span)`` back
        into the pool.  ``rows [S', M]`` block ids per lane, ``dense_*
        [L, S', n_kv, max_len, dh]``, ``pos0 [S']`` first written
        position, ``span`` static, ``lane_mask [S']`` — masked lanes
        (inactive / unused) scatter nothing.  Blocks BELOW ``pos0``'s
        block are never written, so shared prefix blocks stay pristine
        (no re-quantization drift onto co-tenants)."""
        bs, B = self.cfg.block_size, self.cfg.num_blocks
        M, T = self.blocks_per_slot, self._touch_count(span)
        t0 = pos0 // bs                            # first written block
        start = jnp.clip(t0, 0, M - T)             # slice anchor, in range
        logical = start[:, None] + jnp.arange(T)[None]   # [S', T]
        ids = jnp.take_along_axis(rows, jnp.minimum(logical, M - 1), axis=1)
        live = (logical >= t0[:, None]) & (logical < M) \
            & lane_mask[:, None]
        ids = jnp.where(live, ids, B).reshape(-1)  # sentinel -> dropped

        def vals_of(dense):
            # [L, S', nk, max_len, dh] -> per-lane slice [T*bs] at start
            x = jnp.moveaxis(dense, 1, 0)          # [S', L, nk, max_len, dh]
            x = jax.vmap(lambda d, s: lax.dynamic_slice_in_dim(
                d, s * bs, T * bs, axis=2))(x, start)
            x = x.reshape(x.shape[:3] + (T, bs, self.dh))
            # [S', L, nk, T, bs, dh] -> [L, S'*T, nk, bs, dh]
            x = jnp.transpose(x, (1, 0, 3, 2, 4, 5))
            return x.reshape(x.shape[0], -1, self.n_kv, bs, self.dh)

        return self._scatter_values(pkv, ids, vals_of(dense_k),
                                    vals_of(dense_v))

    def _scatter_values(self, pkv: PagedKV, ids: jax.Array,
                        vk: jax.Array, vv: jax.Array) -> PagedKV:
        """Quantize (int8 mode) and scatter per-block values ``[L, N,
        n_kv, bs, dh]`` into the pool at ``ids [N]`` (sentinel ids
        drop) — the one write path both the dense-view commit and the
        kernel path's window commit funnel through."""
        if self.cfg.quantized:
            def quant(v):
                amax = jnp.max(jnp.abs(v.astype(jnp.float32)),
                               axis=(-2, -1))     # [L, N, nk]
                scale = jnp.where(amax > 0, amax / 127.0, 1.0)
                q = jnp.clip(jnp.round(v.astype(jnp.float32)
                                       / scale[..., None, None]),
                             -127, 127).astype(jnp.int8)
                return q, scale

            qk, sk = quant(vk)
            qv, sv = quant(vv)
            return pkv._replace(
                pool_k=pkv.pool_k.at[:, ids].set(qk),
                pool_v=pkv.pool_v.at[:, ids].set(qv),
                scale_k=pkv.scale_k.at[:, ids].set(sk),
                scale_v=pkv.scale_v.at[:, ids].set(sv))
        return pkv._replace(
            pool_k=pkv.pool_k.at[:, ids].set(vk.astype(self.storage_dtype)),
            pool_v=pkv.pool_v.at[:, ids].set(vv.astype(self.storage_dtype)))

    def commit_slots(self, pkv: PagedKV, cache: Dict[str, Any],
                     pos0: jax.Array, span: int,
                     lane_mask: jax.Array) -> PagedKV:
        """Commit a slot-stacked dense cache (post-decode): scatter the
        written blocks of every live lane, adopt the advanced meta."""
        dk = jnp.stack([cache[n]["k"][:, 0] for n in self.layers])
        dv = jnp.stack([cache[n]["v"][:, 0] for n in self.layers])
        pkv = self._commit(pkv, pkv.table, dk, dv, pos0, span, lane_mask)
        return pkv._replace(meta=strip_kv(cache))

    def commit_lanes(self, pkv: PagedKV, cache: Dict[str, Any],
                     rows: jax.Array, dsts: jax.Array, pos0: jax.Array,
                     span: int) -> PagedKV:
        """Commit lane-stacked dense caches (post-prefill): scatter each
        lane's written blocks via its OWN table row (the row may not be
        installed in ``pkv.table`` yet), install the rows and the lane
        meta at ``dsts`` (sentinel dst = unused lane, dropped)."""
        dk = jnp.stack([cache[n]["k"][:, 0] for n in self.layers])
        dv = jnp.stack([cache[n]["v"][:, 0] for n in self.layers])
        pkv = self._commit(pkv, rows, dk, dv, pos0, span,
                           dsts < self.num_slots)
        meta = jax.tree.map(lambda full, lane: full.at[dsts].set(lane),
                            pkv.meta, strip_kv(cache))
        return pkv._replace(table=pkv.table.at[dsts].set(rows), meta=meta)

    # -- kernel path: window views (no dense gather at all) -----------------

    def window_view(self, pkv: PagedKV, span: int) -> Dict[str, Any]:
        """The paged-KERNEL path's decode cache: per-layer WINDOW
        buffers ``k``/``v`` ``[S, n_kv, span, dh]`` (all-zeros — they
        hold only the dispatch's own uncommitted tokens) plus the
        slot-stacked meta.  Unlike :meth:`slot_cache` there is NO pool
        gather here: the Pallas kernel reads live blocks in place, and
        :meth:`commit_window` scatters the window back touching only
        the blocks it spans."""
        cache = jax.tree.map(lambda m: m, pkv.meta)
        for name in self.layers:
            cache[name] = dict(
                cache[name],
                k=jnp.zeros((self.num_slots, self.n_kv, span, self.dh),
                            self.compute_dtype),
                v=jnp.zeros((self.num_slots, self.n_kv, span, self.dh),
                            self.compute_dtype))
        return cache

    def commit_window(self, pkv: PagedKV, view: Dict[str, Any],
                      pos0: jax.Array, span: int,
                      lane_mask: jax.Array) -> PagedKV:
        """Commit a window-view cache (post-decode/verify): gather ONLY
        each live lane's touched blocks (``_touch_count(span)`` of
        them — never ``max_len``), overlay the window at the lane's
        in-block offset, requantize, scatter back, adopt the advanced
        meta.  int8 note: the first touched block re-quantizes with its
        pre-existing positions included, exactly like the dense-view
        commit — same touched-block set, same dequant→overlay→requant
        math, so the commit introduces no divergence of its own (the
        two paths' pools differ only by the attention accumulation
        order upstream, at float tolerance)."""
        bs, B = self.cfg.block_size, self.cfg.num_blocks
        M, T = self.blocks_per_slot, self._touch_count(span)
        wk = jnp.stack([view[n]["k"] for n in self.layers])
        wv = jnp.stack([view[n]["v"] for n in self.layers])
        t0 = pos0 // bs
        start = jnp.clip(t0, 0, M - T)
        logical = start[:, None] + jnp.arange(T)[None]        # [S, T]
        ids = jnp.take_along_axis(pkv.table, jnp.minimum(logical, M - 1),
                                  axis=1)
        live = (logical >= t0[:, None]) & (logical < M) & lane_mask[:, None]
        ids = jnp.where(live, ids, B)                 # sentinel -> dropped
        # old contents of the touched blocks (dequantized) — the part a
        # partially-overwritten first block must carry forward
        old_k, old_v = self._dense_kv(pkv, ids)       # [L, S, nk, T*bs, dh]
        off = pos0 - start * bs

        def overlay(old, w):
            return jax.vmap(
                lambda o, ww, f: lax.dynamic_update_slice(
                    o, ww, (0, 0, f, 0)),
                in_axes=(1, 1, 0), out_axes=1)(old, w, off)

        def vals_of(x):   # [L, S, nk, T*bs, dh] -> [L, S*T, nk, bs, dh]
            x = x.reshape(x.shape[0], x.shape[1], self.n_kv, T, bs, self.dh)
            x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
            return x.reshape(x.shape[0], -1, self.n_kv, bs, self.dh)

        pkv = self._scatter_values(
            pkv, ids.reshape(-1), vals_of(overlay(old_k, wk)),
            vals_of(overlay(old_v, wv)))
        return pkv._replace(meta=strip_kv(view))

    def write_tables(self, rows: jax.Array, pos0: jax.Array,
                     clen: jax.Array, span: int,
                     lane_mask: jax.Array) -> jax.Array:
        """The prefill KERNEL path's write table: physical ids of the
        blocks each lane's chunk ``[pos0, pos0 + clen)`` touches,
        aligned so entry ``w`` is logical block ``pos0 // bs + w``.
        ``rows [S, M]`` per-lane block ids, ``span`` the static chunk
        capacity (bounds the width at ``_touch_count(span)``); entries
        past a lane's actual ``ceil`` span — and whole masked lanes —
        hold the sentinel, so the kernel's garbage blocks drop at the
        commit scatter.  Unlike ``_commit`` this charges only the
        blocks the chunk actually covers (the honest write-bytes
        story), not the full static span."""
        bs, B = self.cfg.block_size, self.cfg.num_blocks
        M, T = self.blocks_per_slot, self._touch_count(span)
        t0 = pos0 // bs
        n_t = jnp.where(clen > 0, (pos0 + clen - 1) // bs - t0 + 1, 0)
        logical = t0[:, None] + jnp.arange(T)[None]           # [S, T]
        ids = jnp.take_along_axis(rows, jnp.minimum(logical, M - 1), axis=1)
        live = (jnp.arange(T)[None] < n_t[:, None]) & (logical < M) \
            & lane_mask[:, None]
        return jnp.where(live, ids, B).astype(jnp.int32)

    def commit_quantized(self, pkv: PagedKV, ids: jax.Array,
                         qk: jax.Array, qv: jax.Array,
                         sk: jax.Array, sv: jax.Array) -> PagedKV:
        """Adopt blocks ALREADY in storage form (the prefill kernel
        quantizes in-registers with ``_scatter_values``'s exact
        formula): raw scatter at ``ids [N]`` (sentinel drops), scales
        taken as given in int8 mode, ignored otherwise.  ``qk``/``qv``
        ``[L, N, n_kv, bs, dh]``, ``sk``/``sv [L, N, n_kv]``."""
        pkv = pkv._replace(
            pool_k=pkv.pool_k.at[:, ids].set(qk.astype(self.storage_dtype)),
            pool_v=pkv.pool_v.at[:, ids].set(qv.astype(self.storage_dtype)))
        if self.cfg.quantized:
            pkv = pkv._replace(
                scale_k=pkv.scale_k.at[:, ids].set(sk),
                scale_v=pkv.scale_v.at[:, ids].set(sv))
        return pkv

    # -- KV handoff (prefill/decode disaggregation) -------------------------

    def extract_lane(self, pkv: PagedKV, slot: jax.Array
                     ) -> Tuple[jax.Array, jax.Array, Any]:
        """One slot's KV as a dense lane ``(k, v, meta1)`` with ``k``/``v``
        ``[L, n_kv, max_len, dh]`` in the compute dtype — the export half
        of the prefill→decode KV handoff.  Positions beyond the slot's
        cursor carry clamped-gather garbage exactly like any dispatch's
        dense view; the importing engine's attention mask excludes them
        bit-identically.  int8 pools dequantize here and re-quantize on
        :meth:`adopt_lane` — a bit-exact round trip (requantizing a
        block whose values came from ``q * scale`` reproduces the same
        ``q`` and ``scale``)."""
        ks, vs = self._dense_kv(pkv, pkv.table[slot])
        meta1 = jax.tree.map(lambda full: full[slot], pkv.meta)
        return ks, vs, meta1

    def adopt_lane(self, pkv: PagedKV, slot: jax.Array, row: jax.Array,
                   ks: jax.Array, vs: jax.Array, meta1: Any) -> PagedKV:
        """Import half of the KV handoff: scatter a dense lane into the
        (host-allocated) table ``row [M]``'s blocks, install the row and
        meta at ``slot``.  Sentinel row entries (footprints shorter than
        ``M`` blocks) drop their scatter, so only the reserved blocks
        are written."""
        cache = jax.tree.map(lambda m: m[None], meta1)
        for li, name in enumerate(self.layers):
            cache[name] = dict(cache[name], k=ks[li][None, None],
                               v=vs[li][None, None])
        return self.commit_lanes(
            pkv, cache, row[None], jnp.reshape(slot, (1,)),
            jnp.zeros((1,), jnp.int32), self.max_len)

    # -- evict --------------------------------------------------------------

    def release(self, pkv: PagedKV, slot: jax.Array,
                free_ids: jax.Array) -> PagedKV:
        """Unmap ``slot`` (sentinel table row + zero meta; a sentinel
        ``slot == num_slots`` skips the unmap) and zero the pool blocks
        in ``free_ids [M]`` (sentinel-padded; only blocks whose host
        refcount hit zero — a shared prefix block outlives any one
        tenant).  Zeroing freed blocks keeps the dense engine's
        no-KV-leakage hygiene: a recycled block never carries a previous
        tenant's K/V into the next gather."""
        B = self.cfg.num_blocks
        zero_blk = jnp.zeros((len(self.layers), free_ids.shape[0], self.n_kv,
                              self.cfg.block_size, self.dh),
                             self.storage_dtype)
        one = jnp.ones((len(self.layers), free_ids.shape[0], self.n_kv),
                       jnp.float32)
        meta = jax.tree.map(
            lambda full: full.at[slot].set(
                jnp.zeros(full.shape[1:], full.dtype)), pkv.meta)
        return pkv._replace(
            pool_k=pkv.pool_k.at[:, free_ids].set(zero_blk),
            pool_v=pkv.pool_v.at[:, free_ids].set(zero_blk),
            scale_k=pkv.scale_k.at[:, free_ids].set(one),
            scale_v=pkv.scale_v.at[:, free_ids].set(one),
            table=pkv.table.at[slot].set(
                jnp.full((self.blocks_per_slot,), B, jnp.int32)),
            meta=meta)

    # -- accounting ---------------------------------------------------------

    @property
    def block_bytes(self) -> int:
        return block_bytes(self.template, self.cfg)

    @property
    def pool_bytes(self) -> int:
        return self.block_bytes * self.cfg.num_blocks

    @property
    def bytes_per_pos(self) -> float:
        return kv_bytes_per_pos(self.template, self.cfg)
