"""One model split across multiple chips — the TPU-native re-design of the
reference's 2-GPU vertical model split (``demo_one_model_multi_gpu.py:17-42``).

The reference places ``layers0`` on device 0 and ``layers1`` on device 1 and
moves activations by hand in ``forward`` (``:40-42``) because CUDA has no
automatic sharding.  On TPU the idiomatic way to put one model on several
chips is to *shard the weight matrices* over a ``model`` mesh axis
(Megatron-style column/row splits) and let XLA's SPMD partitioner insert the
activation collectives — same capability (one model, N chips per replica,
composed with data parallelism, cf. ``DDP(device_ids=None)`` at ``:96-98``),
but expressed as partition specs instead of device placement (SURVEY.md §2.4).
The layer-*group* (pipeline) expression of the same split lives in
``tpudist.parallel.pipeline``.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpudist.runtime.mesh import AXIS_MODEL

# Alternating column/row splits over the hidden 10-wide layers; the 2-wide
# input and 1-wide output stay replicated (cannot and need not be split).
_KERNEL_SPECS = {
    "dense_0": P(None, AXIS_MODEL),  # column-split: output features sharded
    "dense_1": P(AXIS_MODEL, None),  # row-split: input features sharded
    "dense_2": P(None, AXIS_MODEL),
    "dense_3": P(AXIS_MODEL, None),
    "dense_4": P(),                  # (10, 1) head: replicated
}
_BIAS_SPECS = {
    "dense_0": P(AXIS_MODEL),
    "dense_1": P(),
    "dense_2": P(AXIS_MODEL),
    "dense_3": P(),
    "dense_4": P(),
}


def _spec_for_path(path) -> P:
    layer, leafname = None, None
    for entry in path:
        key = getattr(entry, "key", getattr(entry, "name", None))
        if isinstance(key, str):
            if key.startswith("dense_"):
                layer = key
            if key in ("kernel", "bias"):
                leafname = key
    if layer is None:
        return P()  # optimizer counts and anything unrecognized: replicate
    if leafname == "kernel":
        return _KERNEL_SPECS[layer]
    if leafname == "bias":
        return _BIAS_SPECS[layer]
    return P()


def split_state_sharding(mesh: Mesh, tree: Any):
    """Sharding pytree for a states/params tree of :class:`ToyMLP` models,
    splitting each model over the ``model`` mesh axis.

    Works on the full train-state tree: Adam's ``mu``/``nu`` mirror the param
    structure, so their leaves pick up the same specs by key path; scalar
    leaves (step counts) replicate.
    """

    def to_sharding(path, leaf):
        spec = _spec_for_path(path)
        # scalar leaves can't carry a non-empty spec
        if getattr(leaf, "ndim", 0) == 0:
            spec = P()
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(to_sharding, tree)
