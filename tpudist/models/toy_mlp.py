"""The toy MLP, Flax edition.

Architecture parity with the reference ``ToyModel``
(``toy_model_and_data.py:8-25``): Linear 2→10→10→10→10→1 with LeakyReLU
(negative slope 0.01, torch's default) between all but the last layer —
a quadratic-regression head the toy dataset converges on in seconds.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


class ToyMLP(nn.Module):
    features: Sequence[int] = (10, 10, 10, 10, 1)
    negative_slope: float = 0.01

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        for i, f in enumerate(self.features):
            x = nn.Dense(f, name=f"dense_{i}")(x)
            if i != len(self.features) - 1:
                x = nn.leaky_relu(x, negative_slope=self.negative_slope)
        return x


def create_toy_model(rng: jax.Array, input_dim: int = 2):
    """Init a ToyMLP; returns ``(module, params)``.

    Every process must pass the same ``rng`` so replicated parameters are
    bit-identical across hosts — the JAX-native replacement for DDP's
    broadcast-from-rank-0 at wrap time (``demo.py:70-72``).
    """
    module = ToyMLP()
    params = module.init(rng, jnp.zeros((1, input_dim), jnp.float32))
    return module, params
