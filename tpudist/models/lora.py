"""Paged multi-LoRA adapter pool: the device half of per-tenant adapters.

One base model serving thousands of customer fine-tunes is the
production shape (S-LoRA / Punica): every tenant's delta is a rank-r
LoRA factor pair per projection, tiny next to the base weights, and the
slot engine's core invariant — fixed-shape compiled programs with host
decisions riding in as data — extends to them exactly the way it
extended to paged KV (:mod:`tpudist.models.paged`):

- **storage** is a pool of ``num_blocks`` adapter blocks shared by all
  layers (:class:`AdapterPool`: six ``[L, num_blocks, ...]`` arrays —
  A/B factors for the ``qkv``, ``wi``, and ``wo`` projections; one
  block id holds one adapter's whole factor set, so the host registry
  is layer-oblivious like the KV allocator);
- **indirection** is a per-slot ``adapter_id`` in
  :class:`~tpudist.models.generate.SlotState` (sentinel ``num_blocks``
  = base-only): the compiled programs gather each slot's factors from
  the pool IN-GRAPH (:func:`gather_collection`) and compute the
  batched ``base(x) + (x·A_s)·B_s`` delta — shapes never depend on
  which adapters are live, so tenants churn with ZERO recompilation;
- **the base-only contract**: a sentinel id gathers clamped garbage
  (like a sentinel KV block), but the per-slot ``on`` mask selects the
  UNMODIFIED base projection output — ``jnp.where(on, y + Δ, y)``, a
  select, not an add — so a base-only lane is BIT-EXACT against a
  plain engine and the existing oracle suite keeps its teeth;
- **loading** is a host-initiated ``.at[:, bid].set`` per factor array
  (:func:`load_factors`), and freed blocks are zeroed
  (:func:`zero_block`) — no cross-tenant weight leakage, mirroring the
  KV pool's evict hygiene.

The indirection seam itself lives in ``Block.__call__``
(``lora_rank``, the ``"adapters"`` collection): the same per-slot
parameter-indirection later serves multi-model and MoE routing.  The
host half — name → block id, refcounts, LRU eviction of cold adapters,
whole-footprint admission — is :mod:`tpudist.serve.adapters`.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp

#: factor-pair keys, in the order the Block seam consumes them
FACTOR_KEYS = ("a_qkv", "b_qkv", "a_wi", "b_wi", "a_wo", "b_wo")


class AdapterPoolConfig(NamedTuple):
    """Static geometry of an adapter pool.

    - ``num_blocks``: resident adapter capacity (the sentinel id);
    - ``rank``: the LoRA rank r shared by every factor pair (one rank
      per pool keeps the programs fixed-shape; heterogeneous ranks
      would be a second pool).
    """

    num_blocks: int
    rank: int


class AdapterPool(NamedTuple):
    """The device-resident factor pool: ``a_*`` are ``[L, num_blocks,
    d_in, r]``, ``b_*`` ``[L, num_blocks, r, d_out]`` (f32 masters,
    cast to the compute dtype at apply like every flax param)."""

    a_qkv: jax.Array
    b_qkv: jax.Array
    a_wi: jax.Array
    b_wi: jax.Array
    a_wo: jax.Array
    b_wo: jax.Array


def adapter_dims(module) -> Dict[str, tuple]:
    """``(d_in, d_out)`` of each adapted projection for ``module`` (a
    TransformerLM): ``qkv`` covers the fused q/k/v output (GQA-aware),
    ``wi``/``wo`` the dense FFN halves."""
    d = int(module.d_model)
    n_kv = int(module.n_kv_heads or module.n_heads)
    dh = d // int(module.n_heads)
    kv_dim = n_kv * dh
    return {
        "qkv": (d, d + 2 * kv_dim),
        "wi": (d, int(module.d_ff)),
        "wo": (int(module.d_ff), d),
    }


def init_adapter_pool(module, cfg: AdapterPoolConfig) -> AdapterPool:
    """All-zeros pool over ``module``'s geometry (a zero factor pair is
    a no-op delta, so a freshly-allocated block is harmless even before
    its ``on`` mask gates it)."""
    if cfg.num_blocks < 1:
        raise ValueError(f"num_blocks must be >= 1, got {cfg.num_blocks}")
    if cfg.rank < 1:
        raise ValueError(f"rank must be >= 1, got {cfg.rank}")
    L, B, r = int(module.n_layers), cfg.num_blocks, cfg.rank
    dims = adapter_dims(module)
    return AdapterPool(
        a_qkv=jnp.zeros((L, B, dims["qkv"][0], r), jnp.float32),
        b_qkv=jnp.zeros((L, B, r, dims["qkv"][1]), jnp.float32),
        a_wi=jnp.zeros((L, B, dims["wi"][0], r), jnp.float32),
        b_wi=jnp.zeros((L, B, r, dims["wi"][1]), jnp.float32),
        a_wo=jnp.zeros((L, B, dims["wo"][0], r), jnp.float32),
        b_wo=jnp.zeros((L, B, r, dims["wo"][1]), jnp.float32))


def adapter_block_bytes(module, cfg: AdapterPoolConfig) -> int:
    """Resident bytes of ONE adapter block across all layers and
    projections — the unit the registry and serving report account in."""
    L, r = int(module.n_layers), cfg.rank
    total = 0
    for din, dout in adapter_dims(module).values():
        total += L * r * (din + dout) * 4  # f32 masters
    return total


def make_adapter_factors(rng, module, rank: int, *,
                         scale: float = 0.05) -> Dict[str, jax.Array]:
    """Random factor set for ``module`` at ``rank`` (tests/benches; a
    real fine-tune loads its trained factors through the same dict).
    Both halves are non-zero (classic LoRA inits B to zero, which is a
    no-op — useless for exercising the delta path)."""
    L = int(module.n_layers)
    dims = adapter_dims(module)
    out: Dict[str, jax.Array] = {}
    for proj, (din, dout) in dims.items():
        rng, ka, kb = jax.random.split(rng, 3)
        out[f"a_{proj}"] = (jax.random.normal(ka, (L, din, rank), jnp.float32)
                            * (din ** -0.5))
        out[f"b_{proj}"] = (jax.random.normal(kb, (L, rank, dout), jnp.float32)
                            * scale)
    return out


def check_factors(module, cfg: AdapterPoolConfig,
                  factors: Dict[str, Any]) -> None:
    """Loud shape validation before a load touches the pool."""
    import numpy as np

    L, r = int(module.n_layers), cfg.rank
    dims = adapter_dims(module)
    for proj, (din, dout) in dims.items():
        for key, want in ((f"a_{proj}", (L, din, r)),
                          (f"b_{proj}", (L, r, dout))):
            if key not in factors:
                raise ValueError(f"adapter factors missing {key!r}")
            got = tuple(np.shape(factors[key]))
            if got != want:
                raise ValueError(
                    f"adapter factor {key} shape {got} != expected {want} "
                    f"(module geometry × pool rank {r})")


def load_factors(pool: AdapterPool, bid: int,
                 factors: Dict[str, Any]) -> AdapterPool:
    """Write one adapter's factor set into block ``bid`` (host-initiated,
    eager — loads are rare next to decode dispatches)."""
    return AdapterPool(**{
        key: getattr(pool, key).at[:, bid].set(
            jnp.asarray(factors[key], getattr(pool, key).dtype))
        for key in FACTOR_KEYS})


def zero_block(pool: AdapterPool, bid: int) -> AdapterPool:
    """Zero block ``bid`` — a freed block must not leak a tenant's
    fine-tune into a later gather (the KV pool's evict hygiene)."""
    return AdapterPool(**{
        key: getattr(pool, key).at[:, bid].set(0.0)
        for key in FACTOR_KEYS})


def gather_collection(pool: AdapterPool, ids, n_layers: int,
                      layer_prefix: str = "block_") -> Dict[str, Any]:
    """The ``"adapters"`` flax collection for a slot batch: per layer
    ``{a_qkv .. b_wo, on}`` gathered at ``ids`` (scalar for one lane,
    ``[S]`` for a batched/vmapped program).  Sentinel ids clamp into a
    real block — harmless, because ``on = ids < num_blocks`` routes
    those lanes onto the bit-exact base path (the select in
    ``Block.__call__``)."""
    ids = jnp.asarray(ids, jnp.int32)
    B = pool.a_qkv.shape[1]
    on = ids < B
    out: Dict[str, Any] = {}
    for i in range(n_layers):
        col = {key: getattr(pool, key)[i, ids] for key in FACTOR_KEYS}
        col["on"] = on
        out[f"{layer_prefix}{i}"] = col
    return out


def pool_collection(pool: AdapterPool, ids, n_layers: int,
                    layer_prefix: str = "block_") -> Dict[str, Any]:
    """The ``"adapters"`` collection in POOL form, for
    ``Block.lora_kernel`` programs: every layer's dict carries the FULL
    factor pools (the same arrays — no gather, no copy; flax just sees
    one tracer per leaf) plus the per-slot ``ids`` vector, and the
    Pallas gather-matmul (:func:`tpudist.ops.fused_linear.lora_delta`)
    DMAs each slot's factor block inside the kernel.  ``on`` keeps the
    bit-exact base select for sentinel ids, same as
    :func:`gather_collection`."""
    ids = jnp.asarray(ids, jnp.int32)
    B = pool.a_qkv.shape[1]
    col: Dict[str, Any] = {key: getattr(pool, key) for key in FACTOR_KEYS}
    col["ids"] = ids
    col["on"] = ids < B
    return {f"{layer_prefix}{i}": col for i in range(n_layers)}


def adapter_collection(factors: Dict[str, Any], n_layers: int,
                       on: bool = True,
                       layer_prefix: str = "block_") -> Dict[str, Any]:
    """The ``"adapters"`` collection for a SINGLE adapter applied to a
    whole batch — the sequential-oracle path (:func:`tpudist.models.
    generate.generate` ``adapters=``): unbatched factor leaves broadcast
    over the batch, ``on`` a scalar."""
    out: Dict[str, Any] = {}
    for i in range(n_layers):
        col = {key: jnp.asarray(factors[key])[i] for key in FACTOR_KEYS}
        col["on"] = jnp.asarray(bool(on))
        out[f"{layer_prefix}{i}"] = col
    return out


def slice_factor_layers(collection_or_factors: Dict[str, Any],
                        n_layers: int) -> Dict[str, Any]:
    """First ``n_layers`` layers of a factor dict — the weight-tied
    draft's share of its slot's adapter (the draft IS the target's
    first N blocks, so its factors are the pool's first N layer
    slices)."""
    return {key: jnp.asarray(collection_or_factors[key])[:n_layers]
            for key in FACTOR_KEYS}


def pool_bytes(pool: Optional[AdapterPool]) -> int:
    if pool is None:
        return 0
    return sum(int(getattr(pool, k).size) * getattr(pool, k).dtype.itemsize
               for k in FACTOR_KEYS)
