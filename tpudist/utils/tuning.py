"""Hardware-tuned constants: per-platform defaults + env overrides.

Round-2 measurements baked several magic numbers into the hot paths — the
flash-attention routing crossover and block sizes
(``tpudist/models/transformer.py``) and the train loop's scan window
(``tpudist/train/loop.py``) — all measured on ONE v5e through one tunnel.
This module is the escape hatch the advisor asked for: every such constant
resolves here, through

1. an environment override ``TPUDIST_<NAME>`` (operators re-tune a new
   platform generation without touching code; the benchmark harnesses in
   ``benchmarks/`` are the re-derivation tools — ``flash_sweep.py`` for
   the crossover/blocks, ``bench.py`` for the scan window), then
2. a measured tuned-constants file for this ``device_kind`` —
   ``tpudist/tuned/<device_kind>.json``, written by
   :mod:`tpudist.utils.autotune` on real hardware (or any path via
   ``TPUDIST_TUNED_FILE``), then
3. a per-``device_kind`` table of measured values, then
4. the v5e-measured default (the only hardware this repo has ever seen).

Values are read lazily at call time, so tests can monkeypatch env vars and
a process that sets overrides before building models sees them.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Dict

# Measured on TPU v5e (BASELINE.md round 2): dense XLA wins below seq
# 1024; 512-wide tiles; 1024-wide KV tiles amortize grid overhead from
# seq 8192; 256-step scan windows hide tunnel dispatch latency.
_V5E_DEFAULTS: Dict[str, int] = {
    "FLASH_MIN_SEQ": 1024,      # routing crossover: flash at/above this
    "FLASH_BLOCK_Q": 512,
    "FLASH_BLOCK_K": 512,
    "FLASH_BLOCK_K_LONG": 1024,  # KV tile once seq >= FLASH_LONG_SEQ
    "FLASH_LONG_SEQ": 8192,
    "SYNC_EVERY": 256,          # train-loop scan window / metrics cadence
}

# Per-generation tables: add entries as hardware gets measured (the
# benchmark harnesses print the winning values).  Anything missing falls
# back to the v5e numbers — a safe, conservative default since v5e is the
# smallest current chip.
_BY_DEVICE_KIND: Dict[str, Dict[str, int]] = {
    # "TPU v6e": {"FLASH_BLOCK_K_LONG": 2048, ...}  # example shape
}


def _device_kind() -> str:
    """Device kind WITHOUT initializing the backend: resolving a tuned
    constant (e.g. constructing a TrainLoopConfig at argparse time) must
    never lock in platform/topology before the caller has set JAX_PLATFORMS
    / XLA_FLAGS / jax.distributed.initialize.  Before backend init the
    per-kind tables simply don't apply and the v5e defaults hold."""
    try:
        from jax._src import xla_bridge as _xb

        if not _xb.backends_are_initialized():
            return ""
    except Exception:
        # Internal API moved: we can no longer PROVE the backend is up, so
        # we must not risk initializing it — take the v5e defaults.
        return ""
    try:
        import jax

        return getattr(jax.devices()[0], "device_kind", "")
    except Exception:  # no devices
        return ""


def tuned_file_path(device_kind: str | None = None) -> Path:
    """Where measured tuned constants live for ``device_kind`` (defaults
    to the current device).  ``TPUDIST_TUNED_FILE`` overrides the path
    wholesale (one file, any location — e.g. a sweep-scratch dir)."""
    env = os.environ.get("TPUDIST_TUNED_FILE")
    if env:
        return Path(env)
    kind = _device_kind() if device_kind is None else device_kind
    safe = kind.replace(" ", "_").replace("/", "_") or "unknown"
    return Path(__file__).resolve().parent.parent / "tuned" / f"{safe}.json"


_tuned_file_cache: Dict[str, tuple] = {}  # path -> (mtime_ns, parsed dict)


def _from_tuned_file(key: str):
    """Measured-constants file lookup — missing/invalid file is simply
    'no measurement recorded', never an error.  Parsed content is cached
    per (path, mtime): ``tuned()`` runs several times per layer at trace
    time, and re-reading the JSON each call would pay 40+ read/parse
    cycles per 8-layer compile (rewrites — e.g. the autotuner finishing
    mid-session — invalidate via mtime)."""
    path = tuned_file_path()
    try:
        mtime = path.stat().st_mtime_ns
    except OSError:
        return None
    cached = _tuned_file_cache.get(str(path))
    if cached is None or cached[0] != mtime:
        try:
            data = json.loads(path.read_text())
            if not isinstance(data, dict):
                data = {}
        except Exception:
            data = {}
        _tuned_file_cache[str(path)] = (mtime, data)
    else:
        data = cached[1]
    return data.get(key)


def tuned(name: str) -> int:
    """Resolve the tuned constant ``name`` (see ``_V5E_DEFAULTS`` keys):
    ``TPUDIST_<NAME>`` env var > autotuned file > device-kind table >
    v5e default."""
    key = name.upper()
    if key not in _V5E_DEFAULTS:
        raise KeyError(f"unknown tuned constant {name!r}; "
                       f"known: {sorted(_V5E_DEFAULTS)}")
    env = os.environ.get(f"TPUDIST_{key}")
    if env is not None:
        return int(env)
    measured = _from_tuned_file(key)
    if measured is not None:
        return int(measured)
    return _BY_DEVICE_KIND.get(_device_kind(), {}).get(
        key, _V5E_DEFAULTS[key])
