"""Crash-record decorator — parity with ``torch.distributed.elastic``'s
``@record`` (``demo.py:14,156``): worker tracebacks are captured to a
per-rank error file so the launcher (``launch/tpurun``) can surface the
first failure instead of a wall of interleaved stderr.

The file path comes from ``TPUDIST_ERROR_FILE`` (set by the launcher;
``%r`` is replaced by the process id) and defaults to
``/tmp/tpudist_error_<pid>.json``.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time
import traceback
from typing import Callable


def error_file_path(process_id: int) -> str:
    template = os.environ.get("TPUDIST_ERROR_FILE", "/tmp/tpudist_error_%r.json")
    return template.replace("%r", str(process_id))


def record(fn: Callable) -> Callable:
    """Decorate an entry point ``main``; on exception, write a structured
    error record and re-raise."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except Exception as e:  # noqa: BLE001 — we re-raise
            try:
                pid = int(os.environ.get("TPUDIST_PROCESS_ID")
                          or os.environ.get("RANK")
                          or os.environ.get("SLURM_PROCID") or 0)
            except ValueError:
                pid = 0
            payload = {
                "process_id": pid,
                "pid": os.getpid(),
                "timestamp": time.time(),
                "exc_type": type(e).__name__,
                "message": str(e),
                "traceback": traceback.format_exc(),
                "argv": sys.argv,
            }
            try:
                with open(error_file_path(pid), "w") as f:
                    json.dump(payload, f, indent=2)
            except OSError:
                pass
            raise

    return wrapper
