"""Crash-record decorator — parity with ``torch.distributed.elastic``'s
``@record`` (``demo.py:14,156``): worker tracebacks are captured to a
per-rank error file so the launcher (``launch/tpurun``) can surface the
first failure instead of a wall of interleaved stderr.

The file path comes from ``TPUDIST_ERROR_FILE`` (set by the launcher;
``%r`` is replaced by the process id) and defaults to
``/tmp/tpudist_error_<pid>.json``.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time
import traceback
from typing import Callable


def error_file_path(process_id: int) -> str:
    template = os.environ.get("TPUDIST_ERROR_FILE", "/tmp/tpudist_error_%r.json")
    return template.replace("%r", str(process_id))


def _resolve_process_id() -> int:
    from tpudist.utils.envutil import env_rank

    return env_rank(0)


def write_error_record(payload: dict, process_id: "int | None" = None) -> "str | None":
    """Write a crash record atomically (tmp + ``os.replace``, same pattern
    as the checkpoint manager's meta overlays) and return its path.

    Atomicity matters: the record is written while the process is dying —
    a SIGKILL landing mid-``json.dump`` of a plain ``open(...,"w")`` left a
    torn file that ``tpurun``'s ``_read_crash_records`` silently skipped,
    losing the first-failure record the launcher exists to surface.
    Identity fields (process_id/pid/timestamp/argv) are filled in; the
    caller's ``payload`` wins on collision.  Returns ``None`` when the
    record could not be written (never raises — the original failure must
    still propagate).
    """
    if process_id is None:
        process_id = _resolve_process_id()
    full = {
        "process_id": process_id,
        "pid": os.getpid(),
        "timestamp": time.time(),
        "argv": sys.argv,
    }
    full.update(payload)
    path = error_file_path(process_id)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(full, f, indent=2)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except (OSError, TypeError, ValueError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    return path


def record(fn: Callable) -> Callable:
    """Decorate an entry point ``main``; on exception, write a structured
    error record and re-raise."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except Exception as e:  # noqa: BLE001 — we re-raise
            write_error_record({
                "exc_type": type(e).__name__,
                "message": str(e),
                "traceback": traceback.format_exc(),
            })
            raise

    return wrapper
