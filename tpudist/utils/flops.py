"""Analytic FLOPs accounting + MFU for the benchmark harnesses.

The reference publishes no efficiency numbers at all (SURVEY.md §6 —
``/root/reference/README.md`` is four lines); this repo owns its baseline,
so the baseline carries model-FLOPs-utilization: a throughput number alone
cannot say whether a step is 5% or 50% of what the chip can do.

Conventions (the standard accounting, e.g. the PaLM appendix / scaling-book
formulation):

- one multiply-add = 2 FLOPs;
- backward pass = 2x forward (one pass for activations, one for weights);
- causal attention does half the score/value work of full attention;
- embedding lookups, norms, softmax and other vector work are omitted —
  MXU matmul FLOPs dominate and MFU is conventionally model-FLOPs only.
"""

from __future__ import annotations

from typing import Optional

# Peak dense-matmul throughput per chip, bf16, FLOP/s.  Keyed by
# ``jax.Device.device_kind``.  Sources: public TPU spec sheets (v4 275T,
# v5e 197T, v5p 459T, v6e 918T bf16).
PEAK_BF16_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}

# One-way ICI bandwidth PER LINK, bytes/s — the number a ring collective
# rides (each chip forwards on one link per direction per ring axis).
# APPROXIMATE public figures (scaling-book-style accounting; exact specs
# vary by generation/topology doc) — the scaling model treats these as
# stated assumptions and also reports the inverse question ("bandwidth
# needed for the target"), which is spec-independent.
ICI_LINK_BYTES_PER_S = {
    "TPU v4": 4.5e10,
    "TPU v5 lite": 4.5e10,
    "TPU v5e": 4.5e10,
    "TPU v5": 9.0e10,
    "TPU v5p": 9.0e10,
    "TPU v6 lite": 9.0e10,
    "TPU v6e": 9.0e10,
}

# Per-HOST data-center-network bandwidth, bytes/s (the fabric the `data`
# axis rides in the hybrid mesh when it spans hosts) — assumption,
# ~200 Gbps NICs.
DCN_HOST_BYTES_PER_S = 2.5e10

# Per-chip HBM bandwidth, bytes/s, keyed like PEAK_BF16_FLOPS.  Public
# spec-sheet numbers (v4 1.2 TB/s, v5e 819 GB/s, v5p 2.77 TB/s, v6e
# 1.64 TB/s) — the denominator of every bandwidth-bound roofline
# (decode, and benchmarks/roofline.py's training-step HBM time).
HBM_BYTES_PER_S = {
    "TPU v4": 1.2e12,
    "TPU v5 lite": 8.19e11,
    "TPU v5e": 8.19e11,
    "TPU v5": 2.765e12,
    "TPU v5p": 2.765e12,
    "TPU v6 lite": 1.64e12,
    "TPU v6e": 1.64e12,
}


def chip_hbm_bytes_per_s(device=None) -> Optional[float]:
    """HBM bandwidth (bytes/s) for ``device`` (default: first visible);
    None when unknown (CPU virtual mesh)."""
    if device is None:
        import jax

        device = jax.devices()[0]
    return HBM_BYTES_PER_S.get(getattr(device, "device_kind", ""))


def chip_peak_flops(device=None) -> Optional[float]:
    """bf16 peak FLOP/s for ``device`` (default: first visible device);
    None when unknown (e.g. the CPU virtual mesh)."""
    if device is None:
        import jax

        device = jax.devices()[0]
    return PEAK_BF16_FLOPS.get(getattr(device, "device_kind", ""))


def attention_live_pairs(seq_len: int, *, causal: bool = True,
                         window=None) -> float:
    """Number of attended (q, k) pairs — the score-matmul work unit.
    Causal: s(s+1)/2; sliding window W: each token attends min(q+1, W)
    keys; dense: s²."""
    s = seq_len
    if not causal:
        if window is not None:
            # Match the kernel contract (flash_attention.py rejects this
            # combination) rather than silently overstating FLOPs/MFU.
            raise ValueError("window requires causal=True")
        return float(s * s)
    if window is None or window >= s:
        return s * (s + 1) / 2.0
    w = max(int(window), 1)
    # first w tokens attend q+1 keys; the rest attend exactly w
    return w * (w + 1) / 2.0 + (s - w) * float(w)


def transformer_train_flops(
    *,
    batch: int,
    seq_len: int,
    d_model: int,
    n_layers: int,
    d_ff: int,
    vocab: int,
    causal: bool = True,
    window=None,
    fwd_only: bool = False,
) -> float:
    """Analytic matmul FLOPs for one TransformerLM train step
    (:class:`tpudist.models.transformer.TransformerLM` shapes: fused qkv,
    proj, wi/wo FFN, untied head).

    Per block forward: qkv ``6*b*s*d^2`` + proj ``2*b*s*d^2`` + attention
    ``4 * live_pairs * d`` (scores + values over the attended band —
    causal halves the dense count, a sliding ``window`` clamps it to the
    band; see :func:`attention_live_pairs`) + FFN ``4*b*s*d*f``.
    Head: ``2*b*s*d*V``.  Train = 3x forward.  A top-1 capacity MoE FFN has
    the same per-token FLOPs as the dense FFN (each token visits one
    expert), so this formula covers the MoE variant too (router matmul is
    O(b*s*d*E), negligible).
    """
    b, s, d, f, v = batch, seq_len, d_model, d_ff, vocab
    attn = 4.0 * b * attention_live_pairs(s, causal=causal, window=window) * d
    per_block = 8 * b * s * d * d + attn + 4 * b * s * d * f
    fwd = n_layers * per_block + 2 * b * s * d * v
    return fwd if fwd_only else 3.0 * fwd


def transformer_param_count(*, d_model: int, n_layers: int, d_ff: int,
                            vocab: int, max_len: int) -> int:
    """Parameter count for the TransformerLM shapes (fused qkv + proj =
    4d², wi/wo FFN = 2·d·ff per block; embed + untied head = 2·V·d;
    learned positions = max_len·d).  Norm scales/biases omitted (O(d))."""
    per_layer = 4 * d_model * d_model + 2 * d_model * d_ff
    return (n_layers * per_layer + 2 * vocab * d_model
            + max_len * d_model)


def decode_roofline(*, batch: int, prompt_len: int, max_new: int,
                    d_model: int, n_layers: int, d_ff: int, vocab: int,
                    param_bytes: int = 4, cache_bytes: int = 4,
                    hbm_bytes_per_s: Optional[float] = None) -> Optional[dict]:
    """Bandwidth roofline for autoregressive decode (the KV-cache path).

    Decode is HBM-bound: each emitted token must stream every weight once
    (amortized over the whole batch — one read serves all ``batch``
    sequences) and each sequence's KV cache once.  Per decode step at
    context length L:

        bytes = n_params·param_bytes  +  batch·n_layers·2·L·d·cache_bytes

    Averaged over the decode (L runs prompt_len → prompt_len+max_new),
    the ceiling on aggregate throughput is ``batch / (bytes_avg / BW)``
    tokens/sec.  Returns None when the chip's HBM bandwidth is unknown
    (CPU virtual mesh).  MXU FLOPs don't appear: at decode shapes the
    compute time is orders of magnitude under the byte-streaming time.
    """
    if hbm_bytes_per_s is None:
        hbm_bytes_per_s = chip_hbm_bytes_per_s()
    if not hbm_bytes_per_s:
        return None
    max_len = prompt_len + max_new
    n_params = transformer_param_count(
        d_model=d_model, n_layers=n_layers, d_ff=d_ff, vocab=vocab,
        max_len=max_len)
    weight_bytes = n_params * param_bytes
    mean_ctx = prompt_len + (max_new + 1) / 2.0
    kv_bytes = batch * n_layers * 2 * mean_ctx * d_model * cache_bytes
    bytes_per_step = weight_bytes + kv_bytes
    t_step = bytes_per_step / hbm_bytes_per_s
    return {
        "n_params": n_params,
        "weight_bytes_per_step": int(weight_bytes),
        "kv_bytes_per_step_avg": int(kv_bytes),
        "hbm_bytes_per_s": hbm_bytes_per_s,
        "ceiling_tokens_per_sec": round(batch / t_step, 1),
    }


def mfu(
    flops_per_step: float,
    step_seconds: float,
    n_chips: int,
    peak_per_chip: Optional[float] = None,
) -> Optional[float]:
    """Model FLOPs utilization in [0, 1]; None when the chip peak is
    unknown (virtual CPU devices)."""
    if peak_per_chip is None:
        peak_per_chip = chip_peak_flops()
    if not peak_per_chip or step_seconds <= 0:
        return None
    return flops_per_step / step_seconds / (n_chips * peak_per_chip)
