"""Profiling / trace capture.

The reference has no tracing at all (SURVEY.md §5.1 — its only timing is
``time tar`` in staging scripts and tqdm throughput).  The TPU-native
framework exposes XLA's first-class profiler as a flag: a trace window
written per-process (TensorBoard/Perfetto-readable), plus a lightweight
wall-clock timer for the staging-style host phases.
"""

from __future__ import annotations

import contextlib
import time
from pathlib import Path
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def trace(profile_dir: Optional[str]) -> Iterator[None]:
    """Capture a ``jax.profiler`` trace into ``profile_dir`` (no-op when
    ``None``).  Multi-process: each process writes its own subdirectory, so
    traces from all hosts land side by side on shared storage."""
    if profile_dir is None:
        yield
        return
    path = Path(profile_dir) / f"process_{jax.process_index()}"
    path.mkdir(parents=True, exist_ok=True)
    jax.profiler.start_trace(str(path))
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StageTimer:
    """Wall-clock phase timer (the host-side analog of the reference's
    ``time tar`` staging timing) — records named phase durations."""

    def __init__(self):
        self.durations: dict = {}

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.durations[name] = self.durations.get(name, 0.0) + (
                time.perf_counter() - t0
            )
