"""Profiling / trace capture.

The reference has no tracing at all (SURVEY.md §5.1 — its only timing is
``time tar`` in staging scripts and tqdm throughput).  The TPU-native
framework exposes XLA's first-class profiler as a flag: a trace window
written per-process (TensorBoard/Perfetto-readable), plus a lightweight
wall-clock timer for the staging-style host phases.
"""

from __future__ import annotations

import contextlib
import time
from pathlib import Path
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def trace(profile_dir: Optional[str]) -> Iterator[None]:
    """Capture a ``jax.profiler`` trace into ``profile_dir`` (no-op when
    ``None``).  Multi-process: each process writes its own subdirectory, so
    traces from all hosts land side by side on shared storage."""
    if profile_dir is None:
        yield
        return
    path = Path(profile_dir) / f"process_{jax.process_index()}"
    path.mkdir(parents=True, exist_ok=True)
    jax.profiler.start_trace(str(path))
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StageTimer:
    """Wall-clock phase timer (the host-side analog of the reference's
    ``time tar`` staging timing) — records named phase durations.

    Collected durations are published with :meth:`emit` — one committed
    metrics row (``stage/<name>``) plus one telemetry ``stage`` event per
    phase, which the goodput report surfaces in its "Host stages" section.
    Without an emit the durations die with the process, which is exactly
    the collected-then-dropped failure mode this closes."""

    def __init__(self):
        self.durations: dict = {}

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.durations[name] = self.durations.get(name, 0.0) + (
                time.perf_counter() - t0
            )

    def emit(self, logger=None, *, prefix: str = "stage/",
             session=None) -> dict:
        """Publish phase durations: telemetry ``stage`` events (into
        ``session``, default the active session) and, when a
        :class:`~tpudist.utils.metrics.MetricsLogger` is given, one
        committed ``stage/<name>`` metrics row.  Returns the durations;
        call it BEFORE the logger is finished (e.g. right before
        ``run_training``, or at run end for post-loop phases)."""
        from tpudist import telemetry

        sess = session if session is not None else telemetry.active()
        if sess is not None:
            for name, dur in self.durations.items():
                sess.event("stage", stage=name, dur_s=round(dur, 6))
        if logger is not None and self.durations:
            logger.log(
                {f"{prefix}{k}": v for k, v in self.durations.items()},
                commit=True,
            )
        return dict(self.durations)
