from tpudist.utils.metrics import MetricsLogger, init_metrics  # noqa: F401
