from tpudist.utils.metrics import MetricsLogger, init_metrics  # noqa: F401
from tpudist.utils.profiling import StageTimer, trace  # noqa: F401
