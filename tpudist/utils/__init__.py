from tpudist.utils.flops import (  # noqa: F401
    chip_peak_flops,
    mfu,
    transformer_train_flops,
)
from tpudist.utils.metrics import MetricsLogger, init_metrics  # noqa: F401
from tpudist.utils.profiling import StageTimer, trace  # noqa: F401
