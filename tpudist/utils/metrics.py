"""W&B-compatible metrics logging with a first-class offline mode.

Parity targets (SURVEY.md §5.5):
- rank-0-only ``wandb.init(project=…, group=…)`` (``demo.py:76-78``),
- per-iteration ``wandb.log({...}, commit=False)`` + committing log
  (``demo.py:119-121``),
- ``--dry_run`` → ``WANDB_MODE=dryrun`` offline fixture (``demo.py:160-161``),
- ``wandb.finish()`` **before** distributed teardown to avoid shutdown
  races (``demo.py:133-136``),
- API key via ``WANDB_API_KEY`` env (plumbed by the launcher, §2.2 B1).

wandb is an optional dependency: when importable (and not in dry-run mode)
the real client is used; otherwise an in-tree JSONL logger with the same
surface (``log``/``finish``) records to ``<dir>/metrics.jsonl`` so offline
clusters and tests need no network or credentials.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path
from typing import Mapping, Optional

import jax


class MetricsLogger:
    """Minimal wandb-Run-alike: ``log(metrics, commit=)`` + ``finish()``."""

    def __init__(self, run=None, jsonl_path: Optional[Path] = None):
        self._run = run  # a real wandb run, or None
        self._jsonl_path = jsonl_path
        self._jsonl_file = None
        self._pending: dict = {}
        self._step = 0
        if jsonl_path is not None:
            jsonl_path.parent.mkdir(parents=True, exist_ok=True)
            self._jsonl_file = open(jsonl_path, "a")

    def log(self, metrics: Mapping[str, float], commit: bool = True) -> None:
        self._pending.update(metrics)
        if not commit:
            return
        record, self._pending = self._pending, {}
        if self._run is not None:
            self._run.log(record)
        if self._jsonl_file is not None:
            record = {"_step": self._step, "_time": time.time(), **record}
            try:
                # Flush EVERY committed line: a kill between commits must
                # lose at most the line being written, never a window of
                # already-committed rows sitting in the userspace buffer.
                self._jsonl_file.write(json.dumps(record) + "\n")
                self._jsonl_file.flush()
            except (OSError, ValueError):
                # ValueError = file closed underneath (finish() raced a
                # straggling log call); metrics must not take the run
                # down — but the fd must not leak either.
                f, self._jsonl_file = self._jsonl_file, None
                try:
                    f.close()
                except (OSError, ValueError):
                    pass
        self._step += 1

    def finish(self) -> None:
        """Must run before ``runtime.shutdown()`` — same ordering discipline
        as ``wandb.finish()`` before ``destroy_process_group``
        (``demo.py:133-136``).  Idempotent, and safe to call (or to keep
        ``log``-ging) after the underlying file is gone: a double teardown
        path must never crash the run it is cleaning up.  The final fsync
        makes every committed row durable — a SIGKILL right after loses at
        most a trailing partial line."""
        if self._pending:
            self.log({}, commit=True)
        if self._run is not None:
            run, self._run = self._run, None
            try:
                run.finish()
            except Exception:  # noqa: BLE001 — wandb teardown is best-effort
                pass
        f, self._jsonl_file = self._jsonl_file, None
        if f is not None:
            try:
                f.flush()
                os.fsync(f.fileno())
            except (OSError, ValueError):
                pass
            try:
                f.close()
            except OSError:
                pass


class _NullLogger(MetricsLogger):
    def __init__(self):
        super().__init__(run=None, jsonl_path=None)


def init_metrics(
    project: str = "tpudist",
    group: Optional[str] = None,
    *,
    dry_run: bool = False,
    log_dir: str = "runs",
    rank_zero_only: bool = True,
) -> MetricsLogger:
    """Create the job's metrics logger (rank 0 gets the real one; other ranks
    a no-op, mirroring ``if rank == 0: wandb.init`` at ``demo.py:76-78``)."""
    if rank_zero_only and jax.process_index() != 0:
        return _NullLogger()
    if dry_run:
        os.environ["WANDB_MODE"] = "dryrun"  # demo.py:160-161
    use_wandb = not dry_run and os.environ.get("WANDB_MODE") not in ("dryrun", "offline", "disabled")
    run = None
    if use_wandb:
        try:
            import wandb

            run = wandb.init(
                project=project,
                group=group,
                settings=wandb.Settings(start_method="thread"),  # demo.py:78
            )
        except Exception:
            run = None  # no wandb / no credentials → JSONL fallback only
    jsonl = Path(log_dir) / f"{group or project}" / "metrics.jsonl"
    return MetricsLogger(run=run, jsonl_path=jsonl)
