"""On-hardware autotuner for the flash-attention tuned constants.

``benchmarks/flash_sweep.py`` prints A/B timings for a human to read;
this module closes the loop — it times the candidate (block_q, block_k)
tiles and the dense-vs-flash crossover ON THE CURRENT DEVICE and writes
the winners to ``tpudist/tuned/<device_kind>.json``, where
:func:`tpudist.utils.tuning.tuned` resolves them ahead of the baked v5e
defaults (env vars still win over everything).  One command ports the
kernel routing to a new TPU generation:

    python -m tpudist.utils.autotune            # measure + write
    python -m tpudist.utils.autotune --dry-run  # measure + print only

Measurement method matches the sweep harness: each configuration is ONE
dispatched XLA program chaining serially-dependent applications via
``lax.scan``, so the axon tunnel's tens-of-ms per-dispatch latency is
amortized out of the per-application number.

Tuned keys written (see ``tuning._V5E_DEFAULTS``):
- ``FLASH_BLOCK_Q`` / ``FLASH_BLOCK_K`` — fastest tile at the short
  production shape (seq 2048, fwd+bwd);
- ``FLASH_BLOCK_K_LONG`` — fastest KV tile at the long shape (seq 8192);
- ``FLASH_MIN_SEQ`` — smallest measured seq where flash beats the dense
  XLA reference (fwd+bwd), i.e. the routing crossover.
"""

from __future__ import annotations

import argparse
import functools
import json
import sys
import time
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tpudist.utils.tuning import tuned_file_path

HEAD_DIM = 64  # the demo/transformer head width every harness times


def time_one_program(fn: Callable, *args, steps: int = 8) -> float:
    """Per-application seconds for ``fn(*args)`` measured as one
    dispatched program scanning ``steps`` serially-dependent calls."""

    def chained(*xs):
        def body(carry, _):
            out = fn(*carry[1:])
            # re-feed the first operand so the chain is data-dependent
            return (carry[0] + out.ravel()[0].astype(jnp.float32),
                    *carry[1:]), None

        (acc, *_), _ = lax.scan(body, (jnp.float32(0), *xs), None,
                                length=steps)
        return acc

    compiled = jax.jit(chained)
    acc = compiled(*args)
    acc.block_until_ready()  # compile + warm
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        compiled(*args).block_until_ready()
        best = min(best, (time.perf_counter() - t0) / steps)
    return best


def _qkv(seq: int, heads: int = 4, batch: int = 1):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(11), 3)
    shape = (batch, heads, seq, HEAD_DIM)
    return (jax.random.normal(kq, shape, jnp.float32),
            jax.random.normal(kk, shape, jnp.float32),
            jax.random.normal(kv, shape, jnp.float32))


def _flash_grad_fn(bq: int, bk: int):
    from tpudist.ops import flash_attention

    def loss(q, k, v):
        return (flash_attention(q, k, v, True, bq, bk, False, None) ** 2).sum()

    return jax.grad(loss, argnums=(0, 1, 2))


def _dense_grad_fn():
    from tpudist.parallel import attention_reference

    def loss(q, k, v):
        return (attention_reference(q, k, v, causal=True) ** 2).sum()

    return jax.grad(loss, argnums=(0, 1, 2))


def _first_output(fn):
    """Adapt a tuple-returning grad fn to the scalar-chaining timer."""

    @functools.wraps(fn)
    def one(*args):
        return fn(*args)[0]

    return one


def autotune_flash(
    *,
    short_seq: int = 2048,
    long_seq: int = 8192,
    tiles: Sequence[tuple[int, int]] = ((256, 256), (512, 256), (512, 512),
                                       (1024, 512)),
    long_k_tiles: Sequence[int] = (512, 1024, 2048),
    crossover_seqs: Sequence[int] = (512, 1024, 2048),
    timer: Callable = time_one_program,
    log: Callable = functools.partial(print, file=sys.stderr, flush=True),
) -> dict:
    """Measure and return the tuned-constant dict (no file IO here).

    ``timer`` is injectable so the selection logic is testable without
    hardware (tests feed synthetic timings)."""
    report: dict = {"measurements": {}}

    # --- short-shape tile: FLASH_BLOCK_Q / FLASH_BLOCK_K ---
    best_t, best_tile = float("inf"), None
    for bq, bk in tiles:
        if short_seq % bq or short_seq % bk:
            continue
        t = timer(_first_output(_flash_grad_fn(bq, bk)), *_qkv(short_seq))
        report["measurements"][f"short{short_seq}_{bq}x{bk}"] = t
        log(f"# autotune short seq{short_seq} {bq}x{bk}: {t * 1e3:.3f} ms")
        if t < best_t:
            best_t, best_tile = t, (bq, bk)
    if best_tile is None:
        raise ValueError(f"no candidate tile divides seq {short_seq}")
    report["FLASH_BLOCK_Q"], report["FLASH_BLOCK_K"] = best_tile

    # --- long-shape KV tile: FLASH_BLOCK_K_LONG ---
    bq = report["FLASH_BLOCK_Q"]
    best_t, best_bk = float("inf"), None
    for bk in long_k_tiles:
        if long_seq % bk or long_seq % bq:
            continue
        t = timer(_first_output(_flash_grad_fn(bq, bk)), *_qkv(long_seq))
        report["measurements"][f"long{long_seq}_{bq}x{bk}"] = t
        log(f"# autotune long seq{long_seq} {bq}x{bk}: {t * 1e3:.3f} ms")
        if t < best_t:
            best_t, best_bk = t, bk
    if best_bk is not None:
        report["FLASH_BLOCK_K_LONG"] = best_bk

    # --- routing crossover: FLASH_MIN_SEQ ---
    # Smallest seq where flash (at the winning tile, clipped to fit)
    # beats dense.  If flash never wins, the crossover sits above the
    # largest probed seq — park it there so routing stays dense.
    bq0, bk0 = best_tile
    crossover = None
    for s in sorted(crossover_seqs):
        fb_q, fb_k = min(bq0, s), min(bk0, s)
        if s % fb_q or s % fb_k:
            continue
        tf = timer(_first_output(_flash_grad_fn(fb_q, fb_k)), *_qkv(s))
        td = timer(_first_output(_dense_grad_fn()), *_qkv(s))
        report["measurements"][f"crossover{s}"] = {"flash": tf, "dense": td}
        log(f"# autotune crossover seq{s}: flash {tf * 1e3:.3f} ms "
            f"vs dense {td * 1e3:.3f} ms")
        if tf < td and crossover is None:
            crossover = s
    report["FLASH_MIN_SEQ"] = (crossover if crossover is not None
                               else max(crossover_seqs) * 2)
    return report


def write_tuned(report: dict, path=None) -> str:
    """Persist the tuned keys (measurements stay out of the file — the
    resolver wants an int table, the evidence goes to the caller/log)."""
    path = tuned_file_path() if path is None else path
    path.parent.mkdir(parents=True, exist_ok=True)
    keys = {k: v for k, v in report.items() if k.isupper()}
    meta = {"device_kind": jax.devices()[0].device_kind,
            "method": "tpudist.utils.autotune"}
    path.write_text(json.dumps({**keys, "_meta": meta}, indent=2) + "\n")
    return str(path)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dry-run", action="store_true",
                    help="measure and print, do not write the tuned file")
    ap.add_argument("--short-seq", type=int, default=2048)
    ap.add_argument("--long-seq", type=int, default=8192)
    args = ap.parse_args(argv)

    if jax.devices()[0].platform != "tpu":
        print(json.dumps({"error": "autotune needs a real TPU "
                          f"(got {jax.devices()[0].platform})"}))
        return 2
    report = autotune_flash(short_seq=args.short_seq, long_seq=args.long_seq)
    out = {k: v for k, v in report.items() if k != "measurements"}
    if not args.dry_run:
        out["written_to"] = write_tuned(report)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
