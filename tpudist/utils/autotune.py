"""On-hardware autotuner for the flash-attention tuned constants.

``benchmarks/flash_sweep.py`` prints A/B timings for a human to read;
this module closes the loop — it times the candidate (block_q, block_k)
tiles and the dense-vs-flash crossover ON THE CURRENT DEVICE and writes
the winners to ``tpudist/tuned/<device_kind>.json``, where
:func:`tpudist.utils.tuning.tuned` resolves them ahead of the baked v5e
defaults (env vars still win over everything).  One command ports the
kernel routing to a new TPU generation:

    python -m tpudist.utils.autotune            # measure + write
    python -m tpudist.utils.autotune --dry-run  # measure + print only

Measurement method matches the sweep harness: each configuration is ONE
dispatched XLA program chaining serially-dependent applications via
``lax.scan``, so the axon tunnel's tens-of-ms per-dispatch latency is
amortized out of the per-application number.

Tuned keys written (see ``tuning._V5E_DEFAULTS``):
- ``FLASH_BLOCK_Q`` / ``FLASH_BLOCK_K`` — fastest tile at the short
  production shape (seq 2048, fwd+bwd);
- ``FLASH_BLOCK_K_LONG`` — fastest KV tile at the long shape (seq 8192);
- ``FLASH_MIN_SEQ`` — smallest measured seq where flash beats the dense
  XLA reference (fwd+bwd), i.e. the routing crossover.
"""

from __future__ import annotations

import argparse
import functools
import json
import sys
import time
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tpudist.utils.tuning import tuned_file_path

# Production attention shape: the d1024 MFU geometry's head width (d1024 /
# 8 heads = 128; the d512 demo geometry's 64-wide heads share tiles fine),
# bf16 operands (the MXU's native precision — tile selection at f32 rates
# does not transfer), and enough batch×heads that the grid fills the chip
# the way a real step does (b1/h4 measured a different winner than b2/h8).
HEAD_DIM = 128
HEADS = 8
BATCH = 2
DTYPE = jnp.bfloat16


def time_one_program(fn: Callable, *args, steps: int = 128,
                     steps_short: int = 16, repeats: int = 5) -> float:
    """Per-application seconds for ``fn(*args)``: two-point measurement
    over scans of ``steps`` and ``steps_short`` serially-dependent calls,
    per-app = (t_long − t_short) / (steps − steps_short) — the same
    methodology ``benchmarks/flash_sweep.py`` uses, for the same reasons:

    - The serial dependence must run THROUGH the inputs: re-feeding the
      same operands makes ``fn(*xs)`` loop-invariant — XLA hoists the
      application out of the scan and the "timing" measures a scalar
      loop (microsecond readings for millisecond kernels; the winners
      the first tuned file picked were noise).  Feeding ``eps·out`` back
      into the first operand pins one application per iteration.
    - Sync by FETCHING the scalar: through the axon tunnel
      ``block_until_ready`` returns before the device work is done.
    - Two points subtract the constant per-dispatch tunnel cost
      (~tens of ms), which at single-kernel scale dwarfs the op.
    - The long/short gap must be LARGE: at 10-vs-2 steps the extra work
      (~8 sub-ms applications) sat inside the tunnel's run-to-run jitter
      and three consecutive runs picked three different "winners";
      128-vs-16 puts ~50-100x the jitter between the two points
      (lax.scan is rolled, so compile time does not grow with length)."""

    def make(length):
        def chained(*xs):
            def body(carry, _):
                acc, x0, *rest = carry
                out = fn(x0, *rest)
                x0 = x0 + (out
                           * jnp.asarray(1e-8, out.dtype)).astype(x0.dtype)
                return (acc + out.ravel()[0].astype(jnp.float32),
                        x0, *rest), None

            (acc, *_), _ = lax.scan(body, (jnp.float32(0), *xs), None,
                                    length=length)
            return acc

        return jax.jit(chained)

    def best_total(length) -> float:
        compiled = make(length)
        float(np.asarray(compiled(*args)))  # compile + warm
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            float(np.asarray(compiled(*args)))
            best = min(best, time.perf_counter() - t0)
        return best

    t_short = best_total(steps_short)
    t_long = best_total(steps)
    if t_long <= t_short:
        # Tunnel jitter swallowed the extra applications: the difference
        # carries no signal.  Raising (callers skip the candidate) beats
        # returning a near-zero sentinel that would unbeatably "win" the
        # tile selection — the noise-picked-winner failure this timer
        # exists to prevent.
        raise RuntimeError(
            f"two-point timing nonpositive ({t_long:.4f}s <= "
            f"{t_short:.4f}s) — dispatch jitter dominated; remeasure")
    return (t_long - t_short) / (steps - steps_short)


def _qkv(seq: int, heads: int = HEADS, batch: int = BATCH,
         head_dim: int = HEAD_DIM, dtype=None):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(11), 3)
    shape = (batch, heads, seq, head_dim)
    dtype = DTYPE if dtype is None else dtype
    return (jax.random.normal(kq, shape, dtype),
            jax.random.normal(kk, shape, dtype),
            jax.random.normal(kv, shape, dtype))


def _flash_grad_fn(bq: int, bk: int):
    from tpudist.ops import flash_attention

    def loss(q, k, v):
        return (flash_attention(q, k, v, True, bq, bk, False, None) ** 2).sum()

    return jax.grad(loss, argnums=(0, 1, 2))


def _dense_grad_fn():
    from tpudist.parallel import attention_reference

    def loss(q, k, v):
        return (attention_reference(q, k, v, causal=True) ** 2).sum()

    return jax.grad(loss, argnums=(0, 1, 2))


def _first_output(fn):
    """Adapt a tuple-returning grad fn to the scalar-chaining timer."""

    @functools.wraps(fn)
    def one(*args):
        return fn(*args)[0]

    return one


def autotune_flash(
    *,
    short_seq: int = 2048,
    long_seq: int = 8192,
    tiles: Sequence[tuple[int, int]] = ((256, 256), (512, 256), (512, 512),
                                       (512, 1024), (1024, 512),
                                       (1024, 1024)),
    long_k_tiles: Sequence[int] = (512, 1024, 2048),
    crossover_seqs: Sequence[int] = (512, 1024, 2048),
    timer: Callable = time_one_program,
    compile_check: Callable | None = None,
    log: Callable = functools.partial(print, file=sys.stderr, flush=True),
) -> dict:
    """Measure and return the tuned-constant dict (no file IO here).

    ``timer`` and ``compile_check`` are injectable so the selection logic
    is testable without hardware (tests feed synthetic timings/verdicts).

    A candidate that fails to compile (VMEM stack OOM at big tiles) or to
    measure (two-point delta swallowed by dispatch jitter) is SKIPPED,
    not fatal — and because the tuned constants apply to every model
    geometry, each winning tile must also COMPILE at the worst-VMEM
    shape the benches actually run (f32 operands, 64-wide heads:
    measured r4, (1024, 2048) timed fine at bf16/d128 and then OOM'd the
    scoped VMEM in the long bench's f32/d64 rows).  The feasibility probe
    is a single compile+run, not a timing — it only answers yes/no."""
    report: dict = {"measurements": {}}

    if compile_check is None:
        def compile_check(fn, *args) -> bool:
            float(np.asarray(jax.jit(fn)(*args).ravel()[0]))
            return True

    def try_time(tag: str, fn, args) -> float | None:
        try:
            t = timer(fn, *args)
        except Exception as e:  # compile OOM / jitter-dominated — skip
            report["measurements"][tag] = {"error": repr(e)[:300]}
            log(f"# autotune {tag}: SKIPPED ({repr(e)[:120]})")
            return None
        report["measurements"][tag] = t
        log(f"# autotune {tag}: {t * 1e3:.3f} ms")
        return t

    def feasible(tag: str, bq: int, bk: int, seq: int) -> bool:
        try:
            ok = compile_check(_first_output(_flash_grad_fn(bq, bk)),
                               *_qkv(seq, head_dim=64, dtype=jnp.float32))
        except Exception as e:
            report["measurements"][tag] = {"error": repr(e)[:300]}
            log(f"# autotune {tag}: INFEASIBLE ({repr(e)[:120]})")
            return False
        report["measurements"][tag] = bool(ok)
        log(f"# autotune {tag}: {'ok' if ok else 'INFEASIBLE'}")
        return bool(ok)

    # --- short-shape tile: FLASH_BLOCK_Q / FLASH_BLOCK_K ---
    timed: list[tuple[float, tuple[int, int]]] = []
    for bq, bk in tiles:
        if short_seq % bq or short_seq % bk:
            continue
        t = try_time(f"short{short_seq}_{bq}x{bk}",
                     _first_output(_flash_grad_fn(bq, bk)), _qkv(short_seq))
        if t is not None:
            timed.append((t, (bq, bk)))
    best_tile = None
    for t, (bq, bk) in sorted(timed):
        if feasible(f"short{short_seq}_{bq}x{bk}_f32d64", bq, bk, short_seq):
            best_tile = (bq, bk)
            break
    if best_tile is None:
        raise ValueError(
            f"no usable short tile for seq {short_seq}: every candidate "
            "either does not divide the sequence, failed to measure, or "
            "failed the worst-case (f32, 64-wide heads) VMEM feasibility "
            f"probe — see the measurements report: {report['measurements']}")
    report["FLASH_BLOCK_Q"], report["FLASH_BLOCK_K"] = best_tile

    # --- long-shape KV tile: FLASH_BLOCK_K_LONG ---
    bq = report["FLASH_BLOCK_Q"]
    best_t, best_bk = float("inf"), None
    for bk in long_k_tiles:
        if long_seq % bk or long_seq % bq:
            continue
        t = try_time(f"long{long_seq}_{bq}x{bk}",
                     _first_output(_flash_grad_fn(bq, bk)), _qkv(long_seq))
        if t is None or t >= best_t:
            continue
        # A tile that only compiles at the probe shape must not be
        # written as THE constant.
        if not feasible(f"long{long_seq}_{bq}x{bk}_f32d64", bq, bk,
                        long_seq):
            continue
        best_t, best_bk = t, bk
    if best_bk is not None:
        report["FLASH_BLOCK_K_LONG"] = best_bk

    # --- routing crossover: FLASH_MIN_SEQ ---
    # Smallest seq where flash (at the winning tile, clipped to fit)
    # beats dense.  If flash never wins (or no crossover point could be
    # measured), the crossover parks above the largest probed seq so
    # routing stays dense — a failed measurement must not abort the run
    # and discard the completed tile phases.
    bq0, bk0 = best_tile
    crossover = None
    for s in sorted(crossover_seqs):
        fb_q, fb_k = min(bq0, s), min(bk0, s)
        if s % fb_q or s % fb_k:
            continue
        tf = try_time(f"crossover{s}_flash",
                      _first_output(_flash_grad_fn(fb_q, fb_k)), _qkv(s))
        td = try_time(f"crossover{s}_dense",
                      _first_output(_dense_grad_fn()), _qkv(s))
        if tf is None or td is None:
            continue
        log(f"# autotune crossover seq{s}: flash {tf * 1e3:.3f} ms "
            f"vs dense {td * 1e3:.3f} ms")
        if tf < td and crossover is None:
            crossover = s
    report["FLASH_MIN_SEQ"] = (crossover if crossover is not None
                               else max(crossover_seqs) * 2)
    return report


def write_tuned(report: dict, path=None) -> str:
    """Persist the tuned keys (measurements stay out of the file — the
    resolver wants an int table, the evidence goes to the caller/log)."""
    path = tuned_file_path() if path is None else path
    path.parent.mkdir(parents=True, exist_ok=True)
    keys = {k: v for k, v in report.items() if k.isupper()}
    meta = {"device_kind": jax.devices()[0].device_kind,
            "method": "tpudist.utils.autotune"}
    path.write_text(json.dumps({**keys, "_meta": meta}, indent=2) + "\n")
    return str(path)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dry-run", action="store_true",
                    help="measure and print, do not write the tuned file")
    ap.add_argument("--short-seq", type=int, default=2048)
    ap.add_argument("--long-seq", type=int, default=8192)
    args = ap.parse_args(argv)

    if jax.devices()[0].platform != "tpu":
        print(json.dumps({"error": "autotune needs a real TPU "
                          f"(got {jax.devices()[0].platform})"}))
        return 2
    report = autotune_flash(short_seq=args.short_seq, long_seq=args.long_seq)
    out = {k: v for k, v in report.items() if k != "measurements"}
    if not args.dry_run:
        out["written_to"] = write_tuned(report)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
