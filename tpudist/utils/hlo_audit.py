"""Compile-time collective audit: parse a compiled step's optimized HLO
and account for every cross-device collective — kind, bytes moved, replica
grouping, and whether it sits inside a loop body.

Why this exists (SURVEY.md §2.4, VERDICT r3 next-round #3): multi-chip
hardware is not available in the build environment, so runtime scaling
numbers cannot be measured here.  What CAN be established without a pod is
the *communication structure* the compiler actually emitted: a training
step whose HLO contains exactly the predicted collectives with the
predicted byte volumes has a falsifiable perf shape — DP costs one
gradient all-reduce of 2(n−1)/n × param bytes on the wire, ring attention
costs (ring−1) neighbor hops of the KV shard, MoE costs two all_to_alls of
the capacity buffer each way, and so on.  The audit turns "the sharding is
correct" into "the collectives are exactly these, moving exactly these
bytes" — the strongest scaling statement available at compile time.

The reference repo has no analog (its NCCL traffic is implicit in torch's
DDP/autograd internals); this is TPU-native observability of the same
layer the reference trusts blindly.

Usage::

    ops = collect_collectives(jitted_step, state, tokens)
    prof = profile(ops)        # {kind: {count, bytes_total, ...}}

The parser works on the *optimized* (post-SPMD-partitioner, post-fusion)
HLO so what it sees is what executes, not what was requested.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Sequence

# Cross-device collective opcodes (HLO names).  ``*-start`` forms are the
# async halves — counted as the op; their ``*-done`` twin is skipped so a
# (start, done) pair is one collective.
COLLECTIVE_KINDS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "all-to-all",
    "collective-permute",
    "collective-broadcast",
)

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1,
}

_SHAPE_RE = re.compile(r"([a-z]\w*)\[([\d,]*)\]")


def shape_bytes(shape_str: str) -> int:
    """Total bytes of every array literal in an HLO shape string.

    Handles plain shapes (``f32[4,16]{1,0}``), tuples
    (``(f32[4]{0}, bf16[2,2]{1,0})``), and skips non-array types
    (``token[]``, ``u32[]`` scalars count their element size).
    """
    total = 0
    for dtype, dims in _SHAPE_RE.findall(shape_str):
        if dtype not in _DTYPE_BYTES:
            continue  # token[] etc.
        n = 1
        if dims:
            for d in dims.split(","):
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


#: jax named_scope tag that marks a collective as part of a hand-built
#: overlap pipeline (tpudist.parallel.overlap emits every ppermute hop
#: under it; jvp/transpose ops inherit the scope in their op_name).
OVERLAP_SCOPE = "tpudist_overlap"


@dataclasses.dataclass
class CollectiveOp:
    """One collective instruction in the optimized HLO."""

    kind: str            # e.g. "all-reduce" (start forms normalized)
    name: str            # instruction name
    bytes: int           # payload bytes (result for sync, operands for start)
    computation: str     # enclosing HLO computation
    in_loop: bool        # executes inside a while loop (lax.scan body etc.)
    groups: str          # replica_groups= / source_target_pairs= text, if any
    shape: str           # the payload shape text
    op_name: str = ""    # jax op_name metadata (trace provenance)
    # Exposed-vs-overlapped classification (see classify_overlap):
    # True when the wire time is structurally hidden under compute —
    # an async start/done pair with substantive instructions between
    # the halves, or a ppermute-pipeline hop (OVERLAP_SCOPE-tagged).
    overlapped: bool = False


# instruction line:   %name = SHAPE opcode(OPERANDS), attr=..., ...
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.-]+)\s*=\s*(\([^)]*\)|\S+)\s+([\w-]+)\("
)
# computation header: [ENTRY] %name (params) -> type {
_COMP_RE = re.compile(r"^(?:ENTRY\s+)?%?([\w.-]+)\s*(?:\([^)]*\))?\s*->.*\{\s*$")
_COMP_SIMPLE_RE = re.compile(r"^%?([\w.-]+)\s*\{\s*$")
# while-instruction body reference: body=%name
_WHILE_BODY_RE = re.compile(r"body=%?([\w.-]+)")
# callee references that can nest a collective under a while body
_CALLED_RE = re.compile(
    r"(?:calls|to_apply|body|condition)=%?([\w.-]+)"
)
_BRANCHES_RE = re.compile(r"branch_computations=\{([^}]*)\}")
_OPNAME_RE = re.compile(r'op_name="([^"]*)"')
_GROUPS_RE = re.compile(
    r"((?:replica_groups|source_target_pairs)=(?:\{[^=]*?\}\}|\{[^{}]*\}|"
    r"\[[^\]]*\]<=\[[^\]]*\][^,]*))"
)


#: Opcodes that do no real work — async (start, done) pairs separated
#: only by these are NOT overlapped (nothing runs under the transfer).
_BOOKKEEPING_OPS = frozenset((
    "parameter", "constant", "get-tuple-element", "tuple", "bitcast",
    "copy", "after-all", "partition-id", "replica-id",
))

# candidate operand tokens inside an instruction's (...) argument list —
# matched against the pending-start table (dtype/shape tokens like
# ``f32`` can never collide with instruction names registered there)
_OPERAND_TOKEN_RE = re.compile(r"[\w.-]+")


def parse_collectives(hlo_text: str) -> List[CollectiveOp]:
    """Extract every collective instruction from HLO text, tagging each
    with whether it executes inside a ``while`` loop (a ``lax.scan`` /
    ``while_loop`` body) and whether it is structurally OVERLAPPED with
    compute.

    Loop residence is decided two ways, OR-ed: the jax ``op_name``
    provenance metadata contains a ``/while/`` frame (robust across XLA's
    computation outlining), or the instruction's computation is reachable
    from a ``while`` instruction's body in the call graph.

    Overlap is decided two ways, OR-ed (see :func:`overlap_split`):

    - async form: a ``*-start`` whose matching ``*-done`` has at least
      one substantive instruction (not in ``_BOOKKEEPING_OPS``) between
      the halves — XLA committed real work under the transfer;
    - pipeline form: the ``op_name`` provenance carries the
      :data:`OVERLAP_SCOPE` tag — a hand-built ppermute-pipeline hop
      (``tpudist.parallel.overlap`` emits every hop under that scope;
      jvp/transpose ops inherit it), whose chunk transfer runs against
      the neighboring chunk's matmul by construction.
    """
    ops: List[CollectiveOp] = []
    current_comp = "<module>"
    while_bodies: List[str] = []
    calls: Dict[str, List[str]] = {}
    # async pairing state, per enclosing computation: instruction name of
    # a pending -start -> (its CollectiveOp, substantive-op count at start)
    pending: Dict[str, tuple] = {}
    substantive = 0

    for line in hlo_text.splitlines():
        stripped = line.strip()
        m = _COMP_RE.match(stripped) or _COMP_SIMPLE_RE.match(stripped)
        if m and not stripped.startswith(("//", "#")) and "=" not in \
                stripped.split("(")[0]:
            current_comp = m.group(1)
            pending.clear()
            substantive = 0
            continue
        im = _INSTR_RE.match(line)
        if not im:
            continue
        name, shape, opcode = im.groups()
        if opcode.endswith("-done"):
            for tok in _OPERAND_TOKEN_RE.findall(line[im.end():]):
                started = pending.pop(tok, None)
                if started is not None:
                    op0, count0 = started
                    op0.overlapped = substantive > count0
                    break
        elif not opcode.endswith("-start") and \
                opcode not in _BOOKKEEPING_OPS:
            substantive += 1
        # Call-graph edges for loop-reachability.
        for cm in _CALLED_RE.finditer(line):
            calls.setdefault(current_comp, []).append(cm.group(1))
        bm = _BRANCHES_RE.search(line)
        if bm:
            calls.setdefault(current_comp, []).extend(
                t.strip().lstrip("%") for t in bm.group(1).split(",") if t.strip()
            )
        if opcode == "while":
            wb = _WHILE_BODY_RE.search(line)
            if wb:
                while_bodies.append(wb.group(1))
        base = opcode
        if base.endswith("-done"):
            continue  # counted at the -start
        if base.endswith("-start"):
            base = base[: -len("-start")]
        if base not in COLLECTIVE_KINDS:
            continue
        if opcode.endswith("-start"):
            # start-form result shapes carry bookkeeping tuples; measure the
            # operand payload instead.
            operands = line[im.end():].split("),")[0]
            nbytes = shape_bytes(operands)
        else:
            nbytes = shape_bytes(shape)
        gm = _GROUPS_RE.search(line)
        om = _OPNAME_RE.search(line)
        op = CollectiveOp(
            kind=base,
            name=name,
            bytes=nbytes,
            computation=current_comp,
            in_loop=False,  # resolved below
            groups=gm.group(0) if gm else "",
            shape=shape,
            op_name=om.group(1) if om else "",
        )
        if opcode.endswith("-start"):
            pending[name] = (op, substantive)
        ops.append(op)

    # Transitive closure: computations reachable from any while body are
    # loop-resident (a scan body may call fusions/conditionals that hold
    # the collective).
    looped = set()
    frontier = list(while_bodies)
    while frontier:
        c = frontier.pop()
        if c in looped:
            continue
        looped.add(c)
        frontier.extend(calls.get(c, []))
    for op in ops:
        op.in_loop = (op.computation in looped) or ("/while/" in op.op_name)
        if OVERLAP_SCOPE in op.op_name:
            op.overlapped = True
    return ops


def lower_optimized_hlo(jitted, *args, **kwargs) -> str:
    """Compile a jitted function for its example args and return the
    post-optimization HLO text (what actually executes)."""
    compiled = jitted.lower(*args, **kwargs).compile()
    return compiled.as_text()


def lower_preopt_hlo(jitted, *args, **kwargs) -> str:
    """Pre-optimization HLO (post-lowering, before backend passes) — the
    program as REQUESTED.  Needed when a backend pass rewrites what the
    audit checks: e.g. the CPU backend's all-reduce promotion re-widens a
    requested bf16 gradient all-reduce to f32 (CPU has no native bf16
    reduction), while TPU executes it at bf16 as written."""
    return jitted.lower(*args, **kwargs).compiler_ir(
        dialect="hlo").as_hlo_text()


def collect_collectives(jitted, *args, **kwargs) -> List[CollectiveOp]:
    return parse_collectives(lower_optimized_hlo(jitted, *args, **kwargs))


def profile(ops: Sequence[CollectiveOp]) -> Dict[str, dict]:
    """Group a collective list into ``{kind: {count, bytes_total,
    count_in_loop, bytes_in_loop, instructions}}`` (bytes are
    per-execution payload; loop-resident ops execute once per trip)."""
    out: Dict[str, dict] = {}
    for op in ops:
        row = out.setdefault(
            op.kind,
            {"count": 0, "bytes_total": 0, "count_in_loop": 0,
             "bytes_in_loop": 0, "instructions": []},
        )
        row["count"] += 1
        row["bytes_total"] += op.bytes
        if op.in_loop:
            row["count_in_loop"] += 1
            row["bytes_in_loop"] += op.bytes
        row["instructions"].append(
            {"name": op.name, "bytes": op.bytes, "in_loop": op.in_loop,
             "overlapped": op.overlapped, "shape": op.shape,
             "op_name": op.op_name}
        )
    return out


def overlap_split(ops: Sequence[CollectiveOp]) -> Dict[str, object]:
    """Exposed-vs-overlapped accounting over a collective list.

    *Overlapped* = structurally proven hidden under compute (async
    start/done with substantive instructions between the halves, or an
    :data:`OVERLAP_SCOPE`-tagged ppermute-pipeline hop — see
    :func:`parse_collectives`).  Everything else is *exposed*: wire time
    the step serializes on.  This is deliberately conservative — a sync
    collective the TPU scheduler happens to hide still counts exposed,
    so a drop in ``exposed_bytes`` between regimes is real structure,
    not scheduler luck.  Returns totals plus a per-kind breakdown.
    """
    out = {"exposed_bytes": 0, "overlapped_bytes": 0,
           "exposed_count": 0, "overlapped_count": 0,
           "by_kind": {}}
    for op in ops:
        kind = out["by_kind"].setdefault(
            op.kind, {"exposed_bytes": 0, "overlapped_bytes": 0,
                      "exposed_count": 0, "overlapped_count": 0})
        side = "overlapped" if op.overlapped else "exposed"
        for row in (out, kind):
            row[f"{side}_bytes"] += op.bytes
            row[f"{side}_count"] += 1
    return out


def ring_allreduce_wire_bytes(payload_bytes: int, n: int) -> int:
    """Per-device wire traffic of a ring all-reduce: 2(n−1)/n × payload
    (reduce-scatter pass + all-gather pass) — the number to compare against
    ICI/DCN bandwidth when predicting DP scaling."""
    return int(2 * (n - 1) * payload_bytes / n)


def tree_bytes(tree) -> int:
    """Total bytes of every array leaf of a pytree (analytic side of the
    audit: grad bytes == param bytes for a float tree)."""
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree.leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        total += int(np.prod(shape or (1,))) * dtype.itemsize
    return total
