"""Env-var parsing shared by the runtime knobs (watchdog deadline, host
fabric timeout, init retry/backoff).  Kept dependency-free: the watchdog
imports this and must stay importable without jax."""

from __future__ import annotations

import os
from typing import Optional


def env_float(name: str, default: Optional[float] = None) -> Optional[float]:
    """``float(os.environ[name])``, falling back to ``default`` when the
    var is unset, empty, or unparseable (a typo'd knob must never take a
    job down — the default is always a safe behavior)."""
    v = os.environ.get(name)
    if not v:
        return default
    try:
        return float(v)
    except ValueError:
        return default


def env_positive_float(name: str,
                       default: Optional[float] = None) -> Optional[float]:
    """Like :func:`env_float`, with ``<= 0`` meaning "explicitly disabled"
    (maps to ``default``) — the contract of the deadline/timeout knobs."""
    v = env_float(name, None)
    return default if v is None or v <= 0 else v


def env_int(name: str, default: Optional[int] = None) -> Optional[int]:
    """``int(os.environ[name])``, falling back to ``default`` when the var
    is unset, empty, or unparseable."""
    v = os.environ.get(name)
    if not v:
        return default
    try:
        return int(v)
    except ValueError:
        return default


def env_rank(default: Optional[int] = None) -> Optional[int]:
    """This process's global rank from the launcher env contracts, in
    precedence order (tpudist > torchrun > SLURM) — the ONE resolution
    chain shared by crash-record attribution and fault-injection gating,
    so they can never disagree about which rank a process is."""
    for var in ("TPUDIST_PROCESS_ID", "RANK", "SLURM_PROCID"):
        v = os.environ.get(var)
        if v:
            try:
                return int(v)
            except ValueError:
                continue
    return default
