"""Env-var parsing shared by the runtime knobs (watchdog deadline, host
fabric timeout, init retry/backoff).  Kept dependency-free: the watchdog
imports this and must stay importable without jax."""

from __future__ import annotations

import os
from typing import Optional


def env_float(name: str, default: Optional[float] = None) -> Optional[float]:
    """``float(os.environ[name])``, falling back to ``default`` when the
    var is unset, empty, or unparseable (a typo'd knob must never take a
    job down — the default is always a safe behavior)."""
    v = os.environ.get(name)
    if not v:
        return default
    try:
        return float(v)
    except ValueError:
        return default


def env_positive_float(name: str,
                       default: Optional[float] = None) -> Optional[float]:
    """Like :func:`env_float`, with ``<= 0`` meaning "explicitly disabled"
    (maps to ``default``) — the contract of the deadline/timeout knobs."""
    v = env_float(name, None)
    return default if v is None or v <= 0 else v


def env_int(name: str, default: Optional[int] = None) -> Optional[int]:
    """``int(os.environ[name])``, falling back to ``default`` when the var
    is unset, empty, or unparseable."""
    v = os.environ.get(name)
    if not v:
        return default
    try:
        return int(v)
    except ValueError:
        return default


def env_flag(name: str, default: bool = False) -> bool:
    """Boolean env knob: unset → ``default``; set to ``0``/``false``/
    ``off``/``no``/empty (case-insensitive) → False; anything else →
    True.  The contract of the on/off switches (``TPUDIST_TELEMETRY``)."""
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() not in ("", "0", "false", "off", "no")


def env_rank(default: Optional[int] = None) -> Optional[int]:
    """This process's global rank from the launcher env contracts, in
    precedence order (tpudist > torchrun > SLURM) — the ONE resolution
    chain shared by crash-record attribution and fault-injection gating,
    so they can never disagree about which rank a process is."""
    for var in ("TPUDIST_PROCESS_ID", "RANK", "SLURM_PROCID"):
        v = os.environ.get(var)
        if v:
            try:
                return int(v)
            except ValueError:
                continue
    return default


#: The inventory of every ``TPUDIST_*`` environment knob the package
#: reads — name → one-line contract.  This registry is the gate that
#: keeps knobs from shipping undocumented: ``tests/test_env_inventory.py``
#: asserts (a) every ``TPUDIST_*`` name referenced anywhere in the
#: package appears here, and (b) every name here is documented in
#: ``docs/ARCHITECTURE.md``.  Add the entry and the doc row with the
#: code, or the suite fails.
ENV_VARS = {
    # launch contract (set by launch/tpurun; consumed by runtime.bootstrap)
    "TPUDIST_COORDINATOR": "host:port of process 0's coordination service",
    "TPUDIST_NUM_PROCESSES": "world size of the launch contract",
    "TPUDIST_PROCESS_ID": "this process's global rank",
    "TPUDIST_LOCAL_RANK": "rank within the node",
    "TPUDIST_LOCAL_WORLD_SIZE": "processes per node",
    "TPUDIST_RUN_ID": "job-scoped rendezvous/run id",
    "TPUDIST_RESTART_COUNT": "tpurun restart generation (0 on first launch)",
    "TPUDIST_ERROR_FILE": "crash-record path template (%r → rank)",
    "TPUDIST_TMPDIR": "job-local scratch directory",
    # robustness knobs
    "TPUDIST_WATCHDOG_S": "hang-watchdog stall deadline in seconds (<=0 off)",
    "TPUDIST_HOST_TIMEOUT_S": "host-fabric collective deadline in seconds",
    "TPUDIST_INIT_RETRIES": "jax.distributed.initialize retry budget",
    "TPUDIST_INIT_BACKOFF_S": "initialize retry base backoff seconds",
    "TPUDIST_FAULT": "chaos fault-injection grammar (runtime.faults)",
    # serving (tpudist.serve — ServeConfig.from_env)
    "TPUDIST_SERVE_SLOTS": "continuous-batching KV-cache slot count",
    "TPUDIST_SERVE_QUEUE": "serving request-queue bound (backpressure)",
    "TPUDIST_SERVE_MAX_NEW": "default per-request output-token budget",
    "TPUDIST_SERVE_PREFILL_PAD": "prefill chunk length (pad per compiled chunk)",
    "TPUDIST_SERVE_DEADLINE_S": "default per-request deadline seconds (<=0 off)",
    "TPUDIST_SERVE_DECODE_BLOCK": "max fused decode tokens per dispatch (K)",
    "TPUDIST_SERVE_PAGED": "paged KV cache: block pool + per-slot tables",
    "TPUDIST_SERVE_KV_BLOCK": "tokens per KV block (must divide max_len)",
    "TPUDIST_SERVE_KV_BLOCKS": "KV pool size in blocks (default: dense-equivalent)",
    "TPUDIST_SERVE_KV_INT8": "int8 KV storage with per-block dequant scales",
    "TPUDIST_SERVE_PREFIX_CACHE": "shared-prefix LRU cache bound in blocks (0 off)",
    "TPUDIST_SERVE_ATTN_KERNEL":
        "decode attention on the paged cache: gather (dense view per "
        "dispatch) | paged (Pallas kernel, in-kernel block-table walk)",
    "TPUDIST_SERVE_PREFILL_KERNEL":
        "paged-prefill flash kernel: block table walked AND written "
        "in-kernel (requires TPUDIST_SERVE_PAGED)",
    "TPUDIST_SERVE_SAMPLE_KERNEL":
        "fused in-kernel sampling tail: temperature + top-k/top-p + "
        "grammar mask + greedy argmax in one pass",
    "TPUDIST_SERVE_FUSED_ROPE":
        "fused RoPE+QKV projection kernel on the kernel arms "
        "(requires ATTN_KERNEL=paged and/or PREFILL_KERNEL)",
    "TPUDIST_SERVE_LORA_KERNEL":
        "in-kernel LoRA gather-matmul from the adapter pool "
        "(requires ADAPTERS and a kernel arm)",
    "TPUDIST_SERVE_MESH":
        "serving mesh shape 'DxM' (data x model; '1' = single device)",
    "TPUDIST_SERVE_TP_OVERLAP":
        "TP decode collective-matmul routing: off|ring|bidir "
        "(falls back to TPUDIST_OVERLAP)",
    "TPUDIST_SERVE_DISAGG": "prefill/decode disaggregation (separate pools)",
    "TPUDIST_SERVE_PREFILL_WORKERS": "prefill-pool worker count (disagg)",
    "TPUDIST_SERVE_DECODE_WORKERS": "decode-pool worker count (disagg)",
    "TPUDIST_SERVE_PREFILL_SLOTS":
        "slots per prefill worker (disagg; default: the decode slot count)",
    "TPUDIST_SERVE_HANDOFF":
        "KV handoff transport: device (in-mesh) | serial (byte transfer)",
    "TPUDIST_SERVE_HANDOFF_QUEUE": "bounded pending-KV-handoff queue length",
    "TPUDIST_SERVE_RECOVER":
        "self-healing disagg fleet: dead-worker lanes replay on survivors "
        "(default on; 0 = worker death aborts outstanding work)",
    "TPUDIST_SERVE_POOL_RESIZE":
        "iterations of sustained handoff-queue backpressure before the "
        "prefill slot budget shrinks by one (0 = off)",
    "TPUDIST_SERVE_HEALTH_STALE_S":
        "/healthz engine-heartbeat staleness threshold in seconds "
        "(default 300 — must exceed the first-dispatch XLA compile)",
    # host-RAM KV tier + overload control (serve/host_tier.py, overload.py)
    "TPUDIST_SERVE_HOST_TIER":
        "host-RAM KV session tier: park idle/preempted lanes in host "
        "memory, resume without recompute (default off)",
    "TPUDIST_HOST_TIER_BYTES":
        "host-tier byte budget (default 1 GiB; LRU spill beyond it)",
    "TPUDIST_HOST_TIER_TTL_S":
        "idle parked-session expiry in seconds (<=0/unset = LRU only)",
    "TPUDIST_SERVE_PREEMPT":
        "priority preemption: a higher-priority arrival parks a "
        "lower-priority decode lane in the host tier (default on; "
        "effective only with the host tier enabled)",
    "TPUDIST_SERVE_SHED":
        "SLO-aware load shedding off the live per-tenant attainment "
        "gauges (default off; needs TPUDIST_SLO_* targets + metrics)",
    "TPUDIST_SERVE_SHED_ATTAINMENT":
        "protected-class attainment floor that trips shedding "
        "(default 0.9)",
    "TPUDIST_SERVE_SHED_PRIORITY":
        "protected priority class: requests at or above it are never "
        "shed (default 1)",
    "TPUDIST_SERVE_FAIR_SHARE":
        "per-tenant token-rate fairness multiplier — reject a tenant "
        "above this multiple of its equal share once the queue is half "
        "full (0/unset = off)",
    # fleet router (serve/router.py — RouterConfig.from_env)
    "TPUDIST_ROUTER_REPLICAS":
        "fleet size for env-driven multi-replica rigs (default 2; the "
        "router itself takes an explicit replica list)",
    "TPUDIST_ROUTER_PROBE_S":
        "per-replica health-probe interval in seconds (default 0.05)",
    "TPUDIST_ROUTER_PROBE_FAILURES":
        "consecutive probe failures before a replica is marked dead "
        "(default 3; dead replicas re-probe on exponential backoff)",
    "TPUDIST_ROUTER_RETRIES":
        "per-request re-home budget after a replica dies mid-serve "
        "(default 2; exhaustion finishes the request replica_lost)",
    "TPUDIST_ROUTER_RETRY_BACKOFF_S":
        "re-home retry backoff base in seconds (default 0.05; doubles "
        "per failed attempt)",
    "TPUDIST_ROUTER_SPILL":
        "overflow spills to a sibling replica (paying a re-prefill) "
        "instead of rejecting while any replica has headroom "
        "(default on; 0 = reject on the affinity target's answer)",
    "TPUDIST_ROUTER_STASH":
        "router-side parked-package stash: finished session turns are "
        "exported so replica death migrates the session to a survivor "
        "(default on; 0 = death degrades sessions to full re-prefill)",
    "TPUDIST_ROUTER_POLICY":
        "routing policy: affinity (session -> prefix -> least-loaded, "
        "default) | rr (round-robin comparison arm)",
    # per-tenant adapters (serve/adapters.py + models/lora.py)
    "TPUDIST_SERVE_ADAPTERS":
        "per-tenant adapters: paged multi-LoRA factor pool + per-slot "
        "adapter ids, batched gathered decode (default off)",
    "TPUDIST_SERVE_ADAPTER_BLOCKS":
        "adapter-pool capacity in blocks — one resident adapter each "
        "(default 8; LRU-evicts cold adapters on load)",
    "TPUDIST_SERVE_ADAPTER_RANK":
        "LoRA rank r shared by every adapter in the pool (default 8)",
    # measurement-driven planner (tpudist/plan/)
    "TPUDIST_SERVE_AUTO":
        "env spelling of ServeConfig.auto: plan unpinned serving knobs "
        "against the frozen measurement artifacts (default off)",
    "TPUDIST_PLAN_DIR":
        "planner artifact directory (default: the repo root, where "
        "round_snapshot freezes *_rNN.json)",
    "TPUDIST_PLAN_TOPN":
        "rows the plan report prints per workload (default 0 = all)",
    "TPUDIST_PLAN_STALE_ROUNDS":
        "rounds behind the newest artifact before a family is rejected "
        "as stale evidence (default 20)",
    "TPUDIST_PLAN_STRICT":
        "1 = missing/rejected artifact families raise PlanArtifactError "
        "instead of degrading to the analytic model (default off)",
    # structured output (tpudist/constrain/)
    "TPUDIST_SERVE_CONSTRAIN":
        "structured output: per-request grammar/json_schema asks compile "
        "to token FSAs masking decode in-graph (default off)",
    "TPUDIST_CONSTRAIN_BLOCKS":
        "grammar-pool capacity in table blocks — one resident compiled "
        "grammar each (default 4; LRU-evicts unpinned grammars)",
    "TPUDIST_CONSTRAIN_STATES":
        "automaton state cap per compiled grammar — fixes the dense "
        "mask/transition table height (default 64; bigger grammars "
        "reject invalid_grammar)",
    "TPUDIST_SERVE_LOGPROBS":
        "engine-wide top-n logprobs width per emitted token (default 0 "
        "= off; per-request submit(logprobs=n) asks are slices of it)",
    "TPUDIST_SERVE_SPEC":
        "speculative decoding: draft proposes K, target verifies in one pass",
    "TPUDIST_SERVE_SPEC_K": "drafted tokens per speculative block",
    "TPUDIST_SERVE_SPEC_DRAFT_LAYERS":
        "tied-draft depth (target's first N layers; 0 = half the depth)",
    # online draft distillation (tpudist/distill/)
    "TPUDIST_DISTILL_CAPTURE":
        "live-traffic capture ring for draft distillation (default off; "
        "1 = tap finished streams into the bounded buffer)",
    "TPUDIST_DISTILL_BUFFER_TOKENS":
        "capture-ring token budget — oldest streams evict past it "
        "(default 65536)",
    "TPUDIST_DISTILL_SAMPLE":
        "capture every Nth finished stream (default 1 = all; sampled-out "
        "streams are counted, never silently dropped)",
    "TPUDIST_DISTILL_INTERVAL_S":
        "background distillation round cadence in seconds (default 30)",
    "TPUDIST_DISTILL_STEPS":
        "trainer steps per distillation round (default 40)",
    "TPUDIST_DISTILL_MIN_TOKENS":
        "captured-token floor before a round will train (default 256)",
    "TPUDIST_DISTILL_HOLDOUT":
        "held-out fraction of captured streams reserved for the swap "
        "gate's acceptance eval (default 0.25)",
    "TPUDIST_DISTILL_SWAP_MARGIN":
        "hysteresis: candidate must beat the serving draft's measured "
        "acceptance by this margin to hot-swap (default 0.02)",
    "TPUDIST_DISTILL_LR":
        "distillation learning rate (default 3e-3)",
    "TPUDIST_DISTILL_PER_ADAPTER":
        "bias rounds toward the heaviest captured adapter when it is "
        "resident in the adapter registry (default off)",
    # telemetry & goodput
    "TPUDIST_TELEMETRY": "telemetry arm switch (default on; 0/false = off)",
    "TPUDIST_TELEMETRY_DIR": "where per-rank telemetry JSONL + reports land",
    "TPUDIST_TELEMETRY_RING": "in-memory telemetry ring size (records)",
    # live observability plane (metrics / trace / statusz)
    "TPUDIST_METRICS":
        "live metrics registry feed from the span/event seams "
        "(default on; 0 = post-hoc telemetry only)",
    "TPUDIST_METRICS_PORT":
        "scrape endpoint port for /metrics /healthz /statusz "
        "(unset = off; 0 = ephemeral port for CI)",
    "TPUDIST_METRICS_ADDR":
        "scrape endpoint bind address (default 127.0.0.1 — the "
        "documents are unauthenticated; 0.0.0.0 is an explicit opt-in)",
    "TPUDIST_TRACE":
        "per-request trace lifeline spans (req_queue/req_prefill/"
        "req_handoff/req_decode; default on; 0 = trace_ids only)",
    "TPUDIST_SLO_TTFT_MS":
        "declared time-to-first-token SLO target in ms (<=0/unset = "
        "none) -> live attainment gauges + report slo section",
    "TPUDIST_SLO_TPOT_MS":
        "declared time-per-output-token SLO target in ms (<=0/unset = "
        "none) -> live attainment gauges + report slo section",
    # parallel execution strategy
    "TPUDIST_OVERLAP":
        "collective-matmul overlap mode: off|ring|bidir (default off)",
    # caches / tuned constants
    "TPUDIST_COMPILATION_CACHE": "persistent XLA compile cache dir (off = disable)",
    "TPUDIST_CACHE": "native data-loader build cache base dir",
    "TPUDIST_TUNED_FILE": "measured tuned-constants JSON path override",
    "TPUDIST_SYNC_EVERY": "train-loop scan window / metric sync cadence",
    "TPUDIST_FLASH_MIN_SEQ": "flash-attention routing crossover (seq len)",
    "TPUDIST_FLASH_BLOCK_Q": "flash-attention query tile size",
    "TPUDIST_FLASH_BLOCK_K": "flash-attention KV tile size",
    "TPUDIST_FLASH_BLOCK_K_LONG": "flash-attention KV tile at long seq",
    "TPUDIST_FLASH_LONG_SEQ": "seq length where the long KV tile kicks in",
    # sweep harness contract (launch/sweep.py)
    "TPUDIST_SWEEP_METRIC_FILE": "where a sweep trial writes its objective",
    "TPUDIST_SWEEP_RESULTS": "sweep results.jsonl path for the report CLI",
    "TPUDIST_SWEEP_INDEX": "trial index within the sweep",
    "TPUDIST_SWEEP_CONFIG": "the trial's resolved config (repr)",
}
