"""Per-request cross-pool tracing: one ``trace_id`` per request, minted
at ``submit()`` and threaded through everything the request touches —
admission, prefill slots, the serialized KV-handoff package
(``schema_version`` 3 carries it on the wire), decode lanes, recovery
replays, and ``request_finished`` — so the post-hoc aggregator can JOIN
a request's records across pools and processes, and a Perfetto-loadable
timeline can show one request's lifeline crossing
prefill pool → handoff queue → decode pool (and, after a worker death,
the replay jumping to the survivor).

Recording model: the serving loops already hold every per-request
timestamp on the :class:`~tpudist.serve.scheduler.RequestHandle`
(submit/admit/prefill-done/decode-start/first-token/done, plus the
per-worker decode segments the disagg recovery path appends).  At
finish time :func:`emit_request_lifeline` turns those stamps into a
handful of ``req_*`` spans tagged with the trace_id:

- ``req_queue``     submit → admission (the queue wait)
- ``req_prefill``   admission → prompt done (token 0 sampled)
- ``req_handoff``   prefill done → decode slot installed (disagg only)
- ``req_decode``    one span PER DECODE SEGMENT — a lane that replayed
  onto a survivor after ``worker_lost`` gets one span per worker, which
  is exactly the visible "jump" in the exported timeline

Every lifeline span carries ``parent="request"`` so the goodput
accounting keeps treating them as detail (they re-describe wall-clock
the ``prefill``/``decode_block`` spans already account); old streams
without them aggregate byte-identically.

``TPUDIST_TRACE=0`` disarms lifeline emission (trace_ids still mint —
a 16-hex id per request is noise-level); the observability bench
measures the armed cost (``BENCH_OBS``).

:func:`export_chrome_trace` renders the joined records as Chrome
trace-event JSON (Perfetto/chrome://tracing loadable): one process row
per (rank, pool), one thread row per worker, complete ("X") events for
the lifeline spans, instant events for ``lane_recovered``, and flow
arrows ("s"/"t"/"f") stitching each trace_id across rows.

Stdlib-only; importable without jax.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Dict, List, Optional, Tuple

ENV_TRACE = "TPUDIST_TRACE"


def enabled_from_env() -> bool:
    """Lifeline emission is armed by default whenever telemetry is;
    ``TPUDIST_TRACE=0`` disarms just the per-request spans."""
    from tpudist.utils.envutil import env_flag

    return env_flag(ENV_TRACE, True)


#: Cached arm flag — the emitter runs on the serving loop's finish path
#: and must not re-read the environment per request (the metrics._SLO
#: discipline).  Refreshed by :func:`arm_from_env`, which
#: ``metrics.arm_from_env`` (and through it every session construction)
#: calls.
_ARMED = True


def arm_from_env() -> bool:
    global _ARMED
    _ARMED = enabled_from_env()
    return _ARMED


def new_trace_id() -> str:
    """16 hex chars of OS entropy — unique across processes/pools
    without coordination (the property the cross-pool join needs)."""
    return os.urandom(8).hex()


# -- lifeline emission (called by the serving loops at request finish) -------

def emit_request_lifeline(handle) -> None:
    """Emit the ``req_*`` spans for a finished request from its
    handle's timestamps (module doc).  No-op when telemetry is
    disarmed, ``TPUDIST_TRACE=0``, or the handle never got admitted.
    Never raises — observability must not take the serving loop down."""
    from tpudist.telemetry import spans

    s = spans.active()
    if s is None or not _ARMED:
        return
    try:
        _emit_lifeline(s, handle)
    except Exception:
        pass


def _emit_lifeline(s, h) -> None:
    tid = getattr(h, "trace_id", None)
    if not tid:
        return
    req = h.request
    base = {"trace_id": tid}
    tenant = getattr(req, "tenant", None)
    if tenant:
        base["tenant"] = tenant

    def span(name: str, t0: Optional[float], t1: Optional[float], **tags):
        if t0 is None or t1 is None:
            return
        tags = {k: v for k, v in tags.items() if v is not None}
        s.record_span(name, t0, max(0.0, t1 - t0), {**base, **tags},
                      parent="request")

    span("req_queue", h.t_submit, h.t_admitted)
    if h.t_prefill_done is not None:
        # disaggregated path: prefill pool → handoff → decode pool
        span("req_prefill", h.t_admitted, h.t_prefill_done,
             worker=getattr(h, "prefill_worker", None))
        span("req_handoff", h.t_prefill_done,
             h.t_decode_start if h.t_decode_start is not None else h.t_done)
        segs = getattr(h, "decode_segments", None) or []
        for worker, t0, t1 in segs:
            span("req_decode", t0, t1 if t1 is not None else h.t_done,
                 worker=worker)
    else:
        # single-pool path: prefill ends at token 0
        span("req_prefill", h.t_admitted, h.t_first_token)
        span("req_decode", h.t_first_token, h.t_done)


# -- cross-pool join ----------------------------------------------------------

def join_traces(records: List[dict]) -> Dict[str, List[dict]]:
    """Group records by ``trace_id`` (spans AND events — the recovery
    ``lane_recovered`` markers ride along), each trace's records sorted
    on the shared wall-clock axis.  This is the aggregator-side join:
    records from different ranks/pools/generations land in one lifeline
    because the trace_id crossed the process boundary in the handoff
    package."""
    by: Dict[str, List[dict]] = {}
    for r in records:
        tid = r.get("trace_id")
        if isinstance(tid, str) and tid:
            by.setdefault(tid, []).append(r)
    for recs in by.values():
        recs.sort(key=lambda r: float(r.get("t", 0.0)))
    return by


# -- Chrome trace export ------------------------------------------------------

#: Track (pid) assignment: the lifeline names map onto the pool a
#: request was in at that moment.
_POOL_OF_SPAN = {
    "req_queue": "admission queue",
    "req_prefill": "prefill pool",
    "req_handoff": "handoff queue",
    "req_decode": "decode pool",
}


def to_chrome_trace(records: List[dict]) -> dict:
    """Render joined per-request records as Chrome trace-event JSON
    (module doc).  Only trace_id-tagged records contribute; a stream
    without any yields an empty (but still loadable) trace."""
    traces = join_traces(records)
    events: List[dict] = []
    pids: Dict[Tuple[int, str], int] = {}
    tids_named = set()

    def pid_of(rank: int, pool: str) -> int:
        key = (rank, pool)
        if key not in pids:
            pids[key] = len(pids) + 1
            events.append({"ph": "M", "name": "process_name",
                           "pid": pids[key], "tid": 0,
                           "args": {"name": f"{pool} (rank {rank})"}})
        return pids[key]

    def tid_of(pid: int, worker) -> int:
        tid = int(worker) if isinstance(worker, int) else 0
        if (pid, tid) not in tids_named:
            tids_named.add((pid, tid))
            events.append({"ph": "M", "name": "thread_name",
                           "pid": pid, "tid": tid,
                           "args": {"name": f"worker {tid}"}})
        return tid

    for tid_hex, recs in sorted(traces.items()):
        flow_id = int(tid_hex[:8], 16) or 1
        slices = []
        for r in recs:
            rank = int(r.get("rank", 0))
            ts_us = float(r.get("t", 0.0)) * 1e6
            if r.get("kind") == "span" and r.get("name") in _POOL_OF_SPAN:
                pid = pid_of(rank, _POOL_OF_SPAN[r["name"]])
                tid = tid_of(pid, r.get("worker"))
                args = {k: v for k, v in r.items()
                        if k not in ("kind", "t", "dur", "parent")}
                events.append({
                    "ph": "X", "name": r["name"], "cat": "request",
                    "pid": pid, "tid": tid, "ts": ts_us,
                    "dur": max(0.001, float(r.get("dur", 0.0)) * 1e6),
                    "args": args,
                })
                slices.append((ts_us, pid, tid))
            elif r.get("kind") == "event" and r.get("name") == "lane_recovered":
                pool = r.get("pool")
                pool = f"{pool} pool" if isinstance(pool, str) else "decode pool"
                pid = pid_of(rank, pool)
                tid = tid_of(pid, r.get("worker"))
                events.append({
                    "ph": "i", "name": "lane_recovered", "cat": "recovery",
                    "pid": pid, "tid": tid, "ts": ts_us, "s": "p",
                    "args": {k: v for k, v in r.items()
                             if k not in ("kind", "t", "dur")},
                })
        # flow arrows: stitch the lifeline across tracks in slice order
        for i, (ts_us, pid, tid) in enumerate(slices):
            ph = "s" if i == 0 else ("f" if i == len(slices) - 1 else "t")
            if len(slices) < 2:
                break
            ev = {"ph": ph, "name": "request", "cat": "request",
                  "id": flow_id, "pid": pid, "tid": tid,
                  # land the flow binding INSIDE the slice it decorates
                  "ts": ts_us + 0.0005}
            if ph == "f":
                ev["bp"] = "e"
            events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"tool": "tpudist.telemetry.trace",
                          "traces": len(traces)}}


def export_chrome_trace(run_dir: "str | Path",
                        out_path: "str | Path | None" = None) -> Path:
    """Aggregate a run's telemetry JSONL and write the Perfetto-loadable
    Chrome trace next to it (default ``<telemetry dir>/trace.json``).
    Returns the written path."""
    from tpudist.telemetry.aggregate import find_telemetry_dir, load_records

    tdir = find_telemetry_dir(run_dir)
    records = load_records(tdir)
    trace = to_chrome_trace(records)
    out = Path(out_path) if out_path is not None else tdir / "trace.json"
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(trace) + "\n")
    return out
