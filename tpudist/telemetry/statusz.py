"""Scrape endpoints: ``/metrics``, ``/healthz``, ``/statusz`` over a
stdlib HTTP server — the fleet-facing face of the live observability
plane.

Arm with ``TPUDIST_METRICS_PORT`` (unset = off; ``0`` = ephemeral port,
the CI/smoke-test mode — read the bound port back from
``active().port``).  Binds loopback by default — the documents below
carry process internals with no auth, so serving them beyond the host
is an explicit operator decision (``TPUDIST_METRICS_ADDR=0.0.0.0`` for
a fleet scraper).  One endpoint per process, shared by every component
that registers into it:

- ``/metrics`` — Prometheus text exposition (format 0.0.4) of the
  process-wide registry (:mod:`tpudist.telemetry.metrics`): request
  latency sketches, token counters, occupancy/KV gauges, SLO
  attainment, telemetry-drop counters;
- ``/healthz`` — liveness that actually means something: every
  registered health check must pass (engine-thread alive AND no
  ``serve_loop_error`` AND a fresh loop heartbeat; watchdog freshness
  when a watchdog is armed), else **503** with the failing check named
  in the JSON body.  An HTTP thread that answers while the engine loop
  is dead is precisely the failure mode this refuses to hide;
- ``/statusz`` — one JSON document of current state from every
  registered provider: slot occupancy, KV pool bytes/occupancy,
  handoff queue depth, world size + generation, per-tenant in-flight,
  telemetry drop counts.

Registration: components call :func:`register_health` /
:func:`register_status` with a name and a zero-arg callable (health
returns ``(ok, detail_dict)``; status returns a JSON-safe dict) and
:func:`unregister` on close.  Names deduplicate (``serve``,
``serve-2``, …) so multiple servers in one process — a test rig, a
disagg coordinator next to a trainer — coexist on one port.

Failure posture: observability must never take the job down.  A busy
port warns and disables the endpoint; a provider that raises reports
``{"error": ...}`` for its section (and fails its health check) instead
of 500ing the scrape.

Stdlib-only (``http.server`` + daemon thread); importable without jax.
"""

from __future__ import annotations

import json
import os
import threading
import time
import warnings
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple

ENV_PORT = "TPUDIST_METRICS_PORT"
#: Bind address; defaults to loopback — the endpoint serves process
#: internals (paths, tenants, topology) with no auth, so exposing it
#: beyond the host is an explicit operator decision ("0.0.0.0" for a
#: real Prometheus scraper on the fleet network).
ENV_ADDR = "TPUDIST_METRICS_ADDR"
DEFAULT_ADDR = "127.0.0.1"

#: health check: () -> (ok, JSON-safe detail dict)
HealthFn = Callable[[], Tuple[bool, dict]]
#: status provider: () -> JSON-safe dict
StatusFn = Callable[[], dict]


class _Handler(BaseHTTPRequestHandler):
    server_version = "tpudist-statusz"

    def log_message(self, fmt, *args):  # noqa: D102 — silence per-scrape logs
        pass

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        owner: "StatuszServer" = self.server.owner  # type: ignore[attr-defined]
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            from tpudist.telemetry import metrics

            body = metrics.registry().render_prometheus().encode()
            self._reply(200, body, "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/healthz":
            code, doc = owner.healthz()
            self._reply(code, (json.dumps(doc, indent=1) + "\n").encode(),
                        "application/json")
        elif path in ("/statusz", "/"):
            doc = owner.statusz()
            self._reply(200, (json.dumps(doc, indent=1, default=str)
                              + "\n").encode(), "application/json")
        else:
            self._reply(404, b"not found\n", "text/plain")

    def _reply(self, code: int, body: bytes, ctype: str) -> None:
        try:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except (OSError, ValueError):
            pass  # client went away mid-scrape: not our problem


class StatuszServer:
    """The endpoint: a ThreadingHTTPServer on a daemon thread plus the
    named health/status provider registries."""

    def __init__(self, port: int, host: Optional[str] = None):
        if host is None:
            host = os.environ.get(ENV_ADDR, "").strip() or DEFAULT_ADDR
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.owner = self  # type: ignore[attr-defined]
        self._httpd.daemon_threads = True
        #: the BOUND port (differs from the request when port=0)
        self.port: int = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._health: Dict[str, HealthFn] = {}
        self._status: Dict[str, StatusFn] = {}
        self._t0 = time.monotonic()

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "StatuszServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name=f"tpudist-statusz[:{self.port}]", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        t, self._thread = self._thread, None
        if t is not None:
            self._httpd.shutdown()
            t.join(timeout=5.0)
        self._httpd.server_close()

    # -- registration -------------------------------------------------------

    def _dedup(self, table: Dict[str, object], name: str) -> str:
        if name not in table:
            return name
        i = 2
        while f"{name}-{i}" in table:
            i += 1
        return f"{name}-{i}"

    def register_health(self, name: str, fn: HealthFn) -> str:
        """Add a health check; returns the (possibly deduplicated) name
        to pass to :meth:`unregister`."""
        with self._lock:
            name = self._dedup(self._health, name)
            self._health[name] = fn
            return name

    def register_status(self, name: str, fn: StatusFn) -> str:
        with self._lock:
            name = self._dedup(self._status, name)
            self._status[name] = fn
            return name

    def unregister(self, name: str) -> None:
        """Remove ``name`` from both registries (idempotent)."""
        with self._lock:
            self._health.pop(name, None)
            self._status.pop(name, None)

    # -- documents ----------------------------------------------------------

    def healthz(self) -> Tuple[int, dict]:
        """(status_code, body): 200 only when EVERY registered check
        passes; a raising check counts as failed, named in the body."""
        checks: Dict[str, dict] = {}
        ok = True
        for name, fn in sorted(dict(self._health).items()):
            try:
                good, detail = fn()
            except Exception as e:  # a broken check is an unhealthy check
                good, detail = False, {"error": repr(e)}
            ok &= bool(good)
            checks[name] = {"ok": bool(good), **(detail or {})}
        return (200 if ok else 503), {"ok": ok, "checks": checks}

    def statusz(self) -> dict:
        doc: Dict[str, dict] = {}
        for name, fn in sorted(dict(self._status).items()):
            try:
                doc[name] = fn()
            except Exception as e:
                doc[name] = {"error": repr(e)}
        return doc


# -- module-level singleton ---------------------------------------------------

_SERVER: Optional[StatuszServer] = None
_lock = threading.Lock()


def active() -> Optional[StatuszServer]:
    return _SERVER


def ensure_started(port: Optional[int] = None) -> Optional[StatuszServer]:
    """Start the process's endpoint if ``TPUDIST_METRICS_PORT`` (or an
    explicit ``port``) says so; idempotent — later callers get the same
    instance and just register their providers.  Returns ``None`` when
    the endpoint is off or could not bind (warned, never fatal)."""
    global _SERVER
    with _lock:
        if _SERVER is not None:
            return _SERVER
        if port is None:
            raw = os.environ.get(ENV_PORT)
            if raw is None or not raw.strip():
                return None
            try:
                port = int(raw)
            except ValueError:
                warnings.warn(
                    f"{ENV_PORT}={raw!r} is not an integer; scrape "
                    f"endpoint disabled", RuntimeWarning, stacklevel=2)
                return None
        try:
            srv = StatuszServer(port).start()
        except OSError as e:
            warnings.warn(
                f"tpudist.telemetry.statusz: could not bind port {port} "
                f"({e}); scrape endpoint disabled", RuntimeWarning,
                stacklevel=2)
            return None
        _register_defaults(srv)
        _SERVER = srv
        return srv


def _register_defaults(srv: StatuszServer) -> None:
    """Built-in providers every process gets: process identity/uptime,
    watchdog freshness (when a watchdog is armed), and telemetry
    session drop accounting."""
    def _process() -> dict:
        from tpudist.utils.envutil import env_int, env_rank

        return {
            "pid": os.getpid(),
            "uptime_s": round(time.monotonic() - srv._t0, 3),
            "rank": env_rank(0),
            "world": env_int("TPUDIST_NUM_PROCESSES", None),
            "generation": env_int("TPUDIST_RESTART_COUNT", 0),
        }

    def _telemetry() -> dict:
        from tpudist.telemetry import spans

        s = spans.active()
        if s is None:
            return {"session": None}
        return {
            "session": str(s.path),
            "rank": s.rank,
            "generation": s.generation,
            "ring_len": len(s.ring),
            "dropped": dict(s.dropped),
        }

    def _watchdog_health() -> Tuple[bool, dict]:
        from tpudist.runtime import watchdog

        fresh = watchdog.freshness()
        ok = all(v["fresh"] for v in fresh.values())  # vacuously healthy
        return ok, {"watchdogs": fresh}

    srv.register_status("process", _process)
    srv.register_status("telemetry", _telemetry)
    srv.register_health("watchdog", _watchdog_health)


def stop() -> None:
    """Tear the singleton down (tests / embedding callers)."""
    global _SERVER
    with _lock:
        srv, _SERVER = _SERVER, None
    if srv is not None:
        srv.stop()
