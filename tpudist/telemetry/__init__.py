"""Telemetry & goodput subsystem.

Production TPU training lives or dies on goodput accounting: what fraction
of wall-clock went to useful step compute versus compilation, data stalls,
checkpoint I/O, and restart overhead.  This package makes the framework
attribute its own wall-clock:

- :mod:`tpudist.telemetry.spans` — a low-overhead span/event API
  (``span("step")``, ``span("ckpt_save")``, ``event("watchdog_stall")``)
  recording monotonic start/duration per rank into a bounded in-memory
  ring and streaming to a per-rank, per-generation ``telemetry`` JSONL.
- :mod:`tpudist.telemetry.aggregate` — merges every rank's (and every
  restarted process generation's) JSONL into ``report.json`` +
  ``report.md``: step-time p50/p95/max, a goodput breakdown
  (step / compile / data / ckpt / comm / init / other / idle /
  lost_restart) that sums to wall-clock, per-rank stragglers, and the
  joined fault/watchdog/restart event log.
- ``python -m tpudist.telemetry report <run_dir>`` — the post-hoc CLI.

The LIVE half (this is what a fleet scrapes mid-run):

- :mod:`tpudist.telemetry.metrics` — lock-light in-process registry of
  counters, gauges, and mergeable log-bucket quantile sketches, fed
  from the same span/event seams (``TPUDIST_METRICS`` gates the feed),
  plus SLO attainment from declared ``TPUDIST_SLO_TTFT_MS`` /
  ``TPUDIST_SLO_TPOT_MS`` targets;
- :mod:`tpudist.telemetry.trace` — per-request ``trace_id`` lifelines
  joined across pools/processes, exported as a Perfetto-loadable
  Chrome trace (``python -m tpudist.telemetry trace <run_dir>``);
- :mod:`tpudist.telemetry.statusz` — ``/metrics`` (Prometheus text),
  ``/healthz`` (engine-loop liveness + watchdog freshness), and
  ``/statusz`` (JSON state) on ``TPUDIST_METRICS_PORT``.

Armed by default; ``TPUDIST_TELEMETRY=0`` disarms it — the disarmed cost
at every span site is one module-attribute load and a ``None`` check
(same discipline as :mod:`tpudist.runtime.faults`).  The whole package is
importable without jax (rank/generation come from the launcher env
contract), so the watchdog and fault registry can emit events from any
process state.
"""

from tpudist.telemetry.spans import (  # noqa: F401
    DEFAULT_DIR,
    ENV_DIR,
    ENV_ENABLE,
    ENV_RING,
    TelemetrySession,
    abandon,
    active,
    enabled_from_env,
    ensure_started,
    event,
    finish,
    flush,
    span,
    start,
)
from tpudist.telemetry.aggregate import (  # noqa: F401
    aggregate_run,
    load_records,
    render_markdown,
    write_reports,
)

# The live plane (metrics/trace/statusz) is imported lazily by its
# consumers — `from tpudist.telemetry import metrics` etc. — so the
# spans hot path never pays for modules it is not using.
