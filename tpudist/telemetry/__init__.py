"""Telemetry & goodput subsystem.

Production TPU training lives or dies on goodput accounting: what fraction
of wall-clock went to useful step compute versus compilation, data stalls,
checkpoint I/O, and restart overhead.  This package makes the framework
attribute its own wall-clock:

- :mod:`tpudist.telemetry.spans` — a low-overhead span/event API
  (``span("step")``, ``span("ckpt_save")``, ``event("watchdog_stall")``)
  recording monotonic start/duration per rank into a bounded in-memory
  ring and streaming to a per-rank, per-generation ``telemetry`` JSONL.
- :mod:`tpudist.telemetry.aggregate` — merges every rank's (and every
  restarted process generation's) JSONL into ``report.json`` +
  ``report.md``: step-time p50/p95/max, a goodput breakdown
  (step / compile / data / ckpt / comm / init / other / idle /
  lost_restart) that sums to wall-clock, per-rank stragglers, and the
  joined fault/watchdog/restart event log.
- ``python -m tpudist.telemetry report <run_dir>`` — the post-hoc CLI.

Armed by default; ``TPUDIST_TELEMETRY=0`` disarms it — the disarmed cost
at every span site is one module-attribute load and a ``None`` check
(same discipline as :mod:`tpudist.runtime.faults`).  The whole package is
importable without jax (rank/generation come from the launcher env
contract), so the watchdog and fault registry can emit events from any
process state.
"""

from tpudist.telemetry.spans import (  # noqa: F401
    DEFAULT_DIR,
    ENV_DIR,
    ENV_ENABLE,
    ENV_RING,
    TelemetrySession,
    abandon,
    active,
    enabled_from_env,
    ensure_started,
    event,
    finish,
    flush,
    span,
    start,
)
from tpudist.telemetry.aggregate import (  # noqa: F401
    aggregate_run,
    load_records,
    render_markdown,
    write_reports,
)
