"""Live metrics: the ONLINE half of the telemetry subsystem.

PR 2's spans/aggregate pipeline is post-hoc — per-rank JSONL becomes a
report after the run ends.  This module is the in-process registry a
LIVE consumer reads mid-run: the ``/metrics`` scrape endpoint
(:mod:`tpudist.telemetry.statusz`), the SLO attainment gauges the
admission controller (ROADMAP item 2) will consult, and the serving
report's own sanity check (live percentiles must agree with the
post-hoc aggregator within the sketch resolution — tested).

Three metric kinds, all label-aware (``pool=``, ``tenant=``,
``generation=``, arbitrary):

- :class:`Counter` — monotone float (requests finished, tokens out,
  telemetry drops);
- :class:`Gauge` — last-write-wins float (slot occupancy, KV bytes
  resident, SLO attainment);
- :class:`Histogram` — a **mergeable fixed log-bucket quantile sketch**:
  values land in geometric buckets (``GROWTH`` per bucket, 8 per
  octave), so merging two sketches is elementwise count addition —
  EXACT, which is what makes cross-rank/cross-pool aggregation a sum
  rather than an approximation-of-approximations.  Quantiles come back
  as the geometric midpoint of the bucket holding the nearest-rank
  order statistic, so any quantile agrees with the exact nearest-rank
  percentile (``tpudist.telemetry.aggregate._percentile``) within the
  relative bound :data:`QUANTILE_REL_ERROR` (≈4.4%) for values in
  [:data:`BUCKET_LO`, ~3900 s] — the quoted resolution the tests pin.

Concurrency contract (lock-light): writers take one tiny per-metric
lock per update; ``snapshot()`` and ``render_prometheus()`` are
WAIT-FREE for readers — they copy the registry dict (atomic under the
GIL) and read plain ints/floats without acquiring anything, so a
scrape can never stall the engine thread behind it.

Feeding: the registry is populated from the EXISTING span/event seams —
:mod:`tpudist.telemetry.spans` calls :func:`feed_record` (when armed)
for every record it emits, so the instrumented sites (``decode_block``,
``prefill``, ``kv_handoff``, ``request_finished``, ``ckpt_save``,
``step``) did not change.  ``TPUDIST_METRICS=0`` disarms the feed;
disarmed cost at the span site is one module-attribute load + None
check (the telemetry discipline).

SLO layer: declared targets (``TPUDIST_SLO_TTFT_MS`` /
``TPUDIST_SLO_TPOT_MS``) turn every ``request_finished`` into per-tenant
ok/total counters and a live ``tpudist_slo_attainment`` gauge — the
measurement surface SLO-aware admission reads.

Dependency-free (stdlib only), importable without jax.
"""

from __future__ import annotations

import math
import threading
from typing import Callable, Dict, Iterable, List, Optional, Tuple

# -- sketch geometry ----------------------------------------------------------

#: Geometric bucket growth: 8 buckets per octave.
GROWTH = 2.0 ** 0.125
#: Upper edge of bucket 0 (values at or below land there): 1 µs.
BUCKET_LO = 1e-6
#: Bucket count; the top regular bucket edge is
#: ``BUCKET_LO * GROWTH**(NBUCKETS-1)`` ≈ 3.9e3 s.
NBUCKETS = 256
#: Quoted quantile agreement bound vs the exact nearest-rank percentile:
#: a quantile from the sketch is the geometric midpoint of the bucket
#: holding the exact order statistic, so ``|sketch - exact| <=
#: QUANTILE_REL_ERROR * exact`` for exact values in
#: ``[BUCKET_LO, BUCKET_LO * GROWTH**(NBUCKETS-1)]``.
QUANTILE_REL_ERROR = GROWTH ** 0.5 - 1.0

_LOG_GROWTH = math.log(GROWTH)
_LOG_LO = math.log(BUCKET_LO)

ENV_METRICS = "TPUDIST_METRICS"
ENV_SLO_TTFT = "TPUDIST_SLO_TTFT_MS"
ENV_SLO_TPOT = "TPUDIST_SLO_TPOT_MS"


def bucket_index(v: float) -> int:
    """Bucket of value ``v``: 0 holds ``(-inf, BUCKET_LO]``; bucket i>0
    holds ``(BUCKET_LO*GROWTH**(i-1), BUCKET_LO*GROWTH**i]``; the top
    bucket is open-ended."""
    if v <= BUCKET_LO:
        return 0
    idx = 1 + int(math.floor((math.log(v) - _LOG_LO) / _LOG_GROWTH))
    # float-edge guard: a value sitting exactly on a bucket edge must
    # land in the bucket whose upper edge it is
    if v <= BUCKET_LO * GROWTH ** (idx - 1):
        idx -= 1
    return min(max(idx, 0), NBUCKETS - 1)


def bucket_value(idx: int) -> float:
    """Representative (geometric midpoint) of bucket ``idx`` — what a
    quantile query returns."""
    if idx <= 0:
        return BUCKET_LO
    return BUCKET_LO * GROWTH ** (idx - 0.5)


class Counter:
    """Monotone counter.  ``inc`` takes the per-metric lock; ``value``
    is a wait-free read."""

    __slots__ = ("_lock", "value")

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n


class Gauge:
    """Last-write-wins value (occupancy, attainment, queue depth) —
    a single GIL-atomic assignment, no lock (no read-modify-write)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)


class Histogram:
    """Mergeable fixed log-bucket quantile sketch (module doc).

    ``observe`` is the writer path (one lock); ``quantile``/``summary``
    read the bucket array without locking — a reader racing a writer
    sees a sketch at most one observation stale, never a torn one
    (list-of-int reads are atomic under the GIL)."""

    __slots__ = ("_lock", "buckets", "count", "sum", "min", "max")

    def __init__(self):
        self._lock = threading.Lock()
        self.buckets = [0] * NBUCKETS
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    def observe(self, v: float, n: int = 1) -> None:
        """Record ``v`` (``n`` times — a scanned train window covering
        K steps observes its per-step mean with weight K, matching the
        post-hoc aggregator's window weighting at one bucket update)."""
        v = float(v)
        idx = bucket_index(v)
        with self._lock:
            self.buckets[idx] += n
            self.count += n
            self.sum += v * n
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v

    def merge(self, other: "Histogram") -> None:
        """Elementwise bucket addition — EXACT (the log-bucket layout is
        shared by construction, so cross-rank/cross-pool merge loses
        nothing the individual sketches had)."""
        # snapshot the source under ITS lock first (sequential acquire,
        # never nested — no ordering deadlock): merging a LIVE sketch
        # must not tear count away from the bucket totals, or quantile()
        # walks past every bucket and reports the top edge
        with other._lock:
            ob = list(other.buckets)
            ocount, osum = other.count, other.sum
            omin, omax = other.min, other.max
        with self._lock:
            for i in range(NBUCKETS):
                self.buckets[i] += ob[i]
            self.count += ocount
            self.sum += osum
            self.min = min(self.min, omin)
            self.max = max(self.max, omax)

    def quantile(self, q: float) -> float:
        """Nearest-rank quantile (``aggregate._percentile``'s index
        convention, so the chosen bucket CONTAINS the exact order
        statistic) returned as the bucket's geometric midpoint — within
        :data:`QUANTILE_REL_ERROR` of the exact value."""
        count = self.count
        if count <= 0:
            return 0.0
        rank = int(round(q / 100.0 * (count - 1)))
        rank = max(0, min(count - 1, rank))
        seen = 0
        for i, c in enumerate(self.buckets):
            seen += c
            if seen > rank:
                return bucket_value(i)
        return bucket_value(NBUCKETS - 1)

    def summary(self) -> dict:
        if self.count == 0:
            return {"count": 0, "sum": 0.0}
        return {
            "count": self.count,
            "sum": round(self.sum, 9),
            "mean": round(self.sum / self.count, 9),
            "min": round(self.min, 9),
            "max": round(self.max, 9),
            "p50": round(self.quantile(50), 9),
            "p95": round(self.quantile(95), 9),
            "p99": round(self.quantile(99), 9),
        }


_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, object]) -> _LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(key: _LabelKey, extra: Optional[Tuple[str, str]] = None) -> str:
    items = list(key) + ([extra] if extra else [])
    if not items:
        return ""
    return "{" + ",".join(f'{k}="{_escape(v)}"' for k, v in items) + "}"


def _fmt_value(v: float) -> str:
    """Exposition-format a sample value: integral values as bare ints,
    floats at full precision (repr) — ``%g``'s 6 significant digits
    would freeze a counter past ~1e6 (small increments invisible
    between scrapes, so ``rate()`` reads 0 then spikes)."""
    f = float(v)
    if f.is_integer() and abs(f) < 2 ** 63:
        return str(int(f))
    return repr(f)


class MetricsRegistry:
    """Name+labels → metric instance.  Creation takes the registry
    lock once per NEW (name, labels) pair; the common path (metric
    exists) is a dict read.  ``snapshot``/``render_prometheus`` copy
    the dict (atomic under the GIL) and read without locks."""

    def __init__(self):
        self._lock = threading.Lock()
        #: (kind, name, label_key) → metric
        self._metrics: Dict[Tuple[str, str, _LabelKey], object] = {}

    def _get(self, kind: str, cls, name: str, labels: Dict[str, object]):
        key = (kind, name, _label_key(labels))
        m = self._metrics.get(key)
        if m is None:
            with self._lock:
                m = self._metrics.get(key)
                if m is None:
                    m = cls()
                    self._metrics[key] = m
        return m

    def counter(self, name: str, **labels) -> Counter:
        return self._get("counter", Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get("gauge", Gauge, name, labels)

    def histogram(self, name: str, **labels) -> Histogram:
        return self._get("histogram", Histogram, name, labels)

    def clear(self) -> None:
        """Drop every metric (tests; a long-lived process keeps its
        registry across telemetry sessions on purpose)."""
        with self._lock:
            self._metrics = {}
        _TENANTS_SEEN.clear()
        _ADAPTERS_SEEN.clear()

    # -- readers (wait-free) ------------------------------------------------

    def snapshot(self) -> dict:
        """One JSON-safe view of everything: counters/gauges as floats,
        histograms as count/sum/min/max/p50/p95/p99.  Never blocks a
        writer and is never blocked by one."""
        metrics = dict(self._metrics)  # atomic copy under the GIL
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for (kind, name, lkey), m in sorted(metrics.items(),
                                            key=lambda kv: kv[0]):
            label = name + _fmt_labels(lkey)
            if kind == "counter":
                out["counters"][label] = m.value
            elif kind == "gauge":
                out["gauges"][label] = m.value
            else:
                out["histograms"][label] = m.summary()
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition (format 0.0.4).  Counters and
        gauges one line each; histograms render as SUMMARY metrics
        (quantile series + ``_sum``/``_count``) — 5 lines instead of
        256 cumulative buckets per sketch."""
        metrics = dict(self._metrics)
        by_name: Dict[Tuple[str, str], List[Tuple[_LabelKey, object]]] = {}
        for (kind, name, lkey), m in metrics.items():
            by_name.setdefault((kind, name), []).append((lkey, m))
        lines: List[str] = []
        for (kind, name) in sorted(by_name):
            rows = sorted(by_name[(kind, name)], key=lambda kv: kv[0])
            if kind in ("counter", "gauge"):
                lines.append(f"# TYPE {name} {kind}")
                for lkey, m in rows:
                    lines.append(
                        f"{name}{_fmt_labels(lkey)} {_fmt_value(m.value)}")
            else:
                lines.append(f"# TYPE {name} summary")
                for lkey, m in rows:
                    for q in (0.5, 0.95, 0.99):
                        lines.append(
                            f"{name}{_fmt_labels(lkey, ('quantile', f'{q:g}'))}"
                            f" {_fmt_value(m.quantile(q * 100))}")
                    lines.append(f"{name}_sum{_fmt_labels(lkey)} "
                                 f"{_fmt_value(m.sum)}")
                    lines.append(f"{name}_count{_fmt_labels(lkey)} {m.count}")
        return "\n".join(lines) + "\n"


#: The process-wide registry every feeder/scraper shares.  Long-lived on
#: purpose: a restarting telemetry session does not zero the gauges a
#: live scraper is watching.
_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _REGISTRY


# -- SLO targets --------------------------------------------------------------

def slo_targets() -> Dict[str, Optional[float]]:
    """Declared latency targets in SECONDS from the ``TPUDIST_SLO_*_MS``
    knobs (unset / <= 0 = no target for that metric)."""
    from tpudist.utils.envutil import env_positive_float

    ttft_ms = env_positive_float(ENV_SLO_TTFT, None)
    tpot_ms = env_positive_float(ENV_SLO_TPOT, None)
    return {
        "ttft_s": ttft_ms / 1e3 if ttft_ms else None,
        "tpot_s": tpot_ms / 1e3 if tpot_ms else None,
    }


#: Cached targets, resolved once at arm time (the feeder runs on hot
#: paths; it must not re-read the environment per request).
_SLO: Dict[str, Optional[float]] = {"ttft_s": None, "tpot_s": None}


def slo_attainment() -> Dict[Tuple[str, str], float]:
    """The LIVE per-tenant SLO attainment gauges, as ``(metric, tenant)
    -> value`` — the read surface the SLO-aware admission controller
    (:mod:`tpudist.serve.overload`) acts on.  Wait-free like every
    registry read (one GIL-atomic dict copy, no locks)."""
    out: Dict[Tuple[str, str], float] = {}
    for (kind, name, lkey), m in dict(_REGISTRY._metrics).items():
        if kind == "gauge" and name == "tpudist_slo_attainment":
            lab = dict(lkey)
            out[(lab.get("metric", "?"),
                 lab.get("tenant", "default"))] = m.value
    return out


# -- the span/event → metrics feeder -----------------------------------------

def _pool_label(rec: dict) -> Dict[str, str]:
    p = rec.get("pool")
    return {"pool": p} if isinstance(p, str) else {}


#: Distinct-tenant label bound: tenant strings are CALLER data, and each
#: new label set allocates sketches that live for the process — a client
#: passing per-user UUIDs as tenants would grow memory and scrape size
#: without limit.  Tenants past the cap pool under ``"other"`` (their
#: requests still count; only the per-tenant split saturates).
TENANT_LABEL_CAP = 64
_TENANTS_SEEN: set = set()


def _tenant_label(rec: dict) -> Dict[str, str]:
    t = rec.get("tenant")
    t = t if isinstance(t, str) and t else "default"
    if t not in _TENANTS_SEEN:
        if len(_TENANTS_SEEN) >= TENANT_LABEL_CAP:
            return {"tenant": "other"}
        _TENANTS_SEEN.add(t)
    return {"tenant": t}


#: Distinct-adapter label bound (the TENANT_LABEL_CAP rule applied to
#: adapter names): resident adapters are bounded by the pool, but the
#: set of names EVER loaded is not — overflow pools under "other".
ADAPTER_LABEL_CAP = 64
_ADAPTERS_SEEN: set = set()


def _adapter_label(rec: dict) -> Dict[str, str]:
    a = rec.get("adapter")
    a = a if isinstance(a, str) and a else "?"
    if a not in _ADAPTERS_SEEN:
        if len(_ADAPTERS_SEEN) >= ADAPTER_LABEL_CAP:
            return {"adapter": "other"}
        _ADAPTERS_SEEN.add(a)
    return {"adapter": a}


def _num(v) -> Optional[float]:
    return float(v) if isinstance(v, (int, float)) else None


def feed_record(rec: dict) -> None:
    """Map one telemetry record (the spans.py schema) onto the registry.
    Called from ``TelemetrySession._emit`` when armed — the instrumented
    sites themselves did not change for the live plane.  Must never
    raise into the emitter (defensive isinstance checks, and the caller
    guards anyway)."""
    r = _REGISTRY
    kind = rec.get("kind")
    name = rec.get("name")
    if kind == "span":
        dur = _num(rec.get("dur")) or 0.0
        if name == "step":
            n = _num(rec.get("steps"))
            n = max(1, int(n)) if n else 1
            r.counter("tpudist_train_steps_total").inc(n)
            # per-step mean, weighted by the steps the window covered —
            # the aggregator's _step_stats convention
            r.histogram("tpudist_step_seconds").observe(dur / n, n)
        elif name in ("decode_block", "decode_step", "spec_verify"):
            lab = _pool_label(rec)
            r.counter("tpudist_decode_blocks_total", **lab).inc()
            toks = rec.get("tokens")
            if isinstance(toks, (int, float)):
                r.counter("tpudist_decode_tokens_total", **lab).inc(int(toks))
            r.histogram("tpudist_decode_block_seconds", **lab).observe(dur)
            occ = rec.get("occupancy")
            if isinstance(occ, (int, float)):
                r.gauge("tpudist_slot_occupancy", **lab).set(float(occ))
            kvb = rec.get("kv_bytes_resident")
            if isinstance(kvb, (int, float)):
                r.gauge("tpudist_kv_bytes_resident", **lab).set(float(kvb))
            if name == "spec_verify":
                acc = rec.get("accepted")
                if isinstance(acc, (int, float)):
                    r.counter("tpudist_spec_accepted_total", **lab).inc(int(acc))
                drafted = rec.get("drafted")
                if isinstance(drafted, (int, float)) and drafted:
                    r.counter("tpudist_spec_drafted_total",
                              **lab).inc(int(drafted))
                    # live acceptance — the SAME number the distill
                    # swap gate reads, cumulative over the counters so
                    # a scrape and the gate can never disagree
                    a = r.counter("tpudist_spec_accepted_total",
                                  **lab).value
                    d = r.counter("tpudist_spec_drafted_total",
                                  **lab).value
                    if d:
                        r.gauge("tpudist_spec_accept_rate",
                                **lab).set(a / d)
                by_ad = rec.get("accept_by_adapter")
                if isinstance(by_ad, dict):
                    # per-adapter labeled acceptance (bounded like the
                    # adapter residency gauges — the label-cap rule)
                    for ad, pair in by_ad.items():
                        if not (isinstance(pair, (list, tuple))
                                and len(pair) == 2):
                            continue
                        alab = _adapter_label({"adapter": ad})
                        ca = r.counter("tpudist_spec_accepted_total",
                                       **alab)
                        cd = r.counter("tpudist_spec_drafted_total",
                                       **alab)
                        ca.inc(int(pair[0]))
                        cd.inc(int(pair[1]))
                        if cd.value:
                            r.gauge("tpudist_spec_accept_rate",
                                    **alab).set(ca.value / cd.value)
        elif name == "prefill":
            lab = _pool_label(rec)
            r.counter("tpudist_prefill_dispatches_total", **lab).inc()
            r.histogram("tpudist_prefill_seconds", **lab).observe(dur)
        elif name in ("ckpt_save", "ckpt_restore", "ckpt_wait"):
            r.histogram("tpudist_ckpt_seconds", op=name[5:]).observe(dur)
        elif name == "data_wait":
            r.histogram("tpudist_data_wait_seconds").observe(dur)
        return
    # events
    if name == "request_finished":
        tlab = _tenant_label(rec)
        reason = str(rec.get("reason"))
        r.counter("tpudist_requests_finished_total",
                  reason=reason, **tlab).inc()
        toks = rec.get("tokens_out")
        if isinstance(toks, (int, float)):
            r.counter("tpudist_tokens_out_total", **tlab).inc(int(toks))
        for key, metric in (("ttft_s", "tpudist_ttft_seconds"),
                            ("tpot_s", "tpudist_tpot_seconds"),
                            ("queue_wait_s", "tpudist_queue_wait_seconds"),
                            ("handoff_wait_s", "tpudist_handoff_wait_seconds")):
            v = rec.get(key)
            if isinstance(v, (int, float)):
                r.histogram(metric, **tlab).observe(float(v))
        # SLO attainment: per declared target, per tenant — the live
        # gauge the admission controller (ROADMAP item 2) reads
        for key, slo_name in (("ttft_s", "ttft"), ("tpot_s", "tpot")):
            target = _SLO.get(key)
            v = rec.get(key)
            if target is None or not isinstance(v, (int, float)):
                continue
            total = r.counter(f"tpudist_slo_{slo_name}_total", **tlab)
            ok = r.counter(f"tpudist_slo_{slo_name}_ok_total", **tlab)
            total.inc()
            if float(v) <= target:
                ok.inc()
            r.gauge("tpudist_slo_attainment", metric=slo_name,
                    **tlab).set(ok.value / total.value)
    elif name == "serve_rejected":
        reason = str(rec.get("reason", "")).split(":")[0] or "unknown"
        r.counter("tpudist_requests_rejected_total", reason=reason).inc()
    elif name == "kv_handoff":
        r.counter("tpudist_kv_handoffs_total").inc()
        imp = rec.get("import_s")
        if isinstance(imp, (int, float)):
            r.histogram("tpudist_handoff_import_seconds").observe(float(imp))
    elif name in ("adapter_load", "adapter_evict"):
        # per-tenant adapter pool (tpudist.serve.adapters): load/evict
        # counters, a per-adapter residency gauge, and the total-
        # resident gauge riding ON the events.  The per-adapter label
        # is CAPPED like tenants: only the pool's RESIDENT set is
        # bounded — a long-lived server churning thousands of names
        # through load→evict would otherwise grow one dead 0-gauge per
        # historical name without limit
        alab = _adapter_label(rec)
        if name == "adapter_load":
            r.counter("tpudist_adapter_loads_total").inc()
            r.gauge("tpudist_adapter_resident", **alab).set(1.0)
        else:
            r.counter("tpudist_adapter_evicts_total",
                      kind=str(rec.get("evict_kind", "?"))).inc()
            r.gauge("tpudist_adapter_resident", **alab).set(0.0)
        v = rec.get("resident")
        if isinstance(v, (int, float)):
            r.gauge("tpudist_serve_adapters_resident").set(float(v))
    elif name == "draft_swap":
        # online draft distillation: one count per APPLIED gated swap
        # (rejected candidates never get here — the distill_round
        # event stream carries those)
        r.counter("tpudist_draft_swaps_total").inc()
    elif name == "worker_lost":
        r.counter("tpudist_workers_lost_total", **_pool_label(rec)).inc()
    elif name == "lane_recovered":
        r.counter("tpudist_lanes_recovered_total", **_pool_label(rec)).inc()
    elif name == "pool_resize":
        r.counter("tpudist_pool_resizes_total", **_pool_label(rec)).inc()
    elif name == "telemetry_dropped":
        for k in ("ring", "write"):
            v = rec.get(k)
            if isinstance(v, (int, float)) and v:
                r.counter("tpudist_telemetry_dropped_total", kind=k).inc(v)
    # host-RAM KV tier + overload control (tpudist.serve.host_tier /
    # .overload): park/resume/spill/corruption counters, plus the
    # occupancy gauges riding ON the events (the tier has no feed of
    # its own — the server stamps tier_bytes/tier_entries into each
    # park/resume event, so a scrape tracks occupancy with zero new
    # instrumentation seams)
    elif name in ("session_parked", "session_resumed", "host_tier_spill",
                  "session_expired", "host_tier_corrupt", "preempted",
                  "shed_state"):
        kind_lab = ({"kind": str(rec["park_kind"])}
                    if isinstance(rec.get("park_kind"), str) else {})
        if name == "session_parked":
            r.counter("tpudist_host_tier_parks_total", **kind_lab).inc()
        elif name == "session_resumed":
            r.counter("tpudist_host_tier_resumes_total", **kind_lab).inc()
        elif name == "host_tier_spill":
            r.counter("tpudist_host_tier_spills_total").inc(
                int(rec.get("entries", 1) or 1))
        elif name == "session_expired":
            r.counter("tpudist_host_tier_expired_total").inc(
                int(rec.get("entries", 1) or 1))
        elif name == "host_tier_corrupt":
            r.counter("tpudist_host_tier_corrupt_total").inc()
        elif name == "preempted":
            r.counter("tpudist_requests_preempted_total").inc()
        elif name == "shed_state":
            r.gauge("tpudist_shed_active").set(
                1.0 if rec.get("active") else 0.0)
        for key, gname in (("tier_bytes", "tpudist_host_tier_bytes"),
                           ("tier_entries", "tpudist_host_tier_entries")):
            v = rec.get(key)
            if isinstance(v, (int, float)):
                r.gauge(gname).set(float(v))
    # fleet router (tpudist.serve.router): routing/failover counters +
    # the replicas-up gauge riding ON the replica_health events (the
    # router has no feed of its own — same zero-new-seams discipline as
    # the host tier above)
    elif name == "router_config":
        v = rec.get("replicas")
        if isinstance(v, (int, float)):
            r.gauge("tpudist_router_replicas").set(float(v))
    elif name == "router_route":
        r.counter("tpudist_router_routed_total",
                  kind=str(rec.get("route_kind", "?"))).inc()
    elif name == "router_spill":
        r.counter("tpudist_router_spills_total").inc()
    elif name == "router_retry":
        r.counter("tpudist_router_retries_total").inc()
    elif name == "replica_health":
        if not rec.get("up"):
            r.counter("tpudist_router_replica_deaths_total").inc()
        v = rec.get("ups")
        if isinstance(v, (int, float)):
            r.gauge("tpudist_router_replicas_up").set(float(v))
    elif name == "session_migrated":
        r.counter("tpudist_router_sessions_migrated_total",
                  ok=str(bool(rec.get("ok"))).lower()).inc()


def set_train_gauges(iteration: int, values: Dict[str, float]) -> None:
    """Publish training progress to the live registry (no-op when the
    feed is disarmed): the ``tpudist_train_iteration`` gauge plus one
    ``tpudist_train_<key>`` gauge per logged metric, keys sanitized to
    the Prometheus charset.  The one naming/sanitization rule for BOTH
    training flush paths (per-step and scanned — see train/loop.py)."""
    if not armed():
        return
    r = _REGISTRY
    r.gauge("tpudist_train_iteration").set(iteration)
    for k, v in values.items():
        name = "".join(c if c.isalnum() or c == "_" else "_"
                       for c in str(k))
        r.gauge(f"tpudist_train_{name}").set(float(v))


# -- arming -------------------------------------------------------------------

def enabled_from_env() -> bool:
    """The feed is armed by default whenever telemetry is;
    ``TPUDIST_METRICS=0`` disarms just the live registry."""
    from tpudist.utils.envutil import env_flag

    return env_flag(ENV_METRICS, True)


def armed() -> bool:
    from tpudist.telemetry import spans

    return spans._SINK is not None


def arm_from_env() -> bool:
    """Install :func:`feed_record` as the span/event sink (idempotent)
    and cache the SLO targets.  Called by every
    :class:`~tpudist.telemetry.spans.TelemetrySession` construction, so
    any armed process feeds the live registry with zero site changes.
    Also refreshes the trace module's cached arm flag — one arming
    entry point for the whole live plane."""
    from tpudist.telemetry import spans, trace

    global _SLO
    trace.arm_from_env()
    if not enabled_from_env():
        spans._SINK = None
        return False
    _SLO = slo_targets()
    spans._SINK = feed_record
    return True


def disarm() -> None:
    from tpudist.telemetry import spans

    spans._SINK = None
