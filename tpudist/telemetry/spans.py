"""Per-step span tracing: the recording half of the telemetry subsystem.

A *span* is a named wall-clock interval (``step``, ``compile``,
``data_wait``, ``ckpt_save``, ``host_collective``, ``init``); an *event*
is a zero-duration tagged marker (``fault_injected``, ``watchdog_stall``,
``retry``).  Each process records into

1. a bounded in-memory ring (``TPUDIST_TELEMETRY_RING`` entries, for
   in-process inspection and post-mortem dumps), and
2. a line-buffered per-rank, per-generation JSONL file
   ``<dir>/rank<R>_gen<G>.jsonl`` — the generation is
   ``TPUDIST_RESTART_COUNT`` (stamped by ``tpurun``), which is what lets
   the aggregator attribute the wall-clock gap between a killed process
   and its restarted successor as ``lost_restart`` time.

Record schema (one JSON object per line; reserved keys below, arbitrary
extra tags allowed)::

    {"kind": "span"|"event", "name": str, "t": wall_start_s,
     "dur": seconds, "rank": int, "gen": int, "parent": str?, ...tags}

``t`` is wall-clock (``time.time`` epoch) so records from different
processes/generations merge on one axis; durations are measured with
``time.monotonic`` and mapped onto the wall axis through one clock-pair
read at session start (span math never mixes clock reads).

Hot-path cost: disarmed (``TPUDIST_TELEMETRY=0`` or no session) every
site pays one module-attribute load + ``None`` check; armed, a span is
two ``monotonic()`` reads, a small dict, and one buffered ``write``.
Telemetry must never take a job down: I/O errors drop records — but no
longer SILENTLY: stream write failures, and ring evictions when the
session is RING-ONLY (the stream never opened, so an evicted record
exists nowhere), are counted in the session's ``dropped`` dict
(surfaced in ``/statusz``, stamped as a ``telemetry_dropped`` event at
close for the aggregate report, and warned once per session), so a
truncated report announces itself.  Ring rotation on a healthy stream
is the ring's designed behavior, not a drop.

Live plane: every emitted record is also offered to the metrics sink
(:func:`tpudist.telemetry.metrics.feed_record`) when armed
(``TPUDIST_METRICS``), which is what keeps the scrapeable registry
current without touching any instrumented site.

Dependency-free (no jax import): rank and generation resolve from the
launcher env contract via :mod:`tpudist.utils.envutil`, so the watchdog
and fault registry — which must stay importable without jax — can emit.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time
import warnings
from pathlib import Path
from typing import Callable, Dict, Optional

ENV_ENABLE = "TPUDIST_TELEMETRY"
ENV_DIR = "TPUDIST_TELEMETRY_DIR"
ENV_RING = "TPUDIST_TELEMETRY_RING"
DEFAULT_DIR = os.path.join("runs", "telemetry")
DEFAULT_RING = 4096

#: Keys every record carries; tags may not override them.
RESERVED_KEYS = ("kind", "name", "t", "dur", "rank", "gen", "parent")


def enabled_from_env() -> bool:
    """Telemetry is armed by default; ``TPUDIST_TELEMETRY=0`` (or
    false/off/no) disarms it."""
    from tpudist.utils.envutil import env_flag

    return env_flag(ENV_ENABLE, True)


class TelemetrySession:
    """One process generation's recording session: ring + JSONL stream.

    One telemetry dir describes ONE run: a new session for the same
    (rank, generation) truncates the previous stream, so a re-run into
    the same dir reports itself, not a merge of unrelated runs.  Restart
    generations (distinct ``gen``) coexist — that is the cross-restart
    join the aggregator builds ``lost_restart`` from."""

    def __init__(
        self,
        directory: "str | os.PathLike",
        *,
        rank: Optional[int] = None,
        generation: Optional[int] = None,
        ring_size: Optional[int] = None,
    ):
        from tpudist.utils.envutil import env_int, env_rank

        self.rank = env_rank(0) if rank is None else int(rank)
        self.generation = (
            (env_int("TPUDIST_RESTART_COUNT", 0) or 0)
            if generation is None else int(generation)
        )
        #: this generation's world size (launch contract) — stamped on
        #: ``session_start`` so the aggregator can tell an ELASTIC
        #: relaunch (world changed → the inter-generation gap is
        #: ``resize`` time) from a fixed-size restart (``lost_restart``).
        self.world = env_int("TPUDIST_NUM_PROCESSES", None)
        if ring_size is None:
            ring_size = env_int(ENV_RING, DEFAULT_RING) or DEFAULT_RING
        self.ring: "collections.deque[dict]" = collections.deque(
            maxlen=max(1, int(ring_size)))
        self.directory = Path(directory)
        self.path = (self.directory
                     / f"rank{self.rank}_gen{self.generation}.jsonl")
        self._tls = threading.local()
        self._write_lock = threading.Lock()
        self._closed = False
        #: drop accounting (never silent — module doc): ``ring`` = ring
        #: evictions on a RING-ONLY session (stream never opened, so an
        #: evicted record exists nowhere), ``write`` = stream
        #: write/encode failures.  Surfaced in /statusz, stamped as a
        #: ``telemetry_dropped`` event at close, warned once.
        self.dropped: Dict[str, int] = {"ring": 0, "write": 0}
        self._drop_warned = False
        # One clock-pair read: wall-clock for any monotonic stamp is
        # t0_wall + (mono - t0_mono), so a span's t and dur come from the
        # same monotonic reads (never a second time.time() call).
        self._t0_wall = time.time()
        self._t0_mono = time.monotonic()
        self._file = None
        try:
            self.directory.mkdir(parents=True, exist_ok=True)
            self._file = open(self.path, "w", buffering=1)  # line buffered
        except OSError:
            pass  # ring-only session: recording must not take the job down
        # arm the live-metrics sink (TPUDIST_METRICS gates it) so every
        # session — worker, trainer, tpurun agent — feeds the scrapeable
        # registry without site changes
        try:
            from tpudist.telemetry import metrics as _metrics

            _metrics.arm_from_env()
        except Exception:
            pass
        self.event("session_start", pid=os.getpid(),
                   **({"world": self.world} if self.world else {}))

    # -- recording ----------------------------------------------------------

    def _wall(self, mono: float) -> float:
        return self._t0_wall + (mono - self._t0_mono)

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def record_span(self, name: str, t0_mono: float, dur_s: float,
                    tags: Optional[Dict] = None, *,
                    parent: Optional[str] = None) -> None:
        """Record a completed span from explicit ``monotonic()`` stamps —
        the zero-allocation-on-disarm form the hot loops use::

            if tele is not None: t0 = time.monotonic()
            ...work...
            if tele is not None:
                tele.record_span("step", t0, time.monotonic() - t0)

        ``parent``: explicit parent override (the per-request lifeline
        spans in :mod:`tpudist.telemetry.trace` pass ``"request"`` so
        the goodput accounting treats them as detail, never a second
        copy of the wall-clock they re-describe)."""
        rec = {
            "kind": "span",
            "name": name,
            "t": round(self._wall(t0_mono), 6),
            "dur": round(dur_s, 9),
            "rank": self.rank,
            "gen": self.generation,
        }
        if parent is not None:
            rec["parent"] = parent
        else:
            st = self._stack()
            if st:
                rec["parent"] = st[-1]
        if tags:
            for k, v in tags.items():
                if k not in RESERVED_KEYS:
                    rec[k] = v
        self._emit(rec)

    def event(self, name: str, **tags) -> None:
        rec = {
            "kind": "event",
            "name": name,
            "t": round(time.time(), 6),
            "dur": 0.0,
            "rank": self.rank,
            "gen": self.generation,
        }
        for k, v in tags.items():
            if k not in RESERVED_KEYS:
                rec[k] = v
        self._emit(rec)

    @contextlib.contextmanager
    def span(self, name: str, **tags):
        """Nested-aware span bracket: while the body runs, inner spans
        record this one as their ``parent`` (per-thread stack, so the
        prefetch thread's spans never claim a trainer-thread parent)."""
        st = self._stack()
        t0 = time.monotonic()
        st.append(name)
        try:
            yield self
        finally:
            st.pop()
            self.record_span(name, t0, time.monotonic() - t0, tags or None)

    def _emit(self, rec: dict) -> None:
        if self._closed:
            return
        if self._file is None and len(self.ring) == self.ring.maxlen:
            # RING-ONLY session (the stream never opened): the deque
            # eviction is real data loss — nothing else holds the
            # record.  With a live stream, rotation past the bound is
            # the ring's designed behavior, not a drop (the JSONL has
            # every record; counting it would make every long healthy
            # run's report falsely announce incompleteness).
            self.dropped["ring"] += 1
        self.ring.append(rec)
        sink = _SINK
        if sink is not None:
            try:
                sink(rec)  # live-metrics feed (tpudist.telemetry.metrics)
            except Exception:
                pass  # the registry must never take the emitter down
        f = self._file
        if f is None:
            return
        try:
            line = json.dumps(rec) + "\n"
        except (TypeError, ValueError):
            self._count_write_drop()
            return  # unserializable tag: drop the record, not the job
        try:
            with self._write_lock:
                f.write(line)
        except (OSError, ValueError):
            self._count_write_drop()

    def _count_write_drop(self) -> None:
        self.dropped["write"] += 1
        if not self._drop_warned:
            self._drop_warned = True
            warnings.warn(
                f"tpudist.telemetry: dropping records (stream write "
                f"failure on {self.path}) — the post-hoc report for this "
                f"run will be incomplete; counts surface in /statusz and "
                f"the telemetry_dropped event", RuntimeWarning,
                stacklevel=3)

    # -- lifecycle ----------------------------------------------------------

    def flush(self) -> None:
        """Push buffered lines to the OS and fsync — called before
        deliberate aborts (watchdog ``os._exit``, injected SIGKILL) so the
        record that *explains* the death survives it."""
        f = self._file
        if f is None:
            return
        try:
            with self._write_lock:
                f.flush()
                os.fsync(f.fileno())
        except (OSError, ValueError):
            pass

    def close(self) -> None:
        if self._closed:
            return
        if any(self.dropped.values()):
            # best-effort last word: if the stream recovered, the
            # aggregate report learns exactly how much it is missing
            self.event("telemetry_dropped", **self.dropped)
        self.event("session_end")
        self._closed = True
        f, self._file = self._file, None
        if f is not None:
            try:
                f.flush()
                os.fsync(f.fileno())
                f.close()
            except (OSError, ValueError):
                pass

    @property
    def closed(self) -> bool:
        return self._closed


# -- module-level API (the one-branch-per-site surface) ----------------------

_ACTIVE: Optional[TelemetrySession] = None
_lock = threading.Lock()

#: Live-metrics sink: every emitted record is offered to this callable
#: (``tpudist.telemetry.metrics.feed_record`` when armed; ``None``
#: disarmed — one attribute load + None check per record).  Installed by
#: :func:`tpudist.telemetry.metrics.arm_from_env`.
_SINK: Optional[Callable[[dict], None]] = None


# Shared no-op context manager: the disarmed ``span()`` return
# (nullcontext is stateless, so one instance serves every site).
_NULL_SPAN = contextlib.nullcontext()


def active() -> Optional[TelemetrySession]:
    """The live session, or ``None`` — hot loops hoist this once and guard
    each site with one ``is not None`` check."""
    return _ACTIVE


def span(name: str, **tags):
    """``with telemetry.span("ckpt_save", step=7): ...`` — records on the
    active session; a shared no-op context manager when disarmed."""
    s = _ACTIVE
    if s is None:
        return _NULL_SPAN
    return s.span(name, **tags)


def event(name: str, **tags) -> None:
    s = _ACTIVE
    if s is not None:
        s.event(name, **tags)


def flush() -> None:
    s = _ACTIVE
    if s is not None:
        s.flush()


def start(
    directory: "str | os.PathLike | None" = None,
    *,
    rank: Optional[int] = None,
    generation: Optional[int] = None,
    ring_size: Optional[int] = None,
) -> TelemetrySession:
    """Start a session (closing any active one), explicit-args form for
    tests and embedding callers.  Directory: explicit >
    ``TPUDIST_TELEMETRY_DIR`` > ``runs/telemetry``."""
    global _ACTIVE
    with _lock:
        if _ACTIVE is not None:
            _ACTIVE.close()
        _ACTIVE = TelemetrySession(
            directory or os.environ.get(ENV_DIR) or DEFAULT_DIR,
            rank=rank, generation=generation, ring_size=ring_size,
        )
        return _ACTIVE


def ensure_started() -> Optional[TelemetrySession]:
    """Idempotent arm-from-env: start a session if telemetry is enabled
    and none is active.  Called from the runtime seams
    (``bootstrap.initialize``, ``run_training``) so every run records
    without code changes; returns ``None`` when disarmed."""
    if _ACTIVE is not None:
        return _ACTIVE
    if not enabled_from_env():
        return None
    return start()


def abandon() -> None:
    """Drop the active session WITHOUT closing it — the SIGKILL
    simulation hook for chaos tests: a killed process writes no
    ``session_end``, its stream just stops mid-line.  The buffered tail
    is flushed (matching the real pre-kill ``flush()`` the fault
    registry performs) but the file stays un-finalized."""
    global _ACTIVE
    with _lock:
        s = _ACTIVE
        _ACTIVE = None
    if s is not None:
        s.flush()


def finish(write_report: bool = True) -> Optional[dict]:
    """Close the active session; on rank 0 (the aggregation rank) also
    merge every rank/generation JSONL in the session directory into
    ``report.json`` + ``report.md``.  Returns the report dict (rank 0,
    ``write_report=True``) or ``None``.  Never raises — a failed report
    must not fail the run it measured."""
    global _ACTIVE
    with _lock:
        s = _ACTIVE
        _ACTIVE = None
    if s is None:
        return None
    s.close()
    if not (write_report and s.rank == 0):
        return None
    try:
        from tpudist.telemetry.aggregate import write_reports

        report, _paths = write_reports(s.directory)
        return report
    except Exception:
        return None
