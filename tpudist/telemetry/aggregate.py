"""Cross-rank/cross-generation aggregation: JSONL → goodput report.

Reads every ``rank<R>_gen<G>.jsonl`` a run's processes streamed (all
ranks, all restart generations), and produces

- ``report.json`` — machine-readable: wall-clock, step-time
  p50/p95/max, a goodput breakdown whose components sum to wall-clock
  (step / compile / data / ckpt / comm / init / other / idle /
  lost_restart), per-rank rows for straggler hunting, the StageTimer
  phase durations, and the joined fault/watchdog/retry event log;
- ``report.md`` — the same, human-readable.

Attribution rules (the math the tests pin down):

- Only TOP-LEVEL spans (no ``parent``) enter the goodput sum — a
  ``host_collective`` nested inside ``metric_flush`` is detail, not a
  second copy of the same wall-clock.
- Per rank: ``wall = last record end − first record start`` across all
  generations; ``lost_restart = Σ gaps`` between one generation's last
  record and the next generation's first (the time a killed process's
  successor spent being re-launched, re-admitted, and re-initialized
  before it recorded anything); ``idle = wall − Σ busy − lost``
  (clamped at 0; clamped amount reported as ``overlap_s`` so
  double-counted spans are visible, not silently absorbed).
- The run's goodput components are the across-rank MEANS, so they sum
  to the mean rank wall-clock (``wall_clock_s``); the envelope from the
  earliest record of any rank to the latest (``run_span_s``) is
  reported alongside.

Dependency-free (stdlib only) so post-hoc report generation —
``python -m tpudist.telemetry report <dir>`` — needs no jax.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, List, Optional, Tuple

#: span name → goodput component; unmapped top-level spans land in "other".
#: ``metric_flush`` counts as step time on purpose: a jitted step's span
#: brackets only the async dispatch, and the device compute it ran ahead
#: of surfaces in the next blocking loss fetch — attributing that wait to
#: anything but step would make the headline step%% read near-zero on
#: compute-bound runs.
COMPONENT_OF = {
    "step": "step",
    "metric_flush": "step",
    "compile": "compile",
    "data_wait": "data",
    "ckpt_save": "ckpt",
    "ckpt_restore": "ckpt",
    "ckpt_wait": "ckpt",
    "host_collective": "comm",
    "init": "init",
    # serving (tpudist.serve): device work of the engine loop — prefill
    # teacher-forcing and fused decode blocks are the serving analog of
    # a train step.  The first decode_block/prefill dispatch blocks on
    # XLA compilation like any first dispatch; the serving section's
    # TTFT percentiles surface that separately.  decode_step is the
    # pre-block name, still recognized so old streams aggregate.
    "prefill": "step",
    "decode_step": "step",
    "decode_block": "step",
    # speculative decode: one draft-propose + batched-verify block (the
    # decode work of a spec engine's iteration)
    "spec_verify": "step",
}

#: Every component of the breakdown, in report order.  The accounted ones
#: (all but idle/resize/lost_restart) come from spans; idle is the
#: per-rank remainder; the inter-generation gaps split into ``resize``
#: (the next generation launched at a DIFFERENT world size — an elastic
#: relaunch, classified from the ``world`` stamp each session carries)
#: and ``lost_restart`` (a fixed-size restart of the same world).
COMPONENTS = ("step", "compile", "data", "ckpt", "comm", "init", "other",
              "idle", "resize", "lost_restart")

#: Event names surfaced in the report's event log (joined across ranks and
#: generations on the wall-clock axis).
_REPORTED_EVENTS = ("fault_injected", "watchdog_stall", "retry",
                    "prefetch_stats", "serve_drain", "serve_loop_error",
                    "serve_disagg_config", "restart_exhausted",
                    "world_resized", "worker_lost", "lane_recovered",
                    "handoff_rejected", "pool_resize",
                    "adapter_load", "adapter_evict",
                    "replica_health", "session_migrated", "router_error",
                    "distill_round", "draft_swap",
                    "telemetry_dropped", "plan_selected")


def find_telemetry_dir(run_dir: "str | Path") -> Path:
    """Accept either the telemetry dir itself or a run dir containing a
    ``telemetry/`` subdirectory."""
    d = Path(run_dir)
    if list(d.glob("rank*_gen*.jsonl")):
        return d
    sub = d / "telemetry"
    if sub.is_dir() and list(sub.glob("rank*_gen*.jsonl")):
        return sub
    return d


def load_records(run_dir: "str | Path") -> List[dict]:
    """Parse every per-rank/per-generation JSONL under ``run_dir``.
    Torn trailing lines (SIGKILL mid-write) are skipped, not fatal."""
    recs: List[dict] = []
    for p in sorted(find_telemetry_dir(run_dir).glob("rank*_gen*.jsonl")):
        try:
            text = p.read_text()
        except OSError:
            continue
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn write at the kill point
            if isinstance(rec, dict) and "t" in rec and "name" in rec:
                recs.append(rec)
    return recs


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over a pre-sorted list (stdlib-only)."""
    if not sorted_vals:
        return 0.0
    idx = int(round(q / 100.0 * (len(sorted_vals) - 1)))
    return sorted_vals[max(0, min(len(sorted_vals) - 1, idx))]


def _rank_breakdown(rank_recs: List[dict]) -> dict:
    """One rank's wall-clock accounting across all its generations."""
    by_gen: Dict[int, List[dict]] = {}
    for r in rank_recs:
        by_gen.setdefault(int(r.get("gen", 0)), []).append(r)
    gens = sorted(by_gen)
    t0 = min(float(r["t"]) for r in rank_recs)
    t1 = max(float(r["t"]) + float(r.get("dur", 0.0)) for r in rank_recs)
    wall = max(0.0, t1 - t0)

    # Per-generation world size (the session_start stamp) — what lets a
    # gap be attributed as resize vs lost_restart below.
    world_of: Dict[int, Optional[int]] = {}
    for g in gens:
        world_of[g] = next(
            (int(r["world"]) for r in by_gen[g]
             if r.get("name") == "session_start"
             and isinstance(r.get("world"), int)), None)

    # Inter-generation gaps: the successor process's spawn/re-admit/
    # re-init dead time.  A gap into a generation whose world size
    # CHANGED is ``resize`` (the elastic relaunch shrinking/growing the
    # group); same (or unknown) world is ``lost_restart``.
    lost, resize = 0.0, 0.0
    for a, b in zip(gens, gens[1:]):
        end_a = max(float(r["t"]) + float(r.get("dur", 0.0))
                    for r in by_gen[a])
        start_b = min(float(r["t"]) for r in by_gen[b])
        gap = max(0.0, start_b - end_a)
        wa, wb = world_of.get(a), world_of.get(b)
        if wa is not None and wb is not None and wa != wb:
            resize += gap
        else:
            lost += gap

    comp = {c: 0.0 for c in COMPONENTS}
    comp["lost_restart"] = lost
    comp["resize"] = resize
    for r in rank_recs:
        if r.get("kind") != "span" or "parent" in r:
            continue  # nested spans are detail, not additional wall-clock
        comp[COMPONENT_OF.get(r["name"], "other")] += float(r.get("dur", 0.0))
    busy = sum(comp[c] for c in COMPONENTS
               if c not in ("idle", "resize", "lost_restart"))
    idle = wall - busy - lost - resize
    comp["idle"] = max(0.0, idle)
    return {
        "rank": int(rank_recs[0].get("rank", 0)),
        "generations": len(gens),
        "worlds": {str(g): world_of[g] for g in gens
                   if world_of[g] is not None},
        "wall_s": wall,
        "t0": t0,
        "t1": t1,
        "components_s": comp,
        # double-counted span time (overlapping top-level spans) surfaces
        # here instead of silently shrinking idle below zero.
        "overlap_s": max(0.0, -idle),
    }


def _step_stats(records: List[dict], num_ranks: int = 1) -> dict:
    """Per-step time distribution.  A scanned window span carries a
    ``steps`` tag; it contributes its per-step mean once per step so the
    percentiles weight windows by the iterations they covered.
    Percentiles pool every rank's samples, but ``count``/``total_s`` are
    per-rank means — all ranks run the same loop, and summing their
    parallel time would overstate the run by the rank count."""
    vals: List[float] = []
    total = 0.0
    count = 0
    for r in records:
        if r.get("kind") != "span" or r.get("name") != "step":
            continue
        dur = float(r.get("dur", 0.0))
        n = int(r.get("steps", 1) or 1)
        total += dur
        count += n
        vals.extend([dur / n] * min(n, 100_000))
    vals.sort()
    ranks = max(1, num_ranks)
    total /= ranks
    count = round(count / ranks)
    return {
        "count": count,
        "total_s": total,
        "p50_s": _percentile(vals, 50),
        "p95_s": _percentile(vals, 95),
        "max_s": vals[-1] if vals else 0.0,
        "steps_per_s": (count / total) if total > 0 else 0.0,
    }


def _slo_summary(fins: List[dict], slo_config: dict) -> dict:
    """Post-hoc SLO attainment vs the declared targets — the exact
    numbers the live ``tpudist_slo_attainment`` gauges track mid-run,
    recomputed from the ``request_finished`` events so live and post-hoc
    views can be cross-checked.  Per-tenant (requests without a tenant
    tag pool under ``"default"``) plus the overall row."""
    targets: Dict[str, float] = {}
    for key, tag in (("ttft_s", "ttft_ms"), ("tpot_s", "tpot_ms")):
        v = slo_config.get(tag)
        if isinstance(v, (int, float)) and v > 0:
            targets[key] = float(v) / 1e3

    def _attain(group: List[dict]) -> dict:
        out: Dict[str, object] = {"requests": len(group)}
        fracs = []
        for key, target in targets.items():
            vals = [float(r[key]) for r in group
                    if isinstance(r.get(key), (int, float))]
            label = key[:-2] + "_attainment"  # ttft_attainment / tpot_...
            if not vals:
                out[label] = None
                continue
            frac = sum(1 for v in vals if v <= target) / len(vals)
            out[label] = round(frac, 4)
            fracs.append(frac)
        # the headline: worst per-metric attainment (an SLO with two
        # clauses is met only as often as its weakest clause)
        out["attainment"] = round(min(fracs), 4) if fracs else None
        return out

    by_tenant: Dict[str, List[dict]] = {}
    for r in fins:
        t = r.get("tenant")
        by_tenant.setdefault(
            t if isinstance(t, str) and t else "default", []).append(r)
    return {
        "targets_ms": {
            ("ttft_ms" if k == "ttft_s" else "tpot_ms"): round(v * 1e3, 3)
            for k, v in targets.items()},
        "overall": _attain(fins),
        "per_tenant": {t: _attain(g) for t, g in sorted(by_tenant.items())},
    }


def _serving_summary(records: List[dict]) -> Optional[dict]:
    """Serving-goodput section from the serve subsystem's records:
    per-request ``request_finished`` events (TTFT/TPOT/queue-wait
    percentiles, finish-reason counts) plus the ``decode_block`` spans'
    occupancy gauge (duration-weighted — a long low-occupancy stretch
    must weigh what it cost) and their dispatch/host-sync attribution
    (the per-token overhead split — ``decode_step`` is the pre-block
    span name, still folded in).  ``None`` when the run never served."""
    fins = [r for r in records if r.get("kind") == "event"
            and r.get("name") == "request_finished"]
    rejects = sum(1 for r in records if r.get("kind") == "event"
                  and r.get("name") == "serve_rejected")
    # declared SLO targets (slo_config event, stamped at server start
    # when TPUDIST_SLO_*_MS is set) — last one wins across restarts
    slo_config = None
    for r in records:
        if r.get("kind") == "event" and r.get("name") == "slo_config":
            slo_config = r
    occ_w, occ_dur, occ_max, decode_s, prefill_s = 0.0, 0.0, 0.0, 0.0, 0.0
    serve_spans = 0
    decode_blocks, decode_tokens = 0, 0
    dispatch_s, sync_s = 0.0, 0.0
    # KV residency gauges (paged-cache PR): block occupancy duration-
    # weighted like the batch occupancy, peak resident bytes, and the
    # attention's streamed bytes (→ decode bytes/token)
    kv_occ_w, kv_occ_dur, kv_occ_max = 0.0, 0.0, 0.0
    kv_resident_peak, kv_read_bytes = 0, 0
    kv_config = None
    # speculative decoding (spec_verify spans): per-block acceptance →
    # accepted-tokens-per-pass percentiles, the draft/verify wall
    # split, and rollback counts.  Streams without spec events (every
    # pre-spec run, and non-spec engines) skip the whole section.
    spec_blocks, spec_tokens, spec_accepted, spec_drafted = 0, 0, 0, 0
    spec_rollbacks = 0
    spec_draft_s, spec_verify_s = 0.0, 0.0
    spec_per_pass: List[float] = []
    # disaggregated serving (tpudist.serve.disagg): spans tagged with
    # their pool; TTFT belongs to the prefill pool (token 0 is sampled
    # there) and TPOT to the decode pool, with the coordinator's
    # handoff-wait gap in between.
    pool_s: Dict[str, float] = {}
    pool_spans: Dict[str, int] = {}
    handoffs = 0
    handoff_import_s: List[float] = []
    disagg_config = None
    # fleet recovery (self-healing disagg): dead workers, lanes replayed
    # onto survivors, and backpressure-driven pool resizes
    workers_lost, lanes_recovered, pool_resizes = 0, 0, 0
    # host-RAM KV tier + overload control (tpudist.serve.host_tier /
    # .overload): park/resume/spill/corruption counts, preemptions, and
    # the shed-state flips — absent entirely from old streams, so the
    # section below is purely additive
    tier_parks, tier_spills, tier_corrupt, tier_expired = 0, 0, 0, 0
    tier_resumes: Dict[str, int] = {}
    tier_bytes_peak = 0
    preempted_events, shed_flips = 0, 0
    shed_last: Optional[dict] = None
    # per-tenant adapters (tpudist.serve.adapters): pool geometry stamp,
    # load/evict churn, peak residency — absent entirely from old
    # streams, so the section below is purely additive
    ad_config: Optional[dict] = None
    ad_loads, ad_evicts = 0, 0
    ad_evict_kinds: Dict[str, int] = {}
    ad_resident_peak = 0
    # structured output (tpudist.constrain): the serve_constrain_config
    # stamp, per-request constrained/stop/logprobs tags on
    # request_finished, and pool-full admission deferrals — absent
    # entirely from old streams, so the section below is purely additive
    cn_config: Optional[dict] = None
    cn_deferred = 0
    # fleet router (tpudist.serve.router): routing split, spills,
    # re-home retries, replica deaths, session migrations — absent
    # entirely from single-replica streams, so the section below is
    # purely additive
    rt_config: Optional[dict] = None
    rt_routes: Dict[str, int] = {}
    rt_spills, rt_retries, rt_deaths, rt_errors = 0, 0, 0, 0
    rt_migrations: Dict[str, int] = {}
    # online draft distillation (tpudist.distill): distill_round /
    # draft_swap events — absent entirely from old streams, so the
    # section below is purely additive
    di_rounds, di_swaps = 0, 0
    di_reasons: Dict[str, int] = {}
    di_swap_s: List[float] = []
    di_gain: List[float] = []
    di_last: Optional[dict] = None
    for r in records:
        if (r.get("kind") == "event"
                and r.get("name") == "serve_kv_config"):
            kv_config = r  # last one wins (restart/regeneration)
            continue
        if (r.get("kind") == "event"
                and r.get("name") == "serve_adapters_config"):
            ad_config = r
            continue
        if r.get("kind") == "event" \
                and r.get("name") in ("adapter_load", "adapter_evict"):
            if r.get("name") == "adapter_load":
                ad_loads += 1
            else:
                ad_evicts += 1
                k = str(r.get("evict_kind", "?"))
                ad_evict_kinds[k] = ad_evict_kinds.get(k, 0) + 1
            if isinstance(r.get("resident"), (int, float)):
                ad_resident_peak = max(ad_resident_peak,
                                       int(r["resident"]))
            continue
        if (r.get("kind") == "event"
                and r.get("name") == "serve_constrain_config"):
            cn_config = r  # last one wins (restart/regeneration)
            continue
        if (r.get("kind") == "event"
                and r.get("name") == "constrain_deferred"):
            cn_deferred += int(r.get("n", 1) or 0)
            continue
        if (r.get("kind") == "event"
                and r.get("name") == "serve_disagg_config"):
            disagg_config = r
            continue
        if r.get("kind") == "event" and r.get("name") == "kv_handoff":
            handoffs += 1
            if isinstance(r.get("import_s"), (int, float)):
                handoff_import_s.append(float(r["import_s"]))
            continue
        if r.get("kind") == "event" \
                and r.get("name") in ("distill_round", "draft_swap"):
            if r.get("name") == "distill_round":
                di_rounds += 1
                k = str(r.get("reason", "?"))
                di_reasons[k] = di_reasons.get(k, 0) + 1
                ca, b = r.get("candidate_acceptance"), r.get("baseline")
                if (r.get("swapped") and isinstance(ca, (int, float))
                        and isinstance(b, (int, float))):
                    di_gain.append(float(ca) - float(b))
                di_last = r
            else:
                di_swaps += 1
                if isinstance(r.get("swap_s"), (int, float)):
                    di_swap_s.append(float(r["swap_s"]))
            continue
        if r.get("kind") == "event" and r.get("name") == "worker_lost":
            workers_lost += 1
            continue
        if r.get("kind") == "event" and r.get("name") == "lane_recovered":
            lanes_recovered += 1
            continue
        if r.get("kind") == "event" and r.get("name") == "pool_resize":
            pool_resizes += 1
            continue
        if r.get("kind") == "event" and r.get("name") in (
                "router_config", "router_route", "router_spill",
                "router_retry", "replica_health", "session_migrated",
                "router_error"):
            name = r.get("name")
            if name == "router_config":
                rt_config = r  # last one wins (restart/regeneration)
            elif name == "router_route":
                k = str(r.get("route_kind", "?"))
                rt_routes[k] = rt_routes.get(k, 0) + 1
            elif name == "router_spill":
                rt_spills += 1
            elif name == "router_retry":
                rt_retries += 1
            elif name == "replica_health":
                if not r.get("up"):
                    rt_deaths += 1
            elif name == "session_migrated":
                k = "ok" if r.get("ok") else "degraded"
                rt_migrations[k] = rt_migrations.get(k, 0) + 1
            elif name == "router_error":
                rt_errors += 1
            continue
        if r.get("kind") == "event":
            name = r.get("name")
            if name in ("session_parked", "session_resumed",
                        "host_tier_spill", "session_expired",
                        "host_tier_corrupt", "preempted", "shed_state"):
                if name == "session_parked":
                    tier_parks += 1
                elif name == "session_resumed":
                    kind = str(r.get("park_kind", "turn"))
                    tier_resumes[kind] = tier_resumes.get(kind, 0) + 1
                elif name == "host_tier_spill":
                    tier_spills += int(r.get("entries", 1) or 1)
                elif name == "session_expired":
                    tier_expired += int(r.get("entries", 1) or 1)
                elif name == "host_tier_corrupt":
                    tier_corrupt += 1
                elif name == "preempted":
                    preempted_events += 1
                elif name == "shed_state":
                    shed_flips += 1
                    shed_last = {"active": bool(r.get("active")),
                                 "target": r.get("target"),
                                 "attainment": r.get("attainment")}
                if isinstance(r.get("tier_bytes"), (int, float)):
                    tier_bytes_peak = max(tier_bytes_peak,
                                          int(r["tier_bytes"]))
                continue
        if r.get("kind") != "span":
            continue
        pool = r.get("pool")
        if isinstance(pool, str):
            pool_s[pool] = pool_s.get(pool, 0.0) + float(r.get("dur", 0.0))
            pool_spans[pool] = pool_spans.get(pool, 0) + 1
        if r.get("name") in ("decode_block", "decode_step", "spec_verify"):
            serve_spans += 1
            decode_blocks += 1
            dur = float(r.get("dur", 0.0))
            decode_s += dur
            decode_tokens += int(r.get("tokens", 0) or 0)
            dispatch_s += float(r.get("dispatch_s", 0.0) or 0.0)
            sync_s += float(r.get("sync_s", 0.0) or 0.0)
            if r.get("name") == "spec_verify":
                spec_blocks += 1
                toks = int(r.get("tokens", 0) or 0)
                spec_tokens += toks
                spec_accepted += int(r.get("accepted", 0) or 0)
                spec_drafted += int(r.get("drafted", 0) or 0)
                spec_rollbacks += int(r.get("rollbacks", 0) or 0)
                spec_draft_s += float(r.get("draft_s", 0.0) or 0.0)
                spec_verify_s += float(r.get("verify_s", 0.0) or 0.0)
                active = int(r.get("active", 0) or 0)
                if active > 0:
                    spec_per_pass.append(toks / active)
            occ = r.get("occupancy")
            if isinstance(occ, (int, float)):
                occ_w += float(occ) * dur
                occ_dur += dur
                occ_max = max(occ_max, float(occ))
            kocc = r.get("kv_block_occupancy")
            if isinstance(kocc, (int, float)):
                kv_occ_w += float(kocc) * dur
                kv_occ_dur += dur
                kv_occ_max = max(kv_occ_max, float(kocc))
            if isinstance(r.get("kv_bytes_resident"), (int, float)):
                kv_resident_peak = max(kv_resident_peak,
                                       int(r["kv_bytes_resident"]))
            kv_read_bytes += int(r.get("kv_read_bytes", 0) or 0)
        elif r.get("name") == "prefill":
            serve_spans += 1
            prefill_s += float(r.get("dur", 0.0))
    if not fins and not serve_spans and not rejects:
        return None

    def _pcts(key):
        vals = sorted(float(r[key]) for r in fins
                      if isinstance(r.get(key), (int, float)))
        if not vals:
            return None
        return {"p50_s": round(_percentile(vals, 50), 6),
                "p95_s": round(_percentile(vals, 95), 6),
                "max_s": round(vals[-1], 6)}

    reasons: Dict[str, int] = {}
    for r in fins:
        reasons[str(r.get("reason"))] = reasons.get(str(r.get("reason")), 0) + 1
    tokens_out = sum(int(r.get("tokens_out", 0)) for r in fins)
    busy = decode_s + prefill_s
    # host-tier occupancy rides in the kv section (it IS kv — the tier
    # below the pool); resume-TTFT quotes the no-recompute claim
    # directly from the finish-reason split
    tier_any = (tier_parks or tier_resumes or tier_spills or tier_corrupt
                or tier_expired or preempted_events)
    host_tier: Optional[dict] = None
    if tier_any:
        resumed_ttft = sorted(
            float(r["ttft_s"]) for r in fins
            if r.get("reason") == "session_resumed"
            and isinstance(r.get("ttft_s"), (int, float)))
        host_tier = {
            "parks": tier_parks,
            "resumes": dict(tier_resumes),
            "spills": tier_spills,
            "corrupt": tier_corrupt,
            "expired": tier_expired,
            "bytes_peak": tier_bytes_peak or None,
            "preemptions": preempted_events,
            "resume_ttft": ({
                "p50_s": round(_percentile(resumed_ttft, 50), 6),
                "p95_s": round(_percentile(resumed_ttft, 95), 6),
                "max_s": round(resumed_ttft[-1], 6)}
                if resumed_ttft else None),
        }
    overload: Optional[dict] = None
    if shed_flips or reasons.get("shed_load"):
        overload = {
            "shed_state_changes": shed_flips,
            "last_shed_state": shed_last,
            "shed_finished": reasons.get("shed_load", 0),
        }
    kv: Optional[dict] = None
    if kv_config is not None or kv_occ_dur > 0 or kv_read_bytes \
            or host_tier is not None:
        kv = {
            # static geometry from the serve_kv_config stamp
            **({"paged": kv_config.get("paged"),
                "quantized": kv_config.get("quantized"),
                # which decode-attention path produced read_bytes —
                # live-KV accounting (paged kernel) vs pool-geometry
                # accounting (gather/dense) are different quantities
                "attn_kernel": kv_config.get("attn_kernel"),
                "block_size": kv_config.get("block_size"),
                "blocks_total": kv_config.get("blocks_total"),
                "pool_bytes": kv_config.get("pool_bytes"),
                "bytes_per_pos": kv_config.get("bytes_per_pos")}
               if kv_config is not None else {}),
            # measured residency/bandwidth gauges
            "block_occupancy_mean": (round(kv_occ_w / kv_occ_dur, 4)
                                     if kv_occ_dur > 0 else None),
            "block_occupancy_max": (round(kv_occ_max, 4)
                                    if kv_occ_dur > 0 else None),
            "bytes_resident_peak": kv_resident_peak or None,
            "read_bytes": kv_read_bytes or None,
            # decode bytes/token: what the attention streamed per
            # emitted token — the int8 path halves-or-betters this
            "read_bytes_per_token": (round(kv_read_bytes / decode_tokens, 1)
                                     if decode_tokens and kv_read_bytes
                                     else None),
            **({"host_tier": host_tier} if host_tier is not None else {}),
        }
    adapters: Optional[dict] = None
    if ad_config is not None or ad_loads or ad_evicts \
            or any(r.get("adapter") for r in fins):
        by_adapter: Dict[str, int] = {}
        for r in fins:
            a = r.get("adapter")
            if isinstance(a, str) and a:
                by_adapter[a] = by_adapter.get(a, 0) + 1
        adapters = {
            **({"blocks": ad_config.get("blocks"),
                # "rank" is reserved on the wire (process rank); the
                # LoRA rank rides as lora_rank
                "rank": ad_config.get("lora_rank"),
                "block_bytes": ad_config.get("block_bytes"),
                "pool_bytes": ad_config.get("pool_bytes")}
               if ad_config is not None else {}),
            "loads": ad_loads,
            "evicts": ad_evicts,
            **({"evict_kinds": ad_evict_kinds} if ad_evict_kinds else {}),
            "resident_peak": ad_resident_peak or None,
            # per-adapter served-request split (the multi-tenant story:
            # which fine-tunes the traffic actually hit)
            "requests": by_adapter,
            "base_only_requests": len(fins) - sum(by_adapter.values()),
            "missing_finished": reasons.get("adapter_missing", 0),
        }
    constrained: Optional[dict] = None
    if cn_config is not None or cn_deferred \
            or any(r.get("constrained") for r in fins):
        by_kind: Dict[str, int] = {}
        lp_requests = 0
        for r in fins:
            k = r.get("constrained")
            if isinstance(k, str) and k:
                by_kind[k] = by_kind.get(k, 0) + 1
            if r.get("logprobs"):
                lp_requests += 1
        constrained = {
            **({"blocks": cn_config.get("blocks"),
                "max_states": cn_config.get("max_states"),
                "pool_bytes": cn_config.get("pool_bytes"),
                "logprobs_width": cn_config.get("logprobs")}
               if cn_config is not None else {}),
            # per-grammar-kind served-request split (regex vs schema)
            "requests": by_kind,
            "free_requests": len(fins) - sum(by_kind.values()),
            "deferred": cn_deferred,
            # both should stay 0 in healthy runs: violations mean the
            # device mask and the host shadow diverged; stop_sequence
            # is here because the stop satellite shares the section
            "violations_finished": reasons.get("grammar_violation", 0),
            "stop_finished": reasons.get("stop_sequence", 0),
            "logprobs_requests": lp_requests,
        }
    spec: Optional[dict] = None
    if spec_blocks:
        pp = sorted(spec_per_pass)
        spec = {
            "blocks": spec_blocks,
            "tokens": spec_tokens,
            "accepted": spec_accepted,
            "drafted": spec_drafted,
            "acceptance_rate": (round(spec_accepted / spec_drafted, 4)
                                if spec_drafted else None),
            "rollbacks": spec_rollbacks,
            # emitted tokens per verify pass PER LANE — the
            # fewer-target-passes-per-token headline (1.0 = no better
            # than plain decode; the pass emits accepted + 1)
            "accepted_per_pass": ({
                "mean": round(sum(pp) / len(pp), 4),
                "p50": round(_percentile(pp, 50), 4),
                "p95": round(_percentile(pp, 95), 4),
                "max": round(pp[-1], 4)} if pp else None),
            "draft_s": round(spec_draft_s, 6),
            "verify_s": round(spec_verify_s, 6),
        }
    distill: Optional[dict] = None
    if di_rounds or di_swaps:
        sw = sorted(di_swap_s)
        distill = {
            "rounds": di_rounds,
            "swaps": di_swaps,
            # why each round did / didn't swap — "measured_win" is the
            # happy path, everything else is the gate holding the line
            "round_reasons": di_reasons,
            # holdout acceptance gain of APPLIED candidates over the
            # gate baseline (max(serving-on-holdout, live rate))
            "acceptance_gain": ({
                "mean": round(sum(di_gain) / len(di_gain), 4),
                "max": round(max(di_gain), 4)} if di_gain else None),
            "swap_s": ({
                "p50": round(_percentile(sw, 50), 6),
                "max": round(sw[-1], 6)} if sw else None),
            **({"capture": {
                k: di_last[k] for k in
                ("capture_streams", "capture_tokens", "capture_evicted")
                if k in di_last}} if di_last is not None else {}),
        }
    pools: Optional[dict] = None
    if (pool_s or disagg_config is not None or handoffs
            or workers_lost or lanes_recovered):
        hwaits = sorted(float(r["handoff_wait_s"]) for r in fins
                        if isinstance(r.get("handoff_wait_s"), (int, float)))
        pools = {
            **({"config": {k: v for k, v in disagg_config.items()
                           if k not in ("kind", "name", "t", "dur",
                                        "rank", "gen")}}
               if disagg_config is not None else {}),
            "prefill": {
                "span_s": round(pool_s.get("prefill", 0.0), 6),
                "spans": pool_spans.get("prefill", 0),
                # token 0 is sampled in the prefill pool: TTFT is ITS
                # latency number (queue wait included)
                "ttft": _pcts("ttft_s"),
            },
            "decode": {
                "span_s": round(pool_s.get("decode", 0.0), 6),
                "spans": pool_spans.get("decode", 0),
                "tpot": _pcts("tpot_s"),
            },
            "handoffs": handoffs,
            "handoff_wait": ({
                "p50_s": round(_percentile(hwaits, 50), 6),
                "p95_s": round(_percentile(hwaits, 95), 6),
                "max_s": round(hwaits[-1], 6)} if hwaits else None),
            "handoff_import": ({
                "p50_s": round(_percentile(sorted(handoff_import_s), 50), 6),
                "max_s": round(max(handoff_import_s), 6)}
                if handoff_import_s else None),
            "workers_lost": workers_lost,
            "lanes_recovered": lanes_recovered,
            "pool_resizes": pool_resizes,
        }
    fleet: Optional[dict] = None
    if rt_config is not None or rt_routes or rt_spills or rt_retries \
            or rt_deaths or rt_migrations:
        fleet = {
            **({"replicas": rt_config.get("replicas"),
                "policy": rt_config.get("policy")}
               if rt_config is not None else {}),
            # routing split by affinity kind (session/prefix/
            # least_loaded/spill/rr) — the affinity-hit headline
            "routes": dict(rt_routes),
            "spills": rt_spills,
            "retries": rt_retries,
            "replica_deaths": rt_deaths,
            # re-home retries that replayed a stream: the per-request
            # failover count (replica_lost in finish_reasons is the
            # budget-exhausted tail)
            "lost_finished": reasons.get("replica_lost", 0),
            **({"migrations": dict(rt_migrations)}
               if rt_migrations else {}),
            **({"router_errors": rt_errors} if rt_errors else {}),
        }
    return {
        "requests_finished": len(fins),
        "requests_rejected": rejects,
        "finish_reasons": reasons,
        "tokens_out": tokens_out,
        "decode_s": round(decode_s, 6),
        "prefill_s": round(prefill_s, 6),
        "decode_blocks": decode_blocks,
        "decode_tokens": decode_tokens,
        "tokens_per_dispatch": (round(decode_tokens / decode_blocks, 3)
                                if decode_blocks else None),
        "dispatch_s": round(dispatch_s, 6),
        "host_sync_s": round(sync_s, 6),
        "tokens_per_s_busy": round(tokens_out / busy, 3) if busy > 0 else None,
        "ttft": _pcts("ttft_s"),
        "tpot": _pcts("tpot_s"),
        "queue_wait": _pcts("queue_wait_s"),
        "occupancy_mean": round(occ_w / occ_dur, 4) if occ_dur > 0 else None,
        "occupancy_max": round(occ_max, 4) if occ_dur > 0 else None,
        **({"kv": kv} if kv is not None else {}),
        **({"adapters": adapters} if adapters is not None else {}),
        # constrained section only when structured output ran — old
        # streams aggregate byte-identically without it
        **({"constrained": constrained} if constrained is not None else {}),
        **({"spec": spec} if spec is not None else {}),
        # distill section only when the flywheel ran — old streams (and
        # capture-off runs) aggregate byte-identically without it
        **({"distill": distill} if distill is not None else {}),
        **({"pools": pools} if pools is not None else {}),
        **({"overload": overload} if overload is not None else {}),
        # fleet section only when a router ran — single-replica streams
        # (every pre-router run) aggregate byte-identically without it
        **({"fleet": fleet} if fleet is not None else {}),
        # SLO section only when targets were declared — old streams (and
        # target-less runs) aggregate byte-identically without it
        **({"slo": _slo_summary(fins, slo_config)}
           if slo_config is not None else {}),
    }


def aggregate_run(run_dir: "str | Path") -> dict:
    """Merge a run's telemetry into the report dict (see module doc)."""
    records = load_records(run_dir)
    if not records:
        return {"error": f"no telemetry records under {run_dir}",
                "num_records": 0}

    by_rank: Dict[int, List[dict]] = {}
    for r in records:
        by_rank.setdefault(int(r.get("rank", 0)), []).append(r)
    # Event-only streams (e.g. the tpurun agent's staging events) carry
    # no wall-clock to account — they contribute events/stages below but
    # must not enter the per-rank goodput means as phantom zero-wall ranks.
    span_ranks = sorted(
        k for k, rs in by_rank.items()
        if any(r.get("kind") == "span" for r in rs)) or sorted(by_rank)
    per_rank = [_rank_breakdown(by_rank[k]) for k in span_ranks]

    n = len(per_rank)
    wall_mean = sum(p["wall_s"] for p in per_rank) / n
    goodput = {}
    for c in COMPONENTS:
        s = sum(p["components_s"][c] for p in per_rank) / n
        goodput[c] = {
            "s": round(s, 6),
            "frac": round(s / wall_mean, 6) if wall_mean > 0 else 0.0,
        }
    goodput_sum = sum(v["s"] for v in goodput.values())

    # Straggler view: the rank spending the most step time and the one
    # idling the most, with the spread that makes it a straggler.
    step_per_rank = {p["rank"]: p["components_s"]["step"] for p in per_rank}
    max_rank = max(step_per_rank, key=step_per_rank.get)
    min_rank = min(step_per_rank, key=step_per_rank.get)

    stages: Dict[str, float] = {}
    events: List[dict] = []
    for r in records:
        if r.get("kind") != "event":
            continue
        if r.get("name") == "stage" and "stage" in r:
            stages[r["stage"]] = stages.get(r["stage"], 0.0) + float(
                r.get("dur_s", 0.0))
        elif r.get("name") in _REPORTED_EVENTS:
            events.append(r)
    events.sort(key=lambda e: e.get("t", 0.0))

    # Telemetry self-accounting: sessions that dropped records (ring
    # eviction past the bound, stream write failures) say so at close —
    # totaled here so a truncated report ANNOUNCES its truncation.
    # Absent entirely (not zero) for streams without the event, keeping
    # old-stream aggregation byte-identical.
    dropped = {"ring": 0, "write": 0}
    have_drops = False
    for e in events:
        if e.get("name") == "telemetry_dropped":
            have_drops = True
            for k in ("ring", "write"):
                v = e.get(k)
                if isinstance(v, (int, float)):
                    dropped[k] += int(v)

    # Generation-stamped world sizes merged across ranks (the elastic
    # story: gen → how many processes that generation ran with).
    world_sizes: Dict[str, int] = {}
    for p in per_rank:
        for g, w in p.get("worlds", {}).items():
            world_sizes[g] = max(world_sizes.get(g, 0), int(w))

    report = {
        "num_records": len(records),
        "num_ranks": n,
        "generations": max(p["generations"] for p in per_rank),
        **({"world_sizes": {g: world_sizes[g]
                            for g in sorted(world_sizes, key=int)}}
           if world_sizes else {}),
        "wall_clock_s": round(wall_mean, 6),
        "run_span_s": round(
            max(p["t1"] for p in per_rank) - min(p["t0"] for p in per_rank),
            6),
        "step": _step_stats(records, num_ranks=n),
        "goodput": goodput,
        "goodput_sum_s": round(goodput_sum, 6),
        "stragglers": {
            "max_step_rank": max_rank,
            "max_step_s": round(step_per_rank[max_rank], 6),
            "min_step_rank": min_rank,
            "min_step_s": round(step_per_rank[min_rank], 6),
        },
        "per_rank": [
            {
                "rank": p["rank"],
                "generations": p["generations"],
                "wall_s": round(p["wall_s"], 6),
                "overlap_s": round(p["overlap_s"], 6),
                **{c: round(p["components_s"][c], 6) for c in COMPONENTS},
            }
            for p in per_rank
        ],
        "stages": {k: round(v, 6) for k, v in sorted(stages.items())},
        "events": events,
        **({"telemetry_dropped": dropped} if have_drops else {}),
    }
    serving = _serving_summary(records)
    if serving is not None:
        report["serving"] = serving
    # Measurement-driven planner (tpudist.plan): the plan_selected
    # stamps auto mode emitted — prediction next to the measured step/
    # TPOT numbers above.  Additive: absent entirely for streams
    # without the event (old-stream reports stay byte-identical).
    plans = [
        {k: e[k] for k in ("workload", "chosen", "predicted_s",
                           "predicted_ttft_s", "n_candidates",
                           "measured_components",
                           "extrapolated_components", "artifact_rounds",
                           "error_band_frac") if k in e}
        for e in events if e.get("name") == "plan_selected"
    ]
    if plans:
        report["plan"] = plans
    return report


def render_markdown(report: dict) -> str:
    """The human-readable twin of ``report.json``."""
    if report.get("num_records", 0) == 0:
        return f"# tpudist run report\n\n{report.get('error', 'no data')}\n"
    lines = ["# tpudist run report", ""]
    lines.append(
        f"- wall-clock (mean over {report['num_ranks']} rank"
        f"{'s' if report['num_ranks'] != 1 else ''}): "
        f"**{report['wall_clock_s']:.3f} s** "
        f"(run envelope {report['run_span_s']:.3f} s, "
        f"{report['generations']} process generation"
        f"{'s' if report['generations'] != 1 else ''})")
    if report.get("world_sizes"):
        lines.append(
            "- world size by generation: "
            + ", ".join(f"gen {g} → {w}"
                        for g, w in report["world_sizes"].items()))
    st = report["step"]
    lines.append(
        f"- steps: {st['count']} in {st['total_s']:.3f} s "
        f"({st['steps_per_s']:.1f} steps/s) — "
        f"p50 {st['p50_s'] * 1e3:.2f} ms, p95 {st['p95_s'] * 1e3:.2f} ms, "
        f"max {st['max_s'] * 1e3:.2f} ms")
    lines += ["", "## Goodput breakdown", "",
              "| component | seconds | % of wall |",
              "|---|---:|---:|"]
    for c in COMPONENTS:
        v = report["goodput"][c]
        lines.append(f"| {c} | {v['s']:.3f} | {v['frac'] * 100:.1f}% |")
    lines.append(f"| **total** | {report['goodput_sum_s']:.3f} | "
                 f"{report['goodput_sum_s'] / report['wall_clock_s'] * 100:.1f}% |"
                 if report["wall_clock_s"] > 0 else "| **total** | 0 | - |")
    sg = report["stragglers"]
    lines += ["", "## Per-rank", "",
              f"straggler: rank {sg['max_step_rank']} spent "
              f"{sg['max_step_s']:.3f} s in steps vs rank "
              f"{sg['min_step_rank']}'s {sg['min_step_s']:.3f} s", "",
              "| rank | gens | wall s | step | compile | data | ckpt | comm "
              "| init | other | idle | resize | lost_restart |",
              "|---:|---:|---:|---:|---:|---:|---:|---:|---:|---:|---:|---:"
              "|---:|"]
    for p in report["per_rank"]:
        lines.append(
            f"| {p['rank']} | {p['generations']} | {p['wall_s']:.3f} | "
            + " | ".join(f"{p[c]:.3f}" for c in COMPONENTS) + " |")
    if report.get("serving"):
        sv = report["serving"]
        lines += ["", "## Serving", ""]
        lines.append(
            f"- requests: {sv['requests_finished']} finished "
            f"({sv['finish_reasons']}), {sv['requests_rejected']} rejected")
        lines.append(
            f"- tokens out: {sv['tokens_out']} — decode {sv['decode_s']:.3f} s"
            f" + prefill {sv['prefill_s']:.3f} s"
            + (f" → {sv['tokens_per_s_busy']:.1f} tok/s busy"
               if sv["tokens_per_s_busy"] else ""))
        if sv.get("decode_blocks"):
            lines.append(
                f"- decode dispatch overhead: {sv['decode_blocks']} blocks, "
                f"{sv['tokens_per_dispatch']} tok/dispatch, host sync "
                f"{sv['host_sync_s']:.3f} s of {sv['decode_s']:.3f} s decode")
        for label, key in (("TTFT", "ttft"), ("TPOT", "tpot"),
                           ("queue wait", "queue_wait")):
            v = sv.get(key)
            if v:
                lines.append(
                    f"- {label}: p50 {v['p50_s'] * 1e3:.1f} ms, "
                    f"p95 {v['p95_s'] * 1e3:.1f} ms, "
                    f"max {v['max_s'] * 1e3:.1f} ms")
        if sv.get("occupancy_mean") is not None:
            lines.append(
                f"- batch occupancy: mean {sv['occupancy_mean']:.2f}, "
                f"max {sv['occupancy_max']:.2f}")
        if sv.get("slo"):
            slo = sv["slo"]
            tgt = ", ".join(f"{k.replace('_ms', '')} ≤ {v:g} ms"
                            for k, v in slo["targets_ms"].items())
            ov = slo["overall"]
            bits = [f"targets: {tgt}"]
            if ov.get("attainment") is not None:
                bits.append(f"overall attainment "
                            f"{ov['attainment'] * 100:.1f}%")
            for t, row in slo["per_tenant"].items():
                if row.get("attainment") is not None:
                    bits.append(f"{t}: {row['attainment'] * 100:.1f}% "
                                f"({row['requests']} reqs)")
            lines.append("- SLO: " + "; ".join(bits))
        if sv.get("adapters"):
            ad = sv["adapters"]
            bits = []
            if ad.get("blocks") is not None:
                bits.append(f"pool {ad['blocks']} blocks × rank "
                            f"{ad['rank']}")
            bits.append(f"{ad['loads']} loads / {ad['evicts']} evicts")
            if ad.get("resident_peak"):
                bits.append(f"peak resident {ad['resident_peak']}")
            if ad.get("requests"):
                served = ", ".join(f"{n}: {c}" for n, c in
                                   sorted(ad["requests"].items()))
                bits.append(f"requests by adapter ({served}; base "
                            f"{ad['base_only_requests']})")
            if ad.get("missing_finished"):
                bits.append(f"{ad['missing_finished']} adapter_missing")
            lines.append("- adapters: " + "; ".join(bits))
        if sv.get("constrained"):
            cn = sv["constrained"]
            bits = []
            if cn.get("blocks") is not None:
                bits.append(f"pool {cn['blocks']} blocks × "
                            f"{cn['max_states']} states")
            if cn.get("requests"):
                served = ", ".join(f"{k}: {c}" for k, c in
                                   sorted(cn["requests"].items()))
                bits.append(f"constrained requests ({served}; free "
                            f"{cn['free_requests']})")
            if cn.get("deferred"):
                bits.append(f"{cn['deferred']} pool-full deferrals")
            if cn.get("violations_finished"):
                bits.append(f"{cn['violations_finished']} "
                            "grammar_violation")
            if cn.get("stop_finished"):
                bits.append(f"{cn['stop_finished']} stop_sequence")
            if cn.get("logprobs_requests"):
                bits.append(f"{cn['logprobs_requests']} logprobs "
                            f"requests (width {cn.get('logprobs_width')})")
            lines.append("- constrained: " + "; ".join(bits))
        if sv.get("spec"):
            sp = sv["spec"]
            app = sp.get("accepted_per_pass") or {}
            bits = [f"{sp['blocks']} verify passes",
                    f"{sp['accepted']}/{sp['drafted']} drafts accepted"
                    + (f" ({sp['acceptance_rate'] * 100:.0f}%)"
                       if sp.get("acceptance_rate") is not None else ""),
                    f"{sp['rollbacks']} rollbacks"]
            if app:
                bits.append(f"tokens/pass p50 {app['p50']:.2f} / "
                            f"p95 {app['p95']:.2f}")
            bits.append(f"draft {sp['draft_s']:.3f} s vs verify "
                        f"{sp['verify_s']:.3f} s")
            lines.append("- speculative decode: " + "; ".join(bits))
        if sv.get("distill"):
            di = sv["distill"]
            bits = [f"{di['rounds']} rounds", f"{di['swaps']} swaps"]
            if di.get("round_reasons"):
                why = ", ".join(f"{k}: {c}" for k, c in
                                sorted(di["round_reasons"].items()))
                bits.append(f"gate ({why})")
            if di.get("acceptance_gain"):
                bits.append("acceptance gain mean "
                            f"{di['acceptance_gain']['mean']:+.3f}")
            if di.get("swap_s"):
                bits.append(f"swap p50 {di['swap_s']['p50'] * 1e3:.1f} ms")
            lines.append("- draft distillation: " + "; ".join(bits))
        if sv.get("pools"):
            pp = sv["pools"]
            bits = [f"prefill {pp['prefill']['span_s']:.3f} s "
                    f"({pp['prefill']['spans']} spans)",
                    f"decode {pp['decode']['span_s']:.3f} s "
                    f"({pp['decode']['spans']} spans)",
                    f"{pp['handoffs']} KV handoffs"]
            hw = pp.get("handoff_wait")
            if hw:
                bits.append(f"handoff wait p50 {hw['p50_s'] * 1e3:.1f} ms / "
                            f"p95 {hw['p95_s'] * 1e3:.1f} ms")
            if pp.get("workers_lost"):
                bits.append(f"{pp['workers_lost']} worker(s) lost, "
                            f"{pp['lanes_recovered']} lane(s) recovered")
            if pp.get("pool_resizes"):
                bits.append(f"{pp['pool_resizes']} backpressure resize(s)")
            lines.append("- disaggregated pools: " + "; ".join(bits))
            for label, pool, key in (("TTFT", "prefill", "ttft"),
                                     ("TPOT", "decode", "tpot")):
                v = pp[pool].get(key)
                if v:
                    lines.append(
                        f"  - {pool}-pool {label}: p50 "
                        f"{v['p50_s'] * 1e3:.1f} ms, "
                        f"p95 {v['p95_s'] * 1e3:.1f} ms")
        if sv.get("kv"):
            kv = sv["kv"]
            bits = []
            if kv.get("paged"):
                bits.append(
                    f"paged ({kv.get('blocks_total')} × "
                    f"{kv.get('block_size')}-token blocks"
                    + (", int8" if kv.get("quantized") else "") + ")")
            elif kv.get("paged") is False:
                bits.append("dense arena")
            if kv.get("block_occupancy_mean") is not None:
                bits.append(f"block occupancy mean "
                            f"{kv['block_occupancy_mean']:.2f} / max "
                            f"{kv['block_occupancy_max']:.2f}")
            if kv.get("bytes_resident_peak"):
                bits.append(f"peak resident "
                            f"{kv['bytes_resident_peak']:,} B")
            if kv.get("read_bytes_per_token"):
                # which attention path produced the number: live-KV
                # accounting (paged kernel) vs pool-geometry (gather)
                via = (f" via {kv['attn_kernel']}"
                       if kv.get("attn_kernel") else "")
                bits.append(f"decode streams "
                            f"{kv['read_bytes_per_token']:,.0f} B/token"
                            f"{via}")
            lines.append("- KV cache: " + "; ".join(bits))
            if kv.get("host_tier"):
                ht = kv["host_tier"]
                res = ht.get("resumes") or {}
                bits = [f"{ht['parks']} parks",
                        f"{sum(res.values())} resumes ({res})" if res
                        else "0 resumes",
                        f"{ht['spills']} spills",
                        f"{ht['preemptions']} preemptions"]
                if ht.get("corrupt"):
                    bits.append(f"{ht['corrupt']} corrupt (re-prefilled)")
                if ht.get("expired"):
                    bits.append(f"{ht['expired']} expired")
                if ht.get("bytes_peak"):
                    bits.append(f"peak {ht['bytes_peak']:,} B host RAM")
                rt = ht.get("resume_ttft")
                if rt:
                    bits.append(f"resume TTFT p50 {rt['p50_s'] * 1e3:.1f} "
                                f"ms / p95 {rt['p95_s'] * 1e3:.1f} ms")
                lines.append("- KV host tier: " + "; ".join(bits))
        if sv.get("overload"):
            ov = sv["overload"]
            last = ov.get("last_shed_state") or {}
            state = ("active" if last.get("active") else "inactive")
            lines.append(
                f"- overload control: {ov['shed_finished']} shed, "
                f"{ov['shed_state_changes']} shed-state change(s), "
                f"last {state}"
                + (f" at attainment {last.get('attainment')}"
                   if last.get("attainment") else ""))
        if sv.get("fleet"):
            fl = sv["fleet"]
            routes = ", ".join(f"{k}: {c}" for k, c in
                               sorted(fl.get("routes", {}).items()))
            bits = []
            if fl.get("replicas") is not None:
                bits.append(f"{fl['replicas']} replicas "
                            f"({fl.get('policy', '?')})")
            if routes:
                bits.append(f"routes by kind ({routes})")
            bits.append(f"{fl['spills']} spill(s), {fl['retries']} "
                        f"re-home retry(ies)")
            if fl.get("replica_deaths"):
                mig = fl.get("migrations", {})
                bits.append(f"{fl['replica_deaths']} replica death(s), "
                            f"{mig.get('ok', 0)} session(s) migrated, "
                            f"{fl.get('lost_finished', 0)} lost")
            lines.append("- fleet router: " + "; ".join(bits))
    if report.get("plan"):
        lines += ["", "## Plan (auto mode)", ""]
        for p in report["plan"]:
            bits = [f"chose **{p.get('chosen', '?')}** "
                    f"of {p.get('n_candidates', '?')} candidates",
                    f"predicted {p.get('predicted_s', 0) * 1e3:.3f} ms"]
            if p.get("predicted_ttft_s") is not None:
                bits.append(f"TTFT {p['predicted_ttft_s'] * 1e3:.1f} ms")
            bits.append(f"{p.get('measured_components', 0)} measured / "
                        f"{p.get('extrapolated_components', 0)} "
                        "extrapolated components")
            if p.get("error_band_frac") is not None:
                bits.append(f"error band ±{p['error_band_frac'] * 100:.1f}%")
            lines.append(f"- {p.get('workload', '?')}: " + "; ".join(bits))
            if p.get("artifact_rounds"):
                lines.append(f"  - artifacts: {p['artifact_rounds']}")
    if report.get("telemetry_dropped"):
        td = report["telemetry_dropped"]
        lines += ["", f"**⚠ telemetry dropped records** — ring evictions: "
                      f"{td.get('ring', 0)}, stream write failures: "
                      f"{td.get('write', 0)} (this report is incomplete)"]
    if report.get("stages"):
        lines += ["", "## Host stages (StageTimer)", ""]
        for k, v in report["stages"].items():
            lines.append(f"- {k}: {v:.3f} s")
    if report.get("events"):
        lines += ["", "## Events", ""]
        for e in report["events"]:
            tags = {k: v for k, v in e.items()
                    if k not in ("kind", "name", "t", "dur")}
            lines.append(f"- t={e.get('t', 0.0):.3f} **{e['name']}** {tags}")
    lines.append("")
    return "\n".join(lines)


def write_reports(run_dir: "str | Path",
                  out_dir: "str | Path | None" = None
                  ) -> Tuple[dict, Dict[str, Optional[Path]]]:
    """Aggregate ``run_dir`` and write ``report.json`` + ``report.md``
    (into ``out_dir``, default: the telemetry dir itself).  Returns
    ``(report, {"json": path, "md": path})``; paths are ``None`` for
    files that could not be written (the report dict is still returned)."""
    tdir = find_telemetry_dir(run_dir)
    report = aggregate_run(tdir)
    out = Path(out_dir) if out_dir is not None else tdir
    paths: Dict[str, Optional[Path]] = {"json": None, "md": None}
    try:
        out.mkdir(parents=True, exist_ok=True)
        jp = out / "report.json"
        jp.write_text(json.dumps(report, indent=2) + "\n")
        paths["json"] = jp
        mp = out / "report.md"
        mp.write_text(render_markdown(report))
        paths["md"] = mp
    except OSError:
        pass
    return report, paths
