"""``python -m tpudist.telemetry report <run_dir>`` — post-hoc report CLI.

Aggregates every ``rank*_gen*.jsonl`` under ``<run_dir>`` (or its
``telemetry/`` subdirectory) into ``report.json`` + ``report.md`` and
prints the markdown.  No jax required — runs anywhere the JSONL landed.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tpudist.telemetry",
        description="tpudist telemetry tools")
    sub = p.add_subparsers(dest="cmd", required=True)
    rp = sub.add_parser(
        "report",
        help="merge a run's per-rank telemetry JSONL into "
             "report.json + report.md")
    rp.add_argument("run_dir",
                    help="telemetry dir (or a run dir with a telemetry/ "
                         "subdirectory)")
    rp.add_argument("--out-dir", default=None,
                    help="where to write report.json/report.md "
                         "(default: the telemetry dir)")
    rp.add_argument("--json", action="store_true", dest="json_out",
                    help="print report.json instead of the markdown")
    tp = sub.add_parser(
        "trace",
        help="export a run's per-request lifelines as a Perfetto-"
             "loadable Chrome trace (trace.json)")
    tp.add_argument("run_dir",
                    help="telemetry dir (or a run dir with a telemetry/ "
                         "subdirectory)")
    tp.add_argument("--out", default=None,
                    help="output path (default: <telemetry dir>/trace.json)")
    args = p.parse_args(argv)

    if args.cmd == "trace":
        import json as _json

        from tpudist.telemetry.trace import export_chrome_trace

        out = export_chrome_trace(args.run_dir, args.out)
        doc = _json.loads(out.read_text())
        n_events = len(doc.get("traceEvents", []))
        n_traces = doc.get("otherData", {}).get("traces", 0)
        print(f"[tpudist.telemetry] wrote {out} "
              f"({n_traces} request lifelines, {n_events} trace events) — "
              f"load it in Perfetto (ui.perfetto.dev) or chrome://tracing")
        return 0 if n_events else 1

    from tpudist.telemetry.aggregate import render_markdown, write_reports

    report, paths = write_reports(args.run_dir, out_dir=args.out_dir)
    if args.json_out:
        print(json.dumps(report, indent=2))
    else:
        print(render_markdown(report))
    if report.get("num_records", 0) == 0:
        print(f"[tpudist.telemetry] no records under {args.run_dir}",
              file=sys.stderr)
        return 1
    for kind, path in paths.items():
        if path is not None:
            print(f"[tpudist.telemetry] wrote {path}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
