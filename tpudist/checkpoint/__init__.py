"""Checkpoint / resume subsystem.

The reference *provisions* for checkpoints but ships no checkpoint code
(SURVEY.md §5.4): ``job_submitter.sh:157-159`` creates
``${scratch_dir}/${exp_name}/checkpoints`` and the sweep template passes
``--checkpoint_every 1000 --checkpoint_dir …`` (``sweeper.yml:26-31``) to a
hypothetical user program.  This module supplies the real capability the
scaffolding implies, TPU-natively via Orbax:

- the same directory contract (``<scratch_dir>/<exp_name>/checkpoints``),
- multi-host-safe save/restore of the full train state (params + optimizer
  state + data-loader position), sharded arrays written per-host,
- retention policy + atomic finalization (Orbax),
- restore-to-sharding: the state comes back laid out for the current mesh,
  so a job may resume on a different topology.
"""

from tpudist.checkpoint.manager import (  # noqa: F401
    CheckpointConfig,
    CheckpointManager,
    CheckpointRestoreError,
    abstract_like,
    checkpoint_dir_for,
    resolve_checkpoint_location,
    setup_checkpointing,
    sharding_meta,
)
