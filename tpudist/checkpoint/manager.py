"""Orbax-backed checkpoint manager for tpudist train states.

Design: the unit of checkpoint is ``(states, meta)`` where ``states`` is the
``Dict[str, ModelState]`` pytree the compiled step consumes (params + opt
state per model) and ``meta`` carries loop position (iteration, epoch) plus
the base seed — everything needed for a bit-faithful resume of the
reference's training loop (fixed iteration budget + ``set_epoch`` reshuffle,
``demo.py:88,96-98,126-128``).

Multi-host: Orbax's CheckpointManager coordinates across processes through
the JAX distributed client; each host writes its shards of sharded arrays.
Restore takes an ``abstract_state`` (shapes/dtypes/shardings) so the state
lands already laid out for the *current* mesh — topology-change resume.
"""

from __future__ import annotations

import dataclasses
import os
from pathlib import Path
from typing import Any, Optional, Tuple

import jax

from tpudist import telemetry


@dataclasses.dataclass(frozen=True)
class CheckpointConfig:
    directory: str
    save_every: int = 1000          # sweeper.yml:26-31 --checkpoint_every
    max_to_keep: Optional[int] = 3
    async_save: bool = True
    # Transient save I/O errors (filesystem blips on network storage) are
    # retried this many times with a short backoff before surfacing.
    save_retries: int = 2
    save_retry_backoff_s: float = 0.5
    # restore(): fall back to the newest EARLIER valid step when the latest
    # is corrupt/incomplete (bounded by max_to_keep's retention window).
    restore_fallback: bool = True


class CheckpointRestoreError(RuntimeError):
    """Every retained checkpoint step failed to restore."""


# -- logical shardings (the reshard-on-restore contract) ---------------------

def sharding_meta(states) -> dict:
    """JSON-serializable record of a state pytree's LOGICAL shardings —
    per-leaf ``PartitionSpec`` entries by axis *name* plus the mesh
    geometry they were bound to.  Saved as a sidecar next to every
    checkpoint step so :meth:`CheckpointManager.restore_resharded` can
    re-bind the same logical layout onto ANY current mesh shape (a
    surviving world after an elastic resize, a different dp×model split,
    a single chip): names survive topology changes, device assignments
    do not."""
    leaves = jax.tree.leaves(states)
    specs = []
    mesh_info = None
    for leaf in leaves:
        sh = getattr(leaf, "sharding", None)
        if isinstance(sh, jax.sharding.NamedSharding):
            specs.append([list(e) if isinstance(e, tuple) else e
                          for e in tuple(sh.spec)])
            if mesh_info is None:
                m = sh.mesh
                mesh_info = {
                    "axis_names": list(m.axis_names),
                    "shape": [int(m.shape[a]) for a in m.axis_names],
                }
        else:
            specs.append(None)
    return {
        "version": 1,
        "mesh": mesh_info,
        "specs": specs,
        "world": {
            "process_count": int(jax.process_count()),
            "device_count": int(jax.device_count()),
        },
    }


def _map_spec_onto_mesh(spec, shape, mesh) -> "jax.sharding.PartitionSpec":
    """Re-bind one saved logical spec onto the CURRENT mesh: an axis name
    survives iff the mesh has it AND the leaf dimension still divides its
    (new) size; anything else drops to replicated for that dimension —
    restoring slightly-less-sharded beats refusing to restore at all."""
    from jax.sharding import PartitionSpec as P

    if not spec:
        return P()
    entries = []
    for dim, e in enumerate(spec):
        names = [e] if isinstance(e, str) else list(e or [])
        kept = [n for n in names if n in mesh.axis_names]
        prod = 1
        for n in kept:
            prod *= int(mesh.shape[n])
        if (not kept or prod <= 0
                or dim >= len(shape) or shape[dim] % prod != 0):
            entries.append(None)
        else:
            entries.append(kept[0] if len(kept) == 1 else tuple(kept))
    return P(*entries)


def checkpoint_dir_for(
    scratch_dir: Optional[str] = None, exp_name: Optional[str] = None
) -> Path:
    """The reference's directory contract (``job_submitter.sh:157-159``):
    ``${scratch_dir}/[${project_name}/]${exp_name}/checkpoints``, with
    env-var fallbacks on the same names the launcher exports (SURVEY.md
    §5.6).  ``project_name`` (exported by ``launch/job_submitter.sh``)
    namespaces experiments from different checkouts; when unset the path
    matches the reference exactly."""
    scratch = scratch_dir or os.environ.get("scratch_dir", "scratch")
    exp = exp_name or os.environ.get("exp_name", "default_exp")
    project = os.environ.get("project_name")
    base = Path(scratch) / project if project else Path(scratch)
    return base / exp / "checkpoints"


class CheckpointManager:
    """Save/restore ``(states, meta)`` with retention + atomicity via Orbax."""

    def __init__(self, config: CheckpointConfig):
        import orbax.checkpoint as ocp

        self.config = config
        path = Path(config.directory).resolve()
        # All ranks mkdir (idempotent, race-free): gating on process 0 raced
        # every other process's immediate `ocp.CheckpointManager(path)`
        # construction below against the creation.
        path.mkdir(parents=True, exist_ok=True)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=config.max_to_keep,
            enable_async_checkpointing=config.async_save,
        )
        self._ocp = ocp
        self._dir = path
        self._mgr = ocp.CheckpointManager(path, options=options)

    # -- save ---------------------------------------------------------------

    def maybe_save(self, step: int, states: Any, meta: dict) -> bool:
        """Save if ``step`` is on the cadence; returns whether a save started."""
        if self.config.save_every <= 0 or step % self.config.save_every != 0:
            return False
        return self.save(step, states, meta)

    def save(self, step: int, states: Any, meta: dict,
             force: bool = False) -> bool:
        """``force=True`` re-stamps an existing step's meta (e.g. the
        preemption save landing on a cadence boundary must still stamp
        ``preempted``); default is idempotent — cadence save + final save
        may collide.

        The force path is NON-destructive: at a colliding step the arrays
        are identical (same iteration, same states) and only the meta
        differs, so the stamp is written as an atomic sidecar overlay that
        :meth:`restore` merges in.  The existing step is never deleted —
        this runs inside a SIGTERM grace window, and a SIGKILL landing
        between a delete and a completed re-save would destroy the only
        valid checkpoint of that step (r3 advisor finding)."""
        with telemetry.span("ckpt_save", step=step,
                            blocking=not self.config.async_save):
            return self._save(step, states, meta, force)

    def _save(self, step: int, states: Any, meta: dict,
              force: bool = False) -> bool:
        ocp = self._ocp
        if step in self._mgr.all_steps():
            if not force:
                return False
            # Durability first: the colliding save may still be async
            # in-flight — stamp only a finished checkpoint (a SIGKILL
            # mid-overlay then loses the stamp, never the checkpoint).
            self._mgr.wait_until_finished()
            if jax.process_index() == 0:
                self._write_meta_overlay(step, meta)
            return True
        # Transient I/O blips (network FS) are retried before surfacing —
        # losing a whole run to one failed cadence save is the wrong trade;
        # persistent errors still raise after the budget.  NOTE: with
        # async_save=True the OSError Orbax re-raises here may originate
        # from a PREVIOUS step's background write (it surfaces at the next
        # save call) — that step is already lost; the retry keeps THIS
        # step and the run alive.  Single-process only: an Orbax save is
        # COLLECTIVE, and one rank re-entering it alone while its peers
        # already completed would wedge at the internal barrier (the same
        # no-exception-driven-divergence rule _restore_agreed enforces) —
        # multi-host saves surface the error immediately instead.
        from tpudist.runtime.bootstrap import _retry_with_backoff

        save_retries = (self.config.save_retries
                        if jax.process_count() == 1 else 0)
        ok = _retry_with_backoff(
            lambda attempt: self._mgr.save(
                step,
                args=ocp.args.Composite(
                    state=ocp.args.StandardSave(states),
                    meta=ocp.args.JsonSave(meta),
                ),
            ),
            retries=save_retries,
            backoff_s=self.config.save_retry_backoff_s,
            retry_on=(OSError,),
            what=f"checkpoint save(step={step})" + (
                " (error may be from an earlier async save)"
                if self.config.async_save else ""),
        )
        # Logical-sharding sidecar: the reshard-on-restore contract (the
        # elastic world-size path).  Host-side JSON, atomic, best-effort
        # — a failed sidecar degrades restore_resharded to abstract_like,
        # never the save itself.
        if jax.process_index() == 0:
            try:
                self._write_sharding_meta(step, sharding_meta(states))
            except (OSError, TypeError, ValueError):
                pass
        self._gc_meta_overlays()
        # Chaos harness: a due ckpt_corrupt fault garbles this step after
        # the (possibly async) write completes.  One None-check when unarmed.
        from tpudist.runtime import faults

        faults.inject_ckpt_save(step, self._dir / str(step),
                                wait=self._mgr.wait_until_finished)
        return ok

    # -- meta overlays ------------------------------------------------------

    def _overlay_path(self, step: int) -> Path:
        return self._dir / f"meta_overlay_{step}.json"

    def _write_meta_overlay(self, step: int, meta: dict) -> None:
        """Atomic (tmp + rename on the same filesystem) sidecar write."""
        import json

        tmp = self._overlay_path(step).with_suffix(".json.tmp")
        tmp.write_text(json.dumps(meta))
        os.replace(tmp, self._overlay_path(step))

    def _read_meta_overlay(self, step: int) -> dict:
        import json

        p = self._overlay_path(step)
        if not p.exists():
            return {}
        try:
            return dict(json.loads(p.read_text()))
        except (ValueError, OSError):
            return {}  # torn write of the stamp: fall back to base meta

    def _gc_meta_overlays(self) -> None:
        """Drop overlays/sidecars whose step was retired by retention."""
        if jax.process_index() != 0:
            return
        live = set(self._mgr.all_steps())
        for pattern in ("meta_overlay_*.json", "sharding_meta_*.json"):
            for p in self._dir.glob(pattern):
                try:
                    if int(p.stem.rsplit("_", 1)[1]) not in live:
                        p.unlink(missing_ok=True)
                except (ValueError, OSError):
                    pass

    # -- sharding sidecars (reshard-on-restore) -----------------------------

    def _sharding_meta_path(self, step: int) -> Path:
        return self._dir / f"sharding_meta_{step}.json"

    def _write_sharding_meta(self, step: int, meta: dict) -> None:
        import json

        tmp = self._sharding_meta_path(step).with_suffix(".json.tmp")
        tmp.write_text(json.dumps(meta))
        os.replace(tmp, self._sharding_meta_path(step))

    def saved_sharding_meta(self, step: Optional[int] = None
                            ) -> Optional[dict]:
        """The logical-sharding sidecar of ``step`` (default: latest), or
        ``None`` when the step predates the sidecar contract / the write
        failed — callers fall back to a caller-built layout."""
        import json

        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            return None
        p = self._sharding_meta_path(step)
        try:
            return dict(json.loads(p.read_text()))
        except (OSError, ValueError):
            return None

    # -- restore ------------------------------------------------------------

    @property
    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(
        self, abstract_state: Any, step: Optional[int] = None
    ) -> Tuple[Any, dict]:
        """Restore ``(states, meta)``.

        ``abstract_state`` is a pytree of ``jax.ShapeDtypeStruct`` (with
        shardings) matching the saved state — build it from a freshly
        initialized state via :func:`abstract_like`.

        Degraded mode: with no explicit ``step``, a corrupt/incomplete
        latest step (torn files after a mid-save SIGKILL, bit rot) is
        logged and skipped in favor of the newest earlier step that
        restores cleanly — resuming slightly stale beats dying deep inside
        Orbax and burning the restart budget on the same bad step.  The
        fallback window is whatever retention kept (``max_to_keep``).  An
        explicit ``step`` means the caller wants THAT step: no fallback.
        Raises :class:`CheckpointRestoreError` when every retained step
        fails.
        """
        explicit = step is not None
        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint found under {self.config.directory}"
            )
        with telemetry.span("ckpt_restore", step=step):
            return self._restore(step, abstract_state, explicit)

    def restore_resharded(
        self, template: Any, *, mesh=None, step: Optional[int] = None
    ) -> Tuple[Any, dict]:
        """Restore onto the CURRENT topology: shapes/dtypes come from
        ``template`` (a freshly initialized state pytree on this process's
        mesh — same structure as the saved one), shardings come from the
        step's logical-sharding sidecar re-bound to ``mesh``.  This is the
        elastic-resume seam: a checkpoint saved at world ``n`` restores
        bit-faithfully at ``n−1`` (or any other mesh shape) because the
        sidecar records axis NAMES, and Orbax reshards the on-disk arrays
        into whatever layout the abstract target requests.

        - ``mesh``: the current :class:`jax.sharding.Mesh`.  Saved axis
          names missing from it (or whose new size no longer divides the
          leaf dimension) drop to replicated for that dimension.
        - ``mesh=None`` or no sidecar (pre-contract checkpoint): falls
          back to ``template``'s own shardings (:func:`abstract_like`).

        Degraded-mode fallback (corrupt latest step) applies exactly as
        in :meth:`restore` when ``step`` is ``None``.
        """
        saved = self.saved_sharding_meta(step)
        if mesh is None or saved is None or not saved.get("specs"):
            return self.restore(abstract_like(template), step=step)
        leaves, treedef = jax.tree.flatten(template)
        specs = saved["specs"]
        if len(specs) != len(leaves):
            raise CheckpointRestoreError(
                f"sharding sidecar records {len(specs)} leaves but the "
                f"restore template has {len(leaves)} — the saved state "
                "and the template must share one pytree structure")
        targets = []
        for leaf, spec in zip(leaves, specs):
            if isinstance(leaf, (jax.Array, jax.ShapeDtypeStruct)) or \
                    hasattr(leaf, "shape"):
                shape = tuple(getattr(leaf, "shape", ()))
                sharding = jax.sharding.NamedSharding(
                    mesh, _map_spec_onto_mesh(spec, shape, mesh))
                targets.append(jax.ShapeDtypeStruct(
                    shape, leaf.dtype, sharding=sharding))
            else:
                targets.append(leaf)
        return self.restore(jax.tree.unflatten(treedef, targets), step=step)

    def _restore(self, step: int, abstract_state: Any,
                 explicit: bool) -> Tuple[Any, dict]:
        if explicit or not self.config.restore_fallback:
            return self._restore_step(step, abstract_state)
        candidates = sorted(
            (s for s in self._mgr.all_steps() if s <= step), reverse=True
        ) or [step]
        if jax.process_count() > 1:
            return self._restore_agreed(candidates, abstract_state)
        failures = []
        for s in candidates:
            try:
                restored = self._restore_step(s, abstract_state)
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:  # noqa: BLE001 — corruption surfaces as
                # whatever layer noticed it first (Orbax/zarr/json/OS)
                import sys

                print(
                    f"[tpudist.checkpoint] restore(step={s}) failed: "
                    f"{type(e).__name__}: {e}"
                    + ("; falling back to an earlier step"
                       if s != candidates[-1] else ""),
                    file=sys.stderr, flush=True,
                )
                failures.append((s, e))
                continue
            if failures:
                import sys

                print(
                    f"[tpudist.checkpoint] degraded restore: step {s} used "
                    f"instead of corrupt step(s) "
                    f"{[f_s for f_s, _ in failures]}",
                    file=sys.stderr, flush=True,
                )
            return restored
        raise CheckpointRestoreError(
            f"all retained checkpoint steps failed to restore under "
            f"{self.config.directory}: "
            f"{[(s, type(e).__name__) for s, e in failures]}"
        ) from failures[-1][1]

    def _step_locally_plausible(self, step: int) -> bool:
        """Cheap structural sanity of THIS process's view of a step (its
        json metadata parses) — no collective work, so every rank can run
        it independently before agreeing on a restore candidate."""
        import json

        d = self._dir / str(step)
        try:
            if not d.is_dir():
                return False
            for md in (d / "meta" / "metadata", d / "state" / "_METADATA"):
                if md.exists():
                    json.loads(md.read_text())
            return True
        except (OSError, ValueError):
            return False

    def _restore_agreed(
        self, candidates, abstract_state: Any
    ) -> Tuple[Any, dict]:
        """Multi-host degraded restore.  An Orbax restore is COLLECTIVE:
        a rank that falls back on a local exception while its peers
        restore the original step would wedge the collective or silently
        diverge the states (one host's shards may be torn while the
        others' are intact).  So the fallback decision is agreed FIRST —
        each rank structurally checks its local view of every candidate,
        the verdicts are OR-reduced over the host fabric, and the newest
        step every rank deems plausible is restored once, collectively.
        A failure of that agreed restore propagates (no exception-driven
        fallback across a collective boundary)."""
        import sys

        import numpy as np

        from tpudist.comm.collectives import host_allreduce_sum

        # Agree on the candidate LIST first: on eventually-consistent
        # shared storage ranks can see different all_steps() views, and a
        # positional verdict reduce over misaligned lists would pair one
        # rank's verdict for step A with another's for step B (or crash
        # the allgather on length mismatch).  Fixed-size pad -> allgather
        # -> intersect; every rank derives the same ordered `common`.
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            # Pad to the globally largest candidate count (scalar gather
            # first) — a fixed cap would silently shrink the fallback
            # window under unbounded retention (max_to_keep=None).
            lengths = np.asarray(multihost_utils.process_allgather(
                np.int64(len(candidates))))
            pad = max(1, int(lengths.max()))
            local_steps = np.full(pad, -1, dtype=np.int64)
            local_steps[:len(candidates)] = candidates
            gathered = np.asarray(
                multihost_utils.process_allgather(local_steps))
            step_sets = [set(int(s) for s in row if s >= 0)
                         for row in gathered.reshape(-1, pad)]
            common = sorted(set.intersection(*step_sets), reverse=True)
        else:
            common = list(candidates)
        if not common:
            raise CheckpointRestoreError(
                f"ranks see disjoint checkpoint steps under "
                f"{self.config.directory} (eventually-consistent "
                f"storage?); local candidates: {candidates}")
        local_bad = np.array(
            [0.0 if self._step_locally_plausible(s) else 1.0
             for s in common], dtype=np.float64)
        total_bad = np.asarray(host_allreduce_sum(local_bad))
        agreed = [s for s, bad in zip(common, total_bad) if bad == 0.0]
        if not agreed:
            raise CheckpointRestoreError(
                f"no retained checkpoint step passed every rank's "
                f"structural check under {self.config.directory}: "
                f"{common}")
        if agreed[0] != candidates[0]:
            skipped = [s for s in candidates if s > agreed[0]]
            print(
                f"[tpudist.checkpoint] degraded restore (all ranks agree): "
                f"step {agreed[0]} used instead of corrupt step(s) "
                f"{skipped}",
                file=sys.stderr, flush=True,
            )
        return self._restore_step(agreed[0], abstract_state)

    def _restore_step(
        self, step: int, abstract_state: Any
    ) -> Tuple[Any, dict]:
        ocp = self._ocp
        restored = self._mgr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(abstract_state),
                meta=ocp.args.JsonRestore(),
            ),
        )
        meta = dict(restored["meta"])
        meta.update(self._read_meta_overlay(step))  # force-save stamps win
        return restored["state"], meta

    def wait_until_finished(self) -> None:
        """Drain in-flight async saves — recorded as ``ckpt_wait`` so the
        goodput report attributes the background write's blocking tail."""
        with telemetry.span("ckpt_wait"):
            self._mgr.wait_until_finished()

    def close(self) -> None:
        with telemetry.span("ckpt_wait"):
            self._mgr.wait_until_finished()
        self._mgr.close()


def resolve_checkpoint_location(
    directory: Optional[str], *, save_every: int = 0, resume: bool = False
) -> Optional[str]:
    """Resolve where checkpoints live: explicit ``directory`` wins, else the
    launcher's env contract (``scratch_dir``/``exp_name`` exported by
    ``launch/job_submitter.sh``) when checkpointing was requested.  Returns
    ``None`` when checkpointing is off; raises ``ValueError`` when resume is
    requested with no resolvable location.  The single source of truth for
    both the plain demos and the Trainer facade."""
    if directory is not None:
        return directory
    if (save_every > 0 or resume) and (
        "scratch_dir" in os.environ or "exp_name" in os.environ
    ):
        return str(checkpoint_dir_for())
    if resume:
        raise ValueError(
            "resume needs a checkpoint location: pass --checkpoint_dir / "
            "checkpoint_dir or export scratch_dir/exp_name (launcher "
            "contract)"
        )
    return None


def setup_checkpointing(
    states: Any, directory: str, *, save_every: int = 0, resume: bool = False,
    mesh=None,
) -> Tuple["CheckpointManager", Any, int]:
    """Build the manager over a resolved ``directory``; on resume, restore
    the latest step into the current states' layout.  Returns
    ``(manager, states, start_iteration)``.

    With ``mesh``, resume goes through :meth:`CheckpointManager.
    restore_resharded` — the saved logical shardings re-bind onto the
    CURRENT mesh, so a run relaunched at a different world size (elastic
    ``tpurun``) resumes from a checkpoint written at the old one."""
    mgr = CheckpointManager(
        CheckpointConfig(directory=directory, save_every=save_every)
    )
    start = 0
    if resume and mgr.latest_step is not None:
        if mesh is not None:
            states, meta = mgr.restore_resharded(states, mesh=mesh)
        else:
            states, meta = mgr.restore(abstract_like(states))
        start = int(meta.get("iteration", 0))
    return mgr, states, start


def abstract_like(states: Any) -> Any:
    """``jax.ShapeDtypeStruct`` pytree (with shardings) mirroring ``states`` —
    the restore target that tells Orbax the current mesh layout."""

    def to_abstract(x):
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        return x

    return jax.tree.map(to_abstract, states)
