"""Orbax-backed checkpoint manager for tpudist train states.

Design: the unit of checkpoint is ``(states, meta)`` where ``states`` is the
``Dict[str, ModelState]`` pytree the compiled step consumes (params + opt
state per model) and ``meta`` carries loop position (iteration, epoch) plus
the base seed — everything needed for a bit-faithful resume of the
reference's training loop (fixed iteration budget + ``set_epoch`` reshuffle,
``demo.py:88,96-98,126-128``).

Multi-host: Orbax's CheckpointManager coordinates across processes through
the JAX distributed client; each host writes its shards of sharded arrays.
Restore takes an ``abstract_state`` (shapes/dtypes/shardings) so the state
lands already laid out for the *current* mesh — topology-change resume.
"""

from __future__ import annotations

import dataclasses
import os
from pathlib import Path
from typing import Any, Optional, Tuple

import jax


@dataclasses.dataclass(frozen=True)
class CheckpointConfig:
    directory: str
    save_every: int = 1000          # sweeper.yml:26-31 --checkpoint_every
    max_to_keep: Optional[int] = 3
    async_save: bool = True


def checkpoint_dir_for(
    scratch_dir: Optional[str] = None, exp_name: Optional[str] = None
) -> Path:
    """The reference's directory contract (``job_submitter.sh:157-159``):
    ``${scratch_dir}/[${project_name}/]${exp_name}/checkpoints``, with
    env-var fallbacks on the same names the launcher exports (SURVEY.md
    §5.6).  ``project_name`` (exported by ``launch/job_submitter.sh``)
    namespaces experiments from different checkouts; when unset the path
    matches the reference exactly."""
    scratch = scratch_dir or os.environ.get("scratch_dir", "scratch")
    exp = exp_name or os.environ.get("exp_name", "default_exp")
    project = os.environ.get("project_name")
    base = Path(scratch) / project if project else Path(scratch)
    return base / exp / "checkpoints"


class CheckpointManager:
    """Save/restore ``(states, meta)`` with retention + atomicity via Orbax."""

    def __init__(self, config: CheckpointConfig):
        import orbax.checkpoint as ocp

        self.config = config
        path = Path(config.directory).resolve()
        if jax.process_index() == 0:
            path.mkdir(parents=True, exist_ok=True)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=config.max_to_keep,
            enable_async_checkpointing=config.async_save,
        )
        self._ocp = ocp
        self._dir = path
        self._mgr = ocp.CheckpointManager(path, options=options)

    # -- save ---------------------------------------------------------------

    def maybe_save(self, step: int, states: Any, meta: dict) -> bool:
        """Save if ``step`` is on the cadence; returns whether a save started."""
        if self.config.save_every <= 0 or step % self.config.save_every != 0:
            return False
        return self.save(step, states, meta)

    def save(self, step: int, states: Any, meta: dict,
             force: bool = False) -> bool:
        """``force=True`` re-stamps an existing step's meta (e.g. the
        preemption save landing on a cadence boundary must still stamp
        ``preempted``); default is idempotent — cadence save + final save
        may collide.

        The force path is NON-destructive: at a colliding step the arrays
        are identical (same iteration, same states) and only the meta
        differs, so the stamp is written as an atomic sidecar overlay that
        :meth:`restore` merges in.  The existing step is never deleted —
        this runs inside a SIGTERM grace window, and a SIGKILL landing
        between a delete and a completed re-save would destroy the only
        valid checkpoint of that step (r3 advisor finding)."""
        ocp = self._ocp
        if step in self._mgr.all_steps():
            if not force:
                return False
            # Durability first: the colliding save may still be async
            # in-flight — stamp only a finished checkpoint (a SIGKILL
            # mid-overlay then loses the stamp, never the checkpoint).
            self._mgr.wait_until_finished()
            if jax.process_index() == 0:
                self._write_meta_overlay(step, meta)
            return True
        ok = self._mgr.save(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(states),
                meta=ocp.args.JsonSave(meta),
            ),
        )
        self._gc_meta_overlays()
        return ok

    # -- meta overlays ------------------------------------------------------

    def _overlay_path(self, step: int) -> Path:
        return self._dir / f"meta_overlay_{step}.json"

    def _write_meta_overlay(self, step: int, meta: dict) -> None:
        """Atomic (tmp + rename on the same filesystem) sidecar write."""
        import json

        tmp = self._overlay_path(step).with_suffix(".json.tmp")
        tmp.write_text(json.dumps(meta))
        os.replace(tmp, self._overlay_path(step))

    def _read_meta_overlay(self, step: int) -> dict:
        import json

        p = self._overlay_path(step)
        if not p.exists():
            return {}
        try:
            return dict(json.loads(p.read_text()))
        except (ValueError, OSError):
            return {}  # torn write of the stamp: fall back to base meta

    def _gc_meta_overlays(self) -> None:
        """Drop overlays whose step was retired by Orbax retention."""
        if jax.process_index() != 0:
            return
        live = set(self._mgr.all_steps())
        for p in self._dir.glob("meta_overlay_*.json"):
            try:
                if int(p.stem.rsplit("_", 1)[1]) not in live:
                    p.unlink(missing_ok=True)
            except (ValueError, OSError):
                pass

    # -- restore ------------------------------------------------------------

    @property
    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(
        self, abstract_state: Any, step: Optional[int] = None
    ) -> Tuple[Any, dict]:
        """Restore ``(states, meta)``.

        ``abstract_state`` is a pytree of ``jax.ShapeDtypeStruct`` (with
        shardings) matching the saved state — build it from a freshly
        initialized state via :func:`abstract_like`.
        """
        ocp = self._ocp
        step = self._mgr.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint found under {self.config.directory}"
            )
        restored = self._mgr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(abstract_state),
                meta=ocp.args.JsonRestore(),
            ),
        )
        meta = dict(restored["meta"])
        meta.update(self._read_meta_overlay(step))  # force-save stamps win
        return restored["state"], meta

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()


def resolve_checkpoint_location(
    directory: Optional[str], *, save_every: int = 0, resume: bool = False
) -> Optional[str]:
    """Resolve where checkpoints live: explicit ``directory`` wins, else the
    launcher's env contract (``scratch_dir``/``exp_name`` exported by
    ``launch/job_submitter.sh``) when checkpointing was requested.  Returns
    ``None`` when checkpointing is off; raises ``ValueError`` when resume is
    requested with no resolvable location.  The single source of truth for
    both the plain demos and the Trainer facade."""
    if directory is not None:
        return directory
    if (save_every > 0 or resume) and (
        "scratch_dir" in os.environ or "exp_name" in os.environ
    ):
        return str(checkpoint_dir_for())
    if resume:
        raise ValueError(
            "resume needs a checkpoint location: pass --checkpoint_dir / "
            "checkpoint_dir or export scratch_dir/exp_name (launcher "
            "contract)"
        )
    return None


def setup_checkpointing(
    states: Any, directory: str, *, save_every: int = 0, resume: bool = False
) -> Tuple["CheckpointManager", Any, int]:
    """Build the manager over a resolved ``directory``; on resume, restore
    the latest step into the current states' layout.  Returns
    ``(manager, states, start_iteration)``."""
    mgr = CheckpointManager(
        CheckpointConfig(directory=directory, save_every=save_every)
    )
    start = 0
    if resume and mgr.latest_step is not None:
        states, meta = mgr.restore(abstract_like(states))
        start = int(meta.get("iteration", 0))
    return mgr, states, start


def abstract_like(states: Any) -> Any:
    """``jax.ShapeDtypeStruct`` pytree (with shardings) mirroring ``states`` —
    the restore target that tells Orbax the current mesh layout."""

    def to_abstract(x):
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        return x

    return jax.tree.map(to_abstract, states)
