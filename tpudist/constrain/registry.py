"""Resident grammar-block registry.

The adapter-pool discipline (PR 15) applied to grammars: the engine
owns a fixed device pool of ``num_blocks`` table blocks (plus the
sentinel identity block), and this registry decides which compiled
grammar occupies which block.  Binding is host-side bookkeeping only —
the caller performs the actual device write when a bind reports the
block is fresh — so the registry stays importable without JAX.

Blocks are pinned by per-slot refcounts while any lane decodes under
them; a bind for a new grammar evicts the least-recently-used
refcount-zero block.  A pool with every block pinned raises
:class:`GrammarPoolFull`, which admission turns into a deferral (the
request waits for a lane to finish) rather than an error.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from tpudist.constrain.grammar import TokenGrammar

__all__ = ["GrammarPoolFull", "GrammarRegistry"]


class GrammarPoolFull(RuntimeError):
    """Every grammar block is pinned by an active lane."""


class _Block:
    __slots__ = ("key", "grammar", "refs", "stamp")

    def __init__(self) -> None:
        self.key: Optional[str] = None
        self.grammar: Optional[TokenGrammar] = None
        self.refs = 0
        self.stamp = 0


class GrammarRegistry:
    """Host-side occupancy map for the device grammar pool."""

    def __init__(self, num_blocks: int):
        if num_blocks < 1:
            raise ValueError("grammar pool needs at least one block")
        self.num_blocks = int(num_blocks)
        self._blocks: List[_Block] = [_Block() for _ in range(num_blocks)]
        self._by_key: Dict[str, int] = {}
        self._clock = 0
        self._binds = 0
        self._evictions = 0
        self._lock = threading.Lock()

    # -- binding --------------------------------------------------------
    def bind(self, grammar: TokenGrammar) -> "tuple[int, bool]":
        """Pin ``grammar`` into a block; returns ``(block, fresh)``.

        ``fresh`` means the block's device tables must be (re)written
        by the caller before any lane decodes under it.  Raises
        :class:`GrammarPoolFull` when every block is pinned by another
        grammar.
        """
        with self._lock:
            self._clock += 1
            self._binds += 1
            idx = self._by_key.get(grammar.key)
            if idx is not None:
                b = self._blocks[idx]
                b.refs += 1
                b.stamp = self._clock
                return idx, False
            victim = None
            for i, b in enumerate(self._blocks):
                if b.refs == 0 and (
                        victim is None
                        or b.stamp < self._blocks[victim].stamp):
                    victim = i
            if victim is None:
                raise GrammarPoolFull(
                    "all %d grammar blocks are pinned" % self.num_blocks)
            b = self._blocks[victim]
            if b.key is not None:
                self._by_key.pop(b.key, None)
                self._evictions += 1
            b.key = grammar.key
            b.grammar = grammar
            b.refs = 1
            b.stamp = self._clock
            self._by_key[grammar.key] = victim
            return victim, True

    def release(self, block: int) -> None:
        with self._lock:
            b = self._blocks[block]
            if b.refs <= 0:
                raise RuntimeError("release of unpinned grammar block %d"
                                   % block)
            b.refs -= 1

    def grammar_at(self, block: int) -> Optional[TokenGrammar]:
        with self._lock:
            return self._blocks[block].grammar

    def lookup(self, key: str) -> Optional[int]:
        with self._lock:
            return self._by_key.get(key)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "blocks": self.num_blocks,
                "resident": sum(1 for b in self._blocks
                                if b.key is not None),
                "pinned": sum(1 for b in self._blocks if b.refs > 0),
                "binds": self._binds,
                "evictions": self._evictions,
            }
