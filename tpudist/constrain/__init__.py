"""Structured output: grammar-constrained decoding for the slot engine.

The subsystem turns a host-side grammar (a regex, or a JSON schema
lowered to one) into a token-level finite-state automaton — the
Outlines-style compilation: regex → character DFA → vocabulary-indexed
transition/mask tables — and the serving stack applies it as DATA
inside the compiled slot programs:

- :mod:`tpudist.constrain.regex_dfa` — the regex subset parser and the
  Thompson NFA → subset-construction DFA pipeline (pure Python, no
  dependencies);
- :mod:`tpudist.constrain.schema` — JSON schema → regex lowering (a
  schema constrains by becoming a regex over the canonical
  whitespace-free JSON serialization);
- :mod:`tpudist.constrain.grammar` — the token-table compiler
  (:func:`compile_grammar`, cached by grammar hash) and the host-side
  shadow automaton (:class:`TokenGrammar`);
- :mod:`tpudist.constrain.registry` — the resident-block registry the
  engine binds per-request grammars through (the adapter-pool
  discipline applied to grammars: a fixed pool of table blocks, LRU
  eviction of cold refcount-zero entries, per-slot refcount pins).

Per-slot automaton state lives in ``SlotState`` (``gidx``/``gstate``;
the pool's ``num_blocks`` sentinel = unconstrained), the dense tables
ride into ``decode_block``/``spec_verify`` as a read-only program
argument gathered per slot in-graph, and mixed constrained/
unconstrained traffic shares one batch with zero recompilation per
grammar.
"""

from tpudist.constrain.grammar import (ConstrainConfig, GrammarError,
                                       TokenGrammar, compile_cache_stats,
                                       compile_grammar, default_vocab,
                                       grammar_source_key)
from tpudist.constrain.regex_dfa import RegexError, compile_regex_dfa
from tpudist.constrain.registry import GrammarPoolFull, GrammarRegistry
from tpudist.constrain.schema import SchemaError, schema_to_regex

__all__ = [
    "ConstrainConfig",
    "GrammarError",
    "GrammarPoolFull",
    "GrammarRegistry",
    "RegexError",
    "SchemaError",
    "TokenGrammar",
    "compile_cache_stats",
    "compile_grammar",
    "compile_regex_dfa",
    "default_vocab",
    "grammar_source_key",
    "schema_to_regex",
]
