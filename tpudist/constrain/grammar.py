"""Token-level grammar compilation and the host shadow automaton.

:func:`compile_grammar` crosses a character DFA with the serving
vocabulary to produce the dense tables the slot programs consume:

- ``allowed[s, t]`` — may token ``t`` be emitted in automaton state
  ``s``?  Applied as a vocabulary-axis mask on the decode logits.
- ``next_state[s, t]`` — successor state after emitting ``t``
  (self-loop for disallowed tokens, so a defensive gather never
  escapes the table).

Two refinements make the tables safe to sample from:

1. **EOS placement** — the EOS token is allowed exactly in DFA accept
   states (``next = self``), so a constrained lane can only terminate
   on a complete sentence of the grammar.  A grammar therefore
   *requires* an EOS id; requests without one are rejected at submit.
2. **Token-level liveness trim** — a state is live iff it accepts
   (EOS allowed) or some allowed token leads to a live state, computed
   as a fixpoint over the *token* tables (a char-live state can still
   be a token dead end when no vocabulary entry spells a path out).
   Transitions into dead states are removed, so every reachable state
   keeps at least one allowed token and a masked logits row can never
   be all ``-inf``.  A dead start state means the grammar is
   unsatisfiable under this vocabulary and compilation fails.

Compilation is cached by grammar hash — (kind, source, vocabulary
fingerprint, eos id, state cap) — so grammar churn across requests
re-binds pool blocks without re-running the pipeline.
"""

from __future__ import annotations

import hashlib
import json
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from tpudist.constrain.regex_dfa import (ALPHABET, CharDfa, RegexError,
                                         compile_regex_dfa)
from tpudist.constrain.schema import SchemaError, schema_to_regex

__all__ = ["ConstrainConfig", "GrammarError", "TokenGrammar",
           "compile_grammar", "default_vocab", "grammar_source_key"]


class GrammarError(ValueError):
    """An uncompilable grammar: bad syntax, state blowup, or a grammar
    unsatisfiable under the vocabulary.  Surfaces as a synchronous
    ``invalid_grammar`` admission rejection."""


def default_vocab(vocab_size: int, eos_id: Optional[int] = None
                  ) -> Tuple[str, ...]:
    """Synthetic vocabulary for the toy models: token ``i`` decodes to
    one printable character, cycling over the alphabet.  ``eos_id``
    (and token 0, the conventional pad) decode to the empty string."""
    out: List[str] = []
    for i in range(vocab_size):
        if i == 0 or (eos_id is not None and i == eos_id):
            out.append("")
        else:
            out.append(ALPHABET[i % len(ALPHABET)])
    return tuple(out)


@dataclass(frozen=True)
class ConstrainConfig:
    """Engine-facing configuration for the structured-output pool.

    ``vocab`` maps token id → decoded string (the bridge between the
    integer token space and the character grammars).  ``num_blocks``
    is the resident table-pool size G; block id G is the sentinel
    identity block unconstrained lanes index.  ``max_states`` caps the
    per-grammar automaton (S_max), which fixes the dense pool shape
    ``[G+1, S_max, V]``.
    """

    vocab: Tuple[str, ...]
    num_blocks: int = 4
    max_states: int = 64

    def __post_init__(self):
        if self.num_blocks < 1:
            raise ValueError("constrain pool needs at least one block")
        if self.max_states < 2:
            raise ValueError("max_states must be >= 2")

    def vocab_fingerprint(self) -> str:
        h = hashlib.blake2b(digest_size=8)
        for w in self.vocab:
            h.update(w.encode("utf-8"))
            h.update(b"\x00")
        return h.hexdigest()


def grammar_source_key(source: Mapping[str, Any]) -> str:
    """Stable hash of a grammar *source* spec ({"kind", "src", ...}) —
    the disagg wire format ships sources, and the importing side
    re-compiles and re-binds by this key."""
    return hashlib.blake2b(
        json.dumps(source, sort_keys=True).encode("utf-8"),
        digest_size=16).hexdigest()


@dataclass(frozen=True)
class TokenGrammar:
    """A compiled grammar: dense token tables plus the host-side
    shadow automaton the server uses to track delivered tokens."""

    key: str                      # cache/bind key (grammar hash)
    source: Dict[str, Any]        # serializable spec, rides the wire
    eos_id: int
    n_states: int
    allowed: np.ndarray = field(repr=False)      # [n_states, V] bool
    next_state: np.ndarray = field(repr=False)   # [n_states, V] int32
    accept: np.ndarray = field(repr=False)       # [n_states] bool

    # -- host shadow automaton ------------------------------------------
    def token_allowed(self, state: int, tok: int) -> bool:
        return bool(self.allowed[state, tok])

    def advance(self, state: int, tok: int) -> int:
        return int(self.next_state[state, tok])

    def is_accept(self, state: int) -> bool:
        return bool(self.accept[state])

    def walk(self, toks: Sequence[int], state: int = 0) -> Optional[int]:
        """Advance through ``toks``; None on the first violation."""
        for t in toks:
            if not self.allowed[state, t]:
                return None
            state = int(self.next_state[state, t])
        return state


# --------------------------------------------------------------------------
# Compilation
# --------------------------------------------------------------------------

_CACHE_CAP = 64
_cache: "Dict[Tuple, TokenGrammar]" = {}
_cache_order: List[Tuple] = []
_cache_lock = threading.Lock()
_cache_hits = 0
_cache_misses = 0


def compile_cache_stats() -> Dict[str, int]:
    with _cache_lock:
        return {"hits": _cache_hits, "misses": _cache_misses,
                "entries": len(_cache)}


def compile_grammar(*, regex: Optional[str] = None,
                    json_schema: Optional[Mapping[str, Any]] = None,
                    vocab: Sequence[str], eos_id: int,
                    max_states: int = 64) -> TokenGrammar:
    """Compile a regex or JSON schema into a :class:`TokenGrammar`.

    Exactly one of ``regex``/``json_schema`` must be given.  Raises
    :class:`GrammarError` on anything uncompilable — callers reject
    the request synchronously rather than admitting a lane that can
    only dead-end.
    """
    global _cache_hits, _cache_misses
    if (regex is None) == (json_schema is None):
        raise GrammarError("exactly one of regex/json_schema is required")
    if not 0 <= eos_id < len(vocab):
        raise GrammarError("grammar requires a valid eos_id inside the "
                           "vocabulary (got %r)" % (eos_id,))
    if json_schema is not None:
        source: Dict[str, Any] = {"kind": "json_schema", "src": json_schema}
    else:
        source = {"kind": "regex", "src": regex}

    vfp = hashlib.blake2b(
        ("\x00".join(vocab)).encode("utf-8"), digest_size=8).hexdigest()
    ckey = (grammar_source_key(source), vfp, int(eos_id), int(max_states))
    with _cache_lock:
        hit = _cache.get(ckey)
        if hit is not None:
            _cache_hits += 1
            return hit
        _cache_misses += 1

    if json_schema is not None:
        try:
            pattern = schema_to_regex(json_schema)
        except SchemaError as e:
            raise GrammarError("invalid json_schema: %s" % e)
    else:
        pattern = regex
    try:
        dfa = compile_regex_dfa(pattern, max_states=max_states)
    except RegexError as e:
        raise GrammarError("invalid grammar: %s" % e)

    tg = _tokenize(dfa, source, tuple(vocab), int(eos_id), int(max_states))
    with _cache_lock:
        if ckey not in _cache:
            _cache[ckey] = tg
            _cache_order.append(ckey)
            while len(_cache_order) > _CACHE_CAP:
                _cache.pop(_cache_order.pop(0), None)
    return tg


def _tokenize(dfa: CharDfa, source: Dict[str, Any], vocab: Tuple[str, ...],
              eos_id: int, max_states: int) -> TokenGrammar:
    n, vsz = dfa.n_states, len(vocab)
    if n > max_states:  # pragma: no cover - regex layer enforces its own cap
        raise GrammarError("grammar needs %d states, cap is %d"
                           % (n, max_states))
    cand = np.zeros((n, vsz), dtype=bool)
    nxt = np.tile(np.arange(n, dtype=np.int32)[:, None], (1, vsz))

    # Walk every (state, token-string) pair through the char DFA.
    # Empty-string tokens (pad, eos) never advance text and are
    # disallowed, except the EOS column handled below.
    for t, word in enumerate(vocab):
        if t == eos_id or not word:
            continue
        for s in range(n):
            cur: Optional[int] = s
            for ch in word:
                cur = dfa.step(cur, ch)
                if cur is None:
                    break
            if cur is not None:
                cand[s, t] = True
                nxt[s, t] = cur

    accept = np.zeros(n, dtype=bool)
    for s in dfa.accepts:
        accept[s] = True
    cand[:, eos_id] = accept  # EOS exactly in accept states, self-loop

    # Token-level liveness fixpoint: live = accept ∪ {s | ∃t allowed,
    # next[s,t] live}.  Then prune transitions into dead states.
    live = accept.copy()
    while True:
        reach = (cand & live[nxt]).any(axis=1)
        new_live = live | reach
        if (new_live == live).all():
            break
        live = new_live
    if not live[0]:
        raise GrammarError(
            "unsatisfiable grammar: no vocabulary token sequence spells "
            "a complete match (start state is token-dead)")
    allowed = cand & live[nxt]
    allowed[:, eos_id] = accept
    nxt = np.where(allowed, nxt, np.arange(n, dtype=np.int32)[:, None])

    return TokenGrammar(
        key=grammar_source_key(source) + "-" + str(eos_id),
        source=source, eos_id=eos_id, n_states=n,
        allowed=allowed, next_state=nxt.astype(np.int32), accept=accept)
