"""JSON schema → regex lowering.

A JSON schema constrains generation by being lowered to a regex over
the **canonical whitespace-free** JSON serialization (the style
``json.dumps(..., separators=(",", ":"))`` emits): object keys appear
in declaration order, no insignificant whitespace.  The resulting
regex then rides the ordinary :mod:`tpudist.constrain.regex_dfa`
pipeline — schema mode adds zero machinery below this file.

Supported subset (uncompilable schemas are rejected synchronously at
``submit``):

- ``{"type": "object", "properties": {...}}`` — properties emitted in
  declaration order; properties listed in ``required`` (default: all)
  are mandatory, the rest are rejected (optional-key elision would
  need context-free power the DFA does not have, so the lowering
  requires ``required`` to cover every declared property);
- ``{"type": "string"}`` with optional ``enum`` / ``pattern`` (the
  pattern constrains the *content* between the quotes);
- ``{"type": "integer"}`` / ``{"type": "number"}`` with optional
  ``minDigits``/``maxDigits`` hints;
- ``{"type": "boolean"}``, ``{"type": "null"}``;
- ``{"type": "array", "items": ...}`` with ``minItems``/``maxItems``
  (unbounded tails use a Kleene loop, which is cheap in DFA states);
- ``{"enum": [...]}`` over JSON scalars;
- ``{"const": ...}`` for any JSON-serializable value.
"""

from __future__ import annotations

import json
from typing import Any, Mapping

__all__ = ["SchemaError", "schema_to_regex"]

# Characters with meaning in the regex subset; everything literal in a
# lowered schema gets escaped through here.
_SPECIAL = set("\\^$.|?*+()[]{}")


class SchemaError(ValueError):
    """Raised when a schema falls outside the supported subset."""


def _lit(text: str) -> str:
    out = []
    for ch in text:
        out.append("\\" + ch if ch in _SPECIAL else ch)
    return "".join(out)


def _json_lit(value: Any) -> str:
    try:
        return _lit(json.dumps(value, separators=(",", ":"), sort_keys=False))
    except (TypeError, ValueError) as e:
        raise SchemaError("unserializable const/enum value %r: %s"
                          % (value, e))


# JSON string body: any non-quote/backslash printable, or a simple
# escape.  Kept deliberately small — the synthetic vocabulary decodes
# to printable ASCII, so \uXXXX escapes never help generation.
_STRING_BODY = '(?:[^"\\\\]|\\\\["\\\\/bfnrt])*'
_INTEGER = "-?(?:0|[1-9][0-9]*)"
_NUMBER = _INTEGER + "(?:\\.[0-9]+)?(?:[eE][+-]?[0-9]+)?"


def schema_to_regex(schema: Mapping[str, Any]) -> str:
    """Lower ``schema`` to a fullmatch regex over canonical JSON."""
    if not isinstance(schema, Mapping):
        raise SchemaError("schema must be a mapping, got %r" % (schema,))
    return _node(schema, depth=0)


def _node(schema: Mapping[str, Any], depth: int) -> str:
    if depth > 8:
        raise SchemaError("schema nesting exceeds depth cap 8")
    if not isinstance(schema, Mapping):
        raise SchemaError("subschema must be a mapping, got %r" % (schema,))
    if "const" in schema:
        return _json_lit(schema["const"])
    if "enum" in schema:
        opts = schema["enum"]
        if not isinstance(opts, (list, tuple)) or not opts:
            raise SchemaError("enum must be a non-empty list")
        return "(?:%s)" % "|".join(_json_lit(v) for v in opts)
    t = schema.get("type")
    if t == "object":
        return _object(schema, depth)
    if t == "array":
        return _array(schema, depth)
    if t == "string":
        return _string(schema)
    if t == "integer":
        return _INTEGER
    if t == "number":
        return _NUMBER
    if t == "boolean":
        return "(?:true|false)"
    if t == "null":
        return "null"
    raise SchemaError("unsupported schema node %r "
                      "(need type/enum/const)" % (schema,))


def _object(schema: Mapping[str, Any], depth: int) -> str:
    props = schema.get("properties", {})
    if not isinstance(props, Mapping):
        raise SchemaError("properties must be a mapping")
    required = schema.get("required")
    if required is not None and set(required) != set(props):
        raise SchemaError(
            "the lowering emits every declared property in order; "
            "'required' must cover all of %s" % sorted(props))
    parts = []
    for key, sub in props.items():
        parts.append('"%s":%s' % (_lit(str(key)), _node(sub, depth + 1)))
    if not parts:
        return "\\{\\}"
    return "\\{" + ",".join(parts) + "\\}"


def _array(schema: Mapping[str, Any], depth: int) -> str:
    item = _node(schema.get("items", {"type": "integer"}), depth + 1)
    lo = int(schema.get("minItems", 0))
    hi = schema.get("maxItems")
    if lo < 0 or (hi is not None and int(hi) < lo):
        raise SchemaError("bad minItems/maxItems bounds")
    group = "(?:%s)" % item
    tail = "(?:,%s)" % item
    if hi is None:
        if lo == 0:
            body = "(?:%s%s*)?" % (group, tail)
        else:
            body = "%s%s{%d,}" % (group, tail, lo - 1)
    else:
        hi = int(hi)
        if hi == 0:
            body = ""
        elif lo == 0:
            body = "(?:%s%s{0,%d})?" % (group, tail, hi - 1)
        else:
            body = "%s%s{%d,%d}" % (group, tail, lo - 1, hi - 1)
    return "\\[" + body + "\\]"


def _string(schema: Mapping[str, Any]) -> str:
    pattern = schema.get("pattern")
    if pattern is not None:
        # The inner pattern constrains the unquoted content; it must
        # itself avoid raw quotes (they would break JSON framing).
        if '"' in pattern.replace('\\"', ""):
            raise SchemaError("string pattern must not contain raw '\"'")
        return '"(?:%s)"' % pattern
    return '"%s"' % _STRING_BODY
