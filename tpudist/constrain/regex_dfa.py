"""Regex subset → character DFA, with zero dependencies.

The compiler is deliberately a from-scratch implementation of the
classic pipeline — parse → Thompson NFA → subset-construction DFA →
live-state trim — because the container bakes in no regex-automaton
library and the serving path only needs a pragmatic subset:

- literals and escapes (``\\d \\w \\s \\D \\W \\S \\n \\t \\r`` plus
  escaped punctuation),
- character classes ``[a-z0-9_]`` with ranges and ``[^...]`` negation,
- ``.`` (any alphabet character),
- quantifiers ``* + ?`` and bounded ``{m} {m,} {m,n}``,
- groups ``(...)`` / ``(?:...)`` and alternation ``|``.

Semantics are **fullmatch**: the DFA accepts exactly the strings the
pattern matches end-to-end, which is what constrained generation needs
(the emitted stream, decoded to text, must be a complete sentence of
the grammar when the lane finishes).

The alphabet is printable ASCII plus ``\\n``/``\\t`` — the same space
the synthetic serving vocabulary decodes into.  ``.`` and negated
classes range over this alphabet.

The DFA is returned trimmed to *useful* states: every kept state is
reachable from the start and can reach an accept state, so a masked
decode lane can never be steered into a character-level dead end.
Token-level liveness (a state may be char-live but unreachable with
the actual vocabulary) is handled one layer up, in
:mod:`tpudist.constrain.grammar`.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

__all__ = ["ALPHABET", "CharDfa", "RegexError", "compile_regex_dfa"]

# The character universe constrained generation ranges over.  `.` and
# negated classes are relative to this set, not all of Unicode.
ALPHABET: str = (
    " !\"#$%&'()*+,-./0123456789:;<=>?@"
    "ABCDEFGHIJKLMNOPQRSTUVWXYZ[\\]^_`"
    "abcdefghijklmnopqrstuvwxyz{|}~\n\t"
)
_ALPHASET: FrozenSet[str] = frozenset(ALPHABET)

# Bounded-repeat expansion cap: {m,n} unrolls the sub-pattern, so the
# bound keeps a hostile pattern from exploding the NFA host-side.
_MAX_REPEAT = 64


class RegexError(ValueError):
    """Raised for syntax outside the supported subset (or blowups)."""


# --------------------------------------------------------------------------
# Parse: pattern string → AST
# --------------------------------------------------------------------------
# Node shapes (plain tuples keep the walker trivial):
#   ("chars", frozenset)   one character drawn from the set
#   ("cat", [nodes])       concatenation
#   ("alt", [nodes])       alternation
#   ("rep", node, m, n)    m..n repeats; n=None means unbounded

_ESCAPES: Dict[str, FrozenSet[str]] = {
    "d": frozenset("0123456789"),
    "D": _ALPHASET - frozenset("0123456789"),
    "w": frozenset("abcdefghijklmnopqrstuvwxyz"
                   "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_"),
    "W": _ALPHASET - frozenset("abcdefghijklmnopqrstuvwxyz"
                               "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_"),
    "s": frozenset(" \t\n"),
    "S": _ALPHASET - frozenset(" \t\n"),
    "n": frozenset("\n"),
    "t": frozenset("\t"),
    "r": frozenset("\r"),
}

_SPECIAL = set("\\^$.|?*+()[]{}")


class _Parser:
    def __init__(self, pat: str):
        self.pat = pat
        self.i = 0

    def error(self, msg: str) -> RegexError:
        return RegexError("%s at position %d in %r" % (msg, self.i, self.pat))

    def peek(self) -> Optional[str]:
        return self.pat[self.i] if self.i < len(self.pat) else None

    def take(self) -> str:
        ch = self.peek()
        if ch is None:
            raise self.error("unexpected end of pattern")
        self.i += 1
        return ch

    def parse(self):
        node = self._alt()
        if self.i != len(self.pat):
            raise self.error("unbalanced ')'")
        return node

    def _alt(self):
        branches = [self._cat()]
        while self.peek() == "|":
            self.take()
            branches.append(self._cat())
        if len(branches) == 1:
            return branches[0]
        return ("alt", branches)

    def _cat(self):
        items: List = []
        while True:
            ch = self.peek()
            if ch is None or ch in "|)":
                break
            items.append(self._repeat())
        return ("cat", items)

    def _repeat(self):
        atom = self._atom()
        while True:
            ch = self.peek()
            if ch == "*":
                self.take()
                atom = ("rep", atom, 0, None)
            elif ch == "+":
                self.take()
                atom = ("rep", atom, 1, None)
            elif ch == "?":
                self.take()
                atom = ("rep", atom, 0, 1)
            elif ch == "{":
                atom = ("rep", atom, *self._bounds())
            else:
                return atom

    def _bounds(self) -> Tuple[int, Optional[int]]:
        assert self.take() == "{"
        lo = self._int()
        hi: Optional[int] = lo
        if self.peek() == ",":
            self.take()
            hi = None if self.peek() == "}" else self._int()
        if self.take() != "}":
            raise self.error("malformed {m,n} bound")
        if hi is not None and hi < lo:
            raise self.error("repeat bound {%d,%d} is inverted" % (lo, hi))
        if lo > _MAX_REPEAT or (hi is not None and hi > _MAX_REPEAT):
            raise self.error("repeat bound exceeds cap %d" % _MAX_REPEAT)
        return lo, hi

    def _int(self) -> int:
        start = self.i
        while self.peek() is not None and self.peek().isdigit():
            self.take()
        if self.i == start:
            raise self.error("expected integer in {m,n}")
        return int(self.pat[start:self.i])

    def _atom(self):
        ch = self.take()
        if ch == "(":
            if self.peek() == "?":
                self.take()
                if self.take() != ":":
                    raise self.error("only (?:...) groups are supported")
            node = self._alt()
            if self.peek() != ")":
                raise self.error("unbalanced '('")
            self.take()
            return node
        if ch == "[":
            return ("chars", self._char_class())
        if ch == ".":
            return ("chars", _ALPHASET)
        if ch == "\\":
            return ("chars", self._escape())
        if ch in "*+?{":
            raise self.error("quantifier %r has nothing to repeat" % ch)
        if ch in ")|":  # pragma: no cover - callers stop before these
            raise self.error("unexpected %r" % ch)
        if ch not in _ALPHASET:
            raise self.error("character %r outside the alphabet" % ch)
        return ("chars", frozenset(ch))

    def _escape(self) -> FrozenSet[str]:
        ch = self.take()
        if ch in _ESCAPES:
            return _ESCAPES[ch]
        if ch in _SPECIAL or ch in _ALPHASET:
            return frozenset(ch)
        raise self.error("unsupported escape \\%s" % ch)

    def _char_class(self) -> FrozenSet[str]:
        negate = False
        if self.peek() == "^":
            self.take()
            negate = True
        members: Set[str] = set()
        first = True
        while True:
            ch = self.peek()
            if ch is None:
                raise self.error("unterminated character class")
            if ch == "]" and not first:
                self.take()
                break
            first = False
            ch = self.take()
            if ch == "\\":
                members |= self._escape()
                continue
            if self.peek() == "-" and self.i + 1 < len(self.pat) \
                    and self.pat[self.i + 1] != "]":
                self.take()  # '-'
                hi = self.take()
                if hi == "\\":
                    raise self.error("escape cannot end a range")
                if ord(hi) < ord(ch):
                    raise self.error("inverted range %s-%s" % (ch, hi))
                members |= {chr(c) for c in range(ord(ch), ord(hi) + 1)}
                continue
            members.add(ch)
        members &= _ALPHASET
        out = (_ALPHASET - members) if negate else frozenset(members)
        if not out:
            raise self.error("empty character class")
        return out


# --------------------------------------------------------------------------
# Thompson NFA
# --------------------------------------------------------------------------

class _Nfa:
    """States are ints; eps[s] is a list of targets, chars[s] a list of
    (charset, target) edges.  Single start, single accept."""

    def __init__(self):
        self.eps: List[List[int]] = []
        self.chars: List[List[Tuple[FrozenSet[str], int]]] = []

    def state(self) -> int:
        self.eps.append([])
        self.chars.append([])
        return len(self.eps) - 1


def _build_nfa(node, nfa: _Nfa) -> Tuple[int, int]:
    kind = node[0]
    if kind == "chars":
        s, t = nfa.state(), nfa.state()
        nfa.chars[s].append((node[1], t))
        return s, t
    if kind == "cat":
        if not node[1]:
            s = nfa.state()
            return s, s
        start, end = _build_nfa(node[1][0], nfa)
        for sub in node[1][1:]:
            s2, e2 = _build_nfa(sub, nfa)
            nfa.eps[end].append(s2)
            end = e2
        return start, end
    if kind == "alt":
        s, t = nfa.state(), nfa.state()
        for sub in node[1]:
            bs, be = _build_nfa(sub, nfa)
            nfa.eps[s].append(bs)
            nfa.eps[be].append(t)
        return s, t
    if kind == "rep":
        _, sub, lo, hi = node
        s = nfa.state()
        end = s
        for _ in range(lo):
            bs, be = _build_nfa(sub, nfa)
            nfa.eps[end].append(bs)
            end = be
        if hi is None:  # Kleene tail: loop one more copy
            bs, be = _build_nfa(sub, nfa)
            t = nfa.state()
            nfa.eps[end].append(bs)
            nfa.eps[end].append(t)
            nfa.eps[be].append(bs)
            nfa.eps[be].append(t)
            return s, t
        t = nfa.state()
        nfa.eps[end].append(t)
        for _ in range(hi - lo):
            bs, be = _build_nfa(sub, nfa)
            nfa.eps[end].append(bs)
            end = be
            nfa.eps[end].append(t)
        return s, t
    raise AssertionError("unknown node %r" % (kind,))


def _eps_closure(nfa: _Nfa, states: FrozenSet[int]) -> FrozenSet[int]:
    seen = set(states)
    stack = list(states)
    while stack:
        s = stack.pop()
        for t in nfa.eps[s]:
            if t not in seen:
                seen.add(t)
                stack.append(t)
    return frozenset(seen)


# --------------------------------------------------------------------------
# DFA
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class CharDfa:
    """Character-level DFA with fullmatch semantics.

    ``trans[s]`` maps a character to the successor state; characters
    absent from the map are rejected in state ``s``.  State 0 is the
    start.  Every state is reachable and can reach an accept state.
    """

    n_states: int
    trans: Tuple[Dict[str, int], ...]
    accepts: FrozenSet[int]

    def fullmatch(self, text: str) -> bool:
        s = 0
        for ch in text:
            nxt = self.trans[s].get(ch)
            if nxt is None:
                return False
            s = nxt
        return s in self.accepts

    def step(self, state: int, ch: str) -> Optional[int]:
        return self.trans[state].get(ch)


def compile_regex_dfa(pattern: str, *, max_states: int = 256) -> CharDfa:
    """Compile ``pattern`` (fullmatch semantics) to a trimmed DFA.

    Raises :class:`RegexError` for syntax outside the subset, for
    patterns whose DFA exceeds ``max_states``, and for patterns that
    match nothing at all (an unsatisfiable constraint is a caller bug
    better rejected synchronously than discovered as a dead-ended
    decode lane).
    """
    ast = _Parser(pattern).parse()
    nfa = _Nfa()
    start, accept = _build_nfa(ast, nfa)

    d0 = _eps_closure(nfa, frozenset([start]))
    index: Dict[FrozenSet[int], int] = {d0: 0}
    order: List[FrozenSet[int]] = [d0]
    trans: List[Dict[str, int]] = [{}]
    work = [d0]
    while work:
        cur = work.pop()
        ci = index[cur]
        # Group NFA char edges leaving this subset by character.
        by_char: Dict[str, Set[int]] = {}
        for s in cur:
            for charset, t in nfa.chars[s]:
                for ch in charset:
                    by_char.setdefault(ch, set()).add(t)
        for ch, targets in by_char.items():
            nxt = _eps_closure(nfa, frozenset(targets))
            ni = index.get(nxt)
            if ni is None:
                ni = len(order)
                if ni >= max_states:
                    raise RegexError(
                        "pattern %r exceeds the %d-state DFA cap"
                        % (pattern, max_states))
                index[nxt] = ni
                order.append(nxt)
                trans.append({})
                work.append(nxt)
            trans[ci][ch] = ni
    accepts = {i for i, subset in enumerate(order) if accept in subset}

    # Trim to states that can still reach an accept (all states are
    # reachable from the start by construction).
    rev: Dict[int, Set[int]] = {}
    for s, edges in enumerate(trans):
        for t in edges.values():
            rev.setdefault(t, set()).add(s)
    live: Set[int] = set(accepts)
    stack = list(accepts)
    while stack:
        s = stack.pop()
        for p in rev.get(s, ()):
            if p not in live:
                live.add(p)
                stack.append(p)
    if 0 not in live:
        raise RegexError("pattern %r matches no string" % pattern)
    remap = {old: new for new, old in
             enumerate(sorted(live, key=lambda s: (s != 0, s)))}
    new_trans: List[Dict[str, int]] = [{} for _ in remap]
    for old, edges in enumerate(trans):
        if old not in remap:
            continue
        new_trans[remap[old]] = {
            ch: remap[t] for ch, t in edges.items() if t in remap}
    new_accepts = frozenset(remap[s] for s in accepts if s in remap)
    return CharDfa(n_states=len(remap), trans=tuple(new_trans),
                   accepts=new_accepts)
