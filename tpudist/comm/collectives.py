"""Dual-fabric collectives.

The reference runs two fabrics in one job (SURVEY.md §5.8): the fast device
backend (NCCL) for gradient all-reduce inside DDP, plus a second explicit
**Gloo** CPU group used only to all-reduce scalar losses for logging
(``demo.py:84,114-121``).  The TPU-native split is:

- **ICI fabric** — XLA collectives inside the compiled step.  Gradient
  reduction needs no explicit call at all in the pjit formulation (sharded
  batch + replicated params ⇒ XLA inserts the ``psum``); the explicit
  ``psum_tree``/``pmean_tree`` helpers exist for the ``shard_map`` formulation
  and for tests.
- **Host fabric (DCN)** — coordination-service-backed host transfers
  (``multihost_utils``) for scalar metric reduction *off* the compiled path,
  preserving the reference's "log the global batch-weighted mean, not the
  per-rank loss" semantics (``demo.py:113-121``) without ever stalling the
  device step.

The reference's ``--backend {nccl,mpi,gloo}`` flag survives as
:class:`MetricBackend` ``{ici, host}`` selecting where metric reductions run.
"""

from __future__ import annotations

import enum
from typing import Any, Mapping

import jax
import jax.numpy as jnp
import numpy as np


class MetricBackend(str, enum.Enum):
    ICI = "ici"    # reduce on-device inside the compiled step (NCCL analog)
    HOST = "host"  # reduce host-side over DCN (Gloo analog)


def psum_tree(tree: Any, axis_name: str) -> Any:
    """``lax.psum`` over every leaf — gradient all-reduce for the shard_map
    formulation of DP (DDP's bucketed all-reduce, ``demo.py:70-72``, collapses
    to this single fused collective)."""
    return jax.tree.map(lambda x: jax.lax.psum(x, axis_name), tree)


def pmean_tree(tree: Any, axis_name: str) -> Any:
    """``lax.pmean`` over every leaf — DDP averages, so this is the drop-in."""
    return jax.tree.map(lambda x: jax.lax.pmean(x, axis_name), tree)


def host_allreduce_sum(x: Any) -> Any:
    """Sum pytree leaves across *processes* on the host (Gloo-group analog).

    Uses ``multihost_utils.process_allgather`` (DCN / coordination service)
    when the job is multi-process; identity in a single process.
    """
    if jax.process_count() == 1:
        return jax.tree.map(np.asarray, x)
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(x)  # leading axis = process
    return jax.tree.map(lambda g: np.sum(np.asarray(g), axis=0), gathered)


def cross_process_mean_scalar(value, weight: float) -> float:
    """Weighted mean of a scalar across processes: Σ(value·weight)/Σ(weight)."""
    num, den = host_allreduce_sum((np.float64(value) * weight, np.float64(weight)))
    return float(num / den)


def batch_weighted_loss_mean(
    losses: Mapping[str, Any],
    batch_size: int,
    backend: MetricBackend = MetricBackend.HOST,
) -> dict:
    """The reference's logging-loss semantics (``demo.py:113-121``): each
    rank contributes ``loss × batch_size``; the sum over ranks is divided by
    ``batch_size × world_size``.  Assumes equal per-rank batch size every
    iteration, as the reference does (comment at ``demo.py:113``).

    With ``backend=ICI`` the caller's losses are expected to already be global
    means (computed inside the compiled step over the globally-sharded batch),
    so this is a device→host fetch only.
    """
    if backend == MetricBackend.ICI:
        return {k: float(jax.device_get(v)) for k, v in losses.items()}
    local = {k: float(jax.device_get(v)) for k, v in losses.items()}
    return {k: cross_process_mean_scalar(v, batch_size) for k, v in local.items()}


def barrier(name: str = "tpudist_barrier") -> None:
    """Cross-process barrier (``dist.barrier()``, ``demo.py:177``)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def device_put_global(x: np.ndarray, sharding, global_shape=None) -> jax.Array:
    """Assemble a global sharded array from per-process host data.

    Each process passes its *local* shard; the result is a global
    ``jax.Array`` laid out by ``sharding``.  Single-process: a plain
    ``device_put``.  ``global_shape`` defaults to the data-parallel
    convention (dim 0 scaled by process count); pass it explicitly when
    sharding any other dimension (e.g. a seq-sharded ring input).
    """
    if jax.process_count() == 1:
        if global_shape is not None and tuple(x.shape) != tuple(global_shape):
            raise ValueError(
                f"single-process data shape {x.shape} != requested "
                f"global_shape {tuple(global_shape)} — pass the full array"
            )
        return jax.device_put(x, sharding)
    if global_shape is None:
        global_shape = (x.shape[0] * jax.process_count(), *x.shape[1:])
    return jax.make_array_from_process_local_data(sharding, x, global_shape)
