"""Dual-fabric collectives.

The reference runs two fabrics in one job (SURVEY.md §5.8): the fast device
backend (NCCL) for gradient all-reduce inside DDP, plus a second explicit
**Gloo** CPU group used only to all-reduce scalar losses for logging
(``demo.py:84,114-121``).  The TPU-native split is:

- **ICI fabric** — XLA collectives inside the compiled step.  Gradient
  reduction needs no explicit call at all in the pjit formulation (sharded
  batch + replicated params ⇒ XLA inserts the ``psum``); the explicit
  ``psum_tree``/``pmean_tree`` helpers exist for the ``shard_map`` formulation
  and for tests.
- **Host fabric (DCN)** — coordination-service-backed host transfers
  (``multihost_utils``) for scalar metric reduction *off* the compiled path,
  preserving the reference's "log the global batch-weighted mean, not the
  per-rank loss" semantics (``demo.py:113-121``) without ever stalling the
  device step.

The reference's ``--backend {nccl,mpi,gloo}`` flag survives as
:class:`MetricBackend` ``{ici, host}`` selecting where metric reductions run.
"""

from __future__ import annotations

import enum
import threading
from typing import Any, Callable, Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np


class MetricBackend(str, enum.Enum):
    ICI = "ici"    # reduce on-device inside the compiled step (NCCL analog)
    HOST = "host"  # reduce host-side over DCN (Gloo analog)


class HostFabricTimeout(TimeoutError):
    """A host-fabric collective exceeded its deadline.

    Without one, a peer that died un-noticed wedges every other process
    inside ``process_allgather``/``sync_global_devices`` forever — the
    deadline converts that indefinite hang into an error the watchdog /
    crash-record / restart machinery can see and act on."""


_TIMEOUT_ENV = "TPUDIST_HOST_TIMEOUT_S"


def _default_host_timeout() -> Optional[float]:
    from tpudist.utils.envutil import env_positive_float

    return env_positive_float(_TIMEOUT_ENV)


class _DeadlineWorker:
    """One long-lived daemon thread executing deadline-guarded host ops in
    order — reused across calls so the metric path doesn't pay a thread
    spawn per op.  A worker whose op wedged past its deadline is abandoned
    (the caller installs a fresh one); thread creation is then bounded by
    timeout *events*, not op count."""

    def __init__(self):
        import queue

        self._q: "queue.Queue" = queue.Queue()
        self._t = threading.Thread(target=self._run,
                                   name="tpudist-host-fabric", daemon=True)
        self._t.start()

    def _run(self):
        while True:
            fn, result, done = self._q.get()
            try:
                result["value"] = fn()
            except BaseException as e:  # noqa: BLE001 — re-raised in caller
                result["error"] = e
            finally:
                done.set()

    def submit(self, fn):
        result: dict = {}
        done = threading.Event()
        self._q.put((fn, result, done))
        return result, done


_deadline_worker: Optional[_DeadlineWorker] = None
_deadline_lock = threading.Lock()


def _with_deadline(fn: Callable[[], Any], timeout_s: Optional[float],
                   what: str) -> Any:
    """Run ``fn`` under an optional deadline (explicit arg >
    ``TPUDIST_HOST_TIMEOUT_S`` env > none).  The op runs on the shared
    worker thread; on expiry the caller gets :class:`HostFabricTimeout`
    while the wedged op is left to the abandoned (daemon) worker — the
    process is expected to abort/restart shortly after, which is the
    point.  Ops queue in order on one worker, so a caller queued behind a
    wedged op times out too — semantically fine: its deadline measured no
    progress either.

    Every host-fabric op routes through here, so this is also the ONE
    telemetry seam for the ``host_collective`` span (the goodput report's
    ``comm`` component)."""
    from tpudist import telemetry

    with telemetry.span("host_collective", op=what):
        return _with_deadline_inner(fn, timeout_s, what)


def _with_deadline_inner(fn: Callable[[], Any], timeout_s: Optional[float],
                         what: str) -> Any:
    global _deadline_worker
    if timeout_s is None:
        timeout_s = _default_host_timeout()
    if timeout_s is None:
        return fn()
    with _deadline_lock:
        if _deadline_worker is None:
            _deadline_worker = _DeadlineWorker()
        worker = _deadline_worker
    result, done = worker.submit(fn)
    if not done.wait(timeout_s):
        with _deadline_lock:
            if _deadline_worker is worker:  # wedged: next op gets a fresh one
                _deadline_worker = None
        raise HostFabricTimeout(
            f"host-fabric op '{what}' exceeded its {timeout_s:.1f}s "
            f"deadline (wedged peer or dead coordinator?)")
    if "error" in result:
        raise result["error"]
    return result.get("value")


def psum_tree(tree: Any, axis_name: str) -> Any:
    """``lax.psum`` over every leaf — gradient all-reduce for the shard_map
    formulation of DP (DDP's bucketed all-reduce, ``demo.py:70-72``, collapses
    to this single fused collective)."""
    return jax.tree.map(lambda x: jax.lax.psum(x, axis_name), tree)


def pmean_tree(tree: Any, axis_name: str) -> Any:
    """``lax.pmean`` over every leaf — DDP averages, so this is the drop-in."""
    return jax.tree.map(lambda x: jax.lax.pmean(x, axis_name), tree)


def host_allreduce_sum(x: Any, *, timeout_s: Optional[float] = None) -> Any:
    """Sum pytree leaves across *processes* on the host (Gloo-group analog).

    Uses ``multihost_utils.process_allgather`` (DCN / coordination service)
    when the job is multi-process; identity in a single process.
    ``timeout_s`` (or ``TPUDIST_HOST_TIMEOUT_S``) bounds the wait — see
    :class:`HostFabricTimeout`.
    """
    from tpudist.runtime import faults

    def op():
        faults.inject_host()
        if jax.process_count() == 1:
            return jax.tree.map(np.asarray, x)
        from jax.experimental import multihost_utils

        gathered = multihost_utils.process_allgather(x)  # leading axis = process
        return jax.tree.map(lambda g: np.sum(np.asarray(g), axis=0), gathered)

    return _with_deadline(op, timeout_s, "host_allreduce_sum")


def cross_process_mean_scalar(value, weight: float) -> float:
    """Weighted mean of a scalar across processes: Σ(value·weight)/Σ(weight)."""
    num, den = host_allreduce_sum((np.float64(value) * weight, np.float64(weight)))
    return float(num / den)


def batch_weighted_loss_mean(
    losses: Mapping[str, Any],
    batch_size: int,
    backend: MetricBackend = MetricBackend.HOST,
) -> dict:
    """The reference's logging-loss semantics (``demo.py:113-121``): each
    rank contributes ``loss × batch_size``; the sum over ranks is divided by
    ``batch_size × world_size``.  Assumes equal per-rank batch size every
    iteration, as the reference does (comment at ``demo.py:113``).

    With ``backend=ICI`` the caller's losses are expected to already be global
    means (computed inside the compiled step over the globally-sharded batch),
    so this is a device→host fetch only.
    """
    if backend == MetricBackend.ICI:
        return {k: float(jax.device_get(v)) for k, v in losses.items()}
    local = {k: float(jax.device_get(v)) for k, v in losses.items()}
    return {k: cross_process_mean_scalar(v, batch_size) for k, v in local.items()}


def barrier(name: str = "tpudist_barrier", *,
            timeout_s: Optional[float] = None) -> None:
    """Cross-process barrier (``dist.barrier()``, ``demo.py:177``).
    ``timeout_s`` (or ``TPUDIST_HOST_TIMEOUT_S``) bounds the wait — see
    :class:`HostFabricTimeout`."""
    from tpudist.runtime import faults

    def op():
        faults.inject_host()
        if jax.process_count() == 1:
            return
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)

    _with_deadline(op, timeout_s, f"barrier[{name}]")


def device_put_global(x: np.ndarray, sharding, global_shape=None) -> jax.Array:
    """Assemble a global sharded array from per-process host data.

    Each process passes its *local* shard; the result is a global
    ``jax.Array`` laid out by ``sharding``.  Single-process: a plain
    ``device_put``.  ``global_shape`` defaults to the data-parallel
    convention (dim 0 scaled by process count); pass it explicitly when
    sharding any other dimension (e.g. a seq-sharded ring input).
    """
    if jax.process_count() == 1:
        if global_shape is not None and tuple(x.shape) != tuple(global_shape):
            raise ValueError(
                f"single-process data shape {x.shape} != requested "
                f"global_shape {tuple(global_shape)} — pass the full array"
            )
        return jax.device_put(x, sharding)
    if global_shape is None:
        global_shape = (x.shape[0] * jax.process_count(), *x.shape[1:])
    return jax.make_array_from_process_local_data(sharding, x, global_shape)
