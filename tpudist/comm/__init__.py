from tpudist.comm.collectives import (  # noqa: F401
    psum_tree,
    pmean_tree,
    cross_process_mean_scalar,
    batch_weighted_loss_mean,
    host_allreduce_sum,
    barrier,
    MetricBackend,
)
