"""Jitted LM train step for the Transformer family — the long-context
sibling of :mod:`tpudist.train.step`.

Same design stance (no wrapper object, one pure jitted function, explicit
shardings) on a 2-D ``(data, seq)`` mesh: the token batch is sharded over
BOTH axes (``P(data, seq)``), parameters and optimizer state are
replicated, ring attention inside the model handles the sequence-sharded
contraction, and XLA inserts the gradient all-reduce over both mesh axes.
Per-chip activation memory is O(batch/data_n × seq/seq_n) — context length
scales with the ``seq`` axis at constant memory, which is the point.
"""

from __future__ import annotations

from typing import Callable

import jax
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpudist.models.transformer import lm_loss
from tpudist.runtime.mesh import AXIS_DATA, AXIS_SEQ
from tpudist.train.step import ModelState


def token_sharding(mesh: Mesh) -> NamedSharding:
    """``[batch, seq]`` tokens sharded over every mesh axis present."""
    data = AXIS_DATA if AXIS_DATA in mesh.axis_names else None
    seq = AXIS_SEQ if AXIS_SEQ in mesh.axis_names else None
    return NamedSharding(mesh, P(data, seq))


def init_lm_state(params, tx: optax.GradientTransformation) -> ModelState:
    return ModelState(params=params, opt_state=tx.init(params))


def make_lm_train_step(
    apply_fn: Callable,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    *,
    donate_state: bool = True,
    state_sharding=None,
):
    """Build ``step(state, tokens) -> (state, loss)``, compiled once.

    ``apply_fn(params, tokens) -> logits`` is the TransformerLM apply with
    whatever attention op the caller injected (ring for multi-chip).

    ``state_sharding`` (a pytree of ``NamedSharding`` matching the
    ``ModelState``, e.g. from
    :func:`tpudist.models.transformer.transformer_tp_sharding`) overrides
    the default replicated parameter layout — tensor parallelism composed
    with the data/seq sharding of the batch.
    """
    repl = NamedSharding(mesh, P())
    tok_shard = token_sharding(mesh)
    state_out = repl if state_sharding is None else state_sharding

    def step(state: ModelState, tokens):
        def loss_of(params):
            return lm_loss(apply_fn(params, tokens), tokens)

        loss, grads = jax.value_and_grad(loss_of)(state.params)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        return ModelState(params=new_params, opt_state=new_opt), loss

    return jax.jit(
        step,
        in_shardings=(state_out, tok_shard),
        out_shardings=(state_out, repl),
        donate_argnums=(0,) if donate_state else (),
    )
