"""Jitted LM train step for the Transformer family — the long-context
sibling of :mod:`tpudist.train.step`.

Same design stance (no wrapper object, one pure jitted function, explicit
shardings) on a 2-D ``(data, seq)`` mesh: the token batch is sharded over
BOTH axes (``P(data, seq)``), parameters and optimizer state are
replicated, ring attention inside the model handles the sequence-sharded
contraction, and XLA inserts the gradient all-reduce over both mesh axes.
Per-chip activation memory is O(batch/data_n × seq/seq_n) — context length
scales with the ``seq`` axis at constant memory, which is the point.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpudist.models.transformer import lm_loss
from tpudist.runtime.mesh import AXIS_DATA, AXIS_SEQ
from tpudist.train.step import ModelState


def token_sharding(mesh: Mesh) -> NamedSharding:
    """``[batch, seq]`` tokens sharded over every mesh axis present."""
    data = AXIS_DATA if AXIS_DATA in mesh.axis_names else None
    seq = AXIS_SEQ if AXIS_SEQ in mesh.axis_names else None
    return NamedSharding(mesh, P(data, seq))


def init_lm_state(params, tx: optax.GradientTransformation) -> ModelState:
    return ModelState(params=params, opt_state=tx.init(params))


def make_lm_eval_step(
    apply_fn: Callable,
    mesh: Mesh,
    *,
    params_sharding=None,
):
    """Jitted no-grad evaluation: ``eval_step(params, tokens) -> loss``.

    Same sharded-batch contract as the train step; ``params_sharding``
    matches whatever layout the train step keeps (replicated default, or
    e.g. an FSDP/TP sharding tree for ``ModelState.params``)."""
    repl = NamedSharding(mesh, P())
    p_shard = repl if params_sharding is None else params_sharding

    def eval_step(params, tokens):
        return lm_loss(apply_fn(params, tokens), tokens)

    return jax.jit(
        eval_step,
        in_shardings=(p_shard, token_sharding(mesh)),
        out_shardings=repl,
    )


def make_lm_train_step(
    apply_fn: Callable,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    *,
    donate_state: bool = True,
    state_sharding=None,
    aux: bool = False,
    moe_balance_weight: float = 0.0,
    accum_steps: int = 1,
):
    """Build ``step(state, tokens) -> (state, loss)``, compiled once.

    ``apply_fn(params, tokens) -> logits`` is the TransformerLM apply with
    whatever attention op the caller injected (ring for multi-chip).

    ``state_sharding`` (a pytree of ``NamedSharding`` matching the
    ``ModelState``, e.g. from
    :func:`tpudist.models.transformer.transformer_tp_sharding`) overrides
    the default replicated parameter layout — tensor parallelism composed
    with the data/seq sharding of the batch.

    ``aux=True`` runs the model with flax ``intermediates`` collection and
    returns ``step(state, tokens) -> (state, loss, aux_dict)`` where
    ``aux_dict`` carries MoE routing stats averaged over layers
    (``moe_dropped_fraction`` scalar, ``moe_expert_load`` ``[n_experts]``,
    ``moe_balance_loss`` scalar) — empty when the model sows nothing.
    Requires ``apply_fn`` to accept flax's ``mutable=`` kwarg (i.e. a
    ``Module.apply``).

    ``moe_balance_weight`` > 0 adds that multiple of the mean sown
    ``moe_balance_loss`` (the differentiable Switch/GShard auxiliary) to
    the training loss — router load balancing trains even when ``aux`` is
    False; the reported loss stays the plain LM cross entropy.

    ``accum_steps`` > 1 splits the batch into that many microbatches and
    accumulates their gradients in a ``lax.scan`` before the single
    optimizer update — big effective batches at 1/``accum_steps`` peak
    activation memory, numerics equal to the full-batch step up to
    summation order.  Batch size must divide evenly.
    """
    repl = NamedSharding(mesh, P())
    tok_shard = token_sharding(mesh)
    state_out = repl if state_sharding is None else state_sharding
    need_inters = aux or moe_balance_weight > 0.0

    def _collect_aux(inters) -> dict:
        by_name: dict = {}
        for path, leaf in jax.tree_util.tree_flatten_with_path(inters)[0]:
            keys = [getattr(e, "key", getattr(e, "name", None)) for e in path]
            for name in ("moe_dropped_fraction", "moe_expert_load",
                         "moe_balance_loss"):
                if name in keys:
                    by_name.setdefault(name, []).append(leaf)
        return {
            name: jnp.mean(jnp.stack(vals), axis=0)
            for name, vals in by_name.items()
        }

    def grad_of(params, toks):
        """((lm_loss, collected), grads) for one microbatch."""
        if need_inters:
            def loss_of(p):
                logits, mut = apply_fn(p, toks, mutable=["intermediates"])
                # flax omits the collection entirely when nothing was sown
                collected = _collect_aux(mut.get("intermediates", {}))
                lm = lm_loss(logits, toks)
                total = lm
                if moe_balance_weight > 0.0 and "moe_balance_loss" in collected:
                    total = total + moe_balance_weight * collected[
                        "moe_balance_loss"]
                # grads flow from total; the reported loss stays plain LM CE
                return total, (lm, collected)

            (_, out), grads = jax.value_and_grad(
                loss_of, has_aux=True
            )(params)
            return out, grads

        def loss_of(p):
            return lm_loss(apply_fn(p, toks), toks)

        loss, grads = jax.value_and_grad(loss_of)(params)
        return (loss, {}), grads

    def step(state: ModelState, tokens):
        if accum_steps == 1:
            (loss, collected), grads = grad_of(state.params, tokens)
        else:
            b = tokens.shape[0]
            if b % accum_steps:
                raise ValueError(
                    f"batch {b} must divide into {accum_steps} accum steps"
                )
            chunks = tokens.reshape(accum_steps, b // accum_steps,
                                    *tokens.shape[1:])
            acc_shape = jax.eval_shape(grad_of, state.params, chunks[0])
            acc0 = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                                acc_shape)

            def body(acc, chunk):
                out = grad_of(state.params, chunk)
                return jax.tree.map(jnp.add, acc, out), None

            ((loss, collected), grads), _ = lax.scan(body, acc0, chunks)
            scale = 1.0 / accum_steps
            loss = loss * scale
            collected = jax.tree.map(lambda a: a * scale, collected)
            grads = jax.tree.map(lambda g: g * scale, grads)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = ModelState(params=new_params, opt_state=new_opt)
        if aux:
            return new_state, loss, collected
        return new_state, loss

    if aux:
        out_shardings = (state_out, repl, None)  # aux: XLA-chosen (replicated scalars)
    else:
        out_shardings = (state_out, repl)
    return jax.jit(
        step,
        in_shardings=(state_out, tok_shard),
        out_shardings=out_shardings,
        donate_argnums=(0,) if donate_state else (),
    )
