"""Jitted LM train step for the Transformer family — the long-context
sibling of :mod:`tpudist.train.step`.

Same design stance (no wrapper object, one pure jitted function, explicit
shardings) on a 2-D ``(data, seq)`` mesh: the token batch is sharded over
BOTH axes (``P(data, seq)``), parameters and optimizer state are
replicated, ring attention inside the model handles the sequence-sharded
contraction, and XLA inserts the gradient all-reduce over both mesh axes.
Per-chip activation memory is O(batch/data_n × seq/seq_n) — context length
scales with the ``seq`` axis at constant memory, which is the point.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpudist.models.transformer import lm_loss
from tpudist.parallel.overlap import compat_pcast, compat_shard_map
from tpudist.runtime.mesh import AXIS_DATA, AXIS_SEQ
from tpudist.train.step import ModelState


def token_sharding(mesh: Mesh) -> NamedSharding:
    """``[batch, seq]`` tokens sharded over every mesh axis present."""
    data = AXIS_DATA if AXIS_DATA in mesh.axis_names else None
    seq = AXIS_SEQ if AXIS_SEQ in mesh.axis_names else None
    return NamedSharding(mesh, P(data, seq))


def init_lm_state(params, tx: optax.GradientTransformation) -> ModelState:
    return ModelState(params=params, opt_state=tx.init(params))


def _make_lm_train_step_compressed(
    apply_fn: Callable,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    *,
    donate_state: bool,
    reduce_dtype,
    loss_fn: Callable = lm_loss,
):
    """The ``grad_reduce_dtype`` body of :func:`make_lm_train_step`:
    per-shard grads inside ``shard_map``, explicit narrow-dtype ``pmean``
    on the wire, f32 update outside."""
    repl = NamedSharding(mesh, P())
    tok_shard = token_sharding(mesh)

    def shard_body(params, toks):
        # pcast-to-varying FIRST: differentiating w.r.t. replicated
        # (unvarying) inputs makes shard_map's transpose insert its own
        # full-width f32 psum for the cotangents — the very reduce this
        # path exists to narrow.  Varying params keep the grads local,
        # so the explicit narrow pmean below is the ONLY wire traffic
        # (the audit asserts exactly this).
        params = jax.tree.map(
            lambda p: compat_pcast(p, (AXIS_DATA,), to="varying"), params)
        # Local mean over this shard's rows; equal shards (the sharded
        # batch contract) make pmean-of-means the exact global mean.
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(apply_fn(p, toks), toks))(params)
        narrow = jax.tree.map(
            lambda g: lax.pmean(g.astype(reduce_dtype), AXIS_DATA), grads)
        grads = jax.tree.map(lambda g: g.astype(jnp.float32), narrow)
        return lax.pmean(loss, AXIS_DATA), grads

    sharded_grad = compat_shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P(), P(AXIS_DATA)),
        out_specs=(P(), P()),
    )

    def step(state: ModelState, tokens):
        loss, grads = sharded_grad(state.params, tokens)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        return ModelState(params=new_params, opt_state=new_opt), loss

    return jax.jit(
        step,
        in_shardings=(repl, tok_shard),
        out_shardings=(repl, repl),
        donate_argnums=(0,) if donate_state else (),
    )


def make_lm_eval_step(
    apply_fn: Callable,
    mesh: Mesh,
    *,
    loss_fn: Callable = lm_loss,
    params_sharding=None,
):
    """Jitted no-grad evaluation: ``eval_step(params, tokens) -> loss``.

    Same sharded-batch contract as the train step; ``params_sharding``
    matches whatever layout the train step keeps (replicated default, or
    e.g. an FSDP/TP sharding tree for ``ModelState.params``)."""
    repl = NamedSharding(mesh, P())
    p_shard = repl if params_sharding is None else params_sharding

    def eval_step(params, tokens):
        return loss_fn(apply_fn(params, tokens), tokens)

    return jax.jit(
        eval_step,
        in_shardings=(p_shard, token_sharding(mesh)),
        out_shardings=repl,
    )


def make_lm_train_step(
    apply_fn: Callable,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    *,
    donate_state: bool = True,
    state_sharding=None,
    aux: bool = False,
    moe_balance_weight: float = 0.0,
    accum_steps: int = 1,
    grad_reduce_dtype=None,
    loss_fn: Callable = lm_loss,
):
    """Build ``step(state, tokens) -> (state, loss)``, compiled once.

    ``apply_fn(params, tokens) -> logits`` is the TransformerLM apply with
    whatever attention op the caller injected (ring for multi-chip).

    ``state_sharding`` (a pytree of ``NamedSharding`` matching the
    ``ModelState``, e.g. from
    :func:`tpudist.models.transformer.transformer_tp_sharding`) overrides
    the default replicated parameter layout — tensor parallelism composed
    with the data/seq sharding of the batch.

    ``aux=True`` runs the model with flax ``intermediates`` collection and
    returns ``step(state, tokens) -> (state, loss, aux_dict)`` where
    ``aux_dict`` carries MoE routing stats averaged over layers
    (``moe_dropped_fraction`` scalar, ``moe_expert_load`` ``[n_experts]``,
    ``moe_balance_loss`` scalar) — empty when the model sows nothing.
    Requires ``apply_fn`` to accept flax's ``mutable=`` kwarg (i.e. a
    ``Module.apply``).

    ``moe_balance_weight`` > 0 adds that multiple of the mean sown
    ``moe_balance_loss`` (the differentiable Switch/GShard auxiliary) to
    the training loss — router load balancing trains even when ``aux`` is
    False; the reported loss stays the plain LM cross entropy.

    ``accum_steps`` > 1 splits the batch into that many microbatches and
    accumulates their gradients in a ``lax.scan`` before the single
    optimizer update — big effective batches at 1/``accum_steps`` peak
    activation memory, numerics equal to the full-batch step up to
    summation order.  Batch size must divide evenly.

    ``grad_reduce_dtype`` (e.g. ``jnp.bfloat16``) compresses the DP
    gradient all-reduce: each shard's local gradients are cast down, the
    cross-device mean rides the wire at that dtype, and the result is
    cast back to f32 for the optimizer update — halving the per-step DP
    wire bytes (the first thing that binds when the data axis crosses
    DCN; see ``benchmarks/scaling_model.py``).  Master weights, loss and
    optimizer state stay f32; only the reduce payload narrows (the
    gradient stochasticity the mean averages over is far larger than
    bf16's rounding at trained scales — tests bound the drift).
    Implementation: the default path lets XLA insert the f32 psum from
    the global batch mean; this path instead computes per-shard grads in
    a ``shard_map`` and reduces them explicitly at the narrow dtype, so
    it requires the pure-DP layout (replicated state, no
    ``state_sharding``, no ``aux``/``accum_steps`` composition yet) and a
    mesh whose only batch axis is ``data``.
    """
    if grad_reduce_dtype is not None:
        if state_sharding is not None or aux or moe_balance_weight > 0.0 \
                or accum_steps != 1:
            raise ValueError(
                "grad_reduce_dtype requires the pure-DP step (replicated "
                "state; no aux/moe_balance_weight/accum_steps)")
        if AXIS_DATA not in mesh.axis_names:
            raise ValueError("grad_reduce_dtype needs a 'data' mesh axis")
        extra = [a for a in mesh.axis_names
                 if a != AXIS_DATA and mesh.shape[a] > 1]
        if extra:
            raise ValueError(
                f"grad_reduce_dtype supports data-only meshes; axes "
                f"{extra} have size > 1")
        return _make_lm_train_step_compressed(
            apply_fn, tx, mesh, donate_state=donate_state,
            reduce_dtype=grad_reduce_dtype, loss_fn=loss_fn)
    repl = NamedSharding(mesh, P())
    tok_shard = token_sharding(mesh)
    state_out = repl if state_sharding is None else state_sharding
    need_inters = aux or moe_balance_weight > 0.0

    def _collect_aux(inters) -> dict:
        by_name: dict = {}
        for path, leaf in jax.tree_util.tree_flatten_with_path(inters)[0]:
            keys = [getattr(e, "key", getattr(e, "name", None)) for e in path]
            for name in ("moe_dropped_fraction", "moe_expert_load",
                         "moe_balance_loss"):
                if name in keys:
                    by_name.setdefault(name, []).append(leaf)
        return {
            name: jnp.mean(jnp.stack(vals), axis=0)
            for name, vals in by_name.items()
        }

    def grad_of(params, toks):
        """((lm_loss, collected), grads) for one microbatch."""
        if need_inters:
            def loss_of(p):
                logits, mut = apply_fn(p, toks, mutable=["intermediates"])
                # flax omits the collection entirely when nothing was sown
                collected = _collect_aux(mut.get("intermediates", {}))
                lm = loss_fn(logits, toks)
                total = lm
                if moe_balance_weight > 0.0 and "moe_balance_loss" in collected:
                    total = total + moe_balance_weight * collected[
                        "moe_balance_loss"]
                # grads flow from total; the reported loss stays plain LM CE
                return total, (lm, collected)

            (_, out), grads = jax.value_and_grad(
                loss_of, has_aux=True
            )(params)
            return out, grads

        def loss_of(p):
            return loss_fn(apply_fn(p, toks), toks)

        loss, grads = jax.value_and_grad(loss_of)(params)
        return (loss, {}), grads

    def step(state: ModelState, tokens):
        if accum_steps == 1:
            (loss, collected), grads = grad_of(state.params, tokens)
        else:
            b = tokens.shape[0]
            if b % accum_steps:
                raise ValueError(
                    f"batch {b} must divide into {accum_steps} accum steps"
                )
            chunks = tokens.reshape(accum_steps, b // accum_steps,
                                    *tokens.shape[1:])
            acc_shape = jax.eval_shape(grad_of, state.params, chunks[0])
            acc0 = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                                acc_shape)

            def body(acc, chunk):
                out = grad_of(state.params, chunk)
                return jax.tree.map(jnp.add, acc, out), None

            ((loss, collected), grads), _ = lax.scan(body, acc0, chunks)
            scale = 1.0 / accum_steps
            loss = loss * scale
            collected = jax.tree.map(lambda a: a * scale, collected)
            grads = jax.tree.map(lambda g: g * scale, grads)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = ModelState(params=new_params, opt_state=new_opt)
        if aux:
            return new_state, loss, collected
        return new_state, loss

    if aux:
        out_shardings = (state_out, repl, None)  # aux: XLA-chosen (replicated scalars)
    else:
        out_shardings = (state_out, repl)
    return jax.jit(
        step,
        in_shardings=(state_out, tok_shard),
        out_shardings=out_shardings,
        donate_argnums=(0,) if donate_state else (),
    )


def fsdp_overlap_mlp_fn(mesh: Mesh, *, axis_name: str = AXIS_DATA,
                        overlap: str | None = None):
    """Knob-driven overlapped FSDP layer compute for the LM train step.

    The FSDP path (``state_sharding=fsdp_sharding(mesh, state)``) is a
    pure layout: the SPMD partitioner all-gathers each FFN kernel whole
    BEFORE the matmul that consumes it — exposed wire time on the
    critical path.  This helper resolves the ``TPUDIST_OVERLAP`` knob
    (``off``/``ring``/``bidir``; ``overlap`` overrides) and returns the
    pipelined ppermute MLP closure for ``create_transformer(mlp_fn=...)``
    — or ``None`` when off, keeping the byte-identical default.  Wiring::

        mlp_fn = fsdp_overlap_mlp_fn(mesh)              # knob decides
        module, params = create_transformer(rng, mlp_fn=mlp_fn, ...)
        state = init_lm_state(params, tx)
        sharding = fsdp_sharding(mesh, state)
        step = make_lm_train_step(module.apply, tx, mesh,
                                  state_sharding=sharding)

    The step function itself needs no change: the closure carries its
    own ``shard_map`` whose in-specs MATCH the FSDP layout of the FFN
    kernels, so they stream into the ring sharded — no monolithic
    all-gather is ever emitted for them (``benchmarks/comm_audit.py``'s
    ``fsdp_overlap_*`` regimes assert it from optimized HLO).  Numerics:
    the column gather is bit-exact; the contraction gather reassociates
    (bound documented in :mod:`tpudist.parallel.overlap`; tests pin the
    end-to-end step drift).
    """
    from tpudist.parallel.fsdp import overlap_fsdp_mlp

    return overlap_fsdp_mlp(mesh, axis_name=axis_name, overlap=overlap)


def chunk_token_sharding(mesh: Mesh) -> NamedSharding:
    """``[K, batch, seq]`` token windows: iteration axis replicated, the
    rest sharded like :func:`token_sharding`."""
    data = AXIS_DATA if AXIS_DATA in mesh.axis_names else None
    seq = AXIS_SEQ if AXIS_SEQ in mesh.axis_names else None
    return NamedSharding(mesh, P(None, data, seq))


def make_scanned_lm_train_step(
    apply_fn: Callable,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    *,
    loss_fn: Callable = lm_loss,
    donate_state: bool = True,
    state_sharding=None,
):
    """The chunked (``lax.scan``) LM train step — K optimizer steps per
    dispatch, the same amortization that makes the toy headline fast
    through the tunnel (``make_scanned_train_step``), for the LM family.

    Returns ``chunk_step(state, tokens_chunk) -> (state, losses)`` with
    ``tokens_chunk: [K, batch, seq] int32`` (sharded per
    :func:`chunk_token_sharding`) and ``losses: (K,)`` per-iteration
    values — per-step logging semantics preserved while dispatch and
    host sync amortize K×.  Numerics are bit-identical to K calls of the
    plain step (tests assert it).  The plain step's extras (MoE aux,
    accum, grad_reduce_dtype) are out of scope here — use it for the
    small-model/tunnel regime they don't apply to.
    """
    from jax import lax as _lax

    repl = NamedSharding(mesh, P())
    state_out = repl if state_sharding is None else state_sharding

    def chunk(state: ModelState, tokens_chunk):
        def body(st, toks):
            loss, grads = jax.value_and_grad(
                lambda p: loss_fn(apply_fn(p, toks), toks))(st.params)
            updates, new_opt = tx.update(grads, st.opt_state, st.params)
            new = ModelState(params=optax.apply_updates(st.params, updates),
                             opt_state=new_opt)
            return new, loss

        return _lax.scan(body, state, tokens_chunk)

    return jax.jit(
        chunk,
        in_shardings=(state_out, chunk_token_sharding(mesh)),
        out_shardings=(state_out, repl),
        donate_argnums=(0,) if donate_state else (),
    )
