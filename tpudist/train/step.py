"""The jitted training step.

Replaces the body of the reference's hot loop (``demo.py:95-129``): forward +
backward + Adam step for **two independent models per iteration**
(``model_X``/``model_Y``, ``demo.py:100-111``), under data parallelism.

TPU-first design (SURVEY.md §7.5): there is no DDP wrapper object.  The step
is a single pure function jitted once with explicit shardings — the batch is
sharded over the ``data`` mesh axis, parameters/optimizer state are
replicated, and XLA inserts the gradient all-reduce (the entire NCCL
bucketing machinery of torch's C++ reducer collapses into compiler-scheduled
``psum`` fused into the backward).  Both models' updates live in one compiled
program, so their collectives are overlapped by the scheduler instead of
serialized as two autograd-hook streams.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, Dict, Mapping, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpudist.runtime.mesh import AXIS_DATA


def mse_loss(pred: jax.Array, target: jax.Array) -> jax.Array:
    """Mean-squared error (``nn.MSELoss`` in the reference, ``demo.py:80``)."""
    return jnp.mean(jnp.square(pred - target))


@dataclasses.dataclass
class ModelState:
    """Per-model training state: a (params, opt_state) pair.

    Registered as a pytree so a ``Dict[str, ModelState]`` is one jittable
    train state covering all side-by-side models.
    """

    params: Any
    opt_state: Any


jax.tree_util.register_dataclass(
    ModelState, data_fields=["params", "opt_state"], meta_fields=[]
)


def _tx_for(tx, name: str) -> optax.GradientTransformation:
    """``tx`` may be one transformation for all models or a per-model dict
    (the reference's Lightning variant returns one optimizer per model,
    ``demo_pytorch_lightning.py:35-40``)."""
    if isinstance(tx, Mapping):
        return tx[name]
    return tx


def init_model_states(
    models: Mapping[str, Tuple[Callable, Any]],
    tx,
) -> Dict[str, ModelState]:
    """``models`` maps name → ``(apply_fn, params)``; returns the train state."""
    return {
        name: ModelState(params=params, opt_state=_tx_for(tx, name).init(params))
        for name, (_, params) in models.items()
    }


def _multi_model_update(
    apply_fns: Mapping[str, Callable],
    tx,
    loss_fn: Callable,
    states: Dict[str, ModelState],
    x: jax.Array,
    y: jax.Array,
):
    """One fwd+bwd+optimizer update for every side-by-side model — the body
    of the reference hot loop (``demo.py:100-111``) as a pure function."""
    new_states, losses = {}, {}
    for name, state in states.items():
        apply_fn = apply_fns[name]

        def loss_of(params):
            return loss_fn(apply_fn(params, x), y)

        loss, grads = jax.value_and_grad(loss_of)(state.params)
        model_tx = _tx_for(tx, name)
        updates, new_opt = model_tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_states[name] = ModelState(params=new_params, opt_state=new_opt)
        losses[name] = loss
    return new_states, losses


def make_multi_model_train_step(
    apply_fns: Mapping[str, Callable],
    tx,
    mesh: Mesh,
    loss_fn: Callable = mse_loss,
    *,
    batch_axis: str = AXIS_DATA,
    donate_state: bool = True,
    state_sharding=None,
):
    """Build the compiled DP train step.

    Returns ``step(states, x, y) -> (states, losses)`` where ``losses`` is a
    dict of *global* scalar means (computed over the full sharded batch, so
    the reference's batch-weighted cross-rank loss average, ``demo.py:114-121``,
    falls out for free — every epoch's logged loss is already the global mean).

    ``state_sharding`` (a sharding pytree matching the states dict, or a
    single ``NamedSharding``) overrides the default replicated-parameters
    layout — this is how the model-split entry point shards one model's
    weights over the ``model`` mesh axis while staying data-parallel on
    ``data``.  ``tx`` may be a single optax transformation or a per-model
    dict; ``loss_fn`` takes ``(pred, target)``.
    """
    repl = NamedSharding(mesh, P())
    bs = NamedSharding(mesh, P(batch_axis))
    state_sharding = repl if state_sharding is None else state_sharding

    def _step(states: Dict[str, ModelState], x: jax.Array, y: jax.Array):
        return _multi_model_update(apply_fns, tx, loss_fn, states, x, y)

    return jax.jit(
        _step,
        in_shardings=(state_sharding, bs, bs),
        out_shardings=(state_sharding, repl),
        donate_argnums=(0,) if donate_state else (),
    )


def batch_sharding(mesh: Mesh, batch_axis: str = AXIS_DATA) -> NamedSharding:
    return NamedSharding(mesh, P(batch_axis))


def make_scanned_train_step(
    apply_fns: Mapping[str, Callable],
    tx,
    mesh: Mesh,
    loss_fn: Callable = mse_loss,
    *,
    batch_axis: str = AXIS_DATA,
    donate_state: bool = True,
    state_sharding=None,
):
    """The chunked (``lax.scan``) variant of the train step, for datasets
    cached in HBM.

    Returns ``chunk_step(states, x_all, y_all, idx) -> (states, losses)``
    where ``idx`` is ``(K, global_batch)`` int32 — K consecutive iterations'
    global batch indices into the device-resident dataset.  ``losses`` leaves
    are ``(K,)`` per-iteration global means, so per-iteration logging
    semantics (``demo.py:119-121``) are preserved exactly while dispatch and
    host↔device traffic are amortized K× (the reference pays a transfer +
    dispatch + collective every iteration; here the whole window is one XLA
    program that never leaves the device).  Numerics are bit-identical to
    the per-step path — same batch order, same update rule.
    """
    repl = NamedSharding(mesh, P())
    bs = NamedSharding(mesh, P(batch_axis))
    state_sharding = repl if state_sharding is None else state_sharding

    def _chunk(states, x_all, y_all, idx):
        def body(carry, idx_t):
            xb = jax.lax.with_sharding_constraint(jnp.take(x_all, idx_t, axis=0), bs)
            yb = jax.lax.with_sharding_constraint(jnp.take(y_all, idx_t, axis=0), bs)
            return _multi_model_update(apply_fns, tx, loss_fn, carry, xb, yb)

        return jax.lax.scan(body, states, idx)

    return jax.jit(
        _chunk,
        in_shardings=(state_sharding, repl, repl, repl),
        out_shardings=(state_sharding, repl),
        donate_argnums=(0,) if donate_state else (),
    )
