"""The training loop.

Shape parity with ``training_demo`` (``demo.py:75-137``): a fixed iteration
budget spread over epochs (1000 iterations, ``demo.py:88,126-128``), per-epoch
``set_epoch`` reshuffle (``demo.py:96-98``), two models stepped per iteration,
rank-0 tqdm (``demo.py:91-92``), per-iteration global batch-weighted loss
logging (``demo.py:113-121``), and the teardown ordering — metrics logger
finished *before* the distributed runtime goes down (``demo.py:130-136``).

TPU-first deviation (SURVEY.md §3.1 "hot spots"): the reference performs a
synchronous CPU collective + wandb call inside every iteration.  Here the
compiled step returns device scalars; the host only blocks on them at the
logging cadence (``log_every``), keeping the metric path off the XLA critical
path while preserving per-iteration semantics at the default cadence of 1.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Callable, Dict, Optional

import jax

from tpudist import telemetry
from tpudist.comm.collectives import MetricBackend, barrier
from tpudist.data.loader import ShardedLoader, shard_batch
from tpudist.train.step import ModelState, batch_sharding
from tpudist.utils.metrics import MetricsLogger


@dataclasses.dataclass
class TrainLoopConfig:
    total_iterations: int = 1000  # demo.py:88
    log_every: int = 1
    metric_backend: MetricBackend = MetricBackend.ICI
    metric_prefix: str = "loss/"
    progress_bar: bool = True
    # Device→host syncs are batched: losses are fetched (and, for the HOST
    # backend, cross-process reduced) once per ``sync_every`` iterations
    # instead of per step.  Log *rows* stay per-iteration (reference
    # semantics, demo.py:119-121); only the blocking fetch is deferred, so
    # the device stays ahead of the host (SURVEY.md §3.1 "hot spots").
    # 256 (vs the earlier 32): on a real v5e chip the toy step costs
    # ~41 µs inside a 512-long scan vs ~60 µs at window 32 (value-fetch-
    # synced timing) — longer windows amortize per-step overhead ~1.5x.
    # None = resolve via tpudist.utils.tuning (TPUDIST_SYNC_EVERY env /
    # per-device-kind table / the measured 256) at loop start.
    sync_every: Optional[int] = None
    # Device-cached scan path: opt-out plus an HBM budget — the dataset is
    # replicated per device, so only datasets under this cap take the path.
    device_cache: bool = True
    device_cache_max_bytes: int = 256 * 1024 * 1024

    # Preemption-safe shutdown: when a SIGTERM arrived (see
    # tpudist.runtime.preemption — the demos/Trainer install the handler)
    # and a checkpoint manager is active, save at the next sync boundary
    # (all processes agree on it via the host fabric) and return early.
    preempt_save: bool = True

    # Plan stamp (tpudist.plan): when the run's configuration was chosen
    # by the measurement-driven planner (Trainer strategy="auto"), the
    # chosen config + predicted numbers as flat telemetry tags — emitted
    # as ONE plan_selected event once the loop's session is live, so the
    # report shows prediction next to the measured step time.
    plan_stamp: Optional[dict] = None

    # Hang watchdog (tpudist.runtime.watchdog): abort the process with
    # exit 124 + all-thread stack dump when no iteration/window completes
    # within this deadline, so tpurun's restart loop re-admits the group
    # instead of burning the allocation until scheduler timeout.  None =
    # resolve from TPUDIST_WATCHDOG_S (unset = disabled).  Size it above
    # the slowest legitimate gap between PETS — that includes a synchronous
    # checkpoint save and the end-of-run save drain / teardown barrier,
    # not just a step — the first deadline gets 10x slack for XLA
    # compilation.
    watchdog_timeout_s: Optional[float] = None

    def __post_init__(self):
        if self.sync_every is None:
            from tpudist.utils.tuning import tuned

            self.sync_every = tuned("sync_every")


def _preemption_check() -> bool:
    from tpudist.runtime import preemption

    return preemption.check_all()


@contextlib.contextmanager
def preemption_scope(enabled: bool):
    """Per-run preemption bracket, shared by every training loop (the
    per-step and scanned paths here, the Trainer's LM loop): clear the
    sticky per-run record unconditionally — a later run without
    checkpointing must not inherit an earlier run's preempted status —
    install the SIGTERM handler when ``enabled``, and ALWAYS restore the
    process-wide handler on exit (a library must not leave one behind)."""
    from tpudist.runtime import preemption

    preemption.clear_last_run_preempted()
    installed = False
    if enabled:
        # Off the main thread install() degrades to a warned no-op (False).
        installed = preemption.install()
    try:
        yield
    finally:
        if installed:
            preemption.reset()


def finalize_run(states, *, iteration, epoch, preempted, ckpt, logger,
                 flush=None, own_telemetry: bool = True) -> None:
    """The run-teardown ordering CONTRACT (shared by every loop; parity
    with demo.py:130-136 — metrics finish before the end barrier):

    1. final checkpoint save — forced on preemption, because the boundary
       may coincide with a cadence save whose meta lacks the stamp;
    2. sticky preempted note (survives the handler reset in
       :func:`preemption_scope` — callers must be able to tell a
       partially-trained early exit from a completed run);
    3. queued metric rows flushed (``flush``), then ``logger.finish()``;
    4. the end-of-training barrier;
    5. the telemetry session finished — rank 0 merges every rank's and
       generation's JSONL into ``report.json``/``report.md`` so *every*
       run ends with a goodput report — but ONLY when this loop started
       the session (``own_telemetry``).  A loop embedded in a live
       process (the distillation flywheel training inside a serving
       process) must not tear down the host's session: that would
       silently stop every event/metric feed the moment the first
       background round completed.
    """
    if ckpt is not None:
        ckpt.save(iteration, states,
                  {"iteration": iteration, "epoch": epoch,
                   **({"preempted": True} if preempted else {})},
                  force=preempted)
        ckpt.wait_until_finished()
    if preempted:
        from tpudist.runtime import preemption

        preemption.note_run_preempted()
    if flush is not None:
        flush()
    if logger is not None:
        logger.finish()
    barrier("end_of_training")
    if own_telemetry:
        telemetry.finish()


def _data_wait_iter(source, tele):
    """Yield from ``source``, recording each blocking ``next()`` as a
    ``data_wait`` span — the consumer-side stall the goodput report's
    ``data`` component measures.  Plain passthrough when disarmed.

    Uses the stack-pushing ``span()`` form on purpose: a source that
    records its own ``data_wait`` leaves (``prefetch_to_device``) then
    nests under this span instead of double-counting the same stall."""
    if tele is None:
        yield from source
        return
    it = iter(source)
    while True:
        try:
            with tele.span("data_wait"):
                item = next(it)
        except StopIteration:
            return
        yield item


def _make_pbar(config: TrainLoopConfig, initial: int = 0):
    if not config.progress_bar or jax.process_index() != 0:
        return None
    try:
        from tqdm import tqdm
    except ImportError:
        return None
    return tqdm(total=config.total_iterations, desc="train", initial=initial)


class _DeferredMetrics:
    """Collects per-iteration device losses; flushes them to the logger in
    batches — one blocking transfer per ``sync_every`` steps, identical
    logged values."""

    def __init__(self, logger, config: TrainLoopConfig):
        self.logger = logger
        self.config = config
        self._pending = []  # (iteration, batch_size, losses_device_dict)

    def add(self, iteration: int, batch_size: int, losses) -> None:
        self._pending.append((iteration, batch_size, losses))
        if len(self._pending) >= max(1, self.config.sync_every):
            self.flush()

    def flush(self) -> None:
        if not self._pending:
            return
        pending, self._pending = self._pending, []
        # One transfer for the whole window.  The blocking fetch (which
        # absorbs whatever device compute the async dispatch ran ahead
        # of) is its own span so it never masquerades as idle time.
        with telemetry.span("metric_flush", rows=len(pending)):
            fetched = jax.device_get([losses for _, _, losses in pending])
        if self.config.metric_backend == MetricBackend.HOST:
            from tpudist.comm.collectives import host_allreduce_sum
            import numpy as np

            keys = sorted(fetched[0])
            local = np.array(
                [[float(f[k]) * bs for k in keys] for f, (_, bs, _) in zip(fetched, pending)],
                dtype=np.float64,
            )
            weights = np.array([[bs] * len(keys) for _, bs, _ in pending], np.float64)
            num, den = host_allreduce_sum((local, weights))
            fetched = [
                {k: num[i, j] / den[i, j] for j, k in enumerate(keys)}
                for i in range(len(pending))
            ]
        for (iteration, _, _), vals in zip(pending, fetched):
            self.logger.log(
                {f"{self.config.metric_prefix}{k}": float(v) for k, v in vals.items()},
                commit=True,
            )
        # live gauges, once per flush window (the scrape endpoint's view
        # of training progress; step-time sketches come from step spans)
        from tpudist.telemetry import metrics

        last_iter, _, _ = pending[-1]
        metrics.set_train_gauges(
            last_iter, {k: float(v) for k, v in fetched[-1].items()})


def run_training(
    states: Dict[str, ModelState],
    step_fn: Callable,
    loader: ShardedLoader,
    mesh,
    logger: Optional[MetricsLogger] = None,
    config: Optional[TrainLoopConfig] = None,
    per_process_batch_size: Optional[int] = None,
    ckpt=None,
    start_iteration: int = 0,
    chunk_step_fn: Optional[Callable] = None,
):
    """Run to the iteration budget; returns ``(final_states, final_losses)``.

    ``ckpt`` (a :class:`tpudist.checkpoint.CheckpointManager`) enables
    periodic saves on its ``save_every`` cadence; pass ``start_iteration``
    (from restored meta) to resume — the loop fast-forwards through the
    deterministic epoch shuffle so the data stream continues exactly where
    the saved run left off (set_epoch semantics, ``demo.py:96-98``).

    ``chunk_step_fn`` (from :func:`make_scanned_train_step`) switches to the
    device-cached scan path when the dataset fits in HBM: the whole dataset
    is uploaded once, ``sync_every`` iterations run as one XLA program, and
    only tiny index arrays cross the host↔device boundary per window.
    Numerics and log rows are identical to the per-step path.
    """
    config = config or TrainLoopConfig()
    from tpudist.runtime import faults, watchdog

    faults.arm_from_env()  # chaos harness: TPUDIST_FAULT grammar, no code changes
    # Session OWNERSHIP: a pre-existing session belongs to the caller
    # (a serving process running the distill flywheel, a test, a larger
    # job) — this loop records into it but must not finish it.
    owns_telemetry = telemetry.active() is None
    telemetry.ensure_started()  # goodput accounting: TPUDIST_TELEMETRY=0 disarms
    if config.plan_stamp:
        # auditable auto-mode: prediction lands in the same stream the
        # measured step times do (telemetry.aggregate's plan section)
        telemetry.event("plan_selected", **config.plan_stamp)
    # live observability: scrape endpoint (TPUDIST_METRICS_PORT gates it)
    # — step-time/goodput gauges flow from the step spans via the metrics
    # feed; the training loop adds its iteration/loss gauges at each
    # metric flush (never per step)
    from tpudist.telemetry import statusz

    statusz.ensure_started()
    wd = watchdog.from_config(
        config.watchdog_timeout_s, name="train_loop",
        first_deadline_s=(config.watchdog_timeout_s or
                          watchdog.timeout_from_env() or 0.0) * 10,
    )
    with preemption_scope(config.preempt_save and ckpt is not None):
        if wd is not None:
            wd.start()
        try:
            return _dispatch_training(
                states, step_fn, loader, mesh, logger, config,
                ckpt, start_iteration, chunk_step_fn, wd,
                own_telemetry=owns_telemetry)
        finally:
            if wd is not None:
                wd.stop()


def _dispatch_training(states, step_fn, loader, mesh, logger, config,
                       ckpt, start_iteration, chunk_step_fn, wd=None,
                       own_telemetry=True):
    from tpudist.runtime import faults

    if (
        chunk_step_fn is not None
        and config.device_cache
        and loader.plan.mode == "distributed"
        and loader.plan.samples_per_shard % loader.batch_size == 0
        and loader.dataset.x.nbytes + loader.dataset.y.nbytes
        <= config.device_cache_max_bytes
    ):
        return _run_scanned(
            states, chunk_step_fn, loader, mesh, logger, config, ckpt,
            start_iteration, wd, own_telemetry=own_telemetry
        )
    sharding = batch_sharding(mesh)
    # resume fast-forward: whole epochs are skipped arithmetically; only the
    # partial first epoch's batches are skipped via the loader (index-level,
    # nothing materialized).
    batches_per_epoch = len(loader)
    epoch = start_iteration // batches_per_epoch
    iteration = epoch * batches_per_epoch
    skip_in_epoch = start_iteration - iteration
    pbar = _make_pbar(config, initial=start_iteration)

    deferred = _DeferredMetrics(logger, config) if logger is not None else None
    tele = telemetry.active()
    first_step = True  # first dispatch pays XLA compile → its own span
    last_losses = None
    preempted = False
    while iteration < config.total_iterations and not preempted:
        loader.set_epoch(epoch)
        iteration += skip_in_epoch
        skip, skip_in_epoch = skip_in_epoch, 0
        for x, y in _data_wait_iter(loader.iter_from(skip), tele):
            if iteration >= config.total_iterations:
                break
            faults.inject_step(iteration)  # chaos: kill/sigterm@step
            bs = x.shape[0]
            gx, gy = shard_batch((x, y), sharding)
            if tele is not None:
                _t0 = time.monotonic()
            states, losses = step_fn(states, gx, gy)
            if tele is not None:
                if first_step:
                    # Block on the first result so the span measures the
                    # compile, not just the async dispatch.
                    jax.block_until_ready(losses)
                tele.record_span("compile" if first_step else "step",
                                 _t0, time.monotonic() - _t0)
            first_step = False
            if wd is not None:
                # Pet AFTER the step: the first pet must land past the XLA
                # compile so the watchdog's first-deadline slack covers it.
                wd.pet()
            last_losses = losses
            if deferred is not None and iteration % config.log_every == 0:
                deferred.add(iteration, bs, losses)
            iteration += 1
            if ckpt is not None:
                ckpt.maybe_save(
                    iteration, states, {"iteration": iteration, "epoch": epoch}
                )
                if wd is not None:
                    wd.pet()  # a save making I/O progress is not a hang
            if (config.preempt_save and ckpt is not None
                    and iteration < config.total_iterations
                    and iteration % max(1, config.sync_every) == 0
                    and _preemption_check()):
                preempted = True
                break
            if pbar is not None:
                pbar.update(1)
        if not preempted:  # the preempted break leaves epoch mid-flight
            epoch += 1

    if pbar is not None:
        pbar.close()
    finalize_run(states, iteration=iteration, epoch=epoch,
                 preempted=preempted, ckpt=ckpt, logger=logger,
                 flush=deferred.flush if deferred is not None else None,
                 own_telemetry=own_telemetry)
    final_losses = (
        {k: float(jax.device_get(v)) for k, v in last_losses.items()}
        if last_losses is not None
        else {}
    )
    return states, final_losses


def _run_scanned(
    states, chunk_step_fn, loader, mesh, logger, config, ckpt,
    start_iteration, wd=None, own_telemetry=True
):
    """Device-cached scan loop (see ``run_training``).

    The per-epoch global permutation (DistributedSampler/set_epoch
    semantics) is precomputed host-side exactly as the host path derives
    it — global batch ``t`` of epoch ``e`` is the concatenation of every
    shard's ``t``-th batch, matching the layout
    ``make_array_from_process_local_data`` gives the host path — and only
    the int32 index windows are shipped to the device.
    """
    import dataclasses as _dc

    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec

    from tpudist.data.sharding import epoch_indices

    plan = loader.plan
    B = loader.batch_size
    repl = NamedSharding(mesh, PartitionSpec())
    x_np, y_np = loader.dataset.x, loader.dataset.y
    x_all = jax.make_array_from_callback(x_np.shape, repl, lambda i: x_np[i])
    y_all = jax.make_array_from_callback(y_np.shape, repl, lambda i: y_np[i])

    shard_plans = [_dc.replace(plan, shard_id=i) for i in range(plan.num_shards)]
    batches_per_epoch = plan.samples_per_shard // B

    def global_batches(epoch):
        per_shard = [epoch_indices(p, epoch) for p in shard_plans]
        for t in range(batches_per_epoch):
            yield np.concatenate([s[t * B : (t + 1) * B] for s in per_shard])

    pbar = _make_pbar(config, initial=start_iteration)

    total = config.total_iterations
    save_every = ckpt.config.save_every if ckpt is not None else 0
    iteration = start_iteration
    epoch = start_iteration // batches_per_epoch
    batch_in_epoch = start_iteration % batches_per_epoch
    gen = None
    pending_losses = []  # (first_iteration, device dict of (K,) losses)
    last_losses = None

    from tpudist.runtime import faults

    tele = telemetry.active()
    first_window = True  # first dispatch pays XLA compile → its own span
    preempted = False
    while iteration < total:
        faults.inject_step(iteration)  # chaos: kill/sigterm at window edges
        # window length: sync cadence, save cadence, and budget boundaries
        k = min(max(1, config.sync_every), total - iteration)
        if save_every > 0:
            to_save = save_every - (iteration % save_every)
            k = min(k, to_save)
        if tele is not None:
            _t0 = time.monotonic()
        idx_rows = []
        while len(idx_rows) < k:
            if gen is None:
                gen = global_batches(epoch)
                for _ in range(batch_in_epoch):
                    next(gen)
                batch_in_epoch = 0
            for row in gen:
                idx_rows.append(row)
                if len(idx_rows) == k:
                    break
            else:
                gen = None
                epoch += 1
        if tele is not None:
            # host-side index/window assembly = the scanned path's data stall
            tele.record_span("data_wait", _t0, time.monotonic() - _t0)
            _t0 = time.monotonic()
        idx = jax.device_put(np.stack(idx_rows).astype(np.int32), repl)
        states, losses = chunk_step_fn(states, x_all, y_all, idx)
        if tele is not None:
            if first_window:
                jax.block_until_ready(losses)  # span covers the compile
            tele.record_span("compile" if first_window else "step",
                             _t0, time.monotonic() - _t0,
                             {"steps": len(idx_rows)})
        first_window = False
        if wd is not None:
            # Pet AFTER the window: the first pet must land past the XLA
            # compile so the watchdog's first-deadline slack covers it.
            wd.pet()
        last_losses = losses
        if logger is not None:
            pending_losses.append((iteration, losses))
            if len(pending_losses) * k >= config.sync_every:
                _flush_scanned(pending_losses, logger, config)
                pending_losses = []
        iteration += len(idx_rows)
        if ckpt is not None:
            ckpt.maybe_save(iteration, states, {"iteration": iteration, "epoch": epoch})
            if wd is not None:
                wd.pet()  # a save making I/O progress is not a hang
        if pbar is not None:
            pbar.update(len(idx_rows))
        # Window edges are the natural (all-process-agreed) preemption
        # boundaries of the scanned path.  A signal during the FINAL
        # window is not a preemption — the run completed.
        if (config.preempt_save and ckpt is not None
                and iteration < total and _preemption_check()):
            preempted = True
            break

    if pbar is not None:
        pbar.close()
    finalize_run(states, iteration=iteration, epoch=epoch,
                 preempted=preempted, ckpt=ckpt, logger=logger,
                 flush=(lambda: _flush_scanned(pending_losses, logger,
                                               config))
                 if logger is not None else None,
                 own_telemetry=own_telemetry)
    final_losses = {}
    if last_losses is not None:
        fetched = jax.device_get(last_losses)
        final_losses = {k_: float(v[-1]) for k_, v in fetched.items()}
    return states, final_losses


def _flush_scanned(pending, logger, config):
    """Fetch queued (K,) loss windows in one transfer and emit per-iteration
    log rows (values are already global means — computed over the globally
    sharded batch inside the compiled window)."""
    if not pending:
        return
    with telemetry.span("metric_flush", rows=len(pending)):
        fetched = jax.device_get([losses for _, losses in pending])
    for (first_it, _), window in zip(pending, fetched):
        length = len(next(iter(window.values())))
        for j in range(length):
            if (first_it + j) % config.log_every == 0:
                logger.log(
                    {
                        f"{config.metric_prefix}{name}": float(vals[j])
                        for name, vals in window.items()
                    },
                    commit=True,
                )
    # live gauges, once per flush (the scanned-path twin of
    # _DeferredMetrics.flush — both loops keep the scrape view current)
    from tpudist.telemetry import metrics

    first_it, _ = pending[-1]
    last_window = fetched[-1]
    length = len(next(iter(last_window.values())))
    metrics.set_train_gauges(
        first_it + length - 1,
        {k: float(vals[-1]) for k, vals in last_window.items()})
