"""The training loop.

Shape parity with ``training_demo`` (``demo.py:75-137``): a fixed iteration
budget spread over epochs (1000 iterations, ``demo.py:88,126-128``), per-epoch
``set_epoch`` reshuffle (``demo.py:96-98``), two models stepped per iteration,
rank-0 tqdm (``demo.py:91-92``), per-iteration global batch-weighted loss
logging (``demo.py:113-121``), and the teardown ordering — metrics logger
finished *before* the distributed runtime goes down (``demo.py:130-136``).

TPU-first deviation (SURVEY.md §3.1 "hot spots"): the reference performs a
synchronous CPU collective + wandb call inside every iteration.  Here the
compiled step returns device scalars; the host only blocks on them at the
logging cadence (``log_every``), keeping the metric path off the XLA critical
path while preserving per-iteration semantics at the default cadence of 1.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional

import jax

from tpudist.comm.collectives import MetricBackend, batch_weighted_loss_mean, barrier
from tpudist.data.loader import ShardedLoader, shard_batch
from tpudist.train.step import ModelState, batch_sharding
from tpudist.utils.metrics import MetricsLogger


@dataclasses.dataclass
class TrainLoopConfig:
    total_iterations: int = 1000  # demo.py:88
    log_every: int = 1
    metric_backend: MetricBackend = MetricBackend.ICI
    metric_prefix: str = "loss/"
    progress_bar: bool = True


def run_training(
    states: Dict[str, ModelState],
    step_fn: Callable,
    loader: ShardedLoader,
    mesh,
    logger: Optional[MetricsLogger] = None,
    config: Optional[TrainLoopConfig] = None,
    per_process_batch_size: Optional[int] = None,
):
    """Run to the iteration budget; returns ``(final_states, final_losses)``."""
    config = config or TrainLoopConfig()
    sharding = batch_sharding(mesh)
    iteration = 0
    epoch = 0
    pbar = None
    if config.progress_bar and jax.process_index() == 0:
        try:
            from tqdm import tqdm

            pbar = tqdm(total=config.total_iterations, desc="train")
        except ImportError:
            pbar = None

    last_losses = None
    while iteration < config.total_iterations:
        loader.set_epoch(epoch)
        for x, y in loader:
            if iteration >= config.total_iterations:
                break
            bs = x.shape[0]
            gx, gy = shard_batch((x, y), sharding)
            states, losses = step_fn(states, gx, gy)
            last_losses = losses
            if logger is not None and iteration % config.log_every == 0:
                reduced = batch_weighted_loss_mean(
                    losses, bs, backend=config.metric_backend
                )
                logger.log(
                    {f"{config.metric_prefix}{k}": v for k, v in reduced.items()},
                    commit=True,
                )
            iteration += 1
            if pbar is not None:
                pbar.update(1)
        epoch += 1

    if pbar is not None:
        pbar.close()
    # Teardown ordering parity (demo.py:130-136): metrics first, then barrier.
    if logger is not None:
        logger.finish()
    barrier("end_of_training")
    final_losses = (
        {k: float(jax.device_get(v)) for k, v in last_losses.items()}
        if last_losses is not None
        else {}
    )
    return states, final_losses
