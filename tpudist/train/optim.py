"""Optimizer construction with learning-rate schedules.

The reference pins Adam(1e-3) everywhere (``demo.py:80-81``); real LM
training needs warmup + decay.  One helper owns the mapping from the
shared CLI contract (``--lr/--lr_schedule/--warmup_steps``) to an optax
transformation so every entry point and the Trainer agree.
"""

from __future__ import annotations

import functools
from typing import Callable, Union

import jax
import jax.numpy as jnp
import optax

SCHEDULES = ("constant", "cosine", "warmup_cosine")
OPTIMIZERS = ("adam", "adamw", "adafactor", "lion")


def build_schedule(
    lr: float,
    *,
    schedule: str = "constant",
    warmup_steps: int = 0,
    total_steps: int = 1000,
    min_lr_ratio: float = 0.1,
) -> Union[float, Callable]:
    """An optax schedule (or plain float for ``constant``).

    - ``constant``: fixed ``lr``.
    - ``cosine``: cosine decay from ``lr`` to ``lr·min_lr_ratio`` over
      ``total_steps``.
    - ``warmup_cosine``: linear 0 → ``lr`` over ``warmup_steps``, then the
      cosine decay over the remainder.
    """
    if schedule == "constant":
        return lr
    if schedule == "cosine":
        return optax.cosine_decay_schedule(
            lr, decay_steps=max(total_steps, 1), alpha=min_lr_ratio
        )
    if schedule == "warmup_cosine":
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=lr,
            warmup_steps=warmup_steps,
            decay_steps=max(total_steps, warmup_steps + 1),
            end_value=lr * min_lr_ratio,
        )
    raise ValueError(f"unknown lr schedule {schedule!r}; pick from {SCHEDULES}")


def build_optimizer(
    lr: float,
    *,
    optimizer: str = "adam",
    schedule: str = "constant",
    warmup_steps: int = 0,
    total_steps: int = 1000,
    min_lr_ratio: float = 0.1,
    grad_clip: float = 0.0,
    weight_decay: float = 0.0,
) -> optax.GradientTransformation:
    """The one optimizer factory, over :func:`build_schedule`.

    ``optimizer``: ``adam`` (the reference's choice, ``demo.py:80-81``),
    ``adamw`` (decoupled decay), ``adafactor`` (factored second moments —
    the classic memory-lean TPU LM optimizer: O(d) state for a d×d
    matrix), or ``lion`` (sign-momentum; typically wants ~3-10× smaller lr
    and larger decay).  ``weight_decay > 0`` with ``adam`` upgrades it to
    ``adamw`` (back-compat with the pre-``optimizer``-flag CLI).

    ``grad_clip > 0`` prepends global-norm clipping (the whole gradient
    tree is rescaled when its L2 norm exceeds the bound — one ``psum``-free
    pass, XLA fuses it into the step).  Weight decay, where supported, is
    masked to weight matrices (ndim > 1): decaying LayerNorm scales and
    biases measurably hurts convergence.
    """
    if optimizer not in OPTIMIZERS:
        raise ValueError(f"unknown optimizer {optimizer!r}; pick from "
                         f"{OPTIMIZERS}")
    sched = build_schedule(
        lr, schedule=schedule, warmup_steps=warmup_steps,
        total_steps=total_steps, min_lr_ratio=min_lr_ratio,
    )
    decay_mask = functools.partial(jax.tree.map, lambda p: jnp.ndim(p) > 1)
    if optimizer == "adam" and weight_decay > 0:
        optimizer = "adamw"
    if optimizer == "adam":
        opt = optax.adam(sched)
    elif optimizer == "adamw":
        opt = optax.adamw(sched, weight_decay=weight_decay,
                          mask=decay_mask)
    elif optimizer == "adafactor":
        # adafactor owns its own clipping/scaling pipeline; weight decay
        # rides through its decay_rate-independent hook.
        opt = optax.adafactor(sched, weight_decay_rate=weight_decay or None,
                              weight_decay_mask=decay_mask)
    else:  # lion
        opt = optax.lion(sched, weight_decay=weight_decay,
                         mask=decay_mask)
    if grad_clip > 0:
        return optax.chain(optax.clip_by_global_norm(grad_clip), opt)
    return opt


def build_optimizer_from_args(args) -> optax.GradientTransformation:
    """The shared-CLI spelling (``--lr/--lr_schedule/--warmup_steps/
    --total_iterations/--grad_clip/--weight_decay``) of
    :func:`build_optimizer` — entry points call this so the args→kwargs
    mapping lives in exactly one place."""
    return build_optimizer(
        args.lr,
        optimizer=getattr(args, "optimizer", "adam"),
        schedule=args.lr_schedule,
        warmup_steps=args.warmup_steps,
        total_steps=args.total_iterations,
        grad_clip=getattr(args, "grad_clip", 0.0),
        weight_decay=getattr(args, "weight_decay", 0.0),
    )
