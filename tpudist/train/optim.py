"""Optimizer construction with learning-rate schedules.

The reference pins Adam(1e-3) everywhere (``demo.py:80-81``); real LM
training needs warmup + decay.  One helper owns the mapping from the
shared CLI contract (``--lr/--lr_schedule/--warmup_steps``) to an optax
transformation so every entry point and the Trainer agree.
"""

from __future__ import annotations

import functools
from typing import Callable, Union

import jax
import jax.numpy as jnp
import optax

SCHEDULES = ("constant", "cosine", "warmup_cosine")


def build_schedule(
    lr: float,
    *,
    schedule: str = "constant",
    warmup_steps: int = 0,
    total_steps: int = 1000,
    min_lr_ratio: float = 0.1,
) -> Union[float, Callable]:
    """An optax schedule (or plain float for ``constant``).

    - ``constant``: fixed ``lr``.
    - ``cosine``: cosine decay from ``lr`` to ``lr·min_lr_ratio`` over
      ``total_steps``.
    - ``warmup_cosine``: linear 0 → ``lr`` over ``warmup_steps``, then the
      cosine decay over the remainder.
    """
    if schedule == "constant":
        return lr
    if schedule == "cosine":
        return optax.cosine_decay_schedule(
            lr, decay_steps=max(total_steps, 1), alpha=min_lr_ratio
        )
    if schedule == "warmup_cosine":
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=lr,
            warmup_steps=warmup_steps,
            decay_steps=max(total_steps, warmup_steps + 1),
            end_value=lr * min_lr_ratio,
        )
    raise ValueError(f"unknown lr schedule {schedule!r}; pick from {SCHEDULES}")


def build_optimizer(
    lr: float,
    *,
    schedule: str = "constant",
    warmup_steps: int = 0,
    total_steps: int = 1000,
    min_lr_ratio: float = 0.1,
    grad_clip: float = 0.0,
    weight_decay: float = 0.0,
) -> optax.GradientTransformation:
    """Adam/AdamW over :func:`build_schedule` — the one optimizer factory.

    ``grad_clip > 0`` prepends global-norm clipping (the whole gradient
    tree is rescaled when its L2 norm exceeds the bound — one ``psum``-free
    pass, XLA fuses it into the step).  ``weight_decay > 0`` switches to
    decoupled AdamW.
    """
    sched = build_schedule(
        lr, schedule=schedule, warmup_steps=warmup_steps,
        total_steps=total_steps, min_lr_ratio=min_lr_ratio,
    )
    # Standard LM practice: decay only weight matrices — LayerNorm scales
    # and biases (ndim <= 1) are excluded or convergence suffers.
    decay_mask = functools.partial(jax.tree.map, lambda p: jnp.ndim(p) > 1)
    opt = (optax.adamw(sched, weight_decay=weight_decay, mask=decay_mask)
           if weight_decay > 0 else optax.adam(sched))
    if grad_clip > 0:
        return optax.chain(optax.clip_by_global_norm(grad_clip), opt)
    return opt


def build_optimizer_from_args(args) -> optax.GradientTransformation:
    """The shared-CLI spelling (``--lr/--lr_schedule/--warmup_steps/
    --total_iterations/--grad_clip/--weight_decay``) of
    :func:`build_optimizer` — entry points call this so the args→kwargs
    mapping lives in exactly one place."""
    return build_optimizer(
        args.lr,
        schedule=args.lr_schedule,
        warmup_steps=args.warmup_steps,
        total_steps=args.total_iterations,
        grad_clip=getattr(args, "grad_clip", 0.0),
        weight_decay=getattr(args, "weight_decay", 0.0),
    )
