from tpudist.train.step import (  # noqa: F401
    ModelState,
    init_model_states,
    make_multi_model_train_step,
    make_scanned_train_step,
    mse_loss,
)
from tpudist.train.loop import TrainLoopConfig, run_training  # noqa: F401
from tpudist.train.lm import (  # noqa: F401
    chunk_token_sharding,
    fsdp_overlap_mlp_fn,
    init_lm_state,
    make_lm_eval_step,
    make_lm_train_step,
    make_scanned_lm_train_step,
    token_sharding,
)
from tpudist.train.optim import (  # noqa: F401
    SCHEDULES,
    build_optimizer,
    build_optimizer_from_args,
    build_schedule,
)
