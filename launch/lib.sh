# Shared launch-layer helpers (sourced, not executed).

# tpudist_tmpdir <job_id> [allnodes]
#
# Resolve + create the node-local scratch dir as TPUDIST_TMPDIR and
# register cleanup for dirs this job created itself:
#   - a cluster profile's node_tmpdir (launch/clusters/) takes precedence —
#     clusters whose fast local disk is NOT what SLURM_TMPDIR points at
#     declare it there (the reference's per-cluster /scratch-ssd branch,
#     standard_job.sh:13-16),
#   - else a scheduler-owned SLURM_TMPDIR is used as-is and never removed,
#   - else a /tmp fallback is created and removed.
# Scope "allnodes" (dispatchers): workers stage into this path on EVERY
# node's local disk, so cleanup fans out over the allocation via srun
# instead of only running on the batch node.
tpudist_tmpdir() {
  local job_id="$1" scope="${2:-local}" created=0
  if [[ -n "${node_tmpdir:-}" ]]; then
    export TPUDIST_TMPDIR="${node_tmpdir}/tpudist_${job_id}"
    created=1
  else
    export TPUDIST_TMPDIR="${SLURM_TMPDIR:-/tmp/tpudist_${job_id}}"
    [[ -z "${SLURM_TMPDIR:-}" ]] && created=1
  fi
  if [[ "${created}" -eq 1 ]]; then
    if [[ "${scope}" == "allnodes" && -n "${SLURM_JOB_NODELIST:-}" ]]; then
      trap 'srun --ntasks="${SLURM_NNODES:-1}" --ntasks-per-node=1 \
        rm -rf "${TPUDIST_TMPDIR}" 2>/dev/null || rm -rf "${TPUDIST_TMPDIR}"' EXIT
    else
      trap 'rm -rf "${TPUDIST_TMPDIR}"' EXIT
    fi
  fi
  mkdir -p "${TPUDIST_TMPDIR}"
}

# tpudist_stage_data <exp_dir> <comma-separated-dirs>
#
# The reference's tar-once data staging (job_submitter.sh:166-174): each
# dir becomes <exp_dir>/data/<name>.tar, created only when absent.  Sets
# `staged_out` to the comma-joined tarball list (empty when no dirs).
# Shared by the SLURM and gcloud front doors.
tpudist_stage_data() {
  local exp_dir="$1" data_paths="$2" p tb
  staged_out=""
  [[ -z "${data_paths}" ]] && return 0
  local -a paths
  IFS=',' read -ra paths <<< "${data_paths}"
  for p in "${paths[@]}"; do
    tb="${exp_dir}/data/$(basename "${p}").tar"
    if [[ ! -f "${tb}" ]]; then
      echo "staging ${p} -> ${tb}"
      time tar -cf "${tb}" -C "$(dirname "${p}")" "$(basename "${p}")"
    fi
    staged_out="${staged_out:+${staged_out},}${tb}"
  done
}

# tpudist_wandb_key — sets `wandb_key` from ~/wandb_credentials.txt
# (reference job_submitter.sh:154-155: optional credentials file).
# if-form, not `[[ ]] &&`: a falsy final list would make the FUNCTION
# return nonzero and kill `set -e` callers.
tpudist_wandb_key() {
  wandb_key=""
  if [[ -f "${HOME}/wandb_credentials.txt" ]]; then
    wandb_key="$(head -n1 "${HOME}/wandb_credentials.txt")"
  fi
}

# tpudist_experiment_cmd <file> — sets `cmd` to the one-line experiment
# command (reference job_submitter.sh:300: the config file carries one
# command, possibly wrapped with backslashes).
tpudist_experiment_cmd() {
  cmd="$(tr -d '\n\r\\' < "$1")"
}
