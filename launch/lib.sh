# Shared launch-layer helpers (sourced, not executed).

# tpudist_tmpdir <job_id> [allnodes]
#
# Resolve + create the node-local scratch dir as TPUDIST_TMPDIR and
# register cleanup for dirs this job created itself:
#   - a cluster profile's node_tmpdir (launch/clusters/) takes precedence —
#     clusters whose fast local disk is NOT what SLURM_TMPDIR points at
#     declare it there (the reference's per-cluster /scratch-ssd branch,
#     standard_job.sh:13-16),
#   - else a scheduler-owned SLURM_TMPDIR is used as-is and never removed,
#   - else a /tmp fallback is created and removed.
# Scope "allnodes" (dispatchers): workers stage into this path on EVERY
# node's local disk, so cleanup fans out over the allocation via srun
# instead of only running on the batch node.
tpudist_tmpdir() {
  local job_id="$1" scope="${2:-local}" created=0
  if [[ -n "${node_tmpdir:-}" ]]; then
    export TPUDIST_TMPDIR="${node_tmpdir}/tpudist_${job_id}"
    created=1
  else
    export TPUDIST_TMPDIR="${SLURM_TMPDIR:-/tmp/tpudist_${job_id}}"
    [[ -z "${SLURM_TMPDIR:-}" ]] && created=1
  fi
  if [[ "${created}" -eq 1 ]]; then
    if [[ "${scope}" == "allnodes" && -n "${SLURM_JOB_NODELIST:-}" ]]; then
      trap 'srun --ntasks="${SLURM_NNODES:-1}" --ntasks-per-node=1 \
        rm -rf "${TPUDIST_TMPDIR}" 2>/dev/null || rm -rf "${TPUDIST_TMPDIR}"' EXIT
    else
      trap 'rm -rf "${TPUDIST_TMPDIR}"' EXIT
    fi
  fi
  mkdir -p "${TPUDIST_TMPDIR}"
}
