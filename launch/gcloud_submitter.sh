#!/bin/bash
# Cloud TPU front door — the gcloud analog of launch/job_submitter.sh
# (reference hpc_files/job_submitter.sh owns allocation→launch end to end:
# workspace provisioning :157-163, data tarballing :166-174, W&B key
# plumbing :154-155,305-308, submit confirmation :330-344 — this script
# gives the TPU-pod path the same treatment, replacing sbatch with the
# gcloud TPU-VM / queued-resources API).
#
# Usage:
#   bash launch/gcloud_submitter.sh -T NAME -z ZONE [options] [-- CMD...]
# Options:
#   -T NAME      TPU name (required)
#   -z ZONE      zone (required)
#   -A TYPE      accelerator type for provisioning (e.g. v5litepod-8);
#                with -A the TPU is created if absent, else it must exist
#   -V VERSION   runtime version                  (default tpu-ubuntu2204-base)
#   -q           provision through a queued resource (spot-friendly
#                allocation; polls until ACTIVE) instead of direct create
#   -d PATHS     comma-separated data dirs -> staged as tarballs once,
#                pushed + extracted on every worker
#   -s DIR       scratch root                     (default ${SCRATCH:-$HOME/scratch})
#   -e NAME      experiment name                  (default timestamped)
#   -x FILE      experiment config file (one-line command; default
#                launch/experiment_configurations.txt; a trailing -- CMD
#                overrides the file)
#   -r N         max whole-pod restarts on worker failure (default 0;
#                the tpurun --max-restarts contract at pod scope)
#   -b SEC       restart backoff seconds          (default 5)
#   -w N         worker count override (default: parsed from describe)
#   -D           delete the TPU / queued resource on exit (always runs via
#                trap, even when the job fails)
#   -n           no-confirm
#   -h           help
#
# Per-worker stdout/stderr land in
#   ${scratch}/${project}/${exp}/cloud_outputs/attempt${A}-worker${W}.out
# mirroring the reference's hpc_outputs/%x-%j-%N.out per-node capture.
set -euo pipefail

# shellcheck disable=SC1091
source "$(dirname "$0")/lib.sh"

source_dir="$(pwd)"
project_name="$(basename "${source_dir}")"

tpu_name=""; zone=""; accel_type=""; runtime_version="tpu-ubuntu2204-base"
queued=0; data_paths=""; scratch_dir="${SCRATCH:-$HOME/scratch}"
exp_name="exp_$(date +%Y%m%d_%H%M%S)"
exp_configs_path="launch/experiment_configurations.txt"
max_restarts=0; backoff=5; n_workers=""; delete_on_exit=0; confirm=1

while getopts "T:z:A:V:qd:s:e:x:r:b:w:Dnh" opt; do
  case "${opt}" in
    T) tpu_name="${OPTARG}" ;;
    z) zone="${OPTARG}" ;;
    A) accel_type="${OPTARG}" ;;
    V) runtime_version="${OPTARG}" ;;
    q) queued=1 ;;
    d) data_paths="${OPTARG}" ;;
    s) scratch_dir="${OPTARG}" ;;
    e) exp_name="${OPTARG}" ;;
    x) exp_configs_path="${OPTARG}" ;;
    r) max_restarts="${OPTARG}" ;;
    b) backoff="${OPTARG}" ;;
    w) n_workers="${OPTARG}" ;;
    D) delete_on_exit=1 ;;
    n) confirm=0 ;;
    h) sed -n '2,37p' "$0" | sed 's/^# \{0,1\}//'; exit 0 ;;
    *) echo "unknown flag; -h for help" >&2; exit 2 ;;
  esac
done
shift $((OPTIND - 1))
[[ "${1:-}" == "--" ]] && shift

[[ -n "${tpu_name}" && -n "${zone}" ]] || {
  echo "gcloud_submitter: -T NAME and -z ZONE are required" >&2; exit 2; }

tpu() { gcloud compute tpus tpu-vm "$@"; }
qres() { gcloud compute tpus queued-resources "$@"; }

# ---- provision or reuse -------------------------------------------------
# Reuse when the TPU already answers describe; create (directly or through
# a queued resource) only when -A declares what to create.
cleanup_provisioned() {
  if [[ "${delete_on_exit}" -eq 1 ]]; then
    echo "cleanup: deleting ${tpu_name}"
    tpu delete "${tpu_name}" --zone "${zone}" --quiet || true
    if [[ "${queued}" -eq 1 ]]; then
      qres delete "${tpu_name}-qr" --zone "${zone}" --quiet --force || true
    fi
  fi
}
trap cleanup_provisioned EXIT

if tpu describe "${tpu_name}" --zone "${zone}" >/dev/null 2>&1; then
  echo "reusing TPU ${tpu_name} (${zone})"
elif [[ -n "${accel_type}" ]]; then
  if [[ "${queued}" -eq 1 ]]; then
    echo "queueing ${accel_type} as ${tpu_name}-qr…"
    qres create "${tpu_name}-qr" --zone "${zone}" \
      --node-id "${tpu_name}" --accelerator-type "${accel_type}" \
      --runtime-version "${runtime_version}"
    # Poll until the allocation lands (queued capacity can take a while;
    # the reference's install-job poll, job_submitter.sh:184-245, is the
    # same submit-and-wait shape).
    poll_fails=0
    while true; do
      state="$(qres describe "${tpu_name}-qr" --zone "${zone}" \
        --format='value(state.state)' 2>/dev/null)" || state=""
      case "${state}" in
        ACTIVE) break ;;
        FAILED|SUSPENDED)
          echo "queued resource ${tpu_name}-qr entered ${state}" >&2; exit 1 ;;
        "")
          poll_fails=$((poll_fails + 1))
          [[ "${poll_fails}" -ge 30 ]] && {
            echo "queued-resource describe unreachable" >&2; exit 1; } ;;
        *) poll_fails=0 ;;
      esac
      sleep 10
    done
    echo "queued resource ACTIVE"
  else
    echo "creating TPU ${tpu_name} (${accel_type})…"
    tpu create "${tpu_name}" --zone "${zone}" \
      --accelerator-type "${accel_type}" \
      --version "${runtime_version}"
  fi
else
  echo "gcloud_submitter: TPU ${tpu_name} not found and no -A type to create" >&2
  exit 1
fi

# ---- worker topology ----------------------------------------------------
if [[ -z "${n_workers}" ]]; then
  n_workers="$(tpu describe "${tpu_name}" --zone "${zone}" \
    --format='value(networkEndpoints[].ipAddress)' | tr ';' '\n' | grep -c . \
    || true)"
  [[ "${n_workers}" -ge 1 ]] || n_workers=1
fi
echo "workers: ${n_workers}"

# ---- experiment workspace (job_submitter.sh:157-163 parity) -------------
exp_dir="${scratch_dir}/${project_name}/${exp_name}"
mkdir -p "${exp_dir}/checkpoints" "${exp_dir}/cloud_outputs" "${exp_dir}/data"

# ---- stage code + data --------------------------------------------------
# Code: one tarball of the working tree, pushed and unpacked on every
# worker.  In a git checkout, ship tracked + untracked-unignored files
# with their WORKING-TREE content (git archive would ship only committed
# state; plain ls-files would abort on locally-deleted tracked files and
# drop new files) — skipping paths that no longer exist.
code_tar="${exp_dir}/data/${project_name}-code.tar"
if git -C "${source_dir}" rev-parse --git-dir >/dev/null 2>&1; then
  (
    cd "${source_dir}"
    while IFS= read -r -d '' f; do
      # if-form: a `[[ ]] &&` list as the loop's last command would end
      # the subshell with status 1 when the FINAL listed file is deleted,
      # and pipefail would kill the submitter.
      if [[ -e "${f}" ]]; then printf '%s\0' "${f}"; fi
    done < <(git ls-files -z --cached --others --exclude-standard)
  ) | tar -cf "${code_tar}" --null -C "${source_dir}" -T - \
        --transform "s,^,${project_name}/,"
else
  tar -cf "${code_tar}" -C "$(dirname "${source_dir}")" \
    --exclude="${project_name}/.git" --exclude="${project_name}/runs" \
    "${project_name}"
fi

# Data: the reference's tar-once contract (:166-174; launch/lib.sh).
tpudist_stage_data "${exp_dir}" "${data_paths}"
staged="${code_tar}${staged_out:+,${staged_out}}"

# ---- the experiment command --------------------------------------------
if [[ "$#" -gt 0 ]]; then
  cmd="$*"
else
  tpudist_experiment_cmd "${exp_configs_path}"
fi
# basename check like tpurun's _validate_cmd: absolute-path interpreters
# (/opt/venv/bin/python train.py) are the common real shape.
[[ "$(basename "${cmd%% *}")" == python* ]] || {
  echo "gcloud_submitter: command must start with python (got: ${cmd})" >&2
  exit 2; }

# ---- W&B credentials (job_submitter.sh:154-155,306; launch/lib.sh) ------
tpudist_wandb_key

echo "launch: ${cmd}"
echo "  tpu=${tpu_name} zone=${zone} workers=${n_workers} restarts=${max_restarts}"
echo "  outputs=${exp_dir}/cloud_outputs/"
if [[ "${confirm}" -eq 1 ]]; then
  read -r -p "launch? [y/N] " yn
  [[ "${yn}" == "y" || "${yn}" == "Y" ]] || { echo "aborted"; exit 0; }
fi

# ---- ship the experiment environment as a 0600 file ---------------------
# Secrets must never ride the ssh --command argv (visible in `ps` on every
# worker for the job's lifetime); the SLURM path ships them through
# sbatch's exported environment, the pod path ships a sourced env file.
# $HOME/$(whoami) references stay literal here — they expand on the
# WORKER when the file is sourced (multi-user paths differ per VM).
remote_env="/tmp/tpudist_env_${exp_name}"
remote_data="\$HOME/tpudist_data/${exp_name}"
env_file="${exp_dir}/data/remote_env.sh"
cat > "${env_file}" <<EOF
export WANDB_API_KEY='${wandb_key}'
export scratch_dir="\$HOME/scratch"
export exp_name='${exp_name}'
export project_name='${project_name}'
export TPUDIST_TMPDIR="${remote_data}"
EOF
chmod 600 "${env_file}"

# Push + unpack the tarballs on every worker in one fan-out: code into
# \$HOME, data into TPUDIST_TMPDIR (the landing spot the framework's
# staging discovery and the SLURM job scripts share —
# launch/standard_job.sh extracts into the same contract).
IFS=',' read -ra tars <<< "${staged}"
for tb in "${tars[@]}"; do
  tpu scp "${tb}" "${tpu_name}:/tmp/" --zone "${zone}" --worker=all
done
tpu scp "${env_file}" "${tpu_name}:${remote_env}" --zone "${zone}" \
  --worker=all
unpack="chmod 600 ${remote_env} && mkdir -p ${remote_data} && cd \$HOME"
unpack+=" && tar -xf /tmp/$(basename "${code_tar}")"
for tb in "${tars[@]}"; do
  [[ "${tb}" == "${code_tar}" ]] && continue
  unpack+=" && tar -xf /tmp/$(basename "${tb}") -C ${remote_data}"
done
tpu ssh "${tpu_name}" --zone "${zone}" --worker=all --command "${unpack}"

# ---- run with the restart-with-backoff contract -------------------------
# One ssh per worker, backgrounded, per-worker output files, wait on all —
# the dispatcher shape (distributed_dispatcher.sh node loop) at pod scope.
# On TPU VMs jax.distributed.initialize() discovers coordinator/topology
# from the metadata server, so the sourced env only carries the experiment
# contract (scratch/exp/project for checkpoint_dir_for, TPUDIST_TMPDIR for
# staged data, the W&B key) plus per-attempt TPUDIST_RESTART_COUNT for
# crash records.
attempt=0
while :; do
  pids=()
  for ((w = 0; w < n_workers; w++)); do
    out="${exp_dir}/cloud_outputs/attempt${attempt}-worker${w}.out"
    remote="source ${remote_env} && cd \$HOME/${project_name} && \
TPUDIST_RESTART_COUNT='${attempt}' ${cmd}"
    tpu ssh "${tpu_name}" --zone "${zone}" --worker="${w}" \
      --command "${remote}" > "${out}" 2>&1 &
    pids+=("$!")
  done
  rc=0
  for pid in "${pids[@]}"; do
    wait "${pid}" || rc=$?
  done
  if [[ "${rc}" -eq 0 ]]; then
    echo "job finished (attempt ${attempt})"
    break
  fi
  echo "attempt ${attempt} failed (rc=${rc}); outputs in ${exp_dir}/cloud_outputs/"
  if [[ "${attempt}" -ge "${max_restarts}" ]]; then
    echo "restarts exhausted (${max_restarts})" >&2
    exit 1
  fi
  attempt=$((attempt + 1))
  echo "restarting in ${backoff}s (attempt ${attempt}/${max_restarts})…"
  sleep "${backoff}"
done
