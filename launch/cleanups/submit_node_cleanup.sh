#!/bin/bash
# Submit one cleanup job per node — tpudist equivalent of the reference's
# plai_cleanups/submit_plai_cleanup (B13): array of per-node sbatch jobs
# deleting leftover node-local scratch.
#
#   bash launch/cleanups/submit_node_cleanup.sh node1 node2 …
#   bash launch/cleanups/submit_node_cleanup.sh $(sinfo -h -o %n)
set -euo pipefail

[[ $# -ge 1 ]] || { echo "usage: $0 NODE [NODE…]" >&2; exit 2; }
here="$(cd "$(dirname "$0")" && pwd)"

for node in "$@"; do
  sbatch --job-name="tpudist-cleanup-${node}" --nodelist="${node}" \
    --time=00:05:00 --mem=256M --output=/dev/null \
    "${here}/node_cleanup.sh"
done
