#!/bin/bash
# Per-node scratch cleanup job — tpudist equivalent of the reference's
# plai_cleanups/plai_cleanup.sh (B13, SURVEY.md §2.2): delete this user's
# leftover node-local scratch from crashed jobs.
set -euo pipefail

scratch_root="${scratch_root:-/tmp}"
echo "cleaning ${scratch_root}/tpudist_* (user ${USER}) on $(hostname)"
find "${scratch_root}" -maxdepth 1 -name 'tpudist_*' -user "${USER}" \
  -exec rm -rf {} + 2>/dev/null || true
