#!/bin/bash
# Containerized distributed dispatcher — tpudist equivalent of the
# reference's singularity_hpc_files/distributed_dispatcher.sh (B7, SURVEY.md
# §2.2): one containerized task per rank via a single srun; rank derivation
# happens inside the container from the forwarded SLURM env contract
# (bootstrap priority 4), the same "distribution is left to the payload"
# stance as the reference (:3-6).
set -euo pipefail

export MASTER_ADDR="$(hostname)"
export MASTER_PORT="${MASTER_PORT:-2345}"
export WORLD_SIZE="${SLURM_NTASKS:?}"
export TASKS_PER_NODE="${SLURM_NTASKS_PER_NODE:-1}"

# $0 under sbatch is SLURM's spool copy — resolve the sibling script through
# the job payload's source_dir instead.
rc=0
srun bash "${source_dir:?}/launch/container/standard_job.sh" || rc=$?

# Remove each node's shared staging dir (image + data) now that every task
# on it has finished; per-task dirs were cleaned by the tasks themselves.
# Same base resolution as standard_job.sh: profile node_tmpdir > scheduler
# tmpdir > /tmp.
srun --ntasks="${SLURM_NNODES:-1}" --ntasks-per-node=1 \
  bash -c 'rm -rf "${node_tmpdir:-${SLURM_TMPDIR:-/tmp}}/tpudist_${SLURM_JOB_ID}_shared"' || true
exit "${rc}"
