#!/bin/bash
# Containerized job task — tpudist equivalent of the reference's
# singularity_hpc_files/standard_job.sh (B6, SURVEY.md §2.2): image to
# node-local disk, per-job overlay dirs, run the container with bind mounts
# and forwarded env, clean up.  May run as MANY tasks per node (container
# distributed mode): node-shared work (image rsync, data extraction) is done
# once by SLURM_LOCALID 0 behind a sentinel; overlays are per-task.
#
# Env payload (from job_submitter.sh): cmd, source_dir, scratch_dir,
# exp_name, project_name, staged_tarballs, WANDB_API_KEY, sif_path.
set -euo pipefail

sif_path="${sif_path:?path to .sif image}"
job_id="${SLURM_JOB_ID:-$$}"
local_id="${SLURM_LOCALID:-0}"
task_id="${SLURM_PROCID:-0}"

# Fast node-local base: cluster-profile node_tmpdir first (clusters whose
# local SSD is not SLURM_TMPDIR — launch/clusters/), then the scheduler
# tmpdir, then /tmp.
tmp_base="${node_tmpdir:-${SLURM_TMPDIR:-/tmp}}"
mkdir -p "${tmp_base}"
# Node-shared dir: image + extracted data, staged once per node.  Not
# trap-cleaned (sibling tasks may outlive this one); the dispatcher removes
# it per-node after srun returns, and launch/cleanups/ catches crashes.
shared="${tmp_base}/tpudist_${job_id}_shared"
# Per-task dir: overlays + workdir, safe to clean on our own exit.
task_tmp="${tmp_base}/tpudist_${job_id}_task${task_id}"
mkdir -p "${shared}" "${task_tmp}"
# Single-task jobs (the -j standard container path) own the shared dir too;
# multi-task jobs leave it for the dispatcher's per-node cleanup pass.
if [[ "${SLURM_NTASKS:-1}" -le 1 ]]; then
  trap 'rm -rf "${task_tmp}" "${shared}"' EXIT
else
  trap 'rm -rf "${task_tmp}"' EXIT
fi

local_sif="${shared}/$(basename "${sif_path}")"
sentinel="${shared}/.staged"
if [[ "${local_id}" == "0" ]]; then
  # Image to node-local disk first — container startup off shared FS is slow
  # (reference singularity standard_job.sh:19-21).
  time rsync -a "${sif_path}" "${local_sif}"
  if [[ -n "${staged_tarballs:-}" ]]; then
    IFS=',' read -ra tbs <<< "${staged_tarballs}"
    for tb in "${tbs[@]}"; do time tar -xf "${tb}" -C "${shared}"; done
  fi
  touch "${sentinel}"
else
  # Bounded wait — fail fast if the LOCALID-0 staging task died.
  waited=0
  while [[ ! -f "${sentinel}" ]]; do
    sleep 1; waited=$((waited + 1))
    if [[ "${waited}" -ge "${TPUDIST_STAGE_TIMEOUT:-600}" ]]; then
      echo "staging sentinel never appeared (rank-0 staging failed?)" >&2
      exit 1
    fi
  done
fi

# Per-job overlay dirs (reference :30-62): writable tmp/home/workdir so the
# image itself stays read-only.
workdir="${task_tmp}/workdir"
home_overlay="${task_tmp}/home_overlay"
tmp_overlay="${task_tmp}/tmp_overlay"
mkdir -p "${workdir}" "${home_overlay}" "${tmp_overlay}"

# Forward the launch contract into the container (reference :74-78
# SINGULARITYENV_* pattern).
export SINGULARITYENV_WANDB_API_KEY="${WANDB_API_KEY:-}"
export SINGULARITYENV_TPUDIST_WORKDIR="${workdir}"
export SINGULARITYENV_TPUDIST_TMPDIR="${shared}"
for var in SLURM_JOB_ID SLURM_PROCID SLURM_LOCALID SLURM_NTASKS \
           SLURM_NTASKS_PER_NODE MASTER_ADDR MASTER_PORT WORLD_SIZE \
           TASKS_PER_NODE NODE_RANK; do
  [[ -n "${!var:-}" ]] && export "SINGULARITYENV_${var}=${!var}"
done

# --nv is CUDA-only; TPU chips enter the container by binding the accel
# device nodes when present.
tpu_binds=()
for dev in /dev/accel*; do [[ -e "${dev}" ]] && tpu_binds+=(--bind "${dev}"); done

singularity run --cleanenv --no-home --contain --writable-tmpfs \
  "${tpu_binds[@]}" \
  --bind "${scratch_dir:?}","${shared}","${tmp_overlay}:/tmp","${home_overlay}:${HOME}","${workdir}" \
  "${local_sif}" ${cmd:?}
