#!/bin/bash
# SLURM front door — tpudist equivalent of hpc_files/job_submitter.sh
# (reference B1, SURVEY.md §2.2: flag parsing job_submitter.sh:21-141,
# scratch/checkpoint dir provisioning :157-163, data tarballing :166-174,
# job-type branching :254-293, env payload :305-308, confirm+sbatch :330-344).
#
# Usage:
#   bash launch/job_submitter.sh -j {standard|distributed|sweep} [options]
# Options:
#   -j TYPE      job type: standard | distributed | sweep        (default standard)
#   -c N         cpus per task                                   (default 4)
#   -g N         accelerator chips per node                      (default 0)
#   -N N         nodes                                           (default 1)
#   -t TIME      walltime                                        (default 02:00:00)
#   -m MEM       memory per node                                 (default 16G)
#   -p PART      partition
#   -a ACCT      account
#   -d PATHS     comma-separated data dirs -> staged as tarballs
#   -s DIR       scratch dir root             (default ${SCRATCH:-$HOME/scratch})
#   -e NAME      experiment name              (default timestamped)
#   -x FILE      experiment config file (one-line command; default
#                launch/experiment_configurations.txt)
#   -S FILE      sweep spec YAML (sweep jobs; default launch/sweeper.yml)
#   -W WORKFLOW  distributed workflow: tpurun (per-node agent) | trainer
#                (one task per chip, SLURM-env rank derivation)  (default tpurun)
#   -C SIF       run inside a Singularity image (container job scripts)
#   -i           submit a virtualenv-install job first and wait for it
#   -n           no-confirm (skip the interactive prompt)
#   -h           help
set -euo pipefail

source_dir="$(pwd)"
project_name="$(basename "${source_dir}")"

job_type="standard"; cpus=4; gpus=0; nodes=1; walltime="02:00:00"; mem="16G"
partition=""; account=""; data_paths=""
scratch_dir="${SCRATCH:-$HOME/scratch}"
exp_name="exp_$(date +%Y%m%d_%H%M%S)"
exp_configs_path=""
sweep_spec="launch/sweeper.yml"
workflow="tpurun"
sif_path=""
install_env=0
confirm=1

while getopts "j:c:g:N:t:m:p:a:d:s:e:x:S:W:C:inh" opt; do
  case "${opt}" in
    j) job_type="${OPTARG}" ;;
    c) cpus="${OPTARG}" ;;
    g) gpus="${OPTARG}" ;;
    N) nodes="${OPTARG}" ;;
    t) walltime="${OPTARG}" ;;
    m) mem="${OPTARG}" ;;
    p) partition="${OPTARG}" ;;
    a) account="${OPTARG}" ;;
    d) data_paths="${OPTARG}" ;;
    s) scratch_dir="${OPTARG}" ;;
    e) exp_name="${OPTARG}" ;;
    x) exp_configs_path="${OPTARG}" ;;
    S) sweep_spec="${OPTARG}" ;;
    W) workflow="${OPTARG}" ;;
    C) sif_path="${OPTARG}" ;;
    i) install_env=1 ;;
    n) confirm=0 ;;
    h) cat "$(dirname "$0")/.help_message.txt"; exit 0 ;;
    *) echo "unknown flag; -h for help" >&2; exit 2 ;;
  esac
done

case "${job_type}" in standard|distributed|sweep) ;; *)
  echo "job_submitter: -j must be standard|distributed|sweep" >&2; exit 2 ;; esac
case "${workflow}" in tpurun|trainer) ;; *)
  echo "job_submitter: -W must be tpurun|trainer" >&2; exit 2 ;; esac

# Per-workflow default config file (reference torchrun_configs.txt /
# lightning_configs.txt split, job_submitter.sh:296-300).
if [[ -z "${exp_configs_path}" ]]; then
  case "${job_type}/${workflow}" in
    sweep/*)             exp_configs_path="launch/sweep_cmd.txt" ;;
    distributed/trainer) exp_configs_path="launch/trainer_configs.txt" ;;
    distributed/tpurun)  exp_configs_path="launch/distributed_configs.txt" ;;
    *)                   exp_configs_path="launch/experiment_configurations.txt" ;;
  esac
fi

# Experiment workspace: checkpoints + output dirs (job_submitter.sh:157-163).
exp_dir="${scratch_dir}/${project_name}/${exp_name}"
mkdir -p "${exp_dir}/checkpoints" "${exp_dir}/hpc_outputs" "${exp_dir}/data"

# Stage data as tarballs once (job_submitter.sh:166-174).
staged=""
if [[ -n "${data_paths}" ]]; then
  IFS=',' read -ra paths <<< "${data_paths}"
  for p in "${paths[@]}"; do
    tb="${exp_dir}/data/$(basename "${p}").tar"
    if [[ ! -f "${tb}" ]]; then
      echo "staging ${p} -> ${tb}"
      time tar -cf "${tb}" -C "$(dirname "${p}")" "$(basename "${p}")"
    fi
    staged="${staged:+${staged},}${tb}"
  done
fi

# Optional virtualenv bootstrap: submit the install job and poll squeue until
# it leaves the queue (reference job_submitter.sh:184-245 + B8).
if [[ "${install_env}" -eq 1 ]]; then
  install_out="${exp_dir}/hpc_outputs/install-%j.out"
  install_id="$(sbatch --parsable --job-name="${project_name}-install" \
    --time=00:30:00 --mem=4G --cpus-per-task=2 --output="${install_out}" \
    --export="ALL,source_dir=${source_dir}" launch/install_python_packages.sh)"
  echo "waiting for install job ${install_id}…"
  # A failing squeue is NOT job completion — retry transient scheduler
  # errors, give up after 30 consecutive failures.
  squeue_fails=0
  while true; do
    if q_out="$(squeue -h -j "${install_id}" 2>/dev/null)"; then
      squeue_fails=0
      [[ -z "${q_out}" ]] && break
    else
      squeue_fails=$((squeue_fails + 1))
      if [[ "${squeue_fails}" -ge 30 ]]; then
        echo "squeue unreachable while waiting for install job" >&2; exit 1
      fi
    fi
    sleep 10
  done
  echo "install job ${install_id} finished"
fi

# The one-line experiment command (job_submitter.sh:300).
cmd="$(tr -d '\n\r\\' < "${exp_configs_path}")"

# W&B credentials plumbing (job_submitter.sh:154-155,306): optional file.
wandb_key=""
[[ -f "${HOME}/wandb_credentials.txt" ]] && wandb_key="$(head -n1 "${HOME}/wandb_credentials.txt")"

sbatch_cmd=(
  --job-name="${project_name}-${exp_name}"
  --time="${walltime}" --mem="${mem}" --nodes="${nodes}"
  --output="${exp_dir}/hpc_outputs/%x-%j-%N.out"
)
[[ -n "${partition}" ]] && sbatch_cmd+=(--partition="${partition}")
[[ -n "${account}"   ]] && sbatch_cmd+=(--account="${account}")
[[ "${gpus}" -gt 0   ]] && sbatch_cmd+=(--gres="gpu:${gpus}")

# cmd and the tarball list may contain commas, which sbatch's --export parser
# splits on — ship them via the exported environment (ALL) and keep only
# comma-free scalars in the explicit payload.
export cmd
export staged_tarballs="${staged}"
payload="ALL,source_dir=${source_dir},scratch_dir=${scratch_dir}"
payload+=",exp_name=${exp_name},project_name=${project_name}"
payload+=",WANDB_API_KEY=${wandb_key}"

case "${job_type}" in
  sweep)
    # Array job sized by the sweep grid (job_submitter.sh:259-271 pattern,
    # but the grid size comes from the spec — no interactive prompt needed).
    n_sweeps="$(python -m tpudist.launch.sweep count "${sweep_spec}")"
    echo "sweep grid size: ${n_sweeps}"
    sbatch_cmd+=(--array="0-$((n_sweeps - 1))%10" --cpus-per-task="${cpus}" --ntasks-per-node=1)
    [[ "${sweep_spec}" = /* ]] || sweep_spec="${source_dir}/${sweep_spec}"
    payload+=",sweep_spec=${sweep_spec}"
    hpc_file="launch/standard_job.sh"
    ;;
  distributed)
    chips=$(( gpus > 0 ? gpus : 1 ))
    if [[ "${workflow}" == "trainer" ]]; then
      # trainer workflow: one task per chip, ranks derived from SLURM env
      # (reference lightning shape, job_submitter.sh:288).
      sbatch_cmd+=(--ntasks-per-node="${chips}" --cpus-per-task="${cpus}")
    else
      # tpurun workflow: ONE agent task per node that forks the workers
      # itself (job_submitter.sh:290-291: ntasks-per-node=1, cpus *= chips).
      sbatch_cmd+=(--ntasks-per-node=1 --cpus-per-task="$((cpus * chips))")
    fi
    payload+=",chips_per_node=${chips},workflow=${workflow}"
    hpc_file="launch/distributed_dispatcher.sh"
    ;;
  standard)
    sbatch_cmd+=(--ntasks-per-node=1 --cpus-per-task="${cpus}")
    hpc_file="launch/standard_job.sh"
    ;;
esac

# Container jobs swap in the singularity job scripts (reference
# job_submitter.sh:266,286 virtualenv/singularity branch).
if [[ -n "${sif_path}" ]]; then
  payload+=",sif_path=${sif_path}"
  case "${job_type}" in
    distributed)
      # One containerized task per rank; ranks derive from forwarded SLURM
      # env.  Only the tpurun shape (1 fat agent task with cpus×chips) needs
      # undoing — rebuild those two elements exactly rather than pattern-
      # substituting (a substring pattern would corrupt e.g. `=16` → `=166`).
      if [[ "${workflow}" == "tpurun" ]]; then
        rebuilt=()
        for el in "${sbatch_cmd[@]}"; do
          case "${el}" in
            --ntasks-per-node=1) rebuilt+=("--ntasks-per-node=${chips}") ;;
            --cpus-per-task=*)   rebuilt+=("--cpus-per-task=${cpus}") ;;
            *)                   rebuilt+=("${el}") ;;
          esac
        done
        sbatch_cmd=("${rebuilt[@]}")
      fi
      hpc_file="launch/container/distributed_dispatcher.sh"
      ;;
    *) hpc_file="launch/container/standard_job.sh" ;;
  esac
fi
sbatch_cmd+=(--export="${payload}")

echo "sbatch ${sbatch_cmd[*]} ${hpc_file}"
if [[ "${confirm}" -eq 1 ]]; then
  read -r -p "submit? [y/N] " yn   # confirm prompt (job_submitter.sh:330-343)
  [[ "${yn}" == "y" || "${yn}" == "Y" ]] || { echo "aborted"; exit 0; }
fi
sbatch "${sbatch_cmd[@]}" "${hpc_file}"
