#!/bin/bash
# SLURM front door — tpudist equivalent of hpc_files/job_submitter.sh
# (reference B1, SURVEY.md §2.2: flag parsing job_submitter.sh:21-141,
# scratch/checkpoint dir provisioning :157-163, data tarballing :166-174,
# job-type branching :254-293, env payload :305-308, confirm+sbatch :330-344).
#
# Usage:
#   bash launch/job_submitter.sh -j {standard|distributed|sweep} [options]
# Options:
#   -j TYPE      job type: standard | distributed | sweep        (default standard)
#   -c N         cpus per task                                   (default 4)
#   -g N         accelerator chips per node                      (default 0)
#   -N N         nodes                                           (default 1)
#   -t TIME      walltime                                        (default 02:00:00)
#   -m MEM       memory per node                                 (default 16G)
#   -p PART      partition
#   -a ACCT      account
#   -d PATHS     comma-separated data dirs -> staged as tarballs
#   -s DIR       scratch dir root             (default ${SCRATCH:-$HOME/scratch})
#   -e NAME      experiment name              (default timestamped)
#   -x FILE      experiment config file (one-line command; default
#                launch/experiment_configurations.txt)
#   -S FILE      sweep spec YAML (sweep jobs; default launch/sweeper.yml)
#   -I SWEEPID   W&B *server* sweep id (entity/project/id): array tasks run
#                `wandb agent --count 1` against the server instead of the
#                local grid (reference job_submitter.sh:259-265 flow; the
#                interactive prompt asks when -I is omitted on a sweep job)
#   -R N         number of sweep runs = array size for -I server sweeps
#                (the reference's "how many runs" prompt)
#   -W WORKFLOW  distributed workflow: tpurun (per-node agent) | trainer
#                (one task per chip, SLURM-env rank derivation)  (default tpurun)
#   -C SIF       run inside a Singularity image (container job scripts)
#   -P PROFILE   cluster profile: a name under launch/clusters/ (sans
#                .profile), a path, or "none".  Default: auto-detect by
#                matching this host against each profile's "# match:" glob
#                (the reference's per-cluster hostname branches,
#                job_submitter.sh:180-182,267-271,321-327, as data files
#                instead of inline special cases)
#   -i           submit a virtualenv-install job first and wait for it
#   -n           no-confirm (skip the interactive prompt)
#   -h           help
set -euo pipefail

# shellcheck disable=SC1091
source "$(dirname "$0")/lib.sh"

source_dir="$(pwd)"
project_name="$(basename "${source_dir}")"

job_type="standard"; cpus=4; gpus=0; nodes=1; walltime="02:00:00"; mem="16G"
partition=""; account=""; data_paths=""
scratch_dir="${SCRATCH:-$HOME/scratch}"
exp_name="exp_$(date +%Y%m%d_%H%M%S)"
exp_configs_path=""
sweep_spec="launch/sweeper.yml"
workflow="tpurun"
sif_path=""
install_env=0
confirm=1
profile=""
wandb_sweep_id=""
sweep_runs=""
user_cpus=0; user_mem=0; user_walltime=0; user_scratch=0

while getopts "j:c:g:N:t:m:p:a:d:s:e:x:S:W:C:P:I:R:inh" opt; do
  case "${opt}" in
    j) job_type="${OPTARG}" ;;
    c) cpus="${OPTARG}"; user_cpus=1 ;;
    g) gpus="${OPTARG}" ;;
    N) nodes="${OPTARG}" ;;
    t) walltime="${OPTARG}"; user_walltime=1 ;;
    m) mem="${OPTARG}"; user_mem=1 ;;
    p) partition="${OPTARG}" ;;
    a) account="${OPTARG}" ;;
    d) data_paths="${OPTARG}" ;;
    s) scratch_dir="${OPTARG}"; user_scratch=1 ;;
    e) exp_name="${OPTARG}" ;;
    x) exp_configs_path="${OPTARG}" ;;
    S) sweep_spec="${OPTARG}" ;;
    W) workflow="${OPTARG}" ;;
    C) sif_path="${OPTARG}" ;;
    P) profile="${OPTARG}" ;;
    I) wandb_sweep_id="${OPTARG}" ;;
    R) sweep_runs="${OPTARG}" ;;
    i) install_env=1 ;;
    n) confirm=0 ;;
    h) cat "$(dirname "$0")/.help_message.txt"; exit 0 ;;
    *) echo "unknown flag; -h for help" >&2; exit 2 ;;
  esac
done

case "${job_type}" in standard|distributed|sweep) ;; *)
  echo "job_submitter: -j must be standard|distributed|sweep" >&2; exit 2 ;; esac
case "${workflow}" in tpurun|trainer) ;; *)
  echo "job_submitter: -W must be tpurun|trainer" >&2; exit 2 ;; esac

# ---- cluster profile ---------------------------------------------------
# A profile is a sourced bash fragment under launch/clusters/ that adapts
# the submission to one cluster: scheduler defaults (cluster_partition/
# _account/_mem/_walltime/_cpus — applied only where the user passed no
# explicit flag), extra sbatch flags (cluster_sbatch_extra array), a
# node-local fast-disk root (cluster_tmpdir → node_tmpdir for the job
# scripts), and a scratch root (cluster_scratch).  Auto-detected by the
# "# match: <glob>" header against this hostname unless -P selects one.
cluster_partition=""; cluster_account=""; cluster_mem=""; cluster_walltime=""
cluster_cpus=""; cluster_tmpdir=""; cluster_scratch=""; cluster_sbatch_extra=()
profile_file=""
clusters_dir="${TPUDIST_CLUSTERS_DIR:-$(dirname "$0")/clusters}"
if [[ "${profile}" == "none" ]]; then
  :
elif [[ -n "${profile}" ]]; then
  profile_file="${clusters_dir}/${profile}.profile"
  [[ -f "${profile_file}" ]] || profile_file="${profile}"
  [[ -f "${profile_file}" ]] || {
    echo "job_submitter: no cluster profile '${profile}' (looked in ${clusters_dir})" >&2
    exit 2
  }
else
  host_fqdn="$(hostname -f 2>/dev/null || hostname)"
  for f in "${clusters_dir}"/*.profile; do
    [[ -e "${f}" ]] || continue
    pat="$(sed -n 's/^# match: *//p' "${f}" | head -n1)"
    # shellcheck disable=SC2053  # glob match against the declared pattern
    if [[ -n "${pat}" && ( "${host_fqdn}" == ${pat} || "$(hostname)" == ${pat} ) ]]; then
      profile_file="${f}"; break
    fi
  done
fi
if [[ -n "${profile_file}" ]]; then
  echo "cluster profile: ${profile_file}"
  # shellcheck disable=SC1090
  source "${profile_file}"
  [[ -n "${cluster_partition}" && -z "${partition}" ]] && partition="${cluster_partition}"
  [[ -n "${cluster_account}"   && -z "${account}"   ]] && account="${cluster_account}"
  [[ -n "${cluster_mem}"      && "${user_mem}" -eq 0      ]] && mem="${cluster_mem}"
  [[ -n "${cluster_walltime}" && "${user_walltime}" -eq 0 ]] && walltime="${cluster_walltime}"
  [[ -n "${cluster_cpus}"     && "${user_cpus}" -eq 0     ]] && cpus="${cluster_cpus}"
  [[ -n "${cluster_scratch}"  && "${user_scratch}" -eq 0  ]] && scratch_dir="${cluster_scratch}"
fi
# ------------------------------------------------------------------------

# Per-workflow default config file (reference torchrun_configs.txt /
# lightning_configs.txt split, job_submitter.sh:296-300).
if [[ -z "${exp_configs_path}" ]]; then
  case "${job_type}/${workflow}" in
    sweep/*)             exp_configs_path="launch/sweep_cmd.txt" ;;
    distributed/trainer) exp_configs_path="launch/trainer_configs.txt" ;;
    distributed/tpurun)  exp_configs_path="launch/distributed_configs.txt" ;;
    *)                   exp_configs_path="launch/experiment_configurations.txt" ;;
  esac
fi

# Experiment workspace: checkpoints + output dirs (job_submitter.sh:157-163).
exp_dir="${scratch_dir}/${project_name}/${exp_name}"
mkdir -p "${exp_dir}/checkpoints" "${exp_dir}/hpc_outputs" "${exp_dir}/data"

# Stage data as tarballs once (job_submitter.sh:166-174; launch/lib.sh).
tpudist_stage_data "${exp_dir}" "${data_paths}"
staged="${staged_out}"

# Optional virtualenv bootstrap: submit the install job and poll squeue until
# it leaves the queue (reference job_submitter.sh:184-245 + B8).
if [[ "${install_env}" -eq 1 ]]; then
  install_out="${exp_dir}/hpc_outputs/install-%j.out"
  install_id="$(sbatch --parsable --job-name="${project_name}-install" \
    --time=00:30:00 --mem=4G --cpus-per-task=2 --output="${install_out}" \
    --export="ALL,source_dir=${source_dir}" launch/install_python_packages.sh)"
  echo "waiting for install job ${install_id}…"
  # A failing squeue is NOT job completion — retry transient scheduler
  # errors, give up after 30 consecutive failures.
  squeue_fails=0
  while true; do
    if q_out="$(squeue -h -j "${install_id}" 2>/dev/null)"; then
      squeue_fails=0
      [[ -z "${q_out}" ]] && break
    else
      squeue_fails=$((squeue_fails + 1))
      if [[ "${squeue_fails}" -ge 30 ]]; then
        echo "squeue unreachable while waiting for install job" >&2; exit 1
      fi
    fi
    sleep 10
  done
  echo "install job ${install_id} finished"
fi

# The one-line experiment command (job_submitter.sh:300; launch/lib.sh).
tpudist_experiment_cmd "${exp_configs_path}"

# W&B credentials plumbing (job_submitter.sh:154-155,306; launch/lib.sh).
tpudist_wandb_key

sbatch_cmd=(
  --job-name="${project_name}-${exp_name}"
  --time="${walltime}" --mem="${mem}" --nodes="${nodes}"
  --output="${exp_dir}/hpc_outputs/%x-%j-%N.out"
)
[[ -n "${partition}" ]] && sbatch_cmd+=(--partition="${partition}")
[[ -n "${account}"   ]] && sbatch_cmd+=(--account="${account}")
[[ "${gpus}" -gt 0   ]] && sbatch_cmd+=(--gres="gpu:${gpus}")
[[ "${#cluster_sbatch_extra[@]}" -gt 0 ]] && sbatch_cmd+=("${cluster_sbatch_extra[@]}")

# cmd and the tarball list may contain commas, which sbatch's --export parser
# splits on — ship them via the exported environment (ALL) and keep only
# comma-free scalars in the explicit payload.
export cmd
export staged_tarballs="${staged}"
payload="ALL,source_dir=${source_dir},scratch_dir=${scratch_dir}"
payload+=",exp_name=${exp_name},project_name=${project_name}"
payload+=",WANDB_API_KEY=${wandb_key}"
[[ -n "${cluster_tmpdir}" ]] && payload+=",node_tmpdir=${cluster_tmpdir}"

case "${job_type}" in
  sweep)
    # Two sweep modes (reference job_submitter.sh:259-271):
    #   server — -I entity/project/id (prompted for when interactive): each
    #     array task runs `wandb agent --count 1` against the W&B server;
    #     array size = -R runs (the reference's "how many runs" prompt).
    #   local  — no id: the array is sized by the spec's grid and each task
    #     runs its own configuration index, no server round-trip.
    # Prompts only on a real terminal — piped stdin (echo y | …) must keep
    # feeding the final confirm, not be eaten as a sweep id.
    if [[ -z "${wandb_sweep_id}" && "${confirm}" -eq 1 && -t 0 ]]; then
      read -r -p "W&B server sweep id (empty = local grid sweep): " wandb_sweep_id
    fi
    if [[ -n "${wandb_sweep_id}" ]]; then
      if [[ -z "${sweep_runs}" && "${confirm}" -eq 1 && -t 0 ]]; then
        read -r -p "number of sweep runs: " sweep_runs
      fi
      [[ "${sweep_runs}" =~ ^[1-9][0-9]*$ ]] || {
        echo "job_submitter: a server sweep (-I) needs -R <runs>, a positive integer (got '${sweep_runs}')" >&2
        exit 2
      }
      n_sweeps="${sweep_runs}"
      echo "server sweep ${wandb_sweep_id}: ${n_sweeps} runs"
      payload+=",WANDB_SWEEP_ID=${wandb_sweep_id}"
    else
      n_sweeps="$(python -m tpudist.launch.sweep count "${sweep_spec}")"
      echo "sweep grid size: ${n_sweeps}"
      # --export=ALL forwards the submitter's whole environment: blank any
      # ambient WANDB_SWEEP_ID so local grid tasks can't be hijacked into
      # server agents.
      payload+=",WANDB_SWEEP_ID="
    fi
    sbatch_cmd+=(--array="0-$((n_sweeps - 1))%10" --cpus-per-task="${cpus}" --ntasks-per-node=1)
    [[ "${sweep_spec}" = /* ]] || sweep_spec="${source_dir}/${sweep_spec}"
    payload+=",sweep_spec=${sweep_spec}"
    hpc_file="launch/standard_job.sh"
    ;;
  distributed)
    chips=$(( gpus > 0 ? gpus : 1 ))
    if [[ "${workflow}" == "trainer" ]]; then
      # trainer workflow: one task per chip, ranks derived from SLURM env
      # (reference lightning shape, job_submitter.sh:288).
      sbatch_cmd+=(--ntasks-per-node="${chips}" --cpus-per-task="${cpus}")
    else
      # tpurun workflow: ONE agent task per node that forks the workers
      # itself (job_submitter.sh:290-291: ntasks-per-node=1, cpus *= chips).
      sbatch_cmd+=(--ntasks-per-node=1 --cpus-per-task="$((cpus * chips))")
    fi
    payload+=",chips_per_node=${chips},workflow=${workflow}"
    hpc_file="launch/distributed_dispatcher.sh"
    ;;
  standard)
    sbatch_cmd+=(--ntasks-per-node=1 --cpus-per-task="${cpus}")
    hpc_file="launch/standard_job.sh"
    ;;
esac

# Container jobs swap in the singularity job scripts (reference
# job_submitter.sh:266,286 virtualenv/singularity branch).
if [[ -n "${sif_path}" ]]; then
  payload+=",sif_path=${sif_path}"
  case "${job_type}" in
    distributed)
      # One containerized task per rank; ranks derive from forwarded SLURM
      # env.  Only the tpurun shape (1 fat agent task with cpus×chips) needs
      # undoing — rebuild those two elements exactly rather than pattern-
      # substituting (a substring pattern would corrupt e.g. `=16` → `=166`).
      if [[ "${workflow}" == "tpurun" ]]; then
        rebuilt=()
        for el in "${sbatch_cmd[@]}"; do
          case "${el}" in
            --ntasks-per-node=1) rebuilt+=("--ntasks-per-node=${chips}") ;;
            --cpus-per-task=*)   rebuilt+=("--cpus-per-task=${cpus}") ;;
            *)                   rebuilt+=("${el}") ;;
          esac
        done
        sbatch_cmd=("${rebuilt[@]}")
      fi
      hpc_file="launch/container/distributed_dispatcher.sh"
      ;;
    *) hpc_file="launch/container/standard_job.sh" ;;
  esac
fi
sbatch_cmd+=(--export="${payload}")

echo "sbatch ${sbatch_cmd[*]} ${hpc_file}"
if [[ "${confirm}" -eq 1 ]]; then
  read -r -p "submit? [y/N] " yn   # confirm prompt (job_submitter.sh:330-343)
  [[ "${yn}" == "y" || "${yn}" == "Y" ]] || { echo "aborted"; exit 0; }
fi
sbatch "${sbatch_cmd[@]}" "${hpc_file}"
