#!/bin/bash
# Interactive launch matrix — tpudist equivalent of the reference's
# interactive_job_cmds/salloc_torchrun.sh (B9, SURVEY.md §2.2): inside an
# `salloc` allocation, run the SAME training through four launch/backend
# combinations, each writing its own output file, for cross-path consistency
# checking by eyeball (the reference's de-facto integration test, §4).
#
#   salloc --nodes=2 --ntasks-per-node=4 ...
#   bash launch/interactive/salloc_tpurun.sh
set -euo pipefail
export OMP_NUM_THREADS=1    # salloc_torchrun.sh:3 discipline

[[ -f "${HOME}/wandb_credentials.txt" ]] && \
  export WANDB_API_KEY="$(head -n1 "${HOME}/wandb_credentials.txt")"

export WORLD_SIZE="${SLURM_NTASKS:?run inside an salloc allocation}"
export TASKS_PER_NODE="${SLURM_NTASKS_PER_NODE:-1}"

nodes=($(scontrol show hostname "${SLURM_JOB_NODELIST}"))
num_nodes="${#nodes[@]}"
export MASTER_ADDR="$(hostname)"
export MASTER_PORT="${MASTER_PORT:-2345}"

echo "nodes: ${nodes[*]}"
echo "master: ${MASTER_ADDR}:${MASTER_PORT}, world ${WORLD_SIZE}, ${TASKS_PER_NODE}/node"

iters="${ITERS:-200}"
common_flags=(--dry_run --total_iterations "${iters}" --seed 0)

# ── 1. Raw per-node srun + env bootstrap (--use_node_rank) ──────────────────
# The reference's "individual" path (salloc_torchrun.sh:40-49): no managed
# launcher; each process computes rank = NODE_RANK*TASKS_PER_NODE+LOCAL_RANK.
node_rank=0
for node in "${nodes[@]}"; do
  NODE_RANK="${node_rank}" srun -w "${node}" -N1 -n "${TASKS_PER_NODE}" \
    python examples/demo.py --use_node_rank "${common_flags[@]}" \
    > "demo_individual_output.out.${node_rank}" 2>&1 &
  node_rank=$((node_rank + 1))
done
wait
echo "1/4 raw env bootstrap done -> demo_individual_output.out.*"

# ── 2. tpurun agent rendezvous (the torchrun-equivalent path) ───────────────
# salloc_torchrun.sh:60-66 analog: one agent per node, c10d-style run id.
node_rank=0
for node in "${nodes[@]}"; do
  srun -w "${node}" -N1 -n1 \
    python -m tpudist.launch \
      --nprocs "${TASKS_PER_NODE}" --nnodes "${num_nodes}" \
      --node-rank "${node_rank}" \
      --coordinator "${MASTER_ADDR}:${MASTER_PORT}" \
      --run-id "${SLURM_JOB_ID}" --max-restarts 3 \
      -- python examples/demo.py "${common_flags[@]}" \
    > "demo_tpurun_output.out.${node_rank}" 2>&1 &
  node_rank=$((node_rank + 1))
done
wait
echo "2/4 tpurun rendezvous done -> demo_tpurun_output.out.*"

# ── 3. MPI bootstrap (salloc_torchrun.sh:86-90 analog) ──────────────────────
# One fabric (MPI) bootstraps the other (JAX coordination service): rank 0
# broadcasts its hostname + a free port via mpi4py, then every rank calls
# jax.distributed.initialize.  Requires mpi4py + a working mpiexec.
if command -v mpiexec >/dev/null 2>&1; then
  mpiexec -np "${WORLD_SIZE}" \
    python examples/demo_mpi_bootstrap.py "${common_flags[@]}" \
    > demo_mpi_output.out 2>&1 || echo "(mpi path failed — see demo_mpi_output.out)"
  echo "3/4 mpi bootstrap done -> demo_mpi_output.out"
else
  echo "3/4 skipped: no mpiexec on PATH"
fi

# ── 4. host metric backend (salloc_torchrun.sh:94-95 Gloo analog) ───────────
# Same training, but per-iteration loss reduction over the host/DCN fabric
# instead of on-device ICI collectives.
node_rank=0
for node in "${nodes[@]}"; do
  NODE_RANK="${node_rank}" srun -w "${node}" -N1 -n "${TASKS_PER_NODE}" \
    python examples/demo.py --use_node_rank --backend host "${common_flags[@]}" \
    > "demo_host_output.out.${node_rank}" 2>&1 &
  node_rank=$((node_rank + 1))
done
wait
echo "4/4 host-backend done -> demo_host_output.out.*"

echo "all four launch paths complete; compare final losses across outputs"
