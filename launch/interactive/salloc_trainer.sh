#!/bin/bash
# Interactive trainer-facade session — tpudist equivalent of the reference's
# interactive_job_cmds/salloc_lightning.sh (B10, SURVEY.md §2.2): run the
# Trainer entry point under srun with both metric backends (the reference ran
# Lightning with PL_TORCH_DISTRIBUTED_BACKEND=nccl then =gloo,
# salloc_lightning.sh:51-67).
#
#   salloc --nodes=N --ntasks-per-node=G ...
#   bash launch/interactive/salloc_trainer.sh
set -euo pipefail
export OMP_NUM_THREADS=1

[[ -f "${HOME}/wandb_credentials.txt" ]] && \
  export WANDB_API_KEY="$(head -n1 "${HOME}/wandb_credentials.txt")"

export WORLD_SIZE="${SLURM_NTASKS:?run inside an salloc allocation}"
export TASKS_PER_NODE="${SLURM_NTASKS_PER_NODE:-1}"
export MASTER_ADDR="$(hostname)"
export MASTER_PORT="${MASTER_PORT:-2345}"

iters="${ITERS:-200}"

# Trainer requires one task per chip (the Lightning shape, §3.4): rank
# derivation rides the SLURM env contract inside the framework.
echo "trainer over ici metric backend"
srun python examples/demo_trainer.py \
  --dry_run --total_iterations "${iters}" --backend ici \
  > trainer_ici_output.out 2>&1
echo "-> trainer_ici_output.out"

echo "trainer over host metric backend"
srun python examples/demo_trainer.py \
  --dry_run --total_iterations "${iters}" --backend host \
  > trainer_host_output.out 2>&1
echo "-> trainer_host_output.out"
