#!/bin/bash
# Interactive model-split session — tpudist equivalent of the reference's
# interactive_job_cmds/salloc_one_model_multi_gpu_torchrun.sh (B11, SURVEY.md
# §2.2): one process per node, TWO chips per process, the model's layer
# stages sharded across the two chips (DP across nodes × model-split within).
# The reference asserted exactly 2 GPUs per task
# (demo_one_model_multi_gpu.py:89); here the (data, model=2) mesh encodes it.
#
#   salloc --nodes=2 --ntasks-per-node=1 --gres=tpu:2 ...   (or 2 chips/VM)
#   bash launch/interactive/salloc_model_split.sh
set -euo pipefail
export OMP_NUM_THREADS=1

[[ -f "${HOME}/wandb_credentials.txt" ]] && \
  export WANDB_API_KEY="$(head -n1 "${HOME}/wandb_credentials.txt")"

export WORLD_SIZE="${SLURM_NNODES:?run inside an salloc allocation}"
export TASKS_PER_NODE=1    # one process per node, both chips visible to it
export MASTER_ADDR="$(hostname)"
export MASTER_PORT="${MASTER_PORT:-2345}"

nodes=($(scontrol show hostname "${SLURM_JOB_NODELIST}"))
iters="${ITERS:-200}"

node_rank=0
for node in "${nodes[@]}"; do
  NODE_RANK="${node_rank}" srun -w "${node}" -N1 -n1 \
    python examples/demo_model_split.py --use_node_rank \
    --dry_run --total_iterations "${iters}" --seed 0 \
    > "model_split_output.out.${node_rank}" 2>&1 &
  node_rank=$((node_rank + 1))
done
wait
echo "model-split run done -> model_split_output.out.*"
