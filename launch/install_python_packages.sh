#!/bin/bash
# Virtualenv bootstrap job — tpudist equivalent of the reference's
# hpc_files/install_python_packages.sh (B8, SURVEY.md §2.2): builds the
# project environment on a compute node so training jobs only activate it.
# Prefers an existing project definition (pyproject.toml editable install);
# falls back to requirements.txt.  TPU wheels: jax[tpu] from the libtpu
# release index when chips are present, plain jax otherwise.
set -euo pipefail

cd "${source_dir:?}"
venv_dir="${venv_dir:-${source_dir}/virtual_env}"

if [[ ! -d "${venv_dir}" ]]; then
  python3 -m venv "${venv_dir}"
fi
# shellcheck disable=SC1091
source "${venv_dir}/bin/activate"
pip install --upgrade pip

if [[ -f pyproject.toml ]]; then
  pip install -e .
elif [[ -f requirements.txt ]]; then
  pip install -r requirements.txt
fi

# TPU runtime wheels (no-op on CPU-only nodes; the reference pinned its CUDA
# wheel index the same way, Pipfile:6-9).
if [[ -e /dev/accel0 || -n "${TPU_NAME:-}" ]]; then
  pip install 'jax[tpu]' -f https://storage.googleapis.com/jax-releases/libtpu_releases.html
fi

python -c "import jax; print('jax', jax.__version__, 'devices', jax.device_count())"
