#!/bin/bash
# Single-node batch job — tpudist equivalent of
# virtual_env_hpc_files/standard_job.sh (reference B5): node-local scratch,
# data staging, run the experiment command (or one sweep index for array
# jobs), cleanup.
set -euo pipefail

cd "${source_dir:?}"
# Cleanup must survive a failing cmd (standard_job.sh:29-31 discipline, but
# via EXIT trap so set -e cannot skip it); node_tmpdir (cluster profile)
# overrides the scheduler tmpdir — see launch/lib.sh.
source launch/lib.sh
tpudist_tmpdir "${SLURM_JOB_ID:-$$}"

if [[ -n "${staged_tarballs:-}" ]]; then
  IFS=',' read -ra tbs <<< "${staged_tarballs}"
  for tb in "${tbs[@]}"; do time tar -xf "${tb}" -C "${TPUDIST_TMPDIR}"; done
fi

# Sweep jobs: cmd comes from launch/sweep_cmd.txt with a ${sweep_spec}
# placeholder; the agent picks its configuration index from
# SLURM_ARRAY_TASK_ID (one array task = one configuration, §3.5).
if [[ -n "${sweep_spec:-}" ]]; then
  cmd="${cmd//'${sweep_spec}'/${sweep_spec}}"
fi
${cmd:?}
