#!/bin/bash
# Single-node batch job — tpudist equivalent of
# virtual_env_hpc_files/standard_job.sh (reference B5): node-local scratch,
# data staging, run the experiment command (or one sweep index for array
# jobs), cleanup.
set -euo pipefail

cd "${source_dir:?}"
export TPUDIST_TMPDIR="${SLURM_TMPDIR:-/tmp/tpudist_${SLURM_JOB_ID:-$$}}"
mkdir -p "${TPUDIST_TMPDIR}"
# Cleanup must survive a failing cmd (standard_job.sh:29-31 discipline, but
# via EXIT trap so set -e cannot skip it). Never remove a scheduler-owned
# SLURM_TMPDIR — only the /tmp dir we created ourselves.
[[ -z "${SLURM_TMPDIR:-}" ]] && trap 'rm -rf "${TPUDIST_TMPDIR}"' EXIT

if [[ -n "${staged_tarballs:-}" ]]; then
  IFS=',' read -ra tbs <<< "${staged_tarballs}"
  for tb in "${tbs[@]}"; do time tar -xf "${tb}" -C "${TPUDIST_TMPDIR}"; done
fi

if [[ -n "${sweep_spec:-}" ]]; then
  # One array task = one sweep configuration (§3.5 sweep path).
  python -m tpudist.launch.sweep agent "${sweep_spec}" \
    --index "${SLURM_ARRAY_TASK_ID:-0}"
else
  ${cmd:?}
fi
