#!/bin/bash
# TPU-pod launcher — the non-SLURM path. Where the reference drives multi-node
# jobs with per-node srun (distributed_dispatcher.sh:25-34), Cloud TPU pods use
# one gcloud command fanned out to every worker VM (--worker=all); each worker
# runs a tpurun agent that starts one process per host (the standard JAX
# multi-controller shape: 1 process/host, all local chips visible to it).
#
# Usage:
#   bash launch/tpu_pod_run.sh TPU_NAME ZONE "python examples/demo.py --dry_run"
set -euo pipefail

tpu_name="${1:?tpu name}"; zone="${2:?zone}"; shift 2
cmd="$*"
[[ "${cmd}" == python* ]] || { echo "command must start with python" >&2; exit 2; }

# On TPU VMs jax.distributed.initialize() discovers coordinator/world from the
# TPU metadata server, so no TPUDIST_*/MASTER_* plumbing is needed — the
# bootstrap's priority chain falls through to the single-arg initialize path.
gcloud compute tpus tpu-vm ssh "${tpu_name}" --zone "${zone}" --worker=all \
  --command "cd ~/$(basename "$(pwd)") && ${cmd}"
