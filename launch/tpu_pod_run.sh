#!/bin/bash
# TPU-pod one-liner — the interactive quick path: one command fanned out to
# every worker VM of an EXISTING, already-staged TPU (the salloc-analog of
# the interactive/ scripts).  For the full submission contract —
# provisioning/queued resources, code+data staging, W&B key plumbing,
# per-worker output capture, restart-with-backoff, cleanup — use
# launch/gcloud_submitter.sh (the job_submitter.sh analog for clouds).
#
# Usage:
#   bash launch/tpu_pod_run.sh TPU_NAME ZONE "python examples/demo.py --dry_run"
set -euo pipefail

tpu_name="${1:?tpu name}"; zone="${2:?zone}"; shift 2
cmd="$*"
[[ "${cmd}" == python* ]] || { echo "command must start with python" >&2; exit 2; }

# On TPU VMs jax.distributed.initialize() discovers coordinator/world from the
# TPU metadata server, so no TPUDIST_*/MASTER_* plumbing is needed — the
# bootstrap's priority chain falls through to the single-arg initialize path.
gcloud compute tpus tpu-vm ssh "${tpu_name}" --zone "${zone}" --worker=all \
  --command "cd ~/$(basename "$(pwd)") && ${cmd}"
