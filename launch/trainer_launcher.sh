#!/bin/bash
# Per-task trainer launcher — tpudist equivalent of the reference's
# virtual_env_hpc_files/distributed_scripts/lightning_launcher.sh (B3,
# SURVEY.md §2.2).  Runs once per srun task (one task per chip-group); the
# framework process derives its rank from the SLURM env contract
# (tpudist/runtime/bootstrap.py priority 4 — SLURM_PROCID/SLURM_LOCALID +
# MASTER_ADDR/MASTER_PORT exported by the dispatcher), the way Lightning
# infers rank/world from SLURM in the reference (§3.4).
#
# Args: $1 = nnodes, $2 = chips per node, $3 = comma-separated tarballs ("" ok)
# Env:  cmd (the experiment command), MASTER_ADDR/MASTER_PORT, TPUDIST_TMPDIR
set -euo pipefail

nnodes="${1:?nnodes}"; chips="${2:?chips per node}"; tarballs="${3:-}"

# The launcher owns topology: strip any user-passed topology flags and assert
# the authoritative ones (lightning_launcher.sh:12-14 sed-strip + re-append
# discipline).  --torchrun / --use_node_rank would redirect rank derivation
# away from the SLURM contract this mode relies on.
run_cmd="$(sed -E 's/--(torchrun|use_node_rank)([[:space:]]|$)/ /g' <<< "${cmd:?}")"
# cmd must be a python program (torchrun_launcher.sh:23-25 parity; basename so
# absolute interpreter paths pass too).
first_tok="$(basename "${run_cmd%% *}")"
[[ "${first_tok}" == python* ]] || { echo "cmd must start with python" >&2; exit 2; }

export WORLD_SIZE="$((nnodes * chips))"
export TASKS_PER_NODE="${chips}"

# Stage data into node-local scratch exactly once per node: every task checks,
# only SLURM_LOCALID 0 extracts, others wait on the sentinel
# (torchrun_launcher.sh:35-40 staging contract, made multi-task-safe).
if [[ -n "${tarballs}" ]]; then
  tmp="${TPUDIST_TMPDIR:?}"
  mkdir -p "${tmp}"
  sentinel="${tmp}/.staged"
  if [[ "${SLURM_LOCALID:-0}" == "0" ]]; then
    IFS=',' read -ra tbs <<< "${tarballs}"
    for tb in "${tbs[@]}"; do time tar -xf "${tb}" -C "${tmp}"; done
    touch "${sentinel}"
  else
    # Bounded wait: if the staging task died before touching the sentinel,
    # fail fast instead of idling the allocation until walltime.
    waited=0
    while [[ ! -f "${sentinel}" ]]; do
      sleep 1; waited=$((waited + 1))
      if [[ "${waited}" -ge "${TPUDIST_STAGE_TIMEOUT:-600}" ]]; then
        echo "staging sentinel never appeared (rank-0 staging failed?)" >&2
        exit 1
      fi
    done
  fi
fi

exec ${run_cmd}
