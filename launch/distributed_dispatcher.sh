#!/bin/bash
# In-allocation dispatcher — tpudist equivalent of
# virtual_env_hpc_files/distributed_dispatcher.sh (reference B4, SURVEY.md
# §2.2): resolves the node list and coordinator, then launches ONE tpurun
# agent per node via srun (distributed_dispatcher.sh:19-34), each agent
# forking chips_per_node workers with the TPUDIST_* env contract.
set -euo pipefail

cd "${source_dir:?}"

nodes=($(scontrol show hostname "${SLURM_JOB_NODELIST}"))
num_nodes="${#nodes[@]}"
MASTER_ADDR="$(hostname)"
MASTER_PORT="${MASTER_PORT:-2345}"
coordinator="${MASTER_ADDR}:${MASTER_PORT}"
chips="${chips_per_node:-1}"
[[ "${chips}" -ge 1 ]] || chips=1

# Per-job node-local scratch (standard_job.sh:13-16 PLAI pattern); cleaned
# on exit even when a worker group fails (only when we created it ourselves).
# "allnodes": every node's agent stages into its own local disk, so the
# cleanup fans out over the allocation (see launch/lib.sh).
source launch/lib.sh
tpudist_tmpdir "${SLURM_JOB_ID}" allnodes

echo "dispatcher: ${num_nodes} nodes, ${chips} chips/node, coordinator ${coordinator}," \
     "workflow ${workflow:-tpurun}"

# trainer workflow (reference lightning path, distributed_dispatcher.sh:38 +
# SURVEY.md §3.4): ONE srun spawning nodes×chips tasks; every task runs the
# trainer launcher and the framework derives ranks from the SLURM env
# contract.  The sbatch was shaped with --ntasks-per-node=chips by
# job_submitter (reference job_submitter.sh:288).
if [[ "${workflow:-tpurun}" == "trainer" ]]; then
  export MASTER_ADDR MASTER_PORT
  srun bash launch/trainer_launcher.sh \
    "${num_nodes}" "${chips}" "${staged_tarballs:-}"
  exit $?
fi

node_rank=0
for node in "${nodes[@]}"; do
  srun -w "${node}" -N1 -n1 \
    python -m tpudist.launch \
      --nprocs "${chips}" --nnodes "${num_nodes}" --node-rank "${node_rank}" \
      --coordinator "${coordinator}" --run-id "${SLURM_JOB_ID}" \
      ${staged_tarballs:+--stage-data "${staged_tarballs}"} \
      -- ${cmd:?} &
  node_rank=$((node_rank + 1))
done
wait   # distributed_dispatcher.sh:34 — backgrounded per-node sruns
