# match: cedar*
# ComputeCanada-style cluster: allocation accounting is mandatory (set
# your default account here), the scheduler provides a per-job
# SLURM_TMPDIR on node-local disk (so no cluster_tmpdir override), and
# scratch lives on the shared filesystem under ~/scratch (the reference's
# cedar branches, job_submitter.sh:180-182,321-327).
cluster_account="${CLUSTER_ACCOUNT:-def-${USER:-$(id -un)}}"
cluster_mem="32G"
