# match: borg*
# UBC PLAI-style cluster: jobs get no scheduler-managed tmpdir, but every
# node has a local SSD at /scratch-ssd — stage data and scratch there
# (the reference's per-cluster tmpdir branch + plai_cleanups, SURVEY.md
# §2.2 B13; node_tmpdir subdirs are removed on job exit, and
# launch/cleanups/ sweeps leftovers).
cluster_partition="plai"
cluster_tmpdir="/scratch-ssd/${USER:-$(id -un)}"
