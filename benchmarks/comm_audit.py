#!/usr/bin/env python3
"""Compile-time collective audit over every multi-chip sharding regime.

For each regime the driver's ``dryrun_multichip`` exercises (plus pure DP),
this lowers the full jitted train step at n=8 on the virtual CPU mesh,
parses the optimized HLO (:mod:`tpudist.utils.hlo_audit`), and checks the
emitted collectives against analytic predictions:

- **dp**           one gradient all-reduce of exactly param+loss bytes
                   (wire cost 2(n−1)/n × payload — the DP scaling law)
- **ring**         2(ring−1) K/V collective-permutes forward (+ the
                   reversed ring in backward), each of one KV-shard
- **windowed ring** the ring stops early: strictly fewer permutes than
                   dense at the same geometry
- **moe**          2 all_to_alls forward (dispatch/return) + 2 backward,
                   each of the [experts, capacity, d] buffer
- **fsdp**         per-use all-gather of sharded params + reduce-scatter
                   of their grads (ZeRO-3's manual machinery, emitted by
                   the SPMD partitioner from the layout alone)
- **zero1**        plain-DP gradient all-reduce + all-gather of exactly
                   the sharded updated params (weight-update sharding,
                   arXiv:2004.13336)
- **gpipe/1f1b/interleaved**  stage-boundary collective-permutes inside
                   the scan loop (per-tick activation hop), not unrolled

Writes ``COMM_AUDIT_r{NN}.json`` (NN = the round being built,
``benchmarks/_round.py``) and exits nonzero if any check fails.
This is the no-hardware half of the multi-chip scaling story: the
collective *structure* is exactly what a pod would execute; only the link
bandwidths need hardware.  (VERDICT r3 #3; SURVEY.md §2.4.)
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import numpy as np  # noqa: E402


def _force_cpu_mesh(n: int = 8) -> None:
    import jax

    try:
        from jax._src import xla_bridge as _xb

        backend_up = _xb.backends_are_initialized()
    except Exception:
        backend_up = True
    if not backend_up:
        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", max(n, 8))
        except AttributeError:
            # older jax spells the knob via XLA_FLAGS only
            import os

            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags +
                    f" --xla_force_host_platform_device_count={max(n, 8)}"
                ).strip()
    if len(jax.devices()) < n:
        raise RuntimeError(
            f"need {n} devices, have {len(jax.devices())} "
            f"({jax.devices()[0].platform})"
        )


def collect_ops(step, ex_args, info):
    """One collection point for regime HLO (used by main() AND the test
    suite so the shipped artifact and the asserted audit can never
    measure different programs): optimized HLO normally, pre-opt HLO for
    regimes whose checked property a backend pass rewrites."""
    from tpudist.utils.hlo_audit import (
        collect_collectives,
        lower_preopt_hlo,
        parse_collectives,
    )

    if info.get("pre_opt"):
        return parse_collectives(lower_preopt_hlo(step, *ex_args))
    return collect_collectives(step, *ex_args)


# ---------------------------------------------------------------------------
# Regime builders: each returns (jitted_step, example_args, info) where
# info carries the analytic quantities the checks consume.
# ---------------------------------------------------------------------------


def _toy_models():
    import jax
    import optax

    from tpudist.models import create_toy_model
    from tpudist.train import init_model_states

    kx, ky = jax.random.split(jax.random.PRNGKey(0))
    mx, px = create_toy_model(kx)
    my, py = create_toy_model(ky)
    models = {"model_X": (mx.apply, px), "model_Y": (my.apply, py)}
    tx = optax.adam(1e-3)
    states = init_model_states(models, tx)
    return models, tx, states


def regime_dp(devices):
    """Pure DP on (8,): the DDP-parity regime (reference demo.py)."""
    import jax
    from jax.sharding import Mesh

    from tpudist.runtime.mesh import AXIS_DATA
    from tpudist.train import make_multi_model_train_step
    from tpudist.train.step import batch_sharding
    from tpudist.utils.hlo_audit import tree_bytes

    mesh = Mesh(np.asarray(devices), axis_names=(AXIS_DATA,))
    models, tx, states = _toy_models()
    step = make_multi_model_train_step(
        {k: f for k, (f, _) in models.items()}, tx, mesh
    )
    bs = batch_sharding(mesh)
    x = jax.device_put(np.zeros((32, 2), np.float32), bs)
    y = jax.device_put(np.zeros((32, 1), np.float32), bs)
    info = {
        "mesh": {"data": 8},
        "param_bytes": tree_bytes({k: s.params for k, s in states.items()}),
        "n_loss_scalars": 2,
    }
    return step, (states, x, y), info


def regime_dp_bf16_reduce(devices):
    """(8,) pure DP with grad_reduce_dtype=bf16: the gradient all-reduce
    must ride the wire at HALF the f32 payload (tpudist/train/lm.py
    compressed path; the DCN-scaling lever of scaling_model.py)."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh

    from tpudist.models import create_transformer
    from tpudist.runtime.mesh import AXIS_DATA
    from tpudist.train import init_lm_state, make_lm_train_step, token_sharding
    from tpudist.utils.hlo_audit import tree_bytes

    mesh = Mesh(np.asarray(devices), axis_names=(AXIS_DATA,))
    module, params = create_transformer(
        jax.random.PRNGKey(0), seq_len=16, vocab=32, d_model=32,
        n_layers=1, n_heads=2, d_ff=64, max_len=16)
    tx = optax.adam(1e-3)
    state = init_lm_state(params, tx)
    step = make_lm_train_step(module.apply, tx, mesh,
                              grad_reduce_dtype=jnp.bfloat16)
    toks = np.random.default_rng(0).integers(0, 32, size=(8, 16)) \
        .astype(np.int32)
    args = (state, jax.device_put(toks, token_sharding(mesh)))
    return step, args, {
        "mesh": {"data": 8},
        "param_bytes": tree_bytes(state.params),
        # Audit the PRE-optimization HLO: the CPU backend's all-reduce
        # promotion pass re-widens bf16 reduces to f32 (no native bf16
        # reduction on CPU); TPU executes the bf16 width as requested.
        "pre_opt": True,
        "note": "cpu backend promotes bf16 all-reduce to f32; "
                "pre-opt HLO carries the requested wire dtype",
    }


def regime_dp_model_split(devices):
    """(4,2) dp × model — the model-split demo's sharding-spec split."""
    import jax
    from jax.sharding import Mesh

    from tpudist.models.split_mlp import split_state_sharding
    from tpudist.runtime.mesh import AXIS_DATA, AXIS_MODEL
    from tpudist.train import make_multi_model_train_step
    from tpudist.train.step import batch_sharding
    from tpudist.utils.hlo_audit import tree_bytes

    mesh = Mesh(np.asarray(devices).reshape(4, 2),
                axis_names=(AXIS_DATA, AXIS_MODEL))
    models, tx, states = _toy_models()
    sharding = split_state_sharding(mesh, states)
    states = jax.device_put(states, sharding)
    step = make_multi_model_train_step(
        {k: f for k, (f, _) in models.items()}, tx, mesh,
        state_sharding=sharding,
    )
    bs = batch_sharding(mesh)
    x = jax.device_put(np.zeros((32, 2), np.float32), bs)
    y = jax.device_put(np.zeros((32, 1), np.float32), bs)
    info = {
        "mesh": {"data": 4, "model": 2},
        "param_bytes": tree_bytes({k: s.params for k, s in states.items()}),
    }
    return step, (states, x, y), info


def _lm_regime(mesh, *, attention_fn=None, moe_fn=None, mlp_fn=None,
               n_layers=1, n_experts=0, seq_len=64, batch=8,
               state_sharding_fn=None, aux=False, seed=0):
    import jax
    import optax

    from tpudist.models import create_transformer
    from tpudist.train import init_lm_state, make_lm_train_step, token_sharding
    from tpudist.utils.hlo_audit import tree_bytes

    module, params = create_transformer(
        jax.random.PRNGKey(0), seq_len=seq_len, attention_fn=attention_fn,
        moe_fn=moe_fn, mlp_fn=mlp_fn, vocab=32, d_model=32,
        n_layers=n_layers, n_heads=2, d_ff=64, max_len=seq_len,
        n_experts=n_experts,
    )
    tx = optax.adam(1e-3)
    state = init_lm_state(params, tx)
    sharding = None
    if state_sharding_fn is not None:
        sharding = state_sharding_fn(mesh, state)
        state = jax.device_put(state, sharding)
    step = make_lm_train_step(module.apply, tx, mesh,
                              state_sharding=sharding, aux=aux)
    toks = np.random.default_rng(seed).integers(
        0, 32, size=(batch, seq_len)).astype(np.int32)
    gtoks = jax.device_put(toks, token_sharding(mesh))
    return step, (state, gtoks), {"param_bytes": tree_bytes(state.params)}


def regime_dp_sp_ring(devices, window=None):
    """(2,4) dp × sp — ring attention, dense causal (xla carry body)."""
    from jax.sharding import Mesh

    from tpudist.parallel import make_ring_attention
    from tpudist.runtime.mesh import AXIS_DATA, AXIS_SEQ

    mesh = Mesh(np.asarray(devices).reshape(2, 4),
                axis_names=(AXIS_DATA, AXIS_SEQ))
    ring = 4
    seq_len, batch = 64, 4
    attn = make_ring_attention(mesh, causal=True, batch_axis=AXIS_DATA,
                               window=window, kernel="xla")
    step, args, info = _lm_regime(mesh, attention_fn=attn, seq_len=seq_len,
                                  batch=batch)
    # One KV head-split shard: [b_local, heads, seq/ring, head_dim] f32.
    b_local = batch // 2
    kv_shard_bytes = b_local * 2 * (seq_len // ring) * 16 * 4
    # Hops the ring actually executes (the windowed ring breaks early —
    # tpudist/parallel/ring_attention.py:190).
    block = seq_len // ring
    hops = 0
    for s in range(ring):
        if window is not None and window - (s + 1) * block <= -(block - 1):
            break
        if s + 1 < ring:
            hops += 1
    info.update({
        "mesh": {"data": 2, "seq": ring},
        "kv_shard_bytes": kv_shard_bytes,
        "ring_hops_fwd": hops,
        "window": window,
    })
    return step, args, info


def regime_dp_sp_tp(devices):
    """(2,2,2) dp × sp × tp — ring attention + Megatron-style TP weights."""
    from jax.sharding import Mesh

    from tpudist.models.transformer import transformer_tp_sharding
    from tpudist.parallel import make_ring_attention
    from tpudist.runtime.mesh import AXIS_DATA, AXIS_MODEL, AXIS_SEQ

    mesh = Mesh(np.asarray(devices).reshape(2, 2, 2),
                axis_names=(AXIS_DATA, AXIS_SEQ, AXIS_MODEL))
    attn = make_ring_attention(mesh, causal=True, batch_axis=AXIS_DATA,
                               kernel="xla")

    def shard_fn(mesh, state):
        return transformer_tp_sharding(mesh, state)

    step, args, info = _lm_regime(mesh, attention_fn=attn, seq_len=32,
                                  batch=4, state_sharding_fn=shard_fn)
    info["mesh"] = {"data": 2, "seq": 2, "model": 2}
    return step, args, info


def regime_dp_ep_moe(devices):
    """(4,2) dp × ep — MoE with all_to_all token exchange."""
    from jax.sharding import Mesh

    from tpudist.models.transformer import moe_expert_fn
    from tpudist.parallel import make_moe
    from tpudist.runtime.mesh import AXIS_DATA, AXIS_MODEL

    mesh = Mesh(np.asarray(devices).reshape(4, 2),
                axis_names=(AXIS_DATA, AXIS_MODEL))
    ep = 2
    seq_len, batch, d_model = 16, 8, 32
    capacity_factor = 2.0
    moe_fn = make_moe(mesh, moe_expert_fn, batch_axis=AXIS_DATA,
                      capacity_factor=capacity_factor)
    step, args, info = _lm_regime(mesh, moe_fn=moe_fn, seq_len=seq_len,
                                  batch=batch, n_experts=ep, aux=True)
    # moe_shard tokens: per-device batch rows × seq flattened =
    # (batch/dp)·seq; capacity = cf·k·tokens/experts; buffer [ep, cap, d].
    tokens_local = (batch // 4) * seq_len
    capacity = int(capacity_factor * 1 * tokens_local / ep + 0.5)
    info.update({
        "mesh": {"data": 4, "model": ep},
        "a2a_buffer_bytes": ep * capacity * d_model * 4,
        "capacity": capacity,
    })
    return step, args, info


def regime_fsdp(devices):
    """(8,) ZeRO-3: fully-sharded params/opt-state as a pure layout."""
    from jax.sharding import Mesh

    from tpudist.parallel import fsdp_sharding
    from tpudist.runtime.mesh import AXIS_DATA
    from tpudist.utils.hlo_audit import tree_bytes

    mesh = Mesh(np.asarray(devices), axis_names=(AXIS_DATA,))
    min_size = 64

    holder = {}

    def shard_fn(mesh, state):
        sh = fsdp_sharding(mesh, state, min_size=min_size)
        holder["sharding"] = sh
        holder["state"] = state
        return sh

    step, args, info = _lm_regime(mesh, seq_len=16, batch=8,
                                  state_sharding_fn=shard_fn)
    # Analytic split: bytes of param leaves that actually shard vs replicate.
    import jax as _jax
    from jax.sharding import NamedSharding

    sharded_b = repl_b = 0
    for leaf, sh in zip(
        _jax.tree.leaves(holder["state"].params),
        _jax.tree.leaves(holder["sharding"].params,
                         is_leaf=lambda x: isinstance(x, NamedSharding)),
    ):
        b = int(np.prod(leaf.shape or (1,))) * leaf.dtype.itemsize
        if all(a is None for a in tuple(sh.spec)):
            repl_b += b
        else:
            sharded_b += b
    info.update({
        "mesh": {"data": 8},
        "sharded_param_bytes": sharded_b,
        "replicated_param_bytes": repl_b,
    })
    return step, args, info


def regime_dp_zero1(devices):
    """(8,) ZeRO-1: replicated params, data-sharded optimizer state — the
    weight-update sharding of arXiv:2004.13336 as a pure layout."""
    from jax.sharding import Mesh

    from tpudist.parallel import zero1_sharding
    from tpudist.runtime.mesh import AXIS_DATA

    mesh = Mesh(np.asarray(devices), axis_names=(AXIS_DATA,))

    holder = {}

    def shard_fn(mesh, state):
        sh = zero1_sharding(mesh, state, min_size=64)
        holder["sharding"] = sh
        holder["state"] = state
        return sh

    step, args, info = _lm_regime(mesh, seq_len=16, batch=8,
                                  state_sharding_fn=shard_fn)
    import jax as _jax
    from jax.sharding import NamedSharding

    sharded_opt = 0
    for leaf, sh in zip(
        _jax.tree.leaves(holder["state"].opt_state),
        _jax.tree.leaves(holder["sharding"].opt_state,
                         is_leaf=lambda x: isinstance(x, NamedSharding)),
    ):
        if not all(a is None for a in tuple(sh.spec)):
            sharded_opt += int(np.prod(leaf.shape or (1,))) * leaf.dtype.itemsize
    info.update({"mesh": {"data": 8}, "sharded_opt_bytes": sharded_opt,
                 "param_bytes": info.get("param_bytes")})
    return step, args, info


def _pp_regime(devices, schedule):
    import jax
    import optax

    from jax.sharding import Mesh

    from tpudist.models import create_transformer
    from tpudist.parallel import (
        make_pp_lm_train_step,
        pp_state_sharding,
        stack_block_params,
    )
    from tpudist.runtime.mesh import AXIS_DATA, AXIS_STAGE
    from tpudist.train import init_lm_state, token_sharding
    from tpudist.utils.hlo_audit import tree_bytes

    interleaved = schedule == "interleaved"
    n_chunks = 2 if interleaved else 1
    dp, stages = 2, 4
    # Interleaved needs M % stages == 0 (Megatron grouping) and layers
    # divisible into stages*n_chunks virtual stages.
    micro, batch, n_layers = ((4, 8, 8) if interleaved else (2, 4, 4))
    mesh = Mesh(np.asarray(devices).reshape(dp, stages),
                axis_names=(AXIS_DATA, AXIS_STAGE))
    seq_len, d_model = 16, 32
    module, params = create_transformer(
        jax.random.PRNGKey(0), seq_len=seq_len, vocab=32, d_model=d_model,
        n_layers=n_layers, n_heads=2, d_ff=64, max_len=seq_len,
    )
    if interleaved:
        from tpudist.parallel import stack_block_params_interleaved

        pp_params = stack_block_params_interleaved(params, stages, n_chunks)
    else:
        pp_params = stack_block_params(params, n_stages=stages)
    tx = optax.adam(1e-3)
    state = init_lm_state(pp_params, tx)
    sharding = pp_state_sharding(mesh, state)
    state = jax.device_put(state, sharding)
    step = make_pp_lm_train_step(
        mesh, module, tx, n_stages=stages, num_microbatches=micro,
        schedule=schedule, n_chunks=n_chunks, state_sharding=sharding,
    )
    toks = np.random.default_rng(2).integers(
        0, 32, size=(batch, seq_len)).astype(np.int32)
    args = (state, jax.device_put(toks, token_sharding(mesh)))
    # Per-hop payload: one microbatch's activations [b/dp/micro, seq, d].
    act_bytes = (batch // dp // micro) * seq_len * d_model * 4
    return step, args, {
        "mesh": {"data": dp, "stage": stages},
        "param_bytes": tree_bytes(state.params),
        "microbatch_act_bytes": act_bytes,
        "n_stages": stages,
        "num_microbatches": micro,
    }


def _tp_mlp_regime(devices, overlap):
    """(8,) model axis: the explicit TP MLP (column→row pair), fwd+bwd.

    ``overlap=None`` audits the default psum body — ONE exposed
    all-reduce of the output.  ``overlap='ring'/'bidir'`` audits the
    collective-matmul body: the wire traffic must have moved whole into
    OVERLAP_SCOPE-tagged ppermute chunks (pipelined against the chunk
    matmuls), with no monolithic all-gather/all-reduce left.
    """
    import functools

    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from tpudist.parallel import init_mlp_params, mlp_param_sharding
    from tpudist.parallel.overlap import compat_shard_map
    from tpudist.parallel.tensor_parallel import (tp_mlp_overlap_shard,
                                                  tp_mlp_shard)
    from tpudist.runtime.mesh import AXIS_MODEL

    n = 8
    batch, d, f = 64, 32, 128
    mesh = Mesh(np.asarray(devices), axis_names=(AXIS_MODEL,))
    params = init_mlp_params(jax.random.PRNGKey(0), d, f)
    gparams = jax.device_put(params, mlp_param_sharding(mesh, params))
    param_specs = {"w1": P(None, AXIS_MODEL), "b1": P(AXIS_MODEL),
                   "w2": P(AXIS_MODEL, None), "b2": P()}
    if overlap is None:
        body = functools.partial(tp_mlp_shard, axis_name=AXIS_MODEL)
        x_spec = P(None, None)
    else:
        body = functools.partial(tp_mlp_overlap_shard, axis_name=AXIS_MODEL,
                                 mode=overlap)
        x_spec = P(AXIS_MODEL, None)

    def shard_loss(p, x):
        def local_loss(pp):
            out = body(pp, x)
            loss = jnp.sum(out * out)
            if overlap is not None:
                # batch rows are sharded here; the default body's loss is
                # already replicated (post-psum output)
                loss = lax.psum(loss, AXIS_MODEL)
            return loss

        return jax.value_and_grad(local_loss)(p)

    sharded = compat_shard_map(
        shard_loss, mesh=mesh, in_specs=(param_specs, x_spec),
        out_specs=(P(), param_specs))
    step = jax.jit(sharded)
    x = jax.device_put(
        jnp.asarray(np.random.default_rng(1).standard_normal((batch, d)),
                    jnp.float32),
        NamedSharding(mesh, x_spec))
    info = {
        "mesh": {"model": n},
        "overlap": overlap or "off",
        "out_bytes": batch * d * 4,
        # one pipelined chunk: a [batch/n, d] row block (x hops in the
        # gather ring, accumulator hops in the reduce-scatter ring,
        # cotangents retrace both — all the same chunk shape)
        "chunk_bytes": (batch // n) * d * 4,
        "ring": n,
    }
    return step, (gparams, x), info


def regime_tp_mlp(devices):
    return _tp_mlp_regime(devices, None)


def regime_tp_mlp_overlap_ring(devices):
    return _tp_mlp_regime(devices, "ring")


def regime_tp_mlp_overlap_bidir(devices):
    return _tp_mlp_regime(devices, "bidir")


def _fsdp_overlap_regime(devices, mode):
    """(8,) ZeRO-3 LM with the overlapped FFN compute: the FFN kernels
    stream into the ppermute pipeline SHARDED — the partitioner's
    monolithic pre-matmul all-gather of wi/wo must be gone, its bytes
    moved into OVERLAP_SCOPE-tagged chunk permutes."""
    from jax.sharding import Mesh

    from tpudist.parallel import fsdp_sharding
    from tpudist.runtime.mesh import AXIS_DATA
    from tpudist.train import fsdp_overlap_mlp_fn

    mesh = Mesh(np.asarray(devices), axis_names=(AXIS_DATA,))
    min_size = 64
    n = 8
    d_model, d_ff, n_layers = 32, 64, 1

    holder = {}

    def shard_fn(mesh, state):
        sh = fsdp_sharding(mesh, state, min_size=min_size)
        holder["sharding"] = sh
        holder["state"] = state
        return sh

    mlp_fn = fsdp_overlap_mlp_fn(mesh, overlap=mode)
    step, args, info = _lm_regime(mesh, seq_len=16, batch=8,
                                  state_sharding_fn=shard_fn,
                                  mlp_fn=mlp_fn)
    import jax as _jax
    from jax.sharding import NamedSharding

    sharded_b = repl_b = 0
    for leaf, sh in zip(
        _jax.tree.leaves(holder["state"].params),
        _jax.tree.leaves(holder["sharding"].params,
                         is_leaf=lambda x: isinstance(x, NamedSharding)),
    ):
        b = int(np.prod(leaf.shape or (1,))) * leaf.dtype.itemsize
        if all(a is None for a in tuple(sh.spec)):
            repl_b += b
        else:
            sharded_b += b
    ffn_kernel_bytes = d_model * d_ff * 4  # each of wi / wo, per layer
    info.update({
        "mesh": {"data": n},
        "overlap": mode,
        "sharded_param_bytes": sharded_b,
        "replicated_param_bytes": repl_b,
        "n_layers": n_layers,
        "ffn_kernel_bytes": ffn_kernel_bytes,
        "ffn_shard_bytes": ffn_kernel_bytes // n,
        "ring": n,
    })
    return step, args, info


def regime_fsdp_overlap_ring(devices):
    return _fsdp_overlap_regime(devices, "ring")


def regime_fsdp_overlap_bidir(devices):
    return _fsdp_overlap_regime(devices, "bidir")


def _serve_decode_regime(devices, overlap):
    """(1,4) serving mesh: the slot engine's fused ``decode_block``
    program with params + dense slot KV TP-sharded over kv-heads/output
    dims (tpudist/serve/spmd.py — the byte-identity layout).

    ``overlap=None`` audits the layout-only path: the column-sharded
    ``wi`` leaves the FFN activation sharded on ``d_ff``, so the
    partitioner all-gathers it whole BEFORE the replicated ``wo``
    matmul — exposed wire on the decode critical path.  ``'ring'``/
    ``'bidir'`` route both FFN matmuls through ``ag_matmul`` (the
    serve mlp_fn): the kernels stay sharded at rest and ride
    OVERLAP_SCOPE-tagged ppermute chunks pipelined under the chunk
    matmuls — no monolithic kernel-or-activation gather in the FFN, and
    the decode path's collective bytes classify overlapped."""
    import jax
    import jax.numpy as jnp

    from tpudist.models import create_transformer
    from tpudist.models.generate import make_slot_decode
    from tpudist.serve import spmd
    from tpudist.utils.hlo_audit import tree_bytes

    n = 4
    cfg = spmd.ServeMeshConfig(shape=f"1x{n}",
                               tp_overlap=overlap or "off")
    mesh = spmd.build_serve_mesh(cfg)
    d_model, d_ff, n_layers, n_heads = 32, 128, 2, 4
    mlp_fn = (spmd.serve_overlap_mlp_fn(mesh, mode=overlap)
              if overlap else None)
    module, params = create_transformer(
        jax.random.PRNGKey(0), seq_len=16, vocab=64, d_model=d_model,
        n_layers=n_layers, n_heads=n_heads, n_kv_heads=n_heads,
        d_ff=d_ff, max_len=64, mlp_fn=mlp_fn)
    psh = spmd.serve_param_sharding(mesh, params,
                                    overlap=overlap is not None)
    gparams = jax.device_put(params, psh)

    def constraint(tree):
        return jax.lax.with_sharding_constraint(
            tree, spmd.serve_cache_sharding(mesh, tree))

    S, pad, k = 4, 8, 4
    fns = make_slot_decode(module, gparams, S, pad,
                           cache_constraint=constraint)
    state = jax.device_put(
        fns.init_state(), spmd.serve_state_sharding(mesh, fns.init_state()))
    cache = jax.device_put(
        fns.init_slots(),
        spmd.serve_cache_sharding(mesh, fns.init_slots()))
    wi_shard = d_model * d_ff * 4 // n
    wo_shard = d_ff * d_model * 4 // n
    info = {
        "mesh": {"data": 1, "model": n},
        "overlap": overlap or "off",
        "ring": n,
        "n_layers": n_layers,
        "decode_k": k,
        "param_bytes": tree_bytes(params),
        "ffn_kernel_bytes": d_model * d_ff * 4,
        "wi_shard_bytes": wi_shard,
        "wo_shard_bytes": wo_shard,
        # the FFN activation the layout-only path must gather whole:
        # [S, 1, d_ff] f32
        "ff_act_bytes": S * d_ff * 4,
    }
    return fns.decode_block, (state, cache, k), info


def regime_serve_decode_tp(devices):
    return _serve_decode_regime(devices, None)


def regime_serve_decode_tp_ring(devices):
    return _serve_decode_regime(devices, "ring")


def regime_serve_decode_tp_bidir(devices):
    return _serve_decode_regime(devices, "bidir")


def regime_dp_pp_gpipe(devices):
    return _pp_regime(devices, "gpipe")


def regime_dp_pp_1f1b(devices):
    return _pp_regime(devices, "1f1b")


def regime_dp_pp_interleaved(devices):
    return _pp_regime(devices, "interleaved")


REGIMES = {
    "dp": regime_dp,
    "dp_bf16_reduce": regime_dp_bf16_reduce,
    "dp_model_split": regime_dp_model_split,
    "dp_sp_ring": regime_dp_sp_ring,
    "dp_sp_ring_window": lambda d: regime_dp_sp_ring(d, window=12),
    "dp_sp_tp": regime_dp_sp_tp,
    "dp_ep_moe": regime_dp_ep_moe,
    "fsdp": regime_fsdp,
    "dp_zero1": regime_dp_zero1,
    "dp_pp_gpipe": regime_dp_pp_gpipe,
    "dp_pp_1f1b": regime_dp_pp_1f1b,
    "dp_pp_interleaved": regime_dp_pp_interleaved,
    # collective-matmul overlap family (tpudist/parallel/overlap.py):
    # the default TP psum body vs the ppermute-pipelined twins, and the
    # FSDP LM step with the FFN gathers moved into the pipeline.  fsdp
    # MUST precede fsdp_overlap_* (their checks compare against it).
    "tp_mlp": regime_tp_mlp,
    "tp_mlp_overlap_ring": regime_tp_mlp_overlap_ring,
    "tp_mlp_overlap_bidir": regime_tp_mlp_overlap_bidir,
    "fsdp_overlap_ring": regime_fsdp_overlap_ring,
    "fsdp_overlap_bidir": regime_fsdp_overlap_bidir,
    # the TP serving decode path (tpudist/serve/spmd.py): layout-only
    # baseline (exposed activation gather) vs the ag_matmul-routed
    # variants (kernel bytes in overlap-tagged ppermute chunks)
    "serve_decode_tp": regime_serve_decode_tp,
    "serve_decode_tp_ring": regime_serve_decode_tp_ring,
    "serve_decode_tp_bidir": regime_serve_decode_tp_bidir,
}


# ---------------------------------------------------------------------------
# Checks: analytic predictions vs measured HLO profile.  Each returns a
# list of {check, expected, measured, ok}.
# ---------------------------------------------------------------------------


def _c(name, expected, measured, ok=None):
    if ok is None:
        ok = expected == measured
    return {"check": name, "expected": expected, "measured": measured,
            "ok": bool(ok)}


def check_dp(prof, info):
    ar = prof.get("all-reduce",
                  {"count": 0, "bytes_total": 0, "count_in_loop": 0})
    payload = info["param_bytes"] + 4 * info["n_loss_scalars"]
    n = info["mesh"]["data"]
    from tpudist.utils.hlo_audit import ring_allreduce_wire_bytes

    info["predicted_wire_bytes_per_device"] = ring_allreduce_wire_bytes(
        payload, n)
    return [
        _c("only collective kind is all-reduce", ["all-reduce"],
           sorted(prof)),
        _c("one combined gradient all-reduce", 1, ar["count"]),
        _c("all-reduce payload = grad + loss bytes", payload,
           ar["bytes_total"]),
        _c("no loop-resident collectives", 0, ar["count_in_loop"]),
    ]


def check_dp_bf16_reduce(prof, info):
    ar = prof.get("all-reduce",
                  {"count": 0, "bytes_total": 0, "count_in_loop": 0,
                   "instructions": []})
    # Wire payload: every f32 param-grad rides at 2 bytes (half) + the
    # f32 loss scalar's 4.  Checked on the pre-opt HLO (info["pre_opt"])
    # — exactly one f32 instruction (the loss) and the rest bf16.
    payload = info["param_bytes"] // 2 + 4
    f32_instrs = [i for i in ar["instructions"] if "f32[" in i["shape"]]
    return [
        _c("only collective kind is all-reduce", ["all-reduce"],
           sorted(prof)),
        _c("all-reduce payload = bf16 grads + f32 loss", payload,
           ar["bytes_total"]),
        _c("single f32 scalar reduce (the loss); grads all narrow", 1,
           len(f32_instrs)),
        _c("no loop-resident collectives", 0, ar["count_in_loop"]),
    ]


def check_dp_model_split(prof, info):
    ar = prof.get("all-reduce", {"count": 0, "bytes_total": 0})
    # Split weights: grads of model-sharded leaves all-reduce over the data
    # groups only (payload counts the SHARD bytes on the wire schedule, but
    # HLO operand shapes are global) — so payload stays >= param bytes and
    # < param bytes + slack for losses/boundary activations.
    lo = info["param_bytes"]
    hi = info["param_bytes"] + 4096
    checks = [
        _c("collective kinds", True,
           sorted(prof),
           ok=set(prof) <= {"all-reduce", "all-gather",
                            "collective-permute"}),
        _c("all-reduce payload within [params, params+4KB]",
           {"lo": lo, "hi": hi}, ar["bytes_total"],
           ok=lo <= ar["bytes_total"] <= hi),
    ]
    return checks


def check_ring(prof, info):
    cp = prof.get("collective-permute",
                  {"count": 0, "bytes_total": 0, "count_in_loop": 0,
                   "instructions": []})
    ar = prof.get("all-reduce", {"instructions": []})
    hops = info["ring_hops_fwd"]
    kv = info["kv_shard_bytes"]
    # Forward: K and V hop once per executed ring step → 2·hops permutes;
    # the backward retraces the reversed ring with the K/V cotangents →
    # 2·hops more.  Every one moves exactly one KV shard.  (Anything else —
    # e.g. sub-KV-size bookkeeping permutes — must stay tiny.)
    kv_sized = [i for i in cp["instructions"] if i["bytes"] == kv]
    extras = [i for i in cp["instructions"] if i["bytes"] != kv]
    grad_ar = max((i["bytes"] for i in ar["instructions"]), default=0)
    return [
        _c("4·hops KV-shard permutes (K,V × fwd,bwd)", 4 * hops,
           len(kv_sized)),
        _c("non-KV permutes are bookkeeping (<512B)", True,
           all(i["bytes"] < 512 for i in extras)),
        _c("permutes are unrolled (none loop-resident)", 0,
           cp["count_in_loop"]),
        _c("largest all-reduce = grad+loss bytes",
           info["param_bytes"] + 4, grad_ar),
        _c("no all_to_all / reduce-scatter", True,
           not ({"all-to-all", "reduce-scatter"} & set(prof))),
    ]


def check_ring_window(prof, info, dense_prof):
    cp = prof.get("collective-permute", {"count": 0})
    dense_cp = dense_prof.get("collective-permute", {"count": 0})
    checks = check_ring(prof, info)
    checks.append(
        _c("windowed ring needs fewer permutes than dense",
           {"dense": dense_cp["count"]}, cp["count"],
           ok=cp["count"] < dense_cp["count"]))
    return checks


def check_tp(prof, info):
    ar = prof.get("all-reduce", {"count": 0, "bytes_total": 0})
    return [
        _c("all-reduce present (TP activations + grads)", True,
           ar["count"] > 0),
        _c("ring permutes present (sp axis)", True,
           prof.get("collective-permute", {"count": 0})["count"] > 0),
        _c("no all_to_all", True, "all-to-all" not in prof),
    ]


def check_moe(prof, info):
    a2a = prof.get("all-to-all",
                   {"count": 0, "bytes_total": 0, "instructions": []})
    buf = info["a2a_buffer_bytes"]
    per_instr_ok = all(i["bytes"] == buf for i in a2a["instructions"])
    return [
        _c("4 all_to_alls (dispatch+return, fwd+bwd)", 4, a2a["count"]),
        _c("each all_to_all moves the capacity buffer", True, per_instr_ok),
        _c("grad all-reduce present", True, "all-reduce" in prof),
    ]


def check_zero1(prof, info):
    ar = prof.get("all-reduce",
                  {"count": 0, "bytes_total": 0, "count_in_loop": 0})
    ag = prof.get("all-gather",
                  {"count": 0, "bytes_total": 0, "count_in_loop": 0})
    # ZeRO-1's wire signature: plain-DP gradient all-reduce (params are
    # replicated, so backward is untouched) + one all-gather per sharded
    # updated param — total exactly the sharded param bytes, i.e. half
    # the sharded Adam-moment bytes (mu + nu mirror the params).
    return [
        _c("collective kinds are all-reduce + all-gather",
           ["all-gather", "all-reduce"], sorted(prof)),
        _c("one combined gradient all-reduce", 1, ar["count"]),
        _c("all-reduce payload = grad + loss bytes",
           info["param_bytes"] + 4, ar["bytes_total"]),
        _c("all-gathered update bytes = sharded param bytes",
           info["sharded_opt_bytes"] // 2, ag["bytes_total"]),
        _c("no loop-resident collectives", 0,
           ar["count_in_loop"] + ag["count_in_loop"]),
    ]


def check_fsdp(prof, info):
    ag = prof.get("all-gather", {"count": 0, "bytes_total": 0})
    rs = prof.get("reduce-scatter", {"count": 0, "bytes_total": 0})
    ar = prof.get("all-reduce", {"count": 0, "bytes_total": 0})
    sb = info["sharded_param_bytes"]
    # Gradient reduction: the partitioner may emit either the ZeRO-canonical
    # reduce-scatter (each device keeps its shard) or a full all-reduce it
    # then slices (profitable at small sizes) — record which, require the
    # sharded-grad bytes covered either way.
    info["grad_reduction_form"] = (
        "reduce-scatter" if rs["bytes_total"] >= sb else
        "all-reduce" if ar["bytes_total"] >= sb else "missing"
    )
    return [
        # Exactly one gather per sharded param: XLA keeps the gathered f32
        # copy live across fwd+bwd at this model size instead of
        # re-gathering (the ZeRO-3 memory/traffic trade, chosen by the
        # compiler).  Equality is the strong claim.
        _c("all-gather bytes == sharded param bytes (gathered once)",
           sb, ag["bytes_total"]),
        _c("sharded grads reduced (reduce-scatter or all-reduce)", True,
           info["grad_reduction_form"] != "missing"),
    ]


def check_tp_mlp(prof, info, split):
    ar = prof.get("all-reduce", {"count": 0, "bytes_total": 0})
    # The psum body: the output all-reduce is the regime's whole wire
    # story, and it is EXPOSED — the matmul that feeds it must finish
    # first, nothing runs under it.  (The backward may add small
    # bias-grad reduces; the floor is the fwd output psum.)
    return [
        _c("output psum present (>= out bytes, all exposed)", True,
           ar["bytes_total"] >= info["out_bytes"]
           and split["overlapped_bytes"] == 0),
        _c("no ppermute pipeline in the default body", True,
           "collective-permute" not in prof),
        _c("no all-gather", True, "all-gather" not in prof),
    ]


def check_tp_mlp_overlap(prof, info, split):
    cp = prof.get("collective-permute",
                  {"count": 0, "bytes_total": 0, "instructions": []})
    ar = prof.get("all-reduce", {"count": 0, "bytes_total": 0,
                                 "instructions": []})
    chunk = info["chunk_bytes"]
    n = info["ring"]
    # Fwd floor: the input gather ring (n-1 chunk hops) + the
    # reduce-scatter ring (n-1 chunk hops); the backward retraces both.
    floor = 2 * (n - 1) * chunk
    # Remaining all-reduces must be bookkeeping-sized (the scalar loss
    # psum and bias-grad reductions), never the [batch, d] output.
    big_ar = [i for i in ar["instructions"] if i["bytes"] >= info["out_bytes"]]
    return [
        _c("monolithic output psum GONE (no out-sized all-reduce)", 0,
           len(big_ar)),
        _c("no monolithic all-gather", True, "all-gather" not in prof),
        _c("wire moved into ppermute chunks (>= 2(n-1) chunk bytes)",
           {"floor": floor}, cp["bytes_total"],
           ok=cp["bytes_total"] >= floor),
        _c("every permute is overlap-pipeline-tagged", True,
           cp["count"] > 0 and all(i["overlapped"]
                                   for i in cp["instructions"])),
        _c("exposed bytes are bookkeeping only (< 1 chunk)", True,
           split["exposed_bytes"] < chunk),
        _c("no loop-resident collectives (chains unrolled)", 0,
           cp.get("count_in_loop", 0)),
    ]


def check_fsdp_overlap(prof, info, split, dense_prof):
    ag = prof.get("all-gather", {"count": 0, "bytes_total": 0,
                                 "instructions": []})
    cp = prof.get("collective-permute",
                  {"count": 0, "bytes_total": 0, "instructions": []})
    kb = info["ffn_kernel_bytes"]
    shard = info["ffn_shard_bytes"]
    n = info["ring"]
    layers = info["n_layers"]
    # Per layer: wi column ring (n-1 shard hops) + wo contraction ring
    # (n-1 shard hops) in forward; backward retraces both.
    floor = layers * 2 * (n - 1) * shard
    dense_ag = dense_prof.get("all-gather", {"bytes_total": 0})
    # The layout-only fsdp regime gathers every sharded param once
    # (its check asserts equality); here the two FFN kernels per layer
    # must be OUT of the gather budget — they stream sharded into the
    # ppermute pipeline instead.
    budget = info["sharded_param_bytes"] - layers * 2 * kb
    ffn_gathers = [i for i in ag["instructions"]
                   if "/wi/" in i["op_name"] or "/wo/" in i["op_name"]
                   or i["bytes"] == kb]
    return [
        _c("no all-gather of an FFN kernel (by provenance or size)", 0,
           len(ffn_gathers)),
        _c("all-gather bytes fit the non-FFN budget",
           {"budget": budget}, ag["bytes_total"],
           ok=ag["bytes_total"] <= budget),
        _c("FFN wire moved into ppermute chunks (>= 2·layers·(n-1) shards)",
           {"floor": floor}, cp["bytes_total"],
           ok=cp["bytes_total"] >= floor),
        _c("every permute is overlap-pipeline-tagged", True,
           cp["count"] > 0 and all(i["overlapped"]
                                   for i in cp["instructions"])),
        _c("strictly fewer gathered bytes than layout-only fsdp",
           {"fsdp": dense_ag["bytes_total"]}, ag["bytes_total"],
           ok=(dense_ag["bytes_total"] == 0
               or ag["bytes_total"] < dense_ag["bytes_total"])),
        _c("overlapped bytes dominate the permute traffic", True,
           split["overlapped_bytes"] >= cp["bytes_total"]),
    ]


def check_serve_decode_tp(prof, info, split):
    ag = prof.get("all-gather", {"count": 0, "bytes_total": 0,
                                 "instructions": []})
    # The layout-only decode path: the partitioner moves the sharded
    # FFN/attention activations however it likes (observed on this
    # backend: reshard collective-permutes plus a partial-sum
    # all-reduce of each layer's FFN output) — but every one of those
    # bytes is EXPOSED: scheduled on the decode critical path with
    # nothing structurally hidden under compute.  That is the number
    # the overlap routing exists to kill.  (The quoted
    # exposed_fraction lands on the regime row — main() computes it for
    # every regime from the same split.)
    total = split["exposed_bytes"] + split["overlapped_bytes"]
    return [
        _c("decode-path collectives present (TP seams)", True, total > 0),
        _c("ALL collective bytes exposed (nothing pipelined)", 0,
           split["overlapped_bytes"]),
        _c("no kernel ever gathered whole (weights stay sharded)", True,
           all(i["bytes"] < info["ffn_kernel_bytes"]
               for i in ag["instructions"])),
    ]


def check_serve_decode_tp_overlap(prof, info, split):
    cp = prof.get("collective-permute",
                  {"count": 0, "bytes_total": 0, "instructions": []})
    ag = prof.get("all-gather", {"count": 0, "bytes_total": 0,
                                 "instructions": []})
    n, layers = info["ring"], info["n_layers"]
    # Per decode-scan iteration: each layer's wi ring (n-1 chunk hops)
    # + wo ring (n-1 chunk hops).  HLO instruction bytes count the scan
    # body once, so the floor is per-iteration.
    floor = layers * (n - 1) * (info["wi_shard_bytes"]
                                + info["wo_shard_bytes"]) // n
    tagged = sum(i["bytes"] for i in cp["instructions"] if i["overlapped"])
    untagged = cp["bytes_total"] - tagged
    chunk = info["wi_shard_bytes"]
    return [
        _c("FFN kernel bytes ride tagged ppermute chunks (>= floor)",
           {"floor": floor}, tagged, ok=tagged >= floor),
        _c("untagged permutes are partitioner reshards (< 1 chunk)",
           {"chunk": chunk}, untagged, ok=untagged < chunk),
        _c("decode-path collective bytes are majority-overlapped", True,
           split["overlapped_bytes"] > split["exposed_bytes"]),
        _c("no kernel ever gathered whole (weights stay sharded)", True,
           all(i["bytes"] < info["ffn_kernel_bytes"]
               for i in ag["instructions"])),
    ]


def check_pp(prof, info):
    cp = prof.get("collective-permute",
                  {"count": 0, "count_in_loop": 0, "instructions": []})
    act = info["microbatch_act_bytes"]
    # The schedule's stage hops: one activation permute in the forward scan
    # body, one cotangent permute in the backward scan body, each moving
    # one microbatch's activations per tick.  (The off-loop all_to_alls are
    # the dp↔stage microbatch redistribution at the shard_map boundary.)
    loop_act = [i for i in cp["instructions"]
                if i["in_loop"] and i["bytes"] == act]
    return [
        _c("loop-resident stage hops of one microbatch each (fwd+bwd)",
           True, len(loop_act) >= 2),
        _c("all loop permutes are microbatch-sized", True,
           all(i["bytes"] == act for i in cp["instructions"]
               if i["in_loop"])),
        _c("grad all-reduce present (dp axis)", True, "all-reduce" in prof),
        _c("no reduce-scatter", True, "reduce-scatter" not in prof),
    ]


def main(argv=None) -> int:
    from benchmarks._round import current_round  # REPO is on sys.path

    p = argparse.ArgumentParser()
    p.add_argument("--out", default=str(
        REPO / f"COMM_AUDIT_r{current_round():02d}.json"))
    p.add_argument("--only", default=None, help="comma list of regime names")
    p.add_argument("--measure-only", action="store_true",
                   help="print profiles, skip checks")
    args = p.parse_args(argv)

    _force_cpu_mesh(8)
    import jax

    from tpudist.utils.hlo_audit import overlap_split, profile

    devices = jax.devices()[:8]
    wanted = set(args.only.split(",")) if args.only else None

    results, profiles = {}, {}
    n_fail = 0
    for name, builder in REGIMES.items():
        if wanted and name not in wanted:
            continue
        print(f"[comm-audit] lowering {name} ...", flush=True)
        try:
            step, ex_args, info = builder(devices)
            ops = collect_ops(step, ex_args, info)
        except Exception as e:  # noqa: BLE001
            # A regime that cannot BUILD on this box (e.g. a jax API the
            # installed version lacks) is a failed row, not a crashed
            # artifact: later regimes still audit and the file still
            # lands (the scaling_multiproc error-row convention).
            results[name] = {"error": repr(e), "ok": False}
            n_fail += 1
            print(f"[comm-audit] {name}: ERROR {e!r}", flush=True)
            continue
        prof = profile(ops)
        profiles[name] = prof
        split = overlap_split(ops)
        total = split["exposed_bytes"] + split["overlapped_bytes"]
        row = {"mesh": info.get("mesh"), "info": {
            k: v for k, v in info.items() if k != "mesh"},
            "overlap_split": split,
            "exposed_fraction": (round(split["exposed_bytes"] / total, 4)
                                 if total else None),
            "profile": prof}
        if not args.measure_only:
            if name == "dp":
                checks = check_dp(prof, info)
            elif name == "dp_bf16_reduce":
                checks = check_dp_bf16_reduce(prof, info)
            elif name == "dp_model_split":
                checks = check_dp_model_split(prof, info)
            elif name == "dp_sp_ring":
                checks = check_ring(prof, info)
            elif name == "dp_sp_ring_window":
                checks = check_ring_window(prof, info,
                                           profiles.get("dp_sp_ring", {}))
            elif name == "dp_sp_tp":
                checks = check_tp(prof, info)
            elif name == "dp_ep_moe":
                checks = check_moe(prof, info)
            elif name == "fsdp":
                checks = check_fsdp(prof, info)
            elif name == "dp_zero1":
                checks = check_zero1(prof, info)
            elif name == "tp_mlp":
                checks = check_tp_mlp(prof, info, split)
            elif name.startswith("tp_mlp_overlap"):
                checks = check_tp_mlp_overlap(prof, info, split)
            elif name.startswith("fsdp_overlap"):
                checks = check_fsdp_overlap(prof, info, split,
                                            profiles.get("fsdp", {}))
            elif name == "serve_decode_tp":
                checks = check_serve_decode_tp(prof, info, split)
            elif name.startswith("serve_decode_tp_"):
                checks = check_serve_decode_tp_overlap(prof, info, split)
            else:
                checks = check_pp(prof, info)
            row["checks"] = checks
            row["ok"] = all(c["ok"] for c in checks)
            n_fail += 0 if row["ok"] else 1
            status = "ok" if row["ok"] else "FAIL"
        else:
            status = "measured"
        results[name] = row
        kinds = {k: (v["count"], v["bytes_total"]) for k, v in prof.items()}
        print(f"[comm-audit] {name}: {status}  "
              f"exposed={split['exposed_bytes']} "
              f"overlapped={split['overlapped_bytes']}  {kinds}", flush=True)

    out = {"n_devices": 8, "platform": "cpu-virtual",
           "jax_version": jax.__version__, "regimes": results,
           "failed": n_fail}
    if wanted:
        out["only"] = sorted(wanted)
    Path(args.out).write_text(json.dumps(out, indent=2) + "\n")
    print(json.dumps({"regimes": len(results), "failed": n_fail,
                      "out": args.out}))
    return 1 if n_fail else 0


if __name__ == "__main__":
    sys.exit(main())
