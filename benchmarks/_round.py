"""Round detection shared by the benchmark harnesses.

Artifacts freeze per round as ``<NAME>_r{NN}.json`` at the repo root;
the round being BUILT is one past the highest frozen ``BENCH_r*.json``
(the driver writes that file at each round's end).  Deriving output
names from this keeps a standalone harness run from ever clobbering a
frozen round's artifact.
"""

from __future__ import annotations

import re
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def current_round() -> int:
    rounds = [int(m.group(1)) for p in REPO.glob("BENCH_r*.json")
              if (m := re.match(r"BENCH_r(\d+)\.json", p.name))]
    return (max(rounds) + 1) if rounds else 1
