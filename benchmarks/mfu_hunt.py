#!/usr/bin/env python3
"""MFU lever search on the saturating d1024 config (VERDICT r3 #2).

One command, one live tunnel window → the best-achievable MFU row plus
the evidence trail: walks the lever matrix on the real chip —

  batch ladder:   8, 16, 32       (arithmetic intensity)
  remat:          off, dots, full (HBM pressure ↔ recompute; larger
                  batches only fit WITH remat, so the ladder extends to
                  64 under 'dots')

— each rung a watchdogged call of ``bench.bench_lm`` on the fixed
d1024/L8/ff4096/seq2048 bf16 geometry, persisting after every rung to
``MFU_HUNT.json``.  The best rung re-runs with ``jax.profiler`` capture
so ``profile_summary.py`` can name the residual time sinks if the ≥40%
target still isn't met.  Prints one JSON line (best row).

Usage: python benchmarks/mfu_hunt.py [--target 40] [--steps 3]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

OUT = REPO / "MFU_HUNT.json"

GEOM = dict(seq_len=2048, d_model=1024, n_layers=8, n_heads=8, d_ff=4096,
            precision="bf16")

# (tag, batch, remat, remat_policy) — ordered cheap-to-risky so an OOM or
# wedge keeps every earlier rung's row.
# Plain b32 is omitted: the roofline (ROOFLINE_r{NN}.json) shows it
# exceeds the 16 GiB HBM — a guaranteed OOM would burn minutes of a
# live tunnel window confirming arithmetic.
RUNGS = [
    ("b8", 8, False, "nothing"),
    ("b16", 16, False, "nothing"),
    ("b32_dots", 32, True, "dots"),
    ("b64_dots", 64, True, "dots"),
    ("b64_full_remat", 64, True, "nothing"),
]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--target", type=float, default=40.0,
                    help="MFU %% goal (reporting only)")
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--rung-timeout", type=float, default=900.0)
    args = ap.parse_args(argv)

    import bench  # repo-root harness: bench_lm + watchdog + device probe

    if not bench._device_reachable():
        print(json.dumps({"metric": "lm_mfu_best", "value": 0,
                          "error": "device unreachable"}))
        return 2

    results: dict = {"geometry": GEOM, "target_pct": args.target, "rungs": {}}
    if OUT.exists():
        try:
            results = {**json.loads(OUT.read_text()), **results}
        except Exception:
            pass

    best = None
    for tag, batch, remat, policy in RUNGS:
        try:
            row = bench._with_watchdog(
                lambda: bench.bench_lm(
                    name=f"mfu_hunt_{tag}", batch=batch, steps=args.steps,
                    remat=remat, remat_policy=policy, **GEOM),
                args.rung_timeout, f"mfu_hunt {tag}")
        except Exception as e:  # OOM, wedge — record, keep climbing
            row = {"error": repr(e)}
        results["rungs"][tag] = row
        OUT.write_text(json.dumps(results, indent=2) + "\n")
        mfu = row.get("mfu_pct_vs_bf16_peak")
        print(f"# {tag}: "
              f"{mfu if mfu is not None else row.get('error', '?')}",
              file=sys.stderr, flush=True)
        if mfu is not None and (best is None or
                                mfu > best[1].get("mfu_pct_vs_bf16_peak", 0)):
            best = (tag, row)

    if best is None:
        print(json.dumps({"metric": "lm_mfu_best", "value": 0,
                          "error": "no rung completed"}))
        return 1

    tag, row = best
    # Re-run the winner with trace capture for the per-op story.
    try:
        cfg = row["config"]
        traced = bench._with_watchdog(
            lambda: bench.bench_lm(
                name=f"mfu_hunt_{tag}_traced", batch=cfg["batch"],
                steps=args.steps, remat=cfg["remat"],
                remat_policy=cfg["remat_policy"] or "nothing",
                profile_dir=str(REPO / "runs" / "profile_mfu_hunt"),
                **GEOM),
            args.rung_timeout, "mfu_hunt trace")
        results["best_traced"] = traced
    except Exception as e:
        results["best_trace_error"] = repr(e)
    results["best"] = {"rung": tag, **row}
    OUT.write_text(json.dumps(results, indent=2) + "\n")
    print(json.dumps({
        "metric": "lm_mfu_best_pct", "unit": "% of bf16 peak",
        "value": row.get("mfu_pct_vs_bf16_peak"),
        "rung": tag,
        "tokens_per_sec_per_chip": row.get("value"),
        "meets_target": row.get("mfu_pct_vs_bf16_peak", 0) >= args.target,
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
