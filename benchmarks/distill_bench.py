#!/usr/bin/env python3
"""Online draft-distillation bench: the distribution-shift flywheel
story, frozen per round as ``BENCH_DISTILL_r{NN}.json``.

One scenario, CPU-safe (tiny model; CROSS-ARM acceptance on one
schedule is the measurement, absolute tok/s is not):

- Traffic mix A (prompts from the low half of the vocab) serves for a
  warm phase, then the mix FLIPS to B (high half) mid-run — the drift
  that decays any frozen draft's acceptance.
- **frozen** arm: a draft distilled offline on mix A
  (``tpudist.distill.distill_draft`` — the same path
  ``serve_bench --spec-distill`` uses) serves the whole schedule
  unchanged.  Its per-window acceptance timeline shows the decay.
- **flywheel** arm: the SAME initial draft plus the online loop —
  live capture ring (``TPUDIST_DISTILL_CAPTURE`` armed
  programmatically), ``DistillLoop.run_once()`` driven at controlled
  points after the flip, gated hot-swap on a measured held-out win.
  Its timeline shows acceptance RECOVER after the swap while the
  frozen twin stays decayed.

The artifact freezes:

- ``acceptance_timeline`` — per-window acceptance for both arms, the
  decay-and-recovery picture;
- ``swap_timeline`` — every distillation round's gate verdict +
  acceptance numbers, and each applied swap's latency;
- ``outputs_match`` — every flywheel stream byte-identical to the
  frozen arm's (greedy; the draft only proposes, the target decides —
  hot-swaps must never move bytes);
- ``compile_pins_flat`` — jit-cache sizes identical across the swaps
  (the dparams-as-argument contract);
- ``frozen_decayed`` / ``flywheel_recovered`` — the headline claims.

Usage: ``python benchmarks/distill_bench.py [--smoke] [--out PATH]``
(round_snapshot.py freezes it per round; the tier-1 smoke test asserts
the rung fields).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

CFG = dict(vocab=32, d_model=32, n_layers=2, n_heads=2, d_ff=64,
           max_len=64)


def _model(seed: int = 0):
    import jax

    from tpudist.models import create_transformer

    return create_transformer(jax.random.PRNGKey(seed), seq_len=16, **CFG)


def _pool(lo: int, hi: int, n: int, plens, seed: int):
    """A repeat-prompt pool drawn from one vocab band — the two bands
    are the two traffic mixes (acceptance is a property of
    (draft, workload); flipping the band flips the workload)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    return [rng.integers(lo, hi, size=int(rng.integers(
        plens[0], plens[1] + 1))).astype(np.int32) for _ in range(n)]


def _server(module, params, draft, spec_k: int):
    from tpudist.serve import InferenceServer, ServeConfig

    return InferenceServer(
        module, params,
        ServeConfig(num_slots=2, queue_limit=16, prefill_pad=8,
                    spec=True, spec_draft=draft, spec_k=spec_k),
        install_signal_handler=False).start()


def _drive_window(srv, pool, max_new: int, outputs: dict) -> dict:
    """One traffic window: the whole pool once, greedy.  Returns the
    WINDOW's acceptance (cumulative-counter deltas — each window is its
    own measurement, not a running average)."""
    st0 = srv.engine.spec_stats()
    for i, p in enumerate(pool):
        h = srv.submit(p, max_new=max_new)
        assert h.wait(300), "request stalled"
        key = (p.tobytes(), max_new)
        if key in outputs:
            assert outputs[key] == h.tokens, \
                "greedy stream moved across arms/swaps"
        else:
            outputs[key] = h.tokens
    st1 = srv.engine.spec_stats()
    acc = st1["accepted"] - st0["accepted"]
    dra = st1["drafted"] - st0["drafted"]
    return {
        "accepted": acc, "drafted": dra,
        "acceptance": round(acc / dra, 4) if dra else None,
    }


def run_shift(*, smoke: bool, max_new: int, spec_k: int = 4,
              windows_a: int = 2, windows_b: int = 3,
              distill_steps: int = 60, seed: int = 0) -> dict:
    from tpudist.distill import CaptureBuffer, DistillLoop, distill_draft

    module, params = _model(seed)
    pool_n = 4
    plens = (4, 7)
    v = CFG["vocab"]
    pool_a = _pool(0, v // 2, pool_n, plens, seed + 1)
    pool_b = _pool(v // 2, v, pool_n, plens, seed + 2)

    # the cold-start draft both arms begin with: distilled OFFLINE on
    # mix A — the deployment that trained for yesterday's traffic
    draft_mod, draft_params, loss0 = distill_draft(
        module, params, 1, pool_a, distill_steps, max_new)

    timeline = []
    swap_timeline = []
    outputs: dict = {}  # (prompt bytes, max_new) -> tokens; shared
    # across arms AND windows: greedy bytes must never move

    # -- frozen arm ---------------------------------------------------------
    srv = _server(module, params, (draft_mod, draft_params), spec_k)
    frozen_a = []
    frozen_b = []
    try:
        for w in range(windows_a):
            row = _drive_window(srv, pool_a, max_new, outputs)
            frozen_a.append(row["acceptance"])
            timeline.append({"arm": "frozen", "phase": "A", "window": w,
                             **row})
        for w in range(windows_b):
            row = _drive_window(srv, pool_b, max_new, outputs)
            frozen_b.append(row["acceptance"])
            timeline.append({"arm": "frozen", "phase": "B", "window": w,
                             **row})
    finally:
        srv.close(60)

    # -- flywheel arm -------------------------------------------------------
    srv = _server(module, params, (draft_mod, draft_params), spec_k)
    # small ring: phase-A streams evict as B traffic arrives, so the
    # post-flip rounds train on (mostly) the CURRENT mix
    srv.attach_capture(CaptureBuffer(
        budget_tokens=pool_n * (plens[1] + max_new) * (windows_b + 1)))
    loop = DistillLoop(srv, srv.capture, steps=distill_steps,
                       min_tokens=32, holdout=0.25, margin=0.01)
    fly_a = []
    fly_b = []
    pins0 = None
    try:
        for w in range(windows_a):
            row = _drive_window(srv, pool_a, max_new, outputs)
            fly_a.append(row["acceptance"])
            timeline.append({"arm": "flywheel", "phase": "A", "window": w,
                             **row})
        for w in range(windows_b):
            row = _drive_window(srv, pool_b, max_new, outputs)
            fly_b.append(row["acceptance"])
            timeline.append({"arm": "flywheel", "phase": "B", "window": w,
                             "swaps": srv.engine.draft_swaps, **row})
            if pins0 is None:
                # baseline AFTER every traffic shape has been seen once
                # (both pools' prompt-length buckets) — any growth from
                # here is the swaps', and the claim is: none
                pins0 = dict(srv.engine.compile_counts())
            if loop.swaps == 0:
                # the controlled flywheel turn (the background thread's
                # cadence, driven synchronously for determinism)
                r = loop.run_once()
                swap_timeline.append({k: r.get(k) for k in (
                    "round", "swapped", "reason", "loss",
                    "candidate_acceptance", "serving_holdout_acceptance",
                    "live_acceptance", "baseline", "swap_s",
                    "lanes_rearmed", "round_s")})
        pins1 = dict(srv.engine.compile_counts())
        draft_swaps = srv.engine.draft_swaps
        capture_stats = srv.capture.stats()
    finally:
        srv.close(60)

    def _mean(xs):
        xs = [x for x in xs if x is not None]
        return round(sum(xs) / len(xs), 4) if xs else None

    # decay: the frozen draft's phase-B acceptance vs its phase-A
    # acceptance; recovery: the flywheel's POST-SWAP windows vs the
    # frozen arm's same windows
    post_swap = [a for a, t in zip(
        fly_b, [r["swaps"] > 0 for r in timeline
                if r["arm"] == "flywheel" and r["phase"] == "B"]) if t]
    frozen_a_mean = _mean(frozen_a)
    frozen_b_mean = _mean(frozen_b)
    post_swap_mean = _mean(post_swap)
    return {
        "bench": "distill_shift",
        "note": ("tiny-model CPU mechanics — cross-arm acceptance on one "
                 "schedule is the measurement, absolute tok/s is not"),
        "smoke": bool(smoke),
        "spec_k": spec_k, "max_new": max_new,
        "distill_steps": distill_steps,
        "offline_distill_loss": round(float(loss0), 5),
        "acceptance_timeline": timeline,
        "swap_timeline": swap_timeline,
        "swaps": draft_swaps,
        "rounds": loop.rounds,
        "frozen_phase_a_acceptance": frozen_a_mean,
        "frozen_phase_b_acceptance": frozen_b_mean,
        "flywheel_post_swap_acceptance": post_swap_mean,
        "frozen_decayed": (frozen_a_mean is not None
                           and frozen_b_mean is not None
                           and frozen_b_mean < frozen_a_mean),
        "flywheel_recovered": (post_swap_mean is not None
                               and frozen_b_mean is not None
                               and post_swap_mean > frozen_b_mean),
        "outputs_match": True,  # _drive_window asserted per stream
        "compile_pins_flat": pins0 == pins1,
        "capture": {k: capture_stats[k] for k in
                    ("streams", "tokens", "evicted", "captured")},
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="CI-sized run (fewer windows / steps)")
    ap.add_argument("--out", default=None, help="output JSONL path")
    ap.add_argument("--max-new", type=int, default=None)
    ap.add_argument("--steps", type=int, default=None)
    args = ap.parse_args(argv)
    max_new = args.max_new or (6 if args.smoke else 12)
    steps = args.steps or (60 if args.smoke else 200)
    row = run_shift(smoke=args.smoke, max_new=max_new,
                    distill_steps=steps,
                    windows_b=3 if args.smoke else 4)
    line = json.dumps(row)
    print(line)
    if args.out:
        Path(args.out).write_text(line + "\n")
        print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
