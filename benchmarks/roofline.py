#!/usr/bin/env python3
"""Analytic roofline for the MFU-row configs — the no-hardware half of
"drive MFU ≥40% or prove the ceiling" (VERDICT r3 #2).

For each lever-matrix rung of ``mfu_hunt.py`` this computes, from the
model geometry alone:

- model FLOPs per step (``tpudist.utils.flops`` accounting);
- HBM bytes per step: parameter traffic (bf16 weights read in fwd AND
  bwd; f32 master params, grads, and both Adam moments read+written at
  the update) + activation traffic (every residual tensor written once
  in fwd and read once in bwd — or recomputed under remat, which moves
  the traffic to the recompute's reads);
- the resulting compute time at peak vs HBM time at peak bandwidth, and
  the MFU CEILING ``t_compute / max(t_compute, t_hbm)`` — what the chip
  allows if every matmul ran at peak and all traffic streamed at full
  bandwidth.

The point of the number: if the ceiling is ~1.0 (compute-bound) and the
measured MFU is far below it, the residual is schedulable work — kernel
quality, fusion, dispatch — NOT a bandwidth wall; the profile trace is
the tool that names it.  If the ceiling itself is low, the config is
bandwidth-bound and batch/remat are the levers.  Writes
``ROOFLINE_r{NN}.json`` (round auto-detected; r05 added the decode
rung) and prints one row per rung.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

# Public v5e spec: 197 bf16 TFLOP/s, 819 GB/s HBM BW, 16 GiB HBM.
HBM_BYTES_PER_S = 8.19e11
HBM_CAPACITY = 16 * 2 ** 30

GEOM = dict(seq_len=2048, d_model=1024, n_layers=8, d_ff=4096, vocab=256)

RUNGS = [  # (tag, batch, remat)
    ("b8", 8, False),
    ("b16", 16, False),
    ("b32", 32, False),
    ("b32_remat", 32, True),
    ("b64_remat", 64, True),
]


def param_count(*, d_model, n_layers, d_ff, vocab, seq_len, **_):
    """One accounting for the whole repo: the canonical formula lives in
    ``tpudist.utils.flops.transformer_param_count`` (this config's
    ``seq_len`` is the position-table ``max_len``)."""
    from tpudist.utils.flops import transformer_param_count

    return transformer_param_count(d_model=d_model, n_layers=n_layers,
                                   d_ff=d_ff, vocab=vocab, max_len=seq_len)


def activation_bytes(*, batch, seq_len, d_model, d_ff, n_layers, remat,
                     dtype_bytes=2, **_):
    """Residual tensors saved for backward, per step (write in fwd + read
    in bwd => x2 traffic).  Per block: the attention inputs/outputs and
    MLP intermediates that autodiff keeps ~ (6*d + 2*ff) values/token
    (q,k,v,attn-out,2 norms ~ 6d; two MLP intermediates ~ 2ff).  Under
    block remat only the block INPUT is saved (d values/token); the
    recompute re-reads weights instead (counted in weight traffic)."""
    tokens = batch * seq_len
    per_token = (d_model if remat
                 else 6 * d_model + 2 * d_ff)
    return 2 * tokens * per_token * n_layers * dtype_bytes


def weight_traffic_bytes(n_params, *, remat):
    """Per step: bf16 weights read by fwd + bwd (x3 with the remat
    re-forward), f32 grads written+read, f32 master read+written, two
    f32 Adam moments read+written."""
    fwd_bwd_reads = (3 if remat else 2) * 2 * n_params      # bf16
    optimizer = (2 + 2 + 4) * 4 * n_params                  # f32 r/w
    return fwd_bwd_reads + optimizer


def decode_row() -> dict:
    """Roofline for bench.py's ``lm_decode`` config (batch 8, prompt 16,
    +240 tokens, d512/L4/ff2048/V256, fp32) — decode streams weights +
    KV cache per token, so the ceiling is pure HBM bandwidth (the
    training rungs' compute-vs-bandwidth comparison collapses: decode
    compute time is negligible)."""
    from tpudist.utils.flops import decode_roofline

    cfg = dict(batch=8, prompt_len=16, max_new=240, d_model=512,
               n_layers=4, d_ff=2048, vocab=256)
    roof = decode_roofline(**cfg, param_bytes=4, cache_bytes=4,
                           hbm_bytes_per_s=HBM_BYTES_PER_S)
    return {"rung": "decode", "config": cfg, **roof,
            "bound": "bandwidth",
            "note": ("ceiling = batch / ((weight_bytes + avg KV bytes) / "
                     "HBM BW); measured lm_decode rows carry "
                     "pct_of_roofline against this")}


def paged_decode_row() -> dict:
    """Decode roofline on the PAGED serving cache: gather vs the Pallas
    paged-attention kernel (tpudist/ops/paged_attention.py), re-measured
    per the kernel PR.  Per emitted token at live-KV fraction ``f`` of
    ``max_len`` (per decoding lane; weights amortize over the batch):

    - **gather**: the dense-view path streams ``max_len × bytes/pos``
      regardless of cursors — bytes/token are FLAT in ``f`` (pool
      geometry is the denominator);
    - **kernel**: the in-kernel block-table walk streams
      ``ceil(f·max_len / block) × block × bytes/pos`` — bytes/token
      TRACK live KV.

    The serve_bench ``attn_kernel_twin`` rung applies the same per-path
    accounting to a real traffic mix (quantifying the gap at a measured
    occupancy); the independent verification of the kernel's DMA
    elision is an on-chip profile (DECODE_PROFILE's paged phases on
    TPU), not either model.  The HBM-time column converts bytes to a
    per-token floor at peak bandwidth — the ceiling the on-chip run
    decodes against."""
    cfg = dict(batch=8, d_model=512, n_layers=4, vocab=256,
               max_len=2048, kv_block=16, dtype_bytes=4)
    n_params = param_count(d_model=cfg["d_model"], n_layers=cfg["n_layers"],
                           d_ff=4 * cfg["d_model"], vocab=cfg["vocab"],
                           seq_len=cfg["max_len"])
    w_per_tok = n_params * cfg["dtype_bytes"] / cfg["batch"]
    kv_per_pos = 2 * cfg["n_layers"] * cfg["d_model"] * cfg["dtype_bytes"]
    bs = cfg["kv_block"]
    rows = []
    for f in (0.125, 0.25, 0.5, 1.0):
        live = int(f * cfg["max_len"])
        live_blocks = -(-live // bs) * bs
        gather_b = w_per_tok + cfg["max_len"] * kv_per_pos
        kernel_b = w_per_tok + live_blocks * kv_per_pos
        rows.append({
            "live_kv_fraction": f,
            "bytes_per_token_gather": int(gather_b),
            "bytes_per_token_kernel": int(kernel_b),
            "gather_over_kernel": round(gather_b / kernel_b, 3),
            "t_hbm_us_per_token_gather": round(
                gather_b / HBM_BYTES_PER_S * 1e6, 2),
            "t_hbm_us_per_token_kernel": round(
                kernel_b / HBM_BYTES_PER_S * 1e6, 2),
        })
    return {"rung": "paged_decode", "config": cfg, "bound": "bandwidth",
            "rows": rows,
            # the acceptance property, stated by the model itself:
            # kernel bytes/token are monotone in live KV, gather's flat
            "kernel_tracks_live_kv": all(
                rows[i]["bytes_per_token_kernel"]
                < rows[i + 1]["bytes_per_token_kernel"]
                for i in range(len(rows) - 1)),
            "gather_flat_in_occupancy": len(
                {r["bytes_per_token_gather"] for r in rows}) == 1,
            "note": ("bytes/token per decode path (analytic); serve_bench's "
                     "attn_kernel_twin applies the same accounting to real "
                     "traffic — on-chip DECODE_PROFILE is the independent "
                     "check of the DMA elision")}


def paged_prefill_row() -> dict:
    """Prefill roofline on the paged cache: the gather prefill vs the
    paged-prefill kernel (tpudist/ops/paged_prefill.py), per the kernel
    family PR.  Per PROMPT token when a chunk of ``P`` tokens lands on
    a lane whose cursor sits at live-KV fraction ``f`` of ``max_len``
    (the chunked-prefill steady state — each chunk after the first
    attends a committed prefix):

    - **gather**: the dense-view path streams the lane's full
      ``(1 + pad) × max_len`` geometry per dispatch and scatters the
      static pad span — KV bytes/prompt-token are FLAT in ``f``;
    - **kernel**: the in-kernel walk reads ``ceil(f·max_len / block)``
      blocks of prefix and WRITES only the ``ceil``-span of blocks the
      chunk covers — read bytes/prompt-token TRACK live KV and write
      bytes are chunk-proportional.

    ``SlotEngine._prefill_kv_bytes`` applies the same per-path model to
    real traffic (serve_bench's ``kernel_family_twin`` rung quotes it);
    the independent check of the in-kernel write DMA is an on-chip
    profile, not either model."""
    cfg = dict(d_model=512, n_layers=4, max_len=2048, kv_block=16,
               prefill_pad=64, dtype_bytes=4)
    kv_per_pos = 2 * cfg["n_layers"] * cfg["d_model"] * cfg["dtype_bytes"]
    bs, P = cfg["kv_block"], cfg["prefill_pad"]
    rows = []
    for f in (0.125, 0.25, 0.5, 0.875):
        live = int(f * cfg["max_len"])
        prefix_blocks = -(-live // bs) * bs
        chunk_blocks = (-(-(live + P) // bs) - live // bs) * bs
        gather_r = (1 + P) * cfg["max_len"] * kv_per_pos / P
        gather_w = P * kv_per_pos / P  # static pad span ≈ the chunk
        kernel_r = prefix_blocks * kv_per_pos / P
        kernel_w = chunk_blocks * kv_per_pos / P
        rows.append({
            "live_kv_fraction": f,
            "read_bytes_per_prompt_token_gather": int(gather_r),
            "read_bytes_per_prompt_token_kernel": int(kernel_r),
            "write_bytes_per_prompt_token_kernel": int(kernel_w),
            "write_bytes_per_prompt_token_gather": int(gather_w),
            "gather_over_kernel_read": round(gather_r / kernel_r, 3),
            "t_hbm_us_per_prompt_token_gather": round(
                (gather_r + gather_w) / HBM_BYTES_PER_S * 1e6, 2),
            "t_hbm_us_per_prompt_token_kernel": round(
                (kernel_r + kernel_w) / HBM_BYTES_PER_S * 1e6, 2),
        })
    return {"rung": "paged_prefill", "config": cfg, "bound": "bandwidth",
            "rows": rows,
            # the acceptance property: kernel prefill reads are monotone
            # in live KV (they track the walked prefix), gather's flat
            "kernel_tracks_live_kv": all(
                rows[i]["read_bytes_per_prompt_token_kernel"]
                < rows[i + 1]["read_bytes_per_prompt_token_kernel"]
                for i in range(len(rows) - 1)),
            "gather_flat_in_occupancy": len(
                {r["read_bytes_per_prompt_token_gather"]
                 for r in rows}) == 1,
            "kernel_below_gather_everywhere": all(
                r["read_bytes_per_prompt_token_kernel"]
                < r["read_bytes_per_prompt_token_gather"] for r in rows),
            "note": ("KV bytes per prompt token per prefill path "
                     "(analytic); serve_bench's kernel_family_twin "
                     "applies the engine's accounting to real traffic")}


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None,
                    help="artifact path (default ROOFLINE_r{NN}.json at "
                         "the repo root, round auto-detected)")
    args = ap.parse_args(argv)
    from tpudist.utils.flops import PEAK_BF16_FLOPS, transformer_train_flops

    peak = PEAK_BF16_FLOPS["TPU v5 lite"]
    n_params = param_count(**GEOM)
    rows = []
    for tag, batch, remat in RUNGS:
        flops = transformer_train_flops(batch=batch, **GEOM)
        if remat:  # one extra forward of the block stack
            flops = flops * 4 / 3
        act_b = activation_bytes(batch=batch, remat=remat, **GEOM)
        w_b = weight_traffic_bytes(n_params, remat=remat)
        t_c = flops / peak
        t_h = (act_b + w_b) / HBM_BYTES_PER_S
        # Peak live memory sanity: f32 master+grads+moments + bf16 copy
        # + saved activations (absolute lower bound).
        mem = n_params * (4 * 4 + 2) + act_b / 2
        rows.append({
            "rung": tag, "batch": batch, "remat": remat,
            "model_flops_per_step": flops,
            "hbm_bytes_per_step": int(act_b + w_b),
            "t_compute_ms_at_peak": round(t_c * 1e3, 2),
            "t_hbm_ms_at_peak_bw": round(t_h * 1e3, 2),
            "mfu_ceiling": round(t_c / max(t_c, t_h), 4),
            "bound": "compute" if t_c >= t_h else "bandwidth",
            "est_min_live_bytes": int(mem),
            "fits_hbm": mem < HBM_CAPACITY * 0.9,
        })
        print(json.dumps(rows[-1]), flush=True)
    rows.append(decode_row())
    print(json.dumps(rows[-1]), flush=True)
    rows.append(paged_decode_row())
    print(json.dumps(rows[-1]), flush=True)
    rows.append(paged_prefill_row())
    print(json.dumps(rows[-1]), flush=True)
    from benchmarks._round import current_round  # REPO is on sys.path

    out = {"geometry": GEOM, "n_params": n_params,
           "peak_bf16_flops": peak, "hbm_bytes_per_s": HBM_BYTES_PER_S,
           "accounting": "see module docstring", "rows": rows}
    out_path = (Path(args.out) if args.out
                else REPO / f"ROOFLINE_r{current_round():02d}.json")
    out_path.write_text(json.dumps(out, indent=2) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
