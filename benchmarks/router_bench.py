#!/usr/bin/env python3
"""Fleet-router bench: affinity routing vs round-robin, plus the
replica-death migration rung, frozen per round as
``BENCH_ROUTER_r{NN}.json``.

Two rungs, CPU-safe (tiny model; absolute times are interpreter
mechanics — the TWIN DELTAS and the booleans are the measurements):

- **router_affinity_twin** — the same deterministic workload (S
  sessions x T turns at odd S, plus G sessionless prefix groups of M
  members sharing a 16-token base) served through a 2-replica fleet
  twice: ``policy="affinity"`` vs ``policy="rr"``.  Session affinity
  keeps every later turn on the replica holding its parked KV
  (teacher-forcing only the new suffix); round-robin ping-pongs the
  session, paying a fresh or stale-prefix prefill of the whole grown
  context.  Prefix affinity rendezvous-hashes same-base requests onto
  the same replica so its paged prefix cache actually hits; rr
  scatters the group.  Quotes later-turn resume-TTFT and the fleet
  prefix-hit block rate per arm, and asserts both arms' outputs are
  byte-equal — routing is a latency lever, never a numerics one.

- **router_failover** — sessions homed across the fleet, stash
  populated, then ``TPUDIST_FAULT=replica_kill@nth:V`` kills the
  majority replica from the router's own tick thread.  Quotes the
  probe-detection latency, the migration count, and whether every
  session's next turn still RESUMED (the stash adoption landed the
  parked KV on the survivor) — fleet keeps serving, nothing finishes
  ``replica_lost``.

Usage: ``python benchmarks/router_bench.py [--smoke] [--out PATH]``
(round_snapshot.py freezes it per round; the tier-1 smoke test asserts
the rung fields).
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

CFG = dict(vocab=32, d_model=32, n_layers=2, n_heads=2, d_ff=64,
           max_len=64)


def _model(seed: int = 0):
    import jax

    from tpudist.models import create_transformer

    return create_transformer(jax.random.PRNGKey(seed), seq_len=16, **CFG)


def _mean(vals):
    vals = [v for v in vals if v is not None]
    return sum(vals) / len(vals) if vals else None


def _p50(vals):
    vals = sorted(v for v in vals if v is not None)
    return vals[len(vals) // 2] if vals else None


def _wait(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.005)
    return pred()


def _build_fleet(model, *, policy: str, n: int = 2):
    """N warmed replicas behind a router.  Each replica is warmed
    DIRECTLY (before the router fronts it) through the same session
    park/resume cycle the workload uses, so every XLA compile —
    insert/prefill/decode/evict and export/import — is paid on BOTH
    replicas regardless of where the policy would route; the twin
    delta then measures recompute, not first-compile."""
    import numpy as np

    from tpudist.serve import FleetRouter, InferenceServer, RouterConfig
    from tpudist.serve import ServeConfig

    cfg = ServeConfig(num_slots=2, max_new=6, prefill_pad=4,
                      queue_limit=32, paged=True, kv_block=8,
                      prefix_cache_blocks=16, host_tier=True)
    reps = [InferenceServer(*model, cfg,
                            install_signal_handler=False).start()
            for _ in range(n)]
    warm = np.arange(12, dtype=np.int32) % CFG["vocab"]
    for r in reps:
        w = r.submit(warm[:8], max_new=2, session="warm")
        assert w.wait(600)
        w2 = r.submit(np.concatenate([warm[:8],
                                      np.asarray(w.tokens, np.int32),
                                      warm[:4]]),
                      max_new=2, session="warm")
        assert w2.wait(600)
    router = FleetRouter(reps, RouterConfig(policy=policy,
                                            probe_s=0.02)).start()
    return router, reps


def _tier_parks(reps) -> int:
    return sum(r._tier.parks for r in reps)


# ---------------------------------------------------------------------------
# router_affinity_twin


def _run_twin_arm(model, *, policy: str, sessions: int, turns: int,
                  groups: int, members: int) -> dict:
    import numpy as np

    router, reps = _build_fleet(model, policy=policy)
    rng = np.random.default_rng(0)
    contexts = {s: rng.integers(0, CFG["vocab"], size=24).astype(np.int32)
                for s in range(sessions)}
    ttft_by_turn: dict = {t: [] for t in range(turns)}
    reasons: dict = {}
    outputs = []
    try:
        parks0 = _tier_parks(reps)
        for t in range(turns):
            handles = []
            for s in range(sessions):
                if t > 0:
                    new = rng.integers(0, CFG["vocab"],
                                       size=4).astype(np.int32)
                    contexts[s] = np.concatenate([contexts[s], new])
                handles.append(
                    (s, router.submit(contexts[s], max_new=6,
                                      session=f"s{s}", tenant="bench")))
            for s, h in handles:
                assert h.wait(600), "session turn timed out"
                ttft_by_turn[t].append(h.ttft_s)
                reasons[h.finish_reason] = reasons.get(h.finish_reason,
                                                       0) + 1
                outputs.append(("s", t, s, list(h.tokens)))
                contexts[s] = np.concatenate(
                    [contexts[s], np.asarray(h.tokens, np.int32)])
            # the next wave's resume needs this wave's parks landed
            want = parks0 + sessions * (t + 1)
            assert _wait(lambda: _tier_parks(reps) >= want), \
                "session parks did not land"
        # prefix phase: G groups sharing a 16-token base (= the
        # router-side digest window AND two full KV blocks), distinct
        # 4-token tails; sequential so a member's cached blocks are
        # releasable before its siblings arrive.  The hit rate is the
        # kv-counter DIFF over this phase only.
        hits0 = sum(reps[i].engine.kv_stats()["prefix_hit_blocks"]
                    for i in range(len(reps)))
        miss0 = sum(reps[i].engine.kv_stats()["prefix_miss_blocks"]
                    for i in range(len(reps)))
        for g in range(groups):
            base = rng.integers(0, CFG["vocab"], size=16).astype(np.int32)
            for m in range(members):
                tail = rng.integers(0, CFG["vocab"],
                                    size=4).astype(np.int32)
                h = router.submit(np.concatenate([base, tail]), max_new=4)
                assert h.wait(600), "prefix request timed out"
                outputs.append(("p", g, m, list(h.tokens)))
        hits = sum(reps[i].engine.kv_stats()["prefix_hit_blocks"]
                   for i in range(len(reps))) - hits0
        misses = sum(reps[i].engine.kv_stats()["prefix_miss_blocks"]
                     for i in range(len(reps))) - miss0
        stats = router.stats()
    finally:
        router.close(60)
    later = [v for t in range(1, turns) for v in ttft_by_turn[t]]
    return {
        "ttft_turn1_s": _mean(ttft_by_turn[0]),
        "ttft_later_mean_s": _mean(later),
        "ttft_later_p50_s": _p50(later),
        "turns_resumed": reasons.get("session_resumed", 0),
        "finish_reasons": reasons,
        "prefix_hit_blocks": int(hits),
        "prefix_miss_blocks": int(misses),
        "prefix_hit_rate": (hits / (hits + misses)
                            if (hits + misses) else None),
        "routes_by_kind": stats["routes_by_kind"],
        "outputs": outputs,
    }


def run_affinity_twin(sessions: int, turns: int, groups: int,
                      members: int) -> dict:
    model = _model()
    aff = _run_twin_arm(model, policy="affinity", sessions=sessions,
                        turns=turns, groups=groups, members=members)
    rr = _run_twin_arm(model, policy="rr", sessions=sessions,
                       turns=turns, groups=groups, members=members)
    return {
        "rung": "router_affinity_twin",
        "regime": "cpu-smoke",
        "replicas": 2,
        "sessions": sessions,
        "turns": turns,
        "prefix_groups": groups,
        "prefix_members": members,
        "resume_ttft_affinity_s": aff["ttft_later_mean_s"],
        "resume_ttft_rr_s": rr["ttft_later_mean_s"],
        "resume_ttft_affinity_p50_s": aff["ttft_later_p50_s"],
        "resume_ttft_rr_p50_s": rr["ttft_later_p50_s"],
        "affinity_resume_speedup": (rr["ttft_later_mean_s"]
                                    / aff["ttft_later_mean_s"]
                                    if aff["ttft_later_mean_s"] else None),
        "turns_resumed_affinity": aff["turns_resumed"],
        "turns_resumed_rr": rr["turns_resumed"],
        "turns_expected_resumed": sessions * (turns - 1),
        "prefix_hit_rate_affinity": aff["prefix_hit_rate"],
        "prefix_hit_rate_rr": rr["prefix_hit_rate"],
        "routes_by_kind_affinity": aff["routes_by_kind"],
        "routes_by_kind_rr": rr["routes_by_kind"],
        "affinity_beats_rr_resume": (
            aff["ttft_later_mean_s"] is not None
            and rr["ttft_later_mean_s"] is not None
            and aff["ttft_later_mean_s"] < rr["ttft_later_mean_s"]),
        "affinity_beats_rr_prefix": (
            aff["prefix_hit_rate"] is not None
            and rr["prefix_hit_rate"] is not None
            and aff["prefix_hit_rate"] > rr["prefix_hit_rate"]),
        # the correctness half: identical greedy outputs across arms —
        # routing must be a latency lever, never a numerics one
        "outputs_match": aff["outputs"] == rr["outputs"],
        "finish_reasons_affinity": aff["finish_reasons"],
        "finish_reasons_rr": rr["finish_reasons"],
        "note": ("same deterministic workload both arms; CPU absolute "
                 "TTFT is interpreter mechanics — the affinity/rr "
                 "deltas are the recompute and cache misses routing "
                 "avoids"),
    }


# ---------------------------------------------------------------------------
# router_failover


def run_failover(sessions: int) -> dict:
    import numpy as np

    from tpudist.runtime import faults

    model = _model()
    router, reps = _build_fleet(model, policy="affinity")
    rng = np.random.default_rng(3)
    contexts = {s: rng.integers(0, CFG["vocab"], size=24).astype(np.int32)
                for s in range(sessions)}
    try:
        parks0 = _tier_parks(reps)
        handles = [(s, router.submit(contexts[s], max_new=6,
                                     session=f"f{s}", tenant="bench"))
                   for s in range(sessions)]
        for s, h in handles:
            assert h.wait(600)
            contexts[s] = np.concatenate(
                [contexts[s], np.asarray(h.tokens, np.int32)])
        assert _wait(lambda: _tier_parks(reps) >= parks0 + sessions)
        assert _wait(lambda: router.stats()["stash_entries"] >= sessions), \
            "router stash did not fill"
        homes = [router._session_home[("bench", f"f{s}")]
                 for s in range(sessions)]
        victim = max(set(homes), key=homes.count)
        on_victim = homes.count(victim)
        faults.arm(f"replica_kill@nth:{victim}")
        t_kill = time.monotonic()
        assert _wait(lambda: router.stats()["replicas_up"] == 1, 10.0), \
            "router never detected the killed replica"
        detect_s = time.monotonic() - t_kill
        faults.disarm()
        ttfts, resumed = [], 0
        for s in range(sessions):
            new = rng.integers(0, CFG["vocab"], size=4).astype(np.int32)
            h = router.submit(np.concatenate([contexts[s], new]),
                              max_new=6, session=f"f{s}", tenant="bench")
            assert h.wait(600), "post-kill turn timed out"
            ttfts.append(h.ttft_s)
            resumed += h.finish_reason == "session_resumed"
        stats = router.stats()
    finally:
        faults.disarm()
        router.close(60)
    return {
        "rung": "router_failover",
        "regime": "cpu-smoke",
        "replicas": 2,
        "sessions": sessions,
        "sessions_on_victim": on_victim,
        "detect_latency_s": detect_s,
        "migrations": stats["migrations"],
        "replica_deaths": stats["replica_deaths"],
        "turns_resumed_after_kill": resumed,
        "all_resumed_after_kill": resumed == sessions,
        "post_kill_ttft_mean_s": _mean(ttfts),
        "lost": stats["lost"],
        "fleet_kept_serving": stats["lost"] == 0,
        "note": ("replica_kill@nth fires from the router's own tick "
                 "thread; every session homed on the victim migrates "
                 "via the stash and its next turn still resumes on the "
                 "survivor — no request finishes replica_lost"),
    }


# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--smoke", action="store_true",
                   help="CI scale (tiny counts; same rung structure)")
    p.add_argument("--sessions", type=int, default=None,
                   help="sessions per twin arm (kept ODD so round-robin "
                        "actually ping-pongs each session every turn)")
    p.add_argument("--turns", type=int, default=None)
    p.add_argument("--groups", type=int, default=None)
    p.add_argument("--out", default=None)
    args = p.parse_args(argv)

    sessions = args.sessions or (5 if args.smoke else 9)
    if sessions % 2 == 0:
        sessions += 1  # odd, so rr flips every session's replica per turn
    turns = args.turns or (3 if args.smoke else 4)
    groups = args.groups or (3 if args.smoke else 6)
    members = 3 if args.smoke else 4

    # hermetic in-process (the tier-1 smoke test calls main() directly):
    # silence the post-hoc stream unless the caller routed it somewhere
    saved_tel = os.environ.get("TPUDIST_TELEMETRY")
    if "TPUDIST_TELEMETRY_DIR" not in os.environ:
        os.environ["TPUDIST_TELEMETRY"] = "0"
    rows = []
    try:
        rows.append(run_affinity_twin(sessions, turns, groups, members))
        print(json.dumps(rows[-1]))
        rows.append(run_failover(4 if args.smoke else 6))
        print(json.dumps(rows[-1]))
    finally:
        if saved_tel is None:
            os.environ.pop("TPUDIST_TELEMETRY", None)
        else:
            os.environ["TPUDIST_TELEMETRY"] = saved_tel
    if args.out:
        out = Path(args.out)
        out.parent.mkdir(parents=True, exist_ok=True)
        with out.open("w") as f:
            for r in rows:
                # the artifact drops the per-token output dump (it is
                # only for the cross-arm equality check)
                slim = {k: v for k, v in r.items() if k != "outputs"}
                f.write(json.dumps(slim) + "\n")
        print(json.dumps({"wrote": str(out)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
