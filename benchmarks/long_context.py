#!/usr/bin/env python3
"""Long-context throughput harness: tokens/sec for the ring-attention LM
as sequence length and the ``seq`` mesh axis grow.

Demonstrates the point of sequence parallelism: per-chip attention memory
is O(seq/ring), so doubling the ring doubles the reachable context at
constant memory.  On virtual CPU devices the numbers validate mechanics
only (labeled in the output); on a pod they are hardware truth.

Usage:
  python benchmarks/long_context.py --seq-lens 512,1024 --seq-shards 1,4
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax

from tpudist.runtime.compilation_cache import enable_compilation_cache

enable_compilation_cache()
import numpy as np
import optax
from jax.sharding import Mesh


def measure(seq_len: int, seq_shards: int, *, batch: int, steps: int,
            d_model: int, n_layers: int, window=None) -> dict:
    from tpudist.models import create_transformer
    from tpudist.parallel import make_ring_attention
    from tpudist.runtime.mesh import AXIS_DATA, AXIS_SEQ
    from tpudist.train import init_lm_state, make_lm_train_step, token_sharding

    devices = jax.devices()
    if seq_shards > len(devices) or len(devices) % seq_shards:
        raise ValueError(f"{seq_shards} seq shards on {len(devices)} devices")
    # Data axis: the largest divisor of the batch that fits the remaining
    # devices (a seq_shards=1 rung must not demand batch % all_devices == 0).
    data_size = len(devices) // seq_shards
    while batch % data_size:
        data_size -= 1
    mesh = Mesh(
        np.asarray(devices[: data_size * seq_shards]).reshape(
            data_size, seq_shards
        ),
        axis_names=(AXIS_DATA, AXIS_SEQ),
    )
    attention = (
        make_ring_attention(mesh, causal=True, batch_axis=AXIS_DATA,
                            window=window)
        if seq_shards > 1 else None
    )
    module, params = create_transformer(
        jax.random.PRNGKey(0), seq_len=seq_len, attention_fn=attention,
        vocab=256, d_model=d_model, n_layers=n_layers, max_len=seq_len,
        sliding_window=window if seq_shards == 1 else None,
    )
    tx = optax.adam(3e-4)
    state = init_lm_state(params, tx)
    step = make_lm_train_step(module.apply, tx, mesh)

    tokens = jax.device_put(
        np.random.default_rng(0).integers(
            0, 256, size=(batch, seq_len)
        ).astype(np.int32),
        token_sharding(mesh),
    )
    # Sync via value fetch — block_until_ready can return before remote
    # execution finishes on tunneled platforms (see bench.py).
    for _ in range(2):
        state, loss = step(state, tokens)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        state, loss = step(state, tokens)
    float(loss)
    dt = time.perf_counter() - t0

    from tpudist.utils import chip_peak_flops, mfu, transformer_train_flops

    flops = transformer_train_flops(
        batch=batch, seq_len=seq_len, d_model=d_model, n_layers=n_layers,
        d_ff=module.d_ff, vocab=module.vocab, window=window,
    )
    util = mfu(flops, dt / steps, data_size * seq_shards, chip_peak_flops())
    return {
        "seq_len": seq_len,
        "seq_shards": seq_shards,
        "tokens_per_sec": round(batch * seq_len * steps / dt, 1),
        "model_flops_per_step": flops,
        "mfu_pct": round(util * 100, 2) if util is not None else None,
        "window": window,
        "block_per_chip": seq_len // seq_shards,
        "regime": "virtual-cpu" if devices[0].platform == "cpu" else "hardware",
    }


def main(argv=None) -> list:
    p = argparse.ArgumentParser()
    p.add_argument("--seq-lens", default="512,1024")
    p.add_argument("--seq-shards", default="1,2,4")
    p.add_argument("--batch", default=4, type=int)
    p.add_argument("--steps", default=8, type=int)
    p.add_argument("--d-model", default=128, type=int)
    p.add_argument("--n-layers", default=2, type=int)
    p.add_argument("--sliding-window", default=None, type=int,
                   help="sliding-window attention: the ring stops at the "
                        "window, so tokens/sec should hold as seq grows")
    args = p.parse_args(argv)
    if args.sliding_window is not None and args.sliding_window < 1:
        p.error(f"--sliding-window must be >= 1, got {args.sliding_window}")

    results = []
    for s in (int(x) for x in args.seq_lens.split(",")):
        for r in (int(x) for x in args.seq_shards.split(",")):
            try:
                res = measure(s, r, batch=args.batch, steps=args.steps,
                              d_model=args.d_model, n_layers=args.n_layers,
                              window=args.sliding_window)
            except ValueError as e:
                print(f"# skip seq={s} shards={r}: {e}", file=sys.stderr)
                continue
            results.append(res)
            print(json.dumps(res))
    return results


if __name__ == "__main__":
    main()
