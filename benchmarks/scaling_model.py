#!/usr/bin/env python3
"""Analytic multi-chip scaling model — the numbers half of the no-pod
scaling story (VERDICT r3 weak #2; the structure half is
``benchmarks/comm_audit.py``).

The compile-time collective audit proves WHAT moves per step (one
combined gradient all-reduce of exactly param+loss bytes under DP, ring
permutes of one KV shard per hop under sp, …).  This model combines
those audited byte volumes with measured single-chip step times
(``BENCH_EXTENDED.json``) and stated link-bandwidth assumptions
(:mod:`tpudist.utils.flops`) to produce falsifiable predictions:

- DP efficiency vs chip count, with and without compute/communication
  overlap (XLA overlaps the grad all-reduce with the backward; the
  no-overlap row is the hard floor);
- the spec-independent inverse: the per-chip wire bandwidth REQUIRED
  for the >=80% DP-scaling north star (``BASELINE.json``) at each n —
  robust to uncertainty in the assumed link numbers;
- ring-attention sp: per-hop communication vs per-hop compute ratio
  (the ring overlaps hops with block compute; ratio < 1 means the ICI
  hop fully hides).

Model (ring all-reduce over one mesh axis): per-chip wire bytes
``2(n-1)/n x payload``, transferred concurrently on the ring's links, so
``t_comm = wire / link_bw``; with overlap the exposed time is
``max(0, t_comm - t_bwd)`` with ``t_bwd ~ 2/3 t_step`` (the backward is
2/3 of the 3x-forward train step and is where XLA schedules the grad
reduce-scatter/all-reduce).

Writes ``SCALING_MODEL_r{NN}.json`` (round auto-detected).  Every input is recorded in the
artifact so the prediction is checkable the day a pod exists.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def _force_cpu() -> None:
    """Pure-analytic script: never let the axon plugin touch the (maybe
    wedged) tunnel — eval_shape needs no accelerator.

    The env var alone is swallowed by the bench environment's
    sitecustomize (it re-forces the platform via jax.config), so the
    config update is the one that counts; it is unconditional — in this
    script's normal life (a fresh process) backends are never up yet, and
    when embedded in a live-jax process (tests) the failing update is
    correctly ignored (the embedder's platform stands)."""
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass


def _param_bytes_lm(*, d_model, n_layers, n_heads, d_ff, vocab, seq_len):
    """Parameter bytes of the bench TransformerLM via eval_shape (no
    materialization — fine for the d1024 config on CPU)."""
    import jax

    from tpudist.models import create_transformer
    from tpudist.utils.hlo_audit import tree_bytes

    def init():
        _, params = create_transformer(
            jax.random.PRNGKey(0), seq_len=seq_len, vocab=vocab,
            d_model=d_model, n_layers=n_layers, n_heads=n_heads, d_ff=d_ff,
            max_len=seq_len)
        return params

    shapes = jax.eval_shape(init)
    return tree_bytes(shapes)


# The toy DP per-step collective payload: 2 models x 371 f32 param-grads
# + 2 f32 loss scalars.  tests/test_comm_audit.py asserts the compiled
# HLO's all-reduce total equals exactly this constant.
TOY_GRAD_BYTES = 2 * 371 * 4 + 2 * 4


def dp_rows(name, *, grad_bytes, step_s, link_bw, target=0.8,
            ns=(2, 4, 8, 16, 64, 256)):
    """Efficiency vs n for a DP regime whose audited per-step payload is
    ``grad_bytes`` (f32 grads + loss scalars; the audit pins this)."""
    t_bwd = step_s * 2.0 / 3.0
    rows = []
    for n in ns:
        wire = 2 * (n - 1) / n * grad_bytes
        t_comm = wire / link_bw
        exposed = max(0.0, t_comm - t_bwd)
        rows.append({
            "n_chips": n,
            "wire_bytes_per_chip": int(wire),
            "t_comm_ms": round(t_comm * 1e3, 4),
            "efficiency_no_overlap": round(step_s / (step_s + t_comm), 4),
            "efficiency_overlap": round(step_s / (step_s + exposed), 4),
            # Spec-independent: bandwidth needed for `target` efficiency
            # with NO overlap (the conservative requirement).
            "bw_needed_for_target_GBps": round(
                wire / (step_s * (1 - target) / target) / 1e9, 3),
        })
    return {"regime": name, "grad_bytes": int(grad_bytes),
            "step_ms_single_chip": round(step_s * 1e3, 3),
            "assumed_link_bw_GBps": round(link_bw / 1e9, 1),
            "target_efficiency": target, "rows": rows}


def ring_sp_row(*, name, batch, heads, seq, head_dim, ring, link_bw,
                peak_flops, mfu_measured, dtype_bytes=2, kv_heads=None):
    """Ring attention over `ring` chips: per-hop KV bytes vs per-hop
    compute.  The audit pins the payload (one KV shard per hop per
    tensor); the per-hop compute is the flash block attention over one
    shard, estimated from measured MFU.  Only the attention geometry
    (batch·heads·shard·head_dim) and achieved FLOPs drive this — the
    rest of the model never rides the ring."""
    shard = seq // ring
    # GQA: the ring hops the small kv-headed tensors (ring bodies are
    # GQA-native — broadcast happens post-hop), so the wire scales with
    # kv_heads while compute still scales with query heads.
    kv_hop_bytes = (2 * batch * (kv_heads or heads) * shard * head_dim
                    * dtype_bytes)
    # Per-hop attention FLOPs (fwd): one [shard x shard] block of the
    # score+value matmuls for every query shard position.
    hop_flops = 4.0 * batch * heads * shard * shard * head_dim
    achieved = peak_flops * mfu_measured
    t_hop_compute = hop_flops / achieved
    t_hop_comm = kv_hop_bytes / link_bw
    return {
        "regime": name, "ring": ring, "seq": seq, "seq_shard": shard,
        "kv_hop_bytes": int(kv_hop_bytes),
        "t_hop_comm_us": round(t_hop_comm * 1e6, 2),
        "t_hop_compute_us": round(t_hop_compute * 1e6, 2),
        "comm_over_compute": round(t_hop_comm / t_hop_compute, 4),
        "hides_under_compute": t_hop_comm < t_hop_compute,
        "assumptions": {
            "achieved_flops": achieved, "mfu_measured": mfu_measured,
            "link_bw_GBps": round(link_bw / 1e9, 1),
            "dtype_bytes": dtype_bytes},
    }


def ring_causal_balance_row(ring: int) -> dict:
    """Schedule FLOP efficiency of the causal ring, contiguous vs zigzag.

    Hops are ppermute-lockstepped, so a hop lasts one block-compute
    whenever ANY device is live.  Contiguous layout: hop ``t`` keeps
    ``n−t`` devices live → useful/executed = (n+1)/2n → ½ as n grows.
    Zigzag (``zigzag_indices`` layout, tests assert the per-hop balance):
    every device executes 2 half-blocks per hop (+1 at the diagonal hop,
    whose two triangular blocks run as fulls) → 2n/(2n+1) → 1.  Pure
    schedule math — no bandwidth assumptions; the comm side is identical
    to the contiguous ring (same payload, same hop count)."""
    n = ring
    contiguous = (n + 1) / (2 * n)
    zigzag = (2 * n) / (2 * n + 1)
    return {"ring": n,
            "contiguous_schedule_efficiency": round(contiguous, 4),
            "zigzag_schedule_efficiency": round(zigzag, 4),
            "zigzag_speedup": round(zigzag / contiguous, 3)}


def main() -> int:
    _force_cpu()
    from tpudist.utils.flops import (
        DCN_HOST_BYTES_PER_S,
        ICI_LINK_BYTES_PER_S,
        PEAK_BF16_FLOPS,
    )

    # Measured single-chip inputs: the last on-chip record
    # (BENCH_EXTENDED.json, round 2 — re-frozen when the tunnel returns).
    # The spec lookups key off the RECORDED device kind so re-freezing on
    # a different generation can never pair its step times with another
    # chip's link/peak numbers.
    ext = json.loads((REPO / "BENCH_EXTENDED.json").read_text())
    kind = ext.get("device_kind", "TPU v5 lite")
    if kind not in ICI_LINK_BYTES_PER_S or kind not in PEAK_BF16_FLOPS:
        raise SystemExit(
            f"no link/peak specs for recorded device kind {kind!r} — add "
            f"them to tpudist/utils/flops.py before modeling")
    link_bw = ICI_LINK_BYTES_PER_S[kind]
    peak = PEAK_BF16_FLOPS[kind]

    def step_s(key):
        row = ext.get(key) or {}
        ms = row.get("step_ms")
        return ms / 1e3 if ms else None

    out = {
        "inputs": {
            "device_kind": kind,
            "assumed_ici_link_GBps": link_bw / 1e9,
            "assumed_dcn_host_GBps": DCN_HOST_BYTES_PER_S / 1e9,
            "peak_bf16_tflops": peak / 1e12,
            "measured_from": "BENCH_EXTENDED.json",
            "audited_by": (max((p.name for p in
                                REPO.glob("COMM_AUDIT_r*.json")),
                               default="COMM_AUDIT (none found)")),
        },
        "dp": [],
        "sp_ring": [],
    }

    # --- DP regimes ------------------------------------------------------
    # Toy (the reference workload, demo.py): 2 models x 371 params, f32
    # grads + 2 loss scalars — exactly the audit's all-reduce payload.
    toy = ext.get("toy", {})
    if toy.get("value"):
        # batch 256/chip at the measured rate -> per-step seconds.
        t = 256.0 / toy["value"]
        out["dp"].append(dp_rows("toy_dp_batch256",
                                 grad_bytes=TOY_GRAD_BYTES,
                                 step_s=t, link_bw=link_bw))

    for key, cfg in (
        ("lm_dense_bf16", dict(d_model=512, n_layers=4, n_heads=8,
                               d_ff=2048, vocab=256, seq_len=2048)),
        ("lm_mfu_d1024", dict(d_model=1024, n_layers=8, n_heads=8,
                              d_ff=4096, vocab=256, seq_len=2048)),
    ):
        t = step_s(key)
        if t is None:
            continue
        pb = _param_bytes_lm(**cfg)
        out["dp"].append(dp_rows(
            f"{key}_dp", grad_bytes=pb + 4, step_s=t, link_bw=link_bw))
        # Same regime with the data axis over DCN (hybrid mesh, one ring
        # hop per host): per-HOST bandwidth, conservative 1 chip/host...
        # real pods amortize over 4-8 chips/host; recorded as the floor.
        out["dp"].append(dp_rows(
            f"{key}_dp_dcn_floor", grad_bytes=pb + 4, step_s=t,
            link_bw=DCN_HOST_BYTES_PER_S))
        # grad_reduce_dtype=bf16 (tpudist/train/lm.py compressed path,
        # audited in COMM_AUDIT dp_bf16_reduce): grads ride at 2 bytes.
        out["dp"].append(dp_rows(
            f"{key}_dp_dcn_bf16_reduce", grad_bytes=pb // 2 + 4,
            step_s=t, link_bw=DCN_HOST_BYTES_PER_S))

    # --- sp ring ---------------------------------------------------------
    lc = ext.get("lm_long_context_bf16", {})
    lc_mfu = (lc.get("mfu_pct_vs_bf16_peak") or 18.0) / 100.0
    # ring=16 included deliberately: per-hop compute shrinks as shard²
    # while comm shrinks as shard, so the ratio grows ∝ ring — the model
    # must show where hops STOP hiding, not just the friendly regime.
    for ring in (2, 4, 8, 16):
        out["sp_ring"].append(ring_sp_row(
            name="lm_long_context_bf16_sp", batch=4, heads=4, seq=8192,
            head_dim=64, ring=ring,
            link_bw=link_bw, peak_flops=peak, mfu_measured=lc_mfu))
        # GQA at group 2: half the hop bytes, same compute — the
        # crossover where hops stop hiding moves out ~2 x in ring size.
        out["sp_ring"].append(ring_sp_row(
            name="lm_long_context_bf16_sp_gqa2", batch=4, heads=4,
            kv_heads=2, seq=8192, head_dim=64, ring=ring,
            link_bw=link_bw, peak_flops=peak, mfu_measured=lc_mfu))

    # --- causal-balance (layout) ----------------------------------------
    out["sp_ring_causal_balance"] = [
        ring_causal_balance_row(r) for r in (2, 4, 8, 16)]

    from benchmarks._round import current_round  # REPO is on sys.path

    path = REPO / f"SCALING_MODEL_r{current_round():02d}.json"
    path.write_text(json.dumps(out, indent=2) + "\n")
    # Human-readable headline.
    for d in out["dp"]:
        r8 = next((r for r in d["rows"] if r["n_chips"] == 8), None)
        if r8:
            print(f"{d['regime']:28s} n=8: eff(no-ovl)="
                  f"{r8['efficiency_no_overlap']:.3f} eff(ovl)="
                  f"{r8['efficiency_overlap']:.3f} "
                  f"bw needed for 80%: {r8['bw_needed_for_target_GBps']} GB/s")
    for s in out["sp_ring"]:
        print(f"{s['regime']:28s} ring={s['ring']}: comm/compute="
              f"{s['comm_over_compute']:.3f} "
              f"({'hides' if s['hides_under_compute'] else 'EXPOSED'})")
    print(json.dumps({"out": str(path), "dp_regimes": len(out["dp"]),
                      "sp_rows": len(out["sp_ring"])}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
