#!/usr/bin/env python3
"""Structured-output bench: the grammar-constrained decode rungs,
frozen per round as ``BENCH_GRAMMAR_r{NN}.json``.

One rung family, CPU-safe (tiny model; absolute tok/s is interpreter
mechanics — the RATIOS between arms on one engine are the measurement):

- **grammar_mixed_batch** — the SAME engine, the SAME request schedule
  (every slot decoding a full budget), swept over constrained lanes per
  batch ∈ {0 (free), S/2 (mixed), S (all constrained)}: each arm binds
  its grammars, decodes to budget, and evicts — so the sweep ALSO
  drives the registry bind/release churn path (more distinct grammars
  than pool blocks forces LRU eviction between arms).  Quotes decode
  throughput per arm and the constrained-vs-free per-token overhead:
  the claim is that the in-graph mask gather costs a bounded, flat
  per-token increment (one ``[S, V]`` row gather + a ``where`` on the
  logits), not a per-token host round-trip.  The artifact freezes:

  - ``streams_in_grammar`` — every constrained stream (truncated at
    eos) walks its automaton to a live state (correctness rides along
    with the measurement);
  - ``free_lanes_unperturbed`` — the free lanes of the mixed arm are
    byte-identical to the same slots of the all-free arm: sharing a
    batch with constrained neighbours must not perturb free sampling;
  - ``constrained_vs_free`` / ``overhead_per_token_us`` — the
    throughput quote, with a two-probe ``noise_floor`` for context
    (the arms are CPU-timed; the floor says how much of the delta is
    run-to-run jitter);
  - ``compile_pins_flat`` — jit-cache sizes identical after the whole
    bind/decode/evict grammar churn vs after warmup (zero
    recompilation as grammars churn — constraint state is DATA).

Usage: ``python benchmarks/grammar_bench.py [--smoke] [--out PATH]``
(round_snapshot.py freezes it per round; the tier-1 smoke test asserts
the rung fields).
"""

from __future__ import annotations

import argparse
import json
import re as _re
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

CFG = dict(vocab=32, d_model=32, n_layers=2, n_heads=2, d_ff=64,
           max_len=96)
EOS = 1


def _model(seed: int = 0):
    import jax

    from tpudist.models import create_transformer

    return create_transformer(jax.random.PRNGKey(seed), seq_len=16, **CFG)


def _grammars(vocab, n: int, max_states: int):
    """``n`` DISTINCT single-char-class grammars over the synthetic
    vocab (distinct keys → distinct registry entries → real churn)."""
    from tpudist.constrain import compile_grammar

    chars = sorted({w for w in vocab if w})
    out = []
    for i in range(n):
        cls = "".join(_re.escape(c)
                      for c in chars[3 * i:3 * i + 3] or chars[:3])
        out.append(compile_grammar(regex="[%s]{2,12}" % cls, vocab=vocab,
                                   eos_id=EOS, max_states=max_states))
    return out


def _run_arm(eng, prompts, budgets, grammars_by_slot):
    """Fill every slot, decode everything to budget, return
    ``(streams, decode_wall_s, decode_tokens)`` — wall measured over the
    decode blocks only (admission/prefill excluded: the sweep compares
    DECODE throughput, the hot path the mask gather sits on)."""
    items = []
    for slot, (p, b, tg) in enumerate(
            zip(prompts, budgets, grammars_by_slot)):
        items.append((slot, p, 0.8, slot, b, (), True, None, tg))
    streams = {s: [] for s in range(len(prompts))}
    for slot, tok in eng.start_batch(items).items():
        if tok is not None:
            streams[slot].append(tok)
    while eng.prefilling_slots():
        for slot, tok in eng.advance_prefill().items():
            streams[slot].append(tok)
    wall = 0.0
    tokens = 0
    while eng.num_active:
        t0 = time.perf_counter()
        _, blocks = eng.decode_block()
        wall += time.perf_counter() - t0
        for slot, toks in blocks.items():
            streams[slot].extend(toks)
            tokens += len(toks)
        for slot in list(range(eng.num_slots)):
            if eng.occupied[slot] and eng.decoding[slot] \
                    and eng.counts[slot] >= eng.budget[slot]:
                eng.evict(slot)
    return streams, wall, tokens


def run_sweep(*, slots: int, max_new: int, smoke: bool) -> dict:
    import jax
    import numpy as np

    from tpudist.constrain import (ConstrainConfig, compile_cache_stats,
                                   default_vocab)
    from tpudist.serve import SlotEngine

    module, params = _model()
    vocab = default_vocab(CFG["vocab"], EOS)
    max_states = 16
    # more distinct grammars than pool blocks: the constrained and
    # mixed arms then cannot coexist in the pool, so the sweep drives
    # the LRU release/evict path, not just first-bind
    n_grammars = 4
    tgs = _grammars(vocab, n_grammars, max_states)
    ccfg = ConstrainConfig(vocab=vocab, num_blocks=2,
                           max_states=max_states)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(2, CFG["vocab"], size=6).astype(np.int32)
               for s in range(slots)]
    budgets = [max_new] * slots
    eng = SlotEngine(module, params, num_slots=slots, prefill_pad=8,
                     decode_block=8, paged=True, kv_block=8,
                     constrain=ccfg)

    def arm(n_constrained: int, pair: int):
        # each arm round-robins its constrained lanes over ONE pair of
        # grammars (a pair fills the 2-block pool exactly); successive
        # arms use different pairs, so the pool must evict between arms
        bound = [(tgs[(2 * pair + (s % 2)) % n_grammars]
                  if s < n_constrained else None) for s in range(slots)]
        return (*_run_arm(eng, prompts, budgets, bound), bound)

    # warmup: one mixed arm pays every XLA compile (the twin-delta
    # discipline — first-compile must not land in any measured arm; the
    # grammar tail rides every program whenever constrain= is set, so
    # free and constrained arms share the SAME compiled code)
    arm(max(1, slots // 2), 0)
    pins0 = dict(eng.compile_counts())
    # noise probe: the free arm run twice back-to-back — the ratio of
    # the two runs is pure run-to-run jitter at this shape, quoted next
    # to the overhead so a CPU-noise delta can't be misread as mask cost
    _, nw, nt, _ = arm(0, 0)
    probe_tps = (nt / nw) if nw else None

    arms = []
    streams_by_arm = {}
    for name, k, pair in (("free", 0, 0),
                          ("mixed", max(1, slots // 2), 1),
                          ("constrained", slots, 0)):
        streams, wall, tokens, bound = arm(k, pair)
        streams_by_arm[name] = (streams, bound)
        arms.append({"arm": name, "constrained_lanes": k,
                     "decode_tokens": tokens,
                     "decode_wall_s": round(wall, 6),
                     "tokens_per_s":
                         round(tokens / wall, 2) if wall else None})
    pins1 = dict(eng.compile_counts())

    # correctness rides along: every constrained stream, truncated at
    # eos, walks its automaton to a live state
    streams_in_grammar = True
    for name, (streams, bound) in streams_by_arm.items():
        for s, tg in enumerate(bound):
            if tg is None:
                continue
            ts = streams[s]
            ts = ts[:ts.index(EOS)] if EOS in ts else ts
            if tg.walk(ts) is None:
                streams_in_grammar = False
    free_streams = streams_by_arm["free"][0]
    mixed_streams, mixed_bound = streams_by_arm["mixed"]
    free_lanes_unperturbed = all(
        mixed_streams[s] == free_streams[s]
        for s, tg in enumerate(mixed_bound) if tg is None)

    by = {a["arm"]: a["tokens_per_s"] for a in arms}
    free_tps, con_tps = by["free"], by["constrained"]
    noise_floor = (round(min(free_tps, probe_tps)
                         / max(free_tps, probe_tps), 4)
                   if free_tps and probe_tps else 1.0)
    return {
        "rung": "grammar_mixed_batch",
        "regime": "cpu" if jax.devices()[0].platform != "tpu" else "tpu",
        "note": ("tiny-model CPU mechanics — the cross-arm RATIOS on one "
                 "engine are the measurement, absolute tok/s is not"),
        "slots": slots, "max_new": max_new,
        "grammar_states": max_states, "n_grammars": n_grammars,
        "pool_blocks": 2,
        "smoke": bool(smoke),
        "arms": arms,
        "free_tokens_per_s": free_tps,
        "constrained_tokens_per_s": con_tps,
        "constrained_vs_free":
            round(con_tps / free_tps, 4) if free_tps else None,
        "overhead_per_token_us":
            (round((1.0 / con_tps - 1.0 / free_tps) * 1e6, 3)
             if free_tps and con_tps else None),
        "noise_floor": noise_floor,
        "streams_in_grammar": streams_in_grammar,
        "free_lanes_unperturbed": free_lanes_unperturbed,
        "compile_pins_flat": pins0 == pins1,
        "constrain_stats": {
            k: v for k, v in eng.constrain_stats().items()
            if k in ("blocks", "max_states", "pool_bytes", "binds",
                     "evictions", "resident", "pinned")},
        "compile_cache": compile_cache_stats(),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="CI-sized sweep (fewer decode tokens)")
    ap.add_argument("--out", default=None, help="output JSONL path")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--max-new", type=int, default=None)
    args = ap.parse_args(argv)
    max_new = args.max_new or (16 if args.smoke else 48)
    row = run_sweep(slots=args.slots, max_new=max_new, smoke=args.smoke)
    line = json.dumps(row)
    print(line)
    if args.out:
        Path(args.out).write_text(line + "\n")
        print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
