#!/usr/bin/env python3
"""Serving load generator: offered load vs. achieved goodput.

Drives the continuous-batching server (``tpudist.serve``) with synthetic
open-loop traffic — Poisson arrivals at each offered rate, prompt and
output lengths drawn per-request from seeded ranges — and records what
the paper-facing serving questions need:

- **throughput vs. offered load** (achieved requests/s and tokens/s per
  rate rung, including the saturation rung where offered >> capacity);
- **latency percentiles** — TTFT (submit → first token, queue wait
  included) and TPOT (steady decode interval) at p50/p95;
- **the dispatch-overhead split** — wall TPOT vs device-busy TPOT per
  rung (``tpot_busy_s`` = decode dispatch+sync seconds / tokens), plus
  dispatches-per-token and host-sync-per-token, from the engine's
  decode counters: the quantities the fused ``decode_block`` hot path
  exists to shrink;
- **the block-size sweep** — one burst rung per
  ``TPUDIST_SERVE_DECODE_BLOCK`` value (default 1/4/8/16), isolating
  how token-block fusion moves throughput and overhead;
- **batch occupancy** — the utilization gauge continuous batching exists
  to raise (sequential serving pins it at 1/num_slots);
- **backpressure** — rejected counts once the bounded queue overflows;
- **the paged-KV capacity rung** — dense arena at S slots vs paged block
  pool at 4S slots holding the SAME pool bytes, under high-churn
  mixed-length load: the decoupling of slot count from ``max_len`` is
  the whole point of the paged cache (CPU smoke proxies "equal HBM
  bytes-resident" as equal block-pool bytes);
- **the int8-KV sweep** — native vs int8 KV storage at the same
  geometry/load: resident bytes-per-position ratio and throughput, the
  bytes/token lever for bandwidth-bound decode;
- **sharded serving** (``--mesh DxM`` [+ ``--tp-overlap``]) — every
  in-process rung serves SPMD over a serving mesh
  (``tpudist/serve/spmd.py``); the artifact records the mesh geometry
  and the sharded-param accounting;
- **disaggregated serving** (``--disagg``) — rungs serve through the
  prefill/decode coordinator (``tpudist/serve/disagg.py``): per-rung
  handoff counts/bytes/wait percentiles, and the embedded serving
  report splits TTFT (prefill pool) from TPOT (decode pool);
- **the multi-process serve rung** (``--multiproc N``) — N
  tpurun-launched workers, each a disaggregated server SPMD over its
  own ``--devices-per-proc``-emulated mesh with SERIALIZED KV handoff
  (the cross-process transfer), merged per-pool serving report
  embedded.  ``round_snapshot.py`` freezes this rung into the round's
  ``BENCH_SERVE`` artifact.

One warmup request absorbs XLA compilation before any timed rung, so
rows measure the steady engine, not the first dispatch.  Artifact:
``BENCH_SERVE_r{NN}.json`` (round-frozen like every other harness — and
snapshotted into the round scoreboard by ``round_snapshot.py``), with
the run's merged telemetry serving section embedded for cross-checking.
``--smoke`` shrinks everything to a CPU-CI scale (seconds, asserted by
``tests/test_benchmarks.py``).
"""

from __future__ import annotations

import json
import statistics
import sys
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def _pct(vals, q):
    """Nearest-rank percentile — the SAME statistic the telemetry
    report's serving section uses, so the artifact's per-rung columns and
    its embedded ``serving_report`` cross-check without definitional
    skew."""
    if not vals:
        return None
    from tpudist.telemetry.aggregate import _percentile

    return _percentile(sorted(vals), q)


def _ensure_devices(n: int) -> None:
    """Emulate ``n`` CPU devices when the backend is not yet up (the
    comm_audit trick) — standalone ``--mesh``/``--disagg`` runs need
    them; under pytest the conftest's 8-device mesh is already live."""
    import jax

    try:
        from jax._src import xla_bridge as _xb

        backend_up = _xb.backends_are_initialized()
    except Exception:
        backend_up = True
    if not backend_up:
        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", max(n, 2))
        except AttributeError:
            import os

            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + f" --xla_force_host_platform_device_count"
                            f"={max(n, 2)}").strip()


def _server_decode_stats(server) -> dict:
    """Cumulative decode counters for either server shape (the disagg
    coordinator sums its decode pool)."""
    if hasattr(server, "decode_pool"):
        return server.stats()["decode_pool"]["decode"]
    return server.engine.decode_stats()


def _server_kv(server) -> dict:
    if hasattr(server, "decode_pool"):
        return server.stats()["decode_pool"]["kv"]
    return server.stats()["kv"]


def _server_compile_counts(server) -> dict:
    if hasattr(server, "decode_pool"):
        st = server.stats()
        return {"prefill_pool": st["prefill_pool"]["compile_counts"],
                "decode_pool": st["decode_pool"]["compile_counts"]}
    return server.stats()["compile_counts"]


def run_rate(server, *, rate_rps: float, n_requests: int, vocab: int,
             prompt_lens, max_news, seed: int) -> dict:
    """One offered-load rung: open-loop Poisson arrivals at ``rate_rps``
    (``inf``-like rates degenerate to a burst), wait for completion."""
    import numpy as np

    from tpudist.serve import AdmissionError

    rng = np.random.default_rng(seed)
    handles, rejected = [], 0
    lock = threading.Lock()

    def submit_all():
        nonlocal rejected
        for i in range(n_requests):
            plen = int(rng.integers(prompt_lens[0], prompt_lens[1] + 1))
            max_new = int(rng.integers(max_news[0], max_news[1] + 1))
            prompt = rng.integers(0, vocab, size=plen).astype(np.int32)
            try:
                h = server.submit(prompt, max_new=max_new, seed=i)
                with lock:
                    handles.append(h)
            except AdmissionError:
                rejected += 1
            if rate_rps < 1e6:
                time.sleep(float(rng.exponential(1.0 / rate_rps)))

    d0 = _server_decode_stats(server)
    h0 = _server_handoff_stats(server)
    t0 = time.monotonic()
    loader = threading.Thread(target=submit_all, daemon=True)
    loader.start()
    loader.join()
    for h in handles:
        h.wait()
    wall = time.monotonic() - t0
    d1 = _server_decode_stats(server)
    h1 = _server_handoff_stats(server)

    ttfts = [h.ttft_s for h in handles if h.ttft_s is not None]
    tpots = [h.tpot_s for h in handles if h.tpot_s is not None]
    tokens = sum(len(h.tokens) for h in handles)
    # the dispatch-overhead split: wall TPOT (the caller's experience)
    # vs device-busy TPOT (decode dispatch + the blocking token fetch,
    # per emitted token) — the gap is host/scheduler overhead the fused
    # decode block amortizes
    blocks = d1["blocks"] - d0["blocks"]
    dtok = d1["tokens"] - d0["tokens"]
    busy = ((d1["dispatch_s"] - d0["dispatch_s"])
            + (d1["sync_s"] - d0["sync_s"]))
    sync = d1["sync_s"] - d0["sync_s"]
    return {
        "offered_rps": rate_rps if rate_rps < 1e6 else "burst",
        "n_requests": n_requests,
        "completed": len(handles),
        "rejected": rejected,
        "wall_s": round(wall, 3),
        "achieved_rps": round(len(handles) / wall, 3) if wall > 0 else None,
        "achieved_tokens_per_s": round(tokens / wall, 1) if wall > 0 else None,
        "tokens_out": tokens,
        "ttft_s_p50": round(_pct(ttfts, 50), 6) if ttfts else None,
        "ttft_s_p95": round(_pct(ttfts, 95), 6) if ttfts else None,
        "tpot_s_p50": round(_pct(tpots, 50), 6) if tpots else None,
        "tpot_s_p95": round(_pct(tpots, 95), 6) if tpots else None,
        "decode_blocks": blocks,
        "decode_tokens": dtok,
        "dispatches_per_token": round(blocks / dtok, 4) if dtok else None,
        "tpot_busy_s": round(busy / dtok, 6) if dtok else None,
        "host_sync_s_per_token": round(sync / dtok, 6) if dtok else None,
        "mean_tokens_per_request":
            round(statistics.mean([len(h.tokens) for h in handles]), 1)
            if handles else None,
        # KV residency accounting (paged: block pool; dense: the arena)
        "kv": _server_kv(server),
        # disaggregated serving only: the prefill→decode handoff story
        # (None columns on the single-pool server)
        **_handoff_cols(h0, h1, handles),
    }


def _server_handoff_stats(server):
    if not hasattr(server, "decode_pool"):
        return None
    st = server.stats()
    return {"handoffs": st["handoffs"], "bytes": st["handoff_bytes"]}


def _handoff_cols(h0, h1, handles) -> dict:
    if h0 is None or h1 is None:
        return {}
    waits = [h.handoff_wait_s for h in handles
             if h.handoff_wait_s is not None]
    # deltas, like the decode counters: the row must count THIS rung's
    # handoffs, not the server's cumulative total (warmup included)
    return {
        "handoffs": h1["handoffs"] - h0["handoffs"],
        "handoff_bytes": h1["bytes"] - h0["bytes"],
        "handoff_wait_s_p50": round(_pct(waits, 50), 6) if waits else None,
        "handoff_wait_s_p95": round(_pct(waits, 95), 6) if waits else None,
    }


#: Worker body of the multi-process serve rung: one disaggregated
#: server per process, SPMD over that process's emulated device mesh,
#: KV handoff serialized (the cross-process transfer stand-in), traffic
#: seeded per rank.  Launched via the tpurun agent exactly like a real
#: multi-host serving job; telemetry streams into a shared dir whose
#: merged serving report (per-pool TTFT/TPOT split) embeds in the
#: artifact row.
_SERVE_WORKER = """
import json, os, time

os.environ["JAX_PLATFORMS"] = "cpu"
# device count per process comes from tpurun --devices-per-proc

import numpy as np
import jax

from tpudist import telemetry
from tpudist.models import create_transformer
from tpudist.serve import DisaggServer, ServeConfig

rank = int(os.environ.get("TPUDIST_PROCESS_ID", "0"))
requests = int(os.environ["SERVE_REQUESTS"])
mesh = os.environ.get("SERVE_MESH", "") or None
vocab = 64
telemetry.start(os.environ["SERVE_TELE"])
module, params = create_transformer(
    jax.random.PRNGKey(0), seq_len=16, vocab=vocab, d_model=32,
    n_layers=2, n_heads=2, d_ff=128, max_len=64)
cfg = ServeConfig(num_slots=2, queue_limit=max(64, requests), max_new=8,
                  prefill_pad=8, decode_block=4, disagg=True,
                  handoff="serial", mesh=mesh,
                  tp_overlap=os.environ.get("SERVE_TP_OVERLAP") or None)
srv = DisaggServer(module, params, cfg,
                   install_signal_handler=False).start()
# absorb compiles: insert/export/import once, plus every power-of-two
# decode bucket the engine can pick at block size 4
for b in (1, 2, 4):
    srv.submit(np.zeros(4, np.int32), max_new=b + 1).wait()
rng = np.random.default_rng(rank)
t0 = time.monotonic()
hs = []
for i in range(requests):
    plen, mn = int(rng.integers(2, 9)), int(rng.integers(2, 9))
    hs.append(srv.submit(rng.integers(0, vocab, size=plen).astype(np.int32),
                         max_new=mn, seed=i))
for h in hs:
    assert h.wait(300), "request timed out"
wall = time.monotonic() - t0
st = srv.stats()
srv.close()
telemetry.finish(write_report=False)


def pct(vals, q):
    return (vals[min(len(vals) - 1, int(round(q / 100 * (len(vals) - 1))))]
            if vals else None)


ttfts = sorted(h.ttft_s for h in hs if h.ttft_s is not None)
tpots = sorted(h.tpot_s for h in hs if h.tpot_s is not None)
toks = sum(len(h.tokens) for h in hs)
out = {"rank": rank, "n_devices": len(jax.devices()),
       "completed": len(hs), "tokens_out": toks,
       "wall_s": round(wall, 3),
       "tokens_per_s": round(toks / wall, 1) if wall > 0 else None,
       "ttft_s_p50": pct(ttfts, 50), "ttft_s_p95": pct(ttfts, 95),
       "tpot_s_p50": pct(tpots, 50), "tpot_s_p95": pct(tpots, 95),
       "handoffs": st["handoffs"], "handoff_bytes": st["handoff_bytes"],
       "spmd": st["spmd"]}
with open(os.path.join(os.environ["SERVE_OUT"],
                       f"rank{rank}.json"), "w") as f:
    json.dump(out, f)
"""


def run_multiproc_serve(*, n_procs: int, devices_per_proc: int,
                        requests: int, mesh: str = "",
                        tp_overlap: str = "") -> dict:
    """The tpurun-launched multi-process serve rung: ``n_procs``
    disaggregated serving workers, each SPMD over its own
    ``devices_per_proc``-device emulated mesh, serialized KV handoff.
    Returns the artifact row (error-row convention on failure — a dead
    rung must not void the in-process measurements)."""
    import os
    import tempfile
    import textwrap
    import time as _time

    from tpudist.launch.run import main as tpurun_main
    from tpudist.telemetry.aggregate import aggregate_run

    saved_env = dict(os.environ)
    with tempfile.TemporaryDirectory() as td:
        worker = Path(td) / "serve_worker.py"
        worker.write_text(textwrap.dedent(_SERVE_WORKER))
        out_dir = Path(td) / "out"
        out_dir.mkdir()
        tele_dir = Path(td) / "tele"
        try:
            for var in list(os.environ):
                if var.startswith(("TPUDIST_", "SLURM_", "OMPI_")) or var in (
                        "RANK", "WORLD_SIZE", "MASTER_ADDR", "NODE_RANK"):
                    os.environ.pop(var, None)
            os.environ["SERVE_OUT"] = str(out_dir)
            os.environ["SERVE_TELE"] = str(tele_dir)
            os.environ["SERVE_REQUESTS"] = str(requests)
            os.environ["SERVE_MESH"] = mesh or ""
            os.environ["SERVE_TP_OVERLAP"] = tp_overlap or ""
            os.environ["PYTHONPATH"] = (
                str(REPO) + os.pathsep + saved_env["PYTHONPATH"]
                if "PYTHONPATH" in saved_env else str(REPO))
            t0 = _time.perf_counter()
            rc = tpurun_main([
                "--nprocs", str(n_procs), "--max-restarts", "0",
                "--devices-per-proc", str(devices_per_proc),
                "--tmpdir", str(Path(td) / "scratch"),
                "--", sys.executable, str(worker),
            ])
            wall = _time.perf_counter() - t0
        finally:
            os.environ.clear()
            os.environ.update(saved_env)
        if rc != 0:
            return {"regime": "multiprocess-serve", "n_procs": n_procs,
                    "error": f"tpurun rc={rc}"}
        recs = [json.load(open(f))
                for f in sorted(out_dir.glob("rank*.json"))]
        if len(recs) != n_procs:
            return {"regime": "multiprocess-serve", "n_procs": n_procs,
                    "error": f"expected {n_procs} rank records, "
                             f"found {len(recs)}"}
        report = aggregate_run(tele_dir)
    agg = sum(r["tokens_per_s"] or 0 for r in recs)
    return {
        "regime": "multiprocess-serve",
        "n_procs": n_procs,
        "devices_per_proc": devices_per_proc,
        "mesh_per_proc": mesh or None,
        "handoff": "serial",
        "requests_per_proc": requests,
        "agg_tokens_per_s": round(agg, 1),
        # the slowest worker bounds the fleet's tail latency
        "ttft_s_p95_worst": max((r["ttft_s_p95"] for r in recs
                                 if r["ttft_s_p95"] is not None),
                                default=None),
        "tpot_s_p95_worst": max((r["tpot_s_p95"] for r in recs
                                 if r["tpot_s_p95"] is not None),
                                default=None),
        "handoffs_total": sum(r["handoffs"] for r in recs),
        "handoff_bytes_total": sum(r["handoff_bytes"] for r in recs),
        "launch_plus_run_wall_s": round(wall, 1),
        "ranks": recs,
        # the merged cross-rank serving report: TTFT under the prefill
        # pool, TPOT under the decode pool, handoff waits in between
        "serving_report": report.get("serving"),
    }


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--smoke", action="store_true",
                   help="CPU-CI scale: tiny model, two rungs, seconds")
    p.add_argument("--rates", default=None,
                   help="offered requests/sec per rung (comma list; "
                        "'burst' = submit everything at once)")
    p.add_argument("--requests", type=int, default=None)
    p.add_argument("--slots", type=int, default=None)
    p.add_argument("--queue", type=int, default=None)
    p.add_argument("--d-model", type=int, default=None)
    p.add_argument("--n-layers", type=int, default=None)
    p.add_argument("--vocab", type=int, default=128)
    p.add_argument("--max-len", type=int, default=None)
    p.add_argument("--prompt-lens", default=None, help="min:max")
    p.add_argument("--max-news", default=None, help="min:max")
    p.add_argument("--block", type=int, default=None,
                   help="decode block size K for the offered-load rungs "
                        "(default 8)")
    p.add_argument("--blocks", default=None,
                   help="decode block sizes for the sweep (comma list; "
                        "smoke default 1,4 — full default 1,4,8,16)")
    p.add_argument("--paged", action="store_true",
                   help="run the offered-load rungs and block sweep on a "
                        "paged-KV server (block pool + block tables)")
    p.add_argument("--kv-dtype", choices=("native", "int8"), default="native",
                   help="KV storage dtype for --paged rungs (int8 = "
                        "quantized blocks with per-block scales)")
    p.add_argument("--kv-block", type=int, default=None,
                   help="tokens per KV block (default 4 smoke / 16 full; "
                        "must divide max_len)")
    p.add_argument("--kv-blocks", type=int, default=None,
                   help="pool size in blocks (default: dense-equivalent "
                        "bytes for the configured slot count)")
    p.add_argument("--prefix-cache", type=int, default=None,
                   help="shared-prefix LRU cache bound in blocks "
                        "(default: pool size / 4 when paged)")
    p.add_argument("--mesh", default=None,
                   help="SPMD serving mesh 'DxM' (data x model) for every "
                        "in-process rung — params/KV shard, programs don't "
                        "change (tpudist/serve/spmd.py)")
    p.add_argument("--tp-overlap", choices=("off", "ring", "bidir"),
                   default=None,
                   help="route the TP decode matmuls through the "
                        "ppermute-pipelined collective matmul "
                        "(ag_matmul) — gathers hide under compute")
    p.add_argument("--disagg", action="store_true",
                   help="serve the in-process rungs through the "
                        "prefill/decode-disaggregated coordinator "
                        "(separate pools + KV handoff)")
    p.add_argument("--handoff", choices=("device", "serial"),
                   default="serial",
                   help="--disagg KV transfer mode (serial = the "
                        "multi-process byte-transfer stand-in)")
    p.add_argument("--prefill-slots", type=int, default=None,
                   help="--disagg slots per prefill worker")
    p.add_argument("--multiproc", type=int, default=0,
                   help="ALSO run a true multi-process serve rung: N "
                        "tpurun-launched workers, each a disaggregated "
                        "server SPMD over its own emulated mesh, KV "
                        "handoff serialized (0 = skip)")
    p.add_argument("--devices-per-proc", type=int, default=2,
                   help="emulated devices per multiproc worker "
                        "(tpurun --devices-per-proc)")
    p.add_argument("--skip-sweeps", action="store_true",
                   help="skip the always-on paged-capacity and kv-dtype "
                        "sweeps (their sections record {'skipped': true}) "
                        "— for the CI smokes of the mesh/disagg rungs")
    p.add_argument("--seed", type=int, default=0)
    try:
        from benchmarks._round import current_round
    except ImportError:
        from _round import current_round

    p.add_argument("--out", default=str(
        REPO / f"BENCH_SERVE_r{current_round():02d}.json"))
    args = p.parse_args(argv)

    # smoke defaults, overridable flag by flag
    smoke = args.smoke
    slots = args.slots or (2 if smoke else 8)
    queue = args.queue or (8 if smoke else 128)
    requests = args.requests or (6 if smoke else 64)
    d_model = args.d_model or (32 if smoke else 512)
    n_layers = args.n_layers or (2 if smoke else 4)
    max_len = args.max_len or (32 if smoke else 512)
    plens = tuple(int(x) for x in (args.prompt_lens or
                                   ("1:6" if smoke else "4:48")).split(":"))
    mnews = tuple(int(x) for x in (args.max_news or
                                   ("2:6" if smoke else "8:96")).split(":"))
    rates = [(1e9 if r == "burst" else float(r)) for r in
             (args.rates or ("8,burst" if smoke else "1,4,16,burst")
              ).split(",")]
    block = args.block or 8
    blocks = [int(b) for b in
              (args.blocks or ("1,4" if smoke else "1,4,8,16")).split(",")]
    kv_block = args.kv_block or (4 if smoke else 16)

    import tempfile

    if args.mesh:
        from tpudist.serve.spmd import ServeMeshConfig

        _ensure_devices(ServeMeshConfig(shape=args.mesh).n_devices)
    import jax
    import numpy as np

    from tpudist import telemetry
    from tpudist.models import create_transformer
    from tpudist.serve import DisaggServer, InferenceServer, ServeConfig

    tele_dir = tempfile.mkdtemp(prefix="serve_bench_tele_")
    telemetry.start(tele_dir)
    module, params = create_transformer(
        jax.random.PRNGKey(args.seed), seq_len=16, vocab=args.vocab,
        d_model=d_model, n_layers=n_layers, n_heads=max(2, d_model // 64),
        d_ff=4 * d_model, max_len=max_len)

    # the pad is a chunk size, not an admission bound: capping it below
    # the longest prompt makes the full regime exercise chunked prefill
    pad = plens[1] if smoke else min(plens[1], 32)

    def make_server(decode_block, *, n_slots=None, paged=False,
                    kv_blocks=None, kv_int8=False, prefix_cache=None,
                    queue_limit=None, disagg=None, mesh=None):
        n_slots = n_slots or slots
        disagg = args.disagg if disagg is None else disagg
        mesh = args.mesh if mesh is None else (mesh or None)
        if paged and prefix_cache is None:
            prefix_cache = args.prefix_cache
            if prefix_cache is None:
                pool = kv_blocks or n_slots * (max_len // kv_block)
                prefix_cache = pool // 4
        cfg = ServeConfig(num_slots=n_slots, queue_limit=queue_limit or queue,
                          prefill_pad=pad, max_new=mnews[1],
                          decode_block=decode_block,
                          paged=paged, kv_block=kv_block, kv_blocks=kv_blocks,
                          kv_int8=kv_int8,
                          prefix_cache_blocks=prefix_cache or 0,
                          mesh=mesh, tp_overlap=args.tp_overlap,
                          disagg=disagg, handoff=args.handoff,
                          prefill_slots=args.prefill_slots)
        cls = DisaggServer if disagg else InferenceServer
        srv = cls(module, params, cfg, install_signal_handler=False)
        srv.start()
        # warmup: absorb the insert/prefill/decode compiles before any
        # timed rung — the longest prompt (chunked prefill, if the pad
        # splits it), then one request per power-of-two block bucket so
        # every K variant the engine can pick compiles here
        srv.submit(np.zeros(plens[1], np.int32), max_new=2).wait()
        b = 1
        while b <= decode_block:
            # sequential: alone in the batch, a request with b remaining
            # decodes exactly one K=b block
            srv.submit(np.zeros(plens[0], np.int32), max_new=b + 1).wait()
            b *= 2
        return srv

    main_paged = dict(paged=args.paged, kv_blocks=args.kv_blocks,
                      kv_int8=args.kv_dtype == "int8")
    server = make_server(block, **main_paged)
    rows = []
    for i, rate in enumerate(rates):
        row = run_rate(server, rate_rps=rate, n_requests=requests,
                       vocab=args.vocab, prompt_lens=plens, max_news=mnews,
                       seed=args.seed + i)
        occ = server.stats().get("occupancy_mean")
        row["occupancy_mean_cum"] = round(occ, 4) if occ is not None else None
        rows.append(row)
        print(json.dumps(row), flush=True)
    stats = server.stats()
    server.close()

    # block-size sweep: same offered burst through a fresh engine per K,
    # isolating what token-block fusion does to throughput and overhead
    sweep = []
    for b in blocks:
        srv = make_server(b, **main_paged)
        row = run_rate(srv, rate_rps=1e9, n_requests=requests,
                       vocab=args.vocab, prompt_lens=plens, max_news=mnews,
                       seed=args.seed)
        entry = {"decode_block": b, **row,
                 "compile_counts": _server_compile_counts(srv)}
        srv.close()
        sweep.append(entry)
        print(json.dumps(entry), flush=True)

    # The embedded serving report must describe the CONFIGURED regime:
    # finish (and merge) the main stream NOW, before the always-on
    # capacity and dtype sweeps — their servers run other regimes (the
    # dtype sweep's int8 arm starts last and its serve_kv_config would
    # win), which would leave the artifact quoting a composite no run
    # produced.  The sweeps stream into a side directory whose report is
    # discarded; their rows embed their own kv/stats snapshots.
    report = telemetry.finish() or {}
    telemetry.start(Path(tele_dir) / "sweeps")

    if args.skip_sweeps:
        capacity = {"skipped": True}
        kv_dtype_sweep = {"skipped": True}
    else:
        # -- paged-KV capacity rung: the tentpole's headline comparison --------
        # Dense arena at S slots vs paged pool at 4S slots holding the SAME
        # bytes (pool = S dense arenas' worth of blocks), both under a
        # high-churn mixed-length burst (3x the rung's request count so slots
        # churn through admissions).  The dense arm CANNOT hold more than S
        # concurrent sequences at this byte budget; the paged arm packs by
        # actual footprint — peak_occupied_slots is the measured claim.
        cap_requests = requests * 3
        dense_equiv_blocks = slots * (max_len // kv_block)
        capacity = {}
        for arm, kw in (
                ("dense", dict(n_slots=slots)),
                ("paged_4x", dict(n_slots=4 * slots, paged=True,
                                  kv_blocks=dense_equiv_blocks,
                                  prefix_cache=0))):
            # single-pool single-device arms regardless of --mesh/--disagg:
            # the capacity claim is a byte-budget comparison, continuous
            # with the r07 artifact
            srv = make_server(block, queue_limit=max(queue, cap_requests),
                              disagg=False, mesh="", **kw)
            row = run_rate(srv, rate_rps=1e9, n_requests=cap_requests,
                           vocab=args.vocab, prompt_lens=plens, max_news=mnews,
                           seed=args.seed + 17)
            capacity[arm] = {"slots": kw["n_slots"], **row}
            srv.close()
            print(json.dumps({f"capacity_{arm}": capacity[arm]}), flush=True)
        capacity["slots_ratio"] = (capacity["paged_4x"]["slots"]
                                   / capacity["dense"]["slots"])
        capacity["pool_bytes_dense"] = capacity["dense"]["kv"]["pool_bytes"]
        capacity["pool_bytes_paged"] = capacity["paged_4x"]["kv"]["pool_bytes"]
        capacity["equal_pool_bytes"] = (capacity["pool_bytes_dense"]
                                        == capacity["pool_bytes_paged"])
        capacity["peak_concurrent_dense"] = \
            capacity["dense"]["kv"]["peak_occupied_slots"]
        capacity["peak_concurrent_paged"] = \
            capacity["paged_4x"]["kv"]["peak_occupied_slots"]

        # -- int8-KV sweep: bytes/position and throughput, native vs int8 ------
        kv_sweep = []
        for dtype in ("native", "int8"):
            srv = make_server(block, paged=True, kv_int8=dtype == "int8",
                              prefix_cache=0, disagg=False, mesh="")
            row = run_rate(srv, rate_rps=1e9, n_requests=requests,
                           vocab=args.vocab, prompt_lens=plens, max_news=mnews,
                           seed=args.seed)
            kv_sweep.append({"kv_dtype": dtype, **row})
            srv.close()
            print(json.dumps({f"kv_{dtype}": kv_sweep[-1]["kv"]}), flush=True)
        ratio = (kv_sweep[0]["kv"]["bytes_per_pos"]
                 / kv_sweep[1]["kv"]["bytes_per_pos"])
        kv_dtype_sweep = {"rows": kv_sweep,
                          "bytes_per_pos_native": kv_sweep[0]["kv"][
                              "bytes_per_pos"],
                          "bytes_per_pos_int8": kv_sweep[1]["kv"][
                              "bytes_per_pos"],
                          "native_over_int8_bytes": round(ratio, 3)}

    # finish the sweeps side-stream unconditionally — a still-armed
    # session would cross-contaminate whatever this process serves next
    telemetry.finish(write_report=False)

    # -- multi-process serve rung (tpurun-launched; --multiproc N) ---------
    multiproc = None
    if args.multiproc:
        multiproc = run_multiproc_serve(
            n_procs=args.multiproc,
            devices_per_proc=args.devices_per_proc,
            requests=max(4, requests // 2),
            mesh=(args.mesh
                  or (f"1x{args.devices_per_proc}"
                      if args.devices_per_proc > 1 else "")),
            tp_overlap=args.tp_overlap or "")
        print(json.dumps({"multiproc_serve": {
            k: v for k, v in multiproc.items()
            if k not in ("ranks", "serving_report")}}), flush=True)

    artifact = {
        "regime": ("cpu-smoke" if smoke else
                   jax.devices()[0].device_kind),
        "config": {
            "slots": slots, "queue": queue, "requests_per_rung": requests,
            "d_model": d_model, "n_layers": n_layers, "vocab": args.vocab,
            "max_len": max_len, "prompt_lens": list(plens),
            "max_news": list(mnews), "decode_block": block,
            "blocks_sweep": blocks,
            "paged": args.paged, "kv_dtype": args.kv_dtype,
            "kv_block": kv_block,
            "mesh": args.mesh, "tp_overlap": args.tp_overlap,
            "disagg": args.disagg,
            "handoff": args.handoff if args.disagg else None,
        },
        "rows": rows,
        "block_sweep": sweep,
        "paged_capacity": capacity,
        "kv_dtype_sweep": kv_dtype_sweep,
        **({"multiproc_serve": multiproc} if multiproc is not None else {}),
        "server_stats": stats,
        "serving_report": report.get("serving"),
    }
    out = Path(args.out)
    tmp = out.with_suffix(".tmp")
    tmp.write_text(json.dumps(artifact, indent=2) + "\n")
    tmp.replace(out)
    print(json.dumps({"wrote": str(out),
                      "compile_counts": stats.get(
                          "compile_counts",
                          stats.get("decode_pool", {}).get(
                              "compile_counts"))}),
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
