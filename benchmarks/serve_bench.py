#!/usr/bin/env python3
"""Serving load generator: offered load vs. achieved goodput.

Drives the continuous-batching server (``tpudist.serve``) with synthetic
open-loop traffic — Poisson arrivals at each offered rate, prompt and
output lengths drawn per-request from seeded ranges — and records what
the paper-facing serving questions need:

- **throughput vs. offered load** (achieved requests/s and tokens/s per
  rate rung, including the saturation rung where offered >> capacity);
- **latency percentiles** — TTFT (submit → first token, queue wait
  included) and TPOT (steady decode interval) at p50/p95;
- **the dispatch-overhead split** — wall TPOT vs device-busy TPOT per
  rung (``tpot_busy_s`` = decode dispatch+sync seconds / tokens), plus
  dispatches-per-token and host-sync-per-token, from the engine's
  decode counters: the quantities the fused ``decode_block`` hot path
  exists to shrink;
- **the block-size sweep** — one burst rung per
  ``TPUDIST_SERVE_DECODE_BLOCK`` value (default 1/4/8/16), isolating
  how token-block fusion moves throughput and overhead;
- **batch occupancy** — the utilization gauge continuous batching exists
  to raise (sequential serving pins it at 1/num_slots);
- **backpressure** — rejected counts once the bounded queue overflows;
- **the paged-KV capacity rung** — dense arena at S slots vs paged block
  pool at 4S slots holding the SAME pool bytes, under high-churn
  mixed-length load: the decoupling of slot count from ``max_len`` is
  the whole point of the paged cache (CPU smoke proxies "equal HBM
  bytes-resident" as equal block-pool bytes);
- **the int8-KV sweep** — native vs int8 KV storage at the same
  geometry/load: resident bytes-per-position ratio and throughput, the
  bytes/token lever for bandwidth-bound decode;
- **the attn-kernel twin rung** (always-on, like the capacity rung) —
  gather vs the Pallas paged-attention kernel
  (``--attn-kernel`` selects the path for the MAIN rungs too) on the
  same paged geometry at high occupancy: decode KV bytes/token per
  path (live-KV vs pool-geometry — the HBM-roofline quantity) plus
  wall throughput, frozen per round;
- **sharded serving** (``--mesh DxM`` [+ ``--tp-overlap``]) — every
  in-process rung serves SPMD over a serving mesh
  (``tpudist/serve/spmd.py``); the artifact records the mesh geometry
  and the sharded-param accounting;
- **disaggregated serving** (``--disagg``) — rungs serve through the
  prefill/decode coordinator (``tpudist/serve/disagg.py``): per-rung
  handoff counts/bytes/wait percentiles, and the embedded serving
  report splits TTFT (prefill pool) from TPOT (decode pool);
- **the multi-process serve rung** (``--multiproc N``) — N
  tpurun-launched workers, each a disaggregated server SPMD over its
  own ``--devices-per-proc``-emulated mesh with SERIALIZED KV handoff
  (the cross-process transfer), merged per-pool serving report
  embedded.  ``round_snapshot.py`` freezes this rung into the round's
  ``BENCH_SERVE`` artifact;
- **the speculative-decode sweep** (``--spec`` [+ ``--draft-layers``
  ``--draft-k`` ``--spec-distill``]) — the decode roofline said only
  fewer-passes-per-token remained: rungs sweep draft size × drafted-K
  over a REPEAT-PROMPT workload (a fixed pool of popular prompts — the
  distribution a production draft is trained on), quoting
  accepted-tokens-per-pass and wall-TPOT against the single-model
  device-busy TPOT floor measured on a non-spec twin under the same
  traffic.  Draft variants: weight-tied (the target's first N layers,
  zero training — the out-of-the-box floor) and a distilled draft
  (trained for ``--spec-distill`` steps on the pool's greedy streams —
  what "load a trained draft" buys; random-weight targets have no
  pre-existing trained pair, so the bench builds one the way
  production does, from the serving distribution).  A mixed
  spec/non-spec rung interleaves opted-out and sampled requests in the
  same batch.

One warmup request absorbs XLA compilation before any timed rung, so
rows measure the steady engine, not the first dispatch.  Artifact:
``BENCH_SERVE_r{NN}.json`` (round-frozen like every other harness — and
snapshotted into the round scoreboard by ``round_snapshot.py``), with
the run's merged telemetry serving section embedded for cross-checking.
``--smoke`` shrinks everything to a CPU-CI scale (seconds, asserted by
``tests/test_benchmarks.py``).
"""

from __future__ import annotations

import json
import statistics
import sys
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def _pct(vals, q):
    """Nearest-rank percentile — the SAME statistic the telemetry
    report's serving section uses, so the artifact's per-rung columns and
    its embedded ``serving_report`` cross-check without definitional
    skew."""
    if not vals:
        return None
    from tpudist.telemetry.aggregate import _percentile

    return _percentile(sorted(vals), q)


def _ensure_devices(n: int) -> None:
    """Emulate ``n`` CPU devices when the backend is not yet up (the
    comm_audit trick) — standalone ``--mesh``/``--disagg`` runs need
    them; under pytest the conftest's 8-device mesh is already live."""
    import jax

    try:
        from jax._src import xla_bridge as _xb

        backend_up = _xb.backends_are_initialized()
    except Exception:
        backend_up = True
    if not backend_up:
        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", max(n, 2))
        except AttributeError:
            import os

            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + f" --xla_force_host_platform_device_count"
                            f"={max(n, 2)}").strip()


def _server_decode_stats(server) -> dict:
    """Cumulative decode counters for either server shape (the disagg
    coordinator sums its decode pool)."""
    if hasattr(server, "decode_pool"):
        return server.stats()["decode_pool"]["decode"]
    return server.engine.decode_stats()


def _server_kv(server) -> dict:
    if hasattr(server, "decode_pool"):
        return server.stats()["decode_pool"]["kv"]
    return server.stats()["kv"]


def _server_compile_counts(server) -> dict:
    if hasattr(server, "decode_pool"):
        st = server.stats()
        return {"prefill_pool": st["prefill_pool"]["compile_counts"],
                "decode_pool": st["decode_pool"]["compile_counts"]}
    return server.stats()["compile_counts"]


def run_rate(server, *, rate_rps: float, n_requests: int, vocab: int,
             prompt_lens, max_news, seed: int, prompt_pool=None,
             submit_kw=None) -> dict:
    """One offered-load rung: open-loop Poisson arrivals at ``rate_rps``
    (``inf``-like rates degenerate to a burst), wait for completion.

    ``prompt_pool``: draw prompts round-robin from this fixed list
    instead of random per-request (the repeat-traffic workload the spec
    sweep speculates on).  ``submit_kw``: per-request extra submit
    kwargs, a callable ``i -> dict`` (e.g. the mixed spec/non-spec
    rung's alternating opt-out)."""
    import numpy as np

    from tpudist.serve import AdmissionError

    rng = np.random.default_rng(seed)
    handles, rejected = [], 0
    lock = threading.Lock()

    def submit_all():
        nonlocal rejected
        for i in range(n_requests):
            max_new = int(rng.integers(max_news[0], max_news[1] + 1))
            if prompt_pool is not None:
                prompt = prompt_pool[i % len(prompt_pool)]
            else:
                plen = int(rng.integers(prompt_lens[0], prompt_lens[1] + 1))
                prompt = rng.integers(0, vocab, size=plen).astype(np.int32)
            kw = submit_kw(i) if callable(submit_kw) else (submit_kw or {})
            try:
                h = server.submit(prompt, max_new=max_new, seed=i, **kw)
                with lock:
                    handles.append(h)
            except AdmissionError:
                rejected += 1
            if rate_rps < 1e6:
                time.sleep(float(rng.exponential(1.0 / rate_rps)))

    d0 = _server_decode_stats(server)
    s0 = _server_spec_stats(server)
    h0 = _server_handoff_stats(server)
    t0 = time.monotonic()
    loader = threading.Thread(target=submit_all, daemon=True)
    loader.start()
    loader.join()
    for h in handles:
        h.wait()
    wall = time.monotonic() - t0
    d1 = _server_decode_stats(server)
    s1 = _server_spec_stats(server)
    h1 = _server_handoff_stats(server)

    ttfts = [h.ttft_s for h in handles if h.ttft_s is not None]
    tpots = [h.tpot_s for h in handles if h.tpot_s is not None]
    tokens = sum(len(h.tokens) for h in handles)
    # the dispatch-overhead split: wall TPOT (the caller's experience)
    # vs device-busy TPOT (decode dispatch + the blocking token fetch,
    # per emitted token) — the gap is host/scheduler overhead the fused
    # decode block amortizes
    blocks = d1["blocks"] - d0["blocks"]
    dtok = d1["tokens"] - d0["tokens"]
    steps = d1.get("steps", 0) - d0.get("steps", 0)
    busy = ((d1["dispatch_s"] - d0["dispatch_s"])
            + (d1["sync_s"] - d0["sync_s"]))
    sync = d1["sync_s"] - d0["sync_s"]
    return {
        "offered_rps": rate_rps if rate_rps < 1e6 else "burst",
        "n_requests": n_requests,
        "completed": len(handles),
        "rejected": rejected,
        "wall_s": round(wall, 3),
        "achieved_rps": round(len(handles) / wall, 3) if wall > 0 else None,
        "achieved_tokens_per_s": round(tokens / wall, 1) if wall > 0 else None,
        "tokens_out": tokens,
        "ttft_s_p50": round(_pct(ttfts, 50), 6) if ttfts else None,
        "ttft_s_p95": round(_pct(ttfts, 95), 6) if ttfts else None,
        "tpot_s_p50": round(_pct(tpots, 50), 6) if tpots else None,
        "tpot_s_p95": round(_pct(tpots, 95), 6) if tpots else None,
        "decode_blocks": blocks,
        "decode_tokens": dtok,
        "decode_steps": steps,
        # decode-attention KV bytes per emitted token, per the engine's
        # honest path model (live-KV for the paged kernel, the full
        # pool-geometry view for gather/dense) — the roofline column
        # the attn-kernel twin rung compares
        "kv_read_bytes_per_token": (
            round((d1.get("kv_read_bytes", 0)
                   - d0.get("kv_read_bytes", 0)) / dtok, 1)
            if dtok else None),
        "dispatches_per_token": round(blocks / dtok, 4) if dtok else None,
        "tpot_busy_s": round(busy / dtok, 6) if dtok else None,
        # device-busy time per sequential TARGET pass: for a non-spec
        # engine this is the single-model latency floor (a request
        # cannot decode faster than one full-model pass per token); a
        # spec engine's verify pass emits K+1 tokens per lane per step,
        # which is exactly how it gets underneath that floor
        "busy_per_step_s": round(busy / steps, 6) if steps else None,
        "host_sync_s_per_token": round(sync / dtok, 6) if dtok else None,
        "mean_tokens_per_request":
            round(statistics.mean([len(h.tokens) for h in handles]), 1)
            if handles else None,
        # KV residency accounting (paged: block pool; dense: the arena)
        "kv": _server_kv(server),
        # speculative decode only: per-rung acceptance deltas
        **_spec_cols(s0, s1),
        # disaggregated serving only: the prefill→decode handoff story
        # (None columns on the single-pool server)
        **_handoff_cols(h0, h1, handles),
    }


def _server_handoff_stats(server):
    if not hasattr(server, "decode_pool"):
        return None
    st = server.stats()
    return {"handoffs": st["handoffs"], "bytes": st["handoff_bytes"]}


def _server_spec_stats(server):
    """Cumulative speculative-decode counters, or None on a non-spec
    server (rows then omit the spec columns)."""
    if hasattr(server, "decode_pool"):
        st = server.stats()["decode_pool"]["spec"]
    else:
        st = server.stats()["spec"]
    return st if st.get("enabled") else None


def _spec_cols(s0, s1) -> dict:
    if s0 is None or s1 is None:
        return {}
    blocks = s1["blocks"] - s0["blocks"]
    lanes = s1["lane_passes"] - s0["lane_passes"]
    tokens = s1["tokens"] - s0["tokens"]
    accepted = s1["accepted"] - s0["accepted"]
    drafted = s1["drafted"] - s0["drafted"]
    return {
        "spec_blocks": blocks,
        "spec_tokens": tokens,
        # emitted tokens PER LANE per verify pass (1.0 = no better than
        # plain decode) — the fewer-target-passes-per-token headline,
        # normalized so batch occupancy cannot masquerade as acceptance
        "accepted_per_pass": round(tokens / lanes, 3) if lanes else None,
        "acceptance_rate": round(accepted / drafted, 4) if drafted else None,
        "spec_rollbacks": s1["rollbacks"] - s0["rollbacks"],
        "spec_draft_s": round(s1["draft_s"] - s0["draft_s"], 6),
        "spec_verify_s": round(s1["verify_s"] - s0["verify_s"], 6),
    }


def _handoff_cols(h0, h1, handles) -> dict:
    if h0 is None or h1 is None:
        return {}
    waits = [h.handoff_wait_s for h in handles
             if h.handoff_wait_s is not None]
    # deltas, like the decode counters: the row must count THIS rung's
    # handoffs, not the server's cumulative total (warmup included)
    return {
        "handoffs": h1["handoffs"] - h0["handoffs"],
        "handoff_bytes": h1["bytes"] - h0["bytes"],
        "handoff_wait_s_p50": round(_pct(waits, 50), 6) if waits else None,
        "handoff_wait_s_p95": round(_pct(waits, 95), 6) if waits else None,
    }


def _distill_draft(module, params, layers: int, prompt_pool, steps: int,
                   max_new: int):
    """Build a TRAINED draft the way production does — now delegates to
    ``tpudist.distill.distill_draft``, the same distillation path the
    online flywheel uses (see Online draft distillation in
    docs/ARCHITECTURE.md).  Returns ``(draft_module, draft_params,
    final_loss)``."""
    from tpudist.distill import distill_draft

    return distill_draft(module, params, layers, prompt_pool, steps,
                         max_new)


def run_spec_sweep(*, module, params, make_server, vocab, requests, plens,
                   mnews, block, draft_layers, draft_ks, distill_steps,
                   seed) -> dict:
    """The speculative-decode section: a repeat-prompt workload (fixed
    pool of popular prompts), a non-spec FLOOR server measured under the
    same traffic, then one rung per (draft variant × drafted-K) quoting
    accepted-tokens-per-pass and wall-TPOT vs the floor's device-busy
    TPOT, plus a mixed spec/non-spec traffic rung."""
    import numpy as np

    prng = np.random.default_rng(seed + 31)
    P = min(6, max(2, requests))
    pool = [prng.integers(
        0, vocab, size=int(prng.integers(plens[0], plens[1] + 1))
    ).astype(np.int32) for _ in range(P)]

    def rung(srv, submit_kw=None, n=None):
        row = run_rate(srv, rate_rps=1e9, n_requests=n or requests,
                       vocab=vocab, prompt_lens=plens, max_news=mnews,
                       seed=seed + 41, prompt_pool=pool,
                       submit_kw=submit_kw)
        srv.close()
        return row

    floor_row = rung(make_server(block))
    # THE floor: the non-spec engine's device-busy seconds per
    # sequential decode step.  A single model cannot emit a request's
    # tokens faster than one full forward per token no matter how it
    # batches or fuses — speculative decoding is the only lever that
    # goes below it, and only when wall-TPOT (host overhead included)
    # lands under this device-only bound is the win unarguable.
    floor_busy = floor_row["busy_per_step_s"]
    variants = [("tied", int(L), int(L)) for L in draft_layers]
    distilled = None
    if distill_steps:
        dm, dp, loss = _distill_draft(module, params, min(draft_layers),
                                      pool, distill_steps, mnews[1])
        distilled = (dm, dp)
        variants.append(("distilled", min(draft_layers), distilled))
    rows = []
    for kind, layers, draft in variants:
        for k in draft_ks:
            row = rung(make_server(block, spec=draft, spec_k=int(k)))
            wall = row.get("tpot_s_p50")
            rows.append({
                "draft": f"{kind}-{layers}", "draft_layers": layers,
                "distilled": kind == "distilled", "k": int(k), **row,
                "tpot_busy_floor_s": floor_busy,
                # the acceptance criterion: spec wall-TPOT under the
                # single-model device-busy floor (host overhead included
                # on the spec side, excluded from the floor — a strict
                # comparison)
                "below_busy_floor": (wall is not None
                                     and floor_busy is not None
                                     and wall < floor_busy),
            })
            print(json.dumps({"spec_rung": {
                k2: rows[-1][k2] for k2 in (
                    "draft", "k", "accepted_per_pass", "acceptance_rate",
                    "tpot_s_p50", "tpot_busy_floor_s",
                    "below_busy_floor")}}), flush=True)
    # mixed spec/non-spec traffic: half the requests opt out, a third
    # run sampled — heterogeneous acceptance in one batch
    best = distilled if distilled is not None else int(draft_layers[0])
    mixed_row = rung(
        make_server(block, spec=best, spec_k=int(draft_ks[-1])),
        submit_kw=lambda i: {"spec": i % 2 == 0,
                             "temperature": 0.8 if i % 3 == 0 else 0.0},
        n=max(requests, 2 * P))
    return {
        "workload": {"pool_prompts": P, "repeat_traffic": True,
                     "prompt_lens": [int(len(p)) for p in pool]},
        "floor": {**floor_row, "tpot_busy_s": floor_busy},
        "rows": rows,
        "distill_steps": int(distill_steps or 0),
        "mixed": mixed_row,
        "any_below_busy_floor": any(r["below_busy_floor"] for r in rows),
    }


#: Worker body of the multi-process serve rung: one disaggregated
#: server per process, SPMD over that process's emulated device mesh,
#: KV handoff serialized (the cross-process transfer stand-in), traffic
#: seeded per rank.  Launched via the tpurun agent exactly like a real
#: multi-host serving job; telemetry streams into a shared dir whose
#: merged serving report (per-pool TTFT/TPOT split) embeds in the
#: artifact row.
_SERVE_WORKER = """
import json, os, time

os.environ["JAX_PLATFORMS"] = "cpu"
# device count per process comes from tpurun --devices-per-proc

import numpy as np
import jax

from tpudist import telemetry
from tpudist.models import create_transformer
from tpudist.serve import DisaggServer, ServeConfig

rank = int(os.environ.get("TPUDIST_PROCESS_ID", "0"))
requests = int(os.environ["SERVE_REQUESTS"])
mesh = os.environ.get("SERVE_MESH", "") or None
vocab = 64
telemetry.start(os.environ["SERVE_TELE"])
module, params = create_transformer(
    jax.random.PRNGKey(0), seq_len=16, vocab=vocab, d_model=32,
    n_layers=2, n_heads=2, d_ff=128, max_len=64)
cfg = ServeConfig(num_slots=2, queue_limit=max(64, requests), max_new=8,
                  prefill_pad=8, decode_block=4, disagg=True,
                  handoff="serial", mesh=mesh,
                  tp_overlap=os.environ.get("SERVE_TP_OVERLAP") or None)
srv = DisaggServer(module, params, cfg,
                   install_signal_handler=False).start()
# absorb compiles: insert/export/import once, plus every power-of-two
# decode bucket the engine can pick at block size 4
for b in (1, 2, 4):
    srv.submit(np.zeros(4, np.int32), max_new=b + 1).wait()
rng = np.random.default_rng(rank)
t0 = time.monotonic()
hs = []
for i in range(requests):
    plen, mn = int(rng.integers(2, 9)), int(rng.integers(2, 9))
    hs.append(srv.submit(rng.integers(0, vocab, size=plen).astype(np.int32),
                         max_new=mn, seed=i))
for h in hs:
    assert h.wait(300), "request timed out"
wall = time.monotonic() - t0
st = srv.stats()
srv.close()
telemetry.finish(write_report=False)


def pct(vals, q):
    return (vals[min(len(vals) - 1, int(round(q / 100 * (len(vals) - 1))))]
            if vals else None)


ttfts = sorted(h.ttft_s for h in hs if h.ttft_s is not None)
tpots = sorted(h.tpot_s for h in hs if h.tpot_s is not None)
toks = sum(len(h.tokens) for h in hs)
out = {"rank": rank, "n_devices": len(jax.devices()),
       "completed": len(hs), "tokens_out": toks,
       "wall_s": round(wall, 3),
       "tokens_per_s": round(toks / wall, 1) if wall > 0 else None,
       "ttft_s_p50": pct(ttfts, 50), "ttft_s_p95": pct(ttfts, 95),
       "tpot_s_p50": pct(tpots, 50), "tpot_s_p95": pct(tpots, 95),
       "handoffs": st["handoffs"], "handoff_bytes": st["handoff_bytes"],
       "spmd": st["spmd"]}
with open(os.path.join(os.environ["SERVE_OUT"],
                       f"rank{rank}.json"), "w") as f:
    json.dump(out, f)
"""


def run_multiproc_serve(*, n_procs: int, devices_per_proc: int,
                        requests: int, mesh: str = "",
                        tp_overlap: str = "") -> dict:
    """The tpurun-launched multi-process serve rung: ``n_procs``
    disaggregated serving workers, each SPMD over its own
    ``devices_per_proc``-device emulated mesh, serialized KV handoff.
    Returns the artifact row (error-row convention on failure — a dead
    rung must not void the in-process measurements)."""
    import os
    import tempfile
    import textwrap
    import time as _time

    from tpudist.launch.run import main as tpurun_main
    from tpudist.telemetry.aggregate import aggregate_run

    saved_env = dict(os.environ)
    with tempfile.TemporaryDirectory() as td:
        worker = Path(td) / "serve_worker.py"
        worker.write_text(textwrap.dedent(_SERVE_WORKER))
        out_dir = Path(td) / "out"
        out_dir.mkdir()
        tele_dir = Path(td) / "tele"
        try:
            for var in list(os.environ):
                if var.startswith(("TPUDIST_", "SLURM_", "OMPI_")) or var in (
                        "RANK", "WORLD_SIZE", "MASTER_ADDR", "NODE_RANK"):
                    os.environ.pop(var, None)
            os.environ["SERVE_OUT"] = str(out_dir)
            os.environ["SERVE_TELE"] = str(tele_dir)
            os.environ["SERVE_REQUESTS"] = str(requests)
            os.environ["SERVE_MESH"] = mesh or ""
            os.environ["SERVE_TP_OVERLAP"] = tp_overlap or ""
            os.environ["PYTHONPATH"] = (
                str(REPO) + os.pathsep + saved_env["PYTHONPATH"]
                if "PYTHONPATH" in saved_env else str(REPO))
            t0 = _time.perf_counter()
            rc = tpurun_main([
                "--nprocs", str(n_procs), "--max-restarts", "0",
                "--devices-per-proc", str(devices_per_proc),
                "--tmpdir", str(Path(td) / "scratch"),
                "--", sys.executable, str(worker),
            ])
            wall = _time.perf_counter() - t0
        finally:
            os.environ.clear()
            os.environ.update(saved_env)
        if rc != 0:
            return {"regime": "multiprocess-serve", "n_procs": n_procs,
                    "error": f"tpurun rc={rc}"}
        recs = [json.load(open(f))
                for f in sorted(out_dir.glob("rank*.json"))]
        if len(recs) != n_procs:
            return {"regime": "multiprocess-serve", "n_procs": n_procs,
                    "error": f"expected {n_procs} rank records, "
                             f"found {len(recs)}"}
        report = aggregate_run(tele_dir)
    agg = sum(r["tokens_per_s"] or 0 for r in recs)
    return {
        "regime": "multiprocess-serve",
        "n_procs": n_procs,
        "devices_per_proc": devices_per_proc,
        "mesh_per_proc": mesh or None,
        "handoff": "serial",
        "requests_per_proc": requests,
        "agg_tokens_per_s": round(agg, 1),
        # the slowest worker bounds the fleet's tail latency
        "ttft_s_p95_worst": max((r["ttft_s_p95"] for r in recs
                                 if r["ttft_s_p95"] is not None),
                                default=None),
        "tpot_s_p95_worst": max((r["tpot_s_p95"] for r in recs
                                 if r["tpot_s_p95"] is not None),
                                default=None),
        "handoffs_total": sum(r["handoffs"] for r in recs),
        "handoff_bytes_total": sum(r["handoff_bytes"] for r in recs),
        "launch_plus_run_wall_s": round(wall, 1),
        "ranks": recs,
        # the merged cross-rank serving report: TTFT under the prefill
        # pool, TPOT under the decode pool, handoff waits in between
        "serving_report": report.get("serving"),
    }


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--smoke", action="store_true",
                   help="CPU-CI scale: tiny model, two rungs, seconds")
    p.add_argument("--rates", default=None,
                   help="offered requests/sec per rung (comma list; "
                        "'burst' = submit everything at once)")
    p.add_argument("--requests", type=int, default=None)
    p.add_argument("--slots", type=int, default=None)
    p.add_argument("--queue", type=int, default=None)
    p.add_argument("--d-model", type=int, default=None)
    p.add_argument("--n-layers", type=int, default=None)
    p.add_argument("--vocab", type=int, default=128)
    p.add_argument("--max-len", type=int, default=None)
    p.add_argument("--prompt-lens", default=None, help="min:max")
    p.add_argument("--max-news", default=None, help="min:max")
    p.add_argument("--block", type=int, default=None,
                   help="decode block size K for the offered-load rungs "
                        "(default 8)")
    p.add_argument("--blocks", default=None,
                   help="decode block sizes for the sweep (comma list; "
                        "smoke default 1,4 — full default 1,4,8,16)")
    p.add_argument("--paged", action="store_true",
                   help="run the offered-load rungs and block sweep on a "
                        "paged-KV server (block pool + block tables)")
    p.add_argument("--kv-dtype", choices=("native", "int8"), default="native",
                   help="KV storage dtype for --paged rungs (int8 = "
                        "quantized blocks with per-block scales)")
    p.add_argument("--kv-block", type=int, default=None,
                   help="tokens per KV block (default 4 smoke / 16 full; "
                        "must divide max_len)")
    p.add_argument("--kv-blocks", type=int, default=None,
                   help="pool size in blocks (default: dense-equivalent "
                        "bytes for the configured slot count)")
    p.add_argument("--prefix-cache", type=int, default=None,
                   help="shared-prefix LRU cache bound in blocks "
                        "(default: pool size / 4 when paged)")
    p.add_argument("--attn-kernel", choices=("gather", "paged"),
                   default="gather",
                   help="decode attention path for --paged rungs: gather "
                        "(dense view per dispatch) or paged (the Pallas "
                        "paged-attention kernel — in-kernel block-table "
                        "walk, bytes/token ∝ live KV)")
    p.add_argument("--mesh", default=None,
                   help="SPMD serving mesh 'DxM' (data x model) for every "
                        "in-process rung — params/KV shard, programs don't "
                        "change (tpudist/serve/spmd.py)")
    p.add_argument("--tp-overlap", choices=("off", "ring", "bidir"),
                   default=None,
                   help="route the TP decode matmuls through the "
                        "ppermute-pipelined collective matmul "
                        "(ag_matmul) — gathers hide under compute")
    p.add_argument("--disagg", action="store_true",
                   help="serve the in-process rungs through the "
                        "prefill/decode-disaggregated coordinator "
                        "(separate pools + KV handoff)")
    p.add_argument("--handoff", choices=("device", "serial"),
                   default="serial",
                   help="--disagg KV transfer mode (serial = the "
                        "multi-process byte-transfer stand-in)")
    p.add_argument("--prefill-slots", type=int, default=None,
                   help="--disagg slots per prefill worker")
    p.add_argument("--multiproc", type=int, default=0,
                   help="ALSO run a true multi-process serve rung: N "
                        "tpurun-launched workers, each a disaggregated "
                        "server SPMD over its own emulated mesh, KV "
                        "handoff serialized (0 = skip)")
    p.add_argument("--devices-per-proc", type=int, default=2,
                   help="emulated devices per multiproc worker "
                        "(tpurun --devices-per-proc)")
    p.add_argument("--spec", action="store_true",
                   help="ALSO run the speculative-decode sweep: draft "
                        "size x drafted-K rungs on a repeat-prompt "
                        "workload, accepted-tokens/pass and wall-TPOT vs "
                        "the non-spec device-busy TPOT floor, plus a "
                        "mixed spec/non-spec traffic rung")
    p.add_argument("--draft-layers", default=None,
                   help="tied-draft depths for the --spec sweep (comma "
                        "list of target-layer counts; default 1)")
    p.add_argument("--draft-k", default=None,
                   help="drafted tokens per pass for the --spec sweep "
                        "(comma list; smoke default 2,4 — full 2,4,8)")
    p.add_argument("--spec-distill", type=int, default=None,
                   help="distillation steps for the trained-draft rung "
                        "(0 = tied drafts only; default 150 smoke / 200 "
                        "full)")
    p.add_argument("--skip-sweeps", action="store_true",
                   help="skip the always-on paged-capacity and kv-dtype "
                        "sweeps (their sections record {'skipped': true}) "
                        "— for the CI smokes of the mesh/disagg rungs")
    p.add_argument("--seed", type=int, default=0)
    try:
        from benchmarks._round import current_round
    except ImportError:
        from _round import current_round

    p.add_argument("--out", default=str(
        REPO / f"BENCH_SERVE_r{current_round():02d}.json"))
    args = p.parse_args(argv)

    # smoke defaults, overridable flag by flag
    smoke = args.smoke
    slots = args.slots or (2 if smoke else 8)
    queue = args.queue or (8 if smoke else 128)
    requests = args.requests or (6 if smoke else 64)
    d_model = args.d_model or (32 if smoke else 512)
    n_layers = args.n_layers or (2 if smoke else 4)
    max_len = args.max_len or (32 if smoke else 512)
    plens = tuple(int(x) for x in (args.prompt_lens or
                                   ("1:6" if smoke else "4:48")).split(":"))
    mnews = tuple(int(x) for x in (args.max_news or
                                   ("2:6" if smoke else "8:96")).split(":"))
    rates = [(1e9 if r == "burst" else float(r)) for r in
             (args.rates or ("8,burst" if smoke else "1,4,16,burst")
              ).split(",")]
    block = args.block or 8
    blocks = [int(b) for b in
              (args.blocks or ("1,4" if smoke else "1,4,8,16")).split(",")]
    kv_block = args.kv_block or (4 if smoke else 16)

    import tempfile

    if args.mesh:
        from tpudist.serve.spmd import ServeMeshConfig

        _ensure_devices(ServeMeshConfig(shape=args.mesh).n_devices)
    import jax
    import numpy as np

    from tpudist import telemetry
    from tpudist.models import create_transformer
    from tpudist.serve import DisaggServer, InferenceServer, ServeConfig

    tele_dir = tempfile.mkdtemp(prefix="serve_bench_tele_")
    telemetry.start(tele_dir)
    module, params = create_transformer(
        jax.random.PRNGKey(args.seed), seq_len=16, vocab=args.vocab,
        d_model=d_model, n_layers=n_layers, n_heads=max(2, d_model // 64),
        d_ff=4 * d_model, max_len=max_len)

    # the pad is a chunk size, not an admission bound: capping it below
    # the longest prompt makes the full regime exercise chunked prefill
    pad = plens[1] if smoke else min(plens[1], 32)

    def make_server(decode_block, *, n_slots=None, paged=False,
                    kv_blocks=None, kv_int8=False, prefix_cache=None,
                    queue_limit=None, disagg=None, mesh=None,
                    spec=None, spec_k=4, attn_kernel=None,
                    prefill_kernel=False, sample_kernel=False,
                    fused_rope=False):
        n_slots = n_slots or slots
        disagg = args.disagg if disagg is None else disagg
        mesh = args.mesh if mesh is None else (mesh or None)
        # the kernel only exists on the paged cache; dense arms of the
        # capacity rung must not inherit the flag
        if attn_kernel is None:
            attn_kernel = args.attn_kernel if paged else "gather"
        if paged and prefix_cache is None:
            prefix_cache = args.prefix_cache
            if prefix_cache is None:
                pool = kv_blocks or n_slots * (max_len // kv_block)
                prefix_cache = pool // 4
        # spec: None = off, an int = tied-draft depth, a (module,
        # params) pair = a loaded (e.g. distilled) draft
        spec_kw = {}
        if spec is not None:
            spec_kw = dict(
                spec=True, spec_k=spec_k,
                spec_draft_layers=spec if isinstance(spec, int) else 0,
                spec_draft=None if isinstance(spec, int) else spec)
        cfg = ServeConfig(num_slots=n_slots, queue_limit=queue_limit or queue,
                          prefill_pad=pad, max_new=mnews[1],
                          decode_block=decode_block,
                          paged=paged, kv_block=kv_block, kv_blocks=kv_blocks,
                          kv_int8=kv_int8,
                          prefix_cache_blocks=prefix_cache or 0,
                          attn_kernel=attn_kernel,
                          prefill_kernel=prefill_kernel,
                          sample_kernel=sample_kernel,
                          fused_rope=fused_rope,
                          mesh=mesh, tp_overlap=args.tp_overlap,
                          disagg=disagg, handoff=args.handoff,
                          prefill_slots=args.prefill_slots, **spec_kw)
        cls = DisaggServer if disagg else InferenceServer
        srv = cls(module, params, cfg, install_signal_handler=False)
        srv.start()
        # warmup: absorb the insert/prefill/decode compiles before any
        # timed rung — the longest prompt (chunked prefill, if the pad
        # splits it), then one request per power-of-two block bucket so
        # every K variant the engine can pick compiles here
        srv.submit(np.zeros(plens[1], np.int32), max_new=2).wait()
        b = 1
        while b <= decode_block:
            # sequential: alone in the batch, a request with b remaining
            # decodes exactly one K=b block
            srv.submit(np.zeros(plens[0], np.int32), max_new=b + 1).wait()
            b *= 2
        if spec is not None:
            # the spec bucket picker caps K at (max remaining - 1): a
            # request with b + 2 tokens of budget compiles the K=b
            # draft-propose/verify pair — every power-of-two bucket up
            # to spec_k must compile HERE, not inside a timed rung
            b = 1
            while b <= spec_k:
                srv.submit(np.zeros(plens[0], np.int32),
                           max_new=b + 2).wait()
                b *= 2
        return srv

    spec_draft_layers = [int(x) for x in
                         (args.draft_layers or "1").split(",")]
    spec_draft_ks = [int(x) for x in
                     (args.draft_k or ("2,4" if smoke else "2,4,8")
                      ).split(",")]
    main_paged = dict(paged=args.paged, kv_blocks=args.kv_blocks,
                      kv_int8=args.kv_dtype == "int8")
    if args.spec:
        # --spec serves the MAIN rows speculatively too (tied draft at
        # the sweep's first depth), so the offered-load rows and the
        # embedded serving report carry the acceptance counters; the
        # sweep section isolates draft variants against the floor
        main_paged.update(spec=spec_draft_layers[0],
                          spec_k=spec_draft_ks[-1])
    server = make_server(block, **main_paged)
    rows = []
    for i, rate in enumerate(rates):
        row = run_rate(server, rate_rps=rate, n_requests=requests,
                       vocab=args.vocab, prompt_lens=plens, max_news=mnews,
                       seed=args.seed + i)
        occ = server.stats().get("occupancy_mean")
        row["occupancy_mean_cum"] = round(occ, 4) if occ is not None else None
        rows.append(row)
        print(json.dumps(row), flush=True)
    stats = server.stats()
    server.close()

    # block-size sweep: same offered burst through a fresh engine per K,
    # isolating what token-block fusion does to throughput and overhead
    # — always NON-speculative (the spec sweep isolates speculation; a
    # spec engine's iteration shape doesn't vary with the plain block K)
    sweep = []
    block_kw = {k: v for k, v in main_paged.items()
                if k not in ("spec", "spec_k")}
    for b in blocks:
        srv = make_server(b, **block_kw)
        row = run_rate(srv, rate_rps=1e9, n_requests=requests,
                       vocab=args.vocab, prompt_lens=plens, max_news=mnews,
                       seed=args.seed)
        entry = {"decode_block": b, **row,
                 "compile_counts": _server_compile_counts(srv)}
        srv.close()
        sweep.append(entry)
        print(json.dumps(entry), flush=True)

    # The embedded serving report must describe the CONFIGURED regime:
    # finish (and merge) the main stream NOW, before the always-on
    # capacity and dtype sweeps — their servers run other regimes (the
    # dtype sweep's int8 arm starts last and its serve_kv_config would
    # win), which would leave the artifact quoting a composite no run
    # produced.  The sweeps stream into a side directory whose report is
    # discarded; their rows embed their own kv/stats snapshots.
    report = telemetry.finish() or {}
    telemetry.start(Path(tele_dir) / "sweeps")

    if args.skip_sweeps:
        capacity = {"skipped": True}
        kv_dtype_sweep = {"skipped": True}
        attn_kernel_twin = {"skipped": True}
        family_twin = {"skipped": True}
    else:
        # -- paged-KV capacity rung: the tentpole's headline comparison --------
        # Dense arena at S slots vs paged pool at 4S slots holding the SAME
        # bytes (pool = S dense arenas' worth of blocks), both under a
        # high-churn mixed-length burst (3x the rung's request count so slots
        # churn through admissions).  The dense arm CANNOT hold more than S
        # concurrent sequences at this byte budget; the paged arm packs by
        # actual footprint — peak_occupied_slots is the measured claim.
        cap_requests = requests * 3
        dense_equiv_blocks = slots * (max_len // kv_block)
        capacity = {}
        for arm, kw in (
                ("dense", dict(n_slots=slots)),
                ("paged_4x", dict(n_slots=4 * slots, paged=True,
                                  kv_blocks=dense_equiv_blocks,
                                  prefix_cache=0))):
            # single-pool single-device arms regardless of --mesh/--disagg:
            # the capacity claim is a byte-budget comparison, continuous
            # with the r07 artifact
            srv = make_server(block, queue_limit=max(queue, cap_requests),
                              disagg=False, mesh="", **kw)
            row = run_rate(srv, rate_rps=1e9, n_requests=cap_requests,
                           vocab=args.vocab, prompt_lens=plens, max_news=mnews,
                           seed=args.seed + 17)
            capacity[arm] = {"slots": kw["n_slots"], **row}
            srv.close()
            print(json.dumps({f"capacity_{arm}": capacity[arm]}), flush=True)
        capacity["slots_ratio"] = (capacity["paged_4x"]["slots"]
                                   / capacity["dense"]["slots"])
        capacity["pool_bytes_dense"] = capacity["dense"]["kv"]["pool_bytes"]
        capacity["pool_bytes_paged"] = capacity["paged_4x"]["kv"]["pool_bytes"]
        capacity["equal_pool_bytes"] = (capacity["pool_bytes_dense"]
                                        == capacity["pool_bytes_paged"])
        capacity["peak_concurrent_dense"] = \
            capacity["dense"]["kv"]["peak_occupied_slots"]
        capacity["peak_concurrent_paged"] = \
            capacity["paged_4x"]["kv"]["peak_occupied_slots"]

        # -- int8-KV sweep: bytes/position and throughput, native vs int8 ------
        kv_sweep = []
        for dtype in ("native", "int8"):
            srv = make_server(block, paged=True, kv_int8=dtype == "int8",
                              prefix_cache=0, disagg=False, mesh="")
            row = run_rate(srv, rate_rps=1e9, n_requests=requests,
                           vocab=args.vocab, prompt_lens=plens, max_news=mnews,
                           seed=args.seed)
            kv_sweep.append({"kv_dtype": dtype, **row})
            srv.close()
            print(json.dumps({f"kv_{dtype}": kv_sweep[-1]["kv"]}), flush=True)
        ratio = (kv_sweep[0]["kv"]["bytes_per_pos"]
                 / kv_sweep[1]["kv"]["bytes_per_pos"])
        kv_dtype_sweep = {"rows": kv_sweep,
                          "bytes_per_pos_native": kv_sweep[0]["kv"][
                              "bytes_per_pos"],
                          "bytes_per_pos_int8": kv_sweep[1]["kv"][
                              "bytes_per_pos"],
                          "native_over_int8_bytes": round(ratio, 3)}

        # -- attn-kernel twin rung: gather vs the Pallas paged kernel at
        # HIGH occupancy -------------------------------------------------
        # Same paged geometry, same burst (every request at the maximum
        # output budget so the slots stay saturated); the headline
        # column is decode KV bytes/token — the HBM-roofline quantity
        # the kernel exists to shrink: gather's dense view charges
        # max_len per lane per step regardless of cursors, the kernel
        # charges live blocks only.  Wall tok/s is quoted too but on a
        # CPU smoke it measures interpreter mechanics, not the HBM
        # bandwidth the on-chip run converts bytes into (the dh128-twin
        # labeling discipline).
        attn_requests = max(requests, slots * 4)
        attn_kernel_twin = {}
        for arm in ("gather", "paged"):
            srv = make_server(block, paged=True, prefix_cache=0,
                              disagg=False, mesh="", attn_kernel=arm,
                              queue_limit=max(queue, attn_requests))
            row = run_rate(srv, rate_rps=1e9, n_requests=attn_requests,
                           vocab=args.vocab, prompt_lens=plens,
                           max_news=(mnews[1], mnews[1]),
                           seed=args.seed + 29)
            key = "kernel" if arm == "paged" else arm
            attn_kernel_twin[key] = row
            srv.close()
            print(json.dumps({f"attn_{key}": {
                "tokens_per_s": row["achieved_tokens_per_s"],
                "kv_read_bytes_per_token": row["kv_read_bytes_per_token"],
                "peak_occupied_slots":
                    row["kv"]["peak_occupied_slots"]}}), flush=True)
        bg = attn_kernel_twin["gather"]["kv_read_bytes_per_token"]
        bk = attn_kernel_twin["kernel"]["kv_read_bytes_per_token"]
        tg = attn_kernel_twin["gather"]["achieved_tokens_per_s"]
        tk = attn_kernel_twin["kernel"]["achieved_tokens_per_s"]
        attn_kernel_twin.update({
            "read_bytes_per_token_gather": bg,
            "read_bytes_per_token_kernel": bk,
            "bytes_ratio_gather_over_kernel": (
                round(bg / bk, 3) if bg and bk else None),
            # the acceptance claim: at high occupancy the kernel path
            # moves fewer KV bytes per emitted token than the gather
            # path (∝ live KV, not pool geometry)
            "kernel_beats_gather_bytes": bool(bg and bk and bk < bg),
            "tokens_per_s_gather": tg,
            "tokens_per_s_kernel": tk,
            "kernel_beats_gather_wall": bool(tg and tk and tk > tg),
            "note": ("headline = bytes/token, the engine's per-path "
                     "accounting model applied to THIS rung's real "
                     "traffic (live-KV for the kernel, pool-geometry "
                     "for gather) — it quantifies the byte gap at the "
                     "measured occupancy, it does NOT independently "
                     "verify the kernel's DMA elision (that needs an "
                     "on-chip profile, DECODE_PROFILE's paged phases "
                     "on TPU).  Wall tok/s on a cpu-smoke run measures "
                     "the Pallas INTERPRETER — mechanics-only, the "
                     "dh128-twin labeling discipline"),
        })

        # -- kernel-family twin rungs: each fused path vs its in-graph
        # twin on the SAME saturated burst --------------------------------
        # prefill twin headline = the engine's honest prefill KV bytes
        # (reads walk the prefix / dense sweep; writes chunk-span / pad-
        # span); sample and rope_qkv twins quote wall tok/s under the
        # attn-twin labeling discipline (cpu-smoke wall = interpreter
        # mechanics, the on-chip run converts the fused dispatch count
        # into HBM time).
        family_twin = {}
        for pair, base_kw, fused_kw in (
                ("prefill", dict(paged=True),
                 dict(paged=True, prefill_kernel=True)),
                ("sample", dict(paged=True),
                 dict(paged=True, sample_kernel=True)),
                ("rope_qkv", dict(paged=True, attn_kernel="paged"),
                 dict(paged=True, attn_kernel="paged", fused_rope=True))):
            twin = {}
            for arm, kw in (("base", base_kw), ("fused", fused_kw)):
                srv = make_server(block, prefix_cache=0, disagg=False,
                                  mesh="", queue_limit=max(
                                      queue, attn_requests), **kw)
                row = run_rate(srv, rate_rps=1e9, n_requests=attn_requests,
                               vocab=args.vocab, prompt_lens=plens,
                               max_news=(mnews[1], mnews[1]),
                               seed=args.seed + 31)
                twin[arm] = row
                srv.close()
            b, f = twin["base"], twin["fused"]
            summary = {
                "tokens_per_s_base": b["achieved_tokens_per_s"],
                "tokens_per_s_fused": f["achieved_tokens_per_s"],
                "fused_beats_base_wall": bool(
                    f["achieved_tokens_per_s"]
                    > b["achieved_tokens_per_s"]),
            }
            if pair == "prefill":
                summary.update({
                    "prefill_read_bytes_base": b["kv"][
                        "prefill_read_bytes"],
                    "prefill_read_bytes_kernel": f["kv"][
                        "prefill_read_bytes"],
                    "prefill_write_bytes_base": b["kv"][
                        "prefill_write_bytes"],
                    "prefill_write_bytes_kernel": f["kv"][
                        "prefill_write_bytes"],
                    # the acceptance claim (byte-based, regime-honest):
                    # the kernel prefill moves fewer KV bytes than the
                    # dense gather sweep on the same burst
                    "kernel_beats_gather_prefill_bytes": bool(
                        f["kv"]["prefill_read_bytes"]
                        + f["kv"]["prefill_write_bytes"]
                        < b["kv"]["prefill_read_bytes"]
                        + b["kv"]["prefill_write_bytes"]),
                })
            family_twin[pair] = {**twin, **summary}
            print(json.dumps({f"family_{pair}": summary}), flush=True)
        family_twin["note"] = (
            "per-pair twin on the attn-twin burst; prefill headline = "
            "the engine's honest per-path prefill KV bytes, wall tok/s "
            "under the cpu-smoke interpreter labeling discipline")

    # -- speculative-decode sweep (--spec): draft size x K rungs vs the
    # non-spec device-busy floor, on repeat-prompt traffic -----------------
    spec_sweep = None
    if args.spec:
        distill = args.spec_distill
        if distill is None:
            distill = 150 if smoke else 200
        spec_sweep = run_spec_sweep(
            module=module, params=params, make_server=make_server,
            vocab=args.vocab, requests=requests, plens=plens, mnews=mnews,
            block=block, draft_layers=spec_draft_layers,
            draft_ks=spec_draft_ks, distill_steps=distill, seed=args.seed)

    # finish the sweeps side-stream unconditionally — a still-armed
    # session would cross-contaminate whatever this process serves next
    telemetry.finish(write_report=False)

    # -- multi-process serve rung (tpurun-launched; --multiproc N) ---------
    multiproc = None
    if args.multiproc:
        multiproc = run_multiproc_serve(
            n_procs=args.multiproc,
            devices_per_proc=args.devices_per_proc,
            requests=max(4, requests // 2),
            mesh=(args.mesh
                  or (f"1x{args.devices_per_proc}"
                      if args.devices_per_proc > 1 else "")),
            tp_overlap=args.tp_overlap or "")
        print(json.dumps({"multiproc_serve": {
            k: v for k, v in multiproc.items()
            if k not in ("ranks", "serving_report")}}), flush=True)

    artifact = {
        "regime": ("cpu-smoke" if smoke else
                   jax.devices()[0].device_kind),
        "config": {
            "slots": slots, "queue": queue, "requests_per_rung": requests,
            "d_model": d_model, "n_layers": n_layers, "vocab": args.vocab,
            "max_len": max_len, "prompt_lens": list(plens),
            "max_news": list(mnews), "decode_block": block,
            "blocks_sweep": blocks,
            "paged": args.paged, "kv_dtype": args.kv_dtype,
            "kv_block": kv_block, "attn_kernel": args.attn_kernel,
            "mesh": args.mesh, "tp_overlap": args.tp_overlap,
            "disagg": args.disagg,
            "handoff": args.handoff if args.disagg else None,
            "spec": args.spec,
        },
        "rows": rows,
        "block_sweep": sweep,
        "paged_capacity": capacity,
        "kv_dtype_sweep": kv_dtype_sweep,
        "attn_kernel_twin": attn_kernel_twin,
        "kernel_family_twin": family_twin,
        **({"spec_sweep": spec_sweep} if spec_sweep is not None else {}),
        **({"multiproc_serve": multiproc} if multiproc is not None else {}),
        "server_stats": stats,
        "serving_report": report.get("serving"),
    }
    out = Path(args.out)
    tmp = out.with_suffix(".tmp")
    tmp.write_text(json.dumps(artifact, indent=2) + "\n")
    tmp.replace(out)
    print(json.dumps({"wrote": str(out),
                      "compile_counts": stats.get(
                          "compile_counts",
                          stats.get("decode_pool", {}).get(
                              "compile_counts"))}),
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
