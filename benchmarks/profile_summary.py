#!/usr/bin/env python3
"""Per-op breakdown from a ``jax.profiler`` trace directory.

`bench.py` (TPUDIST_BENCH_PROFILE=dir) and the demos (``--profile_dir``)
capture TensorBoard-style profiles; this tool turns the Chrome-trace
export (``**/*.trace.json.gz``) into the table BASELINE.md wants next to
an MFU number: top ops by device self-time, grouped, with percentages —
the "where did the non-matmul time go" evidence (VERDICT r2 weak #2).

Usage:
  python benchmarks/profile_summary.py runs/profile_mfu [--top 25]
  python benchmarks/profile_summary.py trace.json.gz --json

Groups: names are bucketed by leading HLO opcode (fusion, dot/convolution
= MXU, copy/transpose = layout, all-reduce/collective = comm, etc.), so
the one-line summary reads like a roofline attribution.
"""

from __future__ import annotations

import argparse
import gzip
import json
import sys
from collections import defaultdict
from pathlib import Path
from typing import Dict, Iterable, List, Tuple

_GROUPS: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    ("matmul (MXU)", ("dot", "convolution", "cublas", "gemm")),
    ("fusion (fused elementwise/reduce)", ("fusion", "loop_fusion",
                                           "input_fusion")),
    ("collectives", ("all-reduce", "all-gather", "reduce-scatter",
                     "all-to-all", "collective", "ppermute",
                     "collective-permute", "psum")),
    ("layout/copy", ("copy", "transpose", "bitcast", "reshape")),
    ("custom (pallas/kernels)", ("custom-call", "custom_call", "tpu_custom")),
    ("dynamic slicing", ("dynamic-slice", "dynamic-update-slice", "gather",
                         "scatter")),
    ("host/infeed", ("infeed", "outfeed", "host")),
)


def _group_of(name: str, hlo_category: str = "") -> str:
    # TPU traces stamp each op with args.hlo_category ("loop fusion",
    # "custom-call", "convolution", ...) — authoritative where present
    # (instruction NAMES need not mention their opcode: the flash pallas
    # calls appear as "block_3.5").  Name heuristics are the fallback
    # for traces without args.
    for probe in (hlo_category.lower(), name.lower()):
        if not probe:
            continue
        for group, keys in _GROUPS:
            if any(k in probe for k in keys):
                return group
    return "other"


def _iter_trace_files(path: Path) -> Iterable[Path]:
    if path.is_file():
        yield path
        return
    yield from sorted(path.rglob("*.trace.json.gz"))
    yield from sorted(path.rglob("*.trace.json"))


def _load_events(path: Path) -> List[dict]:
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "rt") as f:
        data = json.load(f)
    if isinstance(data, list):  # Chrome "JSON Array Format" root
        return data
    return data.get("traceEvents", [])


def _device_pids(events: List[dict]) -> set:
    """pids whose process metadata names a TPU/device track (filters host
    python threads out of the self-time accounting)."""
    pids = set()
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            name = str(e.get("args", {}).get("name", "")).lower()
            if any(k in name for k in ("tpu", "device", "xla", "/device",
                                       "tensorcore")):
                pids.add(e.get("pid"))
    return pids


def _op_track_tids(events: List[dict]) -> set:
    """(pid, tid) pairs whose thread metadata names the leaf-op track.

    A TPU trace lays the same device time out on PARALLEL tracks — "XLA
    Modules" (one span per executable), "Steps" (one per step), "XLA
    Ops" (the leaf ops).  Summing across tracks counts each microsecond
    once per track (observed: a 3-step d1024 trace reporting 'other
    77%', which was just the module+step wrappers re-counting their
    ops).  When an ops track exists, attribution uses it alone."""
    tids = set()
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            name = str(e.get("args", {}).get("name", "")).lower()
            if "xla ops" in name or name == "ops":
                tids.add((e.get("pid"), e.get("tid")))
    return tids


def _op_track_pids(op_tids: set) -> set:
    """pids that labeled an ops track.  The wrapper-track filter is
    applied PER PID: a device pid without an identified "XLA Ops" thread
    keeps plain summation — filtering it against another pid's ops track
    would silently drop that whole chip from the attribution (multi-chip
    traces do not all label the same thread names)."""
    return {pid for (pid, _tid) in op_tids}


def summarize(path: str | Path, top: int = 25) -> dict:
    files = list(_iter_trace_files(Path(path)))
    if not files:
        return {"error": f"no *.trace.json[.gz] under {path}"}
    by_name: Dict[str, float] = defaultdict(float)
    cat_of: Dict[str, str] = {}
    total = 0.0
    for f in files:
        events = _load_events(f)
        dev = _device_pids(events)
        op_tids = _op_track_tids(events)
        op_pids = _op_track_pids(op_tids)
        # Within the chosen track(s), "X" spans can still NEST; account
        # EXCLUSIVE (self) time — each span's duration minus its direct
        # children's — via an interval stack per track.
        tracks: Dict[tuple, list] = defaultdict(list)
        for e in events:
            if e.get("ph") != "X" or "dur" not in e:
                continue
            if dev and e.get("pid") not in dev:
                continue
            key = (e.get("pid"), e.get("tid"))
            if e.get("pid") in op_pids and key not in op_tids:
                continue  # module/step wrapper tracks re-count op time
            name = e.get("name", "?")
            # host-side python frames ("$file.py:123 fn") leak into traces
            # on backends without a distinct device track — drop them.
            if name.startswith("$") or ".py:" in name:
                continue
            cat = str(e.get("args", {}).get("hlo_category", ""))
            if cat and name not in cat_of:
                cat_of[name] = cat
            if "ts" not in e:
                # No timestamp → nesting is unknowable; a 0.0 default
                # would stack every span under the longest one and
                # undercount.  Plain summation for these.
                by_name[name] += float(e["dur"])
                total += float(e["dur"])
                continue
            tracks[key].append([float(e["ts"]), float(e["dur"]), name])
        for evs in tracks.values():
            # parents sort before their children (same start → longer first)
            evs.sort(key=lambda r: (r[0], -r[1]))
            selfs = [r[1] for r in evs]
            stack: list = []  # [end_ts, index] of open enclosing spans
            for i, (ts, dur, _name) in enumerate(evs):
                while stack and stack[-1][0] <= ts:
                    stack.pop()
                if stack:
                    # child time is not self time — but only the part
                    # INSIDE the parent: a malformed span that starts in
                    # the parent and ends after it must not charge its
                    # overhang against the parent's self time.
                    overlap = min(ts + dur, stack[-1][0]) - ts
                    selfs[stack[-1][1]] -= max(overlap, 0.0)
                stack.append([ts + dur, i])
            for (_ts, _dur, name), sd in zip(evs, selfs):
                sd = max(sd, 0.0)
                by_name[name] += sd
                total += sd
    if total == 0.0:
        return {"error": "no complete ('X') events with durations found"}
    by_group: Dict[str, float] = defaultdict(float)
    for name, dur in by_name.items():
        by_group[_group_of(name, cat_of.get(name, ""))] += dur
    ops = sorted(by_name.items(), key=lambda kv: -kv[1])[:top]
    return {
        "files": [str(f) for f in files],
        "total_us": round(total, 1),
        "groups": {g: {"us": round(d, 1), "pct": round(100 * d / total, 2)}
                   for g, d in sorted(by_group.items(), key=lambda kv: -kv[1])},
        "top_ops": [{"name": n, "us": round(d, 1),
                     "pct": round(100 * d / total, 2)} for n, d in ops],
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("path", help="profile dir (or one trace.json[.gz])")
    p.add_argument("--top", type=int, default=25)
    p.add_argument("--json", action="store_true",
                   help="machine-readable output only")
    args = p.parse_args(argv)
    s = summarize(args.path, top=args.top)
    if args.json or "error" in s:
        print(json.dumps(s, indent=None if args.json else 2))
        return 1 if "error" in s else 0
    print(f"total device time: {s['total_us'] / 1e3:.2f} ms "
          f"across {len(s['files'])} trace file(s)")
    print("\nby group:")
    for g, row in s["groups"].items():
        print(f"  {row['pct']:6.2f}%  {row['us'] / 1e3:9.3f} ms  {g}")
    print(f"\ntop {args.top} ops:")
    for row in s["top_ops"]:
        print(f"  {row['pct']:6.2f}%  {row['us'] / 1e3:9.3f} ms  {row['name']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
