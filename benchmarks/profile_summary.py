#!/usr/bin/env python3
"""Per-op breakdown from a ``jax.profiler`` trace directory.

`bench.py` (TPUDIST_BENCH_PROFILE=dir) and the demos (``--profile_dir``)
capture TensorBoard-style profiles; this tool turns the Chrome-trace
export (``**/*.trace.json.gz``) into the table BASELINE.md wants next to
an MFU number: top ops by device self-time, grouped, with percentages —
the "where did the non-matmul time go" evidence (VERDICT r2 weak #2).

Usage:
  python benchmarks/profile_summary.py runs/profile_mfu [--top 25]
  python benchmarks/profile_summary.py trace.json.gz --json
  python benchmarks/profile_summary.py --capture-decode \
      [--decode-dtype bf16] [--out DECODE_PROFILE_rNN.json]

Groups: names are bucketed by leading HLO opcode (fusion, dot/convolution
= MXU, copy/transpose = layout, all-reduce/collective = comm, etc.), so
the one-line summary reads like a roofline attribution.

``--capture-decode`` (VERDICT Weak #2): the decode roofline pinned the
hot loop at ~100% of its HBM bound but left a ~31% residual of device
time unattributed beyond the attention KV sweep.  This mode traces the
bf16 fused-decode-block loop itself (``make_slot_decode`` →
``decode_block``, the same program the serving engine dispatches),
emits the per-op table that NAMES that residual (fusions, layout
copies, dynamic-slice cache surgery, …), and freezes it as
``DECODE_PROFILE_r{NN}.json`` alongside the round artifacts.
"""

from __future__ import annotations

import argparse
import gzip
import json
import sys
from collections import defaultdict
from pathlib import Path
from typing import Dict, Iterable, List, Tuple

_GROUPS: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    ("matmul (MXU)", ("dot", "convolution", "cublas", "gemm")),
    ("fusion (fused elementwise/reduce)", ("fusion", "loop_fusion",
                                           "input_fusion")),
    ("collectives", ("all-reduce", "all-gather", "reduce-scatter",
                     "all-to-all", "collective", "ppermute",
                     "collective-permute", "psum")),
    ("layout/copy", ("copy", "transpose", "bitcast", "reshape")),
    ("custom (pallas/kernels)", ("custom-call", "custom_call", "tpu_custom")),
    ("dynamic slicing", ("dynamic-slice", "dynamic-update-slice", "gather",
                         "scatter")),
    ("host/infeed", ("infeed", "outfeed", "host")),
)


def _group_of(name: str, hlo_category: str = "") -> str:
    # TPU traces stamp each op with args.hlo_category ("loop fusion",
    # "custom-call", "convolution", ...) — authoritative where present
    # (instruction NAMES need not mention their opcode: the flash pallas
    # calls appear as "block_3.5").  Name heuristics are the fallback
    # for traces without args.
    for probe in (hlo_category.lower(), name.lower()):
        if not probe:
            continue
        for group, keys in _GROUPS:
            if any(k in probe for k in keys):
                return group
    return "other"


def _iter_trace_files(path: Path) -> Iterable[Path]:
    if path.is_file():
        yield path
        return
    yield from sorted(path.rglob("*.trace.json.gz"))
    yield from sorted(path.rglob("*.trace.json"))


def _load_events(path: Path) -> List[dict]:
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "rt") as f:
        data = json.load(f)
    if isinstance(data, list):  # Chrome "JSON Array Format" root
        return data
    return data.get("traceEvents", [])


def _device_pids(events: List[dict]) -> set:
    """pids whose process metadata names a TPU/device track (filters host
    python threads out of the self-time accounting)."""
    pids = set()
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            name = str(e.get("args", {}).get("name", "")).lower()
            if any(k in name for k in ("tpu", "device", "xla", "/device",
                                       "tensorcore")):
                pids.add(e.get("pid"))
    return pids


def _op_track_tids(events: List[dict]) -> set:
    """(pid, tid) pairs whose thread metadata names the leaf-op track.

    A TPU trace lays the same device time out on PARALLEL tracks — "XLA
    Modules" (one span per executable), "Steps" (one per step), "XLA
    Ops" (the leaf ops).  Summing across tracks counts each microsecond
    once per track (observed: a 3-step d1024 trace reporting 'other
    77%', which was just the module+step wrappers re-counting their
    ops).  When an ops track exists, attribution uses it alone."""
    tids = set()
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            name = str(e.get("args", {}).get("name", "")).lower()
            if "xla ops" in name or name == "ops":
                tids.add((e.get("pid"), e.get("tid")))
    return tids


def _op_track_pids(op_tids: set) -> set:
    """pids that labeled an ops track.  The wrapper-track filter is
    applied PER PID: a device pid without an identified "XLA Ops" thread
    keeps plain summation — filtering it against another pid's ops track
    would silently drop that whole chip from the attribution (multi-chip
    traces do not all label the same thread names)."""
    return {pid for (pid, _tid) in op_tids}


def summarize(path: str | Path, top: int = 25) -> dict:
    files = list(_iter_trace_files(Path(path)))
    if not files:
        return {"error": f"no *.trace.json[.gz] under {path}"}
    by_name: Dict[str, float] = defaultdict(float)
    cat_of: Dict[str, str] = {}
    total = 0.0
    for f in files:
        events = _load_events(f)
        dev = _device_pids(events)
        op_tids = _op_track_tids(events)
        op_pids = _op_track_pids(op_tids)
        # Within the chosen track(s), "X" spans can still NEST; account
        # EXCLUSIVE (self) time — each span's duration minus its direct
        # children's — via an interval stack per track.
        tracks: Dict[tuple, list] = defaultdict(list)
        for e in events:
            if e.get("ph") != "X" or "dur" not in e:
                continue
            if dev and e.get("pid") not in dev:
                continue
            key = (e.get("pid"), e.get("tid"))
            if e.get("pid") in op_pids and key not in op_tids:
                continue  # module/step wrapper tracks re-count op time
            name = e.get("name", "?")
            # host-side python frames ("$file.py:123 fn") leak into traces
            # on backends without a distinct device track — drop them.
            if name.startswith("$") or ".py:" in name:
                continue
            cat = str(e.get("args", {}).get("hlo_category", ""))
            if cat and name not in cat_of:
                cat_of[name] = cat
            if "ts" not in e:
                # No timestamp → nesting is unknowable; a 0.0 default
                # would stack every span under the longest one and
                # undercount.  Plain summation for these.
                by_name[name] += float(e["dur"])
                total += float(e["dur"])
                continue
            tracks[key].append([float(e["ts"]), float(e["dur"]), name])
        for evs in tracks.values():
            # parents sort before their children (same start → longer first)
            evs.sort(key=lambda r: (r[0], -r[1]))
            selfs = [r[1] for r in evs]
            stack: list = []  # [end_ts, index] of open enclosing spans
            for i, (ts, dur, _name) in enumerate(evs):
                while stack and stack[-1][0] <= ts:
                    stack.pop()
                if stack:
                    # child time is not self time — but only the part
                    # INSIDE the parent: a malformed span that starts in
                    # the parent and ends after it must not charge its
                    # overhang against the parent's self time.
                    overlap = min(ts + dur, stack[-1][0]) - ts
                    selfs[stack[-1][1]] -= max(overlap, 0.0)
                stack.append([ts + dur, i])
            for (_ts, _dur, name), sd in zip(evs, selfs):
                sd = max(sd, 0.0)
                by_name[name] += sd
                total += sd
    if total == 0.0:
        return {"error": "no complete ('X') events with durations found"}
    by_group: Dict[str, float] = defaultdict(float)
    for name, dur in by_name.items():
        by_group[_group_of(name, cat_of.get(name, ""))] += dur
    ops = sorted(by_name.items(), key=lambda kv: -kv[1])[:top]
    return {
        "files": [str(f) for f in files],
        "total_us": round(total, 1),
        "groups": {g: {"us": round(d, 1), "pct": round(100 * d / total, 2)}
                   for g, d in sorted(by_group.items(), key=lambda kv: -kv[1])},
        "top_ops": [{"name": n, "us": round(d, 1),
                     "pct": round(100 * d / total, 2)} for n, d in ops],
    }


def capture_decode_profile(out_path=None, *, dtype: str = "bf16",
                           d_model: int = 64, n_layers: int = 2,
                           n_heads: int = 2, vocab: int = 128,
                           max_len: int = 128, slots: int = 4,
                           k: int = 8, blocks: int = 16,
                           top: int = 25) -> dict:
    """Trace the bf16 fused decode loop and attribute its device time
    per op (module doc, ``--capture-decode``).  Returns the artifact
    dict; writes it to ``out_path`` when given."""
    import tempfile

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpudist.models import create_transformer
    from tpudist.models.generate import make_slot_decode

    compute = jnp.bfloat16 if dtype == "bf16" else jnp.float32
    module, params = create_transformer(
        jax.random.PRNGKey(0), seq_len=16, vocab=vocab, d_model=d_model,
        n_layers=n_layers, n_heads=n_heads, d_ff=4 * d_model,
        max_len=max_len, dtype=compute)
    pad = min(16, max_len)
    fns = make_slot_decode(module, params, slots, pad)
    state, cache = fns.init_state(), fns.init_slots()
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, vocab, size=(slots, pad)).astype(np.int32)
    state, cache, _ = fns.insert_batch(
        state, cache, jnp.asarray(prompts),
        jnp.full(slots, pad, jnp.int32),
        jnp.arange(slots, dtype=jnp.int32),
        jnp.zeros(slots, jnp.int32), jnp.zeros(slots, jnp.float32),
        jnp.ones(slots, bool))
    # warmup OUTSIDE the trace: the artifact attributes the steady
    # decode loop, not XLA compilation
    state, cache, toks = fns.decode_block(state, cache, k)
    jax.block_until_ready(toks)
    import shutil

    tdir = tempfile.mkdtemp(prefix="decode_profile_")
    try:
        with jax.profiler.trace(tdir):
            for _ in range(blocks):
                state, cache, toks = fns.decode_block(state, cache, k)
            jax.block_until_ready(toks)
        s = summarize(tdir, top=top)
    finally:
        # the raw XLA trace can be tens of MB; the artifact is the
        # summarized table, not the trace
        shutil.rmtree(tdir, ignore_errors=True)
    groups = s.get("groups", {})
    mxu = groups.get("matmul (MXU)", {"us": 0.0, "pct": 0.0})
    residual = {g: row for g, row in groups.items() if g != "matmul (MXU)"}
    artifact = {
        "regime": jax.devices()[0].device_kind,
        "config": {"dtype": dtype, "d_model": d_model,
                   "n_layers": n_layers, "n_heads": n_heads,
                   "max_len": max_len, "slots": slots,
                   "decode_block_k": k, "blocks_traced": blocks},
        "total_us": s.get("total_us"),
        "groups": groups,
        "top_ops": s.get("top_ops"),
        # the named residual: everything the roofline's matmul/bandwidth
        # model does not cover, ranked — fusions (elementwise chains),
        # layout copies, the dynamic-slice cache surgery, host overhead
        "matmul_pct": mxu.get("pct"),
        "residual_pct": round(100.0 - float(mxu.get("pct") or 0.0), 2),
        "residual_groups": dict(sorted(
            residual.items(), key=lambda kv: -kv[1]["us"])),
        **({"error": s["error"]} if "error" in s else {}),
    }
    if out_path is not None:
        out = Path(out_path)
        out.write_text(json.dumps(artifact, indent=2) + "\n")
        print(json.dumps({"wrote": str(out),
                          "matmul_pct": artifact["matmul_pct"],
                          "residual_pct": artifact["residual_pct"]}),
              flush=True)
    return artifact


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("path", nargs="?", default=None,
                   help="profile dir (or one trace.json[.gz])")
    p.add_argument("--top", type=int, default=25)
    p.add_argument("--json", action="store_true",
                   help="machine-readable output only")
    p.add_argument("--capture-decode", action="store_true",
                   help="trace the bf16 fused decode loop and write the "
                        "per-op residual attribution (no path needed)")
    p.add_argument("--decode-dtype", choices=("bf16", "f32"),
                   default="bf16")
    p.add_argument("--decode-blocks", type=int, default=16)
    p.add_argument("--out", default=None,
                   help="--capture-decode artifact path (default "
                        "DECODE_PROFILE_r{NN}.json at the repo root)")
    args = p.parse_args(argv)
    if args.capture_decode:
        if args.out is None:
            sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
            try:
                from benchmarks._round import current_round
            except ImportError:
                from _round import current_round

            repo = Path(__file__).resolve().parent.parent
            args.out = str(
                repo / f"DECODE_PROFILE_r{current_round():02d}.json")
        art = capture_decode_profile(
            args.out, dtype=args.decode_dtype, top=args.top,
            blocks=args.decode_blocks)
        return 1 if "error" in art else 0
    if args.path is None:
        p.error("path is required unless --capture-decode is given")
    s = summarize(args.path, top=args.top)
    if args.json or "error" in s:
        print(json.dumps(s, indent=None if args.json else 2))
        return 1 if "error" in s else 0
    print(f"total device time: {s['total_us'] / 1e3:.2f} ms "
          f"across {len(s['files'])} trace file(s)")
    print("\nby group:")
    for g, row in s["groups"].items():
        print(f"  {row['pct']:6.2f}%  {row['us'] / 1e3:9.3f} ms  {g}")
    print(f"\ntop {args.top} ops:")
    for row in s["top_ops"]:
        print(f"  {row['pct']:6.2f}%  {row['us'] / 1e3:9.3f} ms  {row['name']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
