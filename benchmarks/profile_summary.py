#!/usr/bin/env python3
"""Per-op breakdown from a ``jax.profiler`` trace directory.

`bench.py` (TPUDIST_BENCH_PROFILE=dir) and the demos (``--profile_dir``)
capture TensorBoard-style profiles; this tool turns the Chrome-trace
export (``**/*.trace.json.gz``) into the table BASELINE.md wants next to
an MFU number: top ops by device self-time, grouped, with percentages —
the "where did the non-matmul time go" evidence (VERDICT r2 weak #2).

Usage:
  python benchmarks/profile_summary.py runs/profile_mfu [--top 25]
  python benchmarks/profile_summary.py trace.json.gz --json
  python benchmarks/profile_summary.py --capture-decode \
      [--decode-dtype bf16] [--out DECODE_PROFILE_rNN.json]

Groups: names are bucketed by leading HLO opcode (fusion, dot/convolution
= MXU, copy/transpose = layout, all-reduce/collective = comm, etc.), so
the one-line summary reads like a roofline attribution.

``--capture-decode`` (VERDICT Weak #2): the decode roofline pinned the
hot loop at ~100% of its HBM bound but left a ~31% residual of device
time unattributed beyond the attention KV sweep.  This mode traces the
bf16 fused-decode-block loop itself (``make_slot_decode`` →
``decode_block``, the same program the serving engine dispatches),
emits the per-op table that NAMES that residual (fusions, layout
copies, dynamic-slice cache surgery, …), and freezes it as
``DECODE_PROFILE_r{NN}.json`` alongside the round artifacts.  It also
captures the SPECULATIVE path's three phases separately — the draft
propose loop, the batched target-verify window, and the rollback
(cursor-reset) program in isolation — so the artifact distinguishes
draft, verify, and rollback time per op group (the rollback should
profile as cursor arithmetic, ~free next to either forward).
"""

from __future__ import annotations

import argparse
import gzip
import json
import sys
from collections import defaultdict
from pathlib import Path
from typing import Dict, Iterable, List, Tuple

_GROUPS: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    ("matmul (MXU)", ("dot", "convolution", "cublas", "gemm")),
    ("fusion (fused elementwise/reduce)", ("fusion", "loop_fusion",
                                           "input_fusion")),
    ("collectives", ("all-reduce", "all-gather", "reduce-scatter",
                     "all-to-all", "collective", "ppermute",
                     "collective-permute", "psum")),
    ("layout/copy", ("copy", "transpose", "bitcast", "reshape")),
    ("custom (pallas/kernels)", ("custom-call", "custom_call", "tpu_custom")),
    ("dynamic slicing", ("dynamic-slice", "dynamic-update-slice", "gather",
                         "scatter")),
    ("host/infeed", ("infeed", "outfeed", "host")),
)


def _group_of(name: str, hlo_category: str = "") -> str:
    # TPU traces stamp each op with args.hlo_category ("loop fusion",
    # "custom-call", "convolution", ...) — authoritative where present
    # (instruction NAMES need not mention their opcode: the flash pallas
    # calls appear as "block_3.5").  Name heuristics are the fallback
    # for traces without args.
    for probe in (hlo_category.lower(), name.lower()):
        if not probe:
            continue
        for group, keys in _GROUPS:
            if any(k in probe for k in keys):
                return group
    return "other"


def _iter_trace_files(path: Path) -> Iterable[Path]:
    if path.is_file():
        yield path
        return
    yield from sorted(path.rglob("*.trace.json.gz"))
    yield from sorted(path.rglob("*.trace.json"))


def _load_events(path: Path) -> List[dict]:
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "rt") as f:
        data = json.load(f)
    if isinstance(data, list):  # Chrome "JSON Array Format" root
        return data
    return data.get("traceEvents", [])


def _device_pids(events: List[dict]) -> set:
    """pids whose process metadata names a TPU/device track (filters host
    python threads out of the self-time accounting)."""
    pids = set()
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            name = str(e.get("args", {}).get("name", "")).lower()
            if any(k in name for k in ("tpu", "device", "xla", "/device",
                                       "tensorcore")):
                pids.add(e.get("pid"))
    return pids


def _op_track_tids(events: List[dict]) -> set:
    """(pid, tid) pairs whose thread metadata names the leaf-op track.

    A TPU trace lays the same device time out on PARALLEL tracks — "XLA
    Modules" (one span per executable), "Steps" (one per step), "XLA
    Ops" (the leaf ops).  Summing across tracks counts each microsecond
    once per track (observed: a 3-step d1024 trace reporting 'other
    77%', which was just the module+step wrappers re-counting their
    ops).  When an ops track exists, attribution uses it alone."""
    tids = set()
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            name = str(e.get("args", {}).get("name", "")).lower()
            if "xla ops" in name or name == "ops":
                tids.add((e.get("pid"), e.get("tid")))
    return tids


def _op_track_pids(op_tids: set) -> set:
    """pids that labeled an ops track.  The wrapper-track filter is
    applied PER PID: a device pid without an identified "XLA Ops" thread
    keeps plain summation — filtering it against another pid's ops track
    would silently drop that whole chip from the attribution (multi-chip
    traces do not all label the same thread names)."""
    return {pid for (pid, _tid) in op_tids}


def summarize(path: str | Path, top: int = 25) -> dict:
    files = list(_iter_trace_files(Path(path)))
    if not files:
        return {"error": f"no *.trace.json[.gz] under {path}"}
    by_name: Dict[str, float] = defaultdict(float)
    cat_of: Dict[str, str] = {}
    total = 0.0
    for f in files:
        events = _load_events(f)
        dev = _device_pids(events)
        op_tids = _op_track_tids(events)
        op_pids = _op_track_pids(op_tids)
        # Within the chosen track(s), "X" spans can still NEST; account
        # EXCLUSIVE (self) time — each span's duration minus its direct
        # children's — via an interval stack per track.
        tracks: Dict[tuple, list] = defaultdict(list)
        for e in events:
            if e.get("ph") != "X" or "dur" not in e:
                continue
            if dev and e.get("pid") not in dev:
                continue
            key = (e.get("pid"), e.get("tid"))
            if e.get("pid") in op_pids and key not in op_tids:
                continue  # module/step wrapper tracks re-count op time
            name = e.get("name", "?")
            # host-side python frames ("$file.py:123 fn") leak into traces
            # on backends without a distinct device track — drop them.
            if name.startswith("$") or ".py:" in name:
                continue
            cat = str(e.get("args", {}).get("hlo_category", ""))
            if cat and name not in cat_of:
                cat_of[name] = cat
            if "ts" not in e:
                # No timestamp → nesting is unknowable; a 0.0 default
                # would stack every span under the longest one and
                # undercount.  Plain summation for these.
                by_name[name] += float(e["dur"])
                total += float(e["dur"])
                continue
            tracks[key].append([float(e["ts"]), float(e["dur"]), name])
        for evs in tracks.values():
            # parents sort before their children (same start → longer first)
            evs.sort(key=lambda r: (r[0], -r[1]))
            selfs = [r[1] for r in evs]
            stack: list = []  # [end_ts, index] of open enclosing spans
            for i, (ts, dur, _name) in enumerate(evs):
                while stack and stack[-1][0] <= ts:
                    stack.pop()
                if stack:
                    # child time is not self time — but only the part
                    # INSIDE the parent: a malformed span that starts in
                    # the parent and ends after it must not charge its
                    # overhang against the parent's self time.
                    overlap = min(ts + dur, stack[-1][0]) - ts
                    selfs[stack[-1][1]] -= max(overlap, 0.0)
                stack.append([ts + dur, i])
            for (_ts, _dur, name), sd in zip(evs, selfs):
                sd = max(sd, 0.0)
                by_name[name] += sd
                total += sd
    if total == 0.0:
        return {"error": "no complete ('X') events with durations found"}
    by_group: Dict[str, float] = defaultdict(float)
    for name, dur in by_name.items():
        by_group[_group_of(name, cat_of.get(name, ""))] += dur
    ops = sorted(by_name.items(), key=lambda kv: -kv[1])[:top]
    return {
        "files": [str(f) for f in files],
        "total_us": round(total, 1),
        "groups": {g: {"us": round(d, 1), "pct": round(100 * d / total, 2)}
                   for g, d in sorted(by_group.items(), key=lambda kv: -kv[1])},
        "top_ops": [{"name": n, "us": round(d, 1),
                     "pct": round(100 * d / total, 2)} for n, d in ops],
    }


def _trace_phase(fn, blocks: int, top: int) -> dict:
    """Trace ``blocks`` invocations of ``fn`` (a thunk advancing its own
    state) into a throwaway dir and return the per-op summary."""
    import shutil
    import tempfile

    import jax

    tdir = tempfile.mkdtemp(prefix="decode_profile_")
    try:
        with jax.profiler.trace(tdir):
            out = None
            for _ in range(blocks):
                out = fn()
            jax.block_until_ready(out)
        return summarize(tdir, top=top)
    finally:
        # the raw XLA trace can be tens of MB; the artifact is the
        # summarized table, not the trace
        shutil.rmtree(tdir, ignore_errors=True)


def _slice_table(table, keys=("total_us", "groups", "top_ops", "error")):
    """Phase-table slice + the cross-phase comparison metric: on
    backends without a distinct device track (CPU smoke) the "other"
    bucket absorbs host/trace bookkeeping, so attributed-op time
    (``op_us_excl_other``) is what phases compare on."""
    out = {kk: table.get(kk) for kk in keys if kk in table}
    groups = table.get("groups") or {}
    other = (groups.get("other") or {}).get("us", 0.0)
    if table.get("total_us") is not None:
        out["op_us_excl_other"] = round(table["total_us"] - other, 1)
    return out


def capture_decode_profile(out_path=None, *, dtype: str = "bf16",
                           d_model: int = 64, n_layers: int = 2,
                           n_heads: int = 2, vocab: int = 128,
                           max_len: int = 128, slots: int = 4,
                           k: int = 8, blocks: int = 16,
                           top: int = 25, spec: bool = True,
                           paged: bool = True,
                           family: bool = True) -> dict:
    """Trace the bf16 fused decode loop and attribute its device time
    per op (module doc, ``--capture-decode``).  Returns the artifact
    dict; writes it to ``out_path`` when given.

    ``spec``: also trace the speculative path's three phases separately
    — the draft propose loop, the batched target-verify pass, and the
    rollback (cursor-reset) program in isolation — so the residual
    table distinguishes where a spec block's device time goes (the
    rollback is cursor arithmetic and should profile as ~free; the
    table proves it instead of asserting it).

    ``paged``: additionally trace the PAGED decode loop twice — the
    gather path (dense view per dispatch) and the Pallas
    paged-attention kernel path — as separate phase rows, so the
    artifact splits paged-kernel time (the ``custom (pallas/kernels)``
    group on TPU; interpret-lowered ops on CPU) from the residual
    fusion/layout ops the kernel exists to shrink.

    ``family``: trace the rest of the kernel family (PR 19) as phase
    rows — ``prefill.gather`` vs ``prefill.kernel`` (the batched
    admission prefill, gather path vs the paged-prefill flash kernel
    writing KV blocks in-kernel), ``sample.kernel`` (the fused
    sampling tail riding the decode loop), ``rope_qkv.kernel`` (fused
    RoPE+QKV on the paged decode arm) and ``lora.kernel`` (the
    in-kernel adapter gather-matmul) — so the frozen artifact shows
    each fused path's residual next to its in-graph twin."""
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpudist.models import create_transformer
    from tpudist.models.generate import make_slot_decode, tied_draft

    compute = jnp.bfloat16 if dtype == "bf16" else jnp.float32
    module, params = create_transformer(
        jax.random.PRNGKey(0), seq_len=16, vocab=vocab, d_model=d_model,
        n_layers=n_layers, n_heads=n_heads, d_ff=4 * d_model,
        max_len=max_len, dtype=compute)
    pad = min(16, max_len)
    fns = make_slot_decode(module, params, slots, pad)
    state, cache = fns.init_state(), fns.init_slots()
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, vocab, size=(slots, pad)).astype(np.int32)
    state, cache, _ = fns.insert_batch(
        state, cache, jnp.asarray(prompts),
        jnp.full(slots, pad, jnp.int32),
        jnp.arange(slots, dtype=jnp.int32),
        jnp.zeros(slots, jnp.int32), jnp.zeros(slots, jnp.float32),
        jnp.ones(slots, bool))
    # warmup OUTSIDE the trace: the artifact attributes the steady
    # decode loop, not XLA compilation
    state, cache, toks = fns.decode_block(state, cache, k)
    jax.block_until_ready(toks)

    carry = {"state": state, "cache": cache}

    def plain_block():
        carry["state"], carry["cache"], toks = fns.decode_block(
            carry["state"], carry["cache"], k)
        return toks

    s = _trace_phase(plain_block, blocks, top)

    spec_tables = None
    if spec:
        dpair = tied_draft(module, params, max(1, n_layers // 2))
        dparams = dpair[1]
        sfns = make_slot_decode(module, params, slots, pad, spec=dpair)
        sstate, scache = sfns.init_state(), sfns.init_slots()
        dcache = sfns.init_draft()
        sstate, scache, _ = sfns.insert_batch(
            sstate, scache, jnp.asarray(prompts),
            jnp.full(slots, pad, jnp.int32),
            jnp.arange(slots, dtype=jnp.int32),
            jnp.zeros(slots, jnp.int32), jnp.zeros(slots, jnp.float32),
            jnp.ones(slots, bool))
        dcache = sfns.draft_prefill(
            dcache, jnp.asarray(prompts), jnp.full(slots, pad, jnp.int32),
            jnp.arange(slots, dtype=jnp.int32), dparams)
        sk = min(k, 4)
        spec_on = jnp.ones(slots, bool)
        rem = jnp.full(slots, max_len, jnp.int32)
        # warmup every phase program outside the traces
        dcache, drafts, dlogits = sfns.draft_propose(sstate, dcache, sk,
                                                     dparams)
        sstate, scache, dcache, packed = sfns.spec_verify(
            sstate, scache, dcache, drafts, dlogits, spec_on, rem)
        jax.block_until_ready(packed)

        # draft phase: the propose loop alone (cursor advances sk+1 per
        # call; the budget above keeps every call in bounds)
        dc = {"d": dcache}

        def draft_phase():
            dc["d"], dr, _ = sfns.draft_propose(sstate, dc["d"], sk,
                                                dparams)
            return dr

        n_phase = min(blocks, max(2, (max_len - 2 * pad) // (sk + 1) - 2))
        draft_table = _trace_phase(draft_phase, n_phase, top)

        # verify phase: the batched target-verify (rollback included,
        # as in production) re-verifying one proposal repeatedly
        vc = {"s": sstate, "c": scache, "d": dc["d"]}

        def verify_phase():
            vc["s"], vc["c"], vc["d"], pk = sfns.spec_verify(
                vc["s"], vc["c"], vc["d"], drafts, dlogits, spec_on, rem)
            return pk

        verify_table = _trace_phase(verify_phase, n_phase, top)

        # rollback phase in isolation: the cursor-reset program alone
        # (every non-K/V cache leaf overwritten with the clamped
        # cursor, exactly what spec_verify's rollback does in-graph) —
        # what rollback costs with no forward attached
        def _roll(cache, cur):
            out = {}
            for key, val in cache.items():
                if isinstance(val, dict) and "k" in val and "v" in val:
                    out[key] = {k2: (v2 if k2 in ("k", "v")
                                     else cur.astype(v2.dtype))
                                for k2, v2 in val.items()}
                else:
                    out[key] = cur.astype(val.dtype)
            return out

        # donated like the real program — without donation XLA would
        # copy the untouched K/V leaves and bill rollback for a full
        # arena memcpy it never pays in production
        roll = jax.jit(_roll, donate_argnums=0)
        rb = {"c": vc["c"]}
        cur = jnp.full(slots, pad, jnp.int32)

        def rollback_phase():
            rb["c"] = roll(rb["c"], cur)
            return rb["c"]

        rollback_table = _trace_phase(rollback_phase, blocks, top)

        spec_tables = {
            "draft_k": sk,
            "draft": _slice_table(draft_table),
            "verify": _slice_table(verify_table),
            "rollback": _slice_table(rollback_table, ("total_us", "groups",
                                                      "error")),
        }
    paged_tables = None
    if paged:
        # -- paged decode: gather vs the Pallas kernel, phase by phase.
        # Same geometry, same traffic; the kernel row's attention time
        # lands in "custom (pallas/kernels)" on TPU traces (interpret-
        # lowered ops on CPU), split from the fusion/layout residual
        # the dense-view gather pays.
        from tpudist.models.paged import PagedKVConfig

        kv_block = 16 if max_len % 16 == 0 else max_len
        pcfg = PagedKVConfig(num_blocks=slots * (max_len // kv_block),
                             block_size=kv_block)
        paged_tables = {"kv_block": kv_block}
        for arm in ("gather", "paged"):
            pfns = make_slot_decode(module, params, slots, pad,
                                    paged=pcfg, attn_kernel=arm)
            pstate, pkv = pfns.init_state(), pfns.init_slots()
            M = max_len // kv_block
            tables = np.stack([np.arange(j * M, (j + 1) * M)
                               for j in range(slots)]).astype(np.int32)
            pstate, pkv, _ = pfns.insert_batch(
                pstate, pkv, jnp.asarray(tables),
                jnp.zeros(slots, jnp.int32), jnp.asarray(prompts),
                jnp.full(slots, pad, jnp.int32),
                jnp.arange(slots, dtype=jnp.int32),
                jnp.zeros(slots, jnp.int32), jnp.zeros(slots, jnp.float32),
                jnp.ones(slots, bool))
            pstate, pkv, ptoks = pfns.decode_block(pstate, pkv, k)  # warmup
            jax.block_until_ready(ptoks)
            pc = {"state": pstate, "kv": pkv}

            def paged_block():
                pc["state"], pc["kv"], t = pfns.decode_block(
                    pc["state"], pc["kv"], k)
                return t

            n_pb = min(blocks, max(2, (max_len - 2 * pad) // k - 1))
            table = _trace_phase(paged_block, n_pb, top)
            key = "kernel" if arm == "paged" else arm
            paged_tables[key] = _slice_table(table)
            kg = (table.get("groups") or {}).get(
                "custom (pallas/kernels)") or {}
            paged_tables[key]["kernel_us"] = kg.get("us", 0.0)
            paged_tables[key]["kernel_pct"] = kg.get("pct", 0.0)

    family_tables = None
    if family:
        from tpudist.models.paged import PagedKVConfig

        kv_block = 16 if max_len % 16 == 0 else max_len
        pcfg = PagedKVConfig(num_blocks=slots * (max_len // kv_block),
                             block_size=kv_block)
        M = max_len // kv_block
        tables = np.stack([np.arange(j * M, (j + 1) * M)
                           for j in range(slots)]).astype(np.int32)
        ins_args = (jnp.asarray(tables), jnp.zeros(slots, jnp.int32),
                    jnp.asarray(prompts), jnp.full(slots, pad, jnp.int32),
                    jnp.arange(slots, dtype=jnp.int32),
                    jnp.zeros(slots, jnp.int32),
                    jnp.zeros(slots, jnp.float32), jnp.ones(slots, bool))
        family_tables = {"kv_block": kv_block}

        def _prefill_row(**kw):
            """Trace the batched admission prefill alone: the same
            insert re-dispatched (state/cache threaded; admitting the
            same slots again is a plain overwrite, so the program sees
            steady-state shapes every call)."""
            ffns = make_slot_decode(module, params, slots, pad,
                                    paged=pcfg, **kw)
            fc = {"s": ffns.init_state(), "c": ffns.init_slots()}
            fc["s"], fc["c"], w = ffns.insert_batch(  # warmup
                fc["s"], fc["c"], *ins_args)
            jax.block_until_ready(w)

            def thunk():
                fc["s"], fc["c"], t = ffns.insert_batch(
                    fc["s"], fc["c"], *ins_args)
                return t

            return _slice_table(_trace_phase(thunk, blocks, top))

        family_tables["prefill.gather"] = _prefill_row()
        family_tables["prefill.kernel"] = _prefill_row(prefill_kernel=True)

        def _decode_row(tail=(), **kw):
            """One decode-loop phase row with the given knobs (``tail``
            is the adapter tail: insert takes ``(aids, apool)``, decode
            just ``(apool,)``)."""
            ffns = make_slot_decode(module, params, slots, pad,
                                    paged=pcfg, **kw)
            fs, fcache = ffns.init_state(), ffns.init_slots()
            fs, fcache, _ = ffns.insert_batch(fs, fcache, *ins_args,
                                              *tail)
            fs, fcache, w = ffns.decode_block(fs, fcache, k, *tail[1:])
            jax.block_until_ready(w)
            fc = {"s": fs, "c": fcache}

            def thunk():
                fc["s"], fc["c"], t = ffns.decode_block(
                    fc["s"], fc["c"], k, *tail[1:])
                return t

            n_fb = min(blocks, max(2, (max_len - 2 * pad) // k - 1))
            return _slice_table(_trace_phase(thunk, n_fb, top))

        family_tables["sample.kernel"] = _decode_row(sample_kernel=True)
        family_tables["rope_qkv.kernel"] = _decode_row(
            attn_kernel="paged", fused_rope=True)
        from tpudist.models.lora import (AdapterPoolConfig,
                                         init_adapter_pool,
                                         load_factors,
                                         make_adapter_factors)

        acfg = AdapterPoolConfig(num_blocks=2, rank=4)
        apool = load_factors(
            init_adapter_pool(module, acfg), 0,
            make_adapter_factors(jax.random.PRNGKey(7), module, 4))
        family_tables["lora.kernel"] = _decode_row(
            tail=(jnp.zeros(slots, jnp.int32), apool),
            attn_kernel="paged", adapters=acfg, lora_kernel=True)

    groups = s.get("groups", {})
    mxu = groups.get("matmul (MXU)", {"us": 0.0, "pct": 0.0})
    residual = {g: row for g, row in groups.items() if g != "matmul (MXU)"}
    artifact = {
        "regime": jax.devices()[0].device_kind,
        "config": {"dtype": dtype, "d_model": d_model,
                   "n_layers": n_layers, "n_heads": n_heads,
                   "max_len": max_len, "slots": slots,
                   "decode_block_k": k, "blocks_traced": blocks},
        "total_us": s.get("total_us"),
        "groups": groups,
        "top_ops": s.get("top_ops"),
        # the named residual: everything the roofline's matmul/bandwidth
        # model does not cover, ranked — fusions (elementwise chains),
        # layout copies, the dynamic-slice cache surgery, host overhead
        "matmul_pct": mxu.get("pct"),
        "residual_pct": round(100.0 - float(mxu.get("pct") or 0.0), 2),
        "residual_groups": dict(sorted(
            residual.items(), key=lambda kv: -kv[1]["us"])),
        **({"spec": spec_tables} if spec_tables is not None else {}),
        **({"paged": paged_tables} if paged_tables is not None else {}),
        **({"family": family_tables} if family_tables is not None else {}),
        **({"error": s["error"]} if "error" in s else {}),
    }
    if out_path is not None:
        out = Path(out_path)
        out.write_text(json.dumps(artifact, indent=2) + "\n")
        print(json.dumps({"wrote": str(out),
                          "matmul_pct": artifact["matmul_pct"],
                          "residual_pct": artifact["residual_pct"]}),
              flush=True)
    return artifact


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("path", nargs="?", default=None,
                   help="profile dir (or one trace.json[.gz])")
    p.add_argument("--top", type=int, default=25)
    p.add_argument("--json", action="store_true",
                   help="machine-readable output only")
    p.add_argument("--capture-decode", action="store_true",
                   help="trace the bf16 fused decode loop and write the "
                        "per-op residual attribution (no path needed)")
    p.add_argument("--decode-dtype", choices=("bf16", "f32"),
                   default="bf16")
    p.add_argument("--decode-blocks", type=int, default=16)
    p.add_argument("--out", default=None,
                   help="--capture-decode artifact path (default "
                        "DECODE_PROFILE_r{NN}.json at the repo root)")
    args = p.parse_args(argv)
    if args.capture_decode:
        if args.out is None:
            sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
            try:
                from benchmarks._round import current_round
            except ImportError:
                from _round import current_round

            repo = Path(__file__).resolve().parent.parent
            args.out = str(
                repo / f"DECODE_PROFILE_r{current_round():02d}.json")
        art = capture_decode_profile(
            args.out, dtype=args.decode_dtype, top=args.top,
            blocks=args.decode_blocks)
        return 1 if "error" in art else 0
    if args.path is None:
        p.error("path is required unless --capture-decode is given")
    s = summarize(args.path, top=args.top)
    if args.json or "error" in s:
        print(json.dumps(s, indent=None if args.json else 2))
        return 1 if "error" in s else 0
    print(f"total device time: {s['total_us'] / 1e3:.2f} ms "
          f"across {len(s['files'])} trace file(s)")
    print("\nby group:")
    for g, row in s["groups"].items():
        print(f"  {row['pct']:6.2f}%  {row['us'] / 1e3:9.3f} ms  {g}")
    print(f"\ntop {args.top} ops:")
    for row in s["top_ops"]:
        print(f"  {row['pct']:6.2f}%  {row['us'] / 1e3:9.3f} ms  {row['name']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
