#!/usr/bin/env python3
"""Per-op breakdown from a ``jax.profiler`` trace directory.

`bench.py` (TPUDIST_BENCH_PROFILE=dir) and the demos (``--profile_dir``)
capture TensorBoard-style profiles; this tool turns the Chrome-trace
export (``**/*.trace.json.gz``) into the table BASELINE.md wants next to
an MFU number: top ops by device self-time, grouped, with percentages —
the "where did the non-matmul time go" evidence (VERDICT r2 weak #2).

Usage:
  python benchmarks/profile_summary.py runs/profile_mfu [--top 25]
  python benchmarks/profile_summary.py trace.json.gz --json

Groups: names are bucketed by leading HLO opcode (fusion, dot/convolution
= MXU, copy/transpose = layout, all-reduce/collective = comm, etc.), so
the one-line summary reads like a roofline attribution.
"""

from __future__ import annotations

import argparse
import gzip
import json
import sys
from collections import defaultdict
from pathlib import Path
from typing import Dict, Iterable, List, Tuple

_GROUPS: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    ("matmul (MXU)", ("dot", "convolution", "cublas", "gemm")),
    ("fusion (fused elementwise/reduce)", ("fusion", "loop_fusion",
                                           "input_fusion")),
    ("collectives", ("all-reduce", "all-gather", "reduce-scatter",
                     "all-to-all", "collective", "ppermute",
                     "collective-permute", "psum")),
    ("layout/copy", ("copy", "transpose", "bitcast", "reshape")),
    ("custom (pallas/kernels)", ("custom-call", "custom_call", "tpu_custom")),
    ("dynamic slicing", ("dynamic-slice", "dynamic-update-slice", "gather",
                         "scatter")),
    ("host/infeed", ("infeed", "outfeed", "host")),
)


def _group_of(name: str) -> str:
    low = name.lower()
    for group, keys in _GROUPS:
        if any(k in low for k in keys):
            return group
    return "other"


def _iter_trace_files(path: Path) -> Iterable[Path]:
    if path.is_file():
        yield path
        return
    yield from sorted(path.rglob("*.trace.json.gz"))
    yield from sorted(path.rglob("*.trace.json"))


def _load_events(path: Path) -> List[dict]:
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "rt") as f:
        data = json.load(f)
    if isinstance(data, list):  # Chrome "JSON Array Format" root
        return data
    return data.get("traceEvents", [])


def _device_pids(events: List[dict]) -> set:
    """pids whose process metadata names a TPU/device track (filters host
    python threads out of the self-time accounting)."""
    pids = set()
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            name = str(e.get("args", {}).get("name", "")).lower()
            if any(k in name for k in ("tpu", "device", "xla", "/device",
                                       "tensorcore")):
                pids.add(e.get("pid"))
    return pids


def summarize(path: str | Path, top: int = 25) -> dict:
    files = list(_iter_trace_files(Path(path)))
    if not files:
        return {"error": f"no *.trace.json[.gz] under {path}"}
    by_name: Dict[str, float] = defaultdict(float)
    total = 0.0
    for f in files:
        events = _load_events(f)
        dev = _device_pids(events)
        for e in events:
            if e.get("ph") != "X" or "dur" not in e:
                continue
            if dev and e.get("pid") not in dev:
                continue
            name = e.get("name", "?")
            # host-side python frames ("$file.py:123 fn") leak into traces
            # on backends without a distinct device track — drop them.
            if name.startswith("$") or ".py:" in name:
                continue
            dur = float(e["dur"])  # microseconds
            by_name[name] += dur
            total += dur
    if total == 0.0:
        return {"error": "no complete ('X') events with durations found"}
    by_group: Dict[str, float] = defaultdict(float)
    for name, dur in by_name.items():
        by_group[_group_of(name)] += dur
    ops = sorted(by_name.items(), key=lambda kv: -kv[1])[:top]
    return {
        "files": [str(f) for f in files],
        "total_us": round(total, 1),
        "groups": {g: {"us": round(d, 1), "pct": round(100 * d / total, 2)}
                   for g, d in sorted(by_group.items(), key=lambda kv: -kv[1])},
        "top_ops": [{"name": n, "us": round(d, 1),
                     "pct": round(100 * d / total, 2)} for n, d in ops],
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("path", help="profile dir (or one trace.json[.gz])")
    p.add_argument("--top", type=int, default=25)
    p.add_argument("--json", action="store_true",
                   help="machine-readable output only")
    args = p.parse_args(argv)
    s = summarize(args.path, top=args.top)
    if args.json or "error" in s:
        print(json.dumps(s, indent=None if args.json else 2))
        return 1 if "error" in s else 0
    print(f"total device time: {s['total_us'] / 1e3:.2f} ms "
          f"across {len(s['files'])} trace file(s)")
    print("\nby group:")
    for g, row in s["groups"].items():
        print(f"  {row['pct']:6.2f}%  {row['us'] / 1e3:9.3f} ms  {g}")
    print(f"\ntop {args.top} ops:")
    for row in s["top_ops"]:
        print(f"  {row['pct']:6.2f}%  {row['us'] / 1e3:9.3f} ms  {row['name']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
