#!/usr/bin/env python3
"""Pipeline-schedule comparison: GPipe vs 1F1B memory and bubble math.

The point of 1F1B (``tpudist.parallel.pipeline.pipeline_1f1b_shard``) is
that peak residual memory is O(n_stages) — CONSTANT in the microbatch
count — while GPipe's autodiff backward keeps every microbatch's residuals
live at the forward/backward phase boundary, so its memory grows with M.
Both schedules idle (S−1) fill + (S−1) drain slots; raising M amortizes
that bubble — which only 1F1B can afford memory-wise.

This harness makes that concrete: for S stages and a ladder of M values it
compiles BOTH train steps on the (data × stage) mesh and reports

- XLA's compiled peak temp-buffer bytes per device
  (``compiled.memory_analysis()`` — temp allocations hold the live
  activations/residuals, the thing 1F1B bounds), and
- the analytic bubble fraction of each schedule's tick loop:
  GPipe runs M+S−1 forward ticks then M+S−1 backward ticks → idle
  fraction (S−1)/(M+S−1); the SPMD-uniform "eager" 1F1B here runs
  M+2(S−1) combined fwd+bwd ticks → idle fraction 2(S−1)/(M+2S−2).

Works on the virtual CPU mesh (schedule math and compiled memory are
platform-meaningful there; wall-clock is not measured).

Usage:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python benchmarks/pp_schedules.py [--stages 4] [--micro 4,8,16]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import numpy as np
import optax
from jax.sharding import Mesh


def _peak_temp_bytes(jitted, *args):
    """Per-device temp-allocation peak from XLA's memory analysis."""
    compiled = jitted.lower(*args).compile()
    ma = compiled.memory_analysis()
    if ma is None:  # backend without the analysis API
        return None
    return int(getattr(ma, "temp_size_in_bytes", 0))


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--stages", type=int, default=4)
    p.add_argument("--micro", default="4,8,16",
                   help="comma list of microbatch counts")
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--d-model", type=int, default=64)
    p.add_argument("--batch-per-micro", type=int, default=2,
                   help="global batch = this * num_micro (so per-micro "
                        "work stays fixed while M grows)")
    args = p.parse_args(argv)

    if jax.default_backend() != "cpu" and jax.device_count() < 2:
        print(json.dumps({"error": "need a multi-device mesh"}))
        return []

    from tpudist.models import create_transformer
    from tpudist.parallel import (
        make_pp_lm_apply,
        make_pp_lm_train_step,
        pp_state_sharding,
        stack_block_params,
    )
    from tpudist.runtime.mesh import AXIS_DATA, AXIS_STAGE
    from tpudist.train import init_lm_state, make_lm_train_step, token_sharding

    S = args.stages
    n_dev = jax.device_count()
    data = n_dev // S
    if data < 1 or n_dev % S:
        raise SystemExit(f"{n_dev} devices do not fit {S} stages")
    mesh = Mesh(np.asarray(jax.devices()).reshape(data, S),
                axis_names=(AXIS_DATA, AXIS_STAGE))

    # 2 layers per stage so the same model also splits into the
    # interleaved layout's 2·S virtual stages (1 layer per chunk).
    module, params = create_transformer(
        jax.random.PRNGKey(0), seq_len=args.seq_len, vocab=64,
        d_model=args.d_model, n_layers=2 * S, n_heads=4,
        d_ff=4 * args.d_model, max_len=args.seq_len)
    tx = optax.adam(1e-3)
    pp = stack_block_params(params, S)
    state = init_lm_state(pp, tx)
    shard = pp_state_sharding(mesh, state)
    state = jax.device_put(state, shard)

    rows = []
    for m in (int(x) for x in args.micro.split(",")):
        batch = args.batch_per_micro * m * data
        tokens = jax.device_put(
            np.random.default_rng(0).integers(
                0, 64, size=(batch, args.seq_len)).astype(np.int32),
            token_sharding(mesh))

        apply_g = make_pp_lm_apply(mesh, module, n_stages=S,
                                   num_microbatches=m)
        step_g = make_lm_train_step(apply_g, tx, mesh, donate_state=False,
                                    state_sharding=shard)
        step_f = make_pp_lm_train_step(
            mesh, module, tx, n_stages=S, num_microbatches=m,
            schedule="1f1b", donate_state=False, state_sharding=shard)

        row = {
            "stages": S, "num_micro": m, "global_batch": batch,
            "bubble_gpipe": round((S - 1) / (m + S - 1), 4),
            "bubble_1f1b": round(2 * (S - 1) / (m + 2 * S - 2), 4),
            "temp_bytes_gpipe": _peak_temp_bytes(step_g, state, tokens),
            "temp_bytes_1f1b": _peak_temp_bytes(step_f, state, tokens),
        }
        if row["temp_bytes_gpipe"] and row["temp_bytes_1f1b"]:
            row["mem_ratio_1f1b_vs_gpipe"] = round(
                row["temp_bytes_1f1b"] / row["temp_bytes_gpipe"], 3)

        # Interleaved 1F1B at V=2: the 2S-layer model re-laid out into
        # 2S one-layer virtual stages; needs layers % (V·S) == 0 and the
        # Megatron grouping constraint M % S == 0.
        V = 2
        if (2 * S) % (V * S) == 0 and m % S == 0:
            from tpudist.parallel import stack_block_params_interleaved
            from tpudist.parallel.pipeline_interleaved import (
                interleaved_schedule)
            pp_i = stack_block_params_interleaved(params, S, V)
            st_i = init_lm_state(pp_i, tx)
            sh_i = pp_state_sharding(mesh, st_i)
            st_i = jax.device_put(st_i, sh_i)
            step_i = make_pp_lm_train_step(
                mesh, module, tx, n_stages=S, num_microbatches=m,
                schedule="interleaved", n_chunks=V, donate_state=False,
                state_sharding=sh_i)
            sched = interleaved_schedule(S, V, m)
            # Tick duration scales ~1/V, so the plain tick fraction is
            # already wall-clock-comparable to the analytic formulas.
            row["bubble_interleaved_v2"] = round(
                sched.bubble_ticks / sched.total_ticks, 4)
            row["temp_bytes_interleaved_v2"] = _peak_temp_bytes(
                step_i, st_i, tokens)
        else:
            print(json.dumps({"note": "interleaved row skipped",
                              "needs": f"M % {S} == 0"}), flush=True)
        rows.append(row)
        print(json.dumps(row), flush=True)
    return rows


if __name__ == "__main__":
    main()
