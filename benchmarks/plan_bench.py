#!/usr/bin/env python3
"""Planner honesty loop: predict-vs-measure on live rung geometries.

The planner (``tpudist.plan``) claims it can rank configs from the
frozen artifacts.  This bench closes the loop: on each rung geometry it

1. MEASURES the base candidate (``dp`` for training, the dense-``K=8``
   engine for serving) plus a micro-measured all-reduce bandwidth and
   feeds both in as a :class:`tpudist.plan.Calibration`,
2. PREDICTS every candidate through the same ``plan_training`` /
   ``plan_serving`` entry points the auto modes call,
3. MEASURES every candidate for real — training steps through the same
   step factories ``Trainer._fit_lm`` builds, serving rungs through a
   live ``InferenceServer`` driven by ``serve_bench.run_rate`` — and
4. freezes per-config ``predicted_s`` / ``measured_s`` / ``error_frac``
   plus the predicted-best-vs-measured-best verdict into
   ``PLAN_r{NN}.json``.

The frozen ``error_band`` (max/p50 ``error_frac``) is what
``planner._error_band`` quotes on every future plan report: the
planner's predictions come with the measured size of their own error.

Rung geometries (two per workload, so a ranking that only works at one
scale is caught): training on 4- and 8-device virtual CPU meshes
(subprocess-pinned, the round_snapshot trick); serving on two engine
geometries (slots x max_len).  Virtual-CPU rungs validate the planner's
MECHANICS — the match verdict and error band are real measurements of
the cost model on this host, not hardware truth.

Usage: python benchmarks/plan_bench.py [--round N] [--out PATH]
                                       [--iters N] [--requests N]
"""

from __future__ import annotations

import argparse
import json
import statistics
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

#: Measured-vs-measured tie tolerance for the match verdict: the plan
#: is correct when its pick measures within this fraction of the true
#: floor.  Sized to this host's observed run-to-run variance — the
#: near-tied sharded-family configs (fsdp vs zero1) flip ordering
#: across runs by up to ~8%, so a tighter verdict would grade noise,
#: not the planner.
MATCH_RTOL = 0.10

_STUB = """
import os
# BOTH pins are required: jax.config for this process's first backend
# resolution, and the env var for every code path that re-resolves from
# the environment (the round_snapshot virtual-mesh trick).
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count={devices}")
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", {devices})
except AttributeError:
    pass  # older jax: the XLA_FLAGS pin above did the job
import sys
sys.path.insert(0, {repo!r})
sys.argv = ["plan_bench"]
import importlib.util
spec = importlib.util.spec_from_file_location(
    "plan_bench", {repo!r} + "/benchmarks/plan_bench.py")
mod = importlib.util.module_from_spec(spec)
spec.loader.exec_module(mod)
mod.main({argv!r})
"""


_ROUND_RE = __import__("re").compile(r"^[A-Z][A-Z0-9_]*_r(\d+)\.json$")


def detect_round() -> int:
    """One past the highest round ANY family has frozen.  The plain
    ``BENCH_r*`` counter (benchmarks/_round.py) lags the per-family
    artifacts by many rounds in this tree; writing PLAN under its number
    would fail the artifact loader's stale check against the newest
    BENCH_SERVE round."""
    rounds = [int(m.group(1)) for p in REPO.glob("*_r*.json")
              if (m := _ROUND_RE.match(p.name))]
    return (max(rounds) + 1) if rounds else 1


# -- training rung ------------------------------------------------------


def _train_candidates(n_devices):
    from tpudist.plan import TrainCandidate

    cands = [TrainCandidate("dp"), TrainCandidate("fsdp"),
             TrainCandidate("zero1")]
    if n_devices >= 4:
        # the facade's pp default: stages=2, one microbatch per stage
        cands.append(TrainCandidate("pp", stages=2, microbatches=2))
    return cands


def _make_train_runner(cand, flax_mod, params, tx, tokens):
    """Compiled step runner for one candidate, built EXACTLY the way
    ``Trainer._fit_lm`` builds it (same factories, same sharding
    derivation) — the bench measures what the plan enacts.  Returns a
    closure ``run(iters) -> seconds_per_step`` over persistent state."""
    import jax

    from tpudist.train import init_lm_state, make_lm_train_step, \
        token_sharding

    if cand.strategy == "pp":
        from tpudist.parallel import (
            make_pp_lm_train_step,
            pp_state_sharding,
            stack_block_params,
        )
        from tpudist.runtime.mesh import MeshConfig, make_mesh

        mesh = make_mesh(MeshConfig(data=-1, stage=cand.stages),
                         axis_names=("data", "stage"))
        state = init_lm_state(stack_block_params(params, cand.stages), tx)
        sharding = pp_state_sharding(mesh, state)
        state = jax.device_put(state, sharding)
        step = make_pp_lm_train_step(
            mesh, flax_mod, tx, n_stages=cand.stages,
            num_microbatches=cand.microbatches or cand.stages,
            schedule="1f1b", state_sharding=sharding)
    else:
        from tpudist.runtime.mesh import data_parallel_mesh

        mesh = data_parallel_mesh()
        state = init_lm_state(params, tx)
        sharding = None
        if cand.strategy in ("fsdp", "zero1"):
            from tpudist.parallel import fsdp_sharding, zero1_sharding

            sharding = (fsdp_sharding(mesh, state)
                        if cand.strategy == "fsdp"
                        else zero1_sharding(mesh, state))
            state = jax.device_put(state, sharding)
        step = make_lm_train_step(flax_mod.apply, tx, mesh,
                                  state_sharding=sharding)

    toks = jax.device_put(tokens, token_sharding(mesh))
    state, loss = step(state, toks)  # compile
    jax.block_until_ready(loss)
    box = [state]

    def run(iters: int) -> float:
        st = box[0]
        t0 = time.perf_counter()
        for _ in range(iters):
            st, loss = step(st, toks)
        jax.block_until_ready((st, loss))
        dt = (time.perf_counter() - t0) / iters
        box[0] = st
        return dt

    return run


def _interleaved_measure(runners: dict, iters: int,
                         reps: int = 3) -> dict:
    """Per-candidate best seconds/step, timed ROUND-ROBIN: each rep
    cycles through every candidate before the next rep starts, so host
    load drift hits all candidates equally instead of biasing whichever
    one ran during a quiet minute (back-to-back blocks measured up to
    ~20% cross-candidate skew on this box)."""
    best = {name: float("inf") for name in runners}
    for _ in range(reps):
        for name, run in runners.items():
            best[name] = min(best[name], run(iters))
    return best


def _collective_bandwidth() -> "float | None":
    """Micro-measured all-reduce bandwidth on the data mesh, in the same
    units the cost model divides by (``wire_bytes / bw``): ring-factor
    bytes moved per second."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from tpudist.parallel.overlap import compat_shard_map
    from tpudist.runtime.mesh import data_parallel_mesh

    n = jax.device_count()
    if n < 2:
        return None
    mesh = data_parallel_mesh()
    m = 1 << 18  # 1 MiB of f32 per shard
    x = jnp.ones((n, m), jnp.float32)
    f = jax.jit(compat_shard_map(
        lambda v: jax.lax.psum(v, "data"), mesh=mesh,
        in_specs=P("data"), out_specs=P()))
    jax.block_until_ready(f(x))  # compile
    iters = 20
    t0 = time.perf_counter()
    for _ in range(iters):
        out = f(x)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    ring_bytes = 2.0 * (n - 1) / n * (m * 4)
    return ring_bytes / max(dt, 1e-9)


def _calibrate_state_ratio(tx, iters: int) -> float:
    """Measured zero1/dp step ratio on a PROXY workload (half the bench
    model: different size, same host) — the transferable calibration
    the cost model's ``state_shard_ratio`` quotes.  Predicting the
    TARGET workload's fsdp/zero1 from a proxy measurement is the test:
    circular it is not."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpudist.models import create_transformer
    from tpudist.plan import TrainCandidate

    proxy_mod, proxy_params = create_transformer(
        jax.random.PRNGKey(1), seq_len=16, vocab=64, d_model=32,
        n_layers=2, n_heads=2, d_ff=64)
    host = jax.device_get(proxy_params)
    toks = np.random.default_rng(1).integers(
        0, 64, size=(8, 16)).astype(np.int32)
    runners = {
        c.strategy: _make_train_runner(
            c, proxy_mod, jax.tree.map(jnp.asarray, host), tx, toks)
        for c in (TrainCandidate("dp"), TrainCandidate("zero1"))}
    best = _interleaved_measure(runners, iters)
    return best["zero1"] / best["dp"]


def _rung_training(n_devices: int, iters: int) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from tpudist.models import create_transformer
    from tpudist.plan import (
        Calibration,
        TrainWorkload,
        load_artifacts,
        plan_training,
    )

    assert jax.device_count() == n_devices, (
        jax.device_count(), n_devices)
    cfg = dict(vocab=128, d_model=64, n_layers=4, n_heads=4, d_ff=128)
    seq, batch = 32, 8
    flax_mod, params = create_transformer(
        jax.random.PRNGKey(0), seq_len=seq, **cfg)
    tx = optax.adam(1e-3)
    tokens = np.random.default_rng(0).integers(
        0, cfg["vocab"], size=(batch, seq)).astype(np.int32)

    cands = _train_candidates(n_devices)
    # each candidate's first (donating) step consumes its state buffers,
    # so every candidate starts from a fresh copy of the host params
    host_params = jax.device_get(params)
    runners = {
        c.name: _make_train_runner(
            c, flax_mod, jax.tree.map(jnp.asarray, host_params), tx,
            tokens)
        for c in cands}
    measured = _interleaved_measure(runners, iters)

    pb = sum(int(leaf.size) * leaf.dtype.itemsize
             for leaf in jax.tree.leaves(host_params))
    wl = TrainWorkload(
        param_bytes=float(pb),
        flops_per_step=6.0 * (pb / 4.0) * batch * seq,
        n_devices=n_devices, global_batch=batch, lm=True,
        precision="fp32",
        device_kind=jax.devices()[0].device_kind or "cpu")
    calib = Calibration(base_s=measured["dp"],
                        collective_bytes_per_s=_collective_bandwidth(),
                        state_shard_ratio=_calibrate_state_ratio(
                            tx, max(5, iters // 2)))
    report = plan_training(wl, load_artifacts(), candidates=cands,
                           calibration=calib)
    predicted_best = report.pick().candidate.name

    configs = []
    for pc in report.ranked:
        name = pc.candidate.name
        meas = measured[name]
        configs.append({
            "name": name,
            "predicted_s": round(pc.estimate.seconds, 6),
            "measured_s": round(meas, 6),
            "error_frac": round(
                abs(pc.estimate.seconds - meas) / meas, 4),
        })
    measured_best = min(measured, key=measured.get)
    floor = measured[measured_best]
    match = measured[predicted_best] <= floor * (1 + MATCH_RTOL) + 1e-9
    return {
        "kind": "training",
        "regime": "virtual-cpu",
        "geometry": {"platform": jax.default_backend(),
                     "n_devices": n_devices},
        "iters": iters,
        "base": "dp",
        "collective_bytes_per_s": calib.collective_bytes_per_s,
        "configs": configs,
        "predicted_best": predicted_best,
        "measured_best": measured_best,
        "match": bool(match),
    }


# -- serving rung -------------------------------------------------------


def _serve_candidates(slots):
    from tpudist.plan import ServeCandidate

    return [
        ServeCandidate(decode_block=8, slots=slots),
        ServeCandidate(decode_block=1, slots=slots),
        ServeCandidate(decode_block=8, spec_layers=1, spec_k=4,
                       slots=slots),
        ServeCandidate(decode_block=8, spec_layers=1, spec_k=8,
                       slots=slots),
    ]


def _measure_serve(module, params, cand, slots, n_requests, vocab):
    """Live TPOT/TTFT for one engine config: real ``InferenceServer``,
    burst load through ``serve_bench.run_rate``."""
    import numpy as np

    from tpudist.serve import InferenceServer, ServeConfig

    try:
        from benchmarks import serve_bench
    except ImportError:
        import serve_bench

    kw = dict(num_slots=slots, queue_limit=max(16, 2 * n_requests),
              prefill_pad=8, decode_block=cand.decode_block)
    if cand.spec_layers is not None:
        kw.update(spec=True, spec_draft_layers=cand.spec_layers,
                  spec_k=cand.spec_k)
    server = InferenceServer(module, params, ServeConfig(**kw),
                             install_signal_handler=False).start()
    try:
        # warm both prefill pad buckets + the decode/draft buckets so
        # the timed rung measures steady state, not compiles
        for plen in (6, 12):
            prompt = (np.arange(plen) % vocab).astype(np.int32)
            server.submit(prompt, max_new=32, seed=0).wait()
        row = serve_bench.run_rate(
            server, rate_rps=1e9, n_requests=n_requests, vocab=vocab,
            prompt_lens=(6, 12), max_news=(32, 32), seed=1)
    finally:
        server.close()
    return row


def _rung_serving(slots: int, max_len: int, n_requests: int) -> dict:
    import jax

    from tpudist.models import create_transformer
    from tpudist.plan import Calibration, load_artifacts, plan_serving
    from tpudist.plan.planner import engine_workload

    cfg = dict(vocab=64, d_model=32, n_layers=2, n_heads=2, d_ff=64,
               max_len=max_len)
    module, params = create_transformer(
        jax.random.PRNGKey(0), seq_len=16, **cfg)

    cands = _serve_candidates(slots)
    measured = {c.name: _measure_serve(module, params, c, slots,
                                       n_requests, cfg["vocab"])
                for c in cands}

    base_name = cands[0].name  # dense K=8 anchors the calibration
    wl = engine_workload(module, params, n_devices=1, slots=slots)
    calib = Calibration(base_s=measured[base_name]["tpot_s_p50"])
    report = plan_serving(wl, load_artifacts(), candidates=cands,
                          calibration=calib)
    predicted_best = report.pick().candidate.name

    configs = []
    for pc in report.ranked:
        name = pc.candidate.name
        row = measured[name]
        meas = row["tpot_s_p50"]
        configs.append({
            "name": name,
            "predicted_s": round(pc.estimate.seconds, 6),
            "measured_s": meas,
            "error_frac": round(
                abs(pc.estimate.seconds - meas) / meas, 4)
            if meas else None,
            "predicted_ttft_s": round(pc.ttft.seconds, 6)
            if pc.ttft is not None else None,
            "measured_ttft_s": row.get("ttft_s_p50"),
        })
    tpots = {n: r["tpot_s_p50"] for n, r in measured.items()
             if r["tpot_s_p50"]}
    measured_best = min(tpots, key=tpots.get)
    floor = tpots[measured_best]
    match = tpots.get(predicted_best, float("inf")) \
        <= floor * (1 + MATCH_RTOL) + 1e-9
    return {
        "kind": "serving",
        "regime": "cpu-smoke",
        "geometry": {"platform": jax.default_backend(), "n_devices": 1},
        "slots": slots,
        "max_len": max_len,
        "n_requests": n_requests,
        "base": base_name,
        "configs": configs,
        "predicted_best": predicted_best,
        "measured_best": measured_best,
        "match": bool(match),
    }


# -- orchestration ------------------------------------------------------


def _error_band(rungs) -> "dict | None":
    fracs = [c["error_frac"] for r in rungs
             for c in r.get("configs", [])
             if isinstance(c.get("error_frac"), (int, float))]
    if not fracs:
        return None
    return {"max_frac": round(max(fracs), 4),
            "p50_frac": round(statistics.median(fracs), 4),
            "n_configs": len(fracs),
            "n_rungs": sum(1 for r in rungs if "configs" in r)}


def _run_rung(devices: int, rung_argv: list, timeout: int = 900) -> dict:
    proc = subprocess.run(
        [sys.executable, "-c",
         _STUB.format(devices=devices, repo=str(REPO), argv=rung_argv)],
        capture_output=True, text=True, timeout=timeout, cwd=REPO)
    if proc.returncode != 0:
        raise RuntimeError(
            f"rung {rung_argv} failed rc={proc.returncode}:\n"
            f"{proc.stderr[-2000:]}")
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise RuntimeError(f"rung {rung_argv}: no JSON row in output")


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--round", default=None, type=int)
    p.add_argument("--out", default=None)
    p.add_argument("--iters", default=30, type=int,
                   help="timed training steps per candidate")
    p.add_argument("--requests", default=10, type=int,
                   help="requests per serving rung")
    # internal: run ONE rung in this process (the parent pins the
    # virtual device count before jax imports via _STUB)
    p.add_argument("--_rung", choices=("training", "serving"),
                   default=None, help=argparse.SUPPRESS)
    p.add_argument("--devices", default=8, type=int,
                   help=argparse.SUPPRESS)
    p.add_argument("--slots", default=4, type=int,
                   help=argparse.SUPPRESS)
    p.add_argument("--max-len", default=64, type=int,
                   help=argparse.SUPPRESS)
    args = p.parse_args(argv)

    if args._rung == "training":
        print(json.dumps(_rung_training(args.devices, args.iters)))
        return 0
    if args._rung == "serving":
        print(json.dumps(_rung_serving(args.slots, args.max_len,
                                       args.requests)))
        return 0

    rnd = args.round if args.round is not None else detect_round()
    out = Path(args.out) if args.out else REPO / f"PLAN_r{rnd:02d}.json"

    train_rungs, serve_rungs = [], []
    for nd in (4, 8):
        spec = ["--_rung", "training", "--devices", str(nd),
                "--iters", str(args.iters)]
        try:
            row = _run_rung(nd, spec)
        except Exception as e:  # failure-isolated per rung
            row = {"kind": "training", "geometry": {"n_devices": nd},
                   "error": repr(e)}
        train_rungs.append(row)
        print(json.dumps(row))
    for slots, max_len in ((2, 64), (4, 96)):
        spec = ["--_rung", "serving", "--slots", str(slots),
                "--max-len", str(max_len),
                "--requests", str(args.requests)]
        try:
            row = _run_rung(1, spec)
        except Exception as e:
            row = {"kind": "serving",
                   "geometry": {"slots": slots, "max_len": max_len},
                   "error": repr(e)}
        serve_rungs.append(row)
        print(json.dumps(row))

    good = [r for r in train_rungs + serve_rungs if "configs" in r]
    platform = next((r["geometry"].get("platform") for r in good), "cpu")
    doc = {
        # the header artifacts.py validates: declared metadata beats
        # filename parsing.  Geometry declares only the platform — the
        # per-rung device counts live inside each rung (the PLAN file
        # spans several).
        "artifact": {"schema": 1, "family": "PLAN", "round": rnd,
                     "geometry": {"platform": platform}},
        "training": {"rungs": train_rungs,
                     "error_band": _error_band(train_rungs)},
        "serving": {"rungs": serve_rungs,
                    "error_band": _error_band(serve_rungs)},
        "summary": {
            "match_rtol": MATCH_RTOL,
            "all_match": bool(good) and all(r.get("match")
                                            for r in good),
            "rungs_ok": len(good),
            "rungs_failed": len(train_rungs + serve_rungs) - len(good),
        },
    }
    out.write_text(json.dumps(doc, indent=1) + "\n")
    print(json.dumps({"wrote": out.name,
                      "all_match": doc["summary"]["all_match"],
                      "training_band": doc["training"]["error_band"],
                      "serving_band": doc["serving"]["error_band"]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
